"""Headline benchmark: zkatdlog transfer-proof verification throughput.

Prints ONE JSON line:
  {"metric": "zkatdlog_transfer_verify_throughput", "value": N,
   "unit": "tx/s", "vs_baseline": N / 133.0, ...}

Baseline (BASELINE.md): reference Go implementation, 2-in/2-out transfers
with base=16 exponent=2 range proofs ~= 133 tx/s per x86 core.

Runs on whatever accelerator the ambient JAX platform provides (the axon
TPU under the driver; CPU fallback if the tunnel is down). Proof
generation happens on the host; the measured quantity is block
verification: batched WF + range-equality + membership(4 pairing products
each) kernels plus host Fiat-Shamir re-hashing.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

# Persistent XLA compilation cache is configured centrally in
# fabric_token_sdk_tpu/ops/__init__.py (~/.cache/fts_tpu_jax).


def _reexec_cpu() -> None:
    """Restart this process pinned to local CPU (axon tunnel unhealthy)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["_FTS_BENCH_REEXEC"] = "1"
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if ".axon_site" not in p
    )
    if not os.environ.get("_FTS_BENCH_REEXEC"):
        os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _platform_guard() -> str:
    """Probe device init in a watchdog thread; fall back to CPU if the
    remote TPU tunnel hangs."""
    result = {}

    def probe():
        try:
            import jax

            result["devices"] = jax.devices()
            result["platform"] = result["devices"][0].platform
        except Exception as e:  # pragma: no cover
            result["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout=float(os.environ.get("FTS_BENCH_INIT_TIMEOUT", "120")))
    if "platform" in result:
        return result["platform"]
    _reexec_cpu()  # tunnel hang/failure (no-op if already re-exec'd)
    return "cpu"


def _arm_deadline(platform: str) -> None:
    """A sick tunnel can pass the device probe yet hang the first compile
    or transfer forever. On the axon platform, arm a hard deadline: if the
    benchmark hasn't printed its JSON by then, re-exec pinned to CPU so
    the driver always records a number."""
    if platform == "cpu":
        return
    deadline = float(os.environ.get("FTS_BENCH_DEADLINE", "2400"))

    def watchdog():
        time.sleep(deadline)
        _reexec_cpu()
        os._exit(3)  # re-exec refused (already CPU): fail loudly

    threading.Thread(target=watchdog, daemon=True).start()


def main() -> None:
    platform = _platform_guard()
    _arm_deadline(platform)
    import random

    import numpy as np

    from fabric_token_sdk_tpu.crypto import batch as batch_mod, transfer, token as tok
    from fabric_token_sdk_tpu.crypto.setup import setup

    B = int(os.environ.get("FTS_BENCH_BATCH", "32"))
    base = 16
    exponent = 2
    rng = random.Random(1234)
    t0 = time.time()
    pp = setup(base=base, exponent=exponent, rng=rng)
    setup_s = time.time() - t0

    # build B two-in/two-out transfers (host proving)
    t0 = time.time()
    txs = []
    for i in range(B):
        in_toks, in_w = tok.tokens_with_witness([100, 55], "USD", pp.ped_params, rng)
        out_toks, out_w = tok.tokens_with_witness([120, 35], "USD", pp.ped_params, rng)
        proof = transfer.TransferProver(in_w, out_w, in_toks, out_toks, pp, rng).prove()
        txs.append((in_toks, out_toks, proof))
    gen_s = time.time() - t0

    verifier = batch_mod.BatchedTransferVerifier(pp)
    # warmup (compiles device programs)
    t0 = time.time()
    ok = verifier.verify(txs)
    warm_s = time.time() - t0
    assert bool(np.all(ok)), "benchmark proofs failed to verify"

    # timed runs
    runs = int(os.environ.get("FTS_BENCH_RUNS", "3"))
    t0 = time.time()
    for _ in range(runs):
        ok = verifier.verify(txs)
    elapsed = time.time() - t0
    rate = B * runs / elapsed

    print(
        json.dumps(
            {
                "metric": "zkatdlog_transfer_verify_throughput",
                "value": round(rate, 2),
                "unit": "tx/s",
                "vs_baseline": round(rate / 133.0, 3),
                "platform": platform,
                "batch": B,
                "runs": runs,
                "warmup_s": round(warm_s, 1),
                "provegen_s": round(gen_s, 1),
                "setup_s": round(setup_s, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
