"""Headline benchmark: zkatdlog transfer-proof verification throughput.

Prints ONE JSON line:
  {"metric": "zkatdlog_transfer_verify_throughput", "value": N,
   "unit": "tx/s", "vs_baseline": N / 133.0, ...}

Baseline (BASELINE.md): reference Go implementation, 2-in/2-out transfers
with base=16 exponent=2 range proofs ~= 133 tx/s per x86 core.

Runs on whatever accelerator the ambient JAX platform provides (the axon
TPU under the driver; CPU fallback if the tunnel is down). Proof
generation happens on the host; the measured quantity is block
verification: batched WF + range-equality + membership(4 pairing products
each) kernels plus host Fiat-Shamir re-hashing.

Observability: the run emits phase-stamped heartbeat lines to stderr
(`[fts-bench] phase=warmup_compile elapsed=134s total=250s`) and flushes
a metrics sidecar JSON (per-phase wall times, compile/cache counters,
pipeline histograms) on exit, SIGTERM, or the internal deadline — so
even a timed-out run (rc=124) leaves a full accounting. Sidecar path:
$FTS_METRICS_SIDECAR (default BENCH.metrics.json). Inspect with
`python cmd/ftsmetrics.py show BENCH.metrics.json`.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

# Persistent XLA compilation cache is configured centrally in
# fabric_token_sdk_tpu/ops/__init__.py (~/.cache/fts_tpu_jax).

# set once the result JSON has been printed; the deadline watchdog checks
# it so a completed (or merely slow-but-healthy) run is never clobbered
# by the CPU fallback re-exec
_done = threading.Event()


def _metrics():
    from fabric_token_sdk_tpu.utils import metrics

    return metrics


def _sidecar_path() -> str:
    return os.environ.get("FTS_METRICS_SIDECAR", "BENCH.metrics.json")


def _deadline_sidecar_path() -> str:
    """Distinct path for the pre-re-exec accounting: the CPU child reuses
    the main sidecar path and would otherwise overwrite the record of
    where the accelerator attempt stalled."""
    p = _sidecar_path()
    if p.endswith(".metrics.json"):
        return p[: -len(".metrics.json")] + ".deadline.metrics.json"
    return p + ".deadline.json"


def _reexec_cpu() -> None:
    """Restart this process pinned to local CPU (axon tunnel unhealthy)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # the fallback child must complete at all costs — do not let it
    # inherit the deadline that just killed the accelerator attempt
    env.pop("FTS_BENCH_DEADLINE", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["_FTS_BENCH_REEXEC"] = "1"
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if ".axon_site" not in p
    )
    if not os.environ.get("_FTS_BENCH_REEXEC"):
        # execve skips atexit: record the accelerator attempt before it is
        # replaced — the CPU child reuses (and overwrites) the main path
        mx = _metrics()
        mx.REGISTRY.set_meta("reexec_to_cpu", True)
        mx.flush_sidecar()
        mx.flush_sidecar(_deadline_sidecar_path())
        os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _platform_guard() -> str:
    """Probe device init in a watchdog thread; fall back to CPU if the
    remote TPU tunnel hangs."""
    result = {}

    def probe():
        try:
            import jax

            result["devices"] = jax.devices()
            result["platform"] = result["devices"][0].platform
        except Exception as e:  # pragma: no cover
            result["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout=float(os.environ.get("FTS_BENCH_INIT_TIMEOUT", "120")))
    if "platform" in result:
        return result["platform"]
    _reexec_cpu()  # tunnel hang/failure (no-op if already re-exec'd)
    return "cpu"


def _arm_deadline(platform: str) -> None:
    """A sick tunnel can pass the device probe yet hang the first compile
    or transfer forever. Arm a hard deadline: if the benchmark hasn't
    printed its JSON by then, flush the metrics sidecar (so the run is
    not a zero-information outcome), then on the axon platform re-exec
    pinned to CPU so the driver always records a number."""
    if platform == "cpu" and "FTS_BENCH_DEADLINE" not in os.environ:
        return  # CPU runs have no fallback to arm unless explicitly asked
    deadline = float(os.environ.get("FTS_BENCH_DEADLINE", "2400"))

    def watchdog():
        if _done.wait(timeout=deadline):
            return  # JSON already printed: never clobber a finished run
        mx = _metrics()
        mx.REGISTRY.set_meta("deadline_fired_s", deadline)
        print(
            f"[fts-bench] DEADLINE after {deadline:.0f}s on platform="
            f"{platform}: flushing metrics sidecar and "
            + ("re-exec'ing on CPU" if platform != "cpu" else "exiting 124"),
            file=sys.stderr,
            flush=True,
        )
        if platform != "cpu":
            _reexec_cpu()  # owns the pre-exec sidecar flushes; no return
        mx.flush_sidecar()  # already CPU (or re-exec refused): record...
        os._exit(124)  # ...then fail loudly

    threading.Thread(target=watchdog, daemon=True).start()


def main() -> None:
    mx = _metrics()
    mx.enable(True)
    mx.install_sidecar(_sidecar_path())
    mx.REGISTRY.set_meta("entry", "bench.py")
    mx.REGISTRY.set_meta("argv", " ".join(sys.argv))
    hb = mx.Heartbeat("fts-bench").start()

    hb.set_phase("platform_probe")
    platform = _platform_guard()
    mx.REGISTRY.set_meta("platform", platform)
    _arm_deadline(platform)
    import random

    import numpy as np

    from fabric_token_sdk_tpu.crypto import batch as batch_mod, transfer, token as tok
    from fabric_token_sdk_tpu.crypto.setup import setup

    B = int(os.environ.get("FTS_BENCH_BATCH", "32"))
    base = 16
    exponent = 2
    rng = random.Random(1234)
    hb.set_phase("setup", base=base, exponent=exponent)
    t0 = time.time()
    pp = setup(base=base, exponent=exponent, rng=rng)
    setup_s = time.time() - t0

    # build B two-in/two-out transfers (host proving)
    hb.set_phase("provegen", batch=B)
    t0 = time.time()
    txs = []
    for i in range(B):
        in_toks, in_w = tok.tokens_with_witness([100, 55], "USD", pp.ped_params, rng)
        out_toks, out_w = tok.tokens_with_witness([120, 35], "USD", pp.ped_params, rng)
        proof = transfer.TransferProver(in_w, out_w, in_toks, out_toks, pp, rng).prove()
        txs.append((in_toks, out_toks, proof))
    gen_s = time.time() - t0

    # AOT warmup: precompile the whole stage/pairing program set (persistent
    # cache hits when cmd/ftswarmup.py or a previous run already populated
    # it). FTS_BENCH_WARMUP=0 opts out to measure the lazy-compile path.
    if os.environ.get("FTS_BENCH_WARMUP", "1") != "0":
        from fabric_token_sdk_tpu.ops import warmup as warmup_mod

        hb.set_phase("stage_warmup")
        t0 = time.time()
        wsum = warmup_mod.warmup()
        aot_s = time.time() - t0
        mx.gauge("bench.stage_warmup_s").set(round(aot_s, 3))
        mx.gauge("bench.stage_warmup_compiles").set(wsum["backend_compiles"])
        mx.gauge("bench.stage_warmup_cache_hits").set(wsum["cache_hits"])

    verifier = batch_mod.BatchedTransferVerifier(pp)
    # first verify: with a warm cache this is pure runtime (the compile
    # histogram in the sidecar proves whether any backend compile fired)
    hb.set_phase("warmup_compile", batch=B)
    t0 = time.time()
    ok = verifier.verify(txs)
    warm_s = time.time() - t0
    assert bool(np.all(ok)), "benchmark proofs failed to verify"

    # timed runs
    runs = int(os.environ.get("FTS_BENCH_RUNS", "3"))
    hb.set_phase("timed_runs", runs=runs)
    t0 = time.time()
    for _ in range(runs):
        ok = verifier.verify(txs)
    elapsed = time.time() - t0
    rate = B * runs / elapsed

    hb.set_phase("done")
    mx.gauge("bench.throughput_tx_per_s").set(round(rate, 2))
    mx.gauge("bench.warmup_s").set(round(warm_s, 3))
    mx.gauge("bench.provegen_s").set(round(gen_s, 3))
    mx.gauge("bench.setup_s").set(round(setup_s, 3))
    print(
        json.dumps(
            {
                "metric": "zkatdlog_transfer_verify_throughput",
                "value": round(rate, 2),
                "unit": "tx/s",
                "vs_baseline": round(rate / 133.0, 3),
                "platform": platform,
                "batch": B,
                "runs": runs,
                "warmup_s": round(warm_s, 1),
                "provegen_s": round(gen_s, 1),
                "setup_s": round(setup_s, 1),
                "stage_warmup_s": round(
                    float(mx.REGISTRY.gauge("bench.stage_warmup_s").value or 0), 1
                ),
            }
        ),
        flush=True,
    )
    _done.set()
    hb.stop()
    mx.flush_sidecar()


if __name__ == "__main__":
    main()
