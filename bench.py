"""Headline benchmark: zkatdlog transfer-proof verification throughput.

Prints the result as a JSON line:
  {"metric": "zkatdlog_transfer_verify_throughput", "value": N,
   "unit": "tx/s", "vs_baseline": N / 133.0, ...}

The headline line is printed as soon as the measured runs finish; if the
optional `block_throughput` phase (product-path blocks through the
orderer) completes, one more ENRICHED line — a strict superset of the
same fields plus `block_*` — is printed, so first-line parsers get the
headline and last-line parsers get the superset either way.

Baseline (BASELINE.md): reference Go implementation, 2-in/2-out transfers
with base=16 exponent=2 range proofs ~= 133 tx/s per x86 core.

Runs on whatever accelerator the ambient JAX platform provides (the axon
TPU under the driver; CPU fallback if the tunnel is down). BOTH sides of
the proof pipeline are measured: `provegen` runs through the batched
device prover (`crypto/batch_prove.py`; `prove_txs_per_s`,
`prove_vs_host` against a host-prover sample), and the headline remains
batch verification: batched WF + range-equality + membership(4 pairing
products each) kernels plus host Fiat-Shamir re-hashing.

Observability: the run emits phase-stamped heartbeat lines to stderr
(`[fts-bench] phase=warmup_compile elapsed=134s total=250s`) and flushes
a metrics sidecar JSON (per-phase wall times, compile/cache counters,
pipeline histograms) PLUS a flight-recorder sidecar (`*.flight.json`:
the last N lifecycle events — phases, submits, block cuts, verify
decisions, WAL appends, compiles) on exit, SIGTERM, or the internal
deadline — so even a timed-out run (rc=124) leaves a full accounting of
*what was happening*, not just final counters. Sidecar path:
$FTS_METRICS_SIDECAR (default BENCH.metrics.json; flight dump derived).
Inspect with `python cmd/ftsmetrics.py show BENCH.metrics.json` and
`python cmd/ftstrace.py tail BENCH.flight.json`.

The headline and soak phases also record the device-plane dispatch
ledger (`utils/devobs.py`; `FTS_DEVOBS=0` disables) as the
schema-validated `device` section of the result: batch occupancy,
padding waste, per-program dispatch wall and compile forensics. Gate it
in CI with `python cmd/ftstop.py compare --history BENCH_history.jsonl
--device`; render a recorded round with `python cmd/ftstrace.py
devices BENCH_history.jsonl`.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

# Persistent XLA compilation cache is configured centrally in
# fabric_token_sdk_tpu/ops/__init__.py (~/.cache/fts_tpu_jax).

# BASELINE.md: reference Go implementation, ~133 tx/s per x86 core for
# the headline 2-in/2-out transfer-verify shape — the one denominator
# every vs_baseline field in the result JSON uses
GO_BASELINE_TX_S = 133.0

# set once the result JSON has been printed; the deadline watchdog checks
# it so a completed (or merely slow-but-healthy) run is never clobbered
# by the CPU fallback re-exec
_done = threading.Event()

# armed-deadline bookkeeping (monotonic t0 + budget) so later phases —
# the scaling sweep — can size themselves to the REMAINING window
_armed = {"t0": None, "deadline": None}


def _remaining_budget_s():
    """Seconds left before the armed watchdog fires (None: not armed)."""
    if _armed["t0"] is None:
        return None
    return _armed["deadline"] - (time.monotonic() - _armed["t0"])


def _metrics():
    from fabric_token_sdk_tpu.utils import metrics

    return metrics


def _sidecar_path() -> str:
    return os.environ.get("FTS_METRICS_SIDECAR", "BENCH.metrics.json")


def _history_path() -> str:
    """The perf-regression observatory file (`cmd/ftstop.py compare`
    diffs rounds against it): next to the metrics sidecar unless
    FTS_BENCH_HISTORY pins it elsewhere."""
    p = os.environ.get("FTS_BENCH_HISTORY")
    if p:
        return p
    d = os.path.dirname(_sidecar_path())
    return os.path.join(d, "BENCH_history.jsonl") if d else "BENCH_history.jsonl"


def append_history(result: dict, path: str = None) -> str:
    """Append one result (full, enriched or degraded) to the bench
    history JSONL — every outcome lands in the observatory, so the BENCH
    trajectory is machine-checked instead of eyeballed. Append-only and
    failure-tolerant: history must never cost a run its result line."""
    row = {"ts": round(time.time(), 3), **result}
    p = path or _history_path()
    try:
        with open(p, "a") as fh:
            fh.write(json.dumps(row) + "\n")
    except OSError as e:
        print(f"[fts-bench] history append to {p} failed: {e}",
              file=sys.stderr, flush=True)
        return ""
    return p


def _profile_dir() -> str:
    """Sidecar-derived jax.profiler capture dir (FTS_PROFILE=1)."""
    p = _sidecar_path()
    if p.endswith(".metrics.json"):
        return p[: -len(".metrics.json")] + ".profile"
    return p + ".profile"


def _deadline_sidecar_path() -> str:
    """Distinct path for the pre-re-exec accounting: the CPU child reuses
    the main sidecar path and would otherwise overwrite the record of
    where the accelerator attempt stalled."""
    p = _sidecar_path()
    if p.endswith(".metrics.json"):
        return p[: -len(".metrics.json")] + ".deadline.metrics.json"
    return p + ".deadline.json"


def _reexec_cpu(child_deadline: float = None) -> None:
    """Restart this process pinned to local CPU (axon tunnel unhealthy).

    `child_deadline`: budget hint for the child's watchdog — the
    deadline-fired path passes a short one (its parent burned most of
    the driver window); the early probe-failure path passes none (the
    child inherits nearly the whole window)."""
    from fabric_token_sdk_tpu.utils.cleanenv import clean_cpu_env

    env = clean_cpu_env()
    # the fallback child must complete at all costs — do not let it
    # inherit the deadline that just killed the accelerator attempt
    env.pop("FTS_BENCH_DEADLINE", None)
    if child_deadline is not None:
        env.setdefault("FTS_BENCH_CHILD_DEADLINE", str(child_deadline))
    env["_FTS_BENCH_REEXEC"] = "1"
    if not os.environ.get("_FTS_BENCH_REEXEC"):
        # execve skips atexit: record the accelerator attempt before it is
        # replaced — the CPU child reuses (and overwrites) the main path
        mx = _metrics()
        mx.REGISTRY.set_meta("reexec_to_cpu", True)
        mx.flush_sidecar()
        mx.flush_sidecar(_deadline_sidecar_path())
        os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _platform_guard() -> str:
    """Probe device init in a watchdog thread; fall back to CPU if the
    remote TPU tunnel hangs."""
    result = {}

    def probe():
        try:
            import jax

            result["devices"] = jax.devices()
            result["platform"] = result["devices"][0].platform
        except Exception as e:  # pragma: no cover
            result["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout=float(os.environ.get("FTS_BENCH_INIT_TIMEOUT", "120")))
    if "platform" in result:
        return result["platform"]
    _reexec_cpu()  # tunnel hang/failure (no-op if already re-exec'd)
    return "cpu"


def degraded_result(platform: str, deadline: float, snap: dict) -> dict:
    """Assemble the DEGRADED result (shared bench-result schema,
    `fabric_token_sdk_tpu/utils/benchschema.py`) from a registry
    snapshot: whatever partial numbers the run produced plus the phase
    it died in."""
    gauges = snap.get("gauges", {})
    rate = float(gauges.get("bench.throughput_tx_per_s", 0.0) or 0.0)
    return {
        "metric": "zkatdlog_transfer_verify_throughput",
        "value": round(rate, 2),
        "unit": "tx/s",
        "vs_baseline": round(rate / GO_BASELINE_TX_S, 3),
        "platform": platform,
        "degraded": True,
        "deadline_s": deadline,
        "phase": snap.get("meta", {}).get("progress.phase", "unknown"),
        "stage_warmup_s": round(
            float(gauges.get("bench.stage_warmup_s", 0.0) or 0.0), 1
        ),
        "prove_txs_per_s": float(
            gauges.get("bench.prove_txs_per_s", 0.0) or 0.0
        ) or None,
    }


def headline_result(*, rate: float, platform: str, batch: int, runs: int,
                    warm_s: float, provegen_s: float, provegen_host_s: float,
                    prove_txs: int, prove_rate: float, host_rate: float,
                    prove_degraded: bool, setup_s: float,
                    stage_warmup_s: float) -> dict:
    """Assemble the headline result (shared bench-result schema,
    `fabric_token_sdk_tpu/utils/benchschema.py`; the block phase later
    enriches a copy with `block_*` fields)."""
    return {
        "metric": "zkatdlog_transfer_verify_throughput",
        "value": round(rate, 2),
        "unit": "tx/s",
        "vs_baseline": round(rate / GO_BASELINE_TX_S, 3),
        "platform": platform,
        "batch": batch,
        "runs": runs,
        "warmup_s": round(warm_s, 1),
        "provegen_s": round(provegen_s, 1),
        "provegen_host_s": round(provegen_host_s, 1),
        "prove_txs": prove_txs,
        "prove_txs_per_s": round(prove_rate, 3),
        "prove_vs_host": round(prove_rate / host_rate, 3) if host_rate else None,
        "prove_degraded": prove_degraded,
        "setup_s": round(setup_s, 1),
        "stage_warmup_s": round(stage_warmup_s, 1),
    }


def _degraded_json(platform: str, deadline: float) -> None:
    """The deadline result is never a zero-information rc=124: emit the
    result JSON in DEGRADED form (whatever partial numbers the run
    produced, plus the phase it died in) so the driver always parses
    something — and record the outcome in the bench history."""
    mx = _metrics()
    result = degraded_result(platform, deadline, mx.REGISTRY.snapshot())
    print(json.dumps(result), flush=True)
    append_history(result)


def _arm_deadline(platform: str) -> None:
    """A sick tunnel can pass the device probe yet hang the first compile
    or transfer forever — and a cold-cache CPU run can legitimately
    outlast the DRIVER's own timeout, which kills the process with a
    silent rc=124. Arm an internal deadline strictly INSIDE the driver
    budget (default 2000s < the 2400s driver window; the post-re-exec CPU
    child gets a short 300s budget since its parent already burned most
    of the window): if the benchmark hasn't printed its JSON by then,
    flush the metrics sidecar, emit a DEGRADED-but-parsed result JSON,
    and on the axon platform re-exec pinned to CPU first."""
    if "FTS_BENCH_DEADLINE" in os.environ:  # explicit always wins
        deadline = float(os.environ["FTS_BENCH_DEADLINE"])
    elif os.environ.get("_FTS_BENCH_REEXEC"):
        # _reexec_cpu pops FTS_BENCH_DEADLINE; the watchdog re-exec sets
        # FTS_BENCH_CHILD_DEADLINE=300 (parent burned the window), while
        # an early probe-failure re-exec leaves it unset — that child
        # still has nearly the whole driver budget
        deadline = float(os.environ.get("FTS_BENCH_CHILD_DEADLINE", "1800"))
    else:
        deadline = 2000.0
    _armed["t0"] = time.monotonic()
    _armed["deadline"] = deadline

    def watchdog():
        if _done.wait(timeout=deadline):
            return  # JSON already printed: never clobber a finished run
        mx = _metrics()
        mx.REGISTRY.set_meta("deadline_fired_s", deadline)
        # the flight ring's death marker, recorded BEFORE the platform
        # branch: the accelerator path re-execs (flushing sidecars on the
        # way out) and never reaches _degraded_json — the pre-exec
        # flight dump must still carry the bench.deadline event the
        # rc=124 runbook looks for
        mx.flight(
            "bench.deadline", deadline_s=deadline, platform=platform,
            phase=mx.REGISTRY.snapshot().get("meta", {}).get(
                "progress.phase", "unknown"
            ),
        )
        print(
            f"[fts-bench] DEADLINE after {deadline:.0f}s on platform="
            f"{platform}: flushing metrics sidecar and "
            + (
                "re-exec'ing on CPU"
                if platform != "cpu"
                else "emitting degraded result JSON"
            ),
            file=sys.stderr,
            flush=True,
        )
        if platform != "cpu":
            # owns the pre-exec sidecar flushes; no return. The child
            # gets only a short budget — this parent burned the window.
            _reexec_cpu(child_deadline=300)
        _degraded_json(platform, deadline)
        mx.flush_sidecar()
        os._exit(0)  # degraded JSON was printed: a parseable outcome

    threading.Thread(target=watchdog, daemon=True).start()


def _scaling_sweep(ctx, hb) -> list:
    """Throughput-vs-devices curve: re-run the block phase under mesh
    configs of growing device count (`FTS_BENCH_SCALING_DEVICES`,
    default "1,2,4,8") and report per-point rate + per-device
    efficiency. Each point is a FRESH ledger fed the SAME issue/transfer
    corpus the block phase built, with the `BatchedTransferVerifier`
    dispatch sharded over the point's dp x mp mesh (`Network(mesh=...)`
    -> per-shard stage-tile dispatch; on an emulated single-chip plane
    the mesh is the host-dispatch extent — the mechanism and curve shape
    are what a real slice scales). When the block phase itself ran
    UNSHARDED (no ambient mesh env), its measured rate is reused as the
    free n_devices=1 point. Budget-aware: points are measured [min, max,
    middles...] and the sweep stops LOUDLY when the next point would
    blow min(`FTS_BENCH_SCALING_BUDGET_S`, 80% of the remaining
    watchdog window) — the extremes land first, so a truncated sweep
    still carries >= 2 device counts.
    """
    from fabric_token_sdk_tpu.api.validator import RequestValidator
    from fabric_token_sdk_tpu.ops import stages as st_mod
    from fabric_token_sdk_tpu.parallel import MeshConfig
    from fabric_token_sdk_tpu.services.network import BlockPolicy, Network

    mx = _metrics()
    driver = ctx["driver"]
    issue_bytes = ctx["issue_bytes"]
    transfer_reqs = ctx["transfer_reqs"]
    n = len(transfer_reqs)
    try:
        devices = sorted(
            {
                max(1, int(v))
                for v in os.environ.get(
                    "FTS_BENCH_SCALING_DEVICES", "1,2,4,8"
                ).split(",")
                if v.strip()
            }
        )
    except ValueError:
        devices = [1, 2, 4, 8]
    mp = max(1, int(os.environ.get("FTS_BENCH_SCALING_MP", "1")))
    budget = float(os.environ.get("FTS_BENCH_SCALING_BUDGET_S", "900"))
    remaining = _remaining_budget_s()
    if remaining is not None:
        budget = min(budget, remaining * 0.8)
    points, cost_max = {}, 0.0
    # the base block run IS the n=1 point when it ran unsharded (no
    # ambient mesh/dp env) — one free curve point, no repeat measurement
    if (
        1 in devices
        and ctx.get("base_rate")
        and st_mod.default_dp() == 1
        and MeshConfig.from_env() is None
    ):
        points[1] = ctx["base_rate"]
        cost_max = ctx.get("base_cost_s") or 0.0
    # extremes first: a truncated sweep still yields a 2-point curve
    todo = [d for d in devices if d not in points]
    order = []
    if todo:
        order = [todo[-1]] + [d for d in reversed(todo[:-1])]
        if not points and len(todo) > 1:
            order = [todo[0], todo[-1]] + list(reversed(todo[1:-1]))
    t_sweep = time.time()
    gate_extent = max(devices)
    for i, nd in enumerate(order):
        elapsed = time.time() - t_sweep
        # even-share budgeting: every unvisited point is entitled to an
        # equal slice of the remaining budget, and a skipped point's
        # slice redistributes to the points after it. First-come-first-
        # served (skip nothing until the WHOLE budget is nearly gone)
        # let the extremes starve the mid-extent counts — PR 9's curve
        # dropped n_devices=2 exactly that way. The MAX extent is the
        # exception: `ftstop compare --scaling` gates efficiency at the
        # largest measured device count, so that one point gets first
        # claim on slack (double share, capped at the whole remainder)
        # rather than being the systematic first sacrifice of a tight
        # budget — a truncated sweep keeps the gate point AND the
        # small-extent points, shedding middles first.
        share = (budget - elapsed) / (len(order) - i)
        if nd == gate_extent:
            share = min(budget - elapsed, 2 * share)
        if points and cost_max * 1.2 > share:
            print(
                f"[fts-bench] scaling: skipping n_devices={nd} — "
                f"predicted {cost_max * 1.2:.0f}s exceeds its even share "
                f"{share:.0f}s of the remaining {budget - elapsed:.0f}s "
                "budget",
                file=sys.stderr, flush=True,
            )
            continue
        hb.set_phase("block_scaling", devices=nd, txs=n)
        cfg = MeshConfig.build(nd, mp if nd % mp == 0 else 1)
        wal_path = None
        if ctx.get("wal"):
            # same durability tax as the base point: the n=1 baseline came
            # from a WAL-journaled ledger, so every sweep point journals
            # too — otherwise efficiency is biased upward
            import tempfile

            wal_path = os.path.join(
                tempfile.mkdtemp(prefix=f"fts-scaling-wal-{nd}-"),
                "ledger.wal",
            )
        net = Network(
            RequestValidator(driver),
            policy=BlockPolicy(max_block_txs=n, min_batch=1),
            mesh=cfg,
            wal_path=wal_path,
        )
        t0 = time.time()
        ev = net.submit(issue_bytes)
        assert ev.status.value == "Valid", (
            f"scaling issue rejected: {ev.message}"
        )
        tb = time.time()
        events = net.submit_many(transfer_reqs)
        dt = time.time() - tb
        bad = [e for e in events if e.status.value != "Valid"]
        assert not bad, (
            f"scaling block ({nd} devices) rejected {len(bad)} txs: "
            f"{bad[0].message}"
        )
        points[nd] = n / dt if dt > 0 else 0.0
        cost_max = max(cost_max, time.time() - t0)
    if len(points) < 2:
        # a curve needs >= 2 device counts to say anything about scaling
        # — a lone point (budget starved the sweep) would also let
        # `ftstop compare --scaling` gate at n=1 where efficiency is 1.0
        # by construction; drop it LOUDLY instead
        print(
            f"[fts-bench] scaling: only {len(points)} device count(s) "
            "measured within budget — no curve recorded",
            file=sys.stderr, flush=True,
        )
        return []
    curve = []
    n_min = min(points)
    rate_min = points[n_min]
    for nd in sorted(points):
        rate = points[nd]
        eff = (
            rate * n_min / (nd * rate_min) if rate_min > 0 and nd else 0.0
        )
        curve.append({
            "n_devices": nd,
            "block_txs_per_s": round(rate, 3),
            "efficiency": round(eff, 3),
        })
    mx.gauge("bench.scaling_points").set(len(curve))
    mx.gauge("bench.scaling_efficiency").set(curve[-1]["efficiency"])
    return curve


def _block_throughput(pp, rng, hb, platform: str = "cpu",
                      scaling_ctx=None) -> dict:
    """Product-path benchmark: multi-tx blocks through the orderer.

    Builds B real 2-in/2-out zkatdlog transfer REQUESTS (owner
    signatures, MVCC inputs from a prior issue block) and submits them
    through `Network.submit_many`, so the measured region is the whole
    block pipeline: ordering -> same-shape grouping -> ONE
    `BatchedTransferVerifier` call per group -> signature checks ->
    intra-block MVCC -> atomic commit + finality. Opt out with
    FTS_BENCH_BLOCK=0; FTS_BENCH_BLOCK_TXS sizes the block.
    """
    mx = _metrics()
    n = int(os.environ.get("FTS_BENCH_BLOCK_TXS", "16"))
    from fabric_token_sdk_tpu.api.request import (
        IssueRecord,
        TokenRequest,
        TransferRecord,
    )
    from fabric_token_sdk_tpu.api.validator import RequestValidator
    from fabric_token_sdk_tpu.crypto import sign
    from fabric_token_sdk_tpu.drivers import identity
    from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
    from fabric_token_sdk_tpu.models.token import ID
    from fabric_token_sdk_tpu.services.network import BlockPolicy, Network

    hb.set_phase("block_provegen", txs=n)
    t0 = time.time()
    driver = ZKATDLogDriver(pp)
    # journal the bench ledger so the measured region includes the real
    # durability cost (fsync'd WAL append per block); FTS_BENCH_WAL=0
    # opts out, FTS_BENCH_WAL_PATH pins the journal location
    wal_path = None
    if os.environ.get("FTS_BENCH_WAL", "1") != "0":
        import tempfile

        wal_path = os.environ.get("FTS_BENCH_WAL_PATH") or os.path.join(
            tempfile.mkdtemp(prefix="fts-bench-wal-"), "ledger.wal"
        )
    net = Network(
        RequestValidator(driver),
        policy=BlockPolicy(max_block_txs=n, min_batch=1),
        wal_path=wal_path,
    )
    issuer_key, alice_key = sign.keygen(rng), sign.keygen(rng)
    issuer_id = identity.pk_identity(issuer_key.public)
    alice_id = identity.pk_identity(alice_key.public)

    anchor = "bench-block-issue"
    outcome = driver.issue(
        issuer_id, "USD", [100, 55] * n, [alice_id] * (2 * n),
        anonymous=False, rng=rng,
    )
    issue_req = TokenRequest(anchor=anchor)
    issue_req.issues.append(
        IssueRecord(
            action=outcome.action_bytes, issuer=issuer_id,
            outputs_metadata=outcome.metadata, receivers=[alice_id] * (2 * n),
        )
    )
    issue_req.issues[0].signature = issuer_key.sign(
        issue_req.marshal_to_sign(), rng
    )

    # batched proof generation for the whole block in one pass
    # (driver.transfer_many -> TransferProver.batch -> stage tiles);
    # on the CPU fallback the device plane is far slower than the native
    # host prover, so the corpus generation routes host there by default
    # (FTS_BENCH_BLOCK_DEVICE_PROVE=1/0 overrides either way)
    device_prove = os.environ.get("FTS_BENCH_BLOCK_DEVICE_PROVE")
    if device_prove is None:
        use_device = platform != "cpu"
    else:
        use_device = device_prove != "0"
    id_rows = [[ID(anchor, 2 * i), ID(anchor, 2 * i + 1)] for i in range(n)]
    touts = driver.transfer_many(
        [
            (
                id_rows[i],
                outcome.outputs[2 * i : 2 * i + 2],
                outcome.metadata[2 * i : 2 * i + 2],
                "USD", [120, 35], [alice_id, alice_id],
            )
            for i in range(n)
        ],
        rng=rng,
        min_batch=1 if use_device else n + 1,
    )
    transfer_reqs = []
    for i, tout in enumerate(touts):
        req = TokenRequest(anchor=f"bench-block-t{i}")
        req.transfers.append(
            TransferRecord(
                action=tout.action_bytes, input_ids=id_rows[i],
                senders=[alice_id, alice_id],
                outputs_metadata=tout.metadata,
                receivers=[alice_id, alice_id],
            )
        )
        payload = req.marshal_to_sign()
        req.transfers[0].signatures = [
            alice_key.sign(payload, rng), alice_key.sign(payload, rng)
        ]
        transfer_reqs.append(req.to_bytes())
    gen_s = time.time() - t0
    mx.gauge("bench.block_provegen_s").set(round(gen_s, 3))
    mx.gauge("bench.block_provegen_txs_per_s").set(
        round(n / gen_s, 2) if gen_s > 0 else 0.0
    )

    ev = net.submit(issue_req.to_bytes())
    assert ev.status.value == "Valid", f"bench issue rejected: {ev.message}"

    hb.set_phase("block_throughput", txs=n)
    batched_before = mx.REGISTRY.counter("ledger.validate.batched").value
    wal_hist = mx.REGISTRY.histogram("wal.append.seconds")
    wal_s_before = wal_hist.sum
    t0 = time.time()
    events = net.submit_many(transfer_reqs)
    elapsed = time.time() - t0
    bad = [e for e in events if e.status.value != "Valid"]
    assert not bad, f"bench block rejected {len(bad)} txs: {bad[0].message}"
    batched = mx.REGISTRY.counter("ledger.validate.batched").value - batched_before
    rate = n / elapsed
    mx.gauge("bench.block_txs_per_s").set(round(rate, 2))
    result = {
        "block_txs_per_s": round(rate, 2),
        "block_vs_baseline": round(rate / GO_BASELINE_TX_S, 3),
        "block_txs": n,
        "block_batched_frac": round(batched / n, 3),
        "block_provegen_s": round(gen_s, 1),
    }
    if wal_path is not None:
        # durability tax on the measured region: fsync'd WAL append time
        # as a fraction of block-commit wall time (target: < 0.1)
        frac = (wal_hist.sum - wal_s_before) / elapsed if elapsed > 0 else 0.0
        mx.gauge("bench.wal_overhead_frac").set(round(frac, 4))
        result["wal_overhead_frac"] = round(frac, 4)
    if scaling_ctx is not None:
        # hand the corpus to the scaling sweep (which runs AFTER the
        # enriched block line is printed — a sweep can never cost it)
        scaling_ctx.update(
            driver=driver, issue_bytes=issue_req.to_bytes(),
            transfer_reqs=transfer_reqs, base_rate=rate,
            base_cost_s=elapsed, wal=wal_path is not None,
        )
    return result


def _soak(hb, zk_pp=None) -> dict:
    """Sustained-load soak: N client threads drive `submit_many` of
    chained transfers against ONE pipelined, WAL-journaled,
    admission-controlled node for a fixed wall budget. The measured
    region is the whole streaming engine under concurrent pressure —
    bounded ordering queue (`FTS_BENCH_SOAK_QUEUE_MAX` ->
    `BlockPolicy.queue_max`), typed `Backpressure` shed cooperatively by
    the batch submitters, pipelined verify/commit overlap, the batched
    signature plane (policy via `FTS_SIGN_BATCHED`; `sign_plane` in the
    section records how it resolved), fsync'd WAL per block — reporting
    steady-state tx/s, CLIENT-observed p99 finality (each tx's latency
    is its group's submit_many wall time), queue-depth stability,
    backpressure rejects, the `host_validate_s` fraction of block commit
    wall time, and the `batch.sign.*` / `identity.cache.*` deltas. The
    per-client corpus is a self-transfer CHAIN (tx k spends tx k-1's
    output), so sustained load needs O(1) setup and every block
    exercises MVCC. `FTS_BENCH_SOAK_DRIVER=zkatdlog` swaps the corpus to
    1-in/1-out zkatdlog transfers (host-proved; verify/commit overlap
    plus batched signatures on zk blocks — `zk_pp` injects prebuilt
    params for tests, else a small `setup()` runs outside the measured
    region). Sized by FTS_BENCH_SOAK_S / _CLIENTS / _GROUP;
    budget-aware like the scaling sweep (never outlives the armed
    watchdog window).

    Chaos mode (`FTS_BENCH_SOAK_FAULTS=1`): a chaos-monkey thread
    randomly arms/disarms injected faults for the whole window —
    `error`/`delay`/`hang` kinds at the degrade-safe device sites
    (`batch.verify`, `batch.sign`, where any failure falls to host with
    verdicts unchanged) and `delay` at the fail-fast sites
    (`wal.append`, `orderer.cut`, `selector.lock`, where an injected
    ERROR would be a real commit failure, not a degradable one — the
    soak asserts every acknowledged tx commits Valid). Hang caps exceed
    the device deadline (`FTS_DEVICE_DEADLINE_S`, defaulted to 1s for
    the chaos window when unset) so bounded dispatch + breakers actually
    fire; the soak section gains `faults_injected` / `breaker_trips` /
    `degraded_planes` and the run must stay live throughout."""
    import dataclasses
    import tempfile

    from fabric_token_sdk_tpu.api.request import (
        IssueRecord,
        TokenRequest,
        TransferRecord,
    )
    from fabric_token_sdk_tpu.api.validator import RequestValidator
    from fabric_token_sdk_tpu.crypto import sign
    from fabric_token_sdk_tpu.drivers import identity
    from fabric_token_sdk_tpu.drivers.fabtoken import (
        FabTokenDriver,
        FabTokenPublicParams,
    )
    from fabric_token_sdk_tpu.models.token import ID
    from fabric_token_sdk_tpu.services.network import BlockPolicy, Network

    mx = _metrics()
    import random

    clients = max(1, int(os.environ.get("FTS_BENCH_SOAK_CLIENTS", "4")))
    group = max(1, int(os.environ.get("FTS_BENCH_SOAK_GROUP", "8")))
    duration = float(os.environ.get("FTS_BENCH_SOAK_S", "12"))
    qmax = int(os.environ.get("FTS_BENCH_SOAK_QUEUE_MAX", "64"))
    driver_name = os.environ.get("FTS_BENCH_SOAK_DRIVER", "fabtoken")
    chaos = os.environ.get("FTS_BENCH_SOAK_FAULTS", "0") == "1"
    if driver_name not in ("fabtoken", "zkatdlog"):
        raise ValueError(
            f"FTS_BENCH_SOAK_DRIVER={driver_name!r} (want fabtoken|zkatdlog)"
        )
    remaining = _remaining_budget_s()
    if remaining is not None:
        if remaining < 20:
            print(
                f"[fts-bench] soak: only {remaining:.0f}s of watchdog "
                "budget left — skipping the soak phase",
                file=sys.stderr, flush=True,
            )
            return {}
        duration = min(duration, remaining * 0.5)
    hb.set_phase("soak", clients=clients, group=group, driver=driver_name,
                 duration_s=round(duration, 1), chaos=int(chaos))
    wal_path = os.path.join(
        tempfile.mkdtemp(prefix="fts-soak-wal-"), "ledger.wal"
    )
    if driver_name == "zkatdlog":
        from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver

        if zk_pp is None:
            from fabric_token_sdk_tpu.crypto.setup import setup

            zk_pp = setup(base=4, exponent=2, rng=random.Random(0xF75))
        def make_driver():
            return ZKATDLogDriver(zk_pp)
    else:
        fab_pp = FabTokenPublicParams()

        def make_driver():
            return FabTokenDriver(fab_pp)
    # policy rides the ambient FTS_BLOCK_* / FTS_SIGN_* env (so a zk soak
    # can e.g. disable the proof plane on an emulated host) with the
    # soak's own block size + admission bound imposed on top
    policy = dataclasses.replace(
        BlockPolicy.from_env(), max_block_txs=4 * group, queue_max=qmax
    )
    net = Network(
        RequestValidator(make_driver()),
        policy=policy,
        wal_path=wal_path,
    )
    from fabric_token_sdk_tpu.utils import profiler, slo

    # fresh SLO window for the soak (re-reads FTS_SLO_*; clears the
    # slow-tx exemplar ring so recorded exemplars are soak txs)
    slo.reset()
    # host-path sampling profiler over the soak window: FTS_PROF_HZ
    # wins when set (0 disables); otherwise the soak defaults to a
    # modest rate so every recorded round carries a flamegraph — same
    # precedent as the force-enabled metrics plane
    try:
        prof_hz = float(os.environ.get("FTS_PROF_HZ", "") or 47.0)
    except ValueError:
        prof_hz = 47.0
    legs_before = profiler.leg_totals()
    rejects_before = mx.REGISTRY.counter("orderer.backpressure.rejects").value
    sign_before = {
        name: mx.REGISTRY.counter(name).value
        for name in ("batch.sign.rows", "batch.sign.host",
                     "batch.sign.host_fallbacks",
                     "identity.cache.hits", "identity.cache.misses")
    }
    hv_before = mx.REGISTRY.histogram("ledger.block.host_validate.seconds").sum
    commit_before = mx.REGISTRY.histogram("ledger.block.commit.seconds").sum
    # batch-first host-path accounting (the `host` section): parse-cache
    # counters and hostbatch.* row counters, plus the per-block batch-pass
    # wall histograms — all as window deltas
    host_counter_names = (
        "request.cache.hits", "request.cache.misses",
        "parse.cache.hits", "parse.cache.misses",
        "hostbatch.sign.rows", "hostbatch.proof.rows",
        "hostbatch.conservation.rows",
    )
    host_before = {
        n: mx.REGISTRY.counter(n).value for n in host_counter_names
    }
    host_batch_hists = (
        "ledger.block.host_sign_batch.seconds",
        "ledger.block.host_proof_batch.seconds",
        "ledger.block.host_conservation.seconds",
    )
    host_batch_before = {
        n: mx.REGISTRY.histogram(n).sum for n in host_batch_hists
    }
    # resilience accounting over the soak window: breaker trips, chaos
    # fault counts, and which planes saw at least one host fallback
    # (one counter per device plane — the single source for both the
    # before-snapshot and the degraded_planes computation)
    fallback_counters = (
        "ledger.block.batch_errors",      # verify plane
        "batch.sign.host_fallbacks",      # sign plane
        "batch.prove.host_fallbacks",     # prove plane
        "sharding.fallbacks",             # stages sharded dispatch
    )
    resil_names = ("resilience.breaker.open",) + fallback_counters
    resil_before = {n: mx.REGISTRY.counter(n).value for n in resil_names}
    faults_before = sum(
        v for k, v in mx.REGISTRY.snapshot()["counters"].items()
        if k.startswith("faults.injected.")
    )

    stop = threading.Event()
    depth_peak = [0.0]
    lock = threading.Lock()
    latencies: list = []
    committed = [0]
    errors: list = []

    def sampler():
        g = mx.REGISTRY.gauge("orderer.queue.depth")
        while not stop.is_set():
            with lock:
                depth_peak[0] = max(depth_peak[0], g.value)
            stop.wait(0.02)

    def chaos_monkey():
        """Randomly arm/disarm injected faults for the soak window.
        Degrade-safe device sites take any kind (error/delay/hang —
        every failure falls to host, verdicts unchanged); fail-fast
        sites take `delay` only (an injected error there is a REAL
        commit failure, which the soak's all-Valid assertion must not
        see). Hang caps outlive the device deadline so bounded dispatch
        + breakers fire; every disarm releases any hung worker."""
        from fabric_token_sdk_tpu.utils import faults, resilience

        chaos_rng = random.Random(0x5EED)
        degrade_sites = ("batch.verify", "batch.sign")
        delay_sites = ("wal.append", "orderer.cut", "selector.lock")
        deadline = max(0.5, resilience.device_deadline_s("verify") or 1.0)
        hang_cap = 4.0 * deadline
        armed_site = None
        try:
            while not stop.is_set():
                if chaos_rng.random() < 0.7:
                    site = chaos_rng.choice(degrade_sites)
                    kind = chaos_rng.choice(("error", "delay", "hang"))
                else:
                    site = chaos_rng.choice(delay_sites)
                    kind = "delay"
                faults.arm(
                    site, kind, prob=0.5, count=4,
                    delay_s=hang_cap if kind == "hang" else 0.02,
                )
                armed_site = site
                # a hang must stay armed PAST the device deadline or the
                # disarm below would release the worker before bounded
                # dispatch ever times out — the timeout/breaker path is
                # the thing this mode exists to exercise
                stop.wait(1.5 * deadline if kind == "hang" else 0.25)
                faults.disarm(site)  # releases any hung worker
                armed_site = None
        finally:
            if armed_site is not None:
                faults.disarm(armed_site)
            for site in degrade_sites + delay_sites:
                faults.disarm(site)

    def client(idx):
        profiler.set_thread_role("client")
        rng = random.Random(0xF75 + idx)
        drv = make_driver()
        key = sign.keygen(rng)
        ident = identity.pk_identity(key.public)
        try:
            anchor = f"soak-{idx}-seed"
            outcome = drv.issue(ident, "USD", [7], [ident], anonymous=False)
            req = TokenRequest(anchor=anchor)
            req.issues.append(
                IssueRecord(action=outcome.action_bytes, issuer=ident,
                            outputs_metadata=outcome.metadata,
                            receivers=[ident])
            )
            req.issues[0].signature = key.sign(req.marshal_to_sign(), rng)
            ev = net.submit(req.to_bytes())
            assert ev.status.value == "Valid", f"soak seed: {ev.message}"
            prev = ID(anchor, 0)
            prev_raw, prev_meta = outcome.outputs[0], outcome.metadata[0]
            k = 0
            while not stop.is_set():
                batch = []
                for j in range(group):
                    tx_id = f"soak-{idx}-{k}-{j}"
                    tout = drv.transfer(
                        [prev], [prev_raw], [prev_meta], "USD", [7], [ident]
                    )
                    treq = TokenRequest(anchor=tx_id)
                    treq.transfers.append(
                        TransferRecord(
                            action=tout.action_bytes, input_ids=[prev],
                            senders=[ident],
                            outputs_metadata=tout.metadata,
                            receivers=[ident],
                        )
                    )
                    treq.transfers[0].signatures = [
                        key.sign(treq.marshal_to_sign(), rng)
                    ]
                    batch.append(treq.to_bytes())
                    prev = ID(tx_id, 0)
                    prev_raw, prev_meta = tout.outputs[0], tout.metadata[0]
                t0 = time.monotonic()
                events = net.submit_many(batch)
                dt = time.monotonic() - t0
                bad = [e for e in events if e.status.value != "Valid"]
                if bad:
                    raise AssertionError(
                        f"soak client {idx} rejected: {bad[0].message}"
                    )
                with lock:
                    committed[0] += len(events)
                    latencies.extend([dt] * len(events))
                k += 1
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [
        # named so the sampling profiler classifies them as `client`
        threading.Thread(target=client, args=(i,), daemon=True,
                         name=f"fts-soak-client-{i}")
        for i in range(clients)
    ]
    mon = threading.Thread(target=sampler, daemon=True)
    monkey = (
        threading.Thread(target=chaos_monkey, daemon=True) if chaos else None
    )
    # chaos: bounded dispatch must actually bite inside the window —
    # default the commit-path deadline to 1s (explicit env always wins).
    # Set/restored STRICTLY around the measured window (try/finally), so
    # neither later bench phases nor spawned children ever inherit a 1s
    # deadline that would open breakers against a healthy emulated
    # backend (a cold compile there legitimately takes minutes).
    chaos_deadline_set = chaos and "FTS_DEVICE_DEADLINE_S" not in os.environ
    if chaos_deadline_set:
        os.environ["FTS_DEVICE_DEADLINE_S"] = "1"
    try:
        profiler.start(hz=prof_hz)
        t_begin = time.monotonic()
        mon.start()
        if monkey is not None:
            monkey.start()
        for t in threads:
            t.start()
        time.sleep(duration)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        elapsed = time.monotonic() - t_begin
        mon.join(timeout=5)
        if monkey is not None:
            monkey.join(timeout=10)
    finally:
        prof = profiler.stop()
        if chaos_deadline_set:
            os.environ.pop("FTS_DEVICE_DEADLINE_S", None)
    if errors:
        raise errors[0]
    rate = committed[0] / elapsed if elapsed > 0 else 0.0
    lat = sorted(latencies)
    p99 = lat[max(0, int(len(lat) * 0.99) - 1)] if lat else None
    rejects = (
        mx.REGISTRY.counter("orderer.backpressure.rejects").value
        - rejects_before
    )
    sign_delta = {
        name: int(mx.REGISTRY.counter(name).value - before)
        for name, before in sign_before.items()
    }
    cache_lookups = (
        sign_delta["identity.cache.hits"] + sign_delta["identity.cache.misses"]
    )
    hv_s = (
        mx.REGISTRY.histogram("ledger.block.host_validate.seconds").sum
        - hv_before
    )
    commit_s = (
        mx.REGISTRY.histogram("ledger.block.commit.seconds").sum
        - commit_before
    )
    resil_delta = {
        n: int(mx.REGISTRY.counter(n).value - before)
        for n, before in resil_before.items()
    }
    faults_injected = int(
        sum(
            v for k, v in mx.REGISTRY.snapshot()["counters"].items()
            if k.startswith("faults.injected.")
        )
        - faults_before
    )
    # planes whose host fallback fired at least once during the window
    degraded_planes = sum(1 for n in fallback_counters if resil_delta[n] > 0)
    soak = {
        "steady_txs_per_s": round(rate, 2),
        "p99_finality_s": round(p99, 4) if p99 is not None else None,
        "queue_depth_max": int(depth_peak[0]),
        "backpressure_rejects": int(rejects),
        "clients": clients,
        "duration_s": round(elapsed, 1),
        "txs": committed[0],
        # the batched-signature-plane accounting of this soak: what
        # ACTUALLY happened ("device" = rows rode the device plane,
        # "degraded" = plane enabled but every row fell to host,
        # "host" = plane off), the host_validate leg's share of block
        # commit wall time, and the sign/identity-cache deltas
        "driver": driver_name,
        "sign_plane": (
            "device" if sign_delta["batch.sign.rows"] > 0
            else "degraded" if net._pipeline.sign_enabled()
            else "host"
        ),
        "host_validate_frac": (
            round(hv_s / commit_s, 4) if commit_s > 0 else None
        ),
        "sign_rows": sign_delta["batch.sign.rows"],
        "sign_host": sign_delta["batch.sign.host"],
        "sign_fallbacks": sign_delta["batch.sign.host_fallbacks"],
        "identity_cache_hit_rate": (
            round(sign_delta["identity.cache.hits"] / cache_lookups, 4)
            if cache_lookups else None
        ),
        # resilience accounting of the window: injected chaos volume,
        # breaker trips, and how many device planes degraded to host at
        # least once — all zero in a clean (non-chaos) soak, and the
        # node stayed live + all-Valid either way
        "faults_injected": faults_injected,
        "breaker_trips": resil_delta["resilience.breaker.open"],
        "degraded_planes": degraded_planes,
    }
    # host-path profile of the window: explicit sub-leg wall clock
    # (exclusive time, commit-path only — collected inside the block
    # commit's profiler.collect() window) plus the sampler's collapsed
    # stacks. Coverage = what fraction of the host_validate leg the
    # named sub-legs explain; the remainder is uninstrumented host code.
    legs_now = profiler.leg_totals()
    legs_delta = {
        name: round(legs_now.get(name, 0.0) - legs_before.get(name, 0.0), 6)
        for name in profiler.LEGS
    }
    legs_sum = sum(legs_delta.values())
    stacks = prof.collapsed() if prof is not None else {}
    if len(stacks) > 200:
        stacks = dict(
            sorted(stacks.items(), key=lambda kv: -kv[1])[:200]
        )
    soak["profile"] = {
        "hz": prof.hz if prof is not None else 0.0,
        "samples": int(mx.REGISTRY.counter("prof.samples").value),
        "host_legs": legs_delta,
        "host_leg_coverage": (
            round(min(1.0, legs_sum / hv_s), 4) if hv_s > 0 else None
        ),
        "stacks": stacks,
        "dropped_stacks": int(mx.REGISTRY.counter("prof.dropped").value),
    }
    # batch-first host-validation section (`host` field, schema
    # `benchschema.HOST_*`, gated by `ftstop compare --host`): the
    # scalar tail per leg (exclusive seconds — what the block-level
    # batch passes did NOT absorb), per-block leg p99s, the batch-pass
    # wall + row deltas, and parse-cache effectiveness
    from fabric_token_sdk_tpu.services.network import pipeline as npipe

    host_delta = {
        n: int(mx.REGISTRY.counter(n).value - before)
        for n, before in host_before.items()
    }
    req_lookups = (
        host_delta["request.cache.hits"] + host_delta["request.cache.misses"]
    )
    parse_lookups = (
        host_delta["parse.cache.hits"] + host_delta["parse.cache.misses"]
    )

    def _leg_p99(leg):
        q = mx.REGISTRY.histogram(f"ledger.host.{leg}.seconds").quantile(0.99)
        return round(q, 6) if q is not None else None

    soak["host"] = {
        "unmarshal_s": legs_delta["unmarshal"],
        "fiat_shamir_s": legs_delta["fiat_shamir"],
        "sig_verify_s": legs_delta["sig_verify"],
        "conservation_s": legs_delta["conservation"],
        "input_match_s": legs_delta["input_match"],
        "host_validate_frac": soak["host_validate_frac"],
        "unmarshal_p99_s": _leg_p99("unmarshal"),
        "fiat_shamir_p99_s": _leg_p99("fiat_shamir"),
        "sign_batch_s": round(
            mx.REGISTRY.histogram(host_batch_hists[0]).sum
            - host_batch_before[host_batch_hists[0]], 6
        ),
        "proof_batch_s": round(
            mx.REGISTRY.histogram(host_batch_hists[1]).sum
            - host_batch_before[host_batch_hists[1]], 6
        ),
        "conservation_batch_s": round(
            mx.REGISTRY.histogram(host_batch_hists[2]).sum
            - host_batch_before[host_batch_hists[2]], 6
        ),
        "sign_batch_rows": host_delta["hostbatch.sign.rows"],
        "proof_batch_rows": host_delta["hostbatch.proof.rows"],
        "conservation_rows": host_delta["hostbatch.conservation.rows"],
        "request_cache_hit_rate": (
            round(host_delta["request.cache.hits"] / req_lookups, 4)
            if req_lookups else None
        ),
        "parse_cache_hit_rate": (
            round(host_delta["parse.cache.hits"] / parse_lookups, 4)
            if parse_lookups else None
        ),
        "workers": npipe.host_workers(),
    }
    # SLO verdict over the soak window (engine was reset at soak start,
    # so the sliding window saw only soak traffic)
    soak["slo"] = slo.ENGINE.evaluate()
    # device-plane dispatch ledger THROUGH the soak (cumulative since
    # process start — the section `ftstop compare --device` gates and
    # `ftstrace devices` renders); supersedes the headline-phase record
    from fabric_token_sdk_tpu.utils import devobs

    if devobs.enabled():
        soak["device"] = devobs.section()
    mx.gauge("bench.soak_txs_per_s").set(soak["steady_txs_per_s"])
    if p99 is not None:
        mx.gauge("bench.soak_p99_finality_s").set(soak["p99_finality_s"])
    mx.gauge("bench.soak_queue_depth_max").set(soak["queue_depth_max"])
    mx.gauge("bench.soak_backpressure_rejects").set(soak["backpressure_rejects"])
    if soak["host_validate_frac"] is not None:
        mx.gauge("bench.soak_host_validate_frac").set(soak["host_validate_frac"])
    return soak


def _failover_soak(hb) -> dict:
    """Kill-the-leader chaos soak (`FTS_BENCH_SOAK_FAILOVER=1`): a
    journaled leader ships committed blocks to one journaled follower
    while N `RemoteNetwork` clients — each holding BOTH endpoints —
    drive exactly-once issue traffic. At the half-window mark the
    leader is torn down abruptly; the follower's lease watchdog
    promotes it (fencing epoch bump) and the clients ride their
    failover machinery onto the new leader. The recorded section is
    the replication CONTRACT as numbers: `acked_tx_loss` (acked tx ids
    the promoted node does not hold Valid — must be 0),
    `duplicate_commits` (tx ids committed in more than one block across
    the switch — must be 0), `failover_p99_s` (p99 client-observed
    submit wall across the post-kill half), `follower_lag_max` (max
    shipped-height lag seen before the kill). Schema
    `benchschema.FAILOVER_*`, gated by `ftstop compare --failover`.
    Sized by FTS_BENCH_SOAK_S / _CLIENTS, budget-aware like the soak."""
    import tempfile

    from fabric_token_sdk_tpu.api.request import IssueRecord, TokenRequest
    from fabric_token_sdk_tpu.api.validator import RequestValidator
    from fabric_token_sdk_tpu.crypto import sign
    from fabric_token_sdk_tpu.drivers import identity
    from fabric_token_sdk_tpu.drivers.fabtoken import (
        FabTokenDriver,
        FabTokenPublicParams,
    )
    from fabric_token_sdk_tpu.services.network import Network, replication
    from fabric_token_sdk_tpu.services.network.remote import (
        LedgerServer,
        RemoteNetwork,
    )

    mx = _metrics()
    import random

    clients = max(1, int(os.environ.get("FTS_BENCH_SOAK_CLIENTS", "4")))
    duration = float(os.environ.get("FTS_BENCH_SOAK_S", "12"))
    remaining = _remaining_budget_s()
    if remaining is not None:
        if remaining < 20:
            print(
                f"[fts-bench] failover soak: only {remaining:.0f}s of "
                "watchdog budget left — skipping",
                file=sys.stderr, flush=True,
            )
            return {}
        duration = min(duration, remaining * 0.5)
    hb.set_phase("failover_soak", clients=clients,
                 duration_s=round(duration, 1))
    root = tempfile.mkdtemp(prefix="fts-failover-")
    pp = FabTokenPublicParams()

    def make_net(name):
        return Network(
            RequestValidator(FabTokenDriver(pp)),
            wal_path=os.path.join(root, f"{name}.wal"),
        )

    switches_before = mx.REGISTRY.counter("remote.failover.switches").value
    stale_before = mx.REGISTRY.counter("repl.stale_rejected").value
    # short lease so the auto-promotion fits the window; env always wins
    lease_set = "FTS_REPL_LEASE_S" not in os.environ
    if lease_set:
        os.environ["FTS_REPL_LEASE_S"] = "1.0"
    leader_net, follower_net = make_net("leader"), make_net("follower")
    follower_srv = LedgerServer(network=follower_net).start()
    leader_srv = LedgerServer(network=leader_net).start()
    follower_state = replication.attach_follower(
        follower_net, auto_promote=True
    )
    replication.attach_leader(leader_net, [follower_srv.address])
    endpoints = [leader_srv.address, follower_srv.address]

    stop = threading.Event()
    killed_at = [None]  # monotonic stamp of the kill, set by the killer
    lock = threading.Lock()
    acked: set = set()
    post_latencies: list = []
    lag_max = [0]
    errors: list = []

    def lag_sampler():
        while not stop.is_set() and killed_at[0] is None:
            repl = getattr(leader_net, "repl", None)
            if repl is not None:
                lag = repl.health_section().get("lag") or 0
                with lock:
                    lag_max[0] = max(lag_max[0], int(lag))
            stop.wait(0.05)

    def client(idx):
        rng = random.Random(0xFA11 + idx)
        drv = FabTokenDriver(pp)
        key = sign.keygen(rng)
        ident = identity.pk_identity(key.public)
        remote = RemoteNetwork(endpoints=endpoints, timeout=2.0,
                               retries=10, backoff_s=0.1)
        try:
            k = 0
            while not stop.is_set():
                anchor = f"failover-{idx}-{k}"
                k += 1
                outcome = drv.issue(ident, "USD", [5], [ident],
                                    anonymous=False)
                req = TokenRequest(anchor=anchor)
                req.issues.append(
                    IssueRecord(action=outcome.action_bytes, issuer=ident,
                                outputs_metadata=outcome.metadata,
                                receivers=[ident])
                )
                req.issues[0].signature = key.sign(req.marshal_to_sign(),
                                                   rng)
                t0 = time.monotonic()
                try:
                    ev = remote.submit(req.to_bytes())
                except Exception:
                    continue  # unacked: allowed to be lost
                dt = time.monotonic() - t0
                if ev.status.value != "Valid":
                    raise AssertionError(
                        f"failover client {idx} rejected: {ev.message}"
                    )
                with lock:
                    acked.add(anchor)
                    if killed_at[0] is not None:
                        post_latencies.append(dt)
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)
        finally:
            remote.close()

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True,
                         name=f"fts-failover-client-{i}")
        for i in range(clients)
    ]
    sampler = threading.Thread(target=lag_sampler, daemon=True)
    t_begin = time.monotonic()
    try:
        sampler.start()
        for t in threads:
            t.start()
        time.sleep(duration / 2)
        # the kill: abrupt teardown of the leader node — live client
        # connections are severed, the follower's heartbeats stop, and
        # its lease watchdog must promote it without operator help
        killed_at[0] = time.monotonic()
        leader_srv.stop()
        deadline = time.monotonic() + max(10.0, duration)
        while (follower_state.role != "leader"
               and time.monotonic() < deadline):
            time.sleep(0.05)
        time.sleep(duration / 2)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        sampler.join(timeout=5)
    finally:
        stop.set()
        if lease_set:
            os.environ.pop("FTS_REPL_LEASE_S", None)
        try:
            follower_srv.stop()
        except Exception:
            pass
    if errors:
        raise errors[0]
    if follower_state.role != "leader":
        raise AssertionError("follower never promoted after the kill")
    # the contract, measured on the promoted node's in-process ledger:
    # every acked tx present and Valid, no tx id in two blocks
    lost = sum(
        1 for a in acked
        if (ev := follower_net.status(a)) is None
        or ev.status.value != "Valid"
    )
    seen: dict = {}
    for block in follower_net._blocks:
        for txid in block.txs:
            seen[txid] = seen.get(txid, 0) + 1
    duplicates = sum(n - 1 for n in seen.values() if n > 1)
    post = sorted(post_latencies)
    p99 = post[max(0, int(len(post) * 0.99) - 1)] if post else None
    failover = {
        "acked_tx_loss": int(lost),
        "duplicate_commits": int(duplicates),
        "failover_p99_s": round(p99, 4) if p99 is not None else None,
        "follower_lag_max": int(lag_max[0]),
        "acked_txs": len(acked),
        "killed_at_s": round(killed_at[0] - t_begin, 2),
        "promoted_epoch": int(follower_state.epoch),
        "promotion": "auto",
        "failover_switches": int(
            mx.REGISTRY.counter("remote.failover.switches").value
            - switches_before
        ),
        "stale_rejected": int(
            mx.REGISTRY.counter("repl.stale_rejected").value - stale_before
        ),
    }
    mx.gauge("bench.failover_acked_tx_loss").set(failover["acked_tx_loss"])
    mx.gauge("bench.failover_duplicate_commits").set(
        failover["duplicate_commits"]
    )
    if p99 is not None:
        mx.gauge("bench.failover_p99_s").set(failover["failover_p99_s"])
    return failover


def _state_workload(vault, threads: int, selects: int, duration_s: float,
                    spend: bool = True) -> dict:
    """Concurrent select+spend pressure over one vault: N workers race
    tx-scoped selectors for random amounts of one token type, spend what
    they lock (an atomic `VaultDelta` through the store — journaled when
    the store is persistent), and release via `unlock_by_tx`. Only the
    `select()` call is timed — the recorded p99 is pure selection cost
    under contention, which is the number that must stay sub-linear in
    vault size."""
    import random as _random

    from fabric_token_sdk_tpu.services.selector import SelectorManager
    from fabric_token_sdk_tpu.services.vault import VaultDelta

    mgr = SelectorManager(vault)
    lock = threading.Lock()
    latencies: list = []
    spends = [0]
    errors: list = []
    stop = threading.Event()
    counter = [0]

    def worker(widx):
        # ANY escaping exception must land in errors[] — a silently dead
        # worker would otherwise surface later as a misleading
        # leaked-locks assert instead of the real root cause
        rng = _random.Random(0x57A7E + widx)
        try:
            while not stop.is_set():
                with lock:
                    if counter[0] >= selects:
                        return
                    counter[0] += 1
                    k = counter[0]
                tx_id = f"state-{widx}-{k}"
                amount = rng.randint(50, 500)
                sel = mgr.new_selector(tx_id, deadline_s=5.0)
                t0 = time.monotonic()
                ids, _total = sel.select(amount, "USD")
                dt = time.monotonic() - t0
                if spend:
                    vault.store.apply(
                        VaultDelta(tx_id, spends=[i.key() for i in ids])
                    )
                    with lock:
                        spends[0] += len(ids)
                mgr.unlock_by_tx(tx_id)
                with lock:
                    latencies.append(dt)
        except Exception as e:
            errors.append(e)

    ts = [threading.Thread(target=worker, args=(i,), daemon=True)
          for i in range(threads)]
    t_begin = time.monotonic()
    for t in ts:
        t.start()
    while any(t.is_alive() for t in ts):
        if time.monotonic() - t_begin > duration_s:
            stop.set()
        for t in ts:
            t.join(timeout=0.2)
    if errors:
        raise errors[0]
    lat = sorted(latencies)
    p99 = lat[max(0, int(len(lat) * 0.99) - 1)] if lat else None
    leaked = mgr.locker.locked_count()
    assert leaked == 0, f"selector leaked {leaked} locks"
    return {"selects": len(latencies), "spends": spends[0], "p99_s": p99}


def _state_scale(hb) -> dict:
    """State-plane scale benchmark (host-only — no proofs, no device
    work): populate a synthetic million-token vault through the
    journaled `PersistentTokenStore`, snapshot-compact it, measure
    `Vault.recover` (snapshot + journal replay + re-opening every
    token), then drive concurrent select+spend workers over the
    recovered vault. Reports the schema-validated `state` result section
    (`selector_p99_s`, `populate_s`, `recover_s`, RSS high-water via
    sysmon) plus a small-vault p99 calibration so `sublinear_ratio`
    witnesses that selection cost is sub-linear in vault size (the
    indexed walk touches candidates, not the vault). Sized by
    FTS_BENCH_STATE_TOKENS / _THREADS / _SELECTS; budget-aware like the
    other riders (scales down or skips LOUDLY, never silently)."""
    import gc
    import random as _random
    import tempfile

    from fabric_token_sdk_tpu.drivers.fabtoken import (
        FabTokenDriver,
        FabTokenPublicParams,
    )
    from fabric_token_sdk_tpu.models.token import ID, Owner, Token
    from fabric_token_sdk_tpu.services.vault import (
        InMemoryTokenStore,
        PersistentTokenStore,
        Vault,
        VaultDelta,
    )
    from fabric_token_sdk_tpu.services.vault.store import decoded_token
    from fabric_token_sdk_tpu.utils import sysmon

    mx = _metrics()
    tokens = int(os.environ.get("FTS_BENCH_STATE_TOKENS", "1000000"))
    small = int(os.environ.get("FTS_BENCH_STATE_SMALL", "10000"))
    threads = max(1, int(os.environ.get("FTS_BENCH_STATE_THREADS", "4")))
    selects = max(1, int(os.environ.get("FTS_BENCH_STATE_SELECTS", "400")))
    batch = max(1, int(os.environ.get("FTS_BENCH_STATE_BATCH", "20000")))
    select_budget_s = float(os.environ.get("FTS_BENCH_STATE_S", "20"))
    remaining = _remaining_budget_s()
    if remaining is not None:
        if remaining < 90:
            print(
                f"[fts-bench] state_scale: only {remaining:.0f}s of "
                "watchdog budget left — skipping the state phase",
                file=sys.stderr, flush=True,
            )
            return {}
        if remaining < 420 and tokens > 200_000:
            print(
                f"[fts-bench] state_scale: {remaining:.0f}s of budget left "
                f"— scaling the vault from {tokens} to 200000 tokens",
                file=sys.stderr, flush=True,
            )
            tokens = 200_000

    driver = FabTokenDriver(FabTokenPublicParams())
    me = b"state-owner"

    def owns(ident):
        return ident == me

    rng = _random.Random(0x57A7E)

    def synth_delta(tx_prefix, start, count):
        stores = []
        for i in range(start, start + count):
            tid = ID(f"{tx_prefix}{i}", 0)
            out = Token(Owner(me), "USD", hex(rng.randint(1, 100))).to_bytes()
            stores.append(decoded_token(driver.output_to_unspent, tid, out, None))
        return VaultDelta(f"populate-{tx_prefix}{start}", stores=stores)

    rss_hw = [0.0]

    def rss_sample():
        s = sysmon.sample()
        rss_hw[0] = max(rss_hw[0], s["rss_bytes"] / 1e6)

    # small-vault calibration: a PURE selection pass (single thread, no
    # spends — selection cost, not contention or fsync) — the p99
    # denominator of the sub-linearity witness
    pure_selects = min(selects, 100)
    hb.set_phase("state_small", tokens=small)
    vsmall = Vault(driver, owns, store=InMemoryTokenStore())
    for start in range(0, small, batch):
        vsmall.store.apply(synth_delta("c", start, min(batch, small - start)))
    next(vsmall.iter_unspent("USD"), None)  # warm the lazy index sort
    wl = _state_workload(vsmall, 1, pure_selects, select_budget_s,
                         spend=False)
    p99_small = wl["p99_s"]
    rss_sample()
    del vsmall
    gc.collect()

    # populate the persistent vault (journaled batches, fsync'd); the
    # scratch journal dir is removed however the phase exits — a 1M-token
    # journal + snapshot is hundreds of MB of /tmp per run otherwise
    import shutil

    hb.set_phase("state_populate", tokens=tokens)
    wal_dir = tempfile.mkdtemp(prefix="fts-state-vault-")
    path = os.path.join(wal_dir, "vault.wal")
    vault = None
    try:
        vault = Vault(driver, owns,
                      store=PersistentTokenStore(path, snapshot_every=0))
        store = vault.store
        t0 = time.monotonic()
        for start in range(0, tokens, batch):
            store.apply(synth_delta("s", start, min(batch, tokens - start)))
        store.compact()  # durable snapshot: what recovery will load
        populate_s = time.monotonic() - t0
        held = len(store)
        rss_sample()
        store.close()
        del vault, store
        gc.collect()

        # recover: snapshot load + journal replay + re-open every token
        hb.set_phase("state_recover", tokens=tokens)
        t0 = time.monotonic()
        # snapshot_every=0: at the default cadence (256 events) the
        # select+spend workload's 256th journaled spend would trigger a
        # full million-token snapshot under the store lock, and that
        # stall — not selection — would occupy the gated p99 slot
        vault = Vault.recover(path, driver, owns, snapshot_every=0)
        # the one-time lazy sort of the selection index is part of making
        # a recovered vault serviceable — account it to recover_s, so the
        # selection workload below measures STEADY-STATE p99 (not a
        # convoy behind the first select's O(n log n) index build)
        next(vault.iter_unspent("USD"), None)
        recover_s = time.monotonic() - t0
        assert len(vault.store) == held, (
            f"recover lost tokens: {len(vault.store)} != {held}"
        )
        rss_sample()

        # sub-linearity witness: the SAME pure pass at full size —
        # indexed selection should cost candidates-walked, not vault-size
        pure = _state_workload(vault, 1, pure_selects, select_budget_s,
                               spend=False)
        p99_pure = pure["p99_s"]

        # headline: concurrent select+spend over the recovered
        # million-token vault (sharded locks + journaled spends — the
        # production shape)
        hb.set_phase("state_select", tokens=tokens, threads=threads)
        wl = _state_workload(vault, threads, selects, select_budget_s)
        rss_sample()
    finally:
        try:
            if vault is not None:
                vault.store.close()
        except Exception:
            pass
        shutil.rmtree(wal_dir, ignore_errors=True)

    if not wl["p99_s"]:
        # zero completed selects cannot yield a p99; recording 0.0 would
        # poison the --state gate's median baseline — drop the section
        # LOUDLY instead (the observatory sees a round without `state`)
        print(
            "[fts-bench] state_scale: no selections completed within the "
            "budget — no state section recorded",
            file=sys.stderr, flush=True,
        )
        return {}

    state = {
        "tokens": tokens,
        "populate_s": round(populate_s, 2),
        "populate_tokens_per_s": round(tokens / populate_s, 1)
        if populate_s > 0 else 0.0,
        "recover_s": round(recover_s, 2),
        "recover_tokens_per_s": round(tokens / recover_s, 1)
        if recover_s > 0 else 0.0,
        "selector_p99_s": round(wl["p99_s"], 6),
        "rss_high_water_mb": round(rss_hw[0], 1),
        "selects": wl["selects"],
        "spends": wl["spends"],
        "threads": threads,
        "small_tokens": small,
        "selector_p99_small_s": round(p99_small, 6) if p99_small else None,
        "sublinear_ratio": round(p99_pure / p99_small, 2)
        if p99_pure and p99_small else None,
    }
    mx.gauge("bench.state_tokens").set(tokens)
    mx.gauge("bench.state_populate_s").set(state["populate_s"])
    mx.gauge("bench.state_recover_s").set(state["recover_s"])
    mx.gauge("bench.state_selector_p99_s").set(state["selector_p99_s"])
    mx.gauge("bench.state_rss_high_water_mb").set(state["rss_high_water_mb"])
    if state["sublinear_ratio"] is not None:
        mx.gauge("bench.state_sublinear_ratio").set(state["sublinear_ratio"])
    return state


def main() -> None:
    mx = _metrics()
    mx.enable(True)
    mx.install_sidecar(_sidecar_path())
    mx.REGISTRY.set_meta("entry", "bench.py")
    mx.REGISTRY.set_meta("argv", " ".join(sys.argv))
    hb = mx.Heartbeat("fts-bench").start()

    hb.set_phase("platform_probe")
    platform = _platform_guard()
    mx.REGISTRY.set_meta("platform", platform)
    _arm_deadline(platform)
    import random

    import numpy as np

    from fabric_token_sdk_tpu.crypto import batch as batch_mod, transfer, token as tok
    from fabric_token_sdk_tpu.crypto.setup import setup

    B = int(os.environ.get("FTS_BENCH_BATCH", "32"))
    base = 16
    exponent = 2
    rng = random.Random(1234)
    hb.set_phase("setup", base=base, exponent=exponent)
    t0 = time.time()
    pp = setup(base=base, exponent=exponent, rng=rng)
    setup_s = time.time() - t0

    # AOT warmup FIRST: proof generation now rides the device plane too,
    # so the whole canonical stage/pairing program set (verify AND prove)
    # precompiles before any measured phase (persistent cache hits when
    # cmd/ftswarmup.py or a previous run already populated it).
    # FTS_BENCH_WARMUP=0 opts out to measure the lazy-compile path.
    if os.environ.get("FTS_BENCH_WARMUP", "1") != "0":
        from fabric_token_sdk_tpu.ops import warmup as warmup_mod

        hb.set_phase("stage_warmup")
        t0 = time.time()
        wsum = warmup_mod.warmup()
        aot_s = time.time() - t0
        mx.gauge("bench.stage_warmup_s").set(round(aot_s, 3))
        mx.gauge("bench.stage_warmup_compiles").set(wsum["backend_compiles"])
        mx.gauge("bench.stage_warmup_cache_hits").set(wsum["cache_hits"])

    # build B two-in/two-out transfer witness sets, then MEASURE proof
    # generation: a small host-prover sample for the denominator, and the
    # batched device prover (`TransferProver.batch` -> stage tiles) for
    # the full batch — provegen is no longer dead wall-clock, it is the
    # prove-side throughput number (`prove_txs_per_s`).
    hb.set_phase("provegen", batch=B)
    reqs = []
    for i in range(B):
        in_toks, in_w = tok.tokens_with_witness([100, 55], "USD", pp.ped_params, rng)
        out_toks, out_w = tok.tokens_with_witness([120, 35], "USD", pp.ped_params, rng)
        reqs.append((in_w, out_w, in_toks, out_toks))
    # Device-measured sub-batch: the WHOLE batch on a real accelerator;
    # a bounded slice on the CPU fallback, where the emulated data plane
    # is orders slower than the native host prover and proving all B
    # would burn the internal deadline before the verify measurement
    # this bench exists for. The remainder is host-proved — device and
    # host proofs are byte-compatible, so the verify corpus is uniform.
    if "FTS_BENCH_PROVE_TXS" in os.environ:
        n_dev = max(1, min(B, int(os.environ["FTS_BENCH_PROVE_TXS"])))
    else:
        n_dev = B if platform != "cpu" else min(B, 8)

    # host-prover sample for the prove_vs_host denominator, drawn from
    # the host-proved REMAINDER when one exists so its proofs are reused
    # for the corpus (no duplicate full host proofs on the CPU path)
    n_host = max(1, min(int(os.environ.get("FTS_BENCH_PROVE_HOST_SAMPLE", "2")), B))
    sample = list(range(n_dev, min(B, n_dev + n_host))) or list(range(n_host))
    host_proofs = {}
    t0 = time.time()
    for i in sample:
        host_proofs[i] = transfer.TransferProver(*reqs[i], pp, rng).prove()
    host_prove_s = time.time() - t0
    host_rate = len(sample) / host_prove_s if host_prove_s > 0 else 0.0
    mx.gauge("bench.provegen_host_s").set(round(host_prove_s, 3))

    hb.set_phase("provegen_batched", txs=n_dev, batch=B)
    fall_before = mx.REGISTRY.counter("batch.prove.host_fallbacks").value
    t0 = time.time()
    proofs = transfer.TransferProver.batch(
        reqs[:n_dev], pp, rng=rng, min_batch=1
    )
    gen_s = time.time() - t0
    # a silent device->host degrade must not masquerade as a device
    # number: flag the measurement so the recorded prove throughput is
    # never mislabeled
    prove_degraded = (
        mx.REGISTRY.counter("batch.prove.host_fallbacks").value > fall_before
    )
    prove_rate = n_dev / gen_s if gen_s > 0 else 0.0
    mx.gauge("bench.prove_txs_per_s").set(round(prove_rate, 3))
    mx.gauge("bench.prove_degraded").set(1 if prove_degraded else 0)
    for i in range(n_dev, B):
        proofs.append(
            host_proofs.get(i)
            or transfer.TransferProver(*reqs[i], pp, rng).prove()
        )
    txs = [(r[2], r[3], p) for r, p in zip(reqs, proofs)]

    verifier = batch_mod.BatchedTransferVerifier(pp)
    # first verify: with a warm cache this is pure runtime (the compile
    # histogram in the sidecar proves whether any backend compile fired)
    hb.set_phase("warmup_compile", batch=B)
    t0 = time.time()
    ok = verifier.verify(txs)
    warm_s = time.time() - t0
    assert bool(np.all(ok)), "benchmark proofs failed to verify"

    # timed runs — optionally under a programmatic jax.profiler capture
    # (FTS_PROFILE=1): the trace of the measured region lands in a
    # sidecar dir next to the metrics sidecar, for TensorBoard/XProf
    runs = int(os.environ.get("FTS_BENCH_RUNS", "3"))
    hb.set_phase("timed_runs", runs=runs)
    profile_dir = None
    if os.environ.get("FTS_PROFILE", "0") not in ("", "0"):
        profile_dir = _profile_dir()
        try:
            import jax

            jax.profiler.start_trace(profile_dir)
            mx.counter("profile.captures").inc()
            mx.REGISTRY.set_meta("profile.dir", profile_dir)
        except Exception as e:  # profiling must never cost the headline
            print(f"[fts-bench] profiler capture failed to start: {e}",
                  file=sys.stderr, flush=True)
            profile_dir = None
    t0 = time.time()
    for _ in range(runs):
        ok = verifier.verify(txs)
    elapsed = time.time() - t0
    if profile_dir is not None:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
    rate = B * runs / elapsed

    mx.gauge("bench.throughput_tx_per_s").set(round(rate, 2))
    mx.gauge("bench.warmup_s").set(round(warm_s, 3))
    mx.gauge("bench.provegen_s").set(round(gen_s, 3))
    mx.gauge("bench.setup_s").set(round(setup_s, 3))

    result = headline_result(
        rate=rate, platform=platform, batch=B, runs=runs, warm_s=warm_s,
        provegen_s=gen_s, provegen_host_s=host_prove_s, prove_txs=n_dev,
        prove_rate=prove_rate, host_rate=host_rate,
        prove_degraded=prove_degraded, setup_s=setup_s,
        stage_warmup_s=float(mx.REGISTRY.gauge("bench.stage_warmup_s").value or 0),
    )
    # device-plane dispatch ledger of the headline phase (occupancy,
    # padding waste, per-program compile forensics — utils/devobs.py);
    # refreshed after the soak so the recorded section covers every
    # phase that dispatched
    from fabric_token_sdk_tpu.utils import devobs

    if devobs.enabled():
        result["device"] = devobs.section()
    # The headline is secured the moment it exists: print it (and disarm
    # the watchdog) BEFORE the fallible block phase, so a hang or crash
    # there can never cost the completed accelerator measurement.
    print(json.dumps(result), flush=True)
    mx.flight("bench.result", value=result["value"], platform=platform)
    _done.set()

    # product-path block pipeline (orderer + batched block validation);
    # on success, ONE more enriched JSON line supersedes the headline for
    # last-line parsers (it is a strict superset of the same fields)
    if os.environ.get("FTS_BENCH_BLOCK", "1") != "0":
        scaling_ctx = {}
        try:
            result.update(
                _block_throughput(pp, rng, hb, platform,
                                  scaling_ctx=scaling_ctx)
            )
            print(json.dumps(result), flush=True)
        except Exception as e:  # pragma: no cover
            print(
                f"[fts-bench] block_throughput phase failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
                flush=True,
            )
        # throughput-vs-devices curve (FTS_BENCH_SCALING=0 opts out):
        # runs AFTER the enriched line is secured; on success one final
        # superset line carries the `scaling` list for last-line parsers
        if scaling_ctx and os.environ.get("FTS_BENCH_SCALING", "1") != "0":
            try:
                curve = _scaling_sweep(scaling_ctx, hb)
                if curve:
                    result["scaling"] = curve
                    print(json.dumps(result), flush=True)
            except Exception as e:  # pragma: no cover
                print(
                    f"[fts-bench] scaling sweep failed: "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                    flush=True,
                )

    # sustained-load soak against one pipelined, admission-controlled
    # node (FTS_BENCH_SOAK=0 opts out): steady-state tx/s, client p99
    # finality, queue-depth stability and backpressure rejects join the
    # result as the validated `soak` section — one more superset line
    if os.environ.get("FTS_BENCH_SOAK", "1") != "0":
        try:
            soak = _soak(hb)
            if soak:
                # profile/slo ride inside the soak dict so direct _soak
                # callers (tests) see them; in the recorded result they
                # are schema-validated top-level sections of their own
                for section in ("profile", "slo", "device", "host"):
                    if section in soak:
                        result[section] = soak.pop(section)
                result["soak"] = soak
                print(json.dumps(result), flush=True)
        except Exception as e:  # pragma: no cover
            print(
                f"[fts-bench] soak phase failed: {type(e).__name__}: {e}",
                file=sys.stderr,
                flush=True,
            )

    # kill-the-leader chaos-soak rider (FTS_BENCH_SOAK_FAILOVER=1 opts
    # IN): leader + follower + lease-watchdog promotion under live
    # exactly-once client traffic; the replication contract joins the
    # result as the validated `failover` section
    if os.environ.get("FTS_BENCH_SOAK_FAILOVER", "0") == "1":
        try:
            failover = _failover_soak(hb)
            if failover:
                result["failover"] = failover
                print(json.dumps(result), flush=True)
        except Exception as e:  # pragma: no cover
            print(
                f"[fts-bench] failover soak phase failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
                flush=True,
            )

    # state-plane scale rider (FTS_BENCH_STATE=0 opts out): million-token
    # persistent vault populate/recover + concurrent select+spend p99 —
    # host-only (no device work), one more superset line on success
    if os.environ.get("FTS_BENCH_STATE", "1") != "0":
        try:
            state = _state_scale(hb)
            if state:
                result["state"] = state
                print(json.dumps(result), flush=True)
        except Exception as e:  # pragma: no cover
            print(
                f"[fts-bench] state_scale phase failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
                flush=True,
            )

    # one observatory line per run: the final (enriched if the block
    # phase succeeded, else headline) result joins BENCH_history.jsonl
    append_history(result)
    hb.set_phase("done")
    hb.stop()
    mx.flush_sidecar()


if __name__ == "__main__":
    main()
