"""Transfer action proof: well-formedness + range correctness.

Reference: `crypto/transfer/transfer.go` (Prover/Verifier composition; the
range proof is skipped for 1-in-1-out ownership transfers) and
`crypto/transfer/sender.go` (action assembly).
"""

from __future__ import annotations

import os
import random as _random
from dataclasses import dataclass
from typing import List, Optional, Sequence

from . import hostmath as hm, rangeproof, wellformedness as wf
from .setup import PublicParams
from .serialization import guard, dumps, loads
from .token import TokenDataWitness
from ..utils import metrics as mx, resilience


def _prove_min_batch() -> int:
    try:
        return max(1, int(os.environ.get("FTS_PROVE_MIN_BATCH", "2")))
    except ValueError:
        return 2


@dataclass
class TransferProof:
    wf: bytes
    range_correctness: Optional[bytes]

    def to_bytes(self) -> bytes:
        return dumps({"wf": self.wf, "rc": self.range_correctness})

    @classmethod
    def from_bytes(cls, raw: bytes) -> "TransferProof":
        d = loads(raw)
        return cls(d["wf"], d["rc"])


def _skip_range(n_in: int, n_out: int) -> bool:
    # ownership transfer: single input, single output, conservation is
    # enough (reference transfer.go:55-59)
    return n_in == 1 and n_out == 1


class TransferProver:
    def __init__(
        self,
        in_witnesses: Sequence[TokenDataWitness],
        out_witnesses: Sequence[TokenDataWitness],
        inputs,
        outputs,
        pp: PublicParams,
        rng=None,
    ):
        self.wf_prover = wf.TransferWFProver(
            wf.TransferWFWitness(
                token_type=in_witnesses[0].token_type,
                in_values=[w.value for w in in_witnesses],
                in_bfs=[w.bf for w in in_witnesses],
                out_values=[w.value for w in out_witnesses],
                out_bfs=[w.bf for w in out_witnesses],
            ),
            pp.ped_params,
            inputs,
            outputs,
            rng,
        )
        self.range_prover = None
        if not _skip_range(len(inputs), len(outputs)):
            rp = pp.range_params
            self.range_prover = rangeproof.RangeProver(
                [rangeproof.TokenWitness(w.token_type, w.value, w.bf) for w in out_witnesses],
                outputs,
                rp.signed_values,
                rp.base,
                rp.exponent,
                pp.ped_params,
                rp.sign_pk,
                pp.ped_gen,
                rp.Q,
                rng,
            )

    def prove(self) -> bytes:
        # total proves = path.native + path.python
        mx.counter(
            "transfer.prove.path.native" if hm.NATIVE_G1
            else "transfer.prove.path.python"
        ).inc()
        with mx.span("transfer.prove"):
            return TransferProof(
                wf=self.wf_prover.prove(),
                range_correctness=self.range_prover.prove() if self.range_prover else None,
            ).to_bytes()

    @classmethod
    def batch(
        cls,
        requests: Sequence[tuple],
        pp: PublicParams,
        rng=None,
        min_batch: Optional[int] = None,
        prover=None,
    ) -> List[bytes]:
        """Prove many transfers, routing same-shape groups of at least
        `min_batch` (default FTS_PROVE_MIN_BATCH=2) through the batched
        device plane (`crypto/batch_prove.py` over the `ops/stages.py`
        tiles). Degrade-only contract, same as block validation: ANY
        device-plane error falls back to the host prover for that group
        — batching can only accelerate, never lose, a proof. Each group
        dispatch is bounded (`FTS_DEVICE_DEADLINE_S`, prove plane:
        unbounded by default) and guarded by the `prove` circuit
        breaker (utils/resilience.py): when open, groups host-prove
        immediately; a half-open probe re-engages the device plane.

        `requests`: tuples of `(in_witnesses, out_witnesses, inputs,
        outputs)` — the host constructor's arguments. Returns proof bytes
        in request order, byte-compatible with `prove()` output.
        """
        reqs = list(requests)
        if not reqs:
            return []
        if min_batch is None:
            min_batch = _prove_min_batch()

        groups = {}
        for idx, r in enumerate(reqs):
            groups.setdefault((len(r[2]), len(r[3])), []).append(idx)

        out: List[Optional[bytes]] = [None] * len(reqs)

        def host(indices, fallback=False):
            # counted per tx AFTER each successful prove, so an exception
            # mid-group (malformed witness etc.) never overcounts; the
            # fallback counter likewise only records txs the host plane
            # actually recovered — a request both planes reject is caller
            # error, not a device fault
            for i in indices:
                iw, ow, inputs, outputs = reqs[i]
                out[i] = cls(iw, ow, inputs, outputs, pp, rng).prove()
                mx.counter("batch.prove.host").inc()
                if fallback:
                    mx.counter("batch.prove.host_fallbacks").inc()

        brk = resilience.breaker("prove")
        deadline_s = resilience.device_deadline_s("prove")
        for shape, indices in sorted(groups.items()):
            if len(indices) < min_batch:
                host(indices)
                continue
            if not brk.allow():
                # open breaker: the device prove plane is sick — host-
                # prove this group immediately instead of paying another
                # failure/deadline (no fallback count: no device error
                # happened on THIS group)
                host(indices)
                continue
            if deadline_s > 0:
                # bounded dispatch may ABANDON the device worker mid-
                # prove; each group's worker must own an independent rng
                # stream (forked by one atomic draw per group, on the
                # caller's thread) or a straggler would race the host
                # fallback's — or the NEXT group's — draws on a shared
                # rng. Unbounded dispatch runs inline with the caller's
                # rng — proof bytes stay deterministic under a fixed
                # seed.
                dev_rng = _random.Random(
                    rng.getrandbits(64) if rng is not None else None
                )
            else:
                dev_rng = rng
            try:
                if prover is None:
                    # lazy: host-only callers never pull in the jax stack
                    from .batch_prove import prover_for

                    prover = prover_for(pp)

                def _device_prove(prover=prover, rng=dev_rng,
                                  group=[reqs[i] for i in indices]):
                    return prover.prove(group, rng)

                proofs = resilience.bounded_call(
                    _device_prove, deadline_s, plane="prove"
                )
                for i, p in zip(indices, proofs):
                    out[i] = p
            except resilience.DeviceTimeout:
                brk.record_failure(timeout=True)
                host(indices, fallback=True)
            except Exception:
                brk.record_failure()
                host(indices, fallback=True)
            else:
                brk.record_success()
        return out


class TransferVerifier:
    def __init__(self, inputs, outputs, pp: PublicParams):
        self.wf_verifier = wf.TransferWFVerifier(pp.ped_params, inputs, outputs)
        self.range_verifier = None
        if not _skip_range(len(inputs), len(outputs)):
            rp = pp.range_params
            self.range_verifier = rangeproof.RangeVerifier(
                outputs, rp.base, rp.exponent, pp.ped_params, rp.sign_pk, pp.ped_gen, rp.Q
            )

    @guard
    def verify(self, raw: bytes) -> None:
        mx.counter("transfer.verify.count").inc()
        with mx.span("transfer.verify"):
            proof = TransferProof.from_bytes(raw)
            self.wf_verifier.verify(proof.wf)
            if self.range_verifier is not None:
                if proof.range_correctness is None:
                    raise ValueError("invalid transfer proof: missing range proof")
                self.range_verifier.verify(proof.range_correctness)


def verify_transfer_proofs(specs, pp: PublicParams) -> List[Optional[bool]]:
    """Host-batched transfer proof verification.

    `specs` are (inputs, outputs, raw_proof) triples. Only range-skipped
    shapes (1-in/1-out ownership transfers, the shape that dominates
    traffic) are batch-decidable — for those the WF challenge compare IS
    the whole accept/reject decision, so a True here is exactly a
    `TransferVerifier.verify` accept. Shapes that carry a range proof, and
    proofs the batch cannot parse, return None: degrade-only, the scalar
    verifier re-runs them and owns the precise error.
    """
    specs = list(specs)
    out: List[Optional[bool]] = [None] * len(specs)
    wf_specs, idxs = [], []
    for i, (inputs, outputs, raw) in enumerate(specs):
        if not _skip_range(len(inputs), len(outputs)):
            continue
        try:
            proof = TransferProof.from_bytes(raw)
        except Exception:
            continue
        wf_specs.append((inputs, outputs, proof.wf))
        idxs.append(i)
    if not wf_specs:
        return out
    for i, v in zip(idxs, wf.verify_transfer_wfs(pp.ped_params, wf_specs)):
        out[i] = v
    return out
