"""One-out-of-many proofs (Groth–Kohlweiss) — reference `crypto/o2omp/3omp.go`.

Proves knowledge of (index l, randomness r) such that commitments[l] = h^r
(a commitment to 0), without revealing l. Used for graph-hiding /
serial-number style spend proofs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from . import hostmath as hm
from .serialization import guard, dumps, g1s_bytes, loads


@dataclass
class Proof:
    L: List[tuple]
    A: List[tuple]
    B: List[tuple]
    D: List[tuple]
    vL: List[int]
    vA: List[int]
    vB: List[int]
    vD: int

    def to_bytes(self) -> bytes:
        return dumps(
            {"L": self.L, "A": self.A, "B": self.B, "D": self.D,
             "vl": self.vL, "va": self.vA, "vb": self.vB, "vd": self.vD}
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Proof":
        d = loads(raw)
        return cls(d["L"], d["A"], d["B"], d["D"], d["vl"], d["va"], d["vb"], d["vd"])


def _poly_for_index(j: int, nbits: int, bits_l: List[int], a: List[int]) -> List[int]:
    """Coefficients of prod_i f_{j_i}(x), where f1 = b_i x + a_i and
    f0 = (1-b_i) x - a_i; degree nbits, little-endian coefficients."""
    coeffs = [1]
    for i in range(nbits):
        jbit = (j >> i) & 1
        if jbit:
            alpha, beta = bits_l[i], a[i]
        else:
            alpha, beta = 1 - bits_l[i], -a[i] % hm.R
        # multiply coeffs by (alpha x + beta)
        new = [0] * (len(coeffs) + 1)
        for d, c in enumerate(coeffs):
            new[d] = (new[d] + c * beta) % hm.R
            new[d + 1] = (new[d + 1] + c * alpha) % hm.R
        coeffs = new
    return coeffs


def _challenge(proof_coms, commitments, ped, nbits: int, message: bytes) -> int:
    raw = g1s_bytes(*proof_coms, commitments, ped) + str(nbits).encode() + message
    return hm.hash_to_zr(raw, b"fts/o2omp")


class Prover:
    def __init__(self, commitments, message: bytes, ped, nbits: int, index: int,
                 randomness: int, rng=None):
        self.commitments = list(commitments)
        self.message = message
        self.ped = list(ped)  # 2 bases (g, h)
        self.nbits = nbits
        self.index = index
        self.randomness = randomness
        self.rng = rng

    def prove(self) -> bytes:
        n = self.nbits
        if len(self.commitments) != 1 << n:
            raise ValueError("number of commitments is not 2^bitlength")
        g, h = self.ped
        bits_l = [(self.index >> i) & 1 for i in range(n)]
        a = [hm.rand_zr(self.rng) for _ in range(n)]
        r = [hm.rand_zr(self.rng) for _ in range(n)]
        s = [hm.rand_zr(self.rng) for _ in range(n)]
        t = [hm.rand_zr(self.rng) for _ in range(n)]
        rho = [hm.rand_zr(self.rng) for _ in range(n)]

        L = [
            hm.g1_add(hm.g1_mul(h, r[i]), g if bits_l[i] else None) for i in range(n)
        ]
        A = [hm.g1_multiexp([g, h], [a[i], s[i]]) for i in range(n)]
        B = [
            hm.g1_add(hm.g1_mul(h, t[i]), hm.g1_mul(g, a[i]) if bits_l[i] else None)
            for i in range(n)
        ]
        D = []
        polys = [_poly_for_index(j, n, bits_l, a) for j in range(len(self.commitments))]
        for i in range(n):
            di = hm.g1_mul(h, rho[i])
            for j, cj in enumerate(self.commitments):
                if polys[j][i]:
                    di = hm.g1_add(di, hm.g1_mul(cj, polys[j][i]))
            D.append(di)

        chal = _challenge((L, A, B, D), self.commitments, self.ped, n, self.message)

        vL = [(a[i] + (chal if bits_l[i] else 0)) % hm.R for i in range(n)]
        vA = [(s[i] + r[i] * chal) % hm.R for i in range(n)]
        vB = [(t[i] + r[i] * ((chal - vL[i]) % hm.R)) % hm.R for i in range(n)]
        vD = (self.randomness * pow(chal, n, hm.R) - sum(
            rho[i] * pow(chal, i, hm.R) for i in range(n)
        )) % hm.R
        return Proof(L, A, B, D, vL, vA, vB, vD).to_bytes()


class Verifier:
    def __init__(self, commitments, message: bytes, ped, nbits: int):
        self.commitments = list(commitments)
        self.message = message
        self.ped = list(ped)
        self.nbits = nbits

    @guard
    def verify(self, raw: bytes) -> None:
        n = self.nbits
        if len(self.commitments) != 1 << n:
            raise ValueError("number of commitments is not 2^bitlength")
        p = Proof.from_bytes(raw)
        if any(len(x) != n for x in (p.L, p.A, p.B, p.D, p.vL, p.vA, p.vB)):
            raise ValueError("one-out-of-many proof not well formed")
        g, h = self.ped
        chal = _challenge((p.L, p.A, p.B, p.D), self.commitments, self.ped, n, self.message)
        for i in range(n):
            # L_i^c * A_i == g^{vL_i} h^{vA_i}
            lhs = hm.g1_add(hm.g1_mul(p.L[i], chal), p.A[i])
            if lhs != hm.g1_multiexp([g, h], [p.vL[i], p.vA[i]]):
                raise ValueError("one-out-of-many proof: first equation failed")
            # L_i^{c - vL_i} * B_i == h^{vB_i}
            lhs = hm.g1_add(hm.g1_mul(p.L[i], (chal - p.vL[i]) % hm.R), p.B[i])
            if lhs != hm.g1_mul(h, p.vB[i]):
                raise ValueError("one-out-of-many proof: second equation failed")
        acc = None
        for j, cj in enumerate(self.commitments):
            f = 1
            for i in range(n):
                f = f * (p.vL[i] if (j >> i) & 1 else (chal - p.vL[i])) % hm.R
            acc = hm.g1_add(acc, hm.g1_mul(cj, f))
        for i in range(n):
            acc = hm.g1_add(acc, hm.g1_neg(hm.g1_mul(p.D[i], pow(chal, i, hm.R))))
        if acc != hm.g1_mul(h, p.vD):
            raise ValueError("one-out-of-many proof: third equation failed")
