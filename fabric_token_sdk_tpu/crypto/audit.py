"""Auditing of zkatdlog tokens (reference `crypto/audit/auditor.go`).

The auditor receives token openings (audit info) alongside actions,
recomputes every commitment, inspects owners, and signs the request.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

from . import hostmath as hm, pedersen
from .token import Metadata, Token


@dataclass
class TokenDataOpening:
    token_type: str
    value: int
    bf: int


@dataclass
class OwnerOpening:
    owner_info: bytes


@dataclass
class AuditableToken:
    token: Token
    data: TokenDataOpening
    owner: OwnerOpening


def auditable_token(token: Token, owner_info: bytes, token_type: str, value: int, bf: int) -> AuditableToken:
    return AuditableToken(
        token=token,
        data=TokenDataOpening(token_type, value, bf),
        owner=OwnerOpening(owner_info),
    )


class Auditor:
    """Checks openings against commitments and endorses token requests."""

    def __init__(self, ped_params, signer=None, inspect_owner: Optional[Callable] = None):
        self.ped_params = list(ped_params)
        self.signer = signer
        self.inspect_owner = inspect_owner

    def check_token(self, at: AuditableToken) -> None:
        com = pedersen.token_commitment(
            at.data.token_type, at.data.value, at.data.bf, self.ped_params
        )
        if com != at.token.data:
            raise ValueError("audit check failed: opening does not match token commitment")
        if self.inspect_owner is not None and not at.token.is_redeem():
            self.inspect_owner(at)

    def check(self, inputs: Sequence[AuditableToken], outputs: Sequence[AuditableToken]) -> None:
        for at in list(inputs) + list(outputs):
            self.check_token(at)

    def endorse(self, request_bytes: bytes, rng=None) -> bytes:
        """Sign an audited request (reference auditor.go Endorse)."""
        if self.signer is None:
            raise ValueError("auditor has no signing identity")
        return self.signer.sign(request_bytes, rng)
