"""zkatdlog public parameters + trusted setup (reference `crypto/setup.go`).

PublicParams carry: Pedersen generators, range-proof parameters (PS public
key, Q, PS signatures on 0..base-1, exponent), nym (pseudonym) generators,
auditor/issuer identities, and the quantity precision.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional

from . import hostmath as hm, pssign
from .serialization import dumps, loads

DLOG_LABEL = "zkatdlog"
DEFAULT_PRECISION = 64


@dataclass
class RangeProofParams:
    sign_pk: List[tuple]  # 3 x G2
    Q: tuple  # G2
    signed_values: List[pssign.Signature]  # PS sigs on 0..base-1
    exponent: int

    @property
    def base(self) -> int:
        return len(self.signed_values)

    def validate(self) -> None:
        if len(self.sign_pk) != 3:
            raise ValueError(
                f"invalid range proof parameters: signature public key should be 3, got {len(self.sign_pk)}"
            )
        if len(self.signed_values) < 2:
            raise ValueError("invalid range proof parameters: signed values should be >= 2")
        if self.Q is None:
            raise ValueError("invalid range proof parameters: generator Q is nil")
        if self.exponent == 0:
            raise ValueError("invalid range proof parameters: exponent is 0")


@dataclass
class PublicParams:
    label: str = DLOG_LABEL
    curve: str = "bn254"
    ped_gen: Optional[tuple] = None  # G1: obfuscation base / PedGen
    ped_params: List[tuple] = field(default_factory=list)  # 3 x G1
    range_params: Optional[RangeProofParams] = None
    nym_params: List[tuple] = field(default_factory=list)  # 2 x G1 (pseudonyms)
    auditor: bytes = b""
    issuers: List[bytes] = field(default_factory=list)
    quantity_precision: int = DEFAULT_PRECISION

    # ---- capability flags (driver API parity: setup.go:99-108) ----
    def token_data_hiding(self) -> bool:
        return True

    def graph_hiding(self) -> bool:
        return False

    def identifier(self) -> str:
        return self.label

    def max_token_value(self) -> int:
        return self.range_params.base ** self.range_params.exponent - 1

    def precision(self) -> int:
        return self.quantity_precision

    def add_auditor(self, identity: bytes) -> None:
        self.auditor = identity

    def add_issuer(self, identity: bytes) -> None:
        self.issuers.append(identity)

    def auditors(self) -> List[bytes]:
        return [self.auditor] if self.auditor else []

    # ---------------------------------------------------- serialization

    def serialize(self) -> bytes:
        return dumps(
            {
                "identifier": self.label,
                "curve": self.curve,
                "ped_gen": self.ped_gen,
                "ped_params": self.ped_params,
                "range": {
                    "pk": self.range_params.sign_pk,
                    "q": self.range_params.Q,
                    "sigs": [[s.R, s.S] for s in self.range_params.signed_values],
                    "exp": self.range_params.exponent,
                },
                "nym": self.nym_params,
                "auditor": self.auditor,
                "issuers": list(self.issuers),
                "precision": self.quantity_precision,
            }
        )

    @classmethod
    def deserialize(cls, raw: bytes, label: str = DLOG_LABEL) -> "PublicParams":
        d = loads(raw)
        if d["identifier"] != label:
            raise ValueError(
                f"invalid identifier, expecting [{label}], got [{d['identifier']}]"
            )
        rp = RangeProofParams(
            sign_pk=d["range"]["pk"],
            Q=d["range"]["q"],
            signed_values=[pssign.Signature(r, s) for r, s in d["range"]["sigs"]],
            exponent=d["range"]["exp"],
        )
        return cls(
            label=d["identifier"],
            curve=d["curve"],
            ped_gen=d["ped_gen"],
            ped_params=d["ped_params"],
            range_params=rp,
            nym_params=d["nym"],
            auditor=d["auditor"],
            issuers=d["issuers"],
            quantity_precision=d["precision"],
        )

    def compute_hash(self) -> bytes:
        return hashlib.sha256(self.serialize()).digest()

    def validate(self) -> None:
        if self.ped_gen is None:
            raise ValueError("invalid public parameters: nil Pedersen generator")
        if len(self.ped_params) != 3:
            raise ValueError(
                f"invalid public parameters: length mismatch in Pedersen parameters [{len(self.ped_params)} vs. 3]"
            )
        if len(self.nym_params) != 2:
            raise ValueError("invalid public parameters: nym parameters should be 2")
        if self.range_params is None:
            raise ValueError("invalid public parameters: nil range proof parameters")
        self.range_params.validate()
        if self.quantity_precision != DEFAULT_PRECISION:
            raise ValueError(
                f"invalid public parameters: quantity precision should be {DEFAULT_PRECISION}"
            )
        g1_points = [self.ped_gen] + self.ped_params + self.nym_params
        for s in self.range_params.signed_values:
            g1_points += [s.R, s.S]
        for pt in g1_points:
            if pt is not None and not hm.g1_is_on_curve(pt):
                raise ValueError("invalid public parameters: G1 point not on curve")
        # G2 elements feed pairing equations: enforce r-torsion membership
        # (small-subgroup hardening, cf. hostmath.g2_from_bytes)
        for q in [self.range_params.Q] + self.range_params.sign_pk:
            if not hm.g2_in_subgroup(q):
                raise ValueError("invalid public parameters: G2 point not in subgroup")


def setup(base: int, exponent: int, label: str = DLOG_LABEL, rng=None) -> PublicParams:
    """Trusted setup (reference setup.go:210-236).

    Generates Pedersen + nym generators and PS-signs 0..base-1 for the
    range proof. The PS secret key is discarded.
    """
    signer = pssign.keygen(1, rng)
    signed = [signer.sign([v], rng) for v in range(base)]
    pp = PublicParams(label=label)
    pp.ped_gen = hm.rand_g1(rng)
    pp.ped_params = [hm.rand_g1(rng) for _ in range(3)]
    pp.nym_params = [hm.rand_g1(rng) for _ in range(2)]
    pp.range_params = RangeProofParams(
        sign_pk=signer.pk, Q=signer.Q, signed_values=signed, exponent=exponent
    )
    return pp
