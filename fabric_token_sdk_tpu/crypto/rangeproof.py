"""Range proofs via PS-signed digit set-membership (reference `crypto/range/proof.go`).

Shows each token value v satisfies 0 <= v < base^exponent:
  v = sum_i d_i * base^i, each digit committed separately, each digit proven
  to carry a PS signature from the public signed set {0..base-1}
  (membership proofs), plus an equality sigma proof tying the token
  commitment to the digit commitments.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

from . import hostmath as hm, pssign, schnorr, sigproof
from .serialization import guard, dumps, g1s_bytes, g2s_bytes, loads


@dataclass
class TokenWitness:
    token_type: str
    value: int
    bf: int


@dataclass
class RangeProof:
    challenge: int
    type_resp: int
    value_resps: List[int]
    token_bf_resps: List[int]
    com_bf_resps: List[int]
    # per token: list of digit commitments + their membership proofs
    digit_commitments: List[List[tuple]]
    membership_proofs: List[List[sigproof.MembershipProof]]

    def to_bytes(self) -> bytes:
        return dumps(
            {
                "c": self.challenge,
                "t": self.type_resp,
                "v": self.value_resps,
                "tb": self.token_bf_resps,
                "cb": self.com_bf_resps,
                "dc": self.digit_commitments,
                "mp": [
                    [m.to_bytes() for m in row] for row in self.membership_proofs
                ],
            }
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "RangeProof":
        d = loads(raw)
        mps = [
            [sigproof.MembershipProof.from_bytes(m) for m in row] for row in d["mp"]
        ]
        return cls(d["c"], d["t"], d["v"], d["tb"], d["cb"], d["dc"], mps)


def decompose(value: int, base: int, exponent: int) -> List[int]:
    """v -> little-endian digits; raises if out of range."""
    if not 0 <= value < base**exponent:
        raise ValueError("value of token outside authorized range")
    digits = []
    v = value
    for _ in range(exponent):
        digits.append(v % base)
        v //= base
    return digits


class RangeVerifier:
    def __init__(self, tokens, base, exponent, ped_params, pk, P, Q):
        self.tokens = list(tokens)
        self.base = base
        self.exponent = exponent
        self.ped = list(ped_params)  # 3 bases (type, value, bf)
        self.pk = list(pk)  # 3 G2 (PS key for 1 message)
        self.P = P
        self.Q = Q

    def _challenge(self, com_tokens, com_values, digit_commitments) -> int:
        raw = g1s_bytes([self.P], self.tokens, com_tokens, com_values, self.ped)
        raw += g2s_bytes([self.Q], self.pk)
        for row in digit_commitments:
            raw += g1s_bytes(row)
        return hm.hash_to_zr(raw, b"fts/range")

    @guard
    def verify(self, raw: bytes) -> None:
        p = RangeProof.from_bytes(raw)
        n = len(self.tokens)
        if (
            len(p.membership_proofs) != n
            or len(p.digit_commitments) != n
            or len(p.value_resps) != n
            or len(p.token_bf_resps) != n
            or len(p.com_bf_resps) != n
        ):
            raise ValueError("range proof not well formed")
        # 1. each digit commitment carries a signed (in-range) value
        for k in range(n):
            if len(p.digit_commitments[k]) != self.exponent:
                raise ValueError("range proof not well formed")
            if len(p.membership_proofs[k]) != self.exponent:
                raise ValueError("range proof not well formed")
            for i in range(self.exponent):
                mv = sigproof.MembershipVerifier(
                    p.digit_commitments[k][i], self.P, self.Q, self.pk, self.ped[:2]
                )
                mv.verify(p.membership_proofs[k][i])
        # 2. equality proofs: token opens to (type, v, bf) with
        #    v = sum digits * base^i
        com_tokens = []
        com_values = []
        for k in range(n):
            sp = schnorr.SchnorrProof(
                self.tokens[k],
                [p.type_resp, p.value_resps[k], p.token_bf_resps[k]],
                p.challenge,
            )
            com_tokens.append(schnorr.recompute_commitment(self.ped, sp))
            agg = hm.g1_multiexp(
                p.digit_commitments[k],
                [self.base**i % hm.R for i in range(self.exponent)],
            )
            sp2 = schnorr.SchnorrProof(
                agg, [p.value_resps[k], p.com_bf_resps[k]], p.challenge
            )
            com_values.append(schnorr.recompute_commitment(self.ped[:2], sp2))
        if self._challenge(com_tokens, com_values, p.digit_commitments) != p.challenge:
            raise ValueError("invalid range proof")


@dataclass
class RangeDraw:
    """Witness decomposition + commit-phase randomness of one range proof.

    Drawn once, then consumed by either the host commit path
    (`RangeProver.prove`) or the batched device commit path
    (`crypto/batch_prove.py`); the response phase (`RangeProver.finish`)
    is shared, so device proving can only accelerate — never change —
    the emitted proof distribution.
    """

    digits: List[List[int]]  # per token: little-endian digits
    digit_bfs: List[List[int]]  # per (token, digit): commitment blinding
    mem: List[List[sigproof.MembershipDraw]]  # per (token, digit)
    rho_T: int
    rho_v: List[int]
    rho_tb: List[int]
    rho_cb: List[int]
    agg_bfs: List[int]  # per token: sum bf_i * base^i

    def equality_token_rows(self) -> List[List[int]]:
        """Scalar rows of the per-token equality commitments over the 3
        Pedersen bases (host `g1_multiexp` / device `g1_msm3` tile)."""
        return [
            [self.rho_T, self.rho_v[k], self.rho_tb[k]]
            for k in range(len(self.digits))
        ]

    def equality_value_rows(self) -> List[List[int]]:
        """Scalar rows of the per-token digit-aggregate commitments over
        ped[:2] (host `g1_multiexp` / device `g1_msm2` tile)."""
        return [
            [self.rho_v[k], self.rho_cb[k]] for k in range(len(self.digits))
        ]


class RangeProver(RangeVerifier):
    def __init__(
        self, witnesses: Sequence[TokenWitness], tokens, signatures, base, exponent,
        ped_params, pk, P, Q, rng=None,
    ):
        super().__init__(tokens, base, exponent, ped_params, pk, P, Q)
        self.witnesses = list(witnesses)
        self.signatures = list(signatures)  # PS signatures on 0..base-1
        self.rng = rng

    def draw(self) -> RangeDraw:
        n = len(self.tokens)
        digits = [
            decompose(self.witnesses[k].value, self.base, self.exponent)
            for k in range(n)
        ]
        digit_bfs = [
            [hm.rand_zr(self.rng) for _ in range(self.exponent)] for _ in range(n)
        ]
        mem = [
            [sigproof.membership_draw(self.rng) for _ in range(self.exponent)]
            for _ in range(n)
        ]
        agg_bfs = [
            sum(
                digit_bfs[k][i] * (self.base**i) for i in range(self.exponent)
            ) % hm.R
            for k in range(n)
        ]
        return RangeDraw(
            digits=digits,
            digit_bfs=digit_bfs,
            mem=mem,
            rho_T=hm.rand_zr(self.rng),
            rho_v=[hm.rand_zr(self.rng) for _ in range(n)],
            rho_tb=[hm.rand_zr(self.rng) for _ in range(n)],
            rho_cb=[hm.rand_zr(self.rng) for _ in range(n)],
            agg_bfs=agg_bfs,
        )

    def finish(
        self, d: RangeDraw, digit_coms: List[List[tuple]],
        mem_proofs: List[List[sigproof.MembershipProof]], chal: int,
    ) -> bytes:
        type_hash = hm.hash_to_zr(self.witnesses[0].token_type.encode())
        return RangeProof(
            challenge=chal,
            type_resp=schnorr.respond([type_hash], [d.rho_T], chal)[0],
            value_resps=schnorr.respond([w.value for w in self.witnesses], d.rho_v, chal),
            token_bf_resps=schnorr.respond([w.bf for w in self.witnesses], d.rho_tb, chal),
            com_bf_resps=schnorr.respond(d.agg_bfs, d.rho_cb, chal),
            digit_commitments=digit_coms,
            membership_proofs=mem_proofs,
        ).to_bytes()

    def prove(self) -> bytes:
        n = len(self.tokens)
        d = self.draw()
        digit_coms: List[List[tuple]] = []
        mem_proofs: List[List[sigproof.MembershipProof]] = []
        for k in range(n):
            row_coms, row_proofs = [], []
            for i, dig in enumerate(d.digits[k]):
                bf = d.digit_bfs[k][i]
                com = hm.g1_multiexp(self.ped[:2], [dig, bf])
                w = sigproof.MembershipWitness(self.signatures[dig], dig, bf)
                mp = sigproof.MembershipProver(
                    w, com, self.P, self.Q, self.pk, self.ped[:2], self.rng
                )
                row_coms.append(com)
                row_proofs.append(mp.prove(d.mem[k][i]))
            digit_coms.append(row_coms)
            mem_proofs.append(row_proofs)

        # equality sigma proof
        com_tokens = [
            hm.g1_multiexp(self.ped, row) for row in d.equality_token_rows()
        ]
        com_values = [
            hm.g1_multiexp(self.ped[:2], row) for row in d.equality_value_rows()
        ]
        chal = self._challenge(com_tokens, com_values, digit_coms)
        return self.finish(d, digit_coms, mem_proofs, chal)
