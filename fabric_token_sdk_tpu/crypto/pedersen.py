"""Pedersen vector commitments (host scalar path + batched TPU path).

Reference: `crypto/common/zkproof.go` ComputePedersenCommitment and the
token commitment computation in `crypto/token/token.go:64-76` (token data =
commit(hash(type), value; bf) over PedParams).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from . import hostmath as hm
from ..ops import curve as cv, limbs as lb, stages as st


def commit(openings: Sequence[int], bases: Sequence, curve=None):
    """Host: com = prod bases[i]^openings[i]."""
    if len(openings) != len(bases):
        raise ValueError(f"pedersen commit: {len(openings)} openings vs {len(bases)} bases")
    return hm.g1_multiexp(list(bases), [o % hm.R for o in openings])


class BatchedPedersen:
    """Batched fixed-base committer over the compile-once stage tiles.

    B commitments over the same bases run as ROW_TILE slabs of the
    canonical `g1_msm` tile (`ops/stages.py`), so the program count is
    independent of B — this is the commit engine of the batched transfer
    prover (`crypto/batch_prove.py`: WF announcements, digit
    commitments, equality announcements are all Pedersen rows here)."""

    def __init__(self, bases: Sequence):
        self.bases = list(bases)
        self.table = cv.FixedBaseTable(self.bases)

    def commit_rows(self, scalars: np.ndarray, dp=None) -> np.ndarray:
        """Canonical limb scalars (N, nbases, NLIMBS) -> (N, 3, NLIMBS)
        Jacobian numpy, via the shape-invariant msm stage tile. `dp`
        shards the tile dispatch (per-shard stage-tile dispatch — zero
        new programs, bit-identical output)."""
        return st.g1_msm_rows(self.table.flat, scalars, dp=dp)

    def commit_ints(self, openings_rows: Sequence[Sequence[int]], dp=None):
        """Host int rows -> (host points, device Jacobian): one flat limb
        encode, one tiled msm pass, one host decode."""
        rows = list(openings_rows)
        flat = cv.encode_scalars([s for row in rows for s in row])
        jac = self.commit_rows(
            flat.reshape(len(rows), len(self.bases), lb.NLIMBS), dp=dp
        )
        return cv.decode_points(jac), jac

    def commit_batch(self, openings_rows: Sequence[Sequence[int]]):
        """rows of per-base openings -> list of host G1 points."""
        return self.commit_ints(openings_rows)[0]

    def commit_device(self, scalars):
        """Fused device path: scalars (..., nbases, NLIMBS) canonical ->
        points. NOTE: compiles one program PER leading shape — prefer
        `commit_rows` (stage tiles) anywhere the shape varies."""
        return self.table.msm(scalars)


def token_commitment(token_type: str, value: int, bf: int, ped_params: Sequence):
    """Commitment to (hash(type), value; blinding) — TokenData.

    Reference: token/token.go:68-69.
    """
    return commit([hm.hash_to_zr(token_type.encode()), value, bf], ped_params)
