"""Pedersen vector commitments (host scalar path + batched TPU path).

Reference: `crypto/common/zkproof.go` ComputePedersenCommitment and the
token commitment computation in `crypto/token/token.go:64-76` (token data =
commit(hash(type), value; bf) over PedParams).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from . import hostmath as hm
from ..ops import curve as cv


def commit(openings: Sequence[int], bases: Sequence, curve=None):
    """Host: com = prod bases[i]^openings[i]."""
    if len(openings) != len(bases):
        raise ValueError(f"pedersen commit: {len(openings)} openings vs {len(bases)} bases")
    return hm.g1_multiexp(list(bases), [o % hm.R for o in openings])


class BatchedPedersen:
    """Batched fixed-base committer: B commitments over the same bases in
    one device program (one-hot window lookups + tree add)."""

    def __init__(self, bases: Sequence):
        self.bases = list(bases)
        self.table = cv.FixedBaseTable(self.bases)

    def commit_batch(self, openings_rows: Sequence[Sequence[int]]):
        """rows of per-base openings -> list of host G1 points."""
        scal = jnp.stack([cv.encode_scalars(row) for row in openings_rows])
        return cv.decode_points(self.table.msm(scal))

    def commit_device(self, scalars):
        """Device path: scalars (..., nbases, NLIMBS) canonical -> points."""
        return self.table.msm(scalars)


def token_commitment(token_type: str, value: int, bf: int, ped_params: Sequence):
    """Commitment to (hash(type), value; blinding) — TokenData.

    Reference: token/token.go:68-69.
    """
    return commit([hm.hash_to_zr(token_type.encode()), value, bf], ped_params)
