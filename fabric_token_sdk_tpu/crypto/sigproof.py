"""Proofs of knowledge of Pointcheval-Sanders signatures + set membership.

Reference: `crypto/sigproof/pok.go` and `crypto/sigproof/membership.go`.
A membership proof shows a Pedersen-committed value carries a valid PS
signature from a public signed set (the range-proof digit check).

Verification equation (pairing side), for obfuscated sig (R', S''):
  com_GT = [ e(S''^c, Q) * e(R'^c, -PK_0) ]^{-1}
           * e(R', sum_i PK_i^{z_m_i} + PK_h^{z_hash}) * e(P^{z_bf}, Q)
matches the prover's commitment e(R', PK^rho) * e(P^rho_bf, Q).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from . import hostmath as hm, pssign, schnorr
from .serialization import guard, dumps, g1s_bytes, g2s_bytes, loads


@dataclass
class POK:
    challenge: int
    signature: pssign.Signature  # obfuscated
    messages: List[int]  # responses
    bf_resp: int  # response for the signature blinding factor
    hash_resp: int  # response for the hash message

    def to_dict(self) -> dict:
        return {
            "c": self.challenge,
            "sr": self.signature.R,
            "ss": self.signature.S,
            "m": self.messages,
            "b": self.bf_resp,
            "h": self.hash_resp,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "POK":
        return cls(d["c"], pssign.Signature(d["sr"], d["ss"]), d["m"], d["b"], d["h"])


@dataclass
class POKVerifier:
    pk: List[tuple]  # G2, length l+2
    Q: tuple  # G2
    P: tuple  # G1 (obfuscation base, PedGen)

    def _message_term(self, msg_resps: Sequence[int], hash_resp: int):
        t = None
        for i, z in enumerate(msg_resps):
            t = hm.g2_add(t, hm.g2_mul(self.pk[i + 1], z))
        return hm.g2_add(t, hm.g2_mul(self.pk[-1], hash_resp))

    def recompute_commitment(self, p: POK):
        """GT commitment reconstruction (reference pok.go:163-204)."""
        if len(self.pk) != len(p.messages) + 2:
            raise ValueError("POK: public key does not match proof size")
        t = self._message_term(p.messages, p.hash_resp)
        sc = hm.g1_mul(p.signature.S, p.challenge)
        rc = hm.g1_mul(p.signature.R, p.challenge)
        return hm.pairing_product(
            [
                (hm.g1_neg(sc), self.Q),  # e(S''^c, Q)^-1
                (rc, self.pk[0]),  # e(R'^c, -PK0)^-1 = e(R'^c, PK0)... see below
                (p.signature.R, t),
                (hm.g1_mul(self.P, p.bf_resp), self.Q),
            ]
        )

    def challenge_bytes(self, com_gt, sig: pssign.Signature, extra: bytes = b"") -> int:
        raw = (
            g2s_bytes(self.pk, [self.Q])
            + g1s_bytes([self.P])
            + hm.gt_to_bytes(com_gt)
            + sig.transcript_bytes()
            + extra
        )
        return hm.hash_to_zr(raw, b"fts/ps-pok")


class POKProver(POKVerifier):
    def __init__(self, pk, Q, P, witness_sig: pssign.Signature, messages: Sequence[int], rng=None):
        super().__init__(pk=pk, Q=Q, P=P)
        self.witness_sig = witness_sig
        self.messages = list(messages)
        self.rng = rng

    def obfuscate(self):
        """sigma' = sigma^r; sigma'' = (R', S' * P^bf)."""
        rnd = pssign.SignVerifier(self.pk, self.Q).randomize(self.witness_sig, self.rng)
        bf = hm.rand_zr(self.rng)
        obf = pssign.Signature(rnd.R, hm.g1_add(rnd.S, hm.g1_mul(self.P, bf)))
        return rnd, obf, bf

    def commit(self, rnd_sig):
        rho_m = [hm.rand_zr(self.rng) for _ in self.messages]
        rho_h = hm.rand_zr(self.rng)
        rho_bf = hm.rand_zr(self.rng)
        t = self._message_term(rho_m, rho_h)
        com_gt = hm.pairing_product(
            [(rnd_sig.R, t), (hm.g1_mul(self.P, rho_bf), self.Q)]
        )
        return com_gt, rho_m, rho_h, rho_bf

    def prove(self, extra: bytes = b"") -> POK:
        rnd, obf, bf = self.obfuscate()
        com_gt, rho_m, rho_h, rho_bf = self.commit(rnd)
        chal = self.challenge_bytes(com_gt, obf, extra)
        msg_hash = pssign.hash_messages(self.messages)
        resp = schnorr.respond(
            self.messages + [msg_hash, bf], rho_m + [rho_h, rho_bf], chal
        )
        return POK(
            challenge=chal,
            signature=obf,
            messages=resp[: len(self.messages)],
            hash_resp=resp[len(self.messages)],
            bf_resp=resp[len(self.messages) + 1],
        )


def verify_pok(v: POKVerifier, p: POK, extra: bytes = b"") -> None:
    com = v.recompute_commitment(p)
    if v.challenge_bytes(com, p.signature, extra) != p.challenge:
        raise ValueError("invalid proof of knowledge of PS signature")


# ===================================================================
# Membership proof: committed value is in the signed set
# ===================================================================


@dataclass
class MembershipProof:
    challenge: int
    signature: pssign.Signature  # obfuscated PS signature on the value
    value_resp: int
    com_bf_resp: int
    sig_bf_resp: int
    hash_resp: int
    commitment: tuple  # Pedersen commitment to the value

    def to_bytes(self) -> bytes:
        return dumps(
            {
                "c": self.challenge,
                "sr": self.signature.R,
                "ss": self.signature.S,
                "v": self.value_resp,
                "cb": self.com_bf_resp,
                "sb": self.sig_bf_resp,
                "h": self.hash_resp,
                "com": self.commitment,
            }
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "MembershipProof":
        d = loads(raw)
        return cls(
            d["c"], pssign.Signature(d["sr"], d["ss"]), d["v"], d["cb"], d["sb"], d["h"], d["com"]
        )


@dataclass
class MembershipWitness:
    signature: pssign.Signature  # PS signature on value
    value: int
    com_bf: int  # blinding factor of the Pedersen commitment


@dataclass
class MembershipDraw:
    """Commit-phase randomness of one membership proof. Drawn up front so
    the host prover and the batched device prover (`crypto/batch_prove.py`)
    share one response path (`membership_finish`): the device plane only
    accelerates the group/pairing algebra of the commit phase.

    `r`       — PS signature randomizer (sigma' = sigma^r)
    `sig_bf`  — signature obfuscation blinding (S'' = S' + P^sig_bf)
    `rho_v`   — randomness for the committed value
    `rho_cb`  — randomness for the Pedersen commitment blinding
    `rho_h`   — randomness for the PS hash message
    `rho_bf`  — randomness for the signature blinding factor
    """

    r: int
    sig_bf: int
    rho_v: int
    rho_cb: int
    rho_h: int
    rho_bf: int


def membership_draw(rng=None) -> MembershipDraw:
    return MembershipDraw(
        r=hm.rand_zr(rng),
        sig_bf=hm.rand_zr(rng),
        rho_v=hm.rand_zr(rng),
        rho_cb=hm.rand_zr(rng),
        rho_h=hm.rand_zr(rng),
        rho_bf=hm.rand_zr(rng),
    )


def membership_finish(
    w: MembershipWitness, d: MembershipDraw, obf: pssign.Signature,
    chal: int, commitment,
) -> MembershipProof:
    """Fiat-Shamir response phase (pure Zr arithmetic — always host)."""
    msg_hash = pssign.hash_messages([w.value])
    z = schnorr.respond(
        [w.value, w.com_bf, msg_hash, d.sig_bf],
        [d.rho_v, d.rho_cb, d.rho_h, d.rho_bf],
        chal,
    )
    return MembershipProof(
        challenge=chal,
        signature=obf,
        value_resp=z[0],
        com_bf_resp=z[1],
        hash_resp=z[2],
        sig_bf_resp=z[3],
        commitment=commitment,
    )


class MembershipVerifier:
    """Checks a committed value is PS-signed (reference membership.go)."""

    def __init__(self, commitment, P, Q, pk, ped_params):
        self.commitment = commitment
        self.pok = POKVerifier(pk=list(pk), Q=Q, P=P)
        self.ped = list(ped_params)  # 2 bases: value, bf

    def _challenge(self, com_gt, com_to_value_rand, sig) -> int:
        raw = (
            g1s_bytes(self.ped, [self.commitment, com_to_value_rand, self.pok.P])
            + g2s_bytes(self.pok.pk, [self.pok.Q])
            + hm.gt_to_bytes(com_gt)
            + sig.transcript_bytes()
        )
        return hm.hash_to_zr(raw, b"fts/membership")

    @guard
    def verify(self, p: MembershipProof) -> None:
        if p.commitment != self.commitment:
            raise ValueError("membership proof commitment mismatch")
        pok = POK(
            challenge=p.challenge,
            signature=p.signature,
            messages=[p.value_resp],
            bf_resp=p.sig_bf_resp,
            hash_resp=p.hash_resp,
        )
        com_gt = self.pok.recompute_commitment(pok)
        sp = schnorr.SchnorrProof(self.commitment, [p.value_resp, p.com_bf_resp], p.challenge)
        com_val = schnorr.recompute_commitment(self.ped, sp)
        if self._challenge(com_gt, com_val, p.signature) != p.challenge:
            raise ValueError("invalid membership proof")


class MembershipProver(MembershipVerifier):
    def __init__(self, witness: MembershipWitness, commitment, P, Q, pk, ped_params, rng=None):
        super().__init__(commitment, P, Q, pk, ped_params)
        self.w = witness
        self.rng = rng

    def prove(self, d: Optional[MembershipDraw] = None) -> MembershipProof:
        if d is None:
            d = membership_draw(self.rng)
        rnd, obf = self.obfuscate(d)
        t = self.pok._message_term([d.rho_v], d.rho_h)
        com_gt = hm.pairing_product(
            [(rnd.R, t), (hm.g1_mul(self.pok.P, d.rho_bf), self.pok.Q)]
        )
        com_val = hm.g1_multiexp(self.ped, [d.rho_v, d.rho_cb])
        chal = self._challenge(com_gt, com_val, obf)
        return membership_finish(self.w, d, obf, chal, self.commitment)

    def obfuscate(self, d: MembershipDraw):
        """sigma' = sigma^r; sigma'' = (R', S' + P^sig_bf) — the host
        version of the batched prover's variable-base scalar-mul stage."""
        rnd = pssign.Signature(
            hm.g1_mul(self.w.signature.R, d.r), hm.g1_mul(self.w.signature.S, d.r)
        )
        obf = pssign.Signature(
            rnd.R, hm.g1_add(rnd.S, hm.g1_mul(self.pok.P, d.sig_bf))
        )
        return rnd, obf
