"""Canonical serialization for Fiat-Shamir transcripts and wire formats.

Mirrors the role of reference `crypto/common/array.go` (GetG1Array/Bytes):
deterministic byte strings fed to the challenge hash. JSON-with-hex is the
wire format for proofs/params (reference uses encoding/json of mathlib
types; ours is a cleaner explicit codec, not a byte-compatible one).
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from . import hostmath as hm


def g1s_bytes(*groups) -> bytes:
    """Concatenate canonical encodings of G1 points from several iterables."""
    out = bytearray()
    for group in groups:
        for pt in group:
            out += hm.g1_to_bytes(pt)
    return bytes(out)


def g2s_bytes(*groups) -> bytes:
    out = bytearray()
    for group in groups:
        for pt in group:
            out += hm.g2_to_bytes(pt)
    return bytes(out)


def zrs_bytes(*groups) -> bytes:
    out = bytearray()
    for group in groups:
        for z in group:
            out += hm.zr_to_bytes(z)
    return bytes(out)


# ------------------------------------------------------------ JSON wire fmt

def _enc(v: Any) -> Any:
    if v is None:
        return None
    if isinstance(v, bool):
        return v
    if isinstance(v, int):
        return hex(v)
    if isinstance(v, float):
        return {"f": repr(v)}
    if isinstance(v, bytes):
        return {"b": v.hex()}
    if isinstance(v, tuple):  # G1/G2 points or fp2 pairs, nested ints
        return {"t": [_enc(x) for x in v]}
    if isinstance(v, list):
        return [_enc(x) for x in v]
    if isinstance(v, dict):
        return {k: _enc(x) for k, x in v.items()}
    if isinstance(v, str):
        # wrapped so user strings can never be confused with hex ints
        return {"s": v}
    raise TypeError(f"cannot encode {type(v)}")


def _dec(v: Any) -> Any:
    if v is None:
        return None
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return int(v, 16)
    if isinstance(v, dict):
        if set(v) == {"b"}:
            return bytes.fromhex(v["b"])
        if set(v) == {"s"}:
            return v["s"]
        if set(v) == {"f"}:
            return float(v["f"])
        if set(v) == {"t"}:
            return tuple(_dec(x) for x in v["t"])
        return {k: _dec(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_dec(x) for x in v]
    return v


class MalformedProof(ValueError):
    """Raised when attacker-supplied bytes fail to parse as a valid proof."""


def guard(fn):
    """Decorator for verifier entry points: any structural error from
    malformed input becomes a ValueError (never a crash)."""

    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except ValueError:
            raise
        except Exception as e:  # TypeError/KeyError/IndexError from bad bytes
            raise MalformedProof(f"malformed proof: {type(e).__name__}: {e}") from e

    wrapped.__name__ = fn.__name__
    wrapped.__doc__ = fn.__doc__
    return wrapped


def dumps(obj: dict) -> bytes:
    return json.dumps(_enc(obj), sort_keys=True, separators=(",", ":")).encode()


def loads(raw: bytes) -> dict:
    return _dec(json.loads(raw.decode()))


# ------------------------------------------------------------ parse caches

_BYTES_CACHES: list = []


class BytesCache:
    """Bounded LRU raw-bytes -> parsed object for hot READ-ONLY decode
    paths (serialized actions, tokens): block validation decodes the same
    bytes several times per tx (plan hooks + validate), and chained
    transfers re-decode the previous tx's outputs as inputs.

    Cached objects are shared between callers — only use this for decodes
    whose consumers never mutate the result. Parse failures re-raise on
    every lookup and are never cached. Every instance shares the
    `parse.cache.{hits,misses}` counter family; capacity comes lazily
    from FTS_PARSE_CACHE (default 8192, 0 disables storage and counters)
    and re-resolves after `clear()`.
    """

    def __init__(self, parse: Callable[[bytes], Any],
                 capacity: Optional[int] = None):
        self._parse = parse
        self._from_env = capacity is None
        self._capacity = max(0, capacity) if capacity is not None else None
        self._entries: "OrderedDict[bytes, Any]" = OrderedDict()
        self._lock = threading.Lock()
        _BYTES_CACHES.append(self)

    @property
    def capacity(self) -> int:
        if self._capacity is None:
            try:
                self._capacity = max(
                    0, int(os.environ.get("FTS_PARSE_CACHE", "8192"))
                )
            except ValueError:
                self._capacity = 8192
        return self._capacity

    def lookup(self, raw: bytes) -> Any:
        if self.capacity == 0:  # disabled: no storage, no counters
            return self._parse(raw)
        from ..utils import metrics as _mx

        with self._lock:
            if raw in self._entries:
                self._entries.move_to_end(raw)
                entry = self._entries[raw]
                hit = True
            else:
                hit = False
        if hit:
            _mx.counter("parse.cache.hits").inc()
            return entry
        _mx.counter("parse.cache.misses").inc()
        entry = self._parse(raw)  # may raise — never cached
        with self._lock:
            self._entries[raw] = entry
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
        return entry

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            if self._from_env:
                self._capacity = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def parse_caches_clear() -> None:
    """Drop every registered bytes-parse cache (tests)."""
    for c in _BYTES_CACHES:
        c.clear()


_LOADS_CACHE = BytesCache(loads)


def loads_cached(raw: bytes) -> dict:
    """`loads` through the bounded parse cache — READ-ONLY results."""
    return _LOADS_CACHE.lookup(raw)
