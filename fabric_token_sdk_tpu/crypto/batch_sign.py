"""Batched (TPU) Schnorr signature verification — the sign-side twin of
the batched proof planes (`crypto/batch.py` / `crypto/batch_prove.py`).

Signatures were the LAST per-tx EC workload still executed
scalar-at-a-time on the host: every owner/issuer/auditor check costs two
pure-Python `g1_mul` calls (`crypto/sign.py`). Here a whole block's
`pk`-kind signature obligations verify as ONE flat-row pass over the
existing stage tiles:

    com_i = g^{z_i} · pk_i^{-c_i}

i.e. fixed-base msm for `g^z` (the 1-base `g1_msm1_tile`, same program
the membership verifier's `P^{z_bf}` term rides), variable-base
`g1_mul` for `pk^c`, and the Jacobian sub tile — EXACTLY the composition
`parallel/sharding.py:sharded_schnorr_rows` dispatches, so the plane
adds ZERO new XLA program shapes and the post-warmup zero-cache-miss
guarantee extends to signatures. The Fiat-Shamir re-hash (challenge
rebind per row) stays on host, like every other batched verifier.

Verdict contract (mirrors the proof plane): per-row True/False for rows
whose signature blob parsed, None for rows the collector could not even
parse — those re-verify on host, which reports the precise error. For
parsed rows the device verdict is mathematically identical to
`PublicKey.verify` (host `g1_mul` reduces scalars mod R exactly like the
canonical limb encoding, and the response equation is shared verbatim —
see `sign.response_commitment`), differential-pinned in
tests/test_batch_sign.py including bit-flipped `c`/`z`/message/pk rows.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import hostmath as hm, sign
from .batch import _MeshBound, _spanned
from .serialization import loads
from ..ops import curve as cv
from ..parallel.sharding import sharded_schnorr_rows
from ..utils import metrics as mx, resilience


class BatchedSchnorrVerifier(_MeshBound):
    """Verifies B long-term Schnorr signatures via the stage tiles.

    Rows are `(pk_point, message, sig_raw)` — the public-key POINT (from
    the identity cache, `drivers/identity.py:public_key`), the exact
    message bytes the host path would verify, and the raw signature
    blob. Unlike the proof verifiers there is no shape grouping: Schnorr
    rows are shape-uniform by construction, so one call covers a whole
    block regardless of how many txs/records contributed obligations.
    """

    def __init__(self, mesh=None):
        self.set_mesh(mesh)
        # windowed multiples of the generator (process-wide lru cache —
        # every verifier shares one table build); the 1-base msm PROGRAM
        # shape already exists (warmup's g1_msm1_tile) — tables are
        # runtime arguments, not program keys
        self.table = cv.generator_table(1)

    @_spanned("batch.sign.verify")
    def verify(
        self, rows: Sequence[Tuple[object, bytes, bytes]]
    ) -> List[Optional[bool]]:
        """-> per-row verdicts: True/False device verdict, None when the
        signature blob did not parse (host re-verify). Raises only on
        device-plane failures — the caller degrades those to host."""
        B = len(rows)
        if B == 0:
            return []
        mx.counter("batch.sign.batches").inc()
        parsed: List[Optional[Tuple[int, int]]] = []
        for _pk, _msg, sig_raw in rows:
            try:
                d = loads(sig_raw)
                chal, resp = d["c"], d["z"]
                if (
                    not isinstance(chal, int) or isinstance(chal, bool)
                    or not isinstance(resp, int) or isinstance(resp, bool)
                ):
                    raise ValueError("non-integer signature fields")
                parsed.append((chal, resp))
            except Exception:
                parsed.append(None)  # host path reports the precise error
        live = [i for i in range(B) if parsed[i] is not None]
        verdicts: List[Optional[bool]] = [None] * B
        if not live:
            return verdicts
        # flat rows: com = table^z - pk^c over the msm/mul/sub tiles
        resp_np = cv.encode_scalars([parsed[i][1] for i in live])[:, None, :]
        chal_np = cv.encode_scalars([parsed[i][0] for i in live])
        pk_np = np.stack([cv.encode_point(rows[i][0]) for i in live])
        coms = sharded_schnorr_rows(
            self.table, resp_np, pk_np, chal_np, mesh=self.mesh
        )
        com_pts = cv.decode_points(coms)
        # counted on COMPLETION only (PR-9 precedent): a device failure
        # above falls to host and must never report as device-verified —
        # nor may an ABANDONED bounded worker that completes late (its
        # rows were already counted as host fallbacks by the caller)
        if not resilience.call_abandoned():
            mx.counter("batch.sign.rows").inc(len(live))
        for j, i in enumerate(live):
            pk_point, message, _sig = rows[i]
            verdicts[i] = (
                sign.challenge(pk_point, com_pts[j], message) == parsed[i][0]
            )
        return verdicts
