"""Pointcheval-Sanders multi-message signatures + blind issuance.

Reference: `crypto/pssign/sign.go` (keygen/sign/verify/randomize) and
`crypto/pssign/blindsign.go` (ElGamal-encrypted blind signing with a
correctness proof). The signature underlies range-proof set membership and
PS-credential pseudonyms.

Scheme (asymmetric, messages m_1..m_l, plus an appended hash message):
  SK = (x_0 .. x_{l+1});  Q random G2;  PK_i = Q^{x_i}
  Sign:  R random G1;  S = R^{x_0 + sum_i x_i m_i + x_{l+1} H(m)}
  Verify: e(-S, Q) * e(R, PK_0 + sum PK_i^{m_i} + PK_{l+1}^{H(m)}) == 1
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from . import elgamal, hostmath as hm, schnorr
from .serialization import dumps, g1s_bytes, g2s_bytes, loads, zrs_bytes


def hash_messages(messages: Sequence[int]) -> int:
    """m_{l+1} = H(m_1..m_l) (reference sign.go:198-206)."""
    return hm.hash_to_zr(zrs_bytes(messages), b"fts/ps-msgs")


@dataclass
class Signature:
    R: tuple  # G1
    S: tuple  # G1

    def to_bytes(self) -> bytes:
        return dumps({"r": self.R, "s": self.S})

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Signature":
        d = loads(raw)
        return cls(d["r"], d["s"])

    def transcript_bytes(self) -> bytes:
        return g1s_bytes([self.R, self.S])


@dataclass
class SignVerifier:
    pk: List[tuple]  # G2 points, length l+2
    Q: tuple  # G2

    def message_base(self, messages: Sequence[int], msg_hash: Optional[int] = None):
        """PK_0 + sum PK_{i+1}^{m_i} + PK_{l+1}^{H(m)} in G2."""
        if msg_hash is None:
            msg_hash = hash_messages(messages)
        if len(messages) != len(self.pk) - 2:
            raise ValueError("PS verify: message count does not match public key")
        acc = self.pk[0]
        for i, m in enumerate(messages):
            acc = hm.g2_add(acc, hm.g2_mul(self.pk[i + 1], m))
        return hm.g2_add(acc, hm.g2_mul(self.pk[-1], msg_hash))

    def verify(self, messages: Sequence[int], sig: Signature) -> None:
        self.verify_with_hash(messages, None, sig)

    def verify_with_hash(self, messages, msg_hash: Optional[int], sig: Signature) -> None:
        """Verify with an explicit hash message (used by blind signing,
        where the hash binds the request proof instead of the messages)."""
        if sig.R is None or sig.S is None:
            raise ValueError("PS verify: nil signature")
        h = self.message_base(messages, msg_hash)
        ok = hm.gt_is_unity(
            hm.pairing_product([(hm.g1_neg(sig.S), self.Q), (sig.R, h)])
        )
        if not ok:
            raise ValueError("invalid Pointcheval-Sanders signature")

    def randomize(self, sig: Signature, rng=None) -> Signature:
        r = hm.rand_zr(rng)
        return Signature(hm.g1_mul(sig.R, r), hm.g1_mul(sig.S, r))


@dataclass
class Signer(SignVerifier):
    sk: List[int]

    def sign(self, messages: Sequence[int], rng=None) -> Signature:
        if len(messages) != len(self.sk) - 2:
            raise ValueError("PS sign: message count does not match secret key")
        R = hm.g1_mul(hm.G1_GEN, hm.rand_zr(rng))
        exp = self.sk[0]
        for i, m in enumerate(messages):
            exp = (exp + self.sk[i + 1] * m) % hm.R
        exp = (exp + self.sk[-1] * hash_messages(messages)) % hm.R
        return Signature(R, hm.g1_mul(R, exp))


def keygen(length: int, rng=None) -> Signer:
    """Keys to sign vectors of `length` messages (reference sign.go:43-66)."""
    Q = hm.g2_mul(hm.G2_GEN, hm.rand_zr(rng))
    sk = [hm.rand_zr(rng) for _ in range(length + 2)]
    pk = [hm.g2_mul(Q, x) for x in sk]
    return Signer(pk=pk, Q=Q, sk=sk)


# ===================================================================
# Blind signing (reference blindsign.go): the recipient commits to the
# messages, ElGamal-encrypts them, proves consistency; the signer signs
# homomorphically over the ciphertexts; the recipient decrypts + verifies.
# ===================================================================


@dataclass
class EncProof:
    messages: List[int]
    enc_randomness: List[int]
    com_bf: int
    challenge: int

    def to_bytes(self) -> bytes:
        return dumps(
            {"m": self.messages, "e": self.enc_randomness, "b": self.com_bf, "c": self.challenge}
        )


@dataclass
class BlindSignRequest:
    commitment: tuple  # Pedersen commitment to messages
    ciphertexts: List[elgamal.Ciphertext]
    proof: EncProof
    enc_pk: elgamal.PublicKey


@dataclass
class BlindSignResponse:
    msg_hash: int
    ciphertext: elgamal.Ciphertext


def _enc_challenge(ped, com, enc_pk, cts, c1_coms, c2_coms, com_com) -> int:
    raw = g1s_bytes(
        ped,
        [com, enc_pk.gen, enc_pk.h],
        [c.c1 for c in cts],
        [c.c2 for c in cts],
        c1_coms,
        c2_coms,
        [com_com],
    )
    return hm.hash_to_zr(raw, b"fts/ps-blind")


class Recipient:
    """Requests a blind PS signature on committed messages."""

    def __init__(self, messages, com_bf, commitment, enc_sk, ped_params, verifier, rng=None):
        self.messages = list(messages)
        self.com_bf = com_bf
        self.commitment = commitment
        self.enc_sk = enc_sk
        self.ped = list(ped_params)  # length l+1: bases for messages + bf
        self.verifier = verifier
        self.rng = rng
        self.enc_randomness: List[int] = []

    def request(self) -> BlindSignRequest:
        pk = self.enc_sk.pk
        # messages are encrypted in the exponent over the signature base
        # hash_to_g1(commitment) — the same base the signer uses for R
        # (reference blindsign.go:294-299)
        base = hm.hash_to_g1(hm.g1_to_bytes(self.commitment), b"fts/ps-base")
        cts = []
        self.enc_randomness = []
        for m in self.messages:
            ct, r = pk.encrypt_zr(m, base, self.rng)
            cts.append(ct)
            self.enc_randomness.append(r)
        # prove: commitment opens to messages AND ciphertexts encrypt them
        rho_m = [hm.rand_zr(self.rng) for _ in self.messages]
        rho_e = [hm.rand_zr(self.rng) for _ in self.messages]
        rho_bf = hm.rand_zr(self.rng)
        c1_coms = [hm.g1_mul(pk.gen, rho_e[i]) for i in range(len(self.messages))]
        c2_coms = [
            hm.g1_add(hm.g1_mul(base, rho_m[i]), hm.g1_mul(pk.h, rho_e[i]))
            for i in range(len(self.messages))
        ]
        com_com = hm.g1_multiexp(self.ped, rho_m + [rho_bf])
        chal = _enc_challenge(self.ped, self.commitment, pk, cts, c1_coms, c2_coms, com_com)
        proof = EncProof(
            messages=schnorr.respond(self.messages, rho_m, chal),
            enc_randomness=schnorr.respond(self.enc_randomness, rho_e, chal),
            com_bf=schnorr.respond([self.com_bf], [rho_bf], chal)[0],
            challenge=chal,
        )
        return BlindSignRequest(self.commitment, cts, proof, pk)

    def unblind(self, resp: BlindSignResponse) -> Signature:
        S = self.enc_sk.decrypt(resp.ciphertext)
        R = hm.hash_to_g1(hm.g1_to_bytes(self.commitment), b"fts/ps-base")
        sig = Signature(R, S)
        self.verifier.verify_with_hash(self.messages, resp.msg_hash, sig)
        return sig


# Backwards-compatible alias: verification with an explicit hash lives on
# SignVerifier directly.
VerifierWithHash = SignVerifier


class BlindSigner:
    def __init__(self, signer: Signer, ped_params):
        self.signer = signer
        self.ped = list(ped_params)

    def blind_sign(self, req: BlindSignRequest) -> BlindSignResponse:
        if len(req.ciphertexts) != len(self.signer.sk) - 2:
            raise ValueError("blind sign: ciphertext count does not match key")
        verify_enc_proof(self.ped, req)
        msg_hash = hm.hash_to_zr(req.proof.to_bytes(), b"fts/ps-blind-hash")
        base = hm.hash_to_g1(hm.g1_to_bytes(req.commitment), b"fts/ps-base")
        sk = self.signer.sk
        c1 = None
        c2 = hm.g1_mul(base, sk[0])
        for i, ct in enumerate(req.ciphertexts):
            c1 = hm.g1_add(c1, hm.g1_mul(ct.c1, sk[i + 1]))
            c2 = hm.g1_add(c2, hm.g1_mul(ct.c2, sk[i + 1]))
        c2 = hm.g1_add(c2, hm.g1_mul(base, sk[-1] * msg_hash % hm.R))
        return BlindSignResponse(msg_hash, elgamal.Ciphertext(c1, c2))


def verify_enc_proof(ped, req: BlindSignRequest) -> None:
    """Check the recipient's commitment/encryption consistency proof."""
    p, pk = req.proof, req.enc_pk
    n = len(req.ciphertexts)
    if len(p.messages) != n or len(p.enc_randomness) != n:
        raise ValueError("blind sign: malformed proof")
    c = p.challenge
    base = hm.hash_to_g1(hm.g1_to_bytes(req.commitment), b"fts/ps-base")
    c1_coms = [
        hm.g1_add(hm.g1_mul(pk.gen, p.enc_randomness[i]), hm.g1_neg(hm.g1_mul(req.ciphertexts[i].c1, c)))
        for i in range(n)
    ]
    c2_coms = [
        hm.g1_add(
            hm.g1_add(hm.g1_mul(base, p.messages[i]), hm.g1_mul(pk.h, p.enc_randomness[i])),
            hm.g1_neg(hm.g1_mul(req.ciphertexts[i].c2, c)),
        )
        for i in range(n)
    ]
    com_com = hm.g1_add(
        hm.g1_multiexp(ped, p.messages + [p.com_bf]),
        hm.g1_neg(hm.g1_mul(req.commitment, c)),
    )
    if _enc_challenge(ped, req.commitment, pk, req.ciphertexts, c1_coms, c2_coms, com_com) != c:
        raise ValueError("invalid blind-sign request proof")
