"""Issue action proof: well-formedness + range correctness.

Reference: `crypto/issue/issue.go` (Issue action + proof composition),
`crypto/issue/issuer.go` (anonymous issuer), `crypto/issue/nonanonym/`
(issuer identity in the clear).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from . import rangeproof, wellformedness as wf
from .setup import PublicParams
from .serialization import guard, dumps, loads
from .token import TokenDataWitness


@dataclass
class IssueProof:
    wf: bytes
    range_correctness: Optional[bytes]

    def to_bytes(self) -> bytes:
        return dumps({"wf": self.wf, "rc": self.range_correctness})

    @classmethod
    def from_bytes(cls, raw: bytes) -> "IssueProof":
        d = loads(raw)
        return cls(d["wf"], d["rc"])


class IssueProver:
    def __init__(
        self,
        witnesses: Sequence[TokenDataWitness],
        tokens,
        anonymous: bool,
        pp: PublicParams,
        rng=None,
    ):
        self.wf_prover = wf.IssueWFProver(
            [(w.token_type, w.value, w.bf) for w in witnesses],
            tokens,
            anonymous,
            pp.ped_params,
            rng,
        )
        rp = pp.range_params
        self.range_prover = rangeproof.RangeProver(
            [rangeproof.TokenWitness(w.token_type, w.value, w.bf) for w in witnesses],
            tokens,
            rp.signed_values,
            rp.base,
            rp.exponent,
            pp.ped_params,
            rp.sign_pk,
            pp.ped_gen,
            rp.Q,
            rng,
        )

    def prove(self) -> bytes:
        return IssueProof(
            wf=self.wf_prover.prove(), range_correctness=self.range_prover.prove()
        ).to_bytes()


class IssueVerifier:
    def __init__(self, tokens, anonymous: bool, pp: PublicParams):
        self.wf_verifier = wf.IssueWFVerifier(tokens, anonymous, pp.ped_params)
        rp = pp.range_params
        self.range_verifier = rangeproof.RangeVerifier(
            tokens, rp.base, rp.exponent, pp.ped_params, rp.sign_pk, pp.ped_gen, rp.Q
        )

    @guard
    def verify(self, raw: bytes) -> None:
        proof = IssueProof.from_bytes(raw)
        self.wf_verifier.verify(proof.wf)
        if proof.range_correctness is None:
            raise ValueError("invalid issue proof: missing range proof")
        self.range_verifier.verify(proof.range_correctness)
