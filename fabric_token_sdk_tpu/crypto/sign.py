"""Long-term identity signatures: Schnorr over BN254 G1.

Capability parity with reference `crypto/ecdsa/ecdsa.go` (signing
identities for issuers/auditors built on mathlib curves). We use Schnorr
rather than ECDSA — same API shape (keygen/sign/verify, serializable
public keys), simpler and pairing-free.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

from . import hostmath as hm
from .serialization import guard, dumps, g1s_bytes, loads


@dataclass
class PublicKey:
    point: tuple  # G1 = g^sk

    def to_bytes(self) -> bytes:
        return hm.g1_to_bytes(self.point)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "PublicKey":
        return cls(hm.g1_from_bytes(raw))

    @guard
    def verify(self, message: bytes, sig_raw: bytes) -> None:
        d = loads(sig_raw)
        chal, resp = d["c"], d["z"]
        # com = g^z · pk^{-c}; the negation rides the SCALAR, so this is
        # verbatim the response equation the batched plane's sub tile
        # evaluates (`crypto/batch_sign.py`: msm(g, z) - mul(pk, c)) —
        # one equation, two executors, differential-pinned in
        # tests/test_batch_sign.py
        com = response_commitment(self.point, chal, resp)
        if challenge(self.point, com, message) != chal:
            raise ValueError("invalid signature")


@dataclass
class SigningKey:
    sk: int
    public: PublicKey

    def sign(self, message: bytes, rng=None) -> bytes:
        rho = hm.rand_zr(rng)
        com = hm.g1_mul(hm.G1_GEN, rho)
        chal = _challenge(self.public.point, com, message)
        return dumps({"c": chal, "z": (rho + chal * self.sk) % hm.R})


def keygen(rng=None) -> SigningKey:
    sk = hm.rand_zr(rng)
    return SigningKey(sk, PublicKey(hm.g1_mul(hm.G1_GEN, sk)))


def response_commitment(pk_point, chal: int, resp: int):
    """The shared response equation: com = g^resp · pk^{-chal} with the
    negation folded into the scalar (group order R, so -c ≡ R - c). The
    batched plane computes the identical point via the stage tiles."""
    return hm.g1_add(
        hm.g1_mul(hm.G1_GEN, resp), hm.g1_mul(pk_point, -chal % hm.R)
    )


def challenge(pk_point, com, message: bytes) -> int:
    """Fiat-Shamir challenge binding (pk, commitment, message)."""
    return hm.hash_to_zr(message + g1s_bytes([pk_point, com]), b"fts/schnorr-sig")


def verify_many(rows):
    """Host-batched Schnorr verification over (pk_point, message, sig_raw)
    rows — the row format the block sign collector emits.

    Two block-wide dispatches replace 2N scalar ctypes round trips and 2N
    hashlib calls: one `hm.g1_multiexp_rows` recomputes every response
    commitment (each row is (g, pk) x (z, -c), the exact
    `response_commitment` algebra) and one `hm.hash_to_zr_many`
    recomputes every challenge. Returns one entry per row: True (valid),
    False (challenge mismatch) or None (signature this batch could not
    evaluate — the scalar path owns the precise error). Challenges are
    byte-identical to `PublicKey.verify` by construction
    (differential-pinned in tests/test_host_batch.py).
    """
    rows = list(rows)
    out = [None] * len(rows)
    parsed = []  # (row index, pk_point, message, chal, resp)
    for i, (pk_point, message, sig_raw) in enumerate(rows):
        try:
            d = loads(sig_raw)
            chal, resp = d["c"], d["z"]
            if not isinstance(chal, int) or not isinstance(resp, int):
                raise ValueError("non-integer signature fields")
        except Exception:
            continue
        parsed.append((i, pk_point, message, chal, resp))
    if not parsed:
        return out
    coms = hm.g1_multiexp_rows(
        [[hm.G1_GEN, pk] for _i, pk, _m, _c, _z in parsed],
        [[resp, -chal % hm.R] for _i, _pk, _m, chal, resp in parsed],
    )
    transcripts = []  # (row index, expected chal) aligned with transcripts
    keep = []
    for (i, pk, message, chal, _z), com in zip(parsed, coms):
        try:
            transcripts.append(
                (message + g1s_bytes([pk, com]), b"fts/schnorr-sig")
            )
            keep.append((i, chal))
        except Exception:
            continue  # un-encodable commitment: scalar path reports it
    for (i, chal), got in zip(keep, hm.hash_to_zr_many(transcripts)):
        out[i] = got == chal
    return out


_challenge = challenge  # backwards-compatible private alias
