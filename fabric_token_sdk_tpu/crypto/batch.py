"""Batched (TPU) verification data plane for zkatdlog proofs.

The reference verifies each proof sequentially with goroutines
(`transfer.go:124-154`, `range/proof.go:211-284`); here whole BLOCKS of
transactions verify in a handful of XLA programs:

* `batched_ps_verify`      — Pointcheval-Sanders signature batches
* `BatchedWFVerifier`      — transfer well-formedness sigma proofs
* `batched_membership_gt`  — the pairing side of membership proofs
* `BatchedTransferVerifier`— full transfer proofs (WF + range)

Fiat-Shamir hashes remain on the host (SHA-256) between device stages;
group/pairing math runs on device in fixed shapes.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import hostmath as hm, pssign, schnorr, sigproof
from .rangeproof import RangeProof
from .setup import PublicParams
from .transfer import TransferProof
from .wellformedness import TransferWF, challenge_transfer_wf
from ..ops import curve as cv, curve2 as cv2, pairing as pr, tower as tw
from ..ops.field import FP
from ..utils import metrics as mx


# -------------------------------------------------------------- tiling
#
# Device kernels run in fixed ROW_TILE slabs (padding by repeating row 0;
# padded outputs are discarded), so each kernel compiles exactly once per
# *trailing* shape no matter the batch size — bench and tests share the
# same cached programs.

ROW_TILE = 8


def _spanned(name):
    """Wrap a verify method in a metrics span (no-op when disabled)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kw):
            with mx.span(name):
                return fn(*args, **kw)

        return wrapper

    return deco


def _run_tiled(kernel, *arrays, consts=()):
    """kernel(*consts, *(tile slices)) over ROW_TILE slabs -> numpy.

    `consts` are parameter tensors (tables, public keys) passed whole to
    every tile call — as ARGUMENTS, not baked jit constants, so compiled
    programs are shared across parameter sets.
    """
    B = arrays[0].shape[0]
    pad = (-B) % ROW_TILE
    if pad:
        arrays = tuple(
            np.concatenate([a, np.repeat(a[:1], pad, axis=0)]) for a in arrays
        )
    mx.counter("batch.tiled.calls").inc()
    mx.counter("batch.tiled.rows").inc(B)
    mx.counter("batch.tiled.tiles").inc((B + pad) // ROW_TILE)
    outs = [
        kernel(*consts, *(jnp.asarray(a[t : t + ROW_TILE]) for a in arrays))
        for t in range(0, B + pad, ROW_TILE)
    ]
    if isinstance(outs[0], (tuple, list)):
        return tuple(
            np.concatenate([np.asarray(o[i]) for o in outs])[:B]
            for i in range(len(outs[0]))
        )
    return np.concatenate([np.asarray(o) for o in outs])[:B]


# ===================================================================
# Pointcheval-Sanders batch verification
# ===================================================================


class BatchedPSVerifier:
    """Verifies B signatures on l-message vectors in one device program."""

    def __init__(self, pk, Q):
        self.pk_host = list(pk)
        self.Q_host = Q
        self.pk_dev = jnp.asarray(cv2.encode_points(self.pk_host))  # (l+2,3,2,L)
        self.Q_aff = jnp.asarray(pr.encode_g2([Q]))[0]  # (2,2,L)

    @_spanned("batch.ps.verify")
    def verify(self, messages_rows: Sequence[Sequence[int]], sigs) -> np.ndarray:
        """-> bool array (B,). Raises nothing; invalid rows are False."""
        B = len(sigs)
        if B == 0:
            return np.zeros(0, dtype=bool)
        mx.counter("batch.ps.sigs").inc(B)
        l = len(self.pk_host) - 2
        scal = np.zeros((B, l + 1, 32), dtype=np.int32)
        negS, R = [], []
        malformed = np.zeros(B, dtype=bool)
        for i, (msgs, sig) in enumerate(zip(messages_rows, sigs)):
            try:
                if len(msgs) != l:
                    raise ValueError("PS batch: message count mismatch")
                ms = list(msgs) + [pssign.hash_messages(msgs)]
                scal[i] = cv.encode_scalars(ms)
                negS.append(hm.g1_neg(sig.S))
                R.append(sig.R)
            except Exception:
                malformed[i] = True
                negS.append(hm.G1_GEN)  # placeholder; row forced False
                R.append(hm.G1_GEN)
        P1 = np.asarray(pr.encode_g1(negS))
        P2 = np.asarray(pr.encode_g1(R))
        H_aff = _run_tiled(_ps_g2_kernel, scal, consts=(self.pk_dev,))
        Ps = np.stack([P1, P2], axis=1)  # (B, 2, 2, L) G1 affine
        Qs = np.stack(
            [np.broadcast_to(np.asarray(self.Q_aff), H_aff.shape), H_aff],
            axis=1,
        )  # (B, 2, 2, 2, L)
        gt = pr.pairing_product_staged(Ps, Qs)
        # np.array (copy): device arrays surface as read-only numpy views
        out = np.array(pr.gt_is_one(gt))
        out[malformed] = False
        return out


@jax.jit
def _ps_g2_kernel(pk_dev, scal):
    """H = PK0 + sum PK_i^{m_i} (+ PK_last^{hash}) in G2 -> affine.

    pk_dev is an argument, not a constant: one compiled program serves
    every PS public key of the same message length."""
    B = scal.shape[0]
    bases = jnp.broadcast_to(pk_dev[1:], (B,) + pk_dev[1:].shape)
    terms = cv2.scalar_mul(bases, scal)  # batched over (B, l+1)
    acc = cv2.tree_sum(terms, axis=-4)  # (B, 3, 2, L)
    pk0 = jnp.broadcast_to(pk_dev[0], acc.shape)
    H = cv2.add(acc, pk0)
    return cv2.to_affine_device(H)  # (B, 2, 2, L)


# ===================================================================
# Transfer well-formedness batch verification
# ===================================================================


class BatchedWFVerifier:
    """Recomputes all Schnorr commitments of B same-shape transfer WF
    proofs on device, then re-derives challenges on host."""

    def __init__(self, pp: PublicParams):
        self.pp = pp
        self.table = cv.FixedBaseTable(pp.ped_params)

    @_spanned("batch.wf.verify")
    def verify(self, txs: Sequence[Tuple[list, list, bytes]]) -> np.ndarray:
        """txs: (inputs, outputs, wf_bytes) with uniform shapes.
        Returns bool array (B,)."""
        B = len(txs)
        mx.counter("batch.wf.txs").inc(B)
        n_in = len(txs[0][0])
        n_out = len(txs[0][1])
        n = n_in + n_out + 2  # + the two aggregate statements
        proofs: List[Optional[TransferWF]] = []
        for t in txs:
            try:
                proofs.append(TransferWF.from_bytes(t[2]))
            except Exception:
                proofs.append(None)  # malformed: row verifies False
        stmts: List = []
        resp = np.zeros((B, n, 3, 32), dtype=np.int32)
        chals = np.zeros((B, 32), dtype=np.int32)
        ok_shape = np.ones(B, dtype=bool)
        for i, ((inputs, outputs, _), wf) in enumerate(zip(txs, proofs)):
            if (
                wf is None
                or len(wf.input_values) != n_in
                or len(wf.input_bfs) != n_in
                or len(wf.output_values) != n_out
                or len(wf.output_bfs) != n_out
            ):
                ok_shape[i] = False
                stmts.extend([None] * n)
                continue
            stmts.extend(inputs)
            stmts.append(hm.g1_sum(inputs))
            stmts.extend(outputs)
            stmts.append(hm.g1_sum(outputs))
            rows = []
            for k in range(n_in):
                rows.append([wf.type_resp, wf.input_values[k], wf.input_bfs[k]])
            rows.append(
                [
                    wf.type_resp * n_in % hm.R,
                    wf.sum_resp,
                    sum(wf.input_bfs) % hm.R,
                ]
            )
            for k in range(n_out):
                rows.append([wf.type_resp, wf.output_values[k], wf.output_bfs[k]])
            rows.append(
                [
                    wf.type_resp * n_out % hm.R,
                    wf.sum_resp,
                    sum(wf.output_bfs) % hm.R,
                ]
            )
            for j, r in enumerate(rows):
                resp[i, j] = np.asarray(cv.encode_scalars(r))
            chals[i] = np.asarray(cv.encode_scalars([wf.challenge]))[0]

        stmt_np = np.stack([cv.encode_point(s) for s in stmts]).reshape(
            B, n, 3, 32
        )
        coms = _run_tiled(
            _wf_kernel, resp, stmt_np, chals, consts=(self.table.flat,)
        )
        com_pts = cv.decode_points(coms)  # B*n host points
        out = np.zeros(B, dtype=bool)
        for i, ((inputs, outputs, _), wf) in enumerate(zip(txs, proofs)):
            if not ok_shape[i] or wf is None:
                continue
            row = com_pts[i * n : (i + 1) * n]
            in_coms = row[: n_in + 1]
            out_coms = row[n_in + 1 :]
            chal = challenge_transfer_wf(
                in_coms[:-1], in_coms[-1], out_coms[:-1], out_coms[-1], inputs, outputs
            )
            out[i] = chal == wf.challenge
        return out


@jax.jit
def _wf_kernel(table_flat, resp, stmts, chals):
    """com_j = prod ped_i^{resp_ji} - stmt_j^challenge, batched.

    The Pedersen window table arrives as an argument — one compiled
    program serves every parameter set of the same (n, bases) shape."""
    fixed = cv.msm_flat(table_flat, resp)  # (B, n, 3, L)
    sc = cv.scalar_mul(stmts, chals[:, None, :])  # (B, n, 3, L)
    return cv.add(fixed, cv.neg(sc))


# ===================================================================
# Membership-proof batch: pairing-side commitment reconstruction
# ===================================================================


class BatchedMembershipVerifier:
    """Verifies B membership proofs (the per-digit unit of range proofs).

    Device: GT commitment via 4-pairing products + G1 commitment via
    fixed/variable multiexp. Host: per-proof Fiat-Shamir challenge.
    """

    def __init__(self, pp: PublicParams):
        self.pp = pp
        rp = pp.range_params
        self.pk = rp.sign_pk
        self.Q = rp.Q
        self.P = pp.ped_gen
        self.ped2 = pp.ped_params[:2]
        self.pk_dev = jnp.asarray(cv2.encode_points(self.pk))
        self.Q_aff = jnp.asarray(pr.encode_g2([self.Q]))[0]
        self.Q_np = np.asarray(pr.encode_g2([self.Q]))[0]
        self.pk0_np = np.asarray(pr.encode_g2([self.pk[0]]))[0]
        self.table2 = cv.FixedBaseTable(self.ped2)
        self.tableP = cv.FixedBaseTable([self.P])

    @_spanned("batch.membership.verify")
    def verify(self, proofs: Sequence[sigproof.MembershipProof],
               commitments: Sequence) -> np.ndarray:
        B = len(proofs)
        if B == 0:
            return np.zeros(0, dtype=bool)
        mx.counter("batch.membership.proofs").inc(B)
        z = np.zeros((B, 4, 32), dtype=np.int32)  # value, hash, sig_bf, chal
        com_resp = np.zeros((B, 2, 32), dtype=np.int32)
        S_pts, R_pts, com_pts = [], [], []
        for i, (p, com) in enumerate(zip(proofs, commitments)):
            z[i, 0] = np.asarray(cv.encode_scalars([p.value_resp]))[0]
            z[i, 1] = np.asarray(cv.encode_scalars([p.hash_resp]))[0]
            z[i, 2] = np.asarray(cv.encode_scalars([p.sig_bf_resp]))[0]
            z[i, 3] = np.asarray(cv.encode_scalars([p.challenge]))[0]
            com_resp[i] = np.asarray(
                cv.encode_scalars([p.value_resp, p.com_bf_resp])
            )
            S_pts.append(p.signature.S)
            R_pts.append(p.signature.R)
            com_pts.append(com)
        t_aff, negSc, Rc, Pz, R_aff, com_val = _run_tiled(
            _membership_pre_kernel,
            z,
            com_resp,
            np.asarray(pr.encode_g1(S_pts)),
            np.asarray(pr.encode_g1(R_pts)),
            np.stack([cv.encode_point(c) for c in com_pts]),
            consts=(self.pk_dev, self.tableP.flat, self.table2.flat),
        )
        # 4-leg pairing product via the compile-once staged tile programs
        Ps = np.stack([negSc, Rc, R_aff, Pz], axis=1)  # (B, 4, 2, L)
        Q_np = self.Q_np
        pk0_np = self.pk0_np
        Qs = np.stack(
            [np.broadcast_to(Q_np, t_aff.shape),
             np.broadcast_to(pk0_np, t_aff.shape),
             t_aff,
             np.broadcast_to(Q_np, t_aff.shape)],
            axis=1,
        )  # (B, 4, 2, 2, L)
        gt = pr.pairing_product_staged(Ps, Qs)
        gt_host = tw.decode_fp12(gt)
        com_host = cv.decode_points(com_val)
        out = np.zeros(B, dtype=bool)
        for i, (p, com) in enumerate(zip(proofs, commitments)):
            if p.commitment != com:
                continue
            mv = sigproof.MembershipVerifier(com, self.P, self.Q, self.pk, self.ped2)
            chal = mv._challenge(gt_host[i], com_host[i], p.signature)
            out[i] = chal == p.challenge
        return out


@jax.jit
def _membership_pre_kernel(pk_dev, tableP_flat, table2_flat, z, com_resp,
                           S, R, com_jac):
    """Group-side reconstruction; pairing runs via the staged tiles.

    All parameter tensors (PS public key, window tables) are arguments so
    the program is shared across public-parameter sets."""
    B = z.shape[0]
    # G2 term: t = PK1^{z_v} + PK2^{z_h}
    bases = jnp.broadcast_to(pk_dev[1:3], (B, 2) + pk_dev.shape[1:])
    terms = cv2.scalar_mul(bases, z[:, 0:2])
    t = cv2.tree_sum(terms, axis=-4)
    t_aff = cv2.to_affine_device(t)
    # G1 sides: S^c, R^c (Jacobian scalar mul needs Jacobian input)
    Sj = _affine_to_jac(S)
    Rj = _affine_to_jac(R)
    both = jnp.stack([Sj, Rj], axis=1)  # (B, 2, 3, L)
    cc = jnp.broadcast_to(z[:, 3][:, None, :], (B, 2, 32))
    powc = cv.scalar_mul(both, cc)
    negSc_aff = _jac_to_affine(cv.neg(powc[:, 0]))
    Rc_aff = _jac_to_affine(powc[:, 1])
    Pz = _jac_to_affine(cv.msm_flat(tableP_flat, z[:, 2:3]))  # P^{z_bf}
    R_aff = _jac_to_affine(Rj)
    # G1 commitment: ped0^{z_v} ped1^{z_cb} - com^c
    fixed = cv.msm_flat(table2_flat, com_resp)
    comc = cv.scalar_mul(com_jac, z[:, 3])
    com_val = cv.add(fixed, cv.neg(comc))
    return t_aff, negSc_aff, Rc_aff, Pz, R_aff, com_val


# ===================================================================
# Full transfer-proof batch verification (WF + range)
# ===================================================================


class BatchedTransferVerifier:
    """Verifies whole blocks of same-shape zkatdlog transfer proofs.

    Composition mirrors `transfer.TransferVerifier` but the group/pairing
    work of ALL transactions runs in a few fixed-shape device programs.
    """

    def __init__(self, pp: PublicParams):
        self.pp = pp
        self.wf = BatchedWFVerifier(pp)
        self.membership = BatchedMembershipVerifier(pp)
        self.table3 = self.wf.table  # ped 3-base table
        self.table2 = self.membership.table2  # ped[:2]

    @_spanned("batch.transfer.verify")
    def verify(self, txs: Sequence[Tuple[list, list, bytes]]) -> np.ndarray:
        """txs: (inputs, outputs, transfer_proof_bytes), uniform shapes.
        Returns bool array (B,). 1-in/1-out txs skip range (reference
        transfer.go:55-59)."""
        B = len(txs)
        mx.counter("batch.transfer.txs").inc(B)
        n_in, n_out = len(txs[0][0]), len(txs[0][1])
        proofs = []
        ok = np.ones(B, dtype=bool)
        for i, t in enumerate(txs):
            try:
                proofs.append(TransferProof.from_bytes(t[2]))
            except Exception:
                proofs.append(TransferProof(wf=b"", range_correctness=None))
                ok[i] = False
        wf_ok = self.wf.verify(
            [(t[0], t[1], p.wf) for t, p in zip(txs, proofs)]
        )
        ok &= wf_ok
        if n_in == 1 and n_out == 1:
            return ok

        rp = self.pp.range_params
        exponent, base = rp.exponent, rp.base
        ranges: List[Optional[RangeProof]] = []
        for i, p in enumerate(proofs):
            if p.range_correctness is None:
                ok[i] = False
                ranges.append(None)
                continue
            try:
                rpf = RangeProof.from_bytes(p.range_correctness)
                if (
                    len(rpf.membership_proofs) != n_out
                    or len(rpf.digit_commitments) != n_out
                    or any(len(r) != exponent for r in rpf.membership_proofs)
                    or any(len(r) != exponent for r in rpf.digit_commitments)
                    or len(rpf.value_resps) != n_out
                    or len(rpf.token_bf_resps) != n_out
                    or len(rpf.com_bf_resps) != n_out
                ):
                    raise ValueError("range proof not well formed")
                ranges.append(rpf)
            except Exception:
                ok[i] = False
                ranges.append(None)

        # ---- membership proofs, flattened over (tx, output, digit)
        mem_proofs, mem_coms, mem_idx = [], [], []
        for i, rpf in enumerate(ranges):
            if rpf is None:
                continue
            for k in range(n_out):
                for d in range(exponent):
                    mem_proofs.append(rpf.membership_proofs[k][d])
                    mem_coms.append(rpf.digit_commitments[k][d])
                    mem_idx.append(i)
        if mem_proofs:
            mem_ok = self.membership.verify(mem_proofs, mem_coms)
            for j, i in enumerate(mem_idx):
                if not mem_ok[j]:
                    ok[i] = False

        # ---- equality proofs: token rows (3 bases) + aggregate rows (2)
        live = [i for i in range(B) if ranges[i] is not None]
        if not live:
            return ok
        tok_resp = np.zeros((len(live), n_out, 3, 32), dtype=np.int32)
        tok_stmt = np.zeros((len(live), n_out, 3, 32), dtype=np.int32)
        agg_resp = np.zeros((len(live), n_out, 2, 32), dtype=np.int32)
        agg_stmt = np.zeros((len(live), n_out, 3, 32), dtype=np.int32)
        chals = np.zeros((len(live), 32), dtype=np.int32)
        aggs_host = []
        for li, i in enumerate(live):
            rpf = ranges[i]
            outputs = txs[i][1]
            for k in range(n_out):
                tok_resp[li, k] = np.asarray(
                    cv.encode_scalars(
                        [rpf.type_resp, rpf.value_resps[k], rpf.token_bf_resps[k]]
                    )
                )
                tok_stmt[li, k] = cv.encode_point(outputs[k])
                agg = hm.g1_multiexp(
                    rpf.digit_commitments[k],
                    [base**d % hm.R for d in range(exponent)],
                )
                aggs_host.append(agg)
                agg_stmt[li, k] = cv.encode_point(agg)
                agg_resp[li, k] = np.asarray(
                    cv.encode_scalars([rpf.value_resps[k], rpf.com_bf_resps[k]])
                )
            chals[li] = np.asarray(cv.encode_scalars([rpf.challenge]))[0]

        com_tok, com_val = _run_tiled(
            _equality_kernel, tok_resp, tok_stmt, agg_resp, agg_stmt,
            chals, consts=(self.table3.flat, self.table2.flat),
        )
        com_tok_h = cv.decode_points(com_tok)
        com_val_h = cv.decode_points(com_val)
        from .rangeproof import RangeVerifier

        for li, i in enumerate(live):
            rpf = ranges[i]
            verifier = RangeVerifier(
                txs[i][1], base, exponent, self.pp.ped_params,
                rp.sign_pk, self.pp.ped_gen, rp.Q,
            )
            chal = verifier._challenge(
                com_tok_h[li * n_out : (li + 1) * n_out],
                com_val_h[li * n_out : (li + 1) * n_out],
                rpf.digit_commitments,
            )
            if chal != rpf.challenge:
                ok[i] = False
        return ok


@jax.jit
def _equality_kernel(table3_flat, table2_flat, tok_resp, tok_stmt, agg_resp,
                     agg_stmt, chals):
    com_tok = cv.add(
        cv.msm_flat(table3_flat, tok_resp),
        cv.neg(cv.scalar_mul(tok_stmt, chals[:, None, :])),
    )
    com_val = cv.add(
        cv.msm_flat(table2_flat, agg_resp),
        cv.neg(cv.scalar_mul(agg_stmt, chals[:, None, :])),
    )
    return com_tok, com_val


@jax.jit
def _affine_to_jac(p):
    """(..., 2, L) affine -> (..., 3, L) Jacobian with Z = 1 (Montgomery)."""
    one = jnp.broadcast_to(
        jnp.asarray(np.asarray(FP.one_mont)), p[..., 0, :].shape
    ).astype(jnp.int32)
    return jnp.stack([p[..., 0, :], p[..., 1, :], one], axis=-2)


@jax.jit
def _jac_to_affine(p):
    """Device Jacobian -> affine (inversion via Fermat scan)."""
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    zi = FP.inv(z)
    zi2 = FP.mul(zi, zi)
    return jnp.stack([FP.mul(x, zi2), FP.mul(FP.mul(y, zi2), zi)], axis=-2)
