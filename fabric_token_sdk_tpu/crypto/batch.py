"""Batched (TPU) verification data plane for zkatdlog proofs.

The reference verifies each proof sequentially with goroutines
(`transfer.go:124-154`, `range/proof.go:211-284`); here whole BLOCKS of
transactions verify through a SMALL CONSTANT set of XLA programs:

* `BatchedPSVerifier`      — Pointcheval-Sanders signature batches
* `BatchedWFVerifier`      — transfer well-formedness sigma proofs
* `BatchedMembershipVerifier` — the pairing side of membership proofs
* `BatchedTransferVerifier`— full transfer proofs (WF + range)

Execution model (staged tiles — see `ops/stages.py`): every verifier is a
HOST-SIDE composition of primitive stage kernels (fixed-base multiexp,
variable-base scalar mul, Jacobian add/sub, batch to-affine — each jit'd
once at one canonical ROW_TILE shape) plus the compile-once pairing tiles
(`ops/pairing.py`). All glue between stages — row flattening, challenge
repetition, broadcasting parameter points, Fiat-Shamir re-hashing — is
host numpy, so the distinct-program count is independent of batch size,
transfer shape `(n_in, n_out)`, and parameter set. `ops/warmup.py`
precompiles the whole set.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import hostmath as hm, pssign, sigproof
from .rangeproof import RangeProof
from .setup import PublicParams
from .transfer import TransferProof
from .wellformedness import TransferWF, challenge_transfer_wf
from ..ops import curve as cv, curve2 as cv2, limbs as lb, pairing as pr, \
    stages as st, tower as tw
from ..parallel.sharding import MeshConfig
from ..utils import devobs
from ..utils import metrics as mx, resilience

# Canonical tile height for all stage kernels (re-exported for compat;
# the runner lives in ops/stages.py).
ROW_TILE = st.ROW_TILE


class _MeshBound:
    """Mixin: a verifier bound to an optional `MeshConfig` — its stage
    dispatches shard over dp and its pairing products over dp x mp (the
    per-shard stage-tile dispatch of `parallel/sharding.py`; None falls
    back to the ambient `FTS_MESH_DEVICES`/`FTS_DP_SHARDS` env inside
    the runners). Sharding never changes results — only dispatch."""

    mesh: Optional[MeshConfig] = None

    def set_mesh(self, mesh) -> None:
        self.mesh = MeshConfig.of(mesh)

    @property
    def _dp(self) -> Optional[int]:
        return None if self.mesh is None else self.mesh.dp

    @property
    def _mp(self) -> Optional[int]:
        return None if self.mesh is None else self.mesh.mp


def _spanned(name):
    """Wrap a verify method in a metrics span (no-op when disabled) and
    a dispatch-ledger plane tag (`utils/devobs.py`): every stage
    dispatch the method triggers records its occupancy under the plane
    named by the span's middle token (`batch.sign.verify` -> `sign`,
    every `batch.*.verify` verifier -> `verify`)."""
    middle = name.split(".")[1] if "." in name else name
    plane = middle if middle in ("sign", "prove") else "verify"

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kw):
            with devobs.plane(plane), mx.span(name):
                return fn(*args, **kw)

        return wrapper

    return deco


# ===================================================================
# Pointcheval-Sanders batch verification
# ===================================================================


class BatchedPSVerifier(_MeshBound):
    """Verifies B signatures on l-message vectors via the stage tiles."""

    def __init__(self, pk, Q, mesh=None):
        self.pk_host = list(pk)
        self.Q_host = Q
        self.set_mesh(mesh)
        self.pk_np = np.asarray(cv2.encode_points(self.pk_host))  # (l+2,3,2,L)
        self.Q_np = np.asarray(pr.encode_g2([Q]))[0]  # (2,2,L)

    @_spanned("batch.ps.verify")
    def verify(self, messages_rows: Sequence[Sequence[int]], sigs) -> np.ndarray:
        """-> bool array (B,). Raises nothing; invalid rows are False."""
        B = len(sigs)
        if B == 0:
            return np.zeros(0, dtype=bool)
        mx.counter("batch.ps.sigs").inc(B)
        l = len(self.pk_host) - 2
        scal = np.zeros((B, l + 1, lb.NLIMBS), dtype=np.int32)
        negS, R = [], []
        malformed = np.zeros(B, dtype=bool)
        for i, (msgs, sig) in enumerate(zip(messages_rows, sigs)):
            try:
                if len(msgs) != l:
                    raise ValueError("PS batch: message count mismatch")
                ms = list(msgs) + [pssign.hash_messages(msgs)]
                scal[i] = cv.encode_scalars(ms)
                negS.append(hm.g1_neg(sig.S))
                R.append(sig.R)
            except Exception:
                malformed[i] = True
                negS.append(hm.G1_GEN)  # placeholder; row forced False
                R.append(hm.G1_GEN)
        P1 = np.asarray(pr.encode_g1(negS))
        P2 = np.asarray(pr.encode_g1(R))
        # H = PK0 + sum PK_i^{m_i} (+ PK_last^{hash}) in G2, staged:
        # one flat scalar-mul pass, a host-folded tree sum, one to-affine
        k = l + 1
        bases = np.broadcast_to(
            self.pk_np[1:], (B, k) + self.pk_np.shape[1:]
        ).reshape((B * k,) + self.pk_np.shape[1:])
        terms = st.g2_mul_rows(bases, scal.reshape(B * k, lb.NLIMBS), dp=self._dp)
        acc = st.g2_tree_sum_rows(
            terms.reshape((B, k) + terms.shape[1:]), dp=self._dp
        )
        acc = st.g2_add_rows(
            acc, np.broadcast_to(self.pk_np[0], acc.shape), dp=self._dp
        )
        H_aff = st.g2_to_affine_rows(acc, dp=self._dp)  # (B, 2, 2, L)
        Ps = np.stack([P1, P2], axis=1)  # (B, 2, 2, L) G1 affine
        Qs = np.stack(
            [np.broadcast_to(self.Q_np, H_aff.shape), H_aff], axis=1
        )  # (B, 2, 2, 2, L)
        gt = pr.pairing_product_staged(Ps, Qs, dp=self._dp, mp=self._mp)
        out = pr.gt_is_one_host(gt)
        out[malformed] = False
        return out


# ===================================================================
# Transfer well-formedness batch verification
# ===================================================================


class BatchedWFVerifier(_MeshBound):
    """Recomputes all Schnorr commitments of B same-shape transfer WF
    proofs via the stage tiles, then re-derives challenges on host."""

    def __init__(self, pp: PublicParams, mesh=None):
        self.pp = pp
        self.set_mesh(mesh)
        self.table = cv.FixedBaseTable(pp.ped_params)

    @_spanned("batch.wf.verify")
    def verify(self, txs: Sequence[Tuple[list, list, bytes]]) -> np.ndarray:
        """txs: (inputs, outputs, wf_bytes) with uniform shapes.
        Returns bool array (B,)."""
        B = len(txs)
        if B == 0:
            return np.zeros(0, dtype=bool)
        mx.counter("batch.wf.txs").inc(B)
        n_in = len(txs[0][0])
        n_out = len(txs[0][1])
        n = n_in + n_out + 2  # + the two aggregate statements
        proofs: List[Optional[TransferWF]] = []
        for t in txs:
            try:
                proofs.append(TransferWF.from_bytes(t[2]))
            except Exception:
                proofs.append(None)  # malformed: row verifies False
        stmts: List = []
        resp = np.zeros((B, n, 3, lb.NLIMBS), dtype=np.int32)
        chals = np.zeros((B, lb.NLIMBS), dtype=np.int32)
        ok_shape = np.ones(B, dtype=bool)
        for i, ((inputs, outputs, _), wf) in enumerate(zip(txs, proofs)):
            if (
                wf is None
                or len(wf.input_values) != n_in
                or len(wf.input_bfs) != n_in
                or len(wf.output_values) != n_out
                or len(wf.output_bfs) != n_out
            ):
                ok_shape[i] = False
                stmts.extend([None] * n)
                continue
            stmts.extend(inputs)
            stmts.append(hm.g1_sum(inputs))
            stmts.extend(outputs)
            stmts.append(hm.g1_sum(outputs))
            rows = []
            for k in range(n_in):
                rows.append([wf.type_resp, wf.input_values[k], wf.input_bfs[k]])
            rows.append(
                [
                    wf.type_resp * n_in % hm.R,
                    wf.sum_resp,
                    sum(wf.input_bfs) % hm.R,
                ]
            )
            for k in range(n_out):
                rows.append([wf.type_resp, wf.output_values[k], wf.output_bfs[k]])
            rows.append(
                [
                    wf.type_resp * n_out % hm.R,
                    wf.sum_resp,
                    sum(wf.output_bfs) % hm.R,
                ]
            )
            for j, r in enumerate(rows):
                resp[i, j] = cv.encode_scalars(r)
            chals[i] = cv.encode_scalars([wf.challenge])[0]

        stmt_np = np.stack([cv.encode_point(s) for s in stmts]).reshape(
            B, n, 3, lb.NLIMBS
        )
        # com_j = prod ped_i^{resp_ji} - stmt_j^challenge over B*n flat rows
        fixed = st.g1_msm_rows(
            self.table.flat, resp.reshape(B * n, 3, lb.NLIMBS), dp=self._dp
        )
        sc = st.g1_mul_rows(
            stmt_np.reshape(B * n, 3, lb.NLIMBS), np.repeat(chals, n, axis=0),
            dp=self._dp,
        )
        coms = st.g1_sub_rows(fixed, sc, dp=self._dp)
        com_pts = cv.decode_points(coms)  # B*n host points
        out = np.zeros(B, dtype=bool)
        for i, ((inputs, outputs, _), wf) in enumerate(zip(txs, proofs)):
            if not ok_shape[i] or wf is None:
                continue
            row = com_pts[i * n : (i + 1) * n]
            in_coms = row[: n_in + 1]
            out_coms = row[n_in + 1 :]
            chal = challenge_transfer_wf(
                in_coms[:-1], in_coms[-1], out_coms[:-1], out_coms[-1], inputs, outputs
            )
            out[i] = chal == wf.challenge
        return out


# ===================================================================
# Membership-proof batch: pairing-side commitment reconstruction
# ===================================================================


class BatchedMembershipVerifier(_MeshBound):
    """Verifies B membership proofs (the per-digit unit of range proofs).

    Device: GT commitment via 4-pairing products + G1 commitment via
    fixed/variable multiexp — all through the compile-once stage tiles.
    Host: per-proof Fiat-Shamir challenge.
    """

    def __init__(self, pp: PublicParams, mesh=None):
        self.pp = pp
        self.set_mesh(mesh)
        rp = pp.range_params
        self.pk = rp.sign_pk
        self.Q = rp.Q
        self.P = pp.ped_gen
        self.ped2 = pp.ped_params[:2]
        self.pk_np = np.asarray(cv2.encode_points(self.pk))  # (l+2,3,2,L)
        self.Q_np = np.asarray(pr.encode_g2([self.Q]))[0]
        self.pk0_np = np.asarray(pr.encode_g2([self.pk[0]]))[0]
        self.table2 = cv.FixedBaseTable(self.ped2)
        self.tableP = cv.FixedBaseTable([self.P])

    @_spanned("batch.membership.verify")
    def verify(self, proofs: Sequence[sigproof.MembershipProof],
               commitments: Sequence) -> np.ndarray:
        B = len(proofs)
        if B == 0:
            return np.zeros(0, dtype=bool)
        mx.counter("batch.membership.proofs").inc(B)
        L = lb.NLIMBS
        # one vectorized limb encoding per response field across the batch
        z = np.stack(
            [
                cv.encode_scalars([p.value_resp for p in proofs]),
                cv.encode_scalars([p.hash_resp for p in proofs]),
                cv.encode_scalars([p.sig_bf_resp for p in proofs]),
                cv.encode_scalars([p.challenge for p in proofs]),
            ],
            axis=1,
        )  # (B, 4, L): value, hash, sig_bf, chal
        com_resp = np.stack(
            [z[:, 0], cv.encode_scalars([p.com_bf_resp for p in proofs])], axis=1
        )
        neg_chal = cv.encode_scalars([-p.challenge for p in proofs])
        S_np = np.asarray(pr.encode_g1([p.signature.S for p in proofs]))
        R_np = np.asarray(pr.encode_g1([p.signature.R for p in proofs]))
        com_jac = np.stack([cv.encode_point(c) for c in commitments])

        # G2 term: t = PK1^{z_v} + PK2^{z_h}
        bases = np.broadcast_to(
            self.pk_np[1:3], (B, 2) + self.pk_np.shape[1:]
        ).reshape((2 * B,) + self.pk_np.shape[1:])
        terms = st.g2_mul_rows(bases, z[:, 0:2].reshape(2 * B, L), dp=self._dp)
        terms = terms.reshape((B, 2) + terms.shape[1:])
        t_aff = st.g2_to_affine_rows(
            st.g2_add_rows(terms[:, 0], terms[:, 1], dp=self._dp), dp=self._dp
        )

        # G1 sides: -S^c as S^{r-c} (scalar negation — no extra neg
        # program), R^c, and P^{z_bf}; one fused to-affine pass for all
        Sj = st.affine_to_jac_np(S_np)
        Rj = st.affine_to_jac_np(R_np)
        powc = st.g1_mul_rows(
            np.concatenate([Sj, Rj]), np.concatenate([neg_chal, z[:, 3]]),
            dp=self._dp,
        )
        Pz_j = st.g1_msm_rows(self.tableP.flat, z[:, 2:3], dp=self._dp)
        aff = st.g1_to_affine_rows(np.concatenate([powc, Pz_j]), dp=self._dp)
        negSc, Rc, Pz = aff[:B], aff[B : 2 * B], aff[2 * B :]

        # G1 commitment: ped0^{z_v} ped1^{z_cb} - com^c
        fixed = st.g1_msm_rows(self.table2.flat, com_resp, dp=self._dp)
        comc = st.g1_mul_rows(com_jac, z[:, 3], dp=self._dp)
        com_val = st.g1_sub_rows(fixed, comc, dp=self._dp)

        # 4-leg pairing product via the compile-once staged tile programs
        Ps = np.stack([negSc, Rc, R_np, Pz], axis=1)  # (B, 4, 2, L)
        Qs = np.stack(
            [np.broadcast_to(self.Q_np, t_aff.shape),
             np.broadcast_to(self.pk0_np, t_aff.shape),
             t_aff,
             np.broadcast_to(self.Q_np, t_aff.shape)],
            axis=1,
        )  # (B, 4, 2, 2, L)
        gt = pr.pairing_product_staged(Ps, Qs, dp=self._dp, mp=self._mp)
        gt_host = tw.decode_fp12(gt)
        com_host = cv.decode_points(com_val)
        out = np.zeros(B, dtype=bool)
        for i, (p, com) in enumerate(zip(proofs, commitments)):
            if p.commitment != com:
                continue
            mv = sigproof.MembershipVerifier(com, self.P, self.Q, self.pk, self.ped2)
            chal = mv._challenge(gt_host[i], com_host[i], p.signature)
            out[i] = chal == p.challenge
        return out


# ===================================================================
# Full transfer-proof batch verification (WF + range)
# ===================================================================


class BatchedTransferVerifier(_MeshBound):
    """Verifies whole blocks of same-shape zkatdlog transfer proofs.

    Composition mirrors `transfer.TransferVerifier` but the group/pairing
    work of ALL transactions runs through the fixed-shape stage tiles —
    the total distinct-program count is constant in `(n_in, n_out)`,
    batch size, and parameter set. An optional `MeshConfig` shards the
    dispatch over dp (stage rows) x mp (pairing legs) — same
    executables, bit-identical verdicts.
    """

    def __init__(self, pp: PublicParams, mesh=None):
        self.pp = pp
        self.wf = BatchedWFVerifier(pp, mesh=mesh)
        self.membership = BatchedMembershipVerifier(pp, mesh=mesh)
        self.set_mesh(mesh)
        self.table3 = self.wf.table  # ped 3-base table
        self.table2 = self.membership.table2  # ped[:2]

    def set_mesh(self, mesh) -> None:
        super().set_mesh(mesh)
        # tolerate set_mesh during __init__ (sub-verifiers not built yet)
        if getattr(self, "wf", None) is not None:
            self.wf.set_mesh(mesh)
        if getattr(self, "membership", None) is not None:
            self.membership.set_mesh(mesh)

    @_spanned("batch.transfer.verify")
    def verify(self, txs: Sequence[Tuple[list, list, bytes]]) -> np.ndarray:
        """txs: (inputs, outputs, transfer_proof_bytes), uniform shapes.
        Returns bool array (B,). 1-in/1-out txs skip range (reference
        transfer.go:55-59)."""
        B = len(txs)
        if B == 0:
            return np.zeros(0, dtype=bool)

        def _count_done():
            # counted on COMPLETION (not entry): an ABANDONED bounded
            # worker (verify timeout already degraded the block to host)
            # must not report its discarded txs as device-verified —
            # they were counted under ledger.validate.host instead. An
            # entry-side count would always precede the deadline expiry
            # and defeat the guard.
            if not resilience.call_abandoned():
                mx.counter("batch.transfer.txs").inc(B)

        n_in, n_out = len(txs[0][0]), len(txs[0][1])
        proofs = []
        ok = np.ones(B, dtype=bool)
        for i, t in enumerate(txs):
            try:
                proofs.append(TransferProof.from_bytes(t[2]))
            except Exception:
                proofs.append(TransferProof(wf=b"", range_correctness=None))
                ok[i] = False
        wf_ok = self.wf.verify(
            [(t[0], t[1], p.wf) for t, p in zip(txs, proofs)]
        )
        ok &= wf_ok
        if n_in == 1 and n_out == 1:
            _count_done()
            return ok

        rp = self.pp.range_params
        exponent, base = rp.exponent, rp.base
        ranges: List[Optional[RangeProof]] = []
        for i, p in enumerate(proofs):
            if p.range_correctness is None:
                ok[i] = False
                ranges.append(None)
                continue
            try:
                rpf = RangeProof.from_bytes(p.range_correctness)
                if (
                    len(rpf.membership_proofs) != n_out
                    or len(rpf.digit_commitments) != n_out
                    or any(len(r) != exponent for r in rpf.membership_proofs)
                    or any(len(r) != exponent for r in rpf.digit_commitments)
                    or len(rpf.value_resps) != n_out
                    or len(rpf.token_bf_resps) != n_out
                    or len(rpf.com_bf_resps) != n_out
                ):
                    raise ValueError("range proof not well formed")
                ranges.append(rpf)
            except Exception:
                ok[i] = False
                ranges.append(None)

        # ---- membership proofs, flattened over (tx, output, digit)
        mem_proofs, mem_coms, mem_idx = [], [], []
        for i, rpf in enumerate(ranges):
            if rpf is None:
                continue
            for k in range(n_out):
                for d in range(exponent):
                    mem_proofs.append(rpf.membership_proofs[k][d])
                    mem_coms.append(rpf.digit_commitments[k][d])
                    mem_idx.append(i)
        if mem_proofs:
            mem_ok = self.membership.verify(mem_proofs, mem_coms)
            for j, i in enumerate(mem_idx):
                if not mem_ok[j]:
                    ok[i] = False

        # ---- equality proofs: token rows (3 bases) + aggregate rows (2)
        live = [i for i in range(B) if ranges[i] is not None]
        if not live:
            _count_done()
            return ok
        L = lb.NLIMBS
        nl = len(live)
        tok_resp = np.zeros((nl, n_out, 3, L), dtype=np.int32)
        tok_stmt = np.zeros((nl, n_out, 3, L), dtype=np.int32)
        agg_resp = np.zeros((nl, n_out, 2, L), dtype=np.int32)
        agg_stmt = np.zeros((nl, n_out, 3, L), dtype=np.int32)
        chals = np.zeros((nl, L), dtype=np.int32)
        for li, i in enumerate(live):
            rpf = ranges[i]
            outputs = txs[i][1]
            for k in range(n_out):
                tok_resp[li, k] = cv.encode_scalars(
                    [rpf.type_resp, rpf.value_resps[k], rpf.token_bf_resps[k]]
                )
                tok_stmt[li, k] = cv.encode_point(outputs[k])
                agg = hm.g1_multiexp(
                    rpf.digit_commitments[k],
                    [base**d % hm.R for d in range(exponent)],
                )
                agg_stmt[li, k] = cv.encode_point(agg)
                agg_resp[li, k] = cv.encode_scalars(
                    [rpf.value_resps[k], rpf.com_bf_resps[k]]
                )
            chals[li] = cv.encode_scalars([rpf.challenge])[0]

        chal_rep = np.repeat(chals, n_out, axis=0)
        com_tok = st.g1_sub_rows(
            st.g1_msm_rows(
                self.table3.flat, tok_resp.reshape(nl * n_out, 3, L),
                dp=self._dp,
            ),
            st.g1_mul_rows(
                tok_stmt.reshape(nl * n_out, 3, L), chal_rep, dp=self._dp
            ),
            dp=self._dp,
        )
        com_val = st.g1_sub_rows(
            st.g1_msm_rows(
                self.table2.flat, agg_resp.reshape(nl * n_out, 2, L),
                dp=self._dp,
            ),
            st.g1_mul_rows(
                agg_stmt.reshape(nl * n_out, 3, L), chal_rep, dp=self._dp
            ),
            dp=self._dp,
        )
        com_tok_h = cv.decode_points(com_tok)
        com_val_h = cv.decode_points(com_val)
        from .rangeproof import RangeVerifier

        for li, i in enumerate(live):
            rpf = ranges[i]
            verifier = RangeVerifier(
                txs[i][1], base, exponent, self.pp.ped_params,
                rp.sign_pk, self.pp.ped_gen, rp.Q,
            )
            chal = verifier._challenge(
                com_tok_h[li * n_out : (li + 1) * n_out],
                com_val_h[li * n_out : (li + 1) * n_out],
                rpf.digit_commitments,
            )
            if chal != rpf.challenge:
                ok[i] = False
        _count_done()
        return ok
