"""Pseudonym (nym) signatures: signature of knowledge of (sk, bf) with
NYM = g^sk * h^bf. Reference: `crypto/common/nym.go`.

Token owners in zkatdlog sign transfer requests under fresh pseudonyms;
the auditor can link nyms via audit info.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from . import hostmath as hm, schnorr
from .serialization import guard, dumps, g1s_bytes, loads


@dataclass
class NymSignature:
    challenge: int
    sk_resp: int
    bf_resp: int

    def to_bytes(self) -> bytes:
        return dumps({"c": self.challenge, "s": self.sk_resp, "b": self.bf_resp})

    @classmethod
    def from_bytes(cls, raw: bytes) -> "NymSignature":
        d = loads(raw)
        return cls(d["c"], d["s"], d["b"])


def new_nym(sk: int, nym_params, rng=None) -> Tuple[tuple, int]:
    """Fresh pseudonym for a long-term secret key: returns (NYM, bf)."""
    bf = hm.rand_zr(rng)
    return hm.g1_multiexp(list(nym_params), [sk, bf]), bf


@dataclass
class NymSigner:
    sk: int
    bf: int
    nym: tuple
    nym_params: List[tuple]

    def sign(self, message: bytes, rng=None) -> bytes:
        rho_sk = hm.rand_zr(rng)
        rho_bf = hm.rand_zr(rng)
        com = hm.g1_multiexp(self.nym_params, [rho_sk, rho_bf])
        chal = _challenge(self.nym_params, self.nym, com, message)
        z = schnorr.respond([self.sk, self.bf], [rho_sk, rho_bf], chal)
        return NymSignature(chal, z[0], z[1]).to_bytes()


@dataclass
class NymVerifier:
    nym: tuple
    nym_params: List[tuple]

    @guard
    def verify(self, message: bytes, raw: bytes) -> None:
        sig = NymSignature.from_bytes(raw)
        sp = schnorr.SchnorrProof(self.nym, [sig.sk_resp, sig.bf_resp], sig.challenge)
        com = schnorr.recompute_commitment(self.nym_params, sp)
        if _challenge(self.nym_params, self.nym, com, message) != sig.challenge:
            raise ValueError("invalid nym signature")


def _challenge(nym_params, nym, com, message: bytes) -> int:
    return hm.hash_to_zr(message + g1s_bytes(nym_params, [nym, com]), b"fts/nym")
