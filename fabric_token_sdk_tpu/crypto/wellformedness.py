"""Well-formedness sigma proofs for transfer and issue actions.

Transfer WF (reference `crypto/transfer/wellformedness.go`): inputs and
outputs are Pedersen commitments to (type, value; bf); the proof shows
knowledge of all openings, equal type across all tokens, and equal total
value of inputs and outputs (shared `sum` response).

Issue WF (reference `crypto/issue/wellformedness.go`): issued tokens are
commitments to (type, value; bf); shows knowledge of openings and a common
type — hidden (anonymous issuer) or in the clear.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from . import hostmath as hm
from . import schnorr
from .serialization import guard, dumps, g1s_bytes, loads


def _rand(rng) -> int:
    return hm.rand_zr(rng)


# ===================================================================
# Transfer well-formedness
# ===================================================================


@dataclass
class TransferWF:
    input_values: List[int]
    input_bfs: List[int]
    output_values: List[int]
    output_bfs: List[int]
    type_resp: int
    sum_resp: int
    challenge: int

    def to_bytes(self) -> bytes:
        return dumps(
            {
                "iv": self.input_values,
                "ib": self.input_bfs,
                "ov": self.output_values,
                "ob": self.output_bfs,
                "t": self.type_resp,
                "s": self.sum_resp,
                "c": self.challenge,
            }
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "TransferWF":
        d = loads(raw)
        return cls(d["iv"], d["ib"], d["ov"], d["ob"], d["t"], d["s"], d["c"])


@dataclass
class TransferWFWitness:
    token_type: str
    in_values: List[int]
    in_bfs: List[int]
    out_values: List[int]
    out_bfs: List[int]


@dataclass
class TransferWFDraw:
    """Commit-phase randomness of one transfer WF proof — drawn once,
    consumed by either the host or the batched-device commit path (the
    Fiat-Shamir response math in `finish` is shared by both)."""

    rho_T: int
    rho_sum: int
    rho_iv: List[int]
    rho_ib: List[int]
    rho_ov: List[int]
    rho_ob: List[int]

    def commit_rows(self, n_in: int, n_out: int) -> List[List[int]]:
        """Scalar rows of the commit phase over the 3 Pedersen bases, in
        transcript order: per-input commitments, input sum, per-output
        commitments, output sum. Every commitment is one fixed-base
        3-term multiexp — on host via `hm.g1_multiexp`, on device via the
        `g1_msm3` stage tile (`crypto/batch_prove.py`)."""
        rows = [
            [self.rho_T, self.rho_iv[i], self.rho_ib[i]] for i in range(n_in)
        ]
        rows.append([self.rho_T * n_in, self.rho_sum, sum(self.rho_ib)])
        rows += [
            [self.rho_T, self.rho_ov[i], self.rho_ob[i]] for i in range(n_out)
        ]
        rows.append([self.rho_T * n_out, self.rho_sum, sum(self.rho_ob)])
        return rows


class TransferWFProver:
    def __init__(self, witness: TransferWFWitness, ped_params, inputs, outputs, rng=None):
        self.w = witness
        self.pp = list(ped_params)
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.rng = rng

    def draw(self) -> TransferWFDraw:
        w = self.w
        if len(w.in_values) != len(self.inputs) or len(w.out_values) != len(self.outputs):
            raise ValueError("transfer WF: malformed witness")
        return TransferWFDraw(
            rho_T=_rand(self.rng),
            rho_sum=_rand(self.rng),
            rho_iv=[_rand(self.rng) for _ in self.inputs],
            rho_ib=[_rand(self.rng) for _ in self.inputs],
            rho_ov=[_rand(self.rng) for _ in self.outputs],
            rho_ob=[_rand(self.rng) for _ in self.outputs],
        )

    def finish(self, d: TransferWFDraw, chal: int) -> bytes:
        w = self.w
        t_hash = hm.hash_to_zr(w.token_type.encode())
        return TransferWF(
            input_values=schnorr.respond(w.in_values, d.rho_iv, chal),
            input_bfs=schnorr.respond(w.in_bfs, d.rho_ib, chal),
            output_values=schnorr.respond(w.out_values, d.rho_ov, chal),
            output_bfs=schnorr.respond(w.out_bfs, d.rho_ob, chal),
            type_resp=schnorr.respond([t_hash], [d.rho_T], chal)[0],
            sum_resp=schnorr.respond([sum(w.in_values) % hm.R], [d.rho_sum], chal)[0],
            challenge=chal,
        ).to_bytes()

    def prove(self) -> bytes:
        d = self.draw()
        coms = [
            hm.g1_multiexp(self.pp[:3], [r % hm.R for r in row])
            for row in d.commit_rows(len(self.inputs), len(self.outputs))
        ]
        n_in = len(self.inputs)
        chal = challenge_transfer_wf(
            coms[:n_in], coms[n_in], coms[n_in + 1 : -1], coms[-1],
            self.inputs, self.outputs,
        )
        return self.finish(d, chal)


def challenge_transfer_wf(com_in, in_sum, com_out, out_sum, inputs, outputs) -> int:
    raw = g1s_bytes(com_in, [in_sum], com_out, [out_sum], inputs, outputs)
    return hm.hash_to_zr(raw, b"fts/transfer-wf")


def _side_proofs(tokens, values, bfs, type_resp, sum_resp, challenge):
    """Schnorr proofs for one side (inputs or outputs), incl. the aggregate
    sum proof over Sum(tokens). Reference wellformedness.go:parseProof."""
    if len(values) != len(tokens) or len(bfs) != len(tokens):
        raise ValueError("transfer WF: response count mismatch")
    proofs = [
        schnorr.SchnorrProof(tok, [type_resp, values[i], bfs[i]], challenge)
        for i, tok in enumerate(tokens)
    ]
    agg = hm.g1_sum(tokens)
    proofs.append(
        schnorr.SchnorrProof(
            agg,
            [type_resp * len(tokens) % hm.R, sum_resp, sum(bfs) % hm.R],
            challenge,
        )
    )
    return proofs


class TransferWFVerifier:
    def __init__(self, ped_params, inputs, outputs):
        self.pp = list(ped_params)
        self.inputs = list(inputs)
        self.outputs = list(outputs)

    @guard
    def verify(self, raw: bytes) -> None:
        wf = TransferWF.from_bytes(raw)
        in_proofs = _side_proofs(
            self.inputs, wf.input_values, wf.input_bfs, wf.type_resp, wf.sum_resp, wf.challenge
        )
        out_proofs = _side_proofs(
            self.outputs, wf.output_values, wf.output_bfs, wf.type_resp, wf.sum_resp, wf.challenge
        )
        in_coms = [schnorr.recompute_commitment(self.pp, pr) for pr in in_proofs]
        out_coms = [schnorr.recompute_commitment(self.pp, pr) for pr in out_proofs]
        # the last commitment of each side is the reconstructed sum commitment
        chal = challenge_transfer_wf(
            in_coms[:-1], in_coms[-1], out_coms[:-1], out_coms[-1], self.inputs, self.outputs
        )
        if chal != wf.challenge:
            raise ValueError("invalid transfer well-formedness proof")


def verify_transfer_wfs(ped_params, specs) -> List[Optional[bool]]:
    """Block-level transfer WF verification.

    `specs` are (inputs, outputs, raw_wf) triples — one per proof left to
    the host. Every proof's Schnorr commitment recomputation collapses
    into batched multiexp rows (`schnorr.recompute_commitments`) and every
    Fiat-Shamir challenge into ONE `hm.hash_to_zr_many` dispatch, instead
    of per-proof ctypes/hashlib round trips.

    Returns one entry per spec: True (challenge matches — byte-identical
    to `TransferWFVerifier.verify` accepting), False (challenge mismatch)
    or None (proof this batch could not evaluate). Degrade-only contract:
    callers treat anything but True as "re-verify on the scalar path",
    which owns the precise error message.
    """
    pp = list(ped_params)
    specs = list(specs)
    out: List[Optional[bool]] = [None] * len(specs)
    proofs: List[schnorr.SchnorrProof] = []
    # (spec index, wf, inputs, outputs, com slice start) per parsable spec
    plans = []
    for i, (inputs, outputs, raw) in enumerate(specs):
        try:
            wf = TransferWF.from_bytes(raw)
            start = len(proofs)
            proofs += _side_proofs(
                list(inputs), wf.input_values, wf.input_bfs,
                wf.type_resp, wf.sum_resp, wf.challenge,
            )
            proofs += _side_proofs(
                list(outputs), wf.output_values, wf.output_bfs,
                wf.type_resp, wf.sum_resp, wf.challenge,
            )
        except Exception:
            continue
        plans.append((i, wf, list(inputs), list(outputs), start))
    if not plans:
        return out
    coms = schnorr.recompute_commitments([pp] * len(proofs), proofs)
    transcripts = []
    keep = []  # (spec index, expected challenge) aligned with transcripts
    for i, wf, inputs, outputs, start in plans:
        n_in, n_out = len(inputs), len(outputs)
        in_coms = coms[start : start + n_in + 1]
        out_coms = coms[start + n_in + 1 : start + n_in + n_out + 2]
        try:
            raw = g1s_bytes(
                in_coms[:-1], [in_coms[-1]], out_coms[:-1], [out_coms[-1]],
                inputs, outputs,
            )
        except Exception:
            continue  # un-encodable commitment: scalar path reports it
        transcripts.append((raw, b"fts/transfer-wf"))
        keep.append((i, wf.challenge))
    for (i, expected), got in zip(keep, hm.hash_to_zr_many(transcripts)):
        out[i] = got == expected
    return out


# ===================================================================
# Issue well-formedness
# ===================================================================


@dataclass
class IssueWF:
    type_resp: Optional[int]  # set iff anonymous
    type_clear: Optional[str]  # set iff not anonymous
    values: List[int]
    bfs: List[int]
    challenge: int

    def to_bytes(self) -> bytes:
        return dumps(
            {
                "t": self.type_resp,
                "tc": self.type_clear,
                "v": self.values,
                "b": self.bfs,
                "c": self.challenge,
            }
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "IssueWF":
        d = loads(raw)
        return cls(d["t"], d["tc"], d["v"], d["b"], d["c"])


class IssueWFProver:
    def __init__(self, witnesses, tokens, anonymous: bool, ped_params, rng=None):
        """witnesses: list of (type, value, bf) triples with common type."""
        self.witnesses = witnesses
        self.tokens = list(tokens)
        self.anonymous = anonymous
        self.pp = list(ped_params)
        self.rng = rng

    def prove(self) -> bytes:
        token_type = self.witnesses[0][0]
        rho_T = _rand(self.rng) if self.anonymous else 0
        Q = hm.g1_mul(self.pp[0], rho_T) if self.anonymous else None
        rho_v = [_rand(self.rng) for _ in self.tokens]
        rho_b = [_rand(self.rng) for _ in self.tokens]
        coms = [
            hm.g1_add(Q, hm.g1_multiexp(self.pp[1:3], [rho_v[i], rho_b[i]]))
            for i in range(len(self.tokens))
        ]
        chal = challenge_issue_wf(coms, self.tokens)
        values = [w[1] for w in self.witnesses]
        bfs = [w[2] for w in self.witnesses]
        return IssueWF(
            type_resp=(
                schnorr.respond([hm.hash_to_zr(token_type.encode())], [rho_T], chal)[0]
                if self.anonymous
                else None
            ),
            type_clear=None if self.anonymous else token_type,
            values=schnorr.respond(values, rho_v, chal),
            bfs=schnorr.respond(bfs, rho_b, chal),
            challenge=chal,
        ).to_bytes()


def challenge_issue_wf(coms, tokens) -> int:
    return hm.hash_to_zr(g1s_bytes(coms, tokens), b"fts/issue-wf")


class IssueWFVerifier:
    def __init__(self, tokens, anonymous: bool, ped_params):
        self.tokens = list(tokens)
        self.anonymous = anonymous
        self.pp = list(ped_params)

    @guard
    def verify(self, raw: bytes) -> None:
        wf = IssueWF.from_bytes(raw)
        if self.anonymous:
            if wf.type_resp is None:
                raise ValueError("invalid issue proof: missing hidden-type response")
            type_resp = wf.type_resp
        else:
            if not wf.type_clear:
                raise ValueError("invalid issue proof: missing clear type")
            # non-anonymous: type randomness is zero, response = c * hash(type)
            type_resp = wf.challenge * hm.hash_to_zr(wf.type_clear.encode()) % hm.R
        if len(wf.values) != len(self.tokens) or len(wf.bfs) != len(self.tokens):
            raise ValueError("invalid issue proof: response count mismatch")
        proofs = [
            schnorr.SchnorrProof(tok, [type_resp, wf.values[i], wf.bfs[i]], wf.challenge)
            for i, tok in enumerate(self.tokens)
        ]
        coms = [schnorr.recompute_commitment(self.pp, pr) for pr in proofs]
        if challenge_issue_wf(coms, self.tokens) != wf.challenge:
            raise ValueError("invalid issue well-formedness proof")
