"""zkatdlog token data: Pedersen-committed (type, value) + owner.

Reference: `crypto/token/token.go` — Token{Owner, Data}, Metadata openings,
GetTokensWithWitness, GetTokenInTheClear.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from . import hostmath as hm, pedersen
from .serialization import dumps, loads


@dataclass
class Token:
    """On-ledger token: owner identity bytes + commitment to (type, value)."""

    owner: bytes
    data: tuple  # G1 commitment

    def is_redeem(self) -> bool:
        return len(self.owner) == 0

    def to_bytes(self) -> bytes:
        return dumps({"o": self.owner, "d": self.data})

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Token":
        d = loads(raw)
        return cls(d["o"], d["d"])


@dataclass
class Metadata:
    """Opening of a token commitment, shared off-chain with owner/auditor."""

    token_type: str
    value: int
    bf: int
    owner: bytes = b""
    issuer: bytes = b""

    def to_bytes(self) -> bytes:
        return dumps(
            {"t": self.token_type, "v": self.value, "b": self.bf, "o": self.owner, "i": self.issuer}
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Metadata":
        d = loads(raw)
        return cls(d["t"], d["v"], d["b"], d["o"], d["i"])


@dataclass
class TokenDataWitness:
    token_type: str
    value: int
    bf: int


def compute_tokens(witnesses: Sequence[TokenDataWitness], ped_params) -> List[tuple]:
    """Commitments for a batch of witnesses (reference token.go:64-76)."""
    return [
        pedersen.token_commitment(w.token_type, w.value, w.bf, ped_params)
        for w in witnesses
    ]


def tokens_with_witness(
    values: Sequence[int], token_type: str, ped_params, rng=None
) -> Tuple[List[tuple], List[TokenDataWitness]]:
    """Fresh blinded commitments for given values (reference token.go:78-98)."""
    witnesses = [
        TokenDataWitness(token_type, v, hm.rand_zr(rng)) for v in values
    ]
    return compute_tokens(witnesses, ped_params), witnesses


def token_in_the_clear(token: Token, meta: Metadata, ped_params) -> Tuple[str, int, bytes]:
    """Open a token against its metadata; raises on mismatch
    (reference token.go:48-62)."""
    com = pedersen.token_commitment(meta.token_type, meta.value, meta.bf, ped_params)
    if com != token.data:
        raise ValueError("cannot retrieve token in the clear: output does not match provided opening")
    return meta.token_type, meta.value, token.owner
