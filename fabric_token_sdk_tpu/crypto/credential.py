"""Anonymous credentials from Pointcheval-Sanders signatures (idemix-analog).

Reference capability: the zkatdlog driver uses Fabric idemix for anonymous
owner identities (setup.go IdemixIssuerPK; nogh/identity.go). Here the
same capability is built from the in-house PS machinery:

* a user obtains a credential on hidden attributes via BLIND issuance
  (`pssign.BlindSigner` — the issuer never sees the attributes), and
* presents it unlinkably via a proof of knowledge of the randomized
  signature bound to a presentation message, with SELECTIVE DISCLOSURE:
  revealed attributes move to the statement side of the pairing equation
  (e(R'^c, PK_0 + sum_disclosed PK_i^{v_i})), hidden ones stay witnesses.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from . import elgamal, hostmath as hm, pssign, schnorr, sigproof
from .serialization import dumps, g1s_bytes, g2s_bytes, guard, loads


@dataclass
class CredentialIssuerPublic:
    pk: List[tuple]
    Q: tuple
    ped: List[tuple]


@dataclass
class CredentialIssuer:
    """Issues credentials on `n_attrs` hidden attributes."""

    signer: pssign.Signer
    ped: List[tuple]  # n_attrs + 1 commitment bases

    @classmethod
    def create(cls, n_attrs: int, rng=None) -> "CredentialIssuer":
        signer = pssign.keygen(n_attrs, rng)
        ped = [hm.rand_g1(rng) for _ in range(n_attrs + 1)]
        return cls(signer, ped)

    @property
    def public(self) -> CredentialIssuerPublic:
        return CredentialIssuerPublic(self.signer.pk, self.signer.Q, self.ped)

    def blind_issue(self, request: pssign.BlindSignRequest) -> pssign.BlindSignResponse:
        return pssign.BlindSigner(self.signer, self.ped).blind_sign(request)


@dataclass
class Credential:
    attributes: List[int]
    msg_hash: int  # the PS "hash" message fixed at blind issuance
    signature: pssign.Signature


def _presentation_challenge(pub, com_gt, sig, disclosed: Dict[int, int],
                            message: bytes) -> int:
    raw = (
        g2s_bytes(pub.pk, [pub.Q])
        + g1s_bytes(pub.ped)
        + hm.gt_to_bytes(com_gt)
        + sig.transcript_bytes()
        + dumps({"d": {str(k): v for k, v in sorted(disclosed.items())}})
        + message
    )
    return hm.hash_to_zr(raw, b"fts/credential")


class CredentialUser:
    def __init__(self, issuer_pub: CredentialIssuerPublic, attributes: Sequence[int], rng=None):
        self.pub = issuer_pub
        self.attributes = list(attributes)
        self.rng = rng

    # ------------------------------------------------------------ issuance

    def request_credential(self):
        """-> (recipient_state, BlindSignRequest) for the issuer."""
        bf = hm.rand_zr(self.rng)
        com = hm.g1_multiexp(self.pub.ped, self.attributes + [bf])
        enc_sk = elgamal.keygen(rng=self.rng)
        verifier = pssign.SignVerifier(pk=self.pub.pk, Q=self.pub.Q)
        rec = pssign.Recipient(
            self.attributes, bf, com, enc_sk, self.pub.ped, verifier, self.rng
        )
        return rec, rec.request()

    def finish(self, rec, response: pssign.BlindSignResponse) -> Credential:
        sig = rec.unblind(response)  # verifies internally
        return Credential(self.attributes, response.msg_hash, sig)

    # -------------------------------------------------------- presentation

    def present(self, cred: Credential, message: bytes,
                disclose: Optional[Sequence[int]] = None) -> bytes:
        """Unlinkable presentation bound to `message`, revealing the
        attribute values at the indices in `disclose`."""
        disclose = sorted(set(disclose or []))
        hidden = [i for i in range(len(cred.attributes)) if i not in disclose]
        disclosed = {i: cred.attributes[i] for i in disclose}
        P = self.pub.ped[0]
        # randomize + obfuscate the signature
        rnd = pssign.SignVerifier(self.pub.pk, self.pub.Q).randomize(
            cred.signature, self.rng
        )
        bf = hm.rand_zr(self.rng)
        obf = pssign.Signature(rnd.R, hm.g1_add(rnd.S, hm.g1_mul(P, bf)))
        # commitment over hidden-attribute randomness
        rho = {i: hm.rand_zr(self.rng) for i in hidden}
        rho_h = hm.rand_zr(self.rng)
        rho_bf = hm.rand_zr(self.rng)
        t_rand = hm.g2_mul(self.pub.pk[-1], rho_h)
        for i in hidden:
            t_rand = hm.g2_add(t_rand, hm.g2_mul(self.pub.pk[i + 1], rho[i]))
        com_gt = hm.pairing_product(
            [(rnd.R, t_rand), (hm.g1_mul(P, rho_bf), self.pub.Q)]
        )
        chal = _presentation_challenge(self.pub, com_gt, obf, disclosed, message)
        z_hidden = [
            (rho[i] + chal * cred.attributes[i]) % hm.R for i in hidden
        ]
        return dumps(
            {
                "c": chal,
                "sr": obf.R,
                "ss": obf.S,
                "m": z_hidden,
                "h": (rho_h + chal * cred.msg_hash) % hm.R,
                "b": (rho_bf + chal * bf) % hm.R,
                "d": {str(i): disclosed[i] for i in disclose},
            }
        )


class CredentialVerifier:
    def __init__(self, issuer_pub: CredentialIssuerPublic):
        self.pub = issuer_pub

    @guard
    def verify(self, raw: bytes, message: bytes,
               expect_disclosed: Optional[Dict[int, int]] = None) -> Dict[int, int]:
        d = loads(raw)
        disclosed = {int(k): v for k, v in d["d"].items()}
        n_attrs = len(self.pub.pk) - 2
        hidden = [i for i in range(n_attrs) if i not in disclosed]
        if len(d["m"]) != len(hidden):
            raise ValueError("credential: response count mismatch")
        sig = pssign.Signature(d["sr"], d["ss"])
        chal, z_h, z_bf = d["c"], d["h"], d["b"]
        # t = sum_hidden PK_i^{z_i} + PK_h^{z_h}
        t = hm.g2_mul(self.pub.pk[-1], z_h)
        for z, i in zip(d["m"], hidden):
            t = hm.g2_add(t, hm.g2_mul(self.pub.pk[i + 1], z))
        # statement side: PK_0 + sum_disclosed PK_i^{v_i}
        stmt = self.pub.pk[0]
        for i, v in disclosed.items():
            if not 0 <= i < n_attrs:
                raise ValueError("credential: disclosed index out of range")
            stmt = hm.g2_add(stmt, hm.g2_mul(self.pub.pk[i + 1], v))
        P = self.pub.ped[0]
        com_gt = hm.pairing_product(
            [
                (hm.g1_neg(hm.g1_mul(sig.S, chal)), self.pub.Q),
                (hm.g1_mul(sig.R, chal), stmt),
                (sig.R, t),
                (hm.g1_mul(P, z_bf), self.pub.Q),
            ]
        )
        if _presentation_challenge(self.pub, com_gt, sig, disclosed, message) != chal:
            raise ValueError("invalid credential presentation")
        if expect_disclosed:
            for idx, val in expect_disclosed.items():
                if disclosed.get(idx) != val:
                    raise ValueError("credential: disclosed attribute mismatch")
        return disclosed
