"""Schnorr sigma-protocol core: multi-witness proofs over Pedersen bases.

Reference: `crypto/common/schnorr.go` — Prove (p_i = r_i + c*w_i),
RecomputeCommitment (com = prod P_i^{p_i} / Statement^c), ComputeChallenge.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from . import hostmath as hm


@dataclass
class SchnorrProof:
    """ZK proof of knowledge of (w_1..w_n): statement = prod P_i^{w_i}."""

    statement: tuple  # G1
    responses: List[int]  # Zr
    challenge: int  # Zr


def respond(witnesses: Sequence[int], randomness: Sequence[int], challenge: int) -> List[int]:
    """p_i = r_i + c*w_i mod r (reference schnorr.go:36-56)."""
    if len(witnesses) != len(randomness):
        raise ValueError("schnorr: witness/randomness length mismatch")
    return [(r + challenge * w) % hm.R for w, r in zip(witnesses, randomness)]


def recompute_commitment(bases: Sequence, proof: SchnorrProof):
    """com = prod bases[i]^{responses[i]} - statement*challenge.

    This is the verifier's reconstruction of the prover's randomness
    commitment (reference schnorr.go:78-104).
    """
    if len(proof.responses) > len(bases):
        raise ValueError("schnorr: more responses than bases")
    com = hm.g1_multiexp(list(bases[: len(proof.responses)]), proof.responses)
    return hm.g1_add(com, hm.g1_neg(hm.g1_mul(proof.statement, proof.challenge)))


def commit_randomness(bases: Sequence, randomness: Sequence[int]):
    """Prover side: commitment to fresh randomness."""
    return hm.g1_multiexp(list(bases[: len(randomness)]), list(randomness))


def recompute_commitments(bases_rows: Sequence[Sequence],
                          proofs: Sequence[SchnorrProof]) -> List:
    """Batch `recompute_commitment` over many proofs.

    Each proof folds into ONE multiexp row — (bases..., statement) against
    (responses..., -challenge), the statement negation riding the scalar —
    which is the same group element the scalar helper assembles from
    multiexp + add. All rows then go down in single native dispatches via
    `hm.g1_multiexp_rows` instead of one ctypes round trip per proof.
    """
    if len(bases_rows) != len(proofs):
        raise ValueError("schnorr: bases/proofs length mismatch")
    rows_p, rows_s = [], []
    for bases, proof in zip(bases_rows, proofs):
        if len(proof.responses) > len(bases):
            raise ValueError("schnorr: more responses than bases")
        rows_p.append(list(bases[: len(proof.responses)]) + [proof.statement])
        rows_s.append(list(proof.responses) + [(-proof.challenge) % hm.R])
    return hm.g1_multiexp_rows(rows_p, rows_s)
