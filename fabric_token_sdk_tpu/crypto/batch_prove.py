"""Batched (TPU) transfer-proof GENERATION over the compile-once stage tiles.

`crypto/batch.py` made verification batch-parallel; this module is the
prove-side twin (SURVEY layer 7 promises batch-parallel *prove and*
verify; reference prove side: `crypto/transfer/sender.go`,
`crypto/range/proof.go`). A `BatchedTransferProver` takes N same-shape
`(n_in, n_out)` witness sets and generates N transfer proofs in ONE pass:

* commit phase on device — all Pedersen commitments, Schnorr announcement
  points, PS-signature randomization/obfuscation, and the membership
  GT pre-commitments run as batched fixed-base MSM / variable-base
  scalar-mul / pairing stage calls (`ops/stages.py`, `ops/pairing.py`);
* Fiat-Shamir + responses on host — challenge hashing and the Zr response
  arithmetic stay in python, shared VERBATIM with the host provers via
  the `draw`/`finish` split in `wellformedness.py` / `rangeproof.py` /
  `sigproof.py`.

The emitted proofs are byte-compatible with the host `TransferProver`
output: the unchanged host `TransferVerifier` (and the batched
`BatchedTransferVerifier`) accepts them, and tampering is rejected
identically — device proving may only accelerate, never change,
accept/reject.

Program-set discipline: every device step is a canonical ROW_TILE stage
tile or the staged K=2 pairing product, all of which `ops/warmup.py`
precompiles — batch-proving a NEW transfer shape compiles zero XLA
programs post-warmup (see `tests/test_compile_budget.py`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import hostmath as hm, pssign, rangeproof, sigproof, wellformedness as wf
from .batch import _MeshBound
from .pedersen import BatchedPedersen
from .setup import PublicParams
from .transfer import TransferProof, _skip_range
from ..ops import curve as cv, curve2 as cv2, limbs as lb, pairing as pr, \
    stages as st, tower as tw
from ..utils import devobs
from ..utils import metrics as mx, resilience


class BatchedTransferProver(_MeshBound):
    """Generates whole batches of same-shape zkatdlog transfer proofs.

    One instance caches the fixed-base window tables (Pedersen 3-base and
    2-base, PedGen) and the encoded G2 public keys — constructing it is
    the expensive part; `prove` calls are cheap and reusable across
    shapes and batch sizes (the stage tiles are shape-invariant). An
    optional `MeshConfig` shards the commit-phase dispatch over dp
    (stage rows) x mp (pairing legs) — same compile-once executables,
    byte-identical proofs.
    """

    def __init__(self, pp: PublicParams, mesh=None):
        self.pp = pp
        self.set_mesh(mesh)
        self.ped3 = BatchedPedersen(pp.ped_params)
        self.ped2 = BatchedPedersen(pp.ped_params[:2])
        rp = pp.range_params
        self.pedP = BatchedPedersen([pp.ped_gen]) if rp else None
        if rp is not None:
            self.pk_np = np.asarray(cv2.encode_points(rp.sign_pk))  # (3,3,2,L)
            self.Q_np = np.asarray(pr.encode_g2([rp.Q]))[0]  # (2,2,L)
            # signed-set signature points, encoded once per digit value
            self.sig_R_np = np.stack(
                [cv.encode_point(s.R) for s in rp.signed_values]
            )
            self.sig_S_np = np.stack(
                [cv.encode_point(s.S) for s in rp.signed_values]
            )

    # ------------------------------------------------------------ helpers

    @staticmethod
    def _check_shapes(reqs) -> Tuple[int, int]:
        shapes = {(len(r[2]), len(r[3])) for r in reqs}
        if len(shapes) != 1:
            raise ValueError(
                f"batched prove needs one uniform (n_in, n_out) shape, got {sorted(shapes)}"
            )
        (n_in, n_out), = shapes
        if n_in == 0 or n_out == 0:
            raise ValueError("batched prove: empty inputs or outputs")
        return n_in, n_out

    # ------------------------------------------------------------ WF phase

    def _prove_wf(self, reqs, n_in: int, n_out: int, rng) -> List[bytes]:
        provers = [
            wf.TransferWFProver(
                wf.TransferWFWitness(
                    token_type=iw[0].token_type,
                    in_values=[w.value for w in iw],
                    in_bfs=[w.bf for w in iw],
                    out_values=[w.value for w in ow],
                    out_bfs=[w.bf for w in ow],
                ),
                self.pp.ped_params, inputs, outputs, rng,
            )
            for iw, ow, inputs, outputs in reqs
        ]
        draws = [p.draw() for p in provers]
        n = n_in + n_out + 2
        rows: List[List[int]] = []
        for d in draws:
            rows += d.commit_rows(n_in, n_out)
        coms, _ = self.ped3.commit_ints(rows, dp=self._dp)
        out = []
        for i, (p, d) in enumerate(zip(provers, draws)):
            row = coms[i * n : (i + 1) * n]
            chal = wf.challenge_transfer_wf(
                row[:n_in], row[n_in], row[n_in + 1 : -1], row[-1],
                p.inputs, p.outputs,
            )
            out.append(p.finish(d, chal))
        return out

    # ------------------------------------------------------------ range phase

    def _prove_range(self, reqs, n_out: int, rng) -> List[bytes]:
        pp, rp = self.pp, self.pp.range_params
        if rp is None:
            raise ValueError("public params carry no range-proof parameters")
        base, exponent = rp.base, rp.exponent
        B = len(reqs)
        provers = [
            rangeproof.RangeProver(
                [rangeproof.TokenWitness(w.token_type, w.value, w.bf) for w in ow],
                outputs, rp.signed_values, base, exponent,
                pp.ped_params, rp.sign_pk, pp.ped_gen, rp.Q, rng,
            )
            for _, ow, _, outputs in reqs
        ]
        draws = [p.draw() for p in provers]  # raises on out-of-range values
        M = B * n_out * exponent  # flattened (tx, output, digit) rows
        L = lb.NLIMBS

        # flat per-digit views, in (tx, output, digit) order
        digits = [
            d.digits[k][i]
            for d in draws for k in range(n_out) for i in range(exponent)
        ]
        digit_bfs = [
            d.digit_bfs[k][i]
            for d in draws for k in range(n_out) for i in range(exponent)
        ]
        mems = [
            d.mem[k][i]
            for d in draws for k in range(n_out) for i in range(exponent)
        ]

        # ---- ped[:2] fixed-base MSMs, one call: digit commitments
        # (d, bf), membership value announcements (rho_v, rho_cb), and
        # equality digit-aggregate announcements (rho_v, rho_cb)
        rows2 = (
            [[digits[j], digit_bfs[j]] for j in range(M)]
            + [[m.rho_v, m.rho_cb] for m in mems]
        )
        for d in draws:
            rows2 += d.equality_value_rows()
        coms2, _ = self.ped2.commit_ints(rows2, dp=self._dp)
        digit_coms = coms2[:M]
        mem_com_vals = coms2[M : 2 * M]
        eq_com_values = coms2[2 * M :]  # B*n_out

        # ---- ped 3-base MSM: per-token equality announcements
        rows3: List[List[int]] = []
        for d in draws:
            rows3 += d.equality_token_rows()
        eq_com_tokens, _ = self.ped3.commit_ints(rows3, dp=self._dp)

        # ---- signature randomization: (R^r, S^r) variable-base, then
        # obfuscation S'' = S^r + P^sig_bf (fixed-base + Jacobian add)
        r_enc = cv.encode_scalars([m.r for m in mems])
        sig_R = self.sig_R_np[digits]  # (M, 3, L) gather by digit value
        sig_S = self.sig_S_np[digits]
        rnd = st.g1_mul_rows(
            np.concatenate([sig_R, sig_S]), np.concatenate([r_enc, r_enc]),
            dp=self._dp,
        )
        rnd_R_jac, rnd_S_jac = rnd[:M], rnd[M:]
        pbf_scal = cv.encode_scalars(
            [m.sig_bf for m in mems] + [m.rho_bf for m in mems]
        )
        # decode-free commit path: P^sig_bf feeds the Jacobian add and
        # P^rho_bf is decoded once below with the other transcript points
        pbf_jac = self.pedP.commit_rows(pbf_scal[:, None, :], dp=self._dp)
        obf_S_jac = st.g1_add_rows(rnd_S_jac, pbf_jac[:M], dp=self._dp)

        # one host decode pass for everything that enters a transcript
        host_pts = cv.decode_points(
            np.concatenate([rnd_R_jac, obf_S_jac, pbf_jac[M:]])
        )
        rnd_R, obf_S, p_rho = (
            host_pts[:M], host_pts[M : 2 * M], host_pts[2 * M :]
        )

        # ---- GT pre-commitments: t = PK1^rho_v + PK2^rho_h in G2, then
        # com_gt = e(R', t) * e(P^rho_bf, Q) via the staged K=2 product
        g2_bases = np.concatenate(
            [
                np.broadcast_to(self.pk_np[1], (M,) + self.pk_np.shape[1:]),
                np.broadcast_to(self.pk_np[2], (M,) + self.pk_np.shape[1:]),
            ]
        )
        g2_scal = cv.encode_scalars(
            [m.rho_v for m in mems] + [m.rho_h for m in mems]
        )
        terms = st.g2_mul_rows(g2_bases, g2_scal, dp=self._dp)
        t_aff = st.g2_to_affine_rows(
            st.g2_add_rows(terms[:M], terms[M:], dp=self._dp), dp=self._dp
        )
        Ps = np.stack(
            [np.asarray(pr.encode_g1(rnd_R)), np.asarray(pr.encode_g1(p_rho))],
            axis=1,
        )  # (M, 2, 2, L)
        Qs = np.stack(
            [t_aff, np.broadcast_to(self.Q_np, t_aff.shape)], axis=1
        )  # (M, 2, 2, 2, L)
        gts = tw.decode_fp12(
            pr.pairing_product_staged(Ps, Qs, dp=self._dp, mp=self._mp)
        )

        # ---- host Fiat-Shamir + responses (shared with the host prover)
        mem_proofs_flat: List[sigproof.MembershipProof] = []
        for j in range(M):
            obf = pssign.Signature(rnd_R[j], obf_S[j])
            mv = sigproof.MembershipVerifier(
                digit_coms[j], pp.ped_gen, rp.Q, rp.sign_pk, pp.ped_params[:2]
            )
            chal = mv._challenge(gts[j], mem_com_vals[j], obf)
            w = sigproof.MembershipWitness(
                rp.signed_values[digits[j]], digits[j], digit_bfs[j]
            )
            mem_proofs_flat.append(
                sigproof.membership_finish(w, mems[j], obf, chal, digit_coms[j])
            )

        out = []
        for i, (p, d) in enumerate(zip(provers, draws)):
            span = slice(i * n_out * exponent, (i + 1) * n_out * exponent)
            tx_coms = digit_coms[span]
            tx_mems = mem_proofs_flat[span]
            dc = [
                tx_coms[k * exponent : (k + 1) * exponent] for k in range(n_out)
            ]
            mp = [
                tx_mems[k * exponent : (k + 1) * exponent] for k in range(n_out)
            ]
            chal = p._challenge(
                eq_com_tokens[i * n_out : (i + 1) * n_out],
                eq_com_values[i * n_out : (i + 1) * n_out],
                dc,
            )
            out.append(p.finish(d, dc, mp, chal))
        return out

    # ------------------------------------------------------------ entry

    def prove(self, reqs: Sequence[tuple], rng=None) -> List[bytes]:
        """reqs: (in_witnesses, out_witnesses, inputs, outputs) tuples of
        ONE uniform `(n_in, n_out)` shape — the same arguments the host
        `TransferProver` constructor takes. Returns one transfer-proof
        byte string per request (same wire format as the host prover).
        """
        reqs = list(reqs)
        if not reqs:
            return []
        n_in, n_out = self._check_shapes(reqs)
        with devobs.plane("prove"), mx.span(
            "batch.prove", txs=len(reqs), shape=f"({n_in},{n_out})"
        ):
            with mx.span("batch.prove.wf"):
                wfs = self._prove_wf(reqs, n_in, n_out, rng)
            if _skip_range(n_in, n_out):
                ranges: List[Optional[bytes]] = [None] * len(reqs)
            else:
                with mx.span("batch.prove.range"):
                    ranges = self._prove_range(reqs, n_out, rng)
        # counted on COMPLETION (a device-plane failure re-proves the
        # group on host — those txs land in batch.prove.host instead,
        # and so do the txs of an ABANDONED bounded worker finishing
        # late: its proofs are discarded, they must not report device)
        if not resilience.call_abandoned():
            mx.counter("batch.prove.batches").inc()
            mx.counter("batch.prove.txs").inc(len(reqs))
        return [
            TransferProof(wf=w, range_correctness=rc).to_bytes()
            for w, rc in zip(wfs, ranges)
        ]


# ---------------------------------------------------------------- cache

# Tables are expensive to build (host windowed multiples); keep a small
# identity-keyed cache so repeated `TransferProver.batch` calls against
# the same PublicParams reuse one prover. PublicParams is an unhashable
# mutable dataclass, so the key is object identity with a strong ref
# (params objects are small; the cap bounds growth).
_CACHE: List[Tuple[PublicParams, BatchedTransferProver]] = []
_CACHE_CAP = 4


def prover_for(pp: PublicParams, mesh=None) -> BatchedTransferProver:
    for cached_pp, prover in _CACHE:
        if cached_pp is pp:
            # the cache reuses TABLES; the mesh is per-caller dispatch
            # state and re-binds on every hit (None = ambient/unsharded)
            # so the host `TransferProver.batch` path can never inherit
            # a mesh left over from a mesh-aware caller
            prover.set_mesh(mesh)
            return prover
    prover = BatchedTransferProver(pp, mesh=mesh)
    _CACHE.append((pp, prover))
    if len(_CACHE) > _CACHE_CAP:
        _CACHE.pop(0)
    return prover
