"""Host (pure-Python) BN254 math — the framework's correctness anchor.

The reference SDK delegates all group/pairing math to IBM mathlib (backed by
gnark-crypto); see e.g. reference token/core/zkatdlog/crypto/setup.go:13 and
pssign/sign.go:153 (`Curve.Pairing2`, `Curve.FExp`). This module is the
control-plane twin of the TPU limb-tensor kernels in
``fabric_token_sdk_tpu.ops``: same curve (BN254 / alt_bn128), same canonical
serialization, used for setup, single-shot host ops, and differential tests
against the batched device path.

Representation choices (host-only, speed via Python big ints):
  Fp      : int mod P
  Fp2     : (a, b) = a + b*i,           i^2 = -1
  Fp12    : 6-tuple of Fp2 over basis {1, w, ..., w^5},  w^6 = XI = 9 + i
  G1      : (x, y) ints, None = infinity  (y^2 = x^3 + 3)
  G2      : (x, y) Fp2 pairs, None = infinity (y^2 = x^3 + 3/XI, D-twist)
  GT      : Fp12

Pairing: optimal ate, Miller loop over 6u+2 with the two Frobenius line
corrections, final exponentiation (p^12-1)/r.
"""

from __future__ import annotations

import hashlib
import secrets

# ---------------------------------------------------------------- constants

# BN parameter u and derived primes (p = 36u^4+36u^3+24u^2+6u+1, etc.)
U = 4965661367192848881
P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617
ATE_LOOP = 6 * U + 2

B1 = 3  # G1: y^2 = x^3 + 3
G1_GEN = (1, 2)

# Standard alt_bn128 G2 generator (EIP-197 ordering: x = x0 + x1*i).
G2_GEN = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)

# ---------------------------------------------------------------- Fp

def fp_inv(a: int) -> int:
    return pow(a, P - 2, P)


def fp_sqrt(a: int):
    """Square root in Fp (P = 3 mod 4); returns None if a is not a QR."""
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a % P else None


# ---------------------------------------------------------------- Fp2

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)
XI = (9, 1)  # 9 + i, the sextic non-residue


def fp2(a: int, b: int = 0):
    return (a % P, b % P)


def fp2_add(x, y):
    return ((x[0] + y[0]) % P, (x[1] + y[1]) % P)


def fp2_sub(x, y):
    return ((x[0] - y[0]) % P, (x[1] - y[1]) % P)


def fp2_neg(x):
    return (-x[0] % P, -x[1] % P)


def fp2_mul(x, y):
    a, b = x
    c, d = y
    ac = a * c
    bd = b * d
    # (a+bi)(c+di) = ac - bd + ((a+b)(c+d) - ac - bd) i
    return ((ac - bd) % P, ((a + b) * (c + d) - ac - bd) % P)


def fp2_sqr(x):
    a, b = x
    # (a+bi)^2 = (a+b)(a-b) + 2ab i
    return ((a + b) * (a - b) % P, 2 * a * b % P)


def fp2_scale(x, k: int):
    return (x[0] * k % P, x[1] * k % P)


def fp2_conj(x):
    return (x[0], -x[1] % P)


def fp2_inv(x):
    a, b = x
    n = fp_inv((a * a + b * b) % P)
    return (a * n % P, -b * n % P)


def fp2_sqrt(a):
    """Square root in Fp2 via the norm trick (valid for P = 3 mod 4)."""
    x, y = a
    if y == 0:
        r = fp_sqrt(x)
        if r is not None:
            return (r, 0)
        r = fp_sqrt(-x % P)
        return None if r is None else (0, r)
    s = fp_sqrt((x * x + y * y) % P)
    if s is None:
        return None
    half = fp_inv(2)
    for cand in ((x + s) * half % P, (x - s) * half % P):
        t = fp_sqrt(cand)
        if t is not None and t != 0:
            res = (t, y * fp_inv(2 * t % P) % P)
            if fp2_sqr(res) == (x % P, y % P):
                return res
    return None


def fp2_pow(x, e: int):
    if e < 0:
        return fp2_pow(fp2_inv(x), -e)
    acc = FP2_ONE
    base = x
    while e:
        if e & 1:
            acc = fp2_mul(acc, base)
        base = fp2_sqr(base)
        e >>= 1
    return acc


# ---------------------------------------------------------------- Fp12
# Flat representation: c = sum_j c[j] w^j, c[j] in Fp2, w^6 = XI.
# Tower view used for inversion: Fp6 = Fp2[v]/(v^3 - XI) with v = w^2,
# Fp12 = Fp6[w]/(w^2 - v).

FP12_ZERO = tuple(FP2_ZERO for _ in range(6))
FP12_ONE = (FP2_ONE,) + tuple(FP2_ZERO for _ in range(5))

# Frobenius coefficients gamma_j = XI^(j*(P-1)/6)
_G = [fp2_pow(XI, j * (P - 1) // 6) for j in range(6)]


def fp12_from_fp2(x):
    return (x,) + tuple(FP2_ZERO for _ in range(5))


def fp12_from_int(k: int):
    return fp12_from_fp2(fp2(k))


def fp12_add(x, y):
    return tuple(fp2_add(a, b) for a, b in zip(x, y))


def fp12_sub(x, y):
    return tuple(fp2_sub(a, b) for a, b in zip(x, y))


def fp12_neg(x):
    return tuple(fp2_neg(a) for a in x)


def fp12_mul(x, y):
    # schoolbook 6x6 with w^6 = XI folding
    acc = [[0, 0] for _ in range(6)]
    for jx in range(6):
        a = x[jx]
        if a == FP2_ZERO:
            continue
        for jy in range(6):
            b = y[jy]
            if b == FP2_ZERO:
                continue
            t = fp2_mul(a, b)
            j = jx + jy
            if j >= 6:
                j -= 6
                t = fp2_mul(t, XI)
            acc[j][0] += t[0]
            acc[j][1] += t[1]
    return tuple((c[0] % P, c[1] % P) for c in acc)


def fp12_sqr(x):
    return fp12_mul(x, x)


def fp12_scale_fp2(x, s):
    return tuple(fp2_mul(c, s) for c in x)


def fp12_conj(x):
    """Conjugate over Fp6 (negate odd powers of w) — inverse on unit cyclo."""
    return tuple(fp2_neg(c) if j & 1 else c for j, c in enumerate(x))


# --- tower split helpers: Fp12 = (c0 + c1 w), c0,c1 in Fp6 = (a0,a1,a2) ---

def _split(x):
    return (x[0], x[2], x[4]), (x[1], x[3], x[5])


def _join(c0, c1):
    return (c0[0], c1[0], c0[1], c1[1], c0[2], c1[2])


def _fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    c0 = fp2_add(t0, fp2_mul(XI, fp2_sub(fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), fp2_add(t1, t2))))
    c1 = fp2_add(fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), fp2_add(t0, t1)), fp2_mul(XI, t2))
    c2 = fp2_add(fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), fp2_add(t0, t2)), t1)
    return (c0, c1, c2)


def _fp6_mul_v(a):
    a0, a1, a2 = a
    return (fp2_mul(XI, a2), a0, a1)


def _fp6_neg(a):
    return tuple(fp2_neg(c) for c in a)


def _fp6_sub(a, b):
    return tuple(fp2_sub(x, y) for x, y in zip(a, b))


def _fp6_inv(a):
    a0, a1, a2 = a
    c0 = fp2_sub(fp2_sqr(a0), fp2_mul(XI, fp2_mul(a1, a2)))
    c1 = fp2_sub(fp2_mul(XI, fp2_sqr(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    t = fp2_mul(a2, c1)
    t = fp2_add(t, fp2_mul(a1, c2))
    t = fp2_mul(XI, t)
    t = fp2_add(t, fp2_mul(a0, c0))
    tinv = fp2_inv(t)
    return (fp2_mul(c0, tinv), fp2_mul(c1, tinv), fp2_mul(c2, tinv))


def fp12_inv(x):
    c0, c1 = _split(x)
    # (c0 + c1 w)^-1 = (c0 - c1 w) / (c0^2 - c1^2 v)
    n = _fp6_sub(_fp6_mul(c0, c0), _fp6_mul_v(_fp6_mul(c1, c1)))
    ninv = _fp6_inv(n)
    return _join(_fp6_mul(c0, ninv), _fp6_neg(_fp6_mul(c1, ninv)))


def fp12_frobenius(x, n: int = 1):
    """x -> x^(p^n) using precomputed gamma constants."""
    out = x
    for _ in range(n):
        out = tuple(fp2_mul(fp2_conj(c), _G[j]) for j, c in enumerate(out))
    return out


def fp12_pow(x, e: int):
    if e < 0:
        return fp12_pow(fp12_inv(x), -e)
    acc = FP12_ONE
    base = x
    while e:
        if e & 1:
            acc = fp12_mul(acc, base)
        base = fp12_sqr(base)
        e >>= 1
    return acc


# ---------------------------------------------------------------- G1

def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B1) % P == 0


def g1_neg(pt):
    if pt is None:
        return None
    return (pt[0], -pt[1] % P)


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        m = 3 * x1 * x1 % P * fp_inv(2 * y1 % P) % P
    else:
        m = (y2 - y1) * fp_inv((x2 - x1) % P) % P
    x3 = (m * m - x1 - x2) % P
    y3 = (m * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_double(pt):
    return g1_add(pt, pt)


def g1_mul(pt, k: int):
    k %= R
    acc = None
    add = pt
    while k:
        if k & 1:
            acc = g1_add(acc, add)
        add = g1_add(add, add)
        k >>= 1
    return acc


def g1_sum(points):
    acc = None
    for pt in points:
        acc = g1_add(acc, pt)
    return acc


def g1_multiexp(points, scalars):
    if len(points) != len(scalars):
        raise ValueError(f"multiexp length mismatch: {len(points)} != {len(scalars)}")
    acc = None
    for pt, s in zip(points, scalars):
        acc = g1_add(acc, g1_mul(pt, s))
    return acc


# ---------------------------------------------------------------- G2 (twist)

B2 = fp2_mul(fp2(B1), fp2_inv(XI))  # 3 / (9 + i)


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    lhs = fp2_sqr(y)
    rhs = fp2_add(fp2_mul(fp2_sqr(x), x), B2)
    return lhs == rhs


def g2_neg(pt):
    if pt is None:
        return None
    return (pt[0], fp2_neg(pt[1]))


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp2_add(y1, y2) == FP2_ZERO:
            return None
        m = fp2_mul(fp2_scale(fp2_sqr(x1), 3), fp2_inv(fp2_scale(y1, 2)))
    else:
        m = fp2_mul(fp2_sub(y2, y1), fp2_inv(fp2_sub(x2, x1)))
    x3 = fp2_sub(fp2_sub(fp2_sqr(m), x1), x2)
    y3 = fp2_sub(fp2_mul(m, fp2_sub(x1, x3)), y1)
    return (x3, y3)


def _g2_mul_raw(pt, k: int):
    """Scalar mul WITHOUT reduction mod R — for subgroup/order checks."""
    acc = None
    add = pt
    while k:
        if k & 1:
            acc = g2_add(acc, add)
        add = g2_add(add, add)
        k >>= 1
    return acc


def g2_mul(pt, k: int):
    return _g2_mul_raw(pt, k % R)


def g2_in_subgroup(pt) -> bool:
    return pt is None or (g2_is_on_curve(pt) and _g2_mul_raw(pt, R) is None)


def g2_sum(points):
    acc = None
    for pt in points:
        acc = g2_add(acc, pt)
    return acc


def g2_multiexp(points, scalars):
    if len(points) != len(scalars):
        raise ValueError(f"multiexp length mismatch: {len(points)} != {len(scalars)}")
    acc = None
    for pt, s in zip(points, scalars):
        acc = g2_add(acc, g2_mul(pt, s))
    return acc


# ---------------------------------------------------------------- pairing

def _untwist(q):
    """Map a G2 (twist) point into E(Fp12): (x, y) -> (x w^2, y w^3)."""
    x, y = q
    xw2 = (FP2_ZERO, FP2_ZERO, x, FP2_ZERO, FP2_ZERO, FP2_ZERO)
    yw3 = (FP2_ZERO, FP2_ZERO, FP2_ZERO, y, FP2_ZERO, FP2_ZERO)
    return (xw2, yw3)


def _e12_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp12_add(y1, y2) == FP12_ZERO:
            return None
        m = fp12_mul(fp12_scale_fp2(fp12_sqr(x1), fp2(3)), fp12_inv(fp12_scale_fp2(y1, fp2(2))))
    else:
        m = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    x3 = fp12_sub(fp12_sub(fp12_sqr(m), x1), x2)
    y3 = fp12_sub(fp12_mul(m, fp12_sub(x1, x3)), y1)
    return (x3, y3)


def _linefunc(t1, t2, px12, py12):
    """Evaluate the line through t1,t2 (E(Fp12) points) at embedded G1 point."""
    x1, y1 = t1
    x2, y2 = t2
    if x1 != x2:
        m = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    elif y1 == y2:
        m = fp12_mul(fp12_scale_fp2(fp12_sqr(x1), fp2(3)), fp12_inv(fp12_scale_fp2(y1, fp2(2))))
    else:
        return fp12_sub(px12, x1)
    return fp12_sub(fp12_mul(m, fp12_sub(px12, x1)), fp12_sub(py12, y1))


def miller_loop(p, q):
    """Miller loop of the optimal ate pairing (no final exponentiation)."""
    if p is None or q is None:
        return FP12_ONE
    px12 = fp12_from_int(p[0])
    py12 = fp12_from_int(p[1])
    qe = _untwist(q)
    t = qe
    f = FP12_ONE
    for bit in bin(ATE_LOOP)[3:]:
        f = fp12_mul(fp12_sqr(f), _linefunc(t, t, px12, py12))
        t = _e12_add(t, t)
        if bit == "1":
            f = fp12_mul(f, _linefunc(t, qe, px12, py12))
            t = _e12_add(t, qe)
    # Frobenius corrections: Q1 = pi(Q), Q2 = -pi^2(Q)
    q1 = (fp12_frobenius(qe[0]), fp12_frobenius(qe[1]))
    nq2 = (fp12_frobenius(q1[0]), fp12_neg(fp12_frobenius(q1[1])))
    f = fp12_mul(f, _linefunc(t, q1, px12, py12))
    t = _e12_add(t, q1)
    f = fp12_mul(f, _linefunc(t, nq2, px12, py12))
    return f


_FINAL_EXP_HARD = (P**4 - P**2 + 1) // R


def final_exp(f):
    """f^((p^12-1)/r) = easy part (p^6-1)(p^2+1), then hard part."""
    t = fp12_mul(fp12_conj(f), fp12_inv(f))          # f^(p^6 - 1)
    t = fp12_mul(fp12_frobenius(t, 2), t)            # ^(p^2 + 1)
    return fp12_pow(t, _FINAL_EXP_HARD)


def pairing(p, q):
    """Full optimal ate pairing e(P, Q) -> GT."""
    return final_exp(miller_loop(p, q))


def pairing_product(pairs):
    """prod e(P_i, Q_i) with one shared final exponentiation.

    Mirrors reference `Curve.Pairing2` + `Curve.FExp`
    (pssign/sign.go:153-154): callers combine two pairings and test unity.
    """
    f = FP12_ONE
    for p, q in pairs:
        f = fp12_mul(f, miller_loop(p, q))
    return final_exp(f)


def gt_is_unity(e) -> bool:
    return e == FP12_ONE


# ---------------------------------------------------------------- randomness

def rand_zr(rng=None) -> int:
    if rng is None:
        return secrets.randbelow(R - 1) + 1
    return rng.randrange(1, R)


def rand_g1(rng=None):
    return g1_mul(G1_GEN, rand_zr(rng))


def rand_g2(rng=None):
    return g2_mul(G2_GEN, rand_zr(rng))


# ---------------------------------------------------------------- encodings

def zr_to_bytes(z: int) -> bytes:
    return (z % R).to_bytes(32, "big")


def zr_from_bytes(raw: bytes) -> int:
    return int.from_bytes(raw, "big") % R


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        return b"\x01" + bytes(64)
    return b"\x00" + pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def g1_from_bytes(raw: bytes):
    if len(raw) != 65:
        raise ValueError("invalid G1 encoding: wrong length")
    if raw[0] == 1:
        if any(raw[1:]):
            raise ValueError("invalid G1 encoding: non-canonical infinity")
        return None
    if raw[0] != 0:
        raise ValueError("invalid G1 encoding: bad tag")
    x = int.from_bytes(raw[1:33], "big")
    y = int.from_bytes(raw[33:65], "big")
    if x >= P or y >= P:
        raise ValueError("invalid G1 encoding: coordinate out of range")
    pt = (x, y)
    if not g1_is_on_curve(pt):
        raise ValueError("invalid G1 encoding: point not on curve")
    return pt


def g2_to_bytes(pt) -> bytes:
    if pt is None:
        return b"\x01" + bytes(128)
    (x0, x1), (y0, y1) = pt
    return (
        b"\x00"
        + x0.to_bytes(32, "big")
        + x1.to_bytes(32, "big")
        + y0.to_bytes(32, "big")
        + y1.to_bytes(32, "big")
    )


def g2_from_bytes(raw: bytes):
    if len(raw) != 129:
        raise ValueError("invalid G2 encoding: wrong length")
    if raw[0] == 1:
        if any(raw[1:]):
            raise ValueError("invalid G2 encoding: non-canonical infinity")
        return None
    if raw[0] != 0:
        raise ValueError("invalid G2 encoding: bad tag")
    vals = [int.from_bytes(raw[1 + 32 * k : 33 + 32 * k], "big") for k in range(4)]
    if any(v >= P for v in vals):
        raise ValueError("invalid G2 encoding: coordinate out of range")
    pt = ((vals[0], vals[1]), (vals[2], vals[3]))
    if not g2_is_on_curve(pt):
        raise ValueError("invalid G2 encoding: point not on curve")
    # The twist has a large cofactor: reject wrong-subgroup points
    # (small-subgroup attacks against pairing equations).
    if not g2_in_subgroup(pt):
        raise ValueError("invalid G2 encoding: point not in r-torsion subgroup")
    return pt


def gt_to_bytes(e) -> bytes:
    return b"".join(c[0].to_bytes(32, "big") + c[1].to_bytes(32, "big") for c in e)


# ------------------------------------------------- native G1 fast path
#
# The reference's host math is gnark-crypto assembly behind IBM mathlib;
# ours is ../native/bn254.c (Montgomery 4x64 Jacobian G1) behind ctypes.
# The pure-Python definitions above remain the correctness anchor (and the
# fallback when no C compiler is present): differential tests compare the
# two (tests/test_native_bn254.py). Opt out with FTS_TPU_NO_NATIVE=1.

g1_mul_py = g1_mul
g1_multiexp_py = g1_multiexp
g1_sum_py = g1_sum
g2_mul_py = g2_mul
g2_multiexp_py = g2_multiexp
g2_sum_py = g2_sum
pairing_py = pairing
pairing_product_py = pairing_product
NATIVE_G1 = False


def _selfcheck_fail(reason: str) -> None:
    from ..utils import metrics as _mx

    _mx.counter("native.selfcheck.fail").inc()
    _mx.REGISTRY.set_meta("native.selfcheck.last_failure", reason)


def _install_native() -> None:
    global g1_mul, g1_multiexp, g1_sum, NATIVE_G1
    global g2_mul, g2_multiexp, g2_sum, pairing, pairing_product
    import os

    if os.environ.get("FTS_TPU_NO_NATIVE"):
        return
    try:
        from ..native import bn254py as _nb

        if not _nb.available():
            return
        # Round-trip self-checks before trusting the build. Every function
        # family the swap-in covers is exercised: a toolchain-specific
        # miscompile confined to the G2 or multi-leg pairing-product path
        # must not be silently adopted (the pytest differential suite does
        # not run at import time).
        if _nb.g1_mul(G1_GEN, 12345) != g1_mul_py(G1_GEN, 12345):
            _selfcheck_fail("g1_mul")  # pragma: no cover
            return  # pragma: no cover
        if _nb.g2_mul(G2_GEN, 98765) != g2_mul_py(G2_GEN, 98765):
            _selfcheck_fail("g2_mul")  # pragma: no cover
            return  # pragma: no cover
        if _nb.pairing(G1_GEN, G2_GEN) != pairing_py(G1_GEN, G2_GEN):
            _selfcheck_fail("pairing")  # pragma: no cover
            return  # pragma: no cover
        # e(P,Q) * e(-P,Q) == 1: exercises the multi-leg Miller product
        # and shared final exponentiation.
        if _nb.pairing_product([(G1_GEN, G2_GEN), (g1_neg(G1_GEN), G2_GEN)]) != FP12_ONE:
            _selfcheck_fail("pairing_product")  # pragma: no cover
            return  # pragma: no cover
        # The batch entry points (`g1_mul_batch` / `g1_multiexp_rows`) are
        # the host validation fast path for Schnorr/WF verification — a
        # miscompile confined to the batch loops (distinct C code from the
        # scalar entry) must fail the swap-in too.
        if _nb.g1_mul_batch([G1_GEN, g1_neg(G1_GEN)], [12345, 54321]) != [
            g1_mul_py(G1_GEN, 12345), g1_mul_py(g1_neg(G1_GEN), 54321)
        ]:
            _selfcheck_fail("g1_mul_batch")  # pragma: no cover
            return  # pragma: no cover
        if _nb.g1_multiexp_rows(
            [[G1_GEN, g1_neg(G1_GEN)], [G1_GEN, G1_GEN]], [[3, 5], [7, 11]]
        ) != [
            g1_multiexp_py([G1_GEN, g1_neg(G1_GEN)], [3, 5]),
            g1_multiexp_py([G1_GEN, G1_GEN], [7, 11]),
        ]:
            _selfcheck_fail("g1_multiexp_rows")  # pragma: no cover
            return  # pragma: no cover
    except Exception as e:  # pragma: no cover
        _selfcheck_fail(f"exception: {e}")
        return

    from ..utils import metrics as _mx

    _mx.counter("native.selfcheck.pass").inc()

    def _g1_sum(points):
        return _nb.g1_sum(list(points))

    def _g2_sum(points):
        return _nb.g2_sum(list(points))

    def _pairing(p, q):
        if p is None or q is None:
            return FP12_ONE  # final_exp(miller_loop) of an infinite pair
        return _nb.pairing(p, q)

    def _pairing_product(pairs):
        return _nb.pairing_product(list(pairs))

    # mul/multiexp bind straight to the ctypes layer (it validates lengths
    # and reduces scalars mod R itself); sum/product wrappers only coerce
    # generators / handle infinity.
    g1_mul = _nb.g1_mul
    g1_multiexp = _nb.g1_multiexp
    g1_sum = _g1_sum
    g2_mul = _nb.g2_mul
    g2_multiexp = _nb.g2_multiexp
    g2_sum = _g2_sum
    pairing = _pairing
    pairing_product = _pairing_product
    NATIVE_G1 = True
    _mx.gauge("native.installed").set(1)


_install_native()


def g1_mul_batch(points, scalars):
    """[k_i P_i] in one native call (falls back to a Python loop)."""
    points, scalars = list(points), list(scalars)
    if len(points) != len(scalars):
        raise ValueError(
            f"mul_batch length mismatch: {len(points)} != {len(scalars)}"
        )
    from ..utils import metrics as _mx

    if NATIVE_G1:
        from ..native import bn254py as _nb

        _mx.counter("hostmath.g1_mul_batch.native").inc()
        return _nb.g1_mul_batch(points, scalars)
    _mx.counter("hostmath.g1_mul_batch.python").inc()
    return [g1_mul_py(p, k) for p, k in zip(points, scalars)]


def g1_multiexp_rows(points_rows, scalar_rows):
    """One multiexp per row; same-width runs collapse into single native
    calls (the C kernel requires rectangular input), pure-Python multiexp
    per row otherwise. Rows may be ragged — grouping happens here so
    callers batch heterogeneous Schnorr statements in one shot."""
    points_rows = [list(r) for r in points_rows]
    scalar_rows = [list(r) for r in scalar_rows]
    if len(points_rows) != len(scalar_rows):
        raise ValueError(
            f"multiexp_rows length mismatch: {len(points_rows)} != {len(scalar_rows)}"
        )
    for pr, sr in zip(points_rows, scalar_rows):
        if len(pr) != len(sr):
            raise ValueError("multiexp_rows: row length mismatch")
    from ..utils import metrics as _mx

    if not NATIVE_G1:
        _mx.counter("hostmath.g1_multiexp_rows.python").inc()
        return [g1_multiexp_py(p, s) for p, s in zip(points_rows, scalar_rows)]
    from ..native import bn254py as _nb

    _mx.counter("hostmath.g1_multiexp_rows.native").inc()
    out = [None] * len(points_rows)
    widths = {}
    for i, pr in enumerate(points_rows):
        widths.setdefault(len(pr), []).append(i)
    for width, idxs in widths.items():
        if width == 0:
            continue  # multiexp over nothing is the identity (None)
        res = _nb.g1_multiexp_rows(
            [points_rows[i] for i in idxs], [scalar_rows[i] for i in idxs]
        )
        for i, pt in zip(idxs, res):
            out[i] = pt
    return out


# ---------------------------------------------------------------- hashing

def hash_to_zr(data: bytes, domain: bytes = b"fts-tpu/zr") -> int:
    """Fiat-Shamir hash to the scalar field (ref: Curve.HashToZr).

    Two-block SHA-256 expansion for negligible modular bias.
    """
    h0 = hashlib.sha256(domain + b"\x00" + data).digest()
    h1 = hashlib.sha256(domain + b"\x01" + data).digest()
    return int.from_bytes(h0 + h1, "big") % R


def hash_to_zr_many(items) -> list:
    """Block-level batch Fiat-Shamir: `hash_to_zr` over many (data, domain)
    pairs with ONE `native.sha256_batch` dispatch (fastser offsets buffer)
    instead of 2N per-proof hashlib round trips.

    Byte-identical to `[hash_to_zr(d, dom) for d, dom in items]` by
    construction — the two-block expansion messages are laid out in
    transcript order and hashed by the same primitive; `sha256_many`
    falls back to hashlib scalar hashing when no native library builds
    (differential-pinned in tests/test_host_batch.py, native on and off).
    """
    items = list(items)
    if not items:
        return []
    msgs = []
    for data, domain in items:
        msgs.append(domain + b"\x00" + data)
        msgs.append(domain + b"\x01" + data)
    from ..native import sha256_many

    digests = sha256_many(msgs, force_native=True)
    return [
        int.from_bytes(digests[2 * i] + digests[2 * i + 1], "big") % R
        for i in range(len(items))
    ]


def hash_to_g1(data: bytes, domain: bytes = b"fts-tpu/g1"):
    """Try-and-increment hash to G1 (cofactor 1, so any curve point works)."""
    ctr = 0
    while True:
        d0 = hashlib.sha256(domain + ctr.to_bytes(4, "big") + b"\x00" + data).digest()
        d1 = hashlib.sha256(domain + ctr.to_bytes(4, "big") + b"\x01" + data).digest()
        x = int.from_bytes(d0 + d1, "big") % P
        y = fp_sqrt((x * x * x + B1) % P)
        if y is not None:
            # normalize sign for determinism
            if y > P - y:
                y = P - y
            return (x, y)
        ctr += 1
