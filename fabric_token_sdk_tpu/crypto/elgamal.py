"""ElGamal encryption over G1 (reference: `crypto/elgamal/enc.go`).

Used for audit info and for PS blind-signing requests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from . import hostmath as hm


@dataclass
class Ciphertext:
    c1: tuple  # G1
    c2: tuple  # G1


@dataclass
class PublicKey:
    gen: tuple  # G1 generator g
    h: tuple  # g^x

    def encrypt(self, m, rng=None) -> Tuple[Ciphertext, int]:
        """Encrypt a G1 point; returns (ciphertext, randomness)."""
        r = hm.rand_zr(rng)
        return Ciphertext(hm.g1_mul(self.gen, r), hm.g1_add(m, hm.g1_mul(self.h, r))), r

    def encrypt_zr(self, m: int, base, rng=None) -> Tuple[Ciphertext, int]:
        """Encrypt a scalar as base^m (exponential ElGamal)."""
        return self.encrypt(hm.g1_mul(base, m), rng)


@dataclass
class SecretKey:
    x: int
    pk: PublicKey

    def decrypt(self, c: Ciphertext):
        return hm.g1_add(c.c2, hm.g1_neg(hm.g1_mul(c.c1, self.x)))


def keygen(gen=None, rng=None) -> SecretKey:
    gen = gen if gen is not None else hm.G1_GEN
    x = hm.rand_zr(rng)
    return SecretKey(x, PublicKey(gen, hm.g1_mul(gen, x)))
