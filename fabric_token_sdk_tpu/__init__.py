"""fabric_token_sdk_tpu — a TPU-native privacy-preserving token framework.

Capability-parity re-design of hyperledger-labs/fabric-token-sdk:
UTXO tokens with plaintext (`fabtoken`) and zero-knowledge (`zkatdlog`)
drivers, token transaction services, and a batched JAX/XLA compute path
for the elliptic-curve / pairing cryptography hot loop.

Layers (see SURVEY.md):
  ops/       TPU limb-tensor bigint, fields, curves, pairing, multiexp
  crypto/    ZK protocol layer (pedersen, schnorr, pssign, range, ...)
  models/    token data model (Token, ID, Quantity, actions, request)
  api/       token management service facade (TMS, wallets, validator)
  drivers/   fabtoken + zkatdlog driver implementations
  services/  ttx, vault, selector, ttxdb, auditor, network, ...
  parallel/  mesh sharding of batched proof generation/verification
  utils/     serialization, hashing, tracing, errors
"""

__version__ = "0.5.0"
