from .sharding import (  # noqa: F401
    MeshConfig,
    make_mesh,
    mesh_dp,
    run_rows_dp,
    shard_rows,
    sharded_pairing_product,
    sharded_schnorr_rows,
)
