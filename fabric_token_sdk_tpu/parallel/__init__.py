from .sharding import (  # noqa: F401
    make_mesh,
    shard_rows,
    sharded_pairing_product,
    sharded_wf_verify_kernel,
)
