"""Mesh sharding of batched proof generation + verification.

Scale-out model (SURVEY §2, TPU-scale subsystems): proof batches are
data-parallel over a `dp` mesh axis; the K legs of pairing products can
additionally shard over an `mp` axis, combined with an `all_gather`
collective before the shared final exponentiation — the ICI-friendly
layout (batch stays put, only 12-coefficient GT values move).

Two complementary mechanisms:

* **Per-shard stage-tile dispatch** (`MeshConfig`, `run_rows_dp`,
  `sharded_schnorr_rows`, the default `sharded_pairing_product` path) —
  the dp axis partitions the FLAT ROW stream of the staged execution
  model (`ops/stages.py`) and the mp axis the pairing-leg tile stream
  (`ops/pairing.py`): each shard walks its contiguous span of canonical
  tile slabs through the SAME compile-once tile executables, so sharding
  adds ZERO new XLA programs. This is the dispatch the PRODUCT planes
  ride: `BlockValidationPipeline` group verification (`crypto/batch.py`)
  and the batched prover (`crypto/batch_prove.py`) both accept a
  `MeshConfig` (or the ambient `FTS_MESH_DEVICES`/`FTS_MESH_MP` /
  `FTS_DP_SHARDS` env). Any sharded-dispatch failure degrades to the
  unsharded runner (`sharding.fallbacks`), which itself degrades to host
  validation — accept/reject can never depend on the mesh.
* **`shard_map` pairing product** (`sharded_pairing_product(fused=True)`)
  — the dp x mp showcase for the one kernel where an in-program
  collective pays: Miller legs shard over mp and all_gather before final
  exp. It fuses miller + product + final-exp into ONE fresh XLA program
  per (mesh, shape), which costs a multi-minute compile on small hosts
  (the historic `dryrun_multichip` rc=124) — so it is opt-in
  (`FTS_SHARDED_PAIRING_FUSED=1`), for real slices where the collective
  is worth a prepaid compile.

Degrade-not-raise: `make_mesh` clamps a non-dividing `mp`
(`sharding.clamped`) and `shard_rows` pads a non-dp-divisible batch
(`sharding.padded_rows`) instead of erroring, so odd block-group sizes
can never knock a node off the sharded path.

The reference scales by adding Fabric endorser processes; here one mesh
spans all chips of a slice via `jax.sharding.Mesh`.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import curve as cv, pairing as pr, stages as st, tower as tw
from ..ops.field import FP
from ..utils import devobs
from ..utils import metrics as mx
from ..utils.tracing import logger


def _clamp_mp(n: int, mp: int, where: str) -> int:
    """Largest divisor of n that is <= mp (>= 1). A non-dividing mp is
    CLAMPED, not rejected — counted so the observatory sees it: the
    aggregate `sharding.clamped` tick (pinned by tests/test_parallel.py),
    a per-site `sharding.clamped.<where>` counter, and a
    `sharding.clamped` flight event carrying the full decision."""
    mp = max(1, mp)
    want = mp
    while n % mp:
        mp -= 1
    if mp != want:
        mx.counter("sharding.clamped").inc()
        mx.counter(f"sharding.clamped.{where.lower()}").inc()
        mx.flight(
            "sharding.clamped", where=where, want=want, got=mp,
            n_devices=n,
        )
        logger.warning(
            "sharding: %s clamped mp %d -> %d (n_devices=%d)",
            where, want, mp, n,
        )
    return mp


@dataclass(frozen=True)
class MeshConfig:
    """Host-side mesh description for the per-shard stage-tile dispatch.

    `n_devices` is the mesh extent (dp * mp); `dp` partitions flat rows,
    `mp` partitions pairing legs. Unlike a `jax.sharding.Mesh` this never
    touches the backend — the dp/mp axes exist purely in the host
    dispatch, so a config larger than the physical device count is legal
    (it measures dispatch-level scaling on an emulated plane)."""

    n_devices: int
    dp: int
    mp: int = 1

    @property
    def workers(self) -> int:
        return self.dp * self.mp

    @classmethod
    def build(cls, n_devices: int, mp: int = 1) -> "MeshConfig":
        """Config over n_devices with mp clamped to a divisor (counted
        under `sharding.clamped` when it had to move)."""
        n = max(1, int(n_devices))
        mp = _clamp_mp(n, int(mp), "MeshConfig")
        return cls(n_devices=n, dp=n // mp, mp=mp)

    @classmethod
    def from_env(cls) -> Optional["MeshConfig"]:
        """The ambient mesh (`FTS_MESH_DEVICES` / `FTS_MESH_MP`), or None
        when no mesh is configured (planes then fall back to
        `FTS_DP_SHARDS` via `stages.default_dp`)."""
        n, mp = st.mesh_env()
        return cls(n_devices=n, dp=n // mp, mp=mp) if n > 0 else None

    @classmethod
    def of(cls, mesh) -> Optional["MeshConfig"]:
        """Coerce a Mesh / MeshConfig / None into a MeshConfig."""
        if mesh is None or isinstance(mesh, cls):
            return mesh
        dp = int(mesh.shape["dp"])
        mp = int(mesh.shape.get("mp", 1))
        return cls(n_devices=dp * mp, dp=dp, mp=mp)


def make_mesh(n_devices: Optional[int] = None, mp: int = 1) -> Mesh:
    """Mesh of shape (dp, mp) over the first n_devices devices. A
    non-dividing `mp` is clamped to the largest divisor
    (`sharding.clamped`) instead of raising."""
    devs = jax.devices()
    n = n_devices or len(devs)
    mp = _clamp_mp(n, mp, "make_mesh")
    arr = np.array(devs[:n]).reshape(n // mp, mp)
    return Mesh(arr, ("dp", "mp"))


def shard_rows(arr, mesh: Mesh):
    """Place an array with its leading (batch) axis split over dp; any
    further sharding (e.g. mp over pairing legs) is imposed by the
    consuming shard_map's in_specs. A batch that does not divide dp is
    PADDED to the next span boundary by repeating row 0
    (`sharding.padded_rows`) — callers slice their result back to the
    original row count."""
    a = np.asarray(arr)
    dp = int(mesh.shape["dp"])
    pad = (-a.shape[0]) % dp
    if pad:
        mx.counter("sharding.padded_rows").inc(pad)
        a = np.concatenate([a, np.broadcast_to(a[:1], (pad,) + a.shape[1:])])
    full = P("dp", *([None] * (a.ndim - 1)))
    return jax.device_put(jnp.asarray(a), NamedSharding(mesh, full))


def _fused_pairing_product(Ps, Qs, mesh: Mesh):
    """prod_k e(P_k, Q_k) per batch row as ONE shard_map program: dp over
    rows, mp over the K pairing legs; Miller values all_gather over mp,
    one shared final exp. Ps: (B, K, 2, L), Qs: (B, K, 2, 2, L) with
    B % dp == 0 and K % mp == 0 (the `sharded_pairing_product` wrapper
    pads/degrades)."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp", "mp"), P("dp", "mp")),
        out_specs=P("dp"),
        check_rep=False,
    )
    def run(ps, qs):
        f = pr.miller_loop(ps, qs)  # (b_local, k_local, 6, 2, L)
        f = jax.lax.all_gather(f, "mp", axis=2, tiled=False)
        # f: (b_local, k_local, mp, 6, 2, L) -> combine all K legs locally
        k_total = f.shape[1] * f.shape[2]
        f = f.reshape(f.shape[0], k_total, 6, 2, f.shape[-1])
        while f.shape[1] > 1:
            half = f.shape[1] // 2
            rest = f[:, 2 * half :]
            f = tw.fp12_mul(f[:, :half], f[:, half : 2 * half])
            if rest.shape[1]:
                f = jnp.concatenate([f, rest], axis=1)
        return pr.final_exp(f[:, 0])

    return run(Ps, Qs)


def sharded_pairing_product(Ps, Qs, mesh, fused: Optional[bool] = None):
    """prod_k e(P_k, Q_k) per batch row, dp over rows and mp over the K
    pairing legs. Returns (B, 6, 2, L) GT as host numpy.

    Default path: the STAGED dispatch — `pairing_product_staged` with
    dp x mp worker spans over the compile-once miller/product/final-exp
    tiles (zero new XLA programs; the product planes' path). With
    `fused=True` (or `FTS_SHARDED_PAIRING_FUSED=1`) the in-program
    `shard_map` + `all_gather` collective runs instead — one fresh XLA
    compile per (mesh, shape); rows are padded to a dp boundary and a
    K not divisible by mp degrades to the staged path
    (`sharding.fallbacks`).
    """
    cfg = MeshConfig.of(mesh)
    Ps = np.asarray(Ps)
    Qs = np.asarray(Qs)
    if cfg is None:  # no mesh: staged dispatch with the ambient env dp/mp
        return pr.pairing_product_staged(Ps, Qs)
    if fused is None:
        fused = os.environ.get("FTS_SHARDED_PAIRING_FUSED", "0") == "1"
    if fused and isinstance(mesh, Mesh):
        B, K = Ps.shape[0], Ps.shape[1]
        if K % cfg.mp:
            mx.counter("sharding.fallbacks").inc()
            mx.flight(
                "sharding.fallback", what="fused_pairing",
                workers=cfg.workers, reason="k_not_divisible",
                k=K, mp=cfg.mp,
            )
            devobs.note_degrade(
                "k_not_divisible", program="fused_pairing"
            )
            logger.warning(
                "sharding: fused pairing product needs K %% mp == 0 "
                "(K=%d, mp=%d); degrading to the staged dispatch", K, cfg.mp,
            )
        else:
            # the dp-boundary padding shard_rows is about to add is the
            # fused program's occupancy story — record it on the ledger
            pad = (-B) % cfg.dp
            with devobs.dispatch(
                "fused_pairing", rows=B * K, padded_rows=pad * K,
                dp=cfg.dp, mp=cfg.mp,
            ):
                gt = _fused_pairing_product(
                    shard_rows(Ps, mesh), shard_rows(Qs, mesh), mesh
                )
            return np.asarray(gt)[:B]
    return pr.pairing_product_staged(Ps, Qs, dp=cfg.dp, mp=cfg.mp)


def mesh_dp(mesh) -> Optional[int]:
    """The dp extent of a Mesh or MeshConfig (None mesh -> ambient
    FTS_DP_SHARDS / FTS_MESH_* env)."""
    cfg = MeshConfig.of(mesh)
    return None if cfg is None else cfg.dp


def run_rows_dp(kernel, *arrays, mesh=None, dp: Optional[int] = None,
                consts=()):
    """Per-shard stage-tile dispatch: partition the flat rows into dp
    contiguous ROW_TILE-aligned spans and run each span through the
    canonical compile-once tile executable (`stages.run_rows`). Results
    are bit-identical to the unsharded runner and NO new XLA program is
    compiled — the dp axis exists purely in the host-side dispatch."""
    return st.run_rows(
        kernel, *arrays, consts=consts,
        dp=dp if dp is not None else mesh_dp(mesh),
    )


def sharded_schnorr_rows(table: cv.FixedBaseTable, resp, stmts, chals,
                         mesh=None):
    """Batch-parallel Schnorr commitment reconstruction over dp, as
    per-shard stage-tile dispatch: com = table^resp - stmt^chal.

    The flat-row composition is EXACTLY the one `BatchedWFVerifier`
    runs (msm tile, variable-base mul tile, sub tile) — dp only
    partitions the row stream. resp: (N, nbases, L), stmts: (N, 3, L),
    chals: (N, L) canonical limbs (host numpy); returns (N, 3, L)
    Jacobian numpy."""
    dp = mesh_dp(mesh)
    fixed = run_rows_dp(st._g1_msm_tile, np.asarray(resp), dp=dp,
                        consts=(table.flat,))
    sc = run_rows_dp(cv.scalar_mul, np.asarray(stmts), np.asarray(chals),
                     dp=dp)
    return run_rows_dp(st._g1_sub_tile, fixed, sc, dp=dp)
