"""Mesh sharding of batched proof verification.

Scale-out model (SURVEY §2, TPU-scale subsystems): proof batches are
data-parallel over a `dp` mesh axis; the K legs of pairing products can
additionally shard over an `mp` axis, combined with an `all_gather`
collective before the shared final exponentiation — the ICI-friendly
layout (batch stays put, only 12-coefficient GT values move).

The reference scales by adding Fabric endorser processes; here one program
spans all chips of a slice via `jax.sharding.Mesh` + `shard_map`.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import curve as cv, pairing as pr, tower as tw
from ..ops.field import FP


def make_mesh(n_devices: Optional[int] = None, mp: int = 1) -> Mesh:
    """Mesh of shape (dp, mp) over the first n_devices devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if n % mp:
        raise ValueError("mesh: n_devices must be divisible by mp")
    arr = np.array(devs[:n]).reshape(n // mp, mp)
    return Mesh(arr, ("dp", "mp"))


def shard_rows(arr, mesh: Mesh):
    """Place an array with its leading (batch) axis split over dp; any
    further sharding (e.g. mp over pairing legs) is imposed by the
    consuming shard_map's in_specs."""
    ndim = np.asarray(arr).ndim
    full = P("dp", *([None] * (ndim - 1)))
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, full))


def sharded_pairing_product(Ps, Qs, mesh: Mesh):
    """prod_k e(P_k, Q_k) per batch row, dp over rows and mp over the K
    pairing legs; Miller values all_gather over mp, one final exp.

    Ps: (B, K, 2, L), Qs: (B, K, 2, 2, L); B % dp == 0, K % mp == 0.
    """

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp", "mp"), P("dp", "mp")),
        out_specs=P("dp"),
        check_rep=False,
    )
    def run(ps, qs):
        f = pr.miller_loop(ps, qs)  # (b_local, k_local, 6, 2, L)
        f = jax.lax.all_gather(f, "mp", axis=2, tiled=False)
        # f: (b_local, k_local, mp, 6, 2, L) -> combine all K legs locally
        k_total = f.shape[1] * f.shape[2]
        f = f.reshape(f.shape[0], k_total, 6, 2, f.shape[-1])
        while f.shape[1] > 1:
            half = f.shape[1] // 2
            rest = f[:, 2 * half :]
            f = tw.fp12_mul(f[:, :half], f[:, half : 2 * half])
            if rest.shape[1]:
                f = jnp.concatenate([f, rest], axis=1)
        return pr.final_exp(f[:, 0])

    return run(Ps, Qs)


def sharded_wf_verify_kernel(table: cv.FixedBaseTable, resp, stmts, chals,
                             mesh: Mesh):
    """Batch-parallel Schnorr commitment reconstruction over dp."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=P("dp"),
        check_rep=False,
    )
    def run(r, s, c):
        fixed = table.msm(r)
        sc = cv.scalar_mul(s, c[:, None, :])
        return cv.add(fixed, cv.neg(sc))

    return run(resp, stmts, chals)
