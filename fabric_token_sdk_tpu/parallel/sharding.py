"""Mesh sharding of batched proof generation + verification.

Scale-out model (SURVEY §2, TPU-scale subsystems): proof batches are
data-parallel over a `dp` mesh axis; the K legs of pairing products can
additionally shard over an `mp` axis, combined with an `all_gather`
collective before the shared final exponentiation — the ICI-friendly
layout (batch stays put, only 12-coefficient GT values move).

Two complementary mechanisms:

* **Per-shard stage-tile dispatch** (`run_rows_dp`,
  `sharded_schnorr_rows`) — the dp axis partitions the FLAT ROW stream
  of the staged execution model (`ops/stages.py`): each shard walks its
  contiguous span of canonical ROW_TILE slabs through the SAME
  compile-once tile executables, so sharding adds ZERO new XLA programs.
  This is the dispatch used by both the batched verify plane
  (`crypto/batch.py`) and the batched prover (`crypto/batch_prove.py`)
  via `stages.run_rows(dp=...)` / `FTS_DP_SHARDS`. (The pre-stage-tile
  `sharded_wf_verify_kernel`, which shard_map'ed a fused per-shape
  reconstruction kernel — the exact program-explosion the stage tiles
  removed — is deleted.)
* **`shard_map` pairing product** (`sharded_pairing_product`) — the
  dp x mp showcase for the one kernel where an in-program collective
  pays: Miller legs shard over mp and all_gather before final exp.

The reference scales by adding Fabric endorser processes; here one mesh
spans all chips of a slice via `jax.sharding.Mesh`.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import curve as cv, pairing as pr, stages as st, tower as tw
from ..ops.field import FP


def make_mesh(n_devices: Optional[int] = None, mp: int = 1) -> Mesh:
    """Mesh of shape (dp, mp) over the first n_devices devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if n % mp:
        raise ValueError("mesh: n_devices must be divisible by mp")
    arr = np.array(devs[:n]).reshape(n // mp, mp)
    return Mesh(arr, ("dp", "mp"))


def shard_rows(arr, mesh: Mesh):
    """Place an array with its leading (batch) axis split over dp; any
    further sharding (e.g. mp over pairing legs) is imposed by the
    consuming shard_map's in_specs."""
    ndim = np.asarray(arr).ndim
    full = P("dp", *([None] * (ndim - 1)))
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, full))


def sharded_pairing_product(Ps, Qs, mesh: Mesh):
    """prod_k e(P_k, Q_k) per batch row, dp over rows and mp over the K
    pairing legs; Miller values all_gather over mp, one final exp.

    Ps: (B, K, 2, L), Qs: (B, K, 2, 2, L); B % dp == 0, K % mp == 0.
    """

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp", "mp"), P("dp", "mp")),
        out_specs=P("dp"),
        check_rep=False,
    )
    def run(ps, qs):
        f = pr.miller_loop(ps, qs)  # (b_local, k_local, 6, 2, L)
        f = jax.lax.all_gather(f, "mp", axis=2, tiled=False)
        # f: (b_local, k_local, mp, 6, 2, L) -> combine all K legs locally
        k_total = f.shape[1] * f.shape[2]
        f = f.reshape(f.shape[0], k_total, 6, 2, f.shape[-1])
        while f.shape[1] > 1:
            half = f.shape[1] // 2
            rest = f[:, 2 * half :]
            f = tw.fp12_mul(f[:, :half], f[:, half : 2 * half])
            if rest.shape[1]:
                f = jnp.concatenate([f, rest], axis=1)
        return pr.final_exp(f[:, 0])

    return run(Ps, Qs)


def mesh_dp(mesh: Optional[Mesh]) -> Optional[int]:
    """The dp extent of a mesh (None mesh -> ambient FTS_DP_SHARDS)."""
    return None if mesh is None else int(mesh.shape["dp"])


def run_rows_dp(kernel, *arrays, mesh: Optional[Mesh] = None,
                dp: Optional[int] = None, consts=()):
    """Per-shard stage-tile dispatch: partition the flat rows into dp
    contiguous ROW_TILE-aligned spans and run each span through the
    canonical compile-once tile executable (`stages.run_rows`). Results
    are bit-identical to the unsharded runner and NO new XLA program is
    compiled — the dp axis exists purely in the host-side dispatch."""
    return st.run_rows(
        kernel, *arrays, consts=consts,
        dp=dp if dp is not None else mesh_dp(mesh),
    )


def sharded_schnorr_rows(table: cv.FixedBaseTable, resp, stmts, chals,
                         mesh: Optional[Mesh] = None):
    """Batch-parallel Schnorr commitment reconstruction over dp, as
    per-shard stage-tile dispatch: com = table^resp - stmt^chal.

    The flat-row composition is EXACTLY the one `BatchedWFVerifier`
    runs (msm tile, variable-base mul tile, sub tile) — dp only
    partitions the row stream. resp: (N, nbases, L), stmts: (N, 3, L),
    chals: (N, L) canonical limbs (host numpy); returns (N, 3, L)
    Jacobian numpy."""
    dp = mesh_dp(mesh)
    fixed = run_rows_dp(st._g1_msm_tile, np.asarray(resp), dp=dp,
                        consts=(table.flat,))
    sc = run_rows_dp(cv.scalar_mul, np.asarray(stmts), np.asarray(chals),
                     dp=dp)
    return run_rows_dp(st._g1_sub_tile, fixed, sc, dp=dp)
