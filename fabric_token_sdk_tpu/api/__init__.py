"""Token API layer: driver interfaces, token request, management service.

Reference: `token/driver/*.go` (driver SPI) and `token/*.go` (TMS facade,
Request, wallets).
"""

from .driver import Driver, ValidationError  # noqa: F401
from .request import TokenRequest, RequestMetadata  # noqa: F401
from .tms import ManagementService  # noqa: F401
