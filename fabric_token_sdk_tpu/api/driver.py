"""Driver SPI — what a token driver must implement.

Reference: `token/driver/driver.go`, `issue.go`, `transfer.go`,
`validator.go`, `wallet.go`. A driver owns the privacy model: how tokens
are represented on the ledger, how actions are proven and validated, and
how identities sign.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..models.token import ID, Token, UnspentToken


class ValidationError(Exception):
    """A token request failed validation."""


def vguard(fn):
    """Decorator for driver validate entry points: structural errors from
    attacker-supplied action bytes become ValidationError, never KeyError/
    TypeError/ValueError leaks (cf. crypto.serialization.guard)."""

    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except ValidationError:
            raise
        except (MemoryError, OSError):
            # transient environment faults are NOT validation verdicts:
            # they must reach the ledger's transient path (attempt fails,
            # nothing durable recorded, resubmission can succeed)
            raise
        except Exception as e:
            raise ValidationError(
                f"malformed action: {type(e).__name__}: {e}"
            ) from e

    wrapped.__name__ = fn.__name__
    wrapped.__doc__ = fn.__doc__
    return wrapped


@dataclass
class IssueOutcome:
    """Result of assembling an issue action."""

    action_bytes: bytes
    outputs: List[bytes]  # serialized on-ledger outputs
    metadata: List[bytes]  # per-output opening metadata (off-chain)


@dataclass
class TransferOutcome:
    action_bytes: bytes
    outputs: List[bytes]
    metadata: List[bytes]


class Driver(abc.ABC):
    """A token driver (privacy model + crypto backend)."""

    name: str = ""
    supports_anonymous_issue: bool = False

    # ------------------------------------------------------------ params

    @abc.abstractmethod
    def public_params(self):
        ...

    @abc.abstractmethod
    def precision(self) -> int:
        ...

    # ------------------------------------------------------------ actions

    @abc.abstractmethod
    def issue(self, issuer_identity: bytes, token_type: str, values: Sequence[int],
              owners: Sequence[bytes], anonymous: bool = True) -> IssueOutcome:
        ...

    @abc.abstractmethod
    def transfer(self, input_ids: Sequence[ID], input_tokens: Sequence[bytes],
                 input_metadata: Sequence[bytes], token_type: str,
                 values: Sequence[int], owners: Sequence[bytes]) -> TransferOutcome:
        ...

    # ------------------------------------------------------------ validate

    @abc.abstractmethod
    def validate_issue(self, action_bytes: bytes) -> Tuple[List[bytes], bytes]:
        """Validate an issue action; returns (serialized outputs to write,
        issuer identity whose signature the request must carry — empty for
        anonymous issuance where the proof itself authorizes)."""

    @abc.abstractmethod
    def validate_transfer(self, action_bytes: bytes,
                          resolve_input,  # Callable[[ID], bytes]
                          signed_payload: bytes,
                          signatures: Sequence[bytes],
                          now: Optional[float] = None,
                          proof_verified: Optional[bool] = None,
                          sig_verified: Optional[Dict[int, tuple]] = None,
                          ) -> Tuple[List[ID], List[bytes]]:
        """Validate a transfer action; returns (spent ids, outputs to write).
        `now` is the deterministic commit timestamp (script deadlines etc.
        must not depend on validator wall clocks). `proof_verified` is the
        block-batched plane's verdict on the action's ZK proof — True:
        skip the host proof check, False: reject, None: verify on host.
        Drivers without ZK proofs ignore it (their `transfer_batch_plan`
        never emits a plan, so it is always None for them).

        `sig_verified` carries the batched SIGNATURE plane's verdicts:
        `{signature_index: (identity_bytes, bool)}`. A verdict applies
        ONLY when `identity_bytes` equals the owner identity the host
        check would verify against (defense in depth — the verdict was
        computed over the ACTION-claimed owner, which the inputs==ledger
        pin makes equal); True skips the host signature check, False
        rejects, a missing/mismatched entry host-verifies. The validator
        passes the kwarg only when it HAS verdicts, and verdicts only
        exist for drivers whose own `transfer_sign_plan` emitted owners
        — so accepting `sig_verified` is part of the same SPI opt-in
        (drivers without the sign-plan hooks are never called with it,
        and a `vguard`-decorated validate_transfer would convert a
        binding TypeError into a spurious rejection, so implement both
        or neither)."""

    # ------------------------------------------------------------ batching

    def transfer_batch_plan(self, action_bytes: bytes):
        """Optional hook for the block-batched validation plane: return
        `(shape_key, row)` where all rows sharing `shape_key` can be
        verified together in ONE `batch_verifier().verify(rows)` call, or
        None to route this action through the host path (default)."""
        return None

    def batch_verifier(self, mesh=None):
        """The driver's block-batched transfer-proof verifier (an object
        with `verify(rows) -> bool array`), or None when the driver has
        no batched plane (default). `mesh` is an optional
        `parallel.sharding.MeshConfig` the verifier's dispatch should
        shard over (dp x mp); drivers without a device plane ignore it."""
        return None

    def batch_prover(self, mesh=None):
        """The driver's batched transfer-proof GENERATOR (the prove-side
        twin of `batch_verifier`), or None when the driver proves on the
        host only (default). `mesh` as in `batch_verifier`."""
        return None

    def transfer_sign_plan(self, action_bytes: bytes):
        """Optional hook for the block-batched SIGNATURE plane: the
        owner identity blobs a transfer action's signatures must verify
        against, one per required signature, in signature order — the
        ACTION-claimed owners (`validate_transfer` separately pins them
        to ledger state, so a verdict computed over them is exactly the
        host check). Return None (default) to route every signature of
        this action through the host path (malformed bytes, drivers
        whose owners are not identity blobs)."""
        return None

    def issue_sign_plan(self, action_bytes: bytes):
        """Signature-plane hook for issue actions: the issuer identity
        whose signature the request must carry (the same identity
        `validate_issue` returns after its authorization checks — the
        two MUST agree or the verdict is discarded by the identity
        match), or None when the issue needs no signature (anonymous
        issuance) or the action cannot be planned (default)."""
        return None

    def transfer_many(self, transfers: Sequence[tuple], rng=None,
                      min_batch=None):
        """Batch-prove SPI: build many transfer actions at once.
        `transfers` holds tuples of `transfer()`'s positional arguments;
        outcomes come back in request order. Default: sequential
        `transfer()` calls — the abstract `transfer()` takes no rng, so
        `rng`/`min_batch` are ignored here; drivers that thread
        randomness or batch proof generation override this (zkatdlog
        routes same-shape groups of >= min_batch through
        `TransferProver.batch`)."""
        return [self.transfer(*spec) for spec in transfers]

    # ------------------------------------------------------------ tokens

    @abc.abstractmethod
    def output_to_unspent(self, token_id: ID, output_bytes: bytes,
                          metadata_bytes: Optional[bytes]) -> UnspentToken:
        """Interpret a ledger output (+optional metadata) as a clear token."""

    @abc.abstractmethod
    def output_owner(self, output_bytes: bytes) -> bytes:
        ...

    # ------------------------------------------------------------ identity

    @abc.abstractmethod
    def verify_owner_signature(self, owner_identity: bytes, message: bytes,
                               signature: bytes) -> None:
        ...
