"""Driver SPI — what a token driver must implement.

Reference: `token/driver/driver.go`, `issue.go`, `transfer.go`,
`validator.go`, `wallet.go`. A driver owns the privacy model: how tokens
are represented on the ledger, how actions are proven and validated, and
how identities sign.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..models.token import ID, Token, UnspentToken


class ValidationError(Exception):
    """A token request failed validation."""


def vguard(fn):
    """Decorator for driver validate entry points: structural errors from
    attacker-supplied action bytes become ValidationError, never KeyError/
    TypeError/ValueError leaks (cf. crypto.serialization.guard)."""

    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except ValidationError:
            raise
        except Exception as e:
            raise ValidationError(
                f"malformed action: {type(e).__name__}: {e}"
            ) from e

    wrapped.__name__ = fn.__name__
    wrapped.__doc__ = fn.__doc__
    return wrapped


@dataclass
class IssueOutcome:
    """Result of assembling an issue action."""

    action_bytes: bytes
    outputs: List[bytes]  # serialized on-ledger outputs
    metadata: List[bytes]  # per-output opening metadata (off-chain)


@dataclass
class TransferOutcome:
    action_bytes: bytes
    outputs: List[bytes]
    metadata: List[bytes]


class Driver(abc.ABC):
    """A token driver (privacy model + crypto backend)."""

    name: str = ""
    supports_anonymous_issue: bool = False

    # ------------------------------------------------------------ params

    @abc.abstractmethod
    def public_params(self):
        ...

    @abc.abstractmethod
    def precision(self) -> int:
        ...

    # ------------------------------------------------------------ actions

    @abc.abstractmethod
    def issue(self, issuer_identity: bytes, token_type: str, values: Sequence[int],
              owners: Sequence[bytes], anonymous: bool = True) -> IssueOutcome:
        ...

    @abc.abstractmethod
    def transfer(self, input_ids: Sequence[ID], input_tokens: Sequence[bytes],
                 input_metadata: Sequence[bytes], token_type: str,
                 values: Sequence[int], owners: Sequence[bytes]) -> TransferOutcome:
        ...

    # ------------------------------------------------------------ validate

    @abc.abstractmethod
    def validate_issue(self, action_bytes: bytes) -> Tuple[List[bytes], bytes]:
        """Validate an issue action; returns (serialized outputs to write,
        issuer identity whose signature the request must carry — empty for
        anonymous issuance where the proof itself authorizes)."""

    @abc.abstractmethod
    def validate_transfer(self, action_bytes: bytes,
                          resolve_input,  # Callable[[ID], bytes]
                          signed_payload: bytes,
                          signatures: Sequence[bytes],
                          now: Optional[float] = None) -> Tuple[List[ID], List[bytes]]:
        """Validate a transfer action; returns (spent ids, outputs to write).
        `now` is the deterministic commit timestamp (script deadlines etc.
        must not depend on validator wall clocks)."""

    # ------------------------------------------------------------ tokens

    @abc.abstractmethod
    def output_to_unspent(self, token_id: ID, output_bytes: bytes,
                          metadata_bytes: Optional[bytes]) -> UnspentToken:
        """Interpret a ledger output (+optional metadata) as a clear token."""

    @abc.abstractmethod
    def output_owner(self, output_bytes: bytes) -> bytes:
        ...

    # ------------------------------------------------------------ identity

    @abc.abstractmethod
    def verify_owner_signature(self, owner_identity: bytes, message: bytes,
                               signature: bytes) -> None:
        ...
