"""Wallets: long-term keys, pseudonym derivation, signing.

Reference: `token/wallet.go` + `token/core/zkatdlog/nogh/wallet.go`.
Owner wallets hand out recipient identities (fresh pseudonyms for
zkatdlog, long-term keys for fabtoken) and sign transfer requests for the
identities they control.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..crypto import hostmath as hm, nym as nym_mod, sign
from ..drivers import identity


@dataclass
class IssuerWallet:
    wallet_id: str
    key: sign.SigningKey

    @property
    def identity(self) -> bytes:
        return identity.pk_identity(self.key.public)

    def sign(self, message: bytes, rng=None) -> bytes:
        return self.key.sign(message, rng)


AuditorWallet = IssuerWallet  # same shape: long-term signing identity


class OwnerWallet:
    """Owner wallet: controls long-term secret + derived pseudonyms."""

    def __init__(self, wallet_id: str, anonymous: bool, nym_params=None, rng=None):
        self.wallet_id = wallet_id
        self.anonymous = anonymous
        self.rng = rng
        self.nym_params = list(nym_params) if nym_params else None
        self.key = sign.keygen(rng)
        self._nyms: Dict[bytes, nym_mod.NymSigner] = {}

    def recipient_identity(self) -> bytes:
        """Fresh identity for receiving tokens."""
        if not self.anonymous:
            return identity.pk_identity(self.key.public)
        if not self.nym_params:
            raise ValueError("anonymous wallet requires nym parameters")
        ny, bf = nym_mod.new_nym(self.key.sk, self.nym_params, self.rng)
        ident = identity.nym_identity(ny)
        self._nyms[ident] = nym_mod.NymSigner(self.key.sk, bf, ny, self.nym_params)
        return ident

    def owns(self, ident: bytes) -> bool:
        if ident in self._nyms:
            return True
        try:
            d = identity.parse(ident)
        except ValueError:
            return False
        return d["t"] == "pk" and d["pk"] == self.key.public.to_bytes()

    def sign(self, ident: bytes, message: bytes) -> bytes:
        """Sign on behalf of one of this wallet's identities."""
        if ident in self._nyms:
            return self._nyms[ident].sign(message, self.rng)
        if self.owns(ident):
            return self.key.sign(message, self.rng)
        raise ValueError(f"wallet [{self.wallet_id}] does not own this identity")


@dataclass
class WalletRegistry:
    """All wallets a node controls (reference WalletManager)."""

    owners: Dict[str, OwnerWallet] = field(default_factory=dict)
    issuers: Dict[str, IssuerWallet] = field(default_factory=dict)
    auditors: Dict[str, AuditorWallet] = field(default_factory=dict)

    def owner_wallet(self, wid: str) -> OwnerWallet:
        return self.owners[wid]

    def issuer_wallet(self, wid: str) -> IssuerWallet:
        return self.issuers[wid]

    def auditor_wallet(self, wid: str) -> AuditorWallet:
        return self.auditors[wid]

    def wallet_owning(self, ident: bytes) -> Optional[OwnerWallet]:
        for w in self.owners.values():
            if w.owns(ident):
                return w
        return None
