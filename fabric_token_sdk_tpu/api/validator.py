"""Request-level validator: actions + signatures + audit binding.

Reference: `token/validator.go` + driver validators
(`fabtoken/validator.go`, `zkatdlog/crypto/validator/validator.go`).
Endorsers/committers run this against current ledger state.

Deferred-signature mode: the block pipeline
(`services/network/orderer.py:BlockValidationPipeline.sign_verdicts`)
collects every `pk`-kind signature obligation of a block — auditor,
issuer, transfer owners — verifies them in ONE
`BatchedSchnorrVerifier` pass over the stage tiles, and hands the
verdicts back through `validate(sig_verified=...)`. Each verdict is
`(identity_bytes, bool)` keyed by obligation — it applies ONLY when the
recorded identity equals the one the host check would verify against
(statement pinning), True skips the host check, False rejects, and a
missing/mismatched verdict host-verifies — so accept/reject can never
depend on the batched plane, only get faster.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .driver import Driver, ValidationError
from .request import TokenRequest
from ..drivers import identity
from ..models.token import ID


@dataclass
class ValidationResult:
    spent: List[ID] = field(default_factory=list)
    # outputs in action order; each entry (action_kind, outputs)
    outputs: List[Tuple[str, List[bytes]]] = field(default_factory=list)


# obligation keys of the batched signature plane, shared with
# BlockValidationPipeline.sign_verdicts:
#   ("auditor", 0)                 — the request-level auditor signature
#   ("issue", record_index)        — one issuer signature per issue record
#   ("transfer", record_index, si) — one owner signature per transfer input
SIG_AUDITOR = ("auditor", 0)


class RequestValidator:
    def __init__(self, driver: Driver, auditor_identity: bytes = b""):
        self.driver = driver
        self.auditor = auditor_identity

    def validate(self, request: TokenRequest, resolve_input: Callable[[ID], bytes],
                 now=None,
                 transfer_proofs: Optional[Dict[int, bool]] = None,
                 sig_verified: Optional[Dict[tuple, tuple]] = None,
                 conservation: Optional[Dict[int, bool]] = None) -> ValidationResult:
        """`now`: deterministic commit timestamp for time-locked scripts.

        `transfer_proofs`: verdicts from the block-batched proof plane,
        keyed by transfer-record index — True means the action's ZK proof
        was already verified on the device (the driver skips its host
        proof check), False means it was already REJECTED. Records with
        no verdict verify on host. Everything else (ledger-input
        matching, conservation) always runs here.

        `sig_verified`: verdicts from the block-batched SIGNATURE plane,
        `{obligation_key: (identity_bytes, bool)}` (see the module
        docstring). Only `pk`-kind obligations ever get verdicts;
        nym/htlc identities always host-verify.

        `conservation`: True-only verdicts from the block-level
        vectorized conservation pass, keyed by transfer-record index —
        True means the driver's `validate_conservation_many` hook already
        proved the action's type/value checks over the very bytes the
        input_match leg pins to ledger state, so the driver skips its
        per-tx conservation arithmetic. Records without a verdict (and
        every failure) run the full scalar checks.
        """
        result = ValidationResult()
        payload = request.marshal_to_sign()
        sv = sig_verified or {}

        def _verdict(okey, ident) -> Optional[bool]:
            """Tri-state: True skip host check, False reject, None host."""
            v = sv.get(okey)
            if v is None or not ident or v[0] != ident:
                return None  # no verdict / statement mismatch -> host
            return bool(v[1])

        if self.auditor:
            if not request.auditor_signature:
                raise ValidationError("request is missing the auditor signature")
            ok = _verdict(SIG_AUDITOR, self.auditor)
            if ok is False:
                raise ValidationError(
                    "invalid auditor signature: rejected by the batched "
                    "signature plane"
                )
            if ok is None:
                try:
                    identity.verify_signature(
                        self.auditor, request.marshal_to_audit(),
                        request.auditor_signature,
                    )
                except ValueError as e:
                    raise ValidationError(f"invalid auditor signature: {e}") from e

        for ii, rec in enumerate(request.issues):
            # the driver returns the issuer identity the ACTION names (after
            # authorization checks); the record-level field is untrusted.
            outputs, action_issuer = self.driver.validate_issue(rec.action)
            if action_issuer:
                if not rec.signature:
                    raise ValidationError("issue is missing the issuer signature")
                ok = _verdict(("issue", ii), action_issuer)
                if ok is False:
                    raise ValidationError(
                        "invalid issuer signature: rejected by the batched "
                        "signature plane"
                    )
                if ok is None:
                    try:
                        identity.verify_signature(action_issuer, payload, rec.signature)
                    except ValueError as e:
                        raise ValidationError(f"invalid issuer signature: {e}") from e
            result.outputs.append(("issue", outputs))

        for idx, rec in enumerate(request.transfers):
            rec_sigs = {
                okey[2]: v for okey, v in sv.items()
                if okey[0] == "transfer" and okey[1] == idx
            }
            kwargs = dict(
                now=now,
                proof_verified=None if transfer_proofs is None
                else transfer_proofs.get(idx),
            )
            if rec_sigs:
                # `sig_verified` is passed ONLY when there are verdicts —
                # and verdicts only exist for drivers whose OWN
                # `transfer_sign_plan` hook emitted owners, so accepting
                # the kwarg is part of the same SPI opt-in (a driver
                # without the hooks is never called with it; a vguard-
                # decorated driver would mask a binding TypeError as
                # ValidationError, so there is no post-hoc fallback)
                kwargs["sig_verified"] = rec_sigs
            cv = conservation.get(idx) if conservation else None
            if cv is True:
                # same SPI opt-in as sig_verified: a verdict only exists
                # when THIS driver's validate_conservation_many hook
                # emitted it, so the kwarg is only bound for drivers that
                # declared it (True-only — failures carry no verdict)
                kwargs["conservation_verified"] = True
            spent, outputs = self.driver.validate_transfer(
                rec.action, resolve_input, payload, rec.signatures, **kwargs
            )
            if spent != rec.input_ids:
                raise ValidationError("transfer record ids do not match action")
            result.spent.extend(spent)
            result.outputs.append(("transfer", outputs))

        if not request.issues and not request.transfers:
            raise ValidationError("empty token request")
        # no double spend within one request
        if len(set(result.spent)) != len(result.spent):
            raise ValidationError("request spends the same token twice")
        return result
