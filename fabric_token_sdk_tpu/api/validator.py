"""Request-level validator: actions + signatures + audit binding.

Reference: `token/validator.go` + driver validators
(`fabtoken/validator.go`, `zkatdlog/crypto/validator/validator.go`).
Endorsers/committers run this against current ledger state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .driver import Driver, ValidationError
from .request import TokenRequest
from ..drivers import identity
from ..models.token import ID


@dataclass
class ValidationResult:
    spent: List[ID] = field(default_factory=list)
    # outputs in action order; each entry (action_kind, outputs)
    outputs: List[Tuple[str, List[bytes]]] = field(default_factory=list)


class RequestValidator:
    def __init__(self, driver: Driver, auditor_identity: bytes = b""):
        self.driver = driver
        self.auditor = auditor_identity

    def validate(self, request: TokenRequest, resolve_input: Callable[[ID], bytes],
                 now=None,
                 transfer_proofs: Optional[Dict[int, bool]] = None) -> ValidationResult:
        """`now`: deterministic commit timestamp for time-locked scripts.

        `transfer_proofs`: verdicts from the block-batched proof plane,
        keyed by transfer-record index — True means the action's ZK proof
        was already verified on the device (the driver skips its host
        proof check), False means it was already REJECTED. Records with
        no verdict verify on host. Everything else (ledger-input
        matching, ownership signatures, conservation) always runs here.
        """
        result = ValidationResult()
        payload = request.marshal_to_sign()

        if self.auditor:
            if not request.auditor_signature:
                raise ValidationError("request is missing the auditor signature")
            try:
                identity.verify_signature(
                    self.auditor, request.marshal_to_audit(), request.auditor_signature
                )
            except ValueError as e:
                raise ValidationError(f"invalid auditor signature: {e}") from e

        for rec in request.issues:
            # the driver returns the issuer identity the ACTION names (after
            # authorization checks); the record-level field is untrusted.
            outputs, action_issuer = self.driver.validate_issue(rec.action)
            if action_issuer:
                if not rec.signature:
                    raise ValidationError("issue is missing the issuer signature")
                try:
                    identity.verify_signature(action_issuer, payload, rec.signature)
                except ValueError as e:
                    raise ValidationError(f"invalid issuer signature: {e}") from e
            result.outputs.append(("issue", outputs))

        for idx, rec in enumerate(request.transfers):
            spent, outputs = self.driver.validate_transfer(
                rec.action, resolve_input, payload, rec.signatures, now=now,
                proof_verified=None if transfer_proofs is None
                else transfer_proofs.get(idx),
            )
            if spent != rec.input_ids:
                raise ValidationError("transfer record ids do not match action")
            result.spent.extend(spent)
            result.outputs.append(("transfer", outputs))

        if not request.issues and not request.transfers:
            raise ValidationError("empty token request")
        # no double spend within one request
        if len(set(result.spent)) != len(result.spent):
            raise ValidationError("request spends the same token twice")
        return result
