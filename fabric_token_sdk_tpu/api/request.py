"""TokenRequest: the unit a transaction carries to the ledger.

Reference: `token/request.go` — a request aggregates issue/transfer
actions, collects owner/issuer/auditor signatures, and carries per-output
metadata off-chain (`token/metadata.go`).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..crypto.serialization import dumps, loads
from ..models.token import ID
from ..utils import metrics as mx
from ..utils import profiler


@dataclass
class IssueRecord:
    action: bytes
    issuer: bytes
    outputs_metadata: List[bytes] = field(default_factory=list)
    receivers: List[bytes] = field(default_factory=list)
    signature: bytes = b""


@dataclass
class TransferRecord:
    action: bytes
    input_ids: List[ID] = field(default_factory=list)
    senders: List[bytes] = field(default_factory=list)
    outputs_metadata: List[bytes] = field(default_factory=list)
    receivers: List[bytes] = field(default_factory=list)
    signatures: List[bytes] = field(default_factory=list)


@dataclass
class RequestMetadata:
    """Off-chain opening metadata + application metadata."""

    application: Dict[str, bytes] = field(default_factory=dict)


@dataclass
class TokenRequest:
    anchor: str  # tx id this request is bound to
    issues: List[IssueRecord] = field(default_factory=list)
    transfers: List[TransferRecord] = field(default_factory=list)
    auditor_signature: bytes = b""
    metadata: RequestMetadata = field(default_factory=RequestMetadata)

    # Private memo fields (never dataclass fields): `_wire_raw` is the
    # exact bytes this instance was parsed from, `_sign_memo`/`_audit_memo`
    # the marshal outputs keyed by `_memo_key`. Reassigning any PUBLIC
    # field drops all three (see `__setattr__`); nested record-level
    # mutation of a parsed request is not a supported pattern — parsed
    # requests are read-only below the top-level fields.

    def __setattr__(self, name, value):
        if not name.startswith("_"):
            d = self.__dict__
            d.pop("_wire_raw", None)
            d.pop("_sign_memo", None)
            d.pop("_audit_memo", None)
        object.__setattr__(self, name, value)

    def _memo_key(self) -> tuple:
        # actions are append-only in every assembly flow (api/tms.py), so
        # the record counts + anchor pin the marshal memos; signatures and
        # receivers mutate freely without touching the signed byte string
        return (self.anchor, len(self.issues), len(self.transfers))

    def _clone(self) -> "TokenRequest":
        """Structural copy: fresh records and fresh lists, immutable
        leaves (bytes, IDs) shared. Cache hits hand these out so the
        cached canonical never escapes to mutating callers."""
        c = TokenRequest(anchor=self.anchor)
        c.issues = [
            IssueRecord(
                action=r.action, issuer=r.issuer,
                outputs_metadata=list(r.outputs_metadata),
                receivers=list(r.receivers), signature=r.signature,
            )
            for r in self.issues
        ]
        c.transfers = [
            TransferRecord(
                action=r.action, input_ids=list(r.input_ids),
                senders=list(r.senders),
                outputs_metadata=list(r.outputs_metadata),
                receivers=list(r.receivers), signatures=list(r.signatures),
            )
            for r in self.transfers
        ]
        c.auditor_signature = self.auditor_signature
        c.metadata.application = dict(self.metadata.application)
        raw = self.__dict__.get("_wire_raw")
        if raw is not None:
            object.__setattr__(c, "_wire_raw", raw)
        return c

    # ------------------------------------------------------------ marshal

    def _actions_dict(self) -> dict:
        return {
            "anchor": self.anchor,
            "issues": [
                {"a": r.action, "i": r.issuer} for r in self.issues
            ],
            "transfers": [
                {
                    "a": r.action,
                    "ids": [[i.tx_id, i.index] for i in r.input_ids],
                    "s": r.senders,
                }
                for r in self.transfers
            ],
        }

    def marshal_to_sign(self) -> bytes:
        """Byte string signed by owners/issuers (reference request.go:655).

        Memoized per instance: block validation marshals the same request
        once in the sign-obligation collector and once per validate, and
        the actions dict is append-only — the memo key catches appends,
        `__setattr__` catches field replacement.
        """
        with profiler.leg("unmarshal"):
            key = self._memo_key()
            memo = self.__dict__.get("_sign_memo")
            if memo is not None and memo[0] == key:
                return memo[1]
            raw = dumps(self._actions_dict())
            object.__setattr__(self, "_sign_memo", (key, raw))
            return raw

    def marshal_to_audit(self) -> bytes:
        """Byte string signed by the auditor (reference request.go:643):
        actions + metadata binding. Memoized like `marshal_to_sign`."""
        with profiler.leg("unmarshal"):
            key = self._memo_key()
            memo = self.__dict__.get("_audit_memo")
            if memo is not None and memo[0] == key:
                return memo[1]
            d = self._actions_dict()
            d["meta"] = {
                "issues": [r.outputs_metadata for r in self.issues],
                "transfers": [r.outputs_metadata for r in self.transfers],
            }
            raw = dumps(d)
            object.__setattr__(self, "_audit_memo", (key, raw))
            return raw

    def to_bytes(self) -> bytes:
        return dumps(
            {
                "anchor": self.anchor,
                "issues": [
                    {
                        "a": r.action,
                        "i": r.issuer,
                        "m": r.outputs_metadata,
                        "r": r.receivers,
                        "sig": r.signature,
                    }
                    for r in self.issues
                ],
                "transfers": [
                    {
                        "a": r.action,
                        "ids": [[i.tx_id, i.index] for i in r.input_ids],
                        "s": r.senders,
                        "m": r.outputs_metadata,
                        "r": r.receivers,
                        "sigs": r.signatures,
                    }
                    for r in self.transfers
                ],
                "asig": self.auditor_signature,
                "app": self.metadata.application,
            }
        )

    def wire_bytes(self) -> bytes:
        """The request's wire encoding for durable storage: the exact
        bytes it was parsed from when no field has been reassigned since
        (skipping a full re-serialization on the WAL path), else a fresh
        `to_bytes()`. Replay decodes both forms identically."""
        raw = self.__dict__.get("_wire_raw")
        return raw if raw is not None else self.to_bytes()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "TokenRequest":
        with profiler.leg("unmarshal"):
            req = _CACHE.lookup(raw)
            if req is not None:
                return req
            req = cls._from_bytes_inner(raw)
            object.__setattr__(req, "_wire_raw", raw)
            _CACHE.store(raw, req._clone())
            return req

    @classmethod
    def _from_bytes_inner(cls, raw: bytes) -> "TokenRequest":
        d = loads(raw)
        req = cls(anchor=d["anchor"])
        for r in d["issues"]:
            req.issues.append(
                IssueRecord(
                    action=r["a"], issuer=r["i"], outputs_metadata=r["m"],
                    receivers=r["r"], signature=r["sig"],
                )
            )
        for r in d["transfers"]:
            req.transfers.append(
                TransferRecord(
                    action=r["a"],
                    input_ids=[ID(t, i) for t, i in r["ids"]],
                    senders=r["s"],
                    outputs_metadata=r["m"],
                    receivers=r["r"],
                    signatures=r["sigs"],
                )
            )
        req.auditor_signature = d["asig"]
        req.metadata.application = d["app"]
        return req

    # ------------------------------------------------------------ helpers

    def set_application_metadata(self, k: str, v: bytes) -> None:
        self.metadata.application[k] = v

    def application_metadata(self, k: str) -> Optional[bytes]:
        return self.metadata.application.get(k)


# ------------------------------------------------------------ parse cache


class _RequestCache:
    """Bounded LRU: raw request bytes -> parsed canonical `TokenRequest`,
    mirroring `drivers.identity._IdentityCache` — re-validated and
    resubmitted requests skip unmarshal entirely.

    The canonical entry never escapes: hits (and the miss that populates
    an entry) hand out `_clone()` copies, so a caller mutating its parse
    can never corrupt later lookups. Parse failures are never cached.
    Cache-pressure evictions are counted and surfaced on the flight
    recorder (throttled: the first eviction and every `_FLIGHT_EVERY`-th
    after it, so a thrashing cache cannot flood the ring)."""

    _FLIGHT_EVERY = 512

    def __init__(self, capacity: Optional[int] = None):
        # an explicit capacity is fixed; otherwise FTS_REQUEST_CACHE is
        # resolved lazily on FIRST USE (not at import) and re-resolved
        # after clear(), so tests/operators configuring the env after
        # the SDK imported still take effect
        self._from_env = capacity is None
        self._capacity = max(0, capacity) if capacity is not None else None
        self._entries: "OrderedDict[bytes, TokenRequest]" = OrderedDict()
        self._evictions = 0
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        if self._capacity is None:
            try:
                self._capacity = max(
                    0, int(os.environ.get("FTS_REQUEST_CACHE", "4096"))
                )
            except ValueError:
                self._capacity = 4096
        return self._capacity

    def lookup(self, raw: bytes) -> Optional["TokenRequest"]:
        if self.capacity == 0:  # disabled: no storage, no counters
            return None
        with self._lock:
            entry = self._entries.get(raw)
            if entry is not None:
                self._entries.move_to_end(raw)
        if entry is None:
            mx.counter("request.cache.misses").inc()
            return None
        mx.counter("request.cache.hits").inc()
        return entry._clone()

    def store(self, raw: bytes, req: "TokenRequest") -> None:
        if self.capacity == 0:
            return
        evicted = 0
        with self._lock:
            self._entries[raw] = req
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                evicted += 1
                self._evictions += 1
            total, size = self._evictions, len(self._entries)
        if evicted:
            mx.counter("request.cache.evictions").inc(evicted)
            if total == evicted or (total // self._FLIGHT_EVERY) > (
                (total - evicted) // self._FLIGHT_EVERY
            ):
                mx.flight(
                    "request.cache.evict", evicted=total, size=size,
                    capacity=self.capacity,
                )

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._evictions = 0
            if self._from_env:
                self._capacity = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


_CACHE = _RequestCache()


def cache_clear() -> None:
    """Drop every cached parsed request (tests; also on memory pressure)."""
    _CACHE.clear()


def cache_len() -> int:
    return len(_CACHE)
