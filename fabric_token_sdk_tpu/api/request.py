"""TokenRequest: the unit a transaction carries to the ledger.

Reference: `token/request.go` — a request aggregates issue/transfer
actions, collects owner/issuer/auditor signatures, and carries per-output
metadata off-chain (`token/metadata.go`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..crypto.serialization import dumps, loads
from ..models.token import ID
from ..utils import profiler


@dataclass
class IssueRecord:
    action: bytes
    issuer: bytes
    outputs_metadata: List[bytes] = field(default_factory=list)
    receivers: List[bytes] = field(default_factory=list)
    signature: bytes = b""


@dataclass
class TransferRecord:
    action: bytes
    input_ids: List[ID] = field(default_factory=list)
    senders: List[bytes] = field(default_factory=list)
    outputs_metadata: List[bytes] = field(default_factory=list)
    receivers: List[bytes] = field(default_factory=list)
    signatures: List[bytes] = field(default_factory=list)


@dataclass
class RequestMetadata:
    """Off-chain opening metadata + application metadata."""

    application: Dict[str, bytes] = field(default_factory=dict)


@dataclass
class TokenRequest:
    anchor: str  # tx id this request is bound to
    issues: List[IssueRecord] = field(default_factory=list)
    transfers: List[TransferRecord] = field(default_factory=list)
    auditor_signature: bytes = b""
    metadata: RequestMetadata = field(default_factory=RequestMetadata)

    # ------------------------------------------------------------ marshal

    def _actions_dict(self) -> dict:
        return {
            "anchor": self.anchor,
            "issues": [
                {"a": r.action, "i": r.issuer} for r in self.issues
            ],
            "transfers": [
                {
                    "a": r.action,
                    "ids": [[i.tx_id, i.index] for i in r.input_ids],
                    "s": r.senders,
                }
                for r in self.transfers
            ],
        }

    def marshal_to_sign(self) -> bytes:
        """Byte string signed by owners/issuers (reference request.go:655)."""
        with profiler.leg("unmarshal"):
            return dumps(self._actions_dict())

    def marshal_to_audit(self) -> bytes:
        """Byte string signed by the auditor (reference request.go:643):
        actions + metadata binding."""
        with profiler.leg("unmarshal"):
            d = self._actions_dict()
            d["meta"] = {
                "issues": [r.outputs_metadata for r in self.issues],
                "transfers": [r.outputs_metadata for r in self.transfers],
            }
            return dumps(d)

    def to_bytes(self) -> bytes:
        return dumps(
            {
                "anchor": self.anchor,
                "issues": [
                    {
                        "a": r.action,
                        "i": r.issuer,
                        "m": r.outputs_metadata,
                        "r": r.receivers,
                        "sig": r.signature,
                    }
                    for r in self.issues
                ],
                "transfers": [
                    {
                        "a": r.action,
                        "ids": [[i.tx_id, i.index] for i in r.input_ids],
                        "s": r.senders,
                        "m": r.outputs_metadata,
                        "r": r.receivers,
                        "sigs": r.signatures,
                    }
                    for r in self.transfers
                ],
                "asig": self.auditor_signature,
                "app": self.metadata.application,
            }
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "TokenRequest":
        with profiler.leg("unmarshal"):
            return cls._from_bytes_inner(raw)

    @classmethod
    def _from_bytes_inner(cls, raw: bytes) -> "TokenRequest":
        d = loads(raw)
        req = cls(anchor=d["anchor"])
        for r in d["issues"]:
            req.issues.append(
                IssueRecord(
                    action=r["a"], issuer=r["i"], outputs_metadata=r["m"],
                    receivers=r["r"], signature=r["sig"],
                )
            )
        for r in d["transfers"]:
            req.transfers.append(
                TransferRecord(
                    action=r["a"],
                    input_ids=[ID(t, i) for t, i in r["ids"]],
                    senders=r["s"],
                    outputs_metadata=r["m"],
                    receivers=r["r"],
                    signatures=r["sigs"],
                )
            )
        req.auditor_signature = d["asig"]
        req.metadata.application = d["app"]
        return req

    # ------------------------------------------------------------ helpers

    def set_application_metadata(self, k: str, v: bytes) -> None:
        self.metadata.application[k] = v

    def application_metadata(self, k: str) -> Optional[bytes]:
        return self.metadata.application.get(k)
