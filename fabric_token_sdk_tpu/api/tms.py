"""Token Management Service: the per-network facade binding driver,
wallets, and request assembly.

Reference: `token/tms.go` + `token/request.go` (Issue/Transfer/Redeem).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from .driver import Driver
from .request import IssueRecord, TokenRequest, TransferRecord
from .validator import RequestValidator
from .wallet import IssuerWallet, OwnerWallet, WalletRegistry
from ..models.token import ID, UnspentToken


class ManagementService:
    def __init__(self, driver: Driver, wallets: Optional[WalletRegistry] = None,
                 auditor_identity: bytes = b"", rng=None):
        self.driver = driver
        self.wallets = wallets or WalletRegistry()
        self.auditor_identity = auditor_identity
        self.rng = rng

    # ------------------------------------------------------------ requests

    def new_request(self, anchor: str) -> TokenRequest:
        return TokenRequest(anchor=anchor)

    def add_issue(self, request: TokenRequest, issuer: IssuerWallet, token_type: str,
                  values: Sequence[int], owners: Sequence[bytes],
                  anonymous: bool = True) -> IssueRecord:
        outcome = self.driver.issue(
            issuer.identity, token_type, list(values), list(owners), anonymous
        )
        rec = IssueRecord(
            action=outcome.action_bytes,
            # anonymous issues must not leak the issuer at the request level
            # either — the action already blanks it
            issuer=b""
            if anonymous and self.driver.supports_anonymous_issue
            else issuer.identity,
            outputs_metadata=outcome.metadata,
            receivers=list(owners),
        )
        request.issues.append(rec)
        return rec

    def add_transfer(self, request: TokenRequest, input_ids: Sequence[ID],
                     input_tokens: Sequence[bytes], input_metadata: Sequence[bytes],
                     token_type: str, values: Sequence[int],
                     owners: Sequence[bytes]) -> TransferRecord:
        outcome = self.driver.transfer(
            list(input_ids), list(input_tokens), list(input_metadata),
            token_type, list(values), list(owners),
        )
        senders = [self.driver.output_owner(raw) for raw in input_tokens]
        rec = TransferRecord(
            action=outcome.action_bytes,
            input_ids=list(input_ids),
            senders=senders,
            outputs_metadata=outcome.metadata,
            receivers=list(owners),
        )
        request.transfers.append(rec)
        return rec

    def add_redeem(self, request: TokenRequest, input_ids, input_tokens,
                   input_metadata, token_type: str, redeem_value: int,
                   change_value: int, change_owner: bytes) -> TransferRecord:
        """Redeem = transfer with an empty-owner output (reference
        request.go:315 Redeem)."""
        values = [redeem_value] + ([change_value] if change_value else [])
        owners = [b""] + ([change_owner] if change_value else [])
        return self.add_transfer(
            request, input_ids, input_tokens, input_metadata, token_type, values, owners
        )

    # ------------------------------------------------------------ signing

    def sign_transfers(self, request: TokenRequest) -> None:
        """Each input owner signs the request (CollectEndorsements step)."""
        payload = request.marshal_to_sign()
        for rec in request.transfers:
            rec.signatures = []
            for sender in rec.senders:
                w = self.wallets.wallet_owning(sender)
                if w is None:
                    raise ValueError("no wallet controls a sender identity")
                rec.signatures.append(w.sign(sender, payload))

    def sign_issues(self, request: TokenRequest) -> None:
        payload = request.marshal_to_sign()
        for rec in request.issues:
            if not rec.issuer:
                continue  # anonymous issue: the proof authorizes
            for iw in self.wallets.issuers.values():
                if iw.identity == rec.issuer:
                    rec.signature = iw.sign(payload, self.rng)
                    break
            else:
                raise ValueError("no issuer wallet controls the issue identity")

    def audit(self, request: TokenRequest, auditor_wallet) -> None:
        request.auditor_signature = auditor_wallet.sign(request.marshal_to_audit())

    # ------------------------------------------------------------ validate

    def validator(self) -> RequestValidator:
        return RequestValidator(self.driver, self.auditor_identity)
