"""Concurrent UTXO selector with per-token locks and retry/backoff.

Reference: `token/services/selector/*` (manager.go, selector.go,
inmemory locker). Multiple in-flight transactions compete for the same
unspent tokens; the selector locks candidates, retries while tokens are
busy, and raises typed errors on insufficient funds.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ...models.token import ID, UnspentToken
from ...utils import metrics as mx
from ..vault.vault import Vault


class InsufficientFunds(Exception):
    pass


class SelectorTimeout(Exception):
    pass


class Locker:
    def __init__(self):
        self._locked: Dict[str, str] = {}  # token key -> tx id
        self._mu = threading.Lock()

    def try_lock(self, token_id: ID, tx_id: str) -> bool:
        with self._mu:
            if token_id.key() in self._locked:
                return False
            self._locked[token_id.key()] = tx_id
            return True

    def holder(self, token_id: ID) -> Optional[str]:
        with self._mu:
            return self._locked.get(token_id.key())

    def unlock(self, token_id: ID) -> None:
        with self._mu:
            self._locked.pop(token_id.key(), None)

    def unlock_by_tx(self, tx_id: str) -> None:
        with self._mu:
            for k in [k for k, v in self._locked.items() if v == tx_id]:
                del self._locked[k]

    def is_locked(self, token_id: ID) -> bool:
        with self._mu:
            return token_id.key() in self._locked


class Selector:
    def __init__(self, vault: Vault, locker: Locker, tx_id: str,
                 retries: int = 10, backoff_s: float = 0.02):
        self.vault = vault
        self.locker = locker
        self.tx_id = tx_id
        self.retries = retries
        self.backoff_s = backoff_s

    def select(self, amount: int, token_type: str) -> Tuple[List[ID], int]:
        """Lock unspent tokens of `token_type` totalling >= amount.

        Returns (ids, total). Raises InsufficientFunds / SelectorTimeout.
        """
        t0 = time.monotonic()
        try:
            for attempt in range(self.retries):
                picked: List[ID] = []
                total = 0
                saw_busy = False
                for ut in self.vault.unspent_tokens(token_type):
                    if total >= amount:
                        break
                    if not self.locker.try_lock(ut.id, self.tx_id):
                        # tokens this SAME tx already earmarked can never
                        # free up before it completes: not retryable
                        # contention
                        if self.locker.holder(ut.id) != self.tx_id:
                            saw_busy = True
                            mx.counter("selector.lock.busy").inc()
                        continue
                    mx.counter("selector.lock.acquired").inc()
                    picked.append(ut.id)
                    total += int(ut.quantity)
                if total >= amount:
                    return picked, total
                # not enough: release and maybe retry (tokens may unlock)
                for i in picked:
                    self.locker.unlock(i)
                if not saw_busy:
                    mx.counter("selector.insufficient_funds").inc()
                    raise InsufficientFunds(
                        f"insufficient funds: need {amount} of [{token_type}]"
                    )
                mx.counter("selector.retry").inc()
                time.sleep(self.backoff_s * (attempt + 1))
            mx.counter("selector.timeout").inc()
            raise SelectorTimeout(
                f"token selection timed out: tokens busy for [{token_type}]"
            )
        finally:
            mx.histogram("selector.select.seconds").observe(
                time.monotonic() - t0
            )

    def unselect(self, ids: List[ID]) -> None:
        for i in ids:
            self.locker.unlock(i)


class SelectorManager:
    """Per-party manager handing out tx-scoped selectors over one locker."""

    def __init__(self, vault: Vault):
        self.vault = vault
        self.locker = Locker()

    def new_selector(self, tx_id: str, **kw) -> Selector:
        return Selector(self.vault, self.locker, tx_id, **kw)

    def unlock_by_tx(self, tx_id: str) -> None:
        self.locker.unlock_by_tx(tx_id)
