"""Concurrent UTXO selector: indexed candidates, sharded locks,
deadline-aware backoff.

Reference: `token/services/selector/*` (manager.go, selector.go, the
sharded in-memory locker). Multiple in-flight transactions compete for
the same unspent tokens; the selector walks the vault's (type, owner)
selection index — quantity-descending, so covering an amount needs the
fewest locks and the walk never touches tokens of other types — locks
candidates through a hash-sharded lock table (concurrent spenders on
different tokens almost never share a mutex), retries with backoff
while tokens are busy, and raises typed errors on insufficient funds or
an exhausted retry/wall-clock budget.

Self-hold semantics (pinned by `tests/test_state_plane.py`): a token
already locked by the SAME tx was earmarked by one of this tx's earlier
selects — it is skipped WITHOUT counting toward the new total (counting
it would let one tx double-commit the same token across two transfer
records) and without flagging retryable contention (it can never free
up before the tx completes). A re-entrant select therefore asks only
for funds beyond what the tx already holds; `selector.self_held` counts
the skips so the condition is observable.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ...models.token import ID
from ...utils import faults
from ...utils import metrics as mx
from ..vault.vault import Vault


class InsufficientFunds(Exception):
    pass


class SelectorTimeout(Exception):
    pass


class _LockShard:
    __slots__ = ("mu", "locked", "by_tx")

    def __init__(self):
        self.mu = threading.Lock()
        self.locked: Dict[str, str] = {}  # token key -> tx id
        self.by_tx: Dict[str, Set[str]] = {}  # tx id -> its keys here


class ShardedLocker:
    """Token-lock table sharded by token-key hash: N independent mutexes
    plus a per-shard per-tx key set, so concurrent spenders contend only
    when they race for the SAME shard and `unlock_by_tx` releases a tx's
    locks in O(shards + locks held) instead of scanning every locked
    token under one global mutex."""

    def __init__(self, shards: Optional[int] = None):
        if shards is None:
            shards = int(os.environ.get("FTS_SELECTOR_SHARDS", "16"))
        self._n = max(1, int(shards))
        self._shards = [_LockShard() for _ in range(self._n)]

    def _shard(self, key: str) -> _LockShard:
        return self._shards[hash(key) % self._n]

    def try_lock(self, token_id: ID, tx_id: str) -> bool:
        faults.fire("selector.lock")
        key = token_id.key()
        shard = self._shard(key)
        with shard.mu:
            if key in shard.locked:
                return False
            shard.locked[key] = tx_id
            shard.by_tx.setdefault(tx_id, set()).add(key)
            return True

    def holder(self, token_id: ID) -> Optional[str]:
        key = token_id.key()
        shard = self._shard(key)
        with shard.mu:
            return shard.locked.get(key)

    def unlock(self, token_id: ID) -> None:
        key = token_id.key()
        shard = self._shard(key)
        with shard.mu:
            tx_id = shard.locked.pop(key, None)
            if tx_id is not None:
                held = shard.by_tx.get(tx_id)
                if held is not None:
                    held.discard(key)
                    if not held:
                        del shard.by_tx[tx_id]

    def unlock_by_tx(self, tx_id: str) -> None:
        for shard in self._shards:
            with shard.mu:
                for key in shard.by_tx.pop(tx_id, ()):
                    shard.locked.pop(key, None)

    def is_locked(self, token_id: ID) -> bool:
        key = token_id.key()
        shard = self._shard(key)
        with shard.mu:
            return key in shard.locked

    def locked_count(self) -> int:
        """Total locks held (per-shard sums; approximate under races)."""
        return sum(len(s.locked) for s in self._shards)


# pre-shard name, kept so external callers/tests keep working
Locker = ShardedLocker


class Selector:
    """Tx-scoped selector. `retries`/`backoff_s` govern the legacy
    retry-count budget; `deadline_s` (or `FTS_SELECTOR_DEADLINE_S`)
    switches to a WALL-CLOCK budget — under contention the caller knows
    how long selection may block, not just how many times it looped, and
    each backoff sleep is capped to the remaining budget."""

    def __init__(self, vault: Vault, locker: ShardedLocker, tx_id: str,
                 retries: int = 10, backoff_s: float = 0.02,
                 deadline_s: Optional[float] = None):
        self.vault = vault
        self.locker = locker
        self.tx_id = tx_id
        self.retries = retries
        self.backoff_s = backoff_s
        if deadline_s is None:
            env = os.environ.get("FTS_SELECTOR_DEADLINE_S", "")
            deadline_s = float(env) if env else None
        self.deadline_s = deadline_s

    def select(self, amount: int, token_type: str) -> Tuple[List[ID], int]:
        """Lock unspent tokens of `token_type` totalling >= amount.

        Returns (ids, total). Raises InsufficientFunds / SelectorTimeout.
        """
        t0 = time.monotonic()
        attempt = 0
        try:
            while True:
                picked: List[ID] = []
                total = 0
                scanned = 0
                saw_busy = False
                for ut in self.vault.iter_unspent(token_type):
                    if total >= amount:
                        break
                    scanned += 1
                    if not self.locker.try_lock(ut.id, self.tx_id):
                        if self.locker.holder(ut.id) == self.tx_id:
                            # earmarked by THIS tx's earlier select: never
                            # double-counted, never retryable contention
                            # (see module docstring)
                            mx.counter("selector.self_held").inc()
                        else:
                            saw_busy = True
                            mx.counter("selector.lock.busy").inc()
                        continue
                    mx.counter("selector.lock.acquired").inc()
                    picked.append(ut.id)
                    total += int(ut.quantity)
                # candidates examined this pass — the sub-linearity
                # witness: O(tokens needed + busy skips), not O(vault)
                mx.counter("selector.scanned").inc(scanned)
                if total >= amount:
                    return picked, total
                # not enough: release and maybe retry (tokens may unlock)
                for i in picked:
                    self.locker.unlock(i)
                if not saw_busy:
                    mx.counter("selector.insufficient_funds").inc()
                    raise InsufficientFunds(
                        f"insufficient funds: need {amount} of [{token_type}]"
                    )
                attempt += 1
                elapsed = time.monotonic() - t0
                if self.deadline_s is not None:
                    if elapsed >= self.deadline_s:
                        raise self._timeout(token_type)
                    sleep = min(self.backoff_s * attempt,
                                self.deadline_s - elapsed)
                else:
                    if attempt >= self.retries:
                        raise self._timeout(token_type)
                    sleep = self.backoff_s * attempt
                mx.counter("selector.retry").inc()
                time.sleep(max(0.0, sleep))
        finally:
            mx.histogram("selector.select.seconds").observe(
                time.monotonic() - t0
            )

    def _timeout(self, token_type: str) -> SelectorTimeout:
        mx.counter("selector.timeout").inc()
        return SelectorTimeout(
            f"token selection timed out: tokens busy for [{token_type}]"
        )

    def unselect(self, ids: List[ID]) -> None:
        for i in ids:
            self.locker.unlock(i)


class SelectorManager:
    """Per-party manager handing out tx-scoped selectors over one
    sharded locker."""

    def __init__(self, vault: Vault, shards: Optional[int] = None):
        self.vault = vault
        self.locker = ShardedLocker(shards)

    def new_selector(self, tx_id: str, **kw) -> Selector:
        return Selector(self.vault, self.locker, tx_id, **kw)

    def unlock_by_tx(self, tx_id: str) -> None:
        self.locker.unlock_by_tx(tx_id)
