from .selector import (  # noqa: F401
    InsufficientFunds,
    Locker,
    Selector,
    SelectorManager,
    SelectorTimeout,
    ShardedLocker,
)
