from .selector import InsufficientFunds, Selector, SelectorManager  # noqa: F401
