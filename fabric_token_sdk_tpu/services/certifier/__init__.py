from .certifier import CertificationService  # noqa: F401
