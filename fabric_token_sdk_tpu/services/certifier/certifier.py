"""Token certification: a certifier attests that tokens exist on ledger.

Reference: `token/services/certifier/*` (dummy + interactive drivers) and
`token/certification.go`. Certifications are signatures over (token id,
output bytes) stored in the vault's certification store.
"""

from __future__ import annotations

from typing import List, Optional

from ...crypto import sign
from ...crypto.serialization import dumps
from ...models.token import ID
from ..network.ledger import Network
from ..vault.vault import Vault


class CertificationService:
    def __init__(self, network: Network, key: Optional[sign.SigningKey] = None, rng=None):
        self.network = network
        self.key = key or sign.keygen(rng)
        self.rng = rng

    @property
    def public_key(self) -> sign.PublicKey:
        return self.key.public

    def certify(self, token_id: ID) -> bytes:
        """Interactive certification: check existence, sign attestation."""
        output = self.network.resolve_input(token_id)  # raises if spent/missing
        payload = dumps({"id": [token_id.tx_id, token_id.index], "out": output})
        return self.key.sign(payload, self.rng)

    def verify(self, token_id: ID, output: bytes, cert: bytes) -> None:
        payload = dumps({"id": [token_id.tx_id, token_id.index], "out": output})
        self.key.public.verify(payload, cert)

    def certify_into(self, vault: Vault, token_id: ID) -> None:
        vault.store_certification(token_id, self.certify(token_id))
