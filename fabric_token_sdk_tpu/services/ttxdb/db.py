"""Transaction database (sqlite): records, movements, statuses, queries.

Reference: `token/services/ttxdb/*` (db.go + badger/memory drivers):
payment/holding queries over per-wallet movements, transaction records
with status transitions, audit bookkeeping.
"""

from __future__ import annotations

import sqlite3
import threading
import time
from dataclasses import dataclass
from enum import Enum
from typing import List, Optional


class TxType(Enum):
    ISSUE = "Issue"
    TRANSFER = "Transfer"
    REDEEM = "Redeem"


class MovementDirection(Enum):
    SENT = "Sent"
    RECEIVED = "Received"


@dataclass
class TransactionRecord:
    tx_id: str
    tx_type: str
    sender_eid: str
    recipient_eid: str
    token_type: str
    amount: int
    status: str
    timestamp: float


class TransactionDB:
    """One DB per party (':memory:' or a file path for persistence)."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._mu = threading.Lock()
        with self._mu:
            self._conn.executescript(
                """
                CREATE TABLE IF NOT EXISTS transactions (
                    tx_id TEXT, tx_type TEXT, sender_eid TEXT,
                    recipient_eid TEXT, token_type TEXT, amount TEXT,
                    status TEXT, timestamp REAL
                );
                CREATE TABLE IF NOT EXISTS movements (
                    tx_id TEXT, wallet_eid TEXT, token_type TEXT,
                    amount TEXT, direction TEXT, status TEXT
                );
                CREATE INDEX IF NOT EXISTS tx_idx ON transactions(tx_id);
                """
            )
            self._conn.commit()

    # ------------------------------------------------------------ writes

    def add_transaction(self, tx_id: str, tx_type: TxType, sender: str,
                        recipient: str, token_type: str, amount: int,
                        status: str = "Pending") -> None:
        with self._mu:
            self._conn.execute(
                "INSERT INTO transactions VALUES (?,?,?,?,?,?,?,?)",
                (tx_id, tx_type.value, sender, recipient, token_type,
                 str(amount), status, time.time()),
            )
            self._conn.commit()

    def add_movement(self, tx_id: str, wallet: str, token_type: str,
                     amount: int, direction: MovementDirection,
                     status: str = "Pending") -> None:
        with self._mu:
            self._conn.execute(
                "INSERT INTO movements VALUES (?,?,?,?,?,?)",
                (tx_id, wallet, token_type, str(amount), direction.value, status),
            )
            self._conn.commit()

    def set_status(self, tx_id: str, status: str) -> None:
        with self._mu:
            self._conn.execute(
                "UPDATE transactions SET status=? WHERE tx_id=?", (status, tx_id)
            )
            self._conn.execute(
                "UPDATE movements SET status=? WHERE tx_id=?", (status, tx_id)
            )
            self._conn.commit()

    # ------------------------------------------------------------ queries

    def transactions(self, status: Optional[str] = None) -> List[TransactionRecord]:
        q = "SELECT * FROM transactions"
        args: tuple = ()
        if status:
            q += " WHERE status=?"
            args = (status,)
        with self._mu:
            rows = self._conn.execute(q + " ORDER BY timestamp", args).fetchall()
        return [
            TransactionRecord(r[0], r[1], r[2], r[3], r[4], int(r[5]), r[6], r[7])
            for r in rows
        ]

    def status(self, tx_id: str) -> Optional[str]:
        with self._mu:
            row = self._conn.execute(
                "SELECT status FROM transactions WHERE tx_id=? LIMIT 1", (tx_id,)
            ).fetchone()
        return row[0] if row else None

    def payments(self, wallet: str, token_type: Optional[str] = None) -> int:
        """Total confirmed amount sent by `wallet` (reference: payments filter)."""
        return self._sum_movements(wallet, MovementDirection.SENT, token_type)

    def holdings(self, wallet: str, token_type: Optional[str] = None) -> int:
        """Net confirmed holdings of `wallet` = received - sent."""
        return self._sum_movements(
            wallet, MovementDirection.RECEIVED, token_type
        ) - self._sum_movements(wallet, MovementDirection.SENT, token_type)

    def _sum_movements(self, wallet: str, direction: MovementDirection,
                       token_type: Optional[str]) -> int:
        # amounts are stored as TEXT (sqlite INTEGER caps at 2^63): sum in python
        q = ("SELECT amount FROM movements WHERE wallet_eid=? "
             "AND direction=? AND status='Confirmed'")
        args: list = [wallet, direction.value]
        if token_type:
            q += " AND token_type=?"
            args.append(token_type)
        with self._mu:
            rows = self._conn.execute(q, tuple(args)).fetchall()
        return sum(int(r[0]) for r in rows)
