"""Transaction database (sqlite): records, movements, statuses, queries.

Reference: `token/services/ttxdb/*` (db.go + badger/memory drivers):
payment/holding queries over per-wallet movements, transaction records
with status transitions, audit bookkeeping.
"""

from __future__ import annotations

import sqlite3
import threading
import time
from dataclasses import dataclass
from enum import Enum
from typing import List, Optional


class TxType(Enum):
    ISSUE = "Issue"
    TRANSFER = "Transfer"
    REDEEM = "Redeem"


class MovementDirection(Enum):
    SENT = "Sent"
    RECEIVED = "Received"


@dataclass
class TransactionRecord:
    tx_id: str
    tx_type: str
    sender_eid: str
    recipient_eid: str
    token_type: str
    amount: int
    status: str
    timestamp: float


class TransactionDB:
    """One DB per party (':memory:' or a file path for persistence)."""

    _TRANSACTIONS_DDL = """
        CREATE TABLE IF NOT EXISTS transactions (
            tx_id TEXT PRIMARY KEY, tx_type TEXT, sender_eid TEXT,
            recipient_eid TEXT, token_type TEXT, amount TEXT,
            status TEXT, timestamp REAL
        );
    """

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._mu = threading.Lock()
        with self._mu:
            # WAL journaling: crash-consistent file DBs with concurrent
            # readers never blocked by a writer (a no-op for ':memory:')
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._migrate_legacy_transactions()
            self._conn.executescript(
                self._TRANSACTIONS_DDL
                + """
                CREATE TABLE IF NOT EXISTS movements (
                    tx_id TEXT, wallet_eid TEXT, token_type TEXT,
                    amount TEXT, direction TEXT, status TEXT
                );
                CREATE INDEX IF NOT EXISTS mov_wallet_idx
                    ON movements(wallet_eid, direction, status);
                """
            )
            self._conn.commit()

    def _migrate_legacy_transactions(self) -> None:
        """An on-disk DB created before `tx_id` became the PRIMARY KEY
        has a plain table — `CREATE TABLE IF NOT EXISTS` never retrofits
        the constraint, and the upsert's ON CONFLICT would raise.
        Rebuild it in place, keeping the FIRST row per tx_id (the row
        the old `status()` read order returned) and dropping the legacy
        `tx_idx` index the PK makes redundant. The whole rebuild runs in
        ONE transaction (sqlite DDL is transactional), so a crash
        mid-migration rolls back to the untouched legacy table instead
        of stranding history in a half-renamed one."""
        info = self._conn.execute("PRAGMA table_info(transactions)").fetchall()
        if not info or any(r[1] == "tx_id" and r[5] for r in info):
            return  # no table yet, or already PK-keyed
        self._conn.executescript(
            "BEGIN;"
            "ALTER TABLE transactions RENAME TO transactions_legacy;"
            + self._TRANSACTIONS_DDL
            # rowid order = insertion order: OR IGNORE keeps the first
            # row per tx_id, matching the old duplicate-read semantics
            + """
            INSERT OR IGNORE INTO transactions
                SELECT * FROM transactions_legacy;
            DROP TABLE transactions_legacy;
            DROP INDEX IF EXISTS tx_idx;
            COMMIT;
            """
        )

    # ------------------------------------------------------------ writes

    def add_transaction(self, tx_id: str, tx_type: TxType, sender: str,
                        recipient: str, token_type: str, amount: int,
                        status: str = "Pending") -> None:
        with self._mu:
            # tx_id is the PRIMARY KEY: a resubmitted tx UPSERTS its row
            # (fresh status/timestamp) instead of inserting a duplicate
            # that `status()` would silently shadow
            self._conn.execute(
                "INSERT INTO transactions VALUES (?,?,?,?,?,?,?,?) "
                "ON CONFLICT(tx_id) DO UPDATE SET "
                "tx_type=excluded.tx_type, sender_eid=excluded.sender_eid, "
                "recipient_eid=excluded.recipient_eid, "
                "token_type=excluded.token_type, amount=excluded.amount, "
                "status=excluded.status, timestamp=excluded.timestamp",
                (tx_id, tx_type.value, sender, recipient, token_type,
                 str(amount), status, time.time()),
            )
            self._conn.commit()

    def add_movement(self, tx_id: str, wallet: str, token_type: str,
                     amount: int, direction: MovementDirection,
                     status: str = "Pending") -> None:
        with self._mu:
            self._conn.execute(
                "INSERT INTO movements VALUES (?,?,?,?,?,?)",
                (tx_id, wallet, token_type, str(amount), direction.value, status),
            )
            self._conn.commit()

    def set_status(self, tx_id: str, status: str) -> None:
        with self._mu:
            self._conn.execute(
                "UPDATE transactions SET status=? WHERE tx_id=?", (status, tx_id)
            )
            self._conn.execute(
                "UPDATE movements SET status=? WHERE tx_id=?", (status, tx_id)
            )
            self._conn.commit()

    # ------------------------------------------------------------ queries

    def transactions(self, status: Optional[str] = None) -> List[TransactionRecord]:
        q = "SELECT * FROM transactions"
        args: tuple = ()
        if status:
            q += " WHERE status=?"
            args = (status,)
        with self._mu:
            rows = self._conn.execute(q + " ORDER BY timestamp", args).fetchall()
        return [
            TransactionRecord(r[0], r[1], r[2], r[3], r[4], int(r[5]), r[6], r[7])
            for r in rows
        ]

    def status(self, tx_id: str) -> Optional[str]:
        with self._mu:
            row = self._conn.execute(
                "SELECT status FROM transactions WHERE tx_id=? LIMIT 1", (tx_id,)
            ).fetchone()
        return row[0] if row else None

    def payments(self, wallet: str, token_type: Optional[str] = None) -> int:
        """Total confirmed amount sent by `wallet` (reference: payments filter)."""
        return self._sum_movements(wallet, MovementDirection.SENT, token_type)

    def holdings(self, wallet: str, token_type: Optional[str] = None) -> int:
        """Net confirmed holdings of `wallet` = received - sent."""
        return self._sum_movements(
            wallet, MovementDirection.RECEIVED, token_type
        ) - self._sum_movements(wallet, MovementDirection.SENT, token_type)

    def _sum_movements(self, wallet: str, direction: MovementDirection,
                       token_type: Optional[str]) -> int:
        # amounts are stored as TEXT (sqlite INTEGER caps at 2^63): sum in python
        q = ("SELECT amount FROM movements WHERE wallet_eid=? "
             "AND direction=? AND status='Confirmed'")
        args: list = [wallet, direction.value]
        if token_type:
            q += " AND token_type=?"
            args.append(token_type)
        with self._mu:
            rows = self._conn.execute(q, tuple(args)).fetchall()
        return sum(int(r[0]) for r in rows)
