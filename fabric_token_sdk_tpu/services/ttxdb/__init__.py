from .db import MovementDirection, TransactionDB, TxType  # noqa: F401
