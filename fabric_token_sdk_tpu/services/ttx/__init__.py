from .party import Party  # noqa: F401
from .transaction import Transaction  # noqa: F401
