from .party import Party  # noqa: F401
from .pipeline import PipelinedSubmitter, pipelined_submit  # noqa: F401
from .transaction import Transaction  # noqa: F401
