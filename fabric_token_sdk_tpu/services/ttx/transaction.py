"""Token transaction lifecycle: assemble -> endorse -> order -> finality.

Reference: `token/services/ttx/transaction.go`, `collect.go`, `endorse.go`,
`ordering.go`, `finality.go`. One Transaction wraps one TokenRequest; the
initiating party assembles actions (using its selector for inputs),
collects signatures (owners, issuers, auditor), submits to ordering, and
observes finality.
"""

from __future__ import annotations

import uuid
from typing import List, Optional, Sequence

from ...api.driver import ValidationError
from ...api.request import TokenRequest
from ...models.token import ID
from ...utils import metrics as mx
from ..network.ledger import FinalityEvent, TxStatus
from ..ttxdb.db import MovementDirection, TxType
from .party import Party


class Transaction:
    def __init__(self, party: Party, tx_id: Optional[str] = None):
        self.party = party
        self.tx_id = tx_id or uuid.uuid4().hex
        self.request: TokenRequest = party.tms.new_request(self.tx_id)
        self._selected: List[ID] = []
        self._submission = None  # set by submit_async
        # distributed trace for this tx's whole lifecycle: minted at
        # assembly, active through endorse/order/finality, propagated
        # across the network boundary by remote.py
        self.trace = mx.new_trace()

    # ------------------------------------------------------------ assembly

    def issue(self, issuer_wallet_id: str, token_type: str, values: Sequence[int],
              recipients: Sequence[bytes], anonymous: bool = True) -> None:
        issuer = self.party.wallets.issuer_wallet(issuer_wallet_id)
        anonymous = anonymous and self.party.driver.supports_anonymous_issue
        with mx.use_trace(self.trace), \
                mx.span("ttx.assemble", tx=self.tx_id, kind="issue"):
            self.party.tms.add_issue(
                self.request, issuer, token_type, values, recipients, anonymous
            )
        self.party.db.add_transaction(
            self.tx_id, TxType.ISSUE, issuer_wallet_id, "", token_type, sum(values)
        )

    def transfer(self, owner_wallet_id: str, token_type: str,
                 values: Sequence[int], recipients: Sequence[bytes]) -> None:
        """Select inputs, build the transfer (+change), record movements."""
        with mx.use_trace(self.trace), \
                mx.span("ttx.assemble", tx=self.tx_id, kind="transfer"):
            self._transfer(owner_wallet_id, token_type, values, recipients)

    def _transfer(self, owner_wallet_id: str, token_type: str,
                  values: Sequence[int], recipients: Sequence[bytes]) -> None:
        amount = sum(values)
        selector = self.party.selectors.new_selector(self.tx_id)
        ids, total = selector.select(amount, token_type)
        self._selected.extend(ids)
        outputs_values = list(values)
        out_owners = list(recipients)
        if total > amount:
            # change back to the sender
            wallet = self.party.wallets.owner_wallet(owner_wallet_id)
            outputs_values.append(total - amount)
            out_owners.append(wallet.recipient_identity())
        tokens, metas = self.party.vault.get_many(ids)
        self.party.tms.add_transfer(
            self.request, ids, tokens, metas, token_type, outputs_values, out_owners
        )
        self.party.db.add_transaction(
            self.tx_id, TxType.TRANSFER, owner_wallet_id, "", token_type, amount
        )
        self.party.db.add_movement(
            self.tx_id, owner_wallet_id, token_type, amount, MovementDirection.SENT
        )

    def redeem(self, owner_wallet_id: str, token_type: str, value: int) -> None:
        selector = self.party.selectors.new_selector(self.tx_id)
        ids, total = selector.select(value, token_type)
        self._selected.extend(ids)
        wallet = self.party.wallets.owner_wallet(owner_wallet_id)
        tokens, metas = self.party.vault.get_many(ids)
        self.party.tms.add_redeem(
            self.request, ids, tokens, metas, token_type, value,
            total - value, wallet.recipient_identity() if total > value else b"",
        )
        self.party.db.add_transaction(
            self.tx_id, TxType.REDEEM, owner_wallet_id, "", token_type, value
        )
        self.party.db.add_movement(
            self.tx_id, owner_wallet_id, token_type, value, MovementDirection.SENT
        )

    # ------------------------------------------------------------ endorse

    def collect_endorsements(self, auditor=None) -> None:
        """Owners sign, issuers sign, auditor audits + signs.

        Reference ttx/collect.go + auditor.go: the request is audited
        BEFORE ordering; the auditor signature covers actions + metadata.
        """
        with mx.use_trace(self.trace), mx.span("ttx.endorse", tx=self.tx_id):
            self.party.tms.sign_transfers(self.request)
            self.party.tms.sign_issues(self.request)
            if auditor is not None:
                auditor.audit(self.request)

    # ------------------------------------------------------------ ordering

    def submit(self) -> FinalityEvent:
        """Order + wait for finality (reference ttx/ordering.go then
        finality.go, collapsed for the synchronous caller)."""
        mx.counter("ttx.submitted").inc()
        with mx.use_trace(self.trace), \
                mx.span("ttx.order_and_finality", tx=self.tx_id):
            event = self.party.network.submit(self.request.to_bytes())
        return self._after_finality(event)

    def submit_async(self) -> "Transaction":
        """Enqueue into the network's ordering queue without waiting for
        the block cut — pipelined submission lets many txs land in ONE
        block and ride the batched validation plane. Call `wait()` for
        the finality event."""
        mx.counter("ttx.submitted").inc()
        with mx.use_trace(self.trace), mx.span("ttx.order", tx=self.tx_id):
            self._submission = self.party.network.submit_async(
                self.request.to_bytes()
            )
        return self

    def wait(self, timeout: Optional[float] = None) -> FinalityEvent:
        """Block until the tx's block commits (driving the group commit
        if this caller wins the orderer's race); raise on rejection."""
        if self._submission is None:
            raise RuntimeError(f"tx {self.tx_id} was never submitted")
        with mx.use_trace(self.trace), mx.span("ttx.finality", tx=self.tx_id):
            event = self._submission.result(timeout)
        return self._after_finality(event)

    def _after_finality(self, event: FinalityEvent) -> FinalityEvent:
        if event.status != TxStatus.VALID:
            mx.counter("ttx.rejected").inc()
            self.party.selectors.unlock_by_tx(self.tx_id)
            raise ValidationError(f"tx {self.tx_id} rejected: {event.message}")
        mx.counter("ttx.committed").inc()
        return event

    def abort(self) -> None:
        self.party.selectors.unlock_by_tx(self.tx_id)
