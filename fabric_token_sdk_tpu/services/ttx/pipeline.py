"""Pipelined client path: prove→submit overlap.

The reference decouples request assembly from ordering (`token/services/
ttx/ordering.go` runs as its own view); this is the throughput twin of
that split for batch clients. Proof GENERATION is the client's dominant
cost (`BatchedTransferProver` — seconds per group even on device), and a
sequential client alternates: prove group k, submit group k, wait for
server-side validation, prove group k+1... so the client's prove plane
and the server's verify plane each idle while the other works.

`PipelinedSubmitter` overlaps them with one background submit worker and
a depth-1 hand-off queue (double buffer, mirroring the server-side
`PipelinedBlockEngine`): while group k is in flight — on the wire, in
the server's ordering queue, through its batched verify and commit —
the CALLING thread is already proving group k+1. Group order is
preserved (single worker, FIFO hand-off), results come back in builder
order, and the first submission failure is re-raised on the caller's
stack after the worker drains.

Backpressure: a `Backpressure` rejection from the node's admission
control is retried inside the worker with exponential backoff + jitter
(`ttx.pipeline.backpressure`) — the reject happens BEFORE ordering, so
the retry preserves exactly-once.

Overlap accounting mirrors the block engine: `ttx.pipeline.overlap_frac`
is the fraction of total prove wall time that ran while a submission was
in flight — 0 means the pipeline never helped (groups too small or the
server too fast to matter), 1 means proving was fully hidden behind
server-side validation.
"""

from __future__ import annotations

import queue
import random
import threading
import time
from typing import Callable, Iterable, List, Optional

from ...utils import metrics as mx
from ..network.orderer import Backpressure
from ..network.pipeline import BusyClock


class PipelinedSubmitter:
    """Submit groups of token requests while proving the next group.

    `network` is any object with the `submit_many(List[bytes])` contract
    (in-process `Network` or `RemoteNetwork`). `retries`/`backoff_s`
    govern the worker's Backpressure retry loop.
    """

    def __init__(self, network, retries: int = 8, backoff_s: float = 0.05):
        self.network = network
        self.retries = retries
        self.backoff_s = backoff_s
        self._rng = random.Random()  # backoff jitter

    # ------------------------------------------------------------ worker

    def _submit_with_backoff(self, requests: List[bytes]):
        for attempt in range(self.retries + 1):
            try:
                return self.network.submit_many(requests)
            except Backpressure:
                if attempt >= self.retries:
                    raise
                mx.counter("ttx.pipeline.backpressure").inc()
                delay = (
                    self.backoff_s * (2 ** attempt)
                    * (0.5 + self._rng.random())
                )
                time.sleep(min(delay, 2.0))

    # ------------------------------------------------------------ run

    def run(self, builders: Iterable[Callable[[], List[bytes]]]) -> List[list]:
        """Run every builder (the PROVE work — each returns one group's
        request-bytes list) on the calling thread while a worker submits
        completed groups; returns the per-group finality-event lists in
        builder order. The first submission failure aborts the pipeline
        and re-raises after in-flight work settles."""
        handoff: queue.Queue = queue.Queue(maxsize=1)
        results: dict = {}
        failure: List[BaseException] = []
        submit_clock = BusyClock()

        def worker():
            while True:
                item = handoff.get()
                if item is None:
                    return
                if failure:
                    continue  # drain hand-offs so the caller never blocks
                idx, requests = item
                submit_clock.start()
                try:
                    with mx.span("ttx.pipeline.submit", group=idx,
                                 txs=len(requests)):
                        results[idx] = self._submit_with_backoff(requests)
                    mx.counter("ttx.pipeline.groups").inc()
                    mx.counter("ttx.pipeline.txs").inc(len(requests))
                except BaseException as e:  # surfaced on the caller's stack
                    failure.append(e)
                finally:
                    submit_clock.stop()

        t = threading.Thread(
            target=worker, name="fts-ttx-submit", daemon=True
        )
        t.start()
        prove_s = 0.0
        overlap_s = 0.0
        n_groups = 0
        try:
            for idx, build in enumerate(builders):
                t0 = time.monotonic()
                c0 = submit_clock.value()
                requests = build()  # the prove work — overlaps the wire
                prove_s += time.monotonic() - t0
                overlap_s += submit_clock.value() - c0
                n_groups = idx + 1
                if failure:
                    break  # worker died: stop proving, surface below
                handoff.put((idx, requests))
        finally:
            handoff.put(None)
            t.join()
        if prove_s > 0:
            mx.gauge("ttx.pipeline.overlap_frac").set(
                round(min(1.0, overlap_s / prove_s), 6)
            )
        if failure:
            raise failure[0]
        return [results[i] for i in range(n_groups)]


def pipelined_submit(network, builders,
                     retries: int = 8,
                     backoff_s: float = 0.05) -> List[list]:
    """Convenience wrapper: `PipelinedSubmitter(network).run(builders)`."""
    return PipelinedSubmitter(
        network, retries=retries, backoff_s=backoff_s
    ).run(builders)
