"""A party (node): wallets + vault + selector + ttxdb bound to a network.

Reference: fabric-smart-client node hosting the token SDK stack
(`token/services/ttx/*` views run on such nodes).
"""

from __future__ import annotations

from typing import Dict, Optional

from ...api.driver import Driver
from ...api.tms import ManagementService
from ...models.token import ID
from ...api.wallet import AuditorWallet, IssuerWallet, OwnerWallet, WalletRegistry
from ...crypto import sign
from ..network.ledger import Network
from ..selector.selector import SelectorManager
from ..ttxdb.db import TransactionDB
from ..vault.vault import Vault


class Party:
    def __init__(self, name: str, driver: Driver, network: Network,
                 auditor_identity: bytes = b"", rng=None,
                 db_path: str = ":memory:",
                 vault_path: Optional[str] = None):
        self.name = name
        self.driver = driver
        self.network = network
        self.rng = rng
        self.wallets = WalletRegistry()
        self.tms = ManagementService(driver, self.wallets, auditor_identity, rng)
        if vault_path:
            # crash-safe vault: recover whatever the journal + snapshot
            # hold (a fresh path recovers to empty) and keep journaling
            self.vault = Vault.recover(vault_path, driver, self._owns_identity)
        else:
            self.vault = Vault(driver, self._owns_identity)
        self.selectors = SelectorManager(self.vault)
        self.db = TransactionDB(db_path)
        network.subscribe(self.vault.on_finality)
        network.subscribe(self._on_finality)

    # ------------------------------------------------------------ wallets

    def new_owner_wallet(self, wid: str, anonymous: bool, nym_params=None) -> OwnerWallet:
        w = OwnerWallet(wid, anonymous, nym_params, self.rng)
        self.wallets.owners[wid] = w
        return w

    def new_issuer_wallet(self, wid: str) -> IssuerWallet:
        w = IssuerWallet(wid, sign.keygen(self.rng))
        self.wallets.issuers[wid] = w
        return w

    def new_auditor_wallet(self, wid: str) -> AuditorWallet:
        w = AuditorWallet(wid, sign.keygen(self.rng))
        self.wallets.auditors[wid] = w
        return w

    def _owns_identity(self, ident: bytes) -> bool:
        return self.wallets.wallet_owning(ident) is not None

    # ------------------------------------------------------------ events

    def _on_finality(self, event, request) -> None:
        status = "Confirmed" if event.status.value == "Valid" else "Deleted"
        if self.db.status(event.tx_id) is not None:
            self.db.set_status(event.tx_id, status)
        elif event.status.value == "Valid":
            self._record_received(event.tx_id, request)
        self.selectors.unlock_by_tx(event.tx_id)

    def _record_received(self, tx_id: str, request) -> None:
        """Record RECEIVED movements for outputs owned by this party's
        wallets (receiver-side bookkeeping). Output indices are global across
        actions, matching Vault.on_finality / Network.submit numbering."""
        from ...crypto.serialization import loads
        from ...utils.tracing import logger
        from ..ttxdb.db import MovementDirection

        out_index = 0
        for rec in list(request.issues) + list(request.transfers):
            outputs = loads(rec.action)["outputs"]
            for raw, meta in zip(outputs, rec.outputs_metadata):
                token_id = ID(tx_id, out_index)
                out_index += 1
                owner = self.driver.output_owner(raw)
                if not owner:
                    continue
                wallet = self.wallets.wallet_owning(owner)
                if wallet is None:
                    continue
                try:
                    ut = self.driver.output_to_unspent(token_id, raw, meta)
                except Exception as e:
                    logger.warning(
                        "party %s: cannot open received output %s: %s",
                        self.name, token_id, e,
                    )
                    continue
                self.db.add_movement(
                    tx_id, wallet.wallet_id, ut.type, int(ut.quantity),
                    MovementDirection.RECEIVED, "Confirmed",
                )

    # ------------------------------------------------------------ queries

    def balance(self, token_type: str) -> int:
        return self.vault.balance(token_type)
