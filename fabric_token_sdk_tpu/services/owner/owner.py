"""Owner service: transaction history + status tracking for a party.

Reference: `token/services/owner/*` (manager.go, owner.go).
"""

from __future__ import annotations

from typing import List, Optional

from ..ttxdb.db import TransactionDB, TransactionRecord


class OwnerService:
    def __init__(self, db: TransactionDB):
        self.db = db

    def transaction_status(self, tx_id: str) -> Optional[str]:
        return self.db.status(tx_id)

    def history(self, status: Optional[str] = None) -> List[TransactionRecord]:
        return self.db.transactions(status)

    def payments(self, wallet: str, token_type: Optional[str] = None) -> int:
        return self.db.payments(wallet, token_type)

    def holdings(self, wallet: str, token_type: Optional[str] = None) -> int:
        return self.db.holdings(wallet, token_type)
