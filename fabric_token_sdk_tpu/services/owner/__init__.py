from .owner import OwnerService  # noqa: F401
