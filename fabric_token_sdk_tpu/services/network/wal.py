"""Crash-safe write-ahead log for the ledger's block stream.

Reference parity: the SDK recovers vault + ledger state on node restart
from the committed block stream (`token/services/network/*`,
`token/services/vault/*`); here the durable artifact is an fsync'd,
CRC-framed journal of cut blocks. `Network._commit_block` appends each
block *before* the atomic in-memory merge, so any block a submitter ever
saw finality for is on disk; `Network.recover` replays the journal on
top of the latest snapshot (`<wal>.snap`, written every
`FTS_WAL_SNAPSHOT_EVERY` blocks as the compaction mechanism).

Record framing (all big-endian):

    [4-byte payload length][4-byte CRC32 of payload][payload]

Torn-tail semantics: a crash mid-append (or mid-fsync) leaves a partial
or CRC-broken final record. `replay()` scans records sequentially and
treats the FIRST bad frame — short header, short payload, or CRC
mismatch — as the torn tail: everything before it is returned, the file
is truncated back to the last good record boundary (so later appends
produce a clean journal), and `wal.torn_tails` is incremented. This is
standard redo-log behavior: bytes after a torn record were never
acknowledged to any client, so discarding them loses nothing that was
promised. No record, torn or whole, is ever fatal to recovery.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Iterator, List, Tuple

from ...utils import faults
from ...utils import metrics as mx
from ...utils.tracing import logger

_HDR = struct.Struct(">II")  # payload length, CRC32(payload)


class WALError(RuntimeError):
    """Unrecoverable journal problem (e.g. a height gap on replay)."""


def fsync_dir(path: str) -> None:
    """fsync the directory containing `path`: file creates/renames are
    only durable once the DIRECTORY entry is — without this, a power
    loss can persist a later truncate while losing an earlier rename
    (exactly the snapshot-then-truncate-journal compaction ordering)."""
    dirname = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # platform/filesystem without dir-open semantics
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class WriteAheadLog:
    """Append-only journal of serialized block records.

    `sync=True` (default; env override `FTS_WAL_SYNC=0`) fsyncs every
    append — that is what makes the finality a submitter observes
    durable. Thread-safety: appends/replays/resets serialize on one
    lock; in the ledger they additionally run under the orderer's commit
    lock, which is what orders records correctly.
    """

    def __init__(self, path: str, sync: bool = None):
        self.path = str(path)
        self.sync = (
            os.environ.get("FTS_WAL_SYNC", "1") != "0" if sync is None else sync
        )
        self.poisoned = False  # set when the on-disk state is unknowable
        self._lock = threading.Lock()
        # bumped on every truncation (`_reopen`): record boundaries
        # before and after a truncate are unrelated, so a replay scan
        # started under an older generation must never act on the file
        self._generation = 0
        self._fh = open(self.path, "ab")
        if self.sync:
            fsync_dir(self.path)  # the journal's dir entry must survive too

    # ------------------------------------------------------------ write

    def append(self, payload: bytes) -> None:
        faults.fire("wal.append")
        with self._lock, mx.timed("wal.append.seconds"):
            if self.poisoned:
                raise WALError(
                    f"wal {self.path}: poisoned by an earlier append failure "
                    "(on-disk state unknown; recover the node)"
                )
            start = os.path.getsize(self.path)  # buffer is empty between appends
            try:
                self._fh.write(
                    _HDR.pack(len(payload), zlib.crc32(payload)) + payload
                )
                self._fh.flush()
                if self.sync:
                    os.fsync(self._fh.fileno())
            except Exception:
                # Roll the journal back to the pre-append boundary: a
                # FAILED append must never leave a (possibly durable)
                # record behind, or the next successful commit would
                # journal a second record at the same height and recovery
                # would resurrect the aborted block in its place.
                mx.counter("wal.append_failures").inc()
                try:
                    self._reopen(start)
                except OSError:
                    # can't even truncate: fail-stop — refuse appends
                    # until the node is recovered from disk
                    self.poisoned = True
                    logger.exception(
                        "wal: append failed AND rollback failed; %s is "
                        "poisoned (fail-stop)", self.path,
                    )
                raise
            size = self._fh.tell()
        mx.counter("wal.appends").inc()
        mx.gauge("wal.bytes").set(size)

    def reset(self) -> None:
        """Truncate the journal to empty — called after a snapshot has
        durably captured everything the journal held (compaction)."""
        with self._lock:
            self._reopen(0)
        mx.counter("wal.resets").inc()
        mx.gauge("wal.bytes").set(0)

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except OSError:
                pass

    def _reopen(self, size: int) -> None:
        self._generation += 1
        self._fh.close()
        os.truncate(self.path, size)
        self._fh = open(self.path, "ab")

    # ------------------------------------------------------------ read

    def replay_iter(self, from_offset: int = 0) -> Iterator[Tuple[int, bytes]]:
        """Stream complete records from `from_offset` (a record boundary),
        yielding `(next_offset, payload)` pairs oldest first.

        The journal is read one frame at a time — never materialized
        whole — so replaying a multi-GiB journal costs O(largest record)
        memory, and a follower tail can resume from the last offset it
        applied. The scan is bounded by the file size observed under the
        lock at entry, so records appended concurrently (a live leader
        shipping while committing) are simply not part of this pass; the
        tailer re-enters with the last yielded offset to pick them up.

        Torn-tail semantics match `replay()`: the first bad frame within
        the scanned span — short header, short payload, CRC mismatch —
        ends the stream, and the file is truncated back to the last good
        boundary after re-verifying under the lock that (a) the journal
        has not been truncated/compacted since this scan began (the
        generation guard — post-compaction boundaries are unrelated to
        this scan's offsets, so a stale verdict must be a no-op, never a
        mid-record truncation of live fsync'd records) and (b) no
        complete record landed at the boundary in the meantime (so a
        concurrent append can never be destroyed either).
        """
        with self._lock:
            self._fh.flush()
            size = os.path.getsize(self.path)
            generation = self._generation
        good = from_offset
        yielded = 0
        with open(self.path, "rb") as fh:
            fh.seek(good)
            while good + _HDR.size <= size:
                hdr = fh.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    break  # short header: torn tail
                n, crc = _HDR.unpack(hdr)
                end = good + _HDR.size + n
                if end > size:
                    break  # partial payload: torn tail
                payload = fh.read(n)
                if len(payload) < n or zlib.crc32(payload) != crc:
                    break  # corrupt frame: treat as torn tail
                good = end
                yielded += 1
                yield good, payload
        if good < size:
            self._truncate_torn(good, yielded, generation)

    def _truncate_torn(self, good: int, records: int,
                       generation: int) -> None:
        """Truncate a torn tail back to the record boundary `good`,
        unless the journal was truncated/compacted since the scan began
        (`generation` mismatch: `good` is an offset into a file that no
        longer exists — acting on it would cut a LIVE record in half) or
        a complete record has landed at the boundary in the meantime (a
        concurrent append on a live journal must never be destroyed)."""
        with self._lock:
            if self._generation != generation:
                return  # stale scan: boundaries have moved under it
            self._fh.flush()
            size = os.path.getsize(self.path)
            if size <= good:
                return
            with open(self.path, "rb") as fh:
                fh.seek(good)
                hdr = fh.read(_HDR.size)
                if len(hdr) == _HDR.size:
                    n, crc = _HDR.unpack(hdr)
                    payload = fh.read(n)
                    if len(payload) == n and zlib.crc32(payload) == crc:
                        return  # a whole record landed here: not torn
            mx.counter("wal.torn_tails").inc()
            mx.flight("wal.torn_tail", bytes=size - good, records=records)
            logger.warning(
                "wal: discarding %d-byte torn tail of %s after %d good "
                "records", size - good, self.path, records,
            )
            self._reopen(good)

    def replay(self) -> List[bytes]:
        """Return every complete record, oldest first; truncate any torn
        tail back to the last good record boundary."""
        out = [payload for _off, payload in self.replay_iter()]
        mx.counter("wal.replayed.records").inc(len(out))
        return out
