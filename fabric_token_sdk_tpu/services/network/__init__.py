from .ledger import Block, FinalityEvent, Network, TxStatus  # noqa: F401
from .orderer import BlockPolicy, Orderer, Submission  # noqa: F401
