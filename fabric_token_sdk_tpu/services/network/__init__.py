from .ledger import Block, FinalityEvent, Network, TxStatus  # noqa: F401
