from .ledger import Block, FinalityEvent, Network, TxStatus  # noqa: F401
from .orderer import Backpressure, BlockPolicy, Orderer, Submission  # noqa: F401
from .pipeline import BusyClock, PipelinedBlockEngine  # noqa: F401
from .wal import WALError, WriteAheadLog  # noqa: F401
