"""Pipelined block engine: overlap device verify with commit.

The sequential group commit is stop-and-wait: cut a block -> device
verify (the dominant phase on every measured critical-path breakdown) ->
host validate -> WAL -> merge -> only then cut the next block, so the
device plane idles while the host/WAL plane works and vice versa. This
engine streams instead, exploiting the seam the sequential engine
already proved safe: a block's batched device verification is
STATE-INDEPENDENT (it checks proofs against request bytes, never ledger
state), while host validation / WAL / merge must run in strict height
order.

Two stages, double-buffered:

* **Stage A (verify)** runs on the DRIVING thread — whoever wins the cut
  race: cut block N+1 from the ordering queue, run its batched device
  verification (`Network._verify_stage` -> `BlockValidationPipeline`),
  hand the verdicts off. Stage A is serialized by `stage_lock`, so cut
  order == hand-off order == commit order.
* **Stage B (commit)** runs on one daemon worker thread per engine:
  host-validate + WAL append + atomic merge + finality resolution
  (`Network._commit_stage`), strictly in hand-off order. The bounded
  hand-off queue is the double buffer: while the worker commits block N,
  the driving thread verifies block N+1; a third block blocks in
  `submit()` until the buffer drains.

Invariants preserved (differential-tested against the sequential engine
in `tests/test_pipeline.py`):

* **Height order** — stage B is a single consumer of a FIFO queue fed
  under `stage_lock`; merges happen in exactly cut order.
* **Degrade chain** — stage A is `BlockValidationPipeline.proof_verdicts`
  unchanged: sharded -> unsharded -> host per block, with each device
  dispatch bounded by the plane's `FTS_DEVICE_DEADLINE_S` wall budget
  and guarded by its circuit breaker (utils/resilience.py) — a hung
  XLA call is abandoned at the deadline inside stage A itself, so it
  can never wedge the driving thread, and BOTH engines inherit the
  same seam because the sequential path calls the same pipeline
  methods. A verify-stage exception (outside the pipeline's own
  degrade handling, which never raises) downgrades to `pre=None`,
  making stage B re-run verification exactly as the sequential engine
  would (`orderer.pipeline.verify_errors`).
* **Exactly-once** — dedup at stage A is provisional (skip work already
  recorded); stage B re-checks under the final committed state, so a
  duplicate racing across two in-flight blocks resolves from the
  recorded verdict, never validates twice.
* **Error propagation** — a commit exception on the worker cannot reach
  a driving thread's stack, so stage B attaches it to every stranded
  submission (`Submission._commit_error`) and `result()` re-raises it —
  the same contract the sequential engine gives its driving thread.

Overlap accounting: `BusyClock` tracks stage-B busy time; stage A
measures how much of its verify wall clock ran while stage B was busy
(`orderer.pipeline.overlap.seconds` histogram, `overlap_frac` gauge,
and the `overlap_s` field of the block critical-path breakdown).

`FTS_BLOCK_PIPELINE=0` (or `BlockPolicy(pipeline=False)`) disables the
engine entirely and restores the exact sequential path — accept/reject
can never depend on the overlap.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

from ...utils import metrics as mx
from ...utils import profiler
from ...utils.tracing import logger


# ------------------------------------------------------------ host workers
#
# Shared worker pool for the batch-first HOST validation passes
# (`BlockValidationPipeline._host_sign_batch` / `_host_proof_batch`):
# the native bn254/sha256 calls release the GIL, so chunking one block's
# rows across a few threads overlaps their C time. WAL append and vault
# merge stay single-threaded on the stage-B worker — this pool only ever
# computes pure verdicts over immutable row tuples.

_HOST_MIN_CHUNK = 8

_host_pool: Optional[ThreadPoolExecutor] = None
_host_pool_size = 0
_host_pool_lock = threading.Lock()


def host_workers() -> int:
    """Resolved `FTS_COMMIT_WORKERS`: unset/0 = auto (half the cores,
    capped at 4 — host batch rows only parallelize inside the GIL-free
    native calls, beyond that threads just contend), 1 = inline, N = N
    pool threads."""
    try:
        n = int(os.environ.get("FTS_COMMIT_WORKERS", "0"))
    except ValueError:
        n = 0
    if n <= 0:
        n = min(4, max(1, (os.cpu_count() or 2) // 2))
    return n


def host_map(fn: Callable[[List], List], items) -> List:
    """Fan `fn` (chunk -> aligned verdict list) over `items` on the
    shared commit-host pool, preserving order. Small batches (or a
    1-worker pool) run inline — the pool must never cost more than the
    loop it replaces. A chunk exception propagates to the caller, which
    owns the degrade-to-scalar decision."""
    items = list(items)
    n = host_workers()
    if n <= 1 or len(items) < 2 * _HOST_MIN_CHUNK:
        return list(fn(items))
    global _host_pool, _host_pool_size
    with _host_pool_lock:
        if _host_pool is None or _host_pool_size != n:
            if _host_pool is not None:
                _host_pool.shutdown(wait=False)
            _host_pool = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="fts-commit-host",
                initializer=profiler.set_thread_role,
                initargs=("commit-worker",),
            )
            _host_pool_size = n
        pool = _host_pool
    n_chunks = min(n, len(items) // _HOST_MIN_CHUNK)
    size = (len(items) + n_chunks - 1) // n_chunks
    futs = [
        pool.submit(fn, items[i : i + size])
        for i in range(0, len(items), size)
    ]
    out: List = []
    for f in futs:
        out.extend(f.result())
    return out


class BusyClock:
    """Cumulative busy-time clock: `value()` at two instants brackets how
    long the tracked activity ran in between, including a span still in
    progress — the primitive behind the verify/commit overlap metric."""

    __slots__ = ("_total", "_since", "_lock")

    def __init__(self):
        self._total = 0.0
        self._since: Optional[float] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        with self._lock:
            self._since = time.monotonic()

    def stop(self) -> None:
        with self._lock:
            if self._since is not None:
                self._total += time.monotonic() - self._since
                self._since = None

    def value(self) -> float:
        with self._lock:
            t = self._total
            if self._since is not None:
                t += time.monotonic() - self._since
            return t


class PipelinedBlockEngine:
    """Double-buffered verify/commit pipeline for one ledger.

    `verify_fn(subs) -> pre` is stage A (`Network._verify_stage`);
    `commit_fn(subs, pre)` is stage B (`Network._commit_stage`). `depth`
    bounds the hand-off buffer (1 = classic double buffer: one block in
    verify, one queued/committing).
    """

    def __init__(self, verify_fn: Callable, commit_fn: Callable,
                 depth: int = 1):
        self._verify_fn = verify_fn
        self._commit_fn = commit_fn
        # serializes stage A (cut + verify + hand-off): cut order IS
        # commit order. RLock: a stage-A caller may re-enter via metrics
        # callbacks; reentrancy is harmless here.
        self.stage_lock = threading.RLock()
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._cond = threading.Condition()
        self._submitted = 0
        self._committed = 0
        self._commit_clock = BusyClock()
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()

    # ------------------------------------------------------------ threads

    def on_worker_thread(self) -> bool:
        """True when the calling thread IS the commit worker — a finality
        listener (re)submitting from inside stage B must drive its block
        inline (sequential path) or it would deadlock waiting on itself."""
        return threading.current_thread() is self._worker

    def _ensure_worker(self) -> None:
        with self._worker_lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._run, name="fts-block-commit", daemon=True
                )
                self._worker.start()

    # ------------------------------------------------------------ stage A

    def submit(self, subs: List) -> None:
        """Stage A for one cut block: batched device verify on the
        CALLING thread (overlapping the worker's commit of the previous
        block), then hand off for strictly-ordered commit. Must be called
        with `stage_lock` held. Blocks when the double buffer is full."""
        self._ensure_worker()
        t0 = time.monotonic()
        c0 = self._commit_clock.value()
        try:
            pre = self._verify_fn(subs)
        except Exception:
            # outside the pipeline's own degrade handling (which never
            # raises): downgrade to pre=None so stage B re-runs the
            # verification exactly as the sequential engine would —
            # including raising the same exception if it reproduces
            mx.counter("orderer.pipeline.verify_errors").inc()
            logger.exception(
                "pipeline: verify stage failed; commit stage will re-run"
            )
            pre = None
        if pre is not None:
            verify_wall_s = time.monotonic() - t0
            overlap_s = self._commit_clock.value() - c0
            pre["overlap_s"] = overlap_s
            pre["verify_wall_s"] = verify_wall_s
            mx.histogram("orderer.pipeline.overlap.seconds").observe(overlap_s)
            if verify_wall_s > 0:
                mx.gauge("orderer.pipeline.overlap_frac").set(
                    round(min(1.0, overlap_s / verify_wall_s), 6)
                )
        with self._cond:
            self._submitted += 1
            mx.gauge("orderer.pipeline.depth").set(
                self._submitted - self._committed
            )
        self._q.put((subs, pre))

    # ------------------------------------------------------------ stage B

    def _run(self) -> None:
        # profile role of this thread: every stage-B sample collapses
        # under `commit-worker` in the flamegraph export
        profiler.set_thread_role("commit-worker")
        while True:
            subs, pre = self._q.get()
            self._commit_clock.start()
            try:
                self._commit_fn(subs, pre)
            except Exception:
                # every submission was already resolved (the ledger's
                # stranded contract) and carries the exception for
                # `result()` to re-raise; the worker itself must survive
                # for the next block
                logger.exception("pipeline: block commit failed")
            finally:
                self._commit_clock.stop()
                mx.counter("orderer.pipeline.blocks").inc()
                with self._cond:
                    self._committed += 1
                    mx.gauge("orderer.pipeline.depth").set(
                        self._submitted - self._committed
                    )
                    self._cond.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Condition-variable wait (no spin) until every submitted block
        has committed; returns False on timeout."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._committed >= self._submitted, timeout
            )

    def inflight(self) -> int:
        with self._cond:
            return self._submitted - self._committed
