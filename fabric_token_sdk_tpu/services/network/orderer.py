"""Orderer: multi-tx block cutting + the batched block-validation plane.

Reference: Fabric's ordering service in front of the committing peers,
and the validator scope note in SURVEY §3 — "the validator runs batched
verification for a whole block". Submissions enter an ordering queue;
blocks are cut by size/linger policy; a block validation pipeline groups
same-shape zkatdlog transfers and verifies each group in ONE
`BatchedTransferVerifier` call over the compile-once stage tiles
(`ops/stages.py`), with the host `RequestValidator` as the fallback for
fabtoken transfers, issues, and shapes too rare to batch. The ledger
(`ledger.py`) then applies intra-block MVCC — a double-spend inside a
block invalidates the LATER tx, never the block — and commits the block
atomically with per-tx finality events.

Concurrency model: **group commit without a dedicated thread.**
Submitters enqueue, then race for the commit lock; the winner cuts a
block from everything pending (up to `max_block_txs`) and commits it;
losers either find their submission finalized by the winner's block or
cut the next block themselves. Sequential callers therefore see one-tx
blocks with zero added latency, while concurrent load batches naturally
— and deterministic multi-tx blocks are available via
`Network.submit_many` / `Orderer.flush`.

Pipelined mode (`pipeline.PipelinedBlockEngine`, default on, opt-out
`FTS_BLOCK_PIPELINE=0`): the driving thread runs only the CUT + batched
device verify of block N+1 while a commit worker finishes block N's
host-validate/WAL/merge — verify overlaps commit, height order is
preserved at the hand-off queue, and waiters park on their submission's
event (condition wait, no spinning on the commit lock).

Admission control: `BlockPolicy.queue_max` (`FTS_ORDERER_QUEUE_MAX`)
bounds the ordering queue; a full queue rejects the submission BEFORE it
enters ordering with a typed `Backpressure` error — retry-safe by
construction (nothing was enqueued, nothing can commit), carried over
the wire to remote submitters.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ...api.request import TokenRequest
from ...api.validator import SIG_AUDITOR, RequestValidator
from ...drivers import identity
from ...utils import faults, resilience, slo
from ...utils import metrics as mx
from ...utils.tracing import logger


def host_batch_enabled() -> bool:
    """Master switch for the batch-first HOST validation passes
    (`FTS_HOST_BATCH`, default on): block-level Fiat-Shamir + native
    batch multiply for signatures/proofs the device plane left behind,
    and the vectorized conservation pass. `0` restores the exact per-tx
    scalar path — the differential baseline. All host batch passes are
    degrade-only: they emit True-only verdicts, and every None/False row
    falls back to the scalar check that owns the precise error message."""
    return os.environ.get("FTS_HOST_BATCH", "1") != "0"


class Backpressure(RuntimeError):
    """The ordering queue is at `BlockPolicy.queue_max` capacity: the
    submission was rejected BEFORE entering ordering, so a retry (with
    backoff) is always safe — nothing was enqueued, nothing can commit,
    and the exactly-once contract is untouched. The remote server maps
    this to a typed wire error (`error_class: "Backpressure"`) and the
    remote client raises it back as this same type."""


@dataclass
class BlockPolicy:
    """Block-cut + batched-validation policy.

    `max_block_txs`  — hard cap on txs per block.
    `linger_s`       — how long a driving submitter waits for stragglers
                       before cutting (0 = cut whatever is pending now).
    `min_batch`      — smallest same-shape transfer group worth a device
                       batch call; smaller groups take the host path.
    `use_batched`    — master switch for the batched proof plane.
    `queue_max`      — admission control: ordering-queue depth beyond
                       which enqueues are rejected with `Backpressure`
                       (0 = unbounded, the default).
    `pipeline`       — verify/commit overlap via the pipelined block
                       engine (`FTS_BLOCK_PIPELINE=0` force-disables it
                       regardless of this field — the env kill switch
                       always restores the exact sequential path).
    `sign_batched`   — the batched SIGNATURE plane: True forces it on,
                       False off, None (default, env `auto`) engages it
                       only when the jax backend is a real accelerator —
                       on the CPU-emulated plane a device Schnorr row
                       costs ~3 orders of magnitude more than the host
                       check (measured ~0.4s/row vs ~0.6ms), the same
                       asymmetry the prove plane routes around.
    `sign_min_batch` — smallest per-block pk-obligation count worth the
                       one batched signature call; smaller blocks stay
                       on the host path.
    """

    max_block_txs: int = 64
    linger_s: float = 0.0
    min_batch: int = 2
    use_batched: bool = True
    queue_max: int = 0
    pipeline: bool = True
    sign_batched: Optional[bool] = None
    sign_min_batch: int = 4

    @classmethod
    def from_env(cls) -> "BlockPolicy":
        sign_env = os.environ.get("FTS_SIGN_BATCHED", "auto").lower()
        return cls(
            max_block_txs=int(os.environ.get("FTS_BLOCK_MAX_TXS", "64")),
            linger_s=float(os.environ.get("FTS_BLOCK_LINGER_S", "0")),
            min_batch=int(os.environ.get("FTS_BLOCK_MIN_BATCH", "2")),
            use_batched=os.environ.get("FTS_BLOCK_BATCHED", "1") != "0",
            queue_max=int(os.environ.get("FTS_ORDERER_QUEUE_MAX", "0")),
            pipeline=os.environ.get("FTS_BLOCK_PIPELINE", "1") != "0",
            sign_batched=(
                None if sign_env == "auto" else sign_env not in ("0", "false")
            ),
            sign_min_batch=int(os.environ.get("FTS_SIGN_MIN_BATCH", "4")),
        )


class Submission:
    """Handle for one ordered tx. `result()` drives block cutting until
    the tx is final — under group commit any waiter may end up committing
    the block that contains it. Carries the tx's trace context (captured
    at enqueue) so block-commit work done by WHICHEVER thread wins the
    commit race still lands in the submitting tx's trace."""

    __slots__ = ("request", "event", "_done", "_orderer", "trace",
                 "enqueued_at", "enqueued_unix", "_commit_error")

    def __init__(self, orderer: Optional["Orderer"], request: TokenRequest):
        self.request = request
        self.event = None  # FinalityEvent once resolved
        self._done = threading.Event()
        self._orderer = orderer
        self.trace = None  # TraceContext captured at enqueue
        self.enqueued_at = 0.0  # monotonic, for queue-wait timing
        self.enqueued_unix = 0.0
        # pipelined mode: a commit exception from the worker thread is
        # attached here (alongside the transient stranded event) so
        # `result()` re-raises it on the waiter's own stack — the same
        # contract the sequential engine gives its driving thread
        self._commit_error = None

    def done(self) -> bool:
        return self._done.is_set()

    def _resolve(self, event) -> None:
        if self._done.is_set():
            return  # idempotent: a submission resolves exactly once
        self.event = event
        self._done.set()
        if self._orderer is not None and self.enqueued_at:
            # live in-flight accounting + the submit→finality latency
            # histogram (always on: the ops plane reads its quantiles)
            self._orderer._mark_resolved()
            finality_s = max(0.0, time.monotonic() - self.enqueued_at)
            mx.histogram("network.submit_to_finality.seconds").observe(
                finality_s
            )
            # slow-tx exemplar ring (utils/slo.py): the K slowest txs
            # keep their trace ids so `ftstrace timeline` has a concrete
            # target after a soak
            slo.record_exemplar(
                finality_s, event.tx_id,
                self.trace.trace_id if self.trace else None,
            )
        mx.flight(
            "finality", trace=self.trace,
            tx=event.tx_id, status=event.status.value,
        )

    def result(self, timeout: Optional[float] = None):
        """Block (driving commits as needed) until this tx has finality.
        Re-raises a pipelined commit-worker exception on the waiter's own
        stack (the sequential engine raises it in the driving thread)."""
        if not self._done.is_set() and self._orderer is not None:
            self._orderer.drive(self, timeout)
        if self._commit_error is not None:
            raise self._commit_error
        return self.event


class Orderer:
    """Ordering queue + group-commit block cutter.

    `commit_block` is the ledger's callback: it takes the cut list of
    Submissions, validates + commits them as ONE block, and resolves each
    submission with its per-tx finality event.
    """

    def __init__(self, commit_block: Callable[[List[Submission]], None],
                 policy: Optional[BlockPolicy] = None):
        self._commit_block = commit_block
        self.policy = policy or BlockPolicy()
        self._pending: collections.deque = collections.deque()
        self._mutex = threading.Lock()  # guards _pending + _inflight
        # submissions enqueued but not yet resolved (queued OR inside a
        # block being committed) — the instantaneous signal `ops.health`
        # serves; queue-wait histograms only exist after commit
        self._inflight = 0
        # RLock: a finality listener that (re)submits must not deadlock
        self._commit_lock = threading.RLock()
        # pipelined block engine (ledger.Network wires it when the
        # policy + FTS_BLOCK_PIPELINE enable the verify/commit overlap)
        self._engine = None

    def set_engine(self, engine) -> None:
        self._engine = engine

    # ------------------------------------------------------------ queue

    def enqueue(self, request: TokenRequest) -> Submission:
        sub = Submission(self, request)
        sub.trace = mx.current_trace()
        sub.enqueued_at = time.monotonic()
        sub.enqueued_unix = time.time()
        with self._mutex:
            qmax = self.policy.queue_max
            if qmax > 0 and len(self._pending) >= qmax:
                # admission control: reject BEFORE ordering, so a retry
                # is always safe — nothing enqueued, nothing can commit
                depth = len(self._pending)
                mx.counter("orderer.backpressure.rejects").inc()
                mx.flight("backpressure", trace=sub.trace,
                          tx=request.anchor, depth=depth, max=qmax)
                raise Backpressure(
                    f"ordering queue at capacity ({depth}/{qmax}); "
                    f"tx {request.anchor} rejected before ordering — "
                    "retry with backoff"
                )
            self._pending.append(sub)
            self._inflight += 1
            mx.gauge("orderer.queue.depth").set(len(self._pending))
            mx.gauge("ledger.inflight").set(self._inflight)
        mx.counter("ledger.ordering.enqueued").inc()
        mx.flight("submit", trace=sub.trace, tx=request.anchor)
        return sub

    def pending(self) -> int:
        with self._mutex:
            return len(self._pending)

    def inflight(self) -> int:
        """Submissions enqueued but not yet resolved (includes the block
        currently being committed, unlike `pending`)."""
        with self._mutex:
            return self._inflight

    def _mark_resolved(self) -> None:
        with self._mutex:
            self._inflight -= 1
            mx.gauge("ledger.inflight").set(self._inflight)

    def _cut(self) -> List[Submission]:
        # fault point BEFORE the pop: an injected cut failure strands
        # nothing — every pending submission survives for the next drive
        faults.fire("orderer.cut")
        with self._mutex:
            n = min(len(self._pending), max(1, self.policy.max_block_txs))
            batch = [self._pending.popleft() for _ in range(n)]
            mx.gauge("orderer.queue.depth").set(len(self._pending))
        if batch:
            mx.flight("block.cut", txs=len(batch))
        return batch

    # ------------------------------------------------------------ drive

    def _pipelining(self) -> bool:
        """True when drives should route through the pipelined engine.
        The commit WORKER thread itself must never route back into the
        engine (a finality listener resubmitting from inside stage B
        would deadlock waiting on itself) — it drives inline instead."""
        return self._engine is not None and not self._engine.on_worker_thread()

    def flush(self) -> None:
        """Cut + commit blocks until the ordering queue is empty (and, in
        pipelined mode, every in-flight block has committed)."""
        if self._pipelining():
            engine = self._engine
            while True:
                with engine.stage_lock:
                    batch = self._cut()
                    if batch:
                        engine.submit(batch)
                if not batch:
                    break
            engine.drain()
            return
        while True:
            with self._commit_lock:
                batch = self._cut()
                if not batch:
                    return
                self._commit_block(batch)

    def drive(self, sub: Submission, timeout: Optional[float] = None):
        """Commit blocks until `sub` resolves; returns its finality event.

        The timeout is honored even while another thread holds the commit
        lock mid-block (timed acquire), not just between commit attempts.
        Waiters whose submission is in flight elsewhere (the pipelined
        worker, or another driver's block) park on the submission's event
        — a condition wait, never a spin on the commit lock.
        """
        deadline = None if timeout is None else time.monotonic() + timeout

        def _remaining() -> Optional[float]:
            return None if deadline is None else deadline - time.monotonic()

        def _expired() -> bool:
            return deadline is not None and time.monotonic() > deadline

        def _timeout_check() -> None:
            if not sub._done.is_set() and _expired():
                raise TimeoutError(
                    f"tx {sub.request.anchor} not ordered within {timeout}s"
                )

        while not sub._done.is_set():
            if self.policy.linger_s > 0:
                # a window for concurrent submitters to join this block
                sub._done.wait(self.policy.linger_s)
            if self._pipelining():
                engine = self._engine
                remaining = _remaining()
                if remaining is None:
                    acquired = engine.stage_lock.acquire()
                else:
                    acquired = remaining > 0 and engine.stage_lock.acquire(
                        timeout=remaining
                    )
                batch = None
                if acquired:
                    try:
                        if sub._done.is_set():
                            break
                        batch = self._cut()
                        if batch:
                            # stage A: device verify of this cut overlaps
                            # the worker's commit of the previous block
                            engine.submit(batch)
                    finally:
                        engine.stage_lock.release()
                if not batch and not sub._done.is_set():
                    # nothing left to cut: the sub is in flight in the
                    # engine (or another driver's block) — park on its
                    # event instead of re-racing the lock
                    sub._done.wait(_remaining())
                _timeout_check()
                continue
            if deadline is None:
                acquired = self._commit_lock.acquire()
            else:
                remaining = deadline - time.monotonic()
                acquired = remaining > 0 and self._commit_lock.acquire(
                    timeout=remaining
                )
            if acquired:
                try:
                    if sub._done.is_set():
                        break
                    batch = self._cut()
                    if batch:
                        self._commit_block(batch)
                finally:
                    self._commit_lock.release()
            _timeout_check()
        return sub.event


class BlockValidationPipeline:
    """The batched proof plane for one block.

    Phase 1 (plan): ask the driver for a batch plan per transfer record —
    `(shape_key, (input_points, output_points, proof_bytes))`, or None
    for host validation (fabtoken, malformed bytes, non-batchable kinds).

    Phase 2 (batched verify): group plans by shape; every group of at
    least `min_batch` rows goes through ONE `BatchedTransferVerifier`
    call (constant XLA program count regardless of shape/batch — see
    `crypto/batch.py`). Verdicts come back keyed
    `{tx_index: {transfer_index: bool}}`.

    Phase 3 is the ledger's: sequential per-tx `RequestValidator.validate`
    with MVCC over the block view; records with a verdict skip (True) or
    fail (False) the host proof check, everything else verifies on host.

    The SIGNATURE plane (`sign_verdicts`) is the same idea for the
    block's pk-kind signature obligations — owner/issuer/auditor Schnorr
    checks, collected across every tx and verified in ONE
    `BatchedSchnorrVerifier` call (no shape grouping needed: Schnorr
    rows are uniform). Non-pk identity kinds (nym, htlc) always stay
    host-verified; any device error degrades every row back to the host
    loop (`batch.sign.host_fallbacks`).

    `mesh` (a `parallel.sharding.MeshConfig`, default: the ambient
    `FTS_MESH_DEVICES`/`FTS_MESH_MP` env via the verifier's own
    resolution) shards each group's stage-tile composition over dp and
    its pairing products over dp x mp — the per-shard stage-tile
    dispatch. The degrade chain is sharded -> unsharded (inside the
    runners, `sharding.fallbacks`) -> host (here, `ledger.block.
    batch_errors`): accept/reject never depends on the mesh.

    Resilience (utils/resilience.py): each device dispatch runs under
    `bounded_call` with the plane's `FTS_DEVICE_DEADLINE_S` wall budget
    (a hung XLA call is abandoned at the deadline, its late result
    discarded, and the block falls to host), and each plane carries a
    circuit breaker — repeated failures/timeouts OPEN it so later
    blocks skip straight to host with no deadline paid, and a half-open
    probe after cooldown re-engages the device plane by itself.
    """

    def __init__(self, validator: RequestValidator, policy: BlockPolicy,
                 mesh=None):
        self.validator = validator
        self.policy = policy
        self.mesh = mesh
        # batched signature plane state: the verifier is built lazily on
        # first use (jax import); `sign_batched=None` (auto) resolves
        # once against the live backend. A construction failure records
        # into the `sign` circuit breaker (utils/resilience.py) — an
        # open breaker skips even obligation collection until its
        # cooldown expires and a half-open probe re-tries, so a
        # transient failure (one-off OOM) heals instead of disabling
        # device signatures for the process lifetime.
        self._sign_verifier = None
        self._sign_auto: Optional[bool] = None

    def proof_verdicts(
        self, requests: Sequence[TokenRequest],
        timings: Optional[dict] = None,
        host_verdicts: Optional[Dict[int, Dict[int, bool]]] = None,
    ) -> Dict[int, Dict[int, bool]]:
        """`timings`, when passed, is filled with the critical-path
        split of this call: `grouping_s` (plan + same-shape grouping)
        and `device_verify_s` (time inside batched verify calls,
        including failed ones that degraded to host).

        `host_verdicts`, when passed as a dict, receives True-only
        verdicts from the batch-first HOST pass over every row the
        device plane left behind (`_host_proof_batch`). They are kept
        OUT of the returned device verdicts so the
        `ledger.validate.batched/host` accounting (and every fallback
        counter) still describes the device plane alone; the ledger
        merges the two maps only when handing verdicts to the per-tx
        validator. `None` (the default) skips the host pass — direct
        callers see the exact device-only behavior."""
        if timings is None:
            timings = {}
        timings.setdefault("grouping_s", 0.0)
        timings.setdefault("device_verify_s", 0.0)
        if not self.policy.use_batched:
            return {}
        driver = self.validator.driver
        plan = getattr(driver, "transfer_batch_plan", None)
        if plan is None:
            return {}
        t0 = time.monotonic()
        groups: Dict[tuple, List[Tuple[int, int, tuple]]] = {}
        for ti, req in enumerate(requests):
            for ri, rec in enumerate(req.transfers):
                p = plan(rec.action)
                if p is None:
                    continue
                shape, row = p
                groups.setdefault(shape, []).append((ti, ri, row))
        timings["grouping_s"] = time.monotonic() - t0

        verdicts: Dict[int, Dict[int, bool]] = {}
        verifier = None
        # rows the device plane leaves behind (small groups, open
        # breaker, failed/timed-out dispatches, no device plane at all):
        # the batch-first HOST pass below still verifies them in one
        # native multiexp + one block-level Fiat-Shamir call before the
        # per-tx scalar loop sees them
        leftovers: List[Tuple[int, int, tuple]] = []
        device_dead = False
        brk = resilience.breaker("verify")
        deadline_s = resilience.device_deadline_s("verify")
        for shape, rows in sorted(groups.items()):
            if device_dead or len(rows) < max(1, self.policy.min_batch):
                leftovers.extend(rows)
                continue
            if not brk.allow():
                # open breaker: instant host fallback — no deadline paid,
                # no worker stacked onto a sick backend. The host plane
                # re-verifies these rows with verdicts unchanged.
                mx.flight(
                    "verify.host_fallback", shape=str(shape),
                    txs=len(rows), reason="breaker_open",
                )
                leftovers.extend(rows)
                continue
            if verifier is None:
                try:
                    try:
                        verifier = driver.batch_verifier(mesh=self.mesh)
                    except TypeError:
                        # SPI compat: a custom driver predating the mesh
                        # kwarg still serves the unsharded plane
                        verifier = driver.batch_verifier()
                except Exception:
                    # construction failures (device stack unavailable,
                    # OOM building tables) degrade to host validation,
                    # same as verify failures — never fail a block
                    brk.record_failure()
                    mx.counter("ledger.block.batch_errors").inc()
                    mx.flight("verify.host_fallback", reason="construct")
                    device_dead = True
                    leftovers.extend(rows)
                    continue
                if verifier is None:
                    # the driver HAS no batched plane: neither success
                    # nor failure — release the admission (else a
                    # half-open probe would stay consumed forever)
                    brk.cancel_probe()
                    device_dead = True
                    leftovers.extend(rows)
                    continue

            def _device_verify(rows=rows):
                # device-plane fault point: firing here (INSIDE the
                # bounded worker, so a `hang` kind is governed by the
                # deadline) exercises the degrade-to-host path below
                faults.fire("batch.verify")
                return verifier.verify([row for _, _, row in rows])

            tg = time.monotonic()
            try:
                with mx.span(
                    "ledger.block.batch_verify", shape=str(shape), txs=len(rows)
                ):
                    ok = resilience.bounded_call(
                        _device_verify, deadline_s, plane="verify"
                    )
            except resilience.DeviceTimeout:
                # the dispatch outlived its wall budget: abandon it (the
                # straggler's late result is discarded by the supervisor)
                # and fall to host — the block must not stall
                brk.record_failure(timeout=True)
                mx.counter("ledger.block.batch_errors").inc()
                mx.flight(
                    "verify.host_fallback", shape=str(shape),
                    txs=len(rows), reason="timeout",
                )
                leftovers.extend(rows)
                continue
            except Exception:
                # the host plane re-verifies these rows; never fail a block
                # on a device-plane error
                brk.record_failure()
                mx.counter("ledger.block.batch_errors").inc()
                mx.flight(
                    "verify.host_fallback", shape=str(shape), txs=len(rows)
                )
                leftovers.extend(rows)
                continue
            finally:
                timings["device_verify_s"] += time.monotonic() - tg
            brk.record_success()
            mx.flight(
                "verify.device", shape=str(shape), txs=len(rows),
                ok=int(sum(1 for g in ok if g)),
            )
            for (ti, ri, _), good in zip(rows, ok):
                verdicts.setdefault(ti, {})[ri] = bool(good)
        if host_verdicts is not None:
            self._host_proof_batch(leftovers, host_verdicts, timings)
        return verdicts

    def _host_proof_batch(
        self, rows: List[Tuple[int, int, tuple]],
        verdicts: Dict[int, Dict[int, bool]], timings: dict,
    ) -> None:
        """Batch-first HOST pass over transfer rows the device plane left
        behind: the driver's `transfer_host_batch` hook recomputes every
        proof's commitments in one native multiexp call and derives all
        Fiat-Shamir challenges in one block-level sha256 batch
        (`hostmath.hash_to_zr_many`). True-only: a True verdict skips the
        per-tx scalar proof check; None/False rows (undecidable shapes,
        malformed bytes, failed proofs) fall through to the scalar path
        that owns the precise error. An exception here degrades to the
        scalar path wholesale — accept/reject can never depend on it."""
        timings.setdefault("host_proof_batch_s", 0.0)
        if not rows or not host_batch_enabled():
            return
        hook = getattr(self.validator.driver, "transfer_host_batch", None)
        if hook is None:
            return
        from .pipeline import host_map

        t0 = time.monotonic()
        try:
            try:
                oks = host_map(hook, [row for _, _, row in rows])
            except Exception:
                logger.exception(
                    "host proof batch failed; scalar path verifies"
                )
                return
            batched = 0
            for (ti, ri, _), good in zip(rows, oks):
                if good is True:
                    batched += 1
                    verdicts.setdefault(ti, {})[ri] = True
            if batched:
                mx.counter("hostbatch.proof.rows").inc(batched)
                mx.flight(
                    "verify.host_batch", rows=len(rows), verified=batched
                )
        finally:
            timings["host_proof_batch_s"] += time.monotonic() - t0

    # ------------------------------------------------------ signature plane

    def sign_enabled(self) -> bool:
        """Whether pk-kind signature obligations route to the batched
        device plane. `sign_batched=None` (auto) resolves ONCE against
        the live jax backend: device only on a real accelerator — and
        only if something else already imported jax (this resolver must
        never be the call that initializes a backend on the block-commit
        path; a fabtoken-only node may have no device stack at all)."""
        if self.policy.sign_batched is not None:
            return self.policy.sign_batched
        if self._sign_auto is None:
            import sys

            jax = sys.modules.get("jax")
            if jax is None:
                # NOT latched: jax may arrive later (e.g. the proof
                # plane's first zk block) and the answer would change
                return False
            try:
                self._sign_auto = jax.default_backend() != "cpu"
            except Exception:
                self._sign_auto = False
        return self._sign_auto

    def _collect_sign_obligations(self, requests: Sequence[TokenRequest]):
        """Walk a block's requests and split every signature obligation
        into batched rows (pk-kind identities from the shared identity
        cache) and a host count (non-pk kinds, unplannable records,
        empty/missing signatures — all verified by the host loop
        unchanged). Rows are `(pk_point, message, sig_raw)`; keys are
        `(tx_index, obligation_key, identity_bytes)`."""
        rows, keys, host = [], [], 0
        auditor = self.validator.auditor
        auditor_pk = identity.public_key(auditor) if auditor else None
        driver = self.validator.driver
        issue_plan = getattr(driver, "issue_sign_plan", None)
        transfer_plan = getattr(driver, "transfer_sign_plan", None)
        for ti, req in enumerate(requests):
            # the sign payload is marshalled lazily: a request with no
            # collectable pk obligation never pays the serialization
            # (the host validate pass re-marshals its own copy anyway)
            payload = None

            def _payload():
                nonlocal payload
                if payload is None:
                    payload = req.marshal_to_sign()
                return payload

            if auditor and req.auditor_signature:
                if auditor_pk is not None:
                    rows.append(
                        (auditor_pk.point, req.marshal_to_audit(),
                         req.auditor_signature)
                    )
                    keys.append((ti, SIG_AUDITOR, auditor))
                else:
                    host += 1
            for ii, rec in enumerate(req.issues):
                if not rec.signature or issue_plan is None:
                    continue  # no obligation / legacy driver: host decides
                ident = issue_plan(rec.action)
                if ident is None:
                    continue  # anonymous or unplannable: nothing to check
                pk = identity.public_key(ident)
                if pk is None:
                    host += 1
                    continue
                rows.append((pk.point, _payload(), rec.signature))
                keys.append((ti, ("issue", ii), ident))
            for ri, rec in enumerate(req.transfers):
                if transfer_plan is None:
                    continue
                owners = transfer_plan(rec.action)
                if owners is None or len(owners) != len(rec.signatures):
                    # unplannable / signature-count mismatch (the host
                    # check rejects the latter with its precise error)
                    host += len(rec.signatures)
                    continue
                for si, (ident, sig) in enumerate(zip(owners, rec.signatures)):
                    pk = identity.public_key(ident)
                    if pk is None:
                        host += 1  # nym/htlc/malformed: host-verified
                        continue
                    rows.append((pk.point, _payload(), sig))
                    keys.append((ti, ("transfer", ri, si), ident))
        return rows, keys, host

    def sign_verdicts(
        self, requests: Sequence[TokenRequest],
        timings: Optional[dict] = None,
    ) -> Dict[int, Dict[tuple, tuple]]:
        """One batched `BatchedSchnorrVerifier` pass over ALL pk-kind
        signature obligations of a block. Returns
        `{tx_index: {obligation_key: (identity_bytes, bool)}}` for
        `RequestValidator.validate(sig_verified=...)`. The degrade chain
        is the proof plane's: any device error, deadline timeout, or
        verifier construction failure drops every row to the host loop
        (`batch.sign.host_fallbacks`) and records into the `sign`
        circuit breaker — accept/reject can never depend on this plane,
        and an OPEN breaker skips even the obligation collection until
        a half-open probe heals it (replacing the old process-lifetime
        construction-failure latch). `timings` gains `sign_verify_s`
        (time inside the batched call, including failed ones)."""
        if timings is None:
            timings = {}
        timings.setdefault("sign_verify_s", 0.0)
        if not self.sign_enabled():
            # device plane off (CPU auto / forced host): the batch-first
            # HOST pass still folds every pk obligation of the block into
            # one native multiexp + one Fiat-Shamir sha256 batch
            return self._host_sign_batch(requests, timings)
        brk = resilience.breaker("sign")
        if brk.rejecting():
            # open breaker (cooldown running): skip even the collection —
            # later blocks must not pay per-block marshal/parse work
            # against a plane known sick; the half-open probe after
            # cooldown re-engages it off this fast path
            return {}
        rows, keys, host = self._collect_sign_obligations(requests)
        if host:
            mx.counter("batch.sign.host").inc(host)
        if not rows:
            return {}
        if len(rows) < max(1, self.policy.sign_min_batch):
            mx.counter("batch.sign.host").inc(len(rows))
            return {}
        if not brk.allow():
            # raced another thread's half-open probe: host-verify this
            # block rather than stacking a second dispatch on the probe
            mx.counter("batch.sign.host").inc(len(rows))
            mx.flight(
                "sign.host_fallback", rows=len(rows), reason="breaker_open"
            )
            return {}
        if self._sign_verifier is None:
            try:
                from ...crypto.batch_sign import BatchedSchnorrVerifier

                self._sign_verifier = BatchedSchnorrVerifier(mesh=self.mesh)
            except Exception:
                # one strike, like the latch this breaker replaced: a
                # construction failure is structural (import/OOM) and
                # per-block retries only re-pay marshal/import/log cost
                # — trip immediately; the half-open probe still heals a
                # transient one after cooldown
                brk.record_failure(trip_now=True)
                mx.counter("batch.sign.host_fallbacks").inc(len(rows))
                mx.flight("sign.host_fallback", reason="construct")
                logger.exception(
                    "sign plane: verifier construction failed; block "
                    "signatures host-verify (breaker heals via probe)"
                )
                return {}

        def _device_sign():
            # device-plane fault point: inside the bounded worker, so a
            # `hang` kind is governed by the deadline, never the block
            faults.fire("batch.sign")
            return self._sign_verifier.verify(rows)

        t0 = time.monotonic()
        try:
            with mx.span("ledger.block.batch_sign", rows=len(rows)):
                verdicts = resilience.bounded_call(
                    _device_sign, resilience.device_deadline_s("sign"),
                    plane="sign",
                )
        except resilience.DeviceTimeout:
            brk.record_failure(timeout=True)
            mx.counter("batch.sign.host_fallbacks").inc(len(rows))
            mx.flight("sign.host_fallback", rows=len(rows), reason="timeout")
            logger.warning(
                "sign plane: batched verify timed out; block signatures "
                "host-verify (worker abandoned, result discarded)"
            )
            return {}
        except Exception:
            brk.record_failure()
            mx.counter("batch.sign.host_fallbacks").inc(len(rows))
            mx.flight("sign.host_fallback", rows=len(rows))
            logger.exception(
                "sign plane: batched verify failed; block signatures "
                "host-verify"
            )
            return {}
        finally:
            timings["sign_verify_s"] += time.monotonic() - t0
        brk.record_success()
        out: Dict[int, Dict[tuple, tuple]] = {}
        device = 0
        for (ti, okey, ident), v in zip(keys, verdicts):
            if v is None:
                # the verifier could not parse this signature blob: the
                # host loop re-verifies and reports the precise error
                mx.counter("batch.sign.host").inc()
                continue
            device += 1
            out.setdefault(ti, {})[okey] = (ident, bool(v))
        mx.flight(
            "sign.device", rows=len(rows), device=device,
            ok=sum(1 for v in verdicts if v),
        )
        return out

    def _host_sign_batch(
        self, requests: Sequence[TokenRequest], timings: dict,
    ) -> Dict[int, Dict[tuple, tuple]]:
        """Batch-first HOST signature pass — the block's pk obligations
        verified via `crypto.sign.verify_many`: ONE native bn254 batch
        multiexp recomputes every Schnorr commitment and ONE block-level
        sha256 batch (`hostmath.hash_to_zr_many`) derives every
        Fiat-Shamir challenge, fanned over the commit-host worker pool
        (`FTS_COMMIT_WORKERS`). True-only verdicts: rows that fail or
        don't parse get NO verdict and fall to the per-obligation scalar
        loop, which owns the precise error message — accept/reject can
        never depend on this pass. Shares the device plane's obligation
        collector, so statement pinning (`identity_bytes` echoed with
        each verdict) is identical."""
        timings.setdefault("host_sign_batch_s", 0.0)
        if not host_batch_enabled():
            return {}
        t0 = time.monotonic()
        try:
            rows, keys, host = self._collect_sign_obligations(requests)
            if host:
                mx.counter("batch.sign.host").inc(host)
            if not rows:
                return {}
            try:
                from ...crypto import sign as sign_mod
                from .pipeline import host_map

                oks = host_map(sign_mod.verify_many, rows)
            except Exception:
                mx.counter("batch.sign.host").inc(len(rows))
                logger.exception(
                    "host sign batch failed; block signatures scalar-verify"
                )
                return {}
            out: Dict[int, Dict[tuple, tuple]] = {}
            batched = 0
            for (ti, okey, ident), v in zip(keys, oks):
                if v is not True:
                    # None (unparseable blob) or False (bad signature):
                    # the scalar loop re-verifies and reports precisely
                    mx.counter("batch.sign.host").inc()
                    continue
                batched += 1
                out.setdefault(ti, {})[okey] = (ident, True)
            if batched:
                mx.counter("hostbatch.sign.rows").inc(batched)
                mx.flight(
                    "sign.host_batch", rows=len(rows), verified=batched
                )
            return out
        finally:
            timings["host_sign_batch_s"] += time.monotonic() - t0

    # ------------------------------------------------------ conservation

    def conservation_verdicts(
        self, requests: Sequence[TokenRequest],
        timings: Optional[dict] = None,
    ) -> Dict[int, Dict[int, bool]]:
        """Block-level vectorized conservation/type checks: every
        transfer action's tokens decode into one flat column and the
        per-action verdicts fall out of segment sums
        (`driver.validate_conservation_many`). True-only, keyed
        `{tx_index: {record_index: True}}` for
        `RequestValidator.validate(conservation=...)` — an action with
        no verdict runs the full scalar arithmetic, so the pass can only
        make blocks faster, never change accept/reject."""
        if timings is None:
            timings = {}
        timings.setdefault("host_conservation_batch_s", 0.0)
        if not host_batch_enabled():
            return {}
        hook = getattr(
            self.validator.driver, "validate_conservation_many", None
        )
        if hook is None:
            return {}
        t0 = time.monotonic()
        try:
            actions, keys = [], []
            for ti, req in enumerate(requests):
                for ri, rec in enumerate(req.transfers):
                    actions.append(rec.action)
                    keys.append((ti, ri))
            if not actions:
                return {}
            try:
                oks = hook(actions)
            except Exception:
                logger.exception(
                    "conservation batch failed; scalar checks run per tx"
                )
                return {}
            out: Dict[int, Dict[int, bool]] = {}
            batched = 0
            for (ti, ri), good in zip(keys, oks):
                if good is True:
                    batched += 1
                    out.setdefault(ti, {})[ri] = True
            if batched:
                mx.counter("hostbatch.conservation.rows").inc(batched)
            return out
        finally:
            timings["host_conservation_batch_s"] += time.monotonic() - t0
