"""Orderer: multi-tx block cutting + the batched block-validation plane.

Reference: Fabric's ordering service in front of the committing peers,
and the validator scope note in SURVEY §3 — "the validator runs batched
verification for a whole block". Submissions enter an ordering queue;
blocks are cut by size/linger policy; a block validation pipeline groups
same-shape zkatdlog transfers and verifies each group in ONE
`BatchedTransferVerifier` call over the compile-once stage tiles
(`ops/stages.py`), with the host `RequestValidator` as the fallback for
fabtoken transfers, issues, and shapes too rare to batch. The ledger
(`ledger.py`) then applies intra-block MVCC — a double-spend inside a
block invalidates the LATER tx, never the block — and commits the block
atomically with per-tx finality events.

Concurrency model: **group commit without a dedicated thread.**
Submitters enqueue, then race for the commit lock; the winner cuts a
block from everything pending (up to `max_block_txs`) and commits it;
losers either find their submission finalized by the winner's block or
cut the next block themselves. Sequential callers therefore see one-tx
blocks with zero added latency, while concurrent load batches naturally
— and deterministic multi-tx blocks are available via
`Network.submit_many` / `Orderer.flush`.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ...api.request import TokenRequest
from ...api.validator import RequestValidator
from ...utils import faults
from ...utils import metrics as mx


@dataclass
class BlockPolicy:
    """Block-cut + batched-validation policy.

    `max_block_txs`  — hard cap on txs per block.
    `linger_s`       — how long a driving submitter waits for stragglers
                       before cutting (0 = cut whatever is pending now).
    `min_batch`      — smallest same-shape transfer group worth a device
                       batch call; smaller groups take the host path.
    `use_batched`    — master switch for the batched proof plane.
    """

    max_block_txs: int = 64
    linger_s: float = 0.0
    min_batch: int = 2
    use_batched: bool = True

    @classmethod
    def from_env(cls) -> "BlockPolicy":
        return cls(
            max_block_txs=int(os.environ.get("FTS_BLOCK_MAX_TXS", "64")),
            linger_s=float(os.environ.get("FTS_BLOCK_LINGER_S", "0")),
            min_batch=int(os.environ.get("FTS_BLOCK_MIN_BATCH", "2")),
            use_batched=os.environ.get("FTS_BLOCK_BATCHED", "1") != "0",
        )


class Submission:
    """Handle for one ordered tx. `result()` drives block cutting until
    the tx is final — under group commit any waiter may end up committing
    the block that contains it. Carries the tx's trace context (captured
    at enqueue) so block-commit work done by WHICHEVER thread wins the
    commit race still lands in the submitting tx's trace."""

    __slots__ = ("request", "event", "_done", "_orderer", "trace",
                 "enqueued_at", "enqueued_unix")

    def __init__(self, orderer: Optional["Orderer"], request: TokenRequest):
        self.request = request
        self.event = None  # FinalityEvent once resolved
        self._done = threading.Event()
        self._orderer = orderer
        self.trace = None  # TraceContext captured at enqueue
        self.enqueued_at = 0.0  # monotonic, for queue-wait timing
        self.enqueued_unix = 0.0

    def done(self) -> bool:
        return self._done.is_set()

    def _resolve(self, event) -> None:
        if self._done.is_set():
            return  # idempotent: a submission resolves exactly once
        self.event = event
        self._done.set()
        if self._orderer is not None and self.enqueued_at:
            # live in-flight accounting + the submit→finality latency
            # histogram (always on: the ops plane reads its quantiles)
            self._orderer._mark_resolved()
            mx.histogram("network.submit_to_finality.seconds").observe(
                max(0.0, time.monotonic() - self.enqueued_at)
            )
        mx.flight(
            "finality", trace=self.trace,
            tx=event.tx_id, status=event.status.value,
        )

    def result(self, timeout: Optional[float] = None):
        """Block (driving commits as needed) until this tx has finality."""
        if self._done.is_set() or self._orderer is None:
            return self.event
        return self._orderer.drive(self, timeout)


class Orderer:
    """Ordering queue + group-commit block cutter.

    `commit_block` is the ledger's callback: it takes the cut list of
    Submissions, validates + commits them as ONE block, and resolves each
    submission with its per-tx finality event.
    """

    def __init__(self, commit_block: Callable[[List[Submission]], None],
                 policy: Optional[BlockPolicy] = None):
        self._commit_block = commit_block
        self.policy = policy or BlockPolicy()
        self._pending: collections.deque = collections.deque()
        self._mutex = threading.Lock()  # guards _pending + _inflight
        # submissions enqueued but not yet resolved (queued OR inside a
        # block being committed) — the instantaneous signal `ops.health`
        # serves; queue-wait histograms only exist after commit
        self._inflight = 0
        # RLock: a finality listener that (re)submits must not deadlock
        self._commit_lock = threading.RLock()

    # ------------------------------------------------------------ queue

    def enqueue(self, request: TokenRequest) -> Submission:
        sub = Submission(self, request)
        sub.trace = mx.current_trace()
        sub.enqueued_at = time.monotonic()
        sub.enqueued_unix = time.time()
        with self._mutex:
            self._pending.append(sub)
            self._inflight += 1
            mx.gauge("orderer.queue.depth").set(len(self._pending))
            mx.gauge("ledger.inflight").set(self._inflight)
        mx.counter("ledger.ordering.enqueued").inc()
        mx.flight("submit", trace=sub.trace, tx=request.anchor)
        return sub

    def pending(self) -> int:
        with self._mutex:
            return len(self._pending)

    def inflight(self) -> int:
        """Submissions enqueued but not yet resolved (includes the block
        currently being committed, unlike `pending`)."""
        with self._mutex:
            return self._inflight

    def _mark_resolved(self) -> None:
        with self._mutex:
            self._inflight -= 1
            mx.gauge("ledger.inflight").set(self._inflight)

    def _cut(self) -> List[Submission]:
        # fault point BEFORE the pop: an injected cut failure strands
        # nothing — every pending submission survives for the next drive
        faults.fire("orderer.cut")
        with self._mutex:
            n = min(len(self._pending), max(1, self.policy.max_block_txs))
            batch = [self._pending.popleft() for _ in range(n)]
            mx.gauge("orderer.queue.depth").set(len(self._pending))
        if batch:
            mx.flight("block.cut", txs=len(batch))
        return batch

    # ------------------------------------------------------------ drive

    def flush(self) -> None:
        """Cut + commit blocks until the ordering queue is empty."""
        while True:
            with self._commit_lock:
                batch = self._cut()
                if not batch:
                    return
                self._commit_block(batch)

    def drive(self, sub: Submission, timeout: Optional[float] = None):
        """Commit blocks until `sub` resolves; returns its finality event.

        The timeout is honored even while another thread holds the commit
        lock mid-block (timed acquire), not just between commit attempts.
        """
        deadline = None if timeout is None else time.monotonic() + timeout

        def _expired() -> bool:
            return deadline is not None and time.monotonic() > deadline

        while not sub._done.is_set():
            if self.policy.linger_s > 0:
                # a window for concurrent submitters to join this block
                sub._done.wait(self.policy.linger_s)
            if deadline is None:
                acquired = self._commit_lock.acquire()
            else:
                remaining = deadline - time.monotonic()
                acquired = remaining > 0 and self._commit_lock.acquire(
                    timeout=remaining
                )
            if acquired:
                try:
                    if sub._done.is_set():
                        break
                    batch = self._cut()
                    if batch:
                        self._commit_block(batch)
                finally:
                    self._commit_lock.release()
            if not sub._done.is_set() and _expired():
                raise TimeoutError(
                    f"tx {sub.request.anchor} not ordered within {timeout}s"
                )
        return sub.event


class BlockValidationPipeline:
    """The batched proof plane for one block.

    Phase 1 (plan): ask the driver for a batch plan per transfer record —
    `(shape_key, (input_points, output_points, proof_bytes))`, or None
    for host validation (fabtoken, malformed bytes, non-batchable kinds).

    Phase 2 (batched verify): group plans by shape; every group of at
    least `min_batch` rows goes through ONE `BatchedTransferVerifier`
    call (constant XLA program count regardless of shape/batch — see
    `crypto/batch.py`). Verdicts come back keyed
    `{tx_index: {transfer_index: bool}}`.

    Phase 3 is the ledger's: sequential per-tx `RequestValidator.validate`
    with MVCC over the block view; records with a verdict skip (True) or
    fail (False) the host proof check, everything else verifies on host.

    `mesh` (a `parallel.sharding.MeshConfig`, default: the ambient
    `FTS_MESH_DEVICES`/`FTS_MESH_MP` env via the verifier's own
    resolution) shards each group's stage-tile composition over dp and
    its pairing products over dp x mp — the per-shard stage-tile
    dispatch. The degrade chain is sharded -> unsharded (inside the
    runners, `sharding.fallbacks`) -> host (here, `ledger.block.
    batch_errors`): accept/reject never depends on the mesh.
    """

    def __init__(self, validator: RequestValidator, policy: BlockPolicy,
                 mesh=None):
        self.validator = validator
        self.policy = policy
        self.mesh = mesh

    def proof_verdicts(
        self, requests: Sequence[TokenRequest],
        timings: Optional[dict] = None,
    ) -> Dict[int, Dict[int, bool]]:
        """`timings`, when passed, is filled with the critical-path
        split of this call: `grouping_s` (plan + same-shape grouping)
        and `device_verify_s` (time inside batched verify calls,
        including failed ones that degraded to host)."""
        if timings is None:
            timings = {}
        timings.setdefault("grouping_s", 0.0)
        timings.setdefault("device_verify_s", 0.0)
        if not self.policy.use_batched:
            return {}
        driver = self.validator.driver
        plan = getattr(driver, "transfer_batch_plan", None)
        if plan is None:
            return {}
        t0 = time.monotonic()
        groups: Dict[tuple, List[Tuple[int, int, tuple]]] = {}
        for ti, req in enumerate(requests):
            for ri, rec in enumerate(req.transfers):
                p = plan(rec.action)
                if p is None:
                    continue
                shape, row = p
                groups.setdefault(shape, []).append((ti, ri, row))
        timings["grouping_s"] = time.monotonic() - t0

        verdicts: Dict[int, Dict[int, bool]] = {}
        verifier = None
        for shape, rows in sorted(groups.items()):
            if len(rows) < max(1, self.policy.min_batch):
                continue
            if verifier is None:
                try:
                    try:
                        verifier = driver.batch_verifier(mesh=self.mesh)
                    except TypeError:
                        # SPI compat: a custom driver predating the mesh
                        # kwarg still serves the unsharded plane
                        verifier = driver.batch_verifier()
                except Exception:
                    # construction failures (device stack unavailable,
                    # OOM building tables) degrade to host validation,
                    # same as verify failures — never fail a block
                    mx.counter("ledger.block.batch_errors").inc()
                    mx.flight("verify.host_fallback", reason="construct")
                    return verdicts
                if verifier is None:
                    return verdicts
            tg = time.monotonic()
            try:
                with mx.span(
                    "ledger.block.batch_verify", shape=str(shape), txs=len(rows)
                ):
                    # device-plane fault point: firing here exercises the
                    # degrade-to-host path below (verdicts must not change)
                    faults.fire("batch.verify")
                    ok = verifier.verify([row for _, _, row in rows])
            except Exception:
                # the host plane re-verifies these rows; never fail a block
                # on a device-plane error
                mx.counter("ledger.block.batch_errors").inc()
                mx.flight(
                    "verify.host_fallback", shape=str(shape), txs=len(rows)
                )
                continue
            finally:
                timings["device_verify_s"] += time.monotonic() - tg
            mx.flight(
                "verify.device", shape=str(shape), txs=len(rows),
                ok=int(sum(1 for g in ok if g)),
            )
            for (ti, ri, _), good in zip(rows, ok):
                verdicts.setdefault(ti, {})[ri] = bool(good)
        return verdicts
