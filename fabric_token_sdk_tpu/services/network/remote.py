"""Multi-process network: a fault-tolerant TCP ledger node + thin client.

Reference parity: the SDK talks to a Fabric network over gRPC
(`token/services/network/fabric`); here a JSON-over-TCP node hosts the
MVCC ledger + validator, and `RemoteNetwork` exposes the same API surface
as the in-process `Network` so parties can live in separate processes.

Fault tolerance (client side):

* **Pooled persistent connection** with automatic reconnect — one socket
  per `RemoteNetwork`, re-dialed lazily after any transport failure (a
  restarted server is picked up transparently).
* **Retries with exponential backoff + jitter** for the idempotent ops
  (`status` / `exists` / `resolve` / `height`), counted under
  `remote.retry.*`.
* **Exactly-once submit**: a connection that dies with a submit in
  flight may or may not have committed server-side. The client NEVER
  resubmits blindly — it consults `status(tx_id)` first and adopts the
  recorded verdict if one exists (`remote.submit.recovered`); only a
  tx the ledger has never seen is resubmitted, and the ledger's
  in-flight dedup is the server half of the guarantee.
* **Typed remote errors**: a server-side failure arrives as
  `RemoteError` carrying the server's exception class
  (`.error_class`), not a blanket "malformed request".

Server side: per-op dispatch errors are logged with traceback and
returned typed (`remote.dispatch.errors.<op>`); inbound frames are
capped (`FTS_REMOTE_MAX_FRAME`, default 16 MiB) so a corrupt or hostile
length prefix can never force an arbitrary-size allocation.

Live ops plane: the node answers side-effect-free introspection RPCs —
`ops.health` (uptime, height, WAL state, queue depth, in-flight txs,
last-block critical-path breakdown), `ops.metrics` (a full
`Registry.snapshot()` over the wire, latency quantiles included) and
`ops.flight` (live flight-ring tail). Each runs on its own handler
thread and never takes the orderer's commit lock, so a minutes-long
device verify cannot block a health probe; clients route them through
`_call_idempotent` (read-only, hence retry/backoff safe). A stopping
node answers in-flight probes with a typed `NodeStopped` error instead
of a silently dropped connection.

Fault injection: the client fires the `remote.send` / `remote.recv`
fault points around its frame I/O (`utils/faults.py`), which is how the
chaos suite proves the retry and exactly-once paths.
"""

from __future__ import annotations

import json
import os
import random
import socket
import socketserver
import threading
import time
from typing import Callable, List, Optional, Tuple

from ...api.driver import ValidationError
from ...api.request import TokenRequest
from ...api.validator import RequestValidator
from ...models.token import ID
from ...utils import faults, profiler
from ...utils import metrics as mx
from ...utils.tracing import logger
from .ledger import FinalityEvent, Network, TxStatus
from .orderer import Backpressure, Submission
from .replication import NotLeader, StaleEpoch

DEFAULT_MAX_FRAME = 16 * 1024 * 1024  # 16 MiB


def _max_frame() -> int:
    return int(os.environ.get("FTS_REMOTE_MAX_FRAME", str(DEFAULT_MAX_FRAME)))


class FrameTooLarge(ValueError):
    """A length prefix exceeded the frame cap (corrupt or hostile)."""


class RemoteError(RuntimeError):
    """A server-side failure, typed: `error_class` is the exception class
    name the server hit (e.g. "KeyError"), never a blanket message."""

    def __init__(self, message: str, error_class: Optional[str] = None):
        super().__init__(message)
        self.error_class = error_class


def _parse_endpoints(spec: str) -> List[Tuple[str, int]]:
    """Parse `FTS_REMOTE_ENDPOINTS="host:port,host:port"` — the client's
    view of a replicated cluster (order = initial preference)."""
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _sep, port = part.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"FTS_REMOTE_ENDPOINTS entry {part!r} is not host:port"
            )
        out.append((host, int(port)))
    return out


def _send_msg(sock: socket.socket, obj: dict) -> None:
    raw = json.dumps(obj).encode()
    sock.sendall(len(raw).to_bytes(4, "big") + raw)


def _recv_msg(sock: socket.socket, max_frame: Optional[int] = None) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    n = int.from_bytes(hdr, "big")
    cap = max_frame if max_frame is not None else _max_frame()
    if n > cap:
        # reject BEFORE allocating: a corrupt/hostile prefix must not
        # drive an arbitrary-size allocation
        raise FrameTooLarge(f"frame of {n} bytes exceeds cap of {cap}")
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return json.loads(buf.decode())


class LedgerServer:
    """Hosts a Network (orderer + endorser + committer) over TCP.

    Pass `network=` to serve a pre-built ledger (a `Network.restore` or
    `Network.recover` result — node-restart parity), or `validator=` to
    build a fresh one; `wal_path` makes the fresh ledger journaled.
    `allow_reuse_address` lets a restarted node rebind its old port.
    """

    def __init__(self, validator: Optional[RequestValidator] = None,
                 host: str = "127.0.0.1", port: int = 0, policy=None,
                 network: Optional[Network] = None,
                 wal_path: Optional[str] = None):
        # concurrent client submits land in the node's ordering queue and
        # group-commit into shared blocks (policy: orderer.BlockPolicy)
        if network is None:
            if validator is None:
                raise ValueError("LedgerServer needs a validator or a network")
            network = Network(validator, policy=policy, wal_path=wal_path)
        self.network = network
        self._started_unix = time.time()
        self._stopping = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # profile role: connection threads collapse under
                # `remote-handler` in the flamegraph export
                profiler.set_thread_role("remote-handler")
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    self._serve()
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

            def _serve(self):
                while True:
                    try:
                        msg = _recv_msg(self.request)
                    except FrameTooLarge as e:
                        mx.counter("remote.frames.rejected").inc()
                        logger.warning("ledger server: %s", e)
                        try:
                            _send_msg(self.request, {
                                "ok": False, "error": str(e),
                                "error_class": "FrameTooLarge",
                            })
                        except OSError:
                            pass
                        return  # stream is desynced: drop the connection
                    except OSError:
                        return  # client reset mid-frame
                    if msg is None:
                        return
                    try:
                        _send_msg(self.request, outer._dispatch(msg))
                    except OSError:
                        return  # client went away before the response

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True  # restarted nodes rebind their port
            daemon_threads = True

        self._server = _Server((host, port), Handler)
        self.address: Tuple[str, int] = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)

    def start(self) -> "LedgerServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        # flag first: a probe racing the shutdown gets a typed
        # `NodeStopped` answer instead of a silently severed connection
        self._stopping.set()
        self._server.shutdown()
        self._server.server_close()
        # sever live client connections BEFORE tearing replication down:
        # once the shipper stops, an in-flight submit could still commit
        # locally without ever reaching a follower — if its ack escaped
        # to the client, that would be an acked tx a promoted follower
        # does not hold (acked-loss). Severed first, the ack cannot
        # flush; the client observes a dead node and resubmits through
        # its exactly-once path on the new leader.
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        # now the replication plane: the leader's links stop shipping, a
        # follower's watchdog stops (it must not promote during an
        # orderly stop)
        repl = getattr(self.network, "repl", None)
        if repl is not None:
            repl.close()

    def _dispatch(self, msg: dict) -> dict:
        op = msg.get("op", "?") if isinstance(msg, dict) else "?"
        if self._stopping.is_set():
            # typed shutdown answer for requests already in flight when
            # stop() began — clients can tell "node going away" from a
            # transport fault and react without a blind retry storm
            mx.counter("remote.dispatch.stopped").inc()
            return {"ok": False, "error": "ledger node is stopping",
                    "error_class": "NodeStopped"}
        # trace extraction: adopt the client's trace context so server
        # spans (dispatch, orderer, validate, WAL) stitch into ONE trace
        ctx = (
            mx.TraceContext.from_wire(msg.get("trace"))
            if isinstance(msg, dict) else None
        )
        try:
            with mx.use_trace(ctx):
                with mx.span("remote.server.dispatch", op=op):
                    return self._dispatch_op(op, msg)
        except ValidationError as e:
            return {"ok": False, "validation_error": str(e)}
        except Backpressure as e:
            # expected load shedding, not a server fault: no traceback,
            # typed so the client can back off and retry (the submission
            # never entered ordering — a retry is exactly-once safe)
            mx.counter("remote.dispatch.backpressure").inc()
            return {"ok": False, "error": str(e),
                    "error_class": "Backpressure"}
        except NotLeader as e:
            # expected replication answer, not a server fault: the client
            # fails over to the current leader (`_rediscover`)
            mx.counter("remote.dispatch.not_leader").inc()
            return {"ok": False, "error": str(e),
                    "error_class": "NotLeader"}
        except StaleEpoch as e:
            # fencing verdict for a zombie ex-leader: typed so its
            # shipper demotes itself instead of retrying (already counted
            # under `repl.stale_rejected` at the fence). The fencer's
            # ACTUAL epoch rides along so the zombie adopts it exactly —
            # a guessed demotion epoch could later collide with the real
            # leader's.
            return {"ok": False, "error": str(e),
                    "error_class": "StaleEpoch",
                    "epoch": getattr(e, "epoch", 0)}
        except Exception as e:  # defensive: never kill the server loop —
            # but never mask the failure either: log the traceback
            # server-side and hand the client the typed exception
            mx.counter(f"remote.dispatch.errors.{op}").inc()
            logger.exception("ledger server: op %s failed", op)
            return {"ok": False, "error": f"{type(e).__name__}: {e}",
                    "error_class": type(e).__name__}

    def _dispatch_op(self, op: str, msg: dict) -> dict:
        repl = getattr(self.network, "repl", None)
        if repl is not None and repl.role != "leader" and op in (
            "submit", "submit_many"
        ):
            # a follower (or a fenced ex-leader) must never take writes:
            # the client gets a typed answer and fails over to the leader
            raise NotLeader(
                f"node is a {repl.role} at epoch {repl.epoch}; "
                "submit to the current leader"
            )
        # ---- replication plane (services/network/replication.py): the
        # leader's shipper and the operator's promotion drive these; a
        # node without an attached ReplicaState answers typed instead of
        # guessing. NodeStopped still wins (checked in _dispatch), so a
        # follower mid-bootstrap observes a stopping node cleanly.
        if (op == "promote" or op == "repl.state" or op == "repl.bootstrap"
                or op == "repl.ship" or op == "repl.heartbeat"):
            if repl is None:
                return {"ok": False,
                        "error": "replication not enabled on this node",
                        "error_class": "ReplicationDisabled"}
            return repl.handle(op, msg)
        if op == "submit":
            ev = self.network.submit(bytes.fromhex(msg["request"]))
            # `transient` must cross the wire: a transient internal
            # fault is retry-safe (the ledger records no verdict), a
            # real rejection is final — remote callers need the same
            # distinction local ones get
            return {"ok": True, "status": ev.status.value, "message": ev.message,
                    "tx_id": ev.tx_id, "transient": ev.transient}
        if op == "submit_many":
            # deterministic multi-tx blocks over the wire: enqueue every
            # request (each under ITS OWN extracted trace context), then
            # cut + commit in arrival order — server half of
            # `RemoteNetwork.submit_many`
            # decode EVERY request before enqueuing ANY: a malformed
            # entry must fail the whole batch up front — enqueue-then-
            # fail would strand already-accepted txs in the ordering
            # queue (silently committed by later traffic, or never)
            # while the client was told the batch failed. The parsed
            # requests are handed straight to the ledger (no re-parse).
            parsed = [
                TokenRequest.from_bytes(bytes.fromhex(h))
                for h in msg["requests"]
            ]
            # pad/truncate the trace list to the request list: a length
            # mismatch from a buggy client must never drop requests
            # (zip would silently truncate the batch)
            traces = list(msg.get("traces") or ())[: len(parsed)]
            traces += [None] * (len(parsed) - len(traces))
            subs = []
            for request, wire in zip(parsed, traces):
                with mx.use_trace(mx.TraceContext.from_wire(wire)):
                    # cooperative under a bounded ordering queue — same
                    # contract (and helper) as Network.submit_many
                    subs.append(
                        self.network.submit_request_cooperative(request)
                    )
            self.network.flush()
            events = [s.result() for s in subs]
            return {"ok": True, "events": [
                {"tx_id": e.tx_id, "status": e.status.value,
                 "message": e.message, "transient": e.transient}
                for e in events
            ]}
        if op == "resolve":
            raw = self.network.resolve_input(ID(msg["tx_id"], msg["index"]))
            return {"ok": True, "output": raw.hex()}
        if op == "exists":
            return {"ok": True, "exists": self.network.exists(ID(msg["tx_id"], msg["index"]))}
        if op == "status":
            ev = self.network.status(msg["tx_id"])
            if ev is None:
                return {"ok": True, "status": None}
            return {"ok": True, "status": ev.status.value, "message": ev.message}
        if op == "height":
            return {"ok": True, "height": self.network.height()}
        # ---- live ops plane: side-effect-free introspection RPCs.
        # These run on the connection's own handler thread and never
        # touch the orderer's commit lock (see Network.health), so they
        # answer DURING a long device verify, not after it.
        if op == "ops.health":
            try:
                # refresh the memory gauges so the probe (and the
                # ops.metrics snapshot a live view fetches next) reports
                # CURRENT footprint, not the last data-plane sample
                from ...utils import sysmon

                sysmon.sample()
            except Exception:
                pass
            h = self.network.health()
            h["uptime_s"] = round(time.time() - self._started_unix, 3)
            h["started_unix"] = round(self._started_unix, 3)
            return {"ok": True, "health": h}
        if op == "ops.metrics":
            return {"ok": True, "snapshot": mx.REGISTRY.snapshot()}
        if op == "ops.flight":
            n = msg.get("n") or int(os.environ.get("FTS_OPS_FLIGHT_N", "64"))
            return {"ok": True, "events": mx.FLIGHT.tail(max(1, int(n)))}
        return {"ok": False, "error": f"unknown op [{op}]",
                "error_class": "UnknownOp"}


class RemoteNetwork:
    """Client-side Network facade over a LedgerServer.

    Note: finality events are delivered on submit responses (poll-based),
    so each party process drives its own vault via `apply_finality`.
    """

    def __init__(self, address: Optional[Tuple[str, int]] = None,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 endpoints: Optional[List[Tuple[str, int]]] = None):
        # failover: `endpoints` (or FTS_REMOTE_ENDPOINTS="h:p,h:p") lists
        # every node of a replicated cluster; `address` stays the
        # backward-compatible single-node form and, when given, is the
        # preferred first endpoint. `_rediscover()` re-probes the list
        # when the current node dies or answers NotLeader/NodeStopped.
        if endpoints is None:
            env = os.environ.get("FTS_REMOTE_ENDPOINTS", "").strip()
            endpoints = _parse_endpoints(env) if env else []
        endpoints = [(str(h), int(p)) for h, p in endpoints]
        if address is not None:
            addr = (str(address[0]), int(address[1]))
            if addr not in endpoints:
                endpoints = [addr] + endpoints
        if not endpoints:
            raise ValueError(
                "RemoteNetwork needs an address, endpoints=, or "
                "FTS_REMOTE_ENDPOINTS"
            )
        self.endpoints: List[Tuple[str, int]] = endpoints
        self.address = endpoints[0]
        self.timeout = (
            float(os.environ.get("FTS_REMOTE_TIMEOUT_S", "30"))
            if timeout is None else timeout
        )
        self.retries = (
            int(os.environ.get("FTS_REMOTE_RETRIES", "4"))
            if retries is None else retries
        )
        self.backoff_s = (
            float(os.environ.get("FTS_REMOTE_BACKOFF_S", "0.05"))
            if backoff_s is None else backoff_s
        )
        self._listeners: List[Callable] = []
        self._lock = threading.Lock()  # guards the pooled socket
        self._sock: Optional[socket.socket] = None
        self._rng = random.Random()  # backoff jitter (decorrelates clients)

    # ------------------------------------------------------- transport

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _connect_locked(self) -> None:
        if self._sock is None:
            self._sock = socket.create_connection(self.address, timeout=self.timeout)
            mx.counter("remote.connects").inc()

    def _call(self, msg: dict) -> dict:
        """One request/response over the pooled connection. Any transport
        failure closes the socket (the next call re-dials) and raises
        ConnectionError/OSError; server-side failures raise typed
        ValidationError/RemoteError and keep the connection. The active
        trace context is injected into the request frame so server-side
        spans stitch into the caller's trace."""
        ctx = mx.current_trace()
        if ctx is not None:
            msg["trace"] = ctx.to_wire()
        with self._lock:
            self._connect_locked()
            # timed INSIDE the lock: the pooled connection serializes
            # callers, and waiting for another thread's in-flight call is
            # contention, not wire latency — only send→recv is observed
            t0 = time.monotonic()
            try:
                faults.fire("remote.send")
                _send_msg(self._sock, msg)
                faults.fire("remote.recv")
                resp = _recv_msg(self._sock)
            except (OSError, FrameTooLarge):
                # FaultConnectionDrop is a ConnectionError, hence OSError
                self._close_locked()
                raise
            if resp is None:
                self._close_locked()
                raise ConnectionError("ledger server closed the connection")
            elapsed = time.monotonic() - t0
        # transport round-trip latency, always on (completed exchanges
        # only — failed transports raise above): the remote leg of the
        # live ops plane's quantile set
        mx.histogram("remote.call.seconds").observe(elapsed)
        if not resp.get("ok"):
            if "validation_error" in resp:
                raise ValidationError(resp["validation_error"])
            if resp.get("error_class") == "Backpressure":
                # the server's admission control rejected the submission
                # BEFORE ordering: typed, retry-safe, exactly-once intact
                raise Backpressure(resp.get("error", "ordering queue full"))
            raise RemoteError(resp.get("error", "remote error"),
                              error_class=resp.get("error_class"))
        return resp

    def _backoff(self, attempt: int) -> None:
        delay = self.backoff_s * (2 ** attempt) * (0.5 + self._rng.random())
        time.sleep(min(delay, 2.0))

    # ------------------------------------------------------- failover

    def _probe_endpoint(self, addr: Tuple[str, int]) -> Optional[Tuple[str, int]]:
        """One fresh short-lived `ops.health` probe: returns (role,
        epoch) — a node with no repl section is a standalone leader at
        epoch -1 — or None for a dead/stopping node."""
        try:
            with socket.create_connection(
                addr, timeout=min(self.timeout, 2.0)
            ) as sock:
                sock.settimeout(min(self.timeout, 2.0))
                _send_msg(sock, {"op": "ops.health"})
                resp = _recv_msg(sock)
        except (OSError, FrameTooLarge, ValueError):
            return None
        if not resp or not resp.get("ok"):
            return None
        repl = (resp.get("health") or {}).get("repl")
        if repl is None:
            return ("leader", -1)
        return (str(repl.get("role")), int(repl.get("epoch", 0)))

    def _rediscover(self) -> bool:
        """Find the current leader: probe every configured endpoint and
        adopt the one claiming leadership, highest fencing epoch first
        (two nodes can both claim it across a failover — the zombie's
        epoch is strictly lower). Returns True when the pooled
        connection was re-pointed at a NEW address."""
        if len(self.endpoints) <= 1:
            return False
        best: Optional[Tuple[Tuple[str, int], int]] = None
        for addr in self.endpoints:
            info = self._probe_endpoint(addr)
            if info is None:
                continue
            role, epoch = info
            if role == "leader" and (best is None or epoch > best[1]):
                best = (addr, epoch)
        if best is None or best[0] == self.address:
            return False
        with self._lock:
            old, self.address = self.address, best[0]
            self._close_locked()
        mx.counter("remote.failover.switches").inc()
        mx.flight("failover", old=f"{old[0]}:{old[1]}",
                  new=f"{best[0][0]}:{best[0][1]}", epoch=best[1])
        logger.warning(
            "remote: failed over %s:%d -> %s:%d (epoch %d)",
            old[0], old[1], best[0][0], best[0][1], best[1],
        )
        return True

    @staticmethod
    def _failover_error(e: BaseException) -> bool:
        """A typed answer that means 'this node cannot take writes' —
        grounds to rediscover, exactly like a dead connection."""
        return isinstance(e, RemoteError) and e.error_class in (
            "NotLeader", "NodeStopped"
        )

    def _call_idempotent(self, msg: dict) -> dict:
        """Retry transport failures with exponential backoff + jitter —
        ONLY safe for ops that do not mutate ledger state."""
        op = msg.get("op")
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                return self._call(msg)
            except (ConnectionError, OSError, RemoteError) as e:
                if isinstance(e, RemoteError) and not self._failover_error(e):
                    raise  # a real server-side failure: not retryable
                last = e
                if attempt < self.retries:
                    mx.counter(f"remote.retry.{op}").inc()
                    mx.counter("remote.retry.attempts").inc()
                    mx.flight("retry", op=op, attempt=attempt)
                    self._backoff(attempt)
                    # a dead/stopping/demoted node: look for the leader
                    # before the next attempt (no-op for single-endpoint
                    # clients)
                    self._rediscover()
        mx.counter("remote.retry.exhausted").inc()
        if isinstance(last, RemoteError):
            # exhausted on a TYPED refusal (NodeStopped/NotLeader with no
            # reachable leader): surface it typed, not as transport noise
            raise last
        raise ConnectionError(
            f"remote {op} failed after {self.retries + 1} attempts: {last}"
        ) from last

    # ------------------------------------------------------- Network API

    def subscribe(self, listener) -> None:
        self._listeners.append(listener)

    def submit(self, request_bytes: bytes) -> FinalityEvent:
        request = TokenRequest.from_bytes(request_bytes)
        # client half of the distributed trace: join the caller's trace
        # (ttx) or start one, and carry it across the wire in the frame
        ctx = mx.current_trace() or mx.new_trace()
        with mx.use_trace(ctx):
            with mx.span("remote.submit", tx=request.anchor):
                mx.flight("submit", tx=request.anchor, remote=True)
                event = self._submit_exactly_once(request.anchor, request_bytes)
        if not event.trace_id:
            event.trace_id = ctx.trace_id
        self._notify(event, request)
        return event

    def _submit_exactly_once(self, tx_id: str, request_bytes: bytes) -> FinalityEvent:
        """Submit with at-most-once commit semantics across retries: on a
        dropped connection, consult `status(tx_id)` BEFORE resubmitting —
        the commit may have raced the disconnect. The ledger's in-flight
        dedup covers the residual window where status is still empty.
        Each wire attempt and each status-recovery probe is a child span
        of the caller's `remote.submit`, so retries are visible in the
        tx's stitched trace."""
        msg = {"op": "submit", "request": request_bytes.hex()}
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                with mx.span("remote.submit.attempt", attempt=attempt):
                    resp = self._call(msg)
                return FinalityEvent(
                    resp["tx_id"], TxStatus(resp["status"]), resp["message"],
                    transient=resp.get("transient", False),
                )
            except Backpressure as e:
                # rejected BEFORE ordering: a plain resubmit after backoff
                # is exactly-once safe by construction — no status probe
                # needed (the ledger never saw the tx)
                last = e
                if attempt >= self.retries:
                    raise
                mx.counter("remote.retry.backpressure").inc()
                mx.counter("remote.retry.attempts").inc()
                mx.flight("retry", op="submit", attempt=attempt, tx=tx_id,
                          backpressure=True)
                self._backoff(attempt)
                continue
            except (ConnectionError, OSError, RemoteError) as e:
                # a typed NotLeader/NodeStopped answer means the node
                # cannot take this write — treated exactly like a dead
                # connection: rediscover the leader, then ride the same
                # status-probe exactly-once machinery (an acked tx is
                # never lost or doubled across the switch)
                if isinstance(e, RemoteError) and not self._failover_error(e):
                    raise
                last = e
                if attempt >= self.retries:
                    break
                # counted only when actually retried (same accounting as
                # _call_idempotent)
                mx.counter("remote.retry.submit").inc()
                mx.counter("remote.retry.attempts").inc()
                mx.flight("retry", op="submit", attempt=attempt, tx=tx_id)
                self._backoff(attempt)
                self._rediscover()
                try:
                    with mx.span("remote.submit.recover", attempt=attempt):
                        known = self.status(tx_id)
                except (ConnectionError, OSError) as e2:
                    last = e2
                    continue
                if known is not None:
                    mx.counter("remote.submit.recovered").inc()
                    mx.flight("submit.recovered", tx=tx_id)
                    return known
                # the ledger has never recorded this tx: resubmitting is
                # safe (and dedup'd server-side regardless)
        mx.counter("remote.retry.exhausted").inc()
        if isinstance(last, RemoteError):
            # exhausted on a TYPED refusal (follower with no reachable
            # leader to fail over to): surface it typed
            raise last
        raise ConnectionError(
            f"submit of {tx_id} failed after {self.retries + 1} attempts: {last}"
        ) from last

    def submit_async(self, request_bytes: bytes) -> Submission:
        """API parity with the in-process `Network`: the wire protocol is
        request/response, so ordering happens server-side (the node's own
        Orderer batches concurrent submitters) and the handle returned
        here is already resolved."""
        event = self.submit(request_bytes)
        sub = Submission(None, TokenRequest.from_bytes(request_bytes))
        sub._resolve(event)
        return sub

    def submit_many(self, requests_bytes: List[bytes]) -> List[FinalityEvent]:
        """API parity with `Network.submit_many`: ship the whole batch in
        ONE wire call; the server enqueues everything and cuts
        deterministic blocks (`max_block_txs` txs each). Every request
        gets its OWN trace context, injected alongside the batch
        (`traces` field), so each tx's client leg, server orderer leg,
        batched verify, WAL append and finality stitch into one
        per-transaction trace. NOT retried on transport failure — a
        multi-tx batch is not idempotent; callers needing exactly-once
        semantics should use per-tx `submit`."""
        requests = [TokenRequest.from_bytes(rb) for rb in requests_bytes]
        ctxs = [mx.new_trace() for _ in requests]
        for req, ctx in zip(requests, ctxs):
            mx.flight("submit", trace=ctx, tx=req.anchor, remote=True)
        t0 = time.time()
        with mx.span("remote.submit_many", txs=len(requests)):
            resp = self._call({
                "op": "submit_many",
                "requests": [rb.hex() for rb in requests_bytes],
                "traces": [c.to_wire() for c in ctxs],
            })
        t1 = time.time()
        rows = resp["events"]
        if len(rows) != len(requests):
            # a short (or long) reply means txs lost finality silently —
            # surface the protocol violation instead of zip-truncating
            raise RemoteError(
                f"submit_many returned {len(rows)} events for "
                f"{len(requests)} requests",
                error_class="ProtocolError",
            )
        events: List[FinalityEvent] = []
        for req, ctx, row in zip(requests, ctxs, rows):
            event = FinalityEvent(
                row["tx_id"], TxStatus(row["status"]), row.get("message", ""),
                transient=row.get("transient", False),
                trace_id=ctx.trace_id,
            )
            # per-tx client leg: each tx spent the whole batched wire
            # call waiting client-side — record it in the tx's trace
            mx.record_span("remote.submit", t0, t1, trace=ctx, tx=req.anchor)
            self._notify(event, req)
            events.append(event)
        return events

    def resolve_input(self, token_id: ID) -> bytes:
        resp = self._call_idempotent(
            {"op": "resolve", "tx_id": token_id.tx_id, "index": token_id.index}
        )
        return bytes.fromhex(resp["output"])

    def exists(self, token_id: ID) -> bool:
        return self._call_idempotent(
            {"op": "exists", "tx_id": token_id.tx_id, "index": token_id.index}
        )["exists"]

    def status(self, tx_id: str) -> Optional[FinalityEvent]:
        resp = self._call_idempotent({"op": "status", "tx_id": tx_id})
        if resp["status"] is None:
            return None
        return FinalityEvent(tx_id, TxStatus(resp["status"]), resp.get("message", ""))

    def height(self) -> int:
        return self._call_idempotent({"op": "height"})["height"]

    # ------------------------------------------------------- ops plane

    def ops_health(self) -> dict:
        """Live node introspection (`ops.health`): uptime, height, WAL
        state, queue depth, in-flight txs, last-block critical-path
        breakdown. Read-only, so retried like the other idempotent ops."""
        return self._call_idempotent({"op": "ops.health"})["health"]

    def ops_metrics(self) -> dict:
        """The node's full `Registry.snapshot()` over the wire (counters,
        gauges, histograms WITH p50/p95/p99, span summary, phases)."""
        return self._call_idempotent({"op": "ops.metrics"})["snapshot"]

    def promote(self) -> int:
        """Explicit follower promotion (`promote` RPC) — the operator /
        chaos-harness entry point. Idempotent server-side (a leader
        answers with its current epoch), hence retry-safe. Returns the
        node's fencing epoch after promotion."""
        return int(self._call_idempotent({"op": "promote"})["epoch"])

    def ops_flight(self, n: Optional[int] = None) -> List[dict]:
        """Tail of the node's live flight-recorder ring (default
        `FTS_OPS_FLIGHT_N` events) — the crash trail, without the crash."""
        msg: dict = {"op": "ops.flight"}
        if n is not None:
            msg["n"] = int(n)
        return self._call_idempotent(msg)["events"]

    def apply_finality(self, request_bytes: bytes) -> Optional[FinalityEvent]:
        """Receiver-side sync: given a request distributed off-band (the
        reference's recipient/ttx views), look up its final status on the
        ledger and replay it into local listeners (vault, ttxdb)."""
        request = TokenRequest.from_bytes(request_bytes)
        event = self.status(request.anchor)
        if event is not None:
            self._notify(event, request)
        return event

    def _notify(self, event: FinalityEvent, request: TokenRequest) -> None:
        """Per-listener crash isolation, mirroring the in-process ledger:
        a throwing finality listener is counted and logged, and the
        remaining listeners still run."""
        for listener in self._listeners:
            try:
                listener(event, request)
            except Exception:
                mx.counter("remote.listener.errors").inc()
                logger.exception(
                    "remote: finality listener failed for tx %s", event.tx_id
                )
