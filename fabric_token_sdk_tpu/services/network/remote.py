"""Multi-process network: a TCP ordering/ledger node + thin client.

Reference parity: the SDK talks to a Fabric network over gRPC
(`token/services/network/fabric`); here a JSON-over-TCP node hosts the
MVCC ledger + validator, and `RemoteNetwork` exposes the same API surface
as the in-process `Network` so parties can live in separate processes.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Callable, List, Optional, Tuple

from ...api.driver import ValidationError
from ...api.request import TokenRequest
from ...api.validator import RequestValidator
from ...models.token import ID
from .ledger import FinalityEvent, Network, TxStatus
from .orderer import Submission


def _send_msg(sock: socket.socket, obj: dict) -> None:
    raw = json.dumps(obj).encode()
    sock.sendall(len(raw).to_bytes(4, "big") + raw)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    n = int.from_bytes(hdr, "big")
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return json.loads(buf.decode())


class LedgerServer:
    """Hosts a Network (orderer + endorser + committer) over TCP."""

    def __init__(self, validator: RequestValidator, host: str = "127.0.0.1",
                 port: int = 0, policy=None):
        # concurrent client submits land in the node's ordering queue and
        # group-commit into shared blocks (policy: orderer.BlockPolicy)
        self.network = Network(validator, policy=policy)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    msg = _recv_msg(self.request)
                    if msg is None:
                        return
                    _send_msg(self.request, outer._dispatch(msg))

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.address: Tuple[str, int] = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)

    def start(self) -> "LedgerServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def _dispatch(self, msg: dict) -> dict:
        try:
            op = msg["op"]
            if op == "submit":
                ev = self.network.submit(bytes.fromhex(msg["request"]))
                return {"ok": True, "status": ev.status.value, "message": ev.message,
                        "tx_id": ev.tx_id}
            if op == "resolve":
                raw = self.network.resolve_input(ID(msg["tx_id"], msg["index"]))
                return {"ok": True, "output": raw.hex()}
            if op == "exists":
                return {"ok": True, "exists": self.network.exists(ID(msg["tx_id"], msg["index"]))}
            if op == "status":
                ev = self.network.status(msg["tx_id"])
                if ev is None:
                    return {"ok": True, "status": None}
                return {"ok": True, "status": ev.status.value, "message": ev.message}
            if op == "height":
                return {"ok": True, "height": self.network.height()}
            return {"ok": False, "error": f"unknown op [{op}]"}
        except ValidationError as e:
            return {"ok": False, "validation_error": str(e)}
        except Exception:  # defensive: never kill the server loop
            return {"ok": False, "error": "malformed request"}


class RemoteNetwork:
    """Client-side Network facade over a LedgerServer.

    Note: finality events are delivered on submit responses (poll-based),
    so each party process drives its own vault via `apply_finality`.
    """

    def __init__(self, address: Tuple[str, int]):
        self.address = tuple(address)
        self._listeners: List[Callable] = []
        self._lock = threading.Lock()

    def _call(self, msg: dict) -> dict:
        with socket.create_connection(self.address, timeout=30) as sock:
            _send_msg(sock, msg)
            resp = _recv_msg(sock)
        if resp is None:
            raise ConnectionError("ledger server closed the connection")
        if not resp.get("ok"):
            if "validation_error" in resp:
                raise ValidationError(resp["validation_error"])
            raise RuntimeError(resp.get("error", "remote error"))
        return resp

    def subscribe(self, listener) -> None:
        self._listeners.append(listener)

    def submit(self, request_bytes: bytes) -> FinalityEvent:
        resp = self._call({"op": "submit", "request": request_bytes.hex()})
        event = FinalityEvent(resp["tx_id"], TxStatus(resp["status"]), resp["message"])
        request = TokenRequest.from_bytes(request_bytes)
        for listener in self._listeners:
            listener(event, request)
        return event

    def submit_async(self, request_bytes: bytes) -> Submission:
        """API parity with the in-process `Network`: the wire protocol is
        request/response, so ordering happens server-side (the node's own
        Orderer batches concurrent submitters) and the handle returned
        here is already resolved."""
        event = self.submit(request_bytes)
        sub = Submission(None, TokenRequest.from_bytes(request_bytes))
        sub._resolve(event)
        return sub

    def resolve_input(self, token_id: ID) -> bytes:
        resp = self._call({"op": "resolve", "tx_id": token_id.tx_id, "index": token_id.index})
        return bytes.fromhex(resp["output"])

    def exists(self, token_id: ID) -> bool:
        return self._call(
            {"op": "exists", "tx_id": token_id.tx_id, "index": token_id.index}
        )["exists"]

    def status(self, tx_id: str) -> Optional[FinalityEvent]:
        resp = self._call({"op": "status", "tx_id": tx_id})
        if resp["status"] is None:
            return None
        return FinalityEvent(tx_id, TxStatus(resp["status"]), resp.get("message", ""))

    def height(self) -> int:
        return self._call({"op": "height"})["height"]

    def apply_finality(self, request_bytes: bytes) -> Optional[FinalityEvent]:
        """Receiver-side sync: given a request distributed off-band (the
        reference's recipient/ttx views), look up its final status on the
        ledger and replay it into local listeners (vault, ttxdb)."""
        request = TokenRequest.from_bytes(request_bytes)
        event = self.status(request.anchor)
        if event is not None:
            for listener in self._listeners:
                listener(event, request)
        return event
