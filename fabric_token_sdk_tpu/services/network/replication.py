"""Replicated ledger plane: WAL shipping, promotion, fencing epochs.

Every robustness layer so far hardens ONE node (WAL + recovery,
persistent vault, breakers); a dead leader still takes the service down.
This module generalizes the existing substrate from "replay after death"
to "replay continuously, then promote":

* **Leader**: a `Shipper` with one link (thread + bounded queue) per
  follower. `Network._commit_block_inner` hands every journaled WAL
  record to `ReplicaState.on_commit` right after the fsync'd append and
  BEFORE submitters are resolved, so an acknowledged tx is replicated
  first. The wait is bounded (`FTS_REPL_SHIP_TIMEOUT_S`) and the plane
  is degrade-only: a slow/hung/dead follower is dropped LOUDLY
  (`repl.ship.dropped` / `repl.ship.ack_timeouts`, per-link circuit
  breaker gating reconnects) and never stalls the leader's commit. With
  zero followers (or `FTS_REPL=0`) nothing attaches and the commit path
  is byte-identical to a standalone node.
* **Follower**: a `LedgerServer` whose network carries a follower
  `ReplicaState`. New framed ops: `repl.state` (height/epoch/role),
  `repl.bootstrap` (full snapshot install), `repl.ship` (one WAL record,
  applied through the SAME no-reverify replay path recovery uses and
  journaled to the follower's own WAL), `repl.heartbeat` (lease +
  lag), and `promote`. Submits sent to a follower get a typed
  `NotLeader` answer, so a failing-over client never forks the ledger.
* **Catch-up**: on every (re)connect the link asks `repl.state`, sends a
  full snapshot if the leader's journal no longer covers the follower's
  height (compaction), then streams the journal suffix via
  `WriteAheadLog.replay_iter` — O(one record) memory, and records the
  follower already holds are skipped idempotently by height.
* **Fencing**: the promotion epoch is persisted next to the journal
  (`<wal>.epoch`, fsync'd). `promote` bumps it; every `repl.*` message
  carries the sender's epoch and a receiver at a HIGHER epoch rejects it
  with a typed `StaleEpoch` (`repl.stale_rejected`) — a zombie
  ex-leader's stale appends are rejected, never merged, and the zombie
  demotes itself (`repl.demotions`) the moment it learns of the newer
  epoch. A message at a higher epoch is adopted (and demotes a leader).
* **Promotion**: explicit (`promote` RPC, e.g. from an operator or the
  chaos harness) or automatic — `FTS_REPL_AUTO_PROMOTE=1` arms a lease
  watchdog that promotes the follower after `FTS_REPL_LEASE_S` seconds
  of heartbeat silence.

Fault sites (`utils/faults.py` / `FTS_FAULTS`): `repl.ship` and
`repl.heartbeat` fire on the link thread around sends (so error/drop/
delay/hang degrade ONE link, never the commit path), `repl.apply` fires
in `Network.apply_delta` on the follower.

Client failover lives in `remote.RemoteNetwork` (`FTS_REMOTE_ENDPOINTS`
/ `endpoints=`): on a dead connection or a typed `NotLeader` /
`NodeStopped` answer it re-probes every endpoint's `ops.health`, adopts
the leader with the highest epoch (`remote.failover.switches`), and the
existing status-probe exactly-once machinery guarantees an acknowledged
tx is never lost or doubled across the switch.
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import time
from typing import List, Optional, Tuple

from ...utils import faults, profiler
from ...utils import metrics as mx
from ...utils import resilience
from ...utils.tracing import logger
from .wal import fsync_dir

DEFAULT_SHIP_TIMEOUT_S = 5.0
DEFAULT_QUEUE_MAX = 128
DEFAULT_HEARTBEAT_S = 0.5
DEFAULT_LEASE_S = 3.0


class ReplicationError(RuntimeError):
    """A replication-protocol violation (unknown op, bad role)."""


class NotLeader(ReplicationError):
    """A mutating op was sent to a follower — clients must fail over."""


class StaleEpoch(ReplicationError):
    """A fenced-off message from a stale epoch (zombie ex-leader).

    Carries the rejecting node's epoch so the zombie can demote to the
    fencer's ACTUAL epoch — guessing (e.g. `own epoch + 1`) could leave
    a later re-promotion at an epoch equal to the real leader's, and two
    leaders must never share an epoch."""

    def __init__(self, message: str, epoch: int = 0):
        super().__init__(message)
        self.epoch = epoch


# ------------------------------------------------------------ epoch file


def _load_epoch(path: Optional[str]) -> int:
    if not path:
        return 0
    try:
        with open(path) as fh:
            return int(fh.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def _store_epoch(path: Optional[str], epoch: int) -> None:
    """Persist the fencing epoch durably (atomic tmp+rename, fsync'd
    including the directory): a node restarting after a crash must come
    back at the epoch it last held, or fencing would not survive the
    exact failure it exists for."""
    if not path:
        return
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        fh.write(str(epoch))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(path)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ------------------------------------------------------------ state


class ReplicaState:
    """Per-node replication state: role, fencing epoch, and (on the
    leader) the shipper. Attached to a `Network` as `network.repl` by
    `attach_leader` / `attach_follower`; `Network.health()` publishes
    `health_section()` so lag and role ride the existing `ops.health`
    RPC (the `repl=` column of `ftstop top`)."""

    def __init__(self, network, role: str, epoch_path: Optional[str] = None):
        self.network = network
        self.role = role
        self.epoch_path = epoch_path
        self.epoch = _load_epoch(epoch_path)
        self.shipper: Optional[Shipper] = None
        self.leader_height = network.height()
        self.last_heartbeat = time.monotonic()
        self.lease_s = _env_f("FTS_REPL_LEASE_S", DEFAULT_LEASE_S)
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None

    # ------------------------------------------------------ introspection

    def health_section(self) -> dict:
        with self._lock:
            section = {"role": self.role, "epoch": self.epoch}
            if self.shipper is not None:
                links = self.shipper.link_states()
                section["followers"] = links
                lags = [l["lag"] for l in links if l["lag"] is not None]
                section["lag"] = max(lags) if lags else 0
            else:
                lag = max(0, self.leader_height - self.network.height())
                section["lag"] = lag
                section["leader_height"] = self.leader_height
                section["heartbeat_age_s"] = round(
                    time.monotonic() - self.last_heartbeat, 3
                )
        return section

    # ------------------------------------------------------ role changes

    def promote(self, reason: str = "rpc") -> int:
        """Become the leader: bump + persist the fencing epoch FIRST (a
        crash right after must come back fenced-high), then flip the
        role. Idempotent on an existing leader."""
        with self._lock:
            if self.role == "leader":
                return self.epoch
            self.epoch += 1
            _store_epoch(self.epoch_path, self.epoch)
            self.role = "leader"
            epoch = self.epoch
        mx.counter("repl.promotions").inc()
        mx.flight(
            "repl.promote", epoch=epoch, reason=reason,
            height=self.network.height(),
        )
        logger.warning(
            "repl: promoted to leader at epoch %d height %d (%s)",
            epoch, self.network.height(), reason,
        )
        return epoch

    def demote(self, peer_epoch: int, why: str) -> None:
        """Step down: a higher epoch exists somewhere — this node's
        writes are fenced off, so it must stop acting as a leader (a
        demoted node answers submits with `NotLeader`)."""
        with self._lock:
            if peer_epoch > self.epoch:
                self.epoch = peer_epoch
                _store_epoch(self.epoch_path, self.epoch)
            if self.role != "leader":
                return
            self.role = "follower"
            self.last_heartbeat = time.monotonic()
        mx.counter("repl.demotions").inc()
        mx.flight("repl.demoted", epoch=peer_epoch, why=why)
        logger.warning("repl: demoted to follower (%s, epoch %d)", why,
                       peer_epoch)

    def _fence(self, msg_epoch: int, op: str) -> None:
        """Reject lower epochs (typed `StaleEpoch`), adopt higher ones —
        adopting demotes a leader. A LEADER also rejects its own epoch:
        promotion always bumps, so an equal-epoch `repl.*` frame arriving
        at a leader can only mean a second leader (split brain) — refuse
        it rather than fork-merge."""
        with self._lock:
            if msg_epoch < self.epoch or (
                msg_epoch == self.epoch and self.role == "leader"
            ):
                mx.counter("repl.stale_rejected").inc()
                mx.flight("repl.fenced", op=op, msg_epoch=msg_epoch,
                          epoch=self.epoch)
                raise StaleEpoch(
                    f"{op} from epoch {msg_epoch} rejected: this node is "
                    f"a {self.role} fenced at epoch {self.epoch}",
                    epoch=self.epoch,
                )
        if msg_epoch > self.epoch:
            self.demote(msg_epoch, f"{op} at higher epoch")

    # ------------------------------------------------------ server side

    def handle(self, op: str, msg: dict) -> dict:
        """Server half of the replication protocol — dispatched by
        `LedgerServer._dispatch_op` for `repl.*` and `promote` frames."""
        if op == "promote":
            epoch = self.promote()
            return {"ok": True, "role": self.role, "epoch": epoch,
                    "height": self.network.height()}
        if op == "repl.state":
            with self._lock:
                return {"ok": True, "role": self.role, "epoch": self.epoch,
                        "height": self.network.height()}
        if op == "repl.bootstrap":
            self._fence(int(msg.get("epoch", 0)), op)
            height = self.network.install_snapshot(
                bytes.fromhex(msg["snapshot"])
            )
            with self._lock:
                self.leader_height = max(self.leader_height, height)
                self.last_heartbeat = time.monotonic()
            return {"ok": True, "height": height}
        if op == "repl.ship":
            self._fence(int(msg.get("epoch", 0)), op)
            height = self.network.apply_delta(bytes.fromhex(msg["record"]))
            with self._lock:
                self.leader_height = max(self.leader_height, height)
                self.last_heartbeat = time.monotonic()
            return {"ok": True, "height": height}
        if op == "repl.heartbeat":
            self._fence(int(msg.get("epoch", 0)), op)
            with self._lock:
                self.last_heartbeat = time.monotonic()
                self.leader_height = int(msg.get("height", 0))
            return {"ok": True, "height": self.network.height()}
        raise ReplicationError(f"unknown replication op [{op}]")

    # ------------------------------------------------------ leader side

    def on_commit(self, height: int, record: bytes) -> None:
        """Commit-path hook (`_commit_block_inner`, right after the WAL
        append): hand the journaled record to the shipper. Bounded and
        degrade-only by construction — see `Shipper.ship`."""
        if self.shipper is not None and self.role == "leader":
            self.shipper.ship(height, record)

    # ------------------------------------------------------ lease watchdog

    def start_watchdog(self) -> None:
        """Auto-promotion: a follower that hears no leader heartbeat for
        a full lease promotes itself (FTS_REPL_AUTO_PROMOTE=1)."""
        if self._watchdog is not None:
            return
        self._watchdog = threading.Thread(
            target=self._watch, name="fts-repl-watchdog", daemon=True
        )
        self._watchdog.start()

    def _watch(self) -> None:
        profiler.set_thread_role("repl-watchdog")
        poll = max(0.05, min(self.lease_s / 4.0, 0.5))
        while not self._stop.wait(poll):
            with self._lock:
                if self.role != "follower":
                    return
                age = time.monotonic() - self.last_heartbeat
            if age >= self.lease_s:
                self.promote(reason=f"lease expired ({age:.2f}s silent)")
                return

    def close(self) -> None:
        self._stop.set()
        if self.shipper is not None:
            self.shipper.stop()
        if self._watchdog is not None and self._watchdog.is_alive():
            self._watchdog.join(timeout=2.0)


# ------------------------------------------------------------ shipper


class _LinkStopped(Exception):
    """Internal: the link terminated cleanly (NodeStopped / fenced)."""


class _NeedBootstrap(Exception):
    """Internal: the follower reported a journal gap — re-sync via a
    full snapshot instead of retrying the same doomed delta."""


class _FollowerLink:
    """One follower: a daemon thread owning the socket, a bounded ship
    queue, and an ack watermark. All failure handling lives HERE, off
    the commit path: reconnect backoff is gated by a per-link circuit
    breaker, a typed `NodeStopped` answer ends the link cleanly (a
    stopping node is a demotion, not a retry storm), and a `StaleEpoch`
    answer fences the WHOLE leader (it demotes itself)."""

    def __init__(self, state: ReplicaState, address: Tuple[str, int],
                 ship_timeout_s: float, queue_max: int, heartbeat_s: float):
        self.state = state
        self.address = (str(address[0]), int(address[1]))
        self.ship_timeout_s = ship_timeout_s
        self.heartbeat_s = heartbeat_s
        self.follower_height: Optional[int] = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, queue_max))
        # guards link_state AND follower_height: the commit path reads
        # both (ship's wait loop) while the link thread mutates them, so
        # every transition notifies waiters through this one condition
        self._ack = threading.Condition()
        self.link_state = "connecting"
        self._stop = threading.Event()
        self._dropping = False  # throttles the drop flight event
        self._breaker = resilience.CircuitBreaker(
            f"repl.{self.address[0]}:{self.address[1]}"
        )
        self._thread = threading.Thread(
            target=self._run, name=f"fts-repl-{self.address[1]}", daemon=True
        )

    # ---------------------------------------------- commit-path interface

    def enqueue(self, height: int, record: bytes) -> bool:
        """Non-blocking: a full queue (slow follower) DROPS the record
        loudly — the next reconnect re-syncs from the journal, so a drop
        costs catch-up work, never correctness."""
        with self._ack:
            if self.link_state in ("stopped", "fenced"):
                return False
        try:
            self._queue.put_nowait((height, record))
            return True
        except queue.Full:
            mx.counter("repl.ship.dropped").inc()
            if not self._dropping:
                self._dropping = True
                mx.flight("repl.ship.drop", addr=self._addr_str(),
                          height=height)
            return False

    def wait_acked(self, height: int, deadline: float) -> str:
        """Bounded wait for the follower's ack watermark to reach
        `height` — the follower's POST-apply height, i.e. `block index
        + 1` for the record just shipped. Returns `"acked"`,
        `"timeout"` (deadline expired on a streaming link — the caller
        counts it and moves on, degrade-only), or `"unsynced"` (the
        link is not streaming — connecting, syncing, breaker-open,
        stopped, or fenced — so this record rides the journal re-sync
        instead of the queue; counted by the caller so degraded
        shipping is always visible)."""
        with self._ack:
            while True:
                acked = (
                    -1 if self.follower_height is None
                    else self.follower_height
                )
                if acked >= height:
                    return "acked"
                if self.link_state != "streaming":
                    return "unsynced"
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return "timeout"
                self._ack.wait(timeout=min(remaining, 0.05))

    def _addr_str(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def _set_follower_height(self, height: int) -> None:
        with self._ack:
            self.follower_height = height
            self._ack.notify_all()

    def _set_link_state(self, state: str) -> None:
        with self._ack:
            self.link_state = state
            self._ack.notify_all()

    # ---------------------------------------------- link thread

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._ack:
            self._ack.notify_all()
        try:  # unblock a queue.get in progress
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        profiler.set_thread_role("repl-shipper")
        backoff = 0.05
        while not self._stop.is_set():
            if not self._breaker.allow():
                self._set_link_state("breaker_open")
                self._stop.wait(0.2)
                continue
            sock = None
            try:
                self._set_link_state("connecting")
                sock = socket.create_connection(
                    self.address, timeout=self.ship_timeout_s
                )
                sock.settimeout(self.ship_timeout_s)
                self._catch_up(sock)
                self._breaker.record_success()
                backoff = 0.05
                self._set_link_state("streaming")
                self._dropping = False
                self._stream(sock)
            except _LinkStopped:
                return
            except _NeedBootstrap:
                continue  # reconnect immediately; catch-up will snapshot
            except Exception as e:
                self._breaker.record_failure()
                mx.counter("repl.link.errors").inc()
                self._set_link_state("reconnecting")
                logger.warning(
                    "repl: link to %s failed (%s: %s); reconnecting",
                    self._addr_str(), type(e).__name__, e,
                )
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 2.0)
            finally:
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass

    def _rpc(self, sock: socket.socket, msg: dict) -> dict:
        from .remote import RemoteError, _recv_msg, _send_msg

        _send_msg(sock, msg)
        resp = _recv_msg(sock)
        if resp is None:
            raise ConnectionError(
                f"follower {self._addr_str()} closed the connection"
            )
        if resp.get("ok"):
            return resp
        klass = resp.get("error_class")
        if klass == "NodeStopped":
            # the follower is shutting down on purpose: log the clean
            # demotion and end the link — no retry storm against a
            # stopping node
            mx.counter("repl.link.node_stopped").inc()
            mx.flight("repl.link.stopped", addr=self._addr_str())
            logger.info(
                "repl: follower %s is stopping; link demoted cleanly",
                self._addr_str(),
            )
            self._set_link_state("stopped")
            raise _LinkStopped()
        if klass == "StaleEpoch":
            # WE are the zombie: a promoted node fenced us off. Demote
            # the whole leader to the fencer's ACTUAL epoch (it rides
            # the typed answer) — never a guessed `epoch + 1`, which a
            # later re-promotion could land EQUAL to the real leader's
            # epoch (and equal-epoch leaders would merge each other's
            # frames). `epoch + 1` survives only as the fallback for a
            # peer that omits the field.
            self._set_link_state("fenced")
            fencer_epoch = int(resp.get("epoch") or 0)
            self.state.demote(
                fencer_epoch if fencer_epoch else self.state.epoch + 1,
                "fenced by follower",
            )
            logger.warning(
                "repl: follower %s fenced this leader off (%s)",
                self._addr_str(), resp.get("error"),
            )
            raise _LinkStopped()
        if klass == "WALError":
            raise _NeedBootstrap()
        raise RemoteError(resp.get("error", "replication error"),
                          error_class=klass)

    def _catch_up(self, sock: socket.socket) -> None:
        """Bring the follower to the leader's journal frontier: drain the
        (stale) queue, snapshot-bootstrap if the journal no longer covers
        the follower's height, then stream the journal suffix. Records
        committed DURING catch-up are both in the journal scan and the
        queue — the follower skips re-applies by height, so the overlap
        is idempotent, and a gap is impossible."""
        from ...crypto.serialization import loads

        self._set_link_state("syncing")
        st = self._rpc(sock, {"op": "repl.state"})
        if int(st.get("epoch", 0)) > self.state.epoch:
            self._set_link_state("fenced")
            self.state.demote(int(st["epoch"]), "follower at higher epoch")
            raise _LinkStopped()
        follower_h = int(st.get("height", 0))
        self._set_follower_height(follower_h)
        while True:  # drop whatever queued while the link was down
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        wal = getattr(self.state.network, "_wal", None)
        first = next(wal.replay_iter(), None) if wal is not None else None
        journal_base = loads(first[1])["height"] if first else None
        if follower_h < self.state.network.height() and (
            journal_base is None or journal_base > follower_h
        ):
            snap = self.state.network.snapshot()
            resp = self._rpc(sock, {
                "op": "repl.bootstrap", "snapshot": snap.hex(),
                "epoch": self.state.epoch,
            })
            self._set_follower_height(int(resp["height"]))
            mx.counter("repl.bootstraps.sent").inc()
        if wal is not None:
            for _off, payload in wal.replay_iter():
                if self._stop.is_set():
                    return
                resp = self._rpc(sock, {
                    "op": "repl.ship", "record": payload.hex(),
                    "epoch": self.state.epoch,
                })
                self._set_follower_height(int(resp["height"]))

    def _stream(self, sock: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=self.heartbeat_s)
            except queue.Empty:
                self._heartbeat(sock)
                continue
            if item is None:
                return  # stop sentinel
            _height, record = item
            faults.fire("repl.ship")
            resp = self._rpc(sock, {
                "op": "repl.ship", "record": record.hex(),
                "epoch": self.state.epoch,
            })
            self._set_follower_height(int(resp["height"]))
            mx.counter("repl.shipped.records").inc()

    def _heartbeat(self, sock: socket.socket) -> None:
        faults.fire("repl.heartbeat")
        resp = self._rpc(sock, {
            "op": "repl.heartbeat", "epoch": self.state.epoch,
            "height": self.state.network.height(),
        })
        self._set_follower_height(int(resp["height"]))
        mx.counter("repl.heartbeats").inc()


class Shipper:
    """Leader-side fan-out of journaled WAL records to follower links."""

    def __init__(self, state: ReplicaState,
                 followers: List[Tuple[str, int]],
                 ship_timeout_s: Optional[float] = None,
                 queue_max: Optional[int] = None,
                 heartbeat_s: Optional[float] = None):
        self.state = state
        self.ship_timeout_s = (
            _env_f("FTS_REPL_SHIP_TIMEOUT_S", DEFAULT_SHIP_TIMEOUT_S)
            if ship_timeout_s is None else ship_timeout_s
        )
        qmax = (
            int(os.environ.get("FTS_REPL_QUEUE_MAX",
                               str(DEFAULT_QUEUE_MAX)))
            if queue_max is None else queue_max
        )
        hb = (
            _env_f("FTS_REPL_HEARTBEAT_S", DEFAULT_HEARTBEAT_S)
            if heartbeat_s is None else heartbeat_s
        )
        self._links = [
            _FollowerLink(state, addr, self.ship_timeout_s, qmax, hb)
            for addr in followers
        ]

    def start(self) -> None:
        for link in self._links:
            link.start()

    def stop(self) -> None:
        for link in self._links:
            link.stop()

    def ship(self, height: int, record: bytes) -> None:
        """Commit-path entry: enqueue to every live link, then wait —
        bounded by `ship_timeout_s` — for the streaming links to ack.
        A healthy loopback follower acks in well under a millisecond, so
        an acknowledged tx is replicated before its submitter resolves;
        a sick one times out, is counted, and the commit proceeds.

        `height` is the record's block INDEX (the leader ships before
        its own merge), so the ack target is `height + 1` — the
        follower's post-apply height. Waiting for `height` itself would
        be satisfied by a follower merely caught up through the
        PREVIOUS record, i.e. every commit would only confirm its
        predecessor's replication. Links that are not streaming
        (connecting/syncing/breaker-open/stopped/fenced — including one
        that flips mid-wait) are counted `repl.ship.unsynced`, never
        waited on: their records ride the journal re-sync, and degraded
        shipping stays visible."""
        t0 = time.monotonic()
        for link in self._links:
            link.enqueue(height, record)
        deadline = t0 + self.ship_timeout_s
        target = height + 1
        for link in self._links:
            verdict = link.wait_acked(target, deadline)
            if verdict == "timeout":
                mx.counter("repl.ship.ack_timeouts").inc()
            elif verdict == "unsynced":
                mx.counter("repl.ship.unsynced").inc()
        mx.histogram("repl.ship.wait.seconds").observe(
            time.monotonic() - t0
        )

    def link_states(self) -> List[dict]:
        leader_h = self.state.network.height()
        rows = []
        for link in self._links:
            with link._ack:  # consistent (state, height) snapshot
                fh = link.follower_height
                state = link.link_state
            rows.append({
                "addr": link._addr_str(),
                "state": state,
                "height": fh,
                "lag": (leader_h - fh) if fh is not None else None,
            })
        return rows


# ------------------------------------------------------------ attachment


def _enabled() -> bool:
    return os.environ.get("FTS_REPL", "1") != "0"


def _epoch_path(network, explicit: Optional[str]) -> Optional[str]:
    if explicit:
        return explicit
    wal = getattr(network, "_wal", None)
    return (wal.path + ".epoch") if wal is not None else None


def attach_leader(network, followers: List[Tuple[str, int]],
                  epoch_path: Optional[str] = None,
                  **shipper_opts) -> Optional[ReplicaState]:
    """Make a journaled `Network` the replication leader for `followers`
    (a list of `(host, port)` follower `LedgerServer` addresses).
    Returns None — leaving the commit path byte-identical to a
    standalone node — when `FTS_REPL=0` or the follower list is empty."""
    if not _enabled() or not followers:
        return None
    if getattr(network, "_wal", None) is None:
        raise ReplicationError(
            "replication leader needs a journaled network (wal_path=...)"
        )
    state = ReplicaState(network, "leader",
                         epoch_path=_epoch_path(network, epoch_path))
    state.shipper = Shipper(state, followers, **shipper_opts)
    network.repl = state
    state.shipper.start()
    logger.info(
        "repl: leader at epoch %d shipping to %d follower(s)",
        state.epoch, len(followers),
    )
    return state


def attach_follower(network, epoch_path: Optional[str] = None,
                    auto_promote: Optional[bool] = None
                    ) -> Optional[ReplicaState]:
    """Make a `Network` a replication follower: it answers `repl.*`
    frames, rejects submits with `NotLeader`, and (with
    `FTS_REPL_AUTO_PROMOTE=1` or `auto_promote=True`) promotes itself
    after a full heartbeat lease of silence. Returns None when
    `FTS_REPL=0`."""
    if not _enabled():
        return None
    resolved = _epoch_path(network, epoch_path)
    if resolved is None:
        # same refusal as attach_leader: without a durable epoch file a
        # restarted follower comes back at epoch 0, so fencing would not
        # survive exactly the crash it exists for
        raise ReplicationError(
            "replication follower needs a journaled network (wal_path=...)"
            " or an explicit epoch_path: the fencing epoch must survive a"
            " restart"
        )
    state = ReplicaState(network, "follower", epoch_path=resolved)
    network.repl = state
    if auto_promote is None:
        auto_promote = os.environ.get("FTS_REPL_AUTO_PROMOTE", "0") == "1"
    if auto_promote:
        state.start_watchdog()
    logger.info("repl: follower at epoch %d height %d", state.epoch,
                network.height())
    return state
