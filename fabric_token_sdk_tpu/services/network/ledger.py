"""In-memory token ledger with MVCC double-spend detection + finality events.

Reference: `token/services/network/*` (fabric/orion backends + vault
processor). Ours is a deterministic single-process ledger: an ordering
queue serializes commits; each commit re-validates the request against
current state, detects conflicts (already-spent inputs — the distributed
"race"), applies writes atomically, and notifies finality listeners.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, List, Optional, Tuple

from ...api.driver import ValidationError
from ...api.request import TokenRequest
from ...api.validator import RequestValidator
from ...models.token import ID
from ...utils import metrics as mx
from ...utils.tracing import tracer


class TxStatus(Enum):
    PENDING = "Pending"
    VALID = "Valid"
    INVALID = "Invalid"


@dataclass
class FinalityEvent:
    tx_id: str
    status: TxStatus
    message: str = ""


@dataclass
class Block:
    number: int
    txs: List[str] = field(default_factory=list)
    timestamp: float = 0.0


class Network:
    """Shared ledger + orderer for a set of parties."""

    def __init__(self, validator: RequestValidator):
        self.validator = validator
        self._state: Dict[str, bytes] = {}  # token key -> output bytes
        self._spent: set = set()  # token keys consumed (serials)
        self._blocks: List[Block] = []
        self._status: Dict[str, FinalityEvent] = {}
        self._listeners: List[Callable[[FinalityEvent, TokenRequest], None]] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------ queries

    def resolve_input(self, token_id: ID) -> bytes:
        key = token_id.key()
        with self._lock:
            if key in self._spent:
                raise ValidationError(f"token {token_id} already spent")
            if key not in self._state:
                raise ValidationError(f"token {token_id} does not exist")
            return self._state[key]

    def exists(self, token_id: ID) -> bool:
        key = token_id.key()
        with self._lock:
            return key in self._state and key not in self._spent

    def status(self, tx_id: str) -> Optional[FinalityEvent]:
        with self._lock:
            return self._status.get(tx_id)

    def height(self) -> int:
        with self._lock:
            return len(self._blocks)

    # ------------------------------------------------------------ commit

    def subscribe(self, listener: Callable[[FinalityEvent, TokenRequest], None]) -> None:
        self._listeners.append(listener)

    def submit(self, request_bytes: bytes) -> FinalityEvent:
        """Order + validate + commit one token request (one tx per block).

        Mirrors ordering -> endorser validation -> vault commit. Returns the
        finality event (also pushed to subscribers).
        """
        request = TokenRequest.from_bytes(request_bytes)
        tx_id = request.anchor
        with tracer.span("network.submit", tx=tx_id):
            with self._lock:
                if tx_id in self._status:
                    mx.counter("network.submit.resubmissions").inc()
                    return self._status[tx_id]  # idempotent resubmission
                commit_time = time.time()
                try:
                    with mx.span("network.validate", tx=tx_id):
                        result = self.validator.validate(
                            request, self._resolve_locked, now=commit_time
                        )
                    # MVCC conflict check happens inside _resolve_locked;
                    # apply atomically
                    for token_id in result.spent:
                        self._spent.add(token_id.key())
                        del self._state[token_id.key()]
                    out_index = 0
                    for _, outputs in result.outputs:
                        for raw in outputs:
                            self._state[ID(tx_id, out_index).key()] = raw
                            out_index += 1
                    event = FinalityEvent(tx_id, TxStatus.VALID)
                    mx.counter("network.tx.valid").inc()
                except ValidationError as e:
                    event = FinalityEvent(tx_id, TxStatus.INVALID, str(e))
                    mx.counter("network.tx.invalid").inc()
                self._status[tx_id] = event
                self._blocks.append(Block(len(self._blocks), [tx_id], commit_time))
                mx.gauge("network.height").set(len(self._blocks))
            for listener in self._listeners:
                listener(event, request)
            return event

    def _resolve_locked(self, token_id: ID) -> bytes:
        key = token_id.key()
        if key in self._spent:
            raise ValidationError(f"token {token_id} already spent")
        if key not in self._state:
            raise ValidationError(f"token {token_id} does not exist")
        return self._state[key]

    # --------------------------------------------------- checkpoint/resume

    def snapshot(self) -> bytes:
        """Serialize ledger state (checkpoint; reference parity: vault +
        ledger recovery on node restart)."""
        from ...crypto.serialization import dumps

        with self._lock:
            return dumps(
                {
                    "state": dict(self._state),
                    "spent": sorted(self._spent),
                    "blocks": [[b.number, b.txs, b.timestamp] for b in self._blocks],
                    "status": {
                        t: [e.status.value, e.message]
                        for t, e in self._status.items()
                    },
                }
            )

    @classmethod
    def restore(cls, validator: RequestValidator, raw: bytes) -> "Network":
        from ...crypto.serialization import loads

        d = loads(raw)
        net = cls(validator)
        net._state = dict(d["state"])
        net._spent = set(d["spent"])
        net._blocks = [Block(*row) for row in d["blocks"]]
        net._status = {
            t: FinalityEvent(t, TxStatus(s), m) for t, (s, m) in d["status"].items()
        }
        return net
