"""In-memory token ledger: multi-tx blocks, MVCC, finality events.

Reference: `token/services/network/*` (fabric/orion backends + vault
processor) plus the ordering service in front of them. Submissions enter
the `Orderer`'s queue (`orderer.py`); blocks are cut by size/linger
policy and validated by the block pipeline — same-shape zkatdlog
transfer groups in ONE `BatchedTransferVerifier` call over the
compile-once stage tiles, host `RequestValidator` for the rest — then
committed atomically: intra-block MVCC (a double-spend inside a block
invalidates the LATER tx only), per-tx finality events, and
crash-isolated listener notification.

Durability (`wal.py`): when constructed with a `wal_path`, every cut
block is appended to an fsync'd CRC-framed write-ahead log *before* the
atomic merge, and a full snapshot is written every `snapshot_every`
blocks (compaction: the WAL's replayed prefix is truncated only after
the snapshot is durably on disk). `Network.recover(validator, path)`
rebuilds the ledger from the latest snapshot plus the WAL suffix, with
torn-tail tolerance — a node can be SIGKILLed mid-block and restart
without losing any finality it ever reported.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, List, Optional, Tuple

from ...api.driver import ValidationError
from ...api.request import TokenRequest
from ...api.validator import RequestValidator, ValidationResult
from ...models.token import ID
from ...utils import devobs, faults, profiler, resilience, slo
from ...utils import metrics as mx
from ...utils.tracing import logger, tracer
from .orderer import (
    Backpressure,
    BlockPolicy,
    BlockValidationPipeline,
    Orderer,
    Submission,
)
from .wal import WALError, WriteAheadLog


class TxStatus(Enum):
    PENDING = "Pending"
    VALID = "Valid"
    INVALID = "Invalid"


@dataclass
class FinalityEvent:
    tx_id: str
    status: TxStatus
    message: str = ""
    # True: the rejection was an INTERNAL fault, not a deterministic
    # verdict — the submitter sees it, but nothing durable is recorded
    # (an identical resubmission may succeed). Never persisted.
    transient: bool = False
    # id of the distributed trace this tx's lifecycle was recorded under
    # (diagnostic only — never persisted, empty when tracing was off)
    trace_id: str = ""


@dataclass
class Block:
    number: int
    txs: List[str] = field(default_factory=list)
    timestamp: float = 0.0


class _BlockView:
    """MVCC overlay for one block: txs validate against committed state
    PLUS the writes of earlier valid txs in the same block. Outputs
    created earlier in the block are spendable; inputs consumed earlier
    in the block are conflicts (the later tx is invalidated). Nothing
    touches the committed maps until `merge()` — the block applies
    atomically or (on a crash mid-validate) not at all."""

    def __init__(self, state: Dict[str, bytes], spent: set):
        self._state = state
        self._spent = spent
        self._new: Dict[str, bytes] = {}
        self._consumed: set = set()

    def resolve(self, token_id: ID) -> bytes:
        key = token_id.key()
        if key in self._consumed or key in self._spent:
            raise ValidationError(f"token {token_id} already spent")
        raw = self._new.get(key)
        if raw is None:
            raw = self._state.get(key)
        if raw is None:
            raise ValidationError(f"token {token_id} does not exist")
        return raw

    def apply(self, tx_id: str, result: ValidationResult) -> None:
        for token_id in result.spent:
            key = token_id.key()
            self._consumed.add(key)
            self._new.pop(key, None)
        out_index = 0
        for _, outputs in result.outputs:
            for raw in outputs:
                self._new[ID(tx_id, out_index).key()] = raw
                out_index += 1

    def merge(self) -> None:
        for key in self._consumed:
            self._state.pop(key, None)
            self._spent.add(key)
        self._state.update(self._new)


class Network:
    """Shared ledger + orderer for a set of parties."""

    def __init__(self, validator: RequestValidator,
                 policy: Optional[BlockPolicy] = None,
                 wal_path: Optional[str] = None,
                 snapshot_every: Optional[int] = None,
                 mesh=None):
        self.validator = validator
        self.policy = policy or BlockPolicy.from_env()
        self._state: Dict[str, bytes] = {}  # token key -> output bytes
        self._spent: set = set()  # token keys consumed (serials)
        self._blocks: List[Block] = []
        self._status: Dict[str, FinalityEvent] = {}
        self._listeners: List[Callable[[FinalityEvent, TokenRequest], None]] = []
        self._lock = threading.Lock()
        # `mesh` (parallel.sharding.MeshConfig) shards the block-batched
        # proof plane's dispatch over dp x mp; None = ambient env
        # (FTS_MESH_DEVICES / FTS_DP_SHARDS), resolved in the runners
        self._pipeline = BlockValidationPipeline(validator, self.policy,
                                                 mesh=mesh)
        self._orderer = Orderer(self._commit_block, self.policy)
        # pipelined block engine: overlap block N+1's batched device
        # verify with block N's host-validate/WAL/merge (pipeline.py).
        # FTS_BLOCK_PIPELINE=0 is the kill switch that restores the
        # exact sequential path regardless of policy.
        self._engine = None
        if (
            self.policy.pipeline
            and os.environ.get("FTS_BLOCK_PIPELINE", "1") != "0"
        ):
            from .pipeline import PipelinedBlockEngine

            self._engine = PipelinedBlockEngine(
                self._verify_stage, self._commit_stage
            )
            self._orderer.set_engine(self._engine)
        # last committed block's critical-path breakdown, served live by
        # the `ops.health` RPC (assignment is atomic; readers copy)
        self.last_block: Optional[dict] = None
        # durability plane: journal + snapshot compaction (wal.py). For an
        # EXISTING journal use `Network.recover(...)` — constructing with
        # a non-empty wal_path appends after whatever is already there.
        self.snapshot_every = (
            int(os.environ.get("FTS_WAL_SNAPSHOT_EVERY", "64"))
            if snapshot_every is None else snapshot_every
        )
        self._wal: Optional[WriteAheadLog] = (
            WriteAheadLog(wal_path) if wal_path else None
        )
        self._snapshot_path = (str(wal_path) + ".snap") if wal_path else None
        # replication plane (services/network/replication.py): None on a
        # standalone node — attached by `replication.enable(...)`, which
        # makes this node a leader (WAL shipper) or a follower (delta
        # applier + promotion watchdog). The commit path only ever calls
        # `self.repl.on_commit(...)`, which is bounded and degrade-only.
        self.repl = None

    # ------------------------------------------------------------ queries

    def resolve_input(self, token_id: ID) -> bytes:
        key = token_id.key()
        with self._lock:
            if key in self._spent:
                raise ValidationError(f"token {token_id} already spent")
            if key not in self._state:
                raise ValidationError(f"token {token_id} does not exist")
            return self._state[key]

    def exists(self, token_id: ID) -> bool:
        key = token_id.key()
        with self._lock:
            return key in self._state and key not in self._spent

    def status(self, tx_id: str) -> Optional[FinalityEvent]:
        with self._lock:
            return self._status.get(tx_id)

    def height(self) -> int:
        with self._lock:
            return len(self._blocks)

    def block(self, number: int) -> Optional[Block]:
        with self._lock:
            return self._blocks[number] if 0 <= number < len(self._blocks) else None

    def health(self) -> dict:
        """Side-effect-free node introspection — the body of the
        `ops.health` RPC. Touches only the ledger lock (held briefly by
        queries and the atomic merge) and the orderer's queue mutex,
        NEVER the orderer's commit lock, so a minutes-long device verify
        cannot block a health probe."""
        with self._lock:
            height = len(self._blocks)
            txs_final = len(self._status)
            last = dict(self.last_block) if self.last_block else None
        wal = None
        if self._wal is not None:
            try:
                size = os.path.getsize(self._wal.path)
            except OSError:
                size = -1
            wal = {
                "path": self._wal.path,
                "bytes": size,
                "sync": self._wal.sync,
                "poisoned": self._wal.poisoned,
            }
        return {
            "pid": os.getpid(),
            "height": height,
            "txs_final": txs_final,
            "queue_depth": self._orderer.pending(),
            "inflight": self._orderer.inflight(),
            "wal": wal,
            "last_block": last,
            # per-plane circuit-breaker states (utils/resilience.py):
            # {} until a plane dispatched at least once; a non-"closed"
            # entry is the live signal a device plane is degraded and
            # riding its host fallback (ftstop renders the brk column)
            "breakers": resilience.breaker_states(),
            # live error-budget state (utils/slo.py): per-SLO burn over
            # the sliding window — the `slo=` column of `ftstop top`
            "slo": slo.ENGINE.health_section(),
            # device-plane dispatch ledger (utils/devobs.py): per-plane
            # occupancy and the per-program dispatch/compile forensics
            # behind `ftstop devices`
            "device": devobs.health_section(),
            # host-path parse caches (identity / parsed-request / raw
            # bytes): lifetime hit/miss counters — a cold or thrashing
            # cache shows up here before it shows up as host-leg wall
            "caches": self._caches_section(),
            # replication plane (services/network/replication.py): role,
            # fencing epoch, and per-follower ship lag — None on a
            # standalone (un-replicated) node, which is how `ftstop top`
            # knows not to render a repl column for old nodes
            "repl": self.repl.health_section() if self.repl else None,
        }

    @staticmethod
    def _caches_section() -> dict:
        from ...api import request as request_mod

        def _c(name: str) -> int:
            return mx.REGISTRY.counter(name).value

        return {
            "identity": {
                "hits": _c("identity.cache.hits"),
                "misses": _c("identity.cache.misses"),
            },
            "request": {
                "entries": request_mod.cache_len(),
                "hits": _c("request.cache.hits"),
                "misses": _c("request.cache.misses"),
                "evictions": _c("request.cache.evictions"),
            },
            "parse": {
                "hits": _c("parse.cache.hits"),
                "misses": _c("parse.cache.misses"),
            },
        }

    # ------------------------------------------------------------ ordering

    def subscribe(self, listener: Callable[[FinalityEvent, TokenRequest], None]) -> None:
        self._listeners.append(listener)

    def submit(self, request_bytes: bytes) -> FinalityEvent:
        """Order + validate + commit one token request; blocks until the
        block containing it commits (driving the group commit if this
        caller wins the race). Returns the finality event (also pushed to
        subscribers)."""
        sub = self.submit_async(request_bytes)
        # drive under the tx's trace (minted at enqueue, or the caller's
        # — ttx / remote dispatch); a dedup'd resubmission has no trace
        # of its own and use_trace(None) keeps any caller context live
        with mx.use_trace(sub.trace):
            with tracer.span("network.submit", tx=sub.request.anchor):
                return sub.result()

    def submit_async(self, request_bytes: bytes) -> Submission:
        """Enqueue a request into ordering; returns a Submission handle
        whose `result()` waits for (and, if needed, drives) block commit."""
        return self.submit_request(TokenRequest.from_bytes(request_bytes))

    def submit_request(self, request: TokenRequest) -> Submission:
        """`submit_async` for an already-parsed request (the remote
        node's batched submit path decodes up front — no double parse).
        The active trace context (or a fresh one, minted only when the
        request actually enters ordering — dedup'd resubmissions never
        mint orphan traces) is captured into the Submission so
        block-commit spans land in this tx's trace."""
        with self._lock:
            known = self._status.get(request.anchor)
        if known is not None:  # idempotent resubmission
            mx.counter("network.submit.resubmissions").inc()
            mx.flight("submit", tx=request.anchor, dedup=True)
            sub = Submission(None, request)
            sub._resolve(known)
            return sub
        ctx = mx.current_trace() or mx.new_trace()
        with mx.use_trace(ctx):
            return self._orderer.enqueue(request)

    def submit_request_cooperative(self, request: TokenRequest) -> Submission:
        """`submit_request` for BATCH submitters under a bounded ordering
        queue: instead of surfacing `Backpressure` mid-batch (stranding
        the already enqueued prefix), drain the queue with a flush and
        retry — admission control sheds load from OTHER submitters while
        a deterministic batch still lands whole. Shared by the local and
        the remote-server `submit_many` paths."""
        while True:
            try:
                return self.submit_request(request)
            except Backpressure:
                mx.counter("orderer.backpressure.flushes").inc()
                self._orderer.flush()

    def submit_many(self, requests_bytes: List[bytes]) -> List[FinalityEvent]:
        """Deterministic multi-tx blocks: enqueue everything (cooperating
        with admission control), then cut + commit in arrival order
        (`max_block_txs` txs per block)."""
        subs = [
            self.submit_request_cooperative(TokenRequest.from_bytes(rb))
            for rb in requests_bytes
        ]
        self._orderer.flush()
        return [s.result() for s in subs]

    def flush(self) -> None:
        """Force-commit everything pending in the ordering queue."""
        self._orderer.flush()

    # ------------------------------------------------------------ commit

    def _split_fresh(
        self, subs: List[Submission], resolve_known: bool = True,
    ) -> Tuple[List[Submission], Dict[str, List[Submission]]]:
        """Partition a cut into fresh submissions and duplicates: an
        anchor already recorded resolves immediately from the recorded
        event (idempotent resubmission); an anchor appearing twice in one
        cut validates once. `resolve_known=False` is the verify stage's
        PROVISIONAL split — it skips work without resolving or counting,
        because the commit stage re-checks under the final state."""
        fresh: List[Submission] = []
        dup_of: Dict[str, List[Submission]] = {}
        with self._lock:
            for sub in subs:
                anchor = sub.request.anchor
                known = self._status.get(anchor)
                if known is not None:
                    if resolve_known:
                        mx.counter("network.submit.resubmissions").inc()
                        sub._resolve(known)
                elif anchor in dup_of:
                    # same anchor twice in one cut: validate once
                    if resolve_known:
                        mx.counter("network.submit.resubmissions").inc()
                        dup_of[anchor].append(sub)
                else:
                    fresh.append(sub)
                    dup_of[anchor] = []
        return fresh, dup_of

    def _verify_stage(self, subs: List[Submission]) -> dict:
        """Stage A of the pipelined engine: the batched device verify of
        one cut block — state-independent (proofs are checked against
        request bytes, never ledger state), so it safely overlaps the
        commit of the previous block. Returns verdicts keyed by
        SUBMISSION identity: the commit stage re-runs the dedup split
        under the final committed state (a duplicate racing across two
        in-flight blocks must resolve from the recorded verdict), and
        identity keys survive that re-split where indices would not."""
        cut_mono, cut_unix = time.monotonic(), time.time()
        timings: dict = {}
        fresh, _dups = self._split_fresh(subs, resolve_known=False)
        requests = [s.request for s in fresh]
        host_pv: Dict[int, Dict[int, bool]] = {}
        verdicts = self._pipeline.proof_verdicts(
            requests, timings, host_verdicts=host_pv
        )
        # the batched signature plane is state-independent too (payloads
        # and identities come from request bytes), so it overlaps the
        # previous block's commit exactly like the proof plane
        sig_verdicts = self._pipeline.sign_verdicts(requests, timings)
        # block-level vectorized conservation: also state-independent
        # (it checks the ACTION-claimed bytes; the per-tx input_match leg
        # pins them to ledger state before any verdict is consumed)
        cons_verdicts = self._pipeline.conservation_verdicts(
            requests, timings
        )
        return {
            "verdicts": {id(fresh[ti]): v for ti, v in verdicts.items()},
            "sig_verdicts": {
                id(fresh[ti]): v for ti, v in sig_verdicts.items()
            },
            "cons_verdicts": {
                id(fresh[ti]): v for ti, v in cons_verdicts.items()
            },
            "host_verdicts": {
                id(fresh[ti]): v for ti, v in host_pv.items()
            },
            "timings": timings,
            "cut_mono": cut_mono,
            "cut_unix": cut_unix,
        }

    def _commit_stage(self, subs: List[Submission], pre: Optional[dict]) -> None:
        """Stage B of the pipelined engine (commit-worker thread)."""
        self._commit_block(subs, pre=pre, attach_errors=True)

    def _commit_block(self, subs: List[Submission],
                      pre: Optional[dict] = None,
                      attach_errors: bool = False) -> None:
        """Validate + commit one cut block (serialized end to end —
        sequential mode under the orderer's commit lock, pipelined mode
        on the engine's single commit worker). Every submission in the
        cut is GUARANTEED a resolution — even on an internal crash — or
        its waiters would spin forever. `attach_errors` (pipelined mode)
        additionally attaches an escaping exception to each stranded
        submission so `result()` re-raises it on the waiter's stack."""
        try:
            self._commit_block_inner(subs, pre)
        except Exception as e:
            if attach_errors:
                for sub in subs:
                    if not sub.done():
                        sub._commit_error = e
            raise
        finally:
            stranded = [s for s in subs if not s.done()]
            if stranded:  # internal error escaped: fail them loudly
                mx.counter("ledger.commit.stranded").inc(len(stranded))
                for sub in stranded:
                    sub._resolve(
                        FinalityEvent(
                            sub.request.anchor, TxStatus.INVALID,
                            "internal commit error (see ledger logs)",
                            transient=True,
                        )
                    )

    def _commit_block_inner(self, subs: List[Submission],
                            pre: Optional[dict] = None) -> None:
        fresh, dup_of = self._split_fresh(subs)
        if not fresh:
            return
        requests = [s.request for s in fresh]
        # queue-wait leg of the critical path: how long each submission
        # sat in the ordering queue before its cut picked it up (in
        # pipelined mode the cut happened at verify-stage entry — use
        # the stamped cut time, not the commit stage's start)
        if pre is not None:
            cut_mono = pre.get("cut_mono") or time.monotonic()
            cut_unix = pre.get("cut_unix") or time.time()
        else:
            cut_mono, cut_unix = time.monotonic(), time.time()
        queue_wait_max = 0.0
        for sub in fresh:
            if sub.enqueued_at:
                wait_s = max(0.0, cut_mono - sub.enqueued_at)
                queue_wait_max = max(queue_wait_max, wait_s)
                mx.histogram("ledger.block.queue_wait.seconds").observe(wait_s)
                mx.record_span(
                    "orderer.queue", sub.enqueued_unix, cut_unix,
                    trace=sub.trace, tx=sub.request.anchor,
                )
        with mx.span("ledger.block.validate", txs=len(requests)) as blk:
            # Validation runs OUTSIDE the ledger lock: the device verify
            # (or a cold compile) and the per-tx host checks must not
            # starve concurrent reads. This is safe because every state
            # WRITER is serialized (commit lock, or the engine's single
            # commit worker) — readers under `self._lock` simply observe
            # consistent pre-block state until the atomic merge below.
            if pre is None:
                timings: dict = {}
                host_pv: Dict[int, Dict[int, bool]] = {}
                verdicts = self._pipeline.proof_verdicts(
                    requests, timings, host_verdicts=host_pv
                )
                sig_verdicts = self._pipeline.sign_verdicts(requests, timings)
                cons_verdicts = self._pipeline.conservation_verdicts(
                    requests, timings
                )
            else:
                # stage A already verified this block (overlapping the
                # previous block's commit): adopt its verdicts by
                # submission identity. fresh-at-commit is a subset of
                # fresh-at-verify, so no fresh sub can lack coverage
                # unless stage A found no batchable group for it.
                timings = dict(pre.get("timings") or {})
                timings.setdefault("grouping_s", 0.0)
                timings.setdefault("device_verify_s", 0.0)
                timings.setdefault("sign_verify_s", 0.0)
                pv = pre.get("verdicts") or {}
                verdicts = {
                    ti: pv[id(s)]
                    for ti, s in enumerate(fresh) if id(s) in pv
                }
                psv = pre.get("sig_verdicts") or {}
                sig_verdicts = {
                    ti: psv[id(s)]
                    for ti, s in enumerate(fresh) if id(s) in psv
                }
                pcv = pre.get("cons_verdicts") or {}
                cons_verdicts = {
                    ti: pcv[id(s)]
                    for ti, s in enumerate(fresh) if id(s) in pcv
                }
                phv = pre.get("host_verdicts") or {}
                host_pv = {
                    ti: phv[id(s)]
                    for ti, s in enumerate(fresh) if id(s) in phv
                }
            commit_time = time.time()
            view = _BlockView(self._state, self._spent)
            events: List[FinalityEvent] = []
            t0 = time.monotonic()
            # sub-leg attribution of the host tail: the per-tx loop runs
            # on this one thread, so a thread-local collector decomposes
            # host_validate_s into the named `ledger.host.*` legs
            with profiler.collect() as host_legs:
                for ti, request in enumerate(requests):
                    # device verdicts (True/False) win over the host
                    # batch's True-only rows; the two sets are disjoint
                    # by construction (host rows are device leftovers)
                    dv, hv = verdicts.get(ti), host_pv.get(ti)
                    proofs = {**hv, **dv} if (dv and hv) else (dv or hv)
                    # per-tx validation runs under the TX's trace, not
                    # the committing thread's — whoever wins the race
                    with mx.use_trace(fresh[ti].trace):
                        event = self._validate_tx(
                            request, view, commit_time, proofs,
                            sig_verdicts.get(ti), cons_verdicts.get(ti),
                        )
                    if fresh[ti].trace is not None:
                        event.trace_id = fresh[ti].trace.trace_id
                    events.append(event)
            host_validate_s = time.monotonic() - t0
            faults.fire("ledger.commit_block")
            # WAL append BEFORE the atomic merge: once the record is
            # fsync'd the block is durable — a crash between here and the
            # merge redoes it on recovery (clients that never got an
            # answer re-learn the verdict via status()). A crash before
            # here loses only unacknowledged work.
            wal_s = 0.0
            if self._wal is not None:
                t0 = time.monotonic()
                record = self._wal_record(requests, events, view, commit_time)
                self._wal.append(record)
                wal_s = time.monotonic() - t0
                mx.flight(
                    "wal.append", block=len(self._blocks), bytes=len(record),
                    txs=[e.tx_id for e in events if not e.transient],
                )
                if self.repl is not None:
                    # ship the journaled record to followers BEFORE the
                    # submitters are resolved (below): an acknowledged tx
                    # is replicated first. Degrade-only for the leader —
                    # the wait is bounded, a slow/hung/dead follower is
                    # dropped loudly (counted + breaker), never stalls
                    # this commit.
                    self.repl.on_commit(len(self._blocks), record)
            t0 = time.monotonic()
            with self._lock:
                # atomic apply + finalize; transient-fault events resolve
                # their submitter but leave no durable trace
                view.merge()
                block = Block(
                    len(self._blocks),
                    [e.tx_id for e in events if not e.transient],
                    commit_time,
                )
                self._blocks.append(block)
                for event in events:
                    if not event.transient:
                        self._status[event.tx_id] = event
                self._record_block_metrics(requests, events, verdicts)
            merge_s = time.monotonic() - t0
            # per-block critical-path breakdown: where this block's wall
            # time went (queue wait / grouping / device verify / host
            # validate incl. fallbacks / WAL fsync / atomic merge)
            breakdown = {
                "queue_wait_max_s": round(queue_wait_max, 6),
                "grouping_s": round(timings.get("grouping_s", 0.0), 6),
                "device_verify_s": round(timings.get("device_verify_s", 0.0), 6),
                "sign_verify_s": round(timings.get("sign_verify_s", 0.0), 6),
                # batch-first host passes (FTS_HOST_BATCH): block-level
                # sign / proof / conservation work hoisted out of the
                # per-tx loop — their wall is NOT in host_validate_s
                "host_sign_batch_s": round(
                    timings.get("host_sign_batch_s", 0.0), 6
                ),
                "host_proof_batch_s": round(
                    timings.get("host_proof_batch_s", 0.0), 6
                ),
                "host_conservation_batch_s": round(
                    timings.get("host_conservation_batch_s", 0.0), 6
                ),
                "host_validate_s": round(host_validate_s, 6),
                "wal_s": round(wal_s, 6),
                "merge_s": round(merge_s, 6),
            }
            # the host leg decomposed (utils/profiler.py sub-leg timers):
            # exclusive per-leg seconds of THIS block's host-validate loop
            for leg_name in profiler.LEGS:
                breakdown[f"host_{leg_name}_s"] = round(
                    host_legs.get(leg_name, 0.0), 6
                )
            if pre is not None:
                # pipelined engine: how much of THIS block's device
                # verify ran while the previous block's commit stage was
                # still busy — the overlap the pipeline exists to create
                breakdown["overlap_s"] = round(pre.get("overlap_s", 0.0), 6)
            mx.histogram("ledger.block.host_validate.seconds").observe(
                host_validate_s
            )
            mx.histogram("ledger.block.merge.seconds").observe(merge_s)
            # per-block wall of the batch-first host passes (zero-valued
            # blocks skipped: the quantiles should describe blocks that
            # actually ran a pass)
            if timings.get("host_sign_batch_s", 0.0) > 0:
                mx.histogram("ledger.block.host_sign_batch.seconds").observe(
                    timings["host_sign_batch_s"]
                )
            if timings.get("host_proof_batch_s", 0.0) > 0:
                mx.histogram("ledger.block.host_proof_batch.seconds").observe(
                    timings["host_proof_batch_s"]
                )
            if timings.get("host_conservation_batch_s", 0.0) > 0:
                mx.histogram(
                    "ledger.block.host_conservation.seconds"
                ).observe(timings["host_conservation_batch_s"])
            # whole-block commit latency, always on (the quantiles the
            # live ops plane serves), plus the breakdown `ops.health`
            # reports for the LAST committed block
            commit_wall_s = time.monotonic() - cut_mono
            mx.histogram("ledger.block.commit.seconds").observe(commit_wall_s)
            self.last_block = {
                "number": block.number,
                "txs": len(requests),
                "committed_unix": round(commit_time, 3),
                "commit_s": round(commit_wall_s, 6),
                "breakdown": breakdown,
            }
            if blk is not None:
                blk.attrs.update(breakdown)
            mx.flight(
                "block.commit", block=block.number,
                txs=[r.anchor for r in requests],
                traces=[s.trace.trace_id if s.trace else None for s in fresh],
                **breakdown,
            )
        # error-budget bookkeeping (throttled internally): breaches must
        # surface during load even when nothing polls `ops.health`
        slo.ENGINE.tick()
        # snapshot compaction: still under the orderer's commit lock (the
        # only WAL writer), outside the ledger lock (snapshot() retakes
        # it). The block is already durable in the journal by now, so a
        # compaction failure must never poison its acknowledgement — the
        # journal just keeps growing until a later compaction succeeds.
        if (
            self._wal is not None
            and self.snapshot_every > 0
            and len(self._blocks) % self.snapshot_every == 0
        ):
            try:
                self._compact()
            except Exception:
                mx.counter("wal.snapshot_failures").inc()
                logger.exception(
                    "ledger: snapshot compaction failed; journal keeps growing"
                )
        # listeners run outside the ledger lock; resolve afterwards so a
        # submitter returning from submit() sees vault/db effects applied
        for event, request in zip(events, requests):
            if not event.transient:
                self._notify(event, request)
        for sub, event in zip(fresh, events):
            sub._resolve(event)
            for dup in dup_of.get(event.tx_id, ()):
                dup._resolve(event)

    def _validate_tx(self, request: TokenRequest, view: _BlockView,
                     commit_time: float,
                     proofs: Optional[Dict[int, bool]],
                     sigs: Optional[Dict[tuple, tuple]] = None,
                     cons: Optional[Dict[int, bool]] = None) -> FinalityEvent:
        tx_id = request.anchor
        try:
            with mx.span("network.validate", tx=tx_id):
                result = self.validator.validate(
                    request, view.resolve, now=commit_time,
                    transfer_proofs=proofs, sig_verified=sigs,
                    conservation=cons,
                )
            view.apply(tx_id, result)
            mx.counter("network.tx.valid").inc()
            return FinalityEvent(tx_id, TxStatus.VALID)
        except ValidationError as e:
            mx.counter("network.tx.invalid").inc()
            return FinalityEvent(tx_id, TxStatus.INVALID, str(e))
        except Exception as e:  # defensive: one bad tx never aborts a block
            logger.exception("ledger: unexpected validation error for %s", tx_id)
            mx.counter("ledger.validate.unexpected_errors").inc()
            mx.counter("network.tx.invalid").inc()
            return FinalityEvent(
                tx_id, TxStatus.INVALID,
                f"internal validation error: {type(e).__name__}: {e}",
                transient=True,
            )

    def _record_block_metrics(self, requests, events, verdicts) -> None:
        mx.counter("ledger.blocks.committed").inc()
        mx.histogram(
            "ledger.block.size", buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256)
        ).observe(len(requests))
        batched = sum(len(v) for v in verdicts.values())
        transfers = sum(len(r.transfers) for r in requests)
        mx.counter("ledger.validate.batched").inc(batched)
        mx.counter("ledger.validate.host").inc(transfers - batched)
        if transfers:
            mx.histogram(
                "ledger.block.batched_frac",
                buckets=(0.0, 0.25, 0.5, 0.75, 0.9, 1.0),
            ).observe(batched / transfers)
        mx.gauge("network.height").set(len(self._blocks))

    # ------------------------------------------------------------ durability

    def _wal_record(self, requests, events, view: _BlockView,
                    commit_time: float) -> bytes:
        """One journal record = one cut block: the raw request bytes (for
        audit/replay), the per-tx verdicts, and the exact durable state
        delta the merge will apply. Replay applies the delta — it never
        re-validates, so recovery is deterministic and cheap regardless
        of how expensive the original proofs were. Transient (internal-
        fault) events leave no durable trace here either."""
        from ...crypto.serialization import dumps

        return dumps(
            {
                "height": len(self._blocks),
                "ts": commit_time,
                # wire_bytes: the exact bytes each request was parsed
                # from when unmodified since (skips a full re-serialize
                # on this hot path); replay decodes both forms identically
                "requests": [r.wire_bytes() for r in requests],
                "txs": [
                    [e.tx_id, e.status.value, e.message]
                    for e in events if not e.transient
                ],
                "consumed": sorted(view._consumed),
                "outputs": dict(view._new),
            }
        )

    def _compact(self) -> None:
        """Write a full snapshot (atomic tmp+rename, fsync'd — including
        the DIRECTORY, so the rename is durable before the truncate can
        be) and only then truncate the journal. A crash in between
        leaves snapshot AND journal, whose replayed prefix is skipped by
        height."""
        from .wal import fsync_dir

        raw = self.snapshot()
        tmp = f"{self._snapshot_path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as fh:
            fh.write(raw)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._snapshot_path)
        if self._wal.sync:
            fsync_dir(self._snapshot_path)
        self._wal.reset()
        mx.counter("wal.snapshots").inc()

    # ---------------------------------------------------------- replication

    def _apply_wal_record(self, d: dict) -> Block:
        """Apply one decoded WAL record's durable delta to the in-memory
        maps — the no-reverify replay path shared by crash recovery and
        follower delta apply. The record IS the verdict: state delta and
        per-tx statuses are applied as journaled, never re-validated.
        Caller owns locking and height sequencing."""
        for key in d["consumed"]:
            self._state.pop(key, None)
            self._spent.add(key)
        self._state.update(d["outputs"])
        txs = []
        for tx_id, status, message in d["txs"]:
            self._status[tx_id] = FinalityEvent(tx_id, TxStatus(status), message)
            txs.append(tx_id)
        block = Block(d["height"], txs, d["ts"])
        self._blocks.append(block)
        return block

    def apply_delta(self, record: bytes) -> int:
        """Follower-side replication apply: journal one shipped WAL
        record to this node's OWN journal, then apply it through the
        no-reverify replay path. Idempotent below the current height
        (re-shipped records are skipped, not re-applied); a height GAP
        raises `WALError` — the follower missed records and must be
        re-bootstrapped, never guess-merged. Returns the new height."""
        from ...crypto.serialization import loads

        faults.fire("repl.apply")
        d = loads(record)
        height = d["height"]
        with self._lock:
            if height < len(self._blocks):
                mx.counter("repl.apply.skipped").inc()
                return len(self._blocks)
            if height > len(self._blocks):
                raise WALError(
                    f"replication gap: shipped record at height {height} "
                    f"but follower holds {len(self._blocks)} blocks "
                    "(re-bootstrap required)"
                )
            if self._wal is not None:
                # journal-first, same as the leader: a follower that
                # crashes after this fsync recovers the block
                self._wal.append(record)
            self._apply_wal_record(d)
            new_height = len(self._blocks)
        mx.counter("repl.applied.records").inc()
        mx.gauge("network.height").set(new_height)
        # follower-side snapshot compaction, same cadence as the leader
        # (degrade-only: a failure just means the journal keeps growing)
        if (
            self._wal is not None
            and self.snapshot_every > 0
            and new_height % self.snapshot_every == 0
        ):
            try:
                self._compact()
            except Exception:
                mx.counter("wal.snapshot_failures").inc()
                logger.exception(
                    "repl: follower compaction failed; journal keeps growing"
                )
        return new_height

    def install_snapshot(self, raw: bytes) -> int:
        """Follower-side bootstrap: replace the live in-memory state with
        the leader's snapshot wholesale, persist it as this node's own
        `<wal>.snap`, and truncate the local journal — the shipped deltas
        that follow build on exactly this base. Returns the new height."""
        from ...crypto.serialization import loads

        d = loads(raw)
        with self._lock:
            self._state = dict(d["state"])
            self._spent = set(d["spent"])
            self._blocks = [Block(*row) for row in d["blocks"]]
            self._status = {
                t: FinalityEvent(t, TxStatus(s), m)
                for t, (s, m) in d["status"].items()
            }
            height = len(self._blocks)
        if self._wal is not None:
            try:
                self._compact()
            except Exception:
                mx.counter("wal.snapshot_failures").inc()
                logger.exception(
                    "repl: bootstrap snapshot persist failed; follower "
                    "holds the state in memory only until the next "
                    "successful compaction"
                )
        mx.counter("repl.bootstraps").inc()
        mx.gauge("network.height").set(height)
        mx.flight("repl.bootstrap", height=height, bytes=len(raw))
        return height

    def _notify(self, event: FinalityEvent, request: TokenRequest) -> None:
        """Per-listener crash isolation: a throwing finality listener is
        counted and logged, never allowed to abort the commit loop."""
        for listener in self._listeners:
            try:
                listener(event, request)
            except Exception:
                mx.counter("ledger.listener.errors").inc()
                logger.exception(
                    "ledger: finality listener failed for tx %s", event.tx_id
                )

    # --------------------------------------------------- checkpoint/resume

    def snapshot(self) -> bytes:
        """Serialize ledger state (checkpoint; reference parity: vault +
        ledger recovery on node restart)."""
        from ...crypto.serialization import dumps

        with self._lock:
            return dumps(
                {
                    "state": dict(self._state),
                    "spent": sorted(self._spent),
                    "blocks": [[b.number, b.txs, b.timestamp] for b in self._blocks],
                    "status": {
                        t: [e.status.value, e.message]
                        for t, e in self._status.items()
                    },
                }
            )

    @classmethod
    def restore(cls, validator: RequestValidator, raw: bytes,
                policy: Optional[BlockPolicy] = None) -> "Network":
        from ...crypto.serialization import loads

        d = loads(raw)
        net = cls(validator, policy=policy)
        net._state = dict(d["state"])
        net._spent = set(d["spent"])
        net._blocks = [Block(*row) for row in d["blocks"]]
        net._status = {
            t: FinalityEvent(t, TxStatus(s), m) for t, (s, m) in d["status"].items()
        }
        return net

    @classmethod
    def recover(cls, validator: RequestValidator, wal_path: str,
                policy: Optional[BlockPolicy] = None,
                snapshot_every: Optional[int] = None) -> "Network":
        """Rebuild a crashed node's ledger: latest snapshot (if any) plus
        a replay of the WAL suffix, then keep journaling to the same
        files. Records at heights the snapshot already covers are skipped
        (the crash-between-snapshot-and-truncate window); a torn final
        record is discarded by `WriteAheadLog.replay`. A height GAP means
        the journal lost acknowledged blocks — that is unrecoverable and
        raises `WALError` rather than resurrecting a forked ledger."""
        from ...crypto.serialization import loads

        snap_path = str(wal_path) + ".snap"
        if os.path.exists(snap_path):
            with open(snap_path, "rb") as fh:
                net = cls.restore(validator, fh.read(), policy=policy)
        else:
            net = cls(validator, policy=policy)
        wal = WriteAheadLog(wal_path)
        replayed = 0
        records = 0
        # streaming replay (replay_iter): one record in memory at a time,
        # so recovering a multi-GiB journal costs O(largest record) RSS
        for _off, raw in wal.replay_iter():
            records += 1
            d = loads(raw)
            height = d["height"]
            if height < len(net._blocks):
                if replayed:
                    # a low height is only legitimate BEFORE the first
                    # applied record (the snapshot-covered prefix); after
                    # that it means two blocks were journaled at one
                    # height — a forked journal, not a replayable one
                    raise WALError(
                        f"wal {wal_path}: duplicate record at height "
                        f"{height} after replay began"
                    )
                continue  # prefix already captured by the snapshot
            if height > len(net._blocks):
                raise WALError(
                    f"wal {wal_path}: record at height {height} but ledger "
                    f"recovered only {len(net._blocks)} blocks (journal gap)"
                )
            net._apply_wal_record(d)
            replayed += 1
        mx.counter("wal.replayed.records").inc(records)
        net._wal = wal
        net._snapshot_path = snap_path
        if snapshot_every is not None:
            net.snapshot_every = snapshot_every
        mx.counter("wal.recoveries").inc()
        mx.counter("wal.replayed.blocks").inc(replayed)
        mx.flight("wal.recover", blocks=len(net._blocks), replayed=replayed)
        mx.gauge("network.height").set(len(net._blocks))
        logger.info(
            "ledger: recovered %d blocks (%d from wal replay) from %s",
            len(net._blocks), replayed, wal_path,
        )
        return net
