"""Auditor service: inspect request openings, record, endorse.

Reference: `token/services/auditor/*` + `zkatdlog/crypto/audit/auditor.go`.
The auditor receives every request before ordering, opens all outputs from
the metadata, checks consistency with the on-ledger commitments, records
the flows, and signs.
"""

from __future__ import annotations

from typing import List, Optional

from ...api.driver import Driver, ValidationError
from ...api.request import TokenRequest
from ...api.wallet import AuditorWallet
from ...crypto.serialization import loads
from ...models.token import ID
from ..ttxdb.db import MovementDirection, TransactionDB, TxType


class AuditorService:
    def __init__(self, driver: Driver, wallet: AuditorWallet, db: Optional[TransactionDB] = None):
        self.driver = driver
        self.wallet = wallet
        self.db = db or TransactionDB()

    @property
    def identity(self) -> bytes:
        return self.wallet.identity

    def audit(self, request: TokenRequest) -> None:
        """Open every output against its metadata; raise on mismatch; sign."""
        for rec in request.issues:
            outputs = loads(rec.action)["outputs"]
            if len(rec.outputs_metadata) != len(outputs):
                raise ValidationError("audit: metadata does not cover all issue outputs")
            total = 0
            token_type = ""
            for idx, (raw, meta) in enumerate(zip(outputs, rec.outputs_metadata)):
                ut = self.driver.output_to_unspent(ID(request.anchor, idx), raw, meta)
                total += int(ut.quantity)
                token_type = ut.type
            self.db.add_transaction(
                request.anchor, TxType.ISSUE, "", "", token_type, total, "Pending"
            )
        for rec in request.transfers:
            outputs = loads(rec.action)["outputs"]
            if len(rec.outputs_metadata) != len(outputs):
                raise ValidationError("audit: metadata does not cover all transfer outputs")
            total = 0
            redeemed = 0
            token_type = ""
            for idx, (raw, meta) in enumerate(zip(outputs, rec.outputs_metadata)):
                # redeem (burn) outputs are audited too: their openings must
                # still match, and the burned amount is recorded
                ut = self.driver.output_to_unspent(ID(request.anchor, idx), raw, meta)
                token_type = ut.type
                if self.driver.output_owner(raw):
                    total += int(ut.quantity)
                else:
                    redeemed += int(ut.quantity)
            self.db.add_transaction(
                request.anchor,
                TxType.REDEEM if redeemed else TxType.TRANSFER,
                "", "", token_type, total + redeemed, "Pending",
            )
        request.auditor_signature = self.wallet.sign(request.marshal_to_audit())

    def on_finality(self, event, request) -> None:
        status = "Confirmed" if event.status.value == "Valid" else "Deleted"
        if self.db.status(event.tx_id) is not None:
            self.db.set_status(event.tx_id, status)
