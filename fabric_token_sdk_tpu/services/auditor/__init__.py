from .auditor import AuditorService  # noqa: F401
