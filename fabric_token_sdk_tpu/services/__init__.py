"""Token services runtime: network, vault, selector, ttx, ttxdb, auditor,
owner, query, certifier, nfttx, interop.

Reference: `token/services/*`. The reference rides fabric-smart-client on a
Fabric network; ours is a self-contained runtime with an in-memory MVCC
ledger (deterministic, race-detecting) that the same service APIs drive.
"""
