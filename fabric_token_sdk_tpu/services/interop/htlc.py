"""Hash-time-locked contracts: cross-network atomic-swap ownership scripts.

Reference: `token/services/interop/htlc/*` (script.go, lock.go, claim
views) and `token/core/interop/htlc`. A token owned by an HTLC script can
be claimed by the recipient with the hash preimage before the deadline, or
reclaimed by the sender after it.
"""

from __future__ import annotations

import hashlib
import time as _time
from dataclasses import dataclass
from typing import Optional

from ...crypto.serialization import dumps, guard, loads
from ...drivers import identity as identity_mod


@dataclass
class HTLCScript:
    sender: bytes  # identity that can reclaim after the deadline
    recipient: bytes  # identity that can claim with the preimage
    deadline: float  # unix seconds
    hash_value: bytes  # H(preimage)
    hash_func: str = "sha256"

    def to_identity(self) -> bytes:
        return identity_mod.htlc_identity(
            {
                "sender": self.sender,
                "recipient": self.recipient,
                "deadline": self.deadline,
                "hash": self.hash_value,
                "hash_func": self.hash_func,
            }
        )

    @classmethod
    def from_identity(cls, raw: bytes) -> "HTLCScript":
        d = identity_mod.parse(raw)
        if d["t"] != "htlc":
            raise ValueError("identity is not an HTLC script")
        s = d["script"]
        return cls(s["sender"], s["recipient"], s["deadline"], s["hash"], s["hash_func"])

    def check_preimage(self, preimage: bytes) -> bool:
        h = hashlib.new(self.hash_func)
        h.update(preimage)
        return h.digest() == self.hash_value


def lock(sender_identity: bytes, recipient_identity: bytes, preimage_hash: bytes,
         deadline: float, hash_func: str = "sha256") -> HTLCScript:
    """Build the script under which locked tokens are owned."""
    return HTLCScript(sender_identity, recipient_identity, deadline,
                      preimage_hash, hash_func)


@dataclass
class HTLCClaimSignature:
    """Signature wrapper carrying the preimage for claims (reference:
    htlc claim signature = recipient sig + preimage)."""

    preimage: bytes
    inner: bytes  # recipient identity's signature

    def to_bytes(self) -> bytes:
        return dumps({"p": self.preimage, "s": self.inner})

    @classmethod
    def from_bytes(cls, raw: bytes) -> "HTLCClaimSignature":
        d = loads(raw)
        return cls(d["p"], d["s"])


def claim(script: HTLCScript, preimage: bytes, recipient_sign, message: bytes,
          now: Optional[float] = None) -> bytes:
    """Recipient claims before the deadline with the correct preimage."""
    now = _time.time() if now is None else now
    if now >= script.deadline:
        raise ValueError("htlc: deadline passed, claim window closed")
    if not script.check_preimage(preimage):
        raise ValueError("htlc: wrong preimage")
    return HTLCClaimSignature(preimage, recipient_sign(message)).to_bytes()


def reclaim(script: HTLCScript, sender_sign, message: bytes,
            now: Optional[float] = None) -> bytes:
    """Sender reclaims after the deadline."""
    now = _time.time() if now is None else now
    if now < script.deadline:
        raise ValueError("htlc: deadline not reached, cannot reclaim")
    return sender_sign(message)


@guard
def verify_htlc_spend(script_identity: bytes, message: bytes, signature: bytes,
                      nym_params=None, now: Optional[float] = None) -> None:
    """Validator-side script check: claim (preimage + recipient sig before
    deadline) or reclaim (sender sig after deadline)."""
    script = HTLCScript.from_identity(script_identity)
    now = _time.time() if now is None else now
    if now < script.deadline:
        sig = HTLCClaimSignature.from_bytes(signature)
        if not script.check_preimage(sig.preimage):
            raise ValueError("htlc: invalid claim preimage")
        identity_mod.verify_signature(script.recipient, message, sig.inner, nym_params)
    else:
        identity_mod.verify_signature(script.sender, message, signature, nym_params)
