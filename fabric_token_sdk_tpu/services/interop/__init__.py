from .htlc import HTLCScript, lock, claim, reclaim  # noqa: F401
