"""Pluggable token-store backends for the vault (the client state plane).

Reference: `token/services/vault/*` — the Go SDK keeps owned tokens in a
DB-backed token store behind a query engine; here the same split lives as
a small SPI (`TokenStore`) with two implementations:

* `InMemoryTokenStore` — the historical behavior (everything in dicts),
  now with a selection index: tokens are bucketed by
  ``(token_type, owner)`` and each bucket keeps its candidates
  quantity-DESCENDING, so `Selector.select` walks only the tokens of the
  requested type (largest first — fewest locks to reach an amount)
  instead of scanning the whole vault per retry.
* `PersistentTokenStore` — the crash-safe backend: every applied
  `VaultDelta` (one acknowledged finality event: spent-deletes +
  stored-outputs + certifications) is appended to the same CRC-framed
  fsync'd journal the ledger uses (`services/network/wal.py`) BEFORE it
  mutates the in-memory view, with atomic snapshot compaction
  (tmp+rename+fsync, directory fsync'd before the journal truncate) every
  `FTS_VAULT_SNAPSHOT_EVERY` events. `PersistentTokenStore.recover` =
  snapshot + journal replay with torn-tail truncation — a client process
  SIGKILLed mid-workload restarts with exactly the acknowledged state.

Recovery invariants (vs the ledger WAL, whose records are height-chained):
vault deltas are IDEMPOTENT — stores set unique keys, spends delete keys
— and the journal is only ever truncated as a whole after a snapshot is
durably on disk, so the crash-between-snapshot-and-truncate window
replays the complete since-last-reset history on top of the snapshot and
converges to the same state (no heights needed). Causality is preserved
without a global append+apply lock because an event spending a token can
only be constructed AFTER the event storing it was fully applied (and
therefore journaled) — journal order can never spend-before-store.

A FAILED journal append degrades LOUDLY, never corruptingly: the counter
`vault.append_failures` + a `vault.append_failed` flight event fire, the
in-memory view still applies (this process keeps working), only the
durability promise is degraded until the journal heals.
"""

from __future__ import annotations

import heapq
import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ...models.token import ID, UnspentToken
from ...utils import faults
from ...utils import metrics as mx
from ...utils.tracing import logger
from ..network.wal import WriteAheadLog, fsync_dir


@dataclass
class StoredToken:
    id: ID
    output: bytes
    metadata: Optional[bytes]
    decoded: Optional[UnspentToken] = None  # cached opening (immutable)


@dataclass
class VaultDelta:
    """The vault-state change of ONE acknowledged finality event — the
    unit of atomicity (and, in the persistent store, of journaling)."""

    tx_id: str = ""
    spends: List[str] = field(default_factory=list)  # token keys deleted
    stores: List[StoredToken] = field(default_factory=list)
    certs: List[Tuple[str, bytes]] = field(default_factory=list)


class _Bucket:
    """Quantity-descending candidate set of one (type, owner) bucket.

    Mutation-cheap and iteration-lazy: `add` appends to a pending list,
    `discard` only counts a tombstone, and `merged()` (called under the
    store lock at selection time) folds pending entries into the sorted
    list — building a NEW list whenever it changes, so an iterator handed
    out earlier keeps walking its own consistent snapshot. Two
    compaction mechanisms keep selection cost bounded under sustained
    select+spend load: the DEAD PREFIX is trimmed on every `merged()`
    (selection picks largest-first, so spent tokens pile up exactly at
    the front — each trimmed entry is examined once, amortized O(1) per
    spend), and a full rebuild fires once mid-list tombstones outnumber
    the live entries. A million appends cost one O(n log n) sort at the
    next selection, not a million O(n) insorts.
    """

    __slots__ = ("_sorted", "_pending", "_live", "_stale")

    def __init__(self):
        self._sorted: List[Tuple[int, str]] = []  # (-quantity, key)
        self._pending: List[Tuple[int, str]] = []
        self._live: Dict[str, int] = {}  # key -> quantity (the truth)
        self._stale = 0

    def add(self, key: str, quantity: int) -> None:
        self._live[key] = quantity
        self._pending.append((-quantity, key))

    def discard(self, key: str) -> None:
        if self._live.pop(key, None) is not None:
            self._stale += 1

    def __len__(self) -> int:
        return len(self._live)

    def merged(self) -> List[Tuple[int, str]]:
        """The sorted candidate list (may contain tombstones — callers
        re-check liveness per key). Call under the owning store's lock."""
        live = self._live
        if self._pending or self._stale > len(live):
            self._sorted = sorted(
                e for e in self._sorted + self._pending if e[1] in live
            )
            self._pending = []
            self._stale = 0
        elif self._stale:
            # trim the dead PREFIX (a new list: snapshots stay immutable)
            lst = self._sorted
            i = 0
            while i < len(lst) and lst[i][1] not in live:
                i += 1
            if i:
                self._sorted = lst[i:]
                self._stale -= i
        return self._sorted


class TokenStore:
    """SPI of the vault's storage plane. Implementations must make
    `apply` atomic with respect to every reader."""

    def apply(self, delta: VaultDelta) -> Dict[str, int]:
        """Apply one finality event's delta; returns counts
        (`spent`/`stored`/`certs_dropped`) for the vault's metrics."""
        raise NotImplementedError

    def get(self, key: str) -> Optional[StoredToken]:
        raise NotImplementedError

    def tokens(self) -> List[StoredToken]:
        """Every stored token, insertion-ordered (API-compat with the
        pre-SPI vault, which several suites rely on)."""
        raise NotImplementedError

    def candidates(self, token_type: str,
                   owner: Optional[bytes] = None) -> Iterator[Tuple[int, str]]:
        """(quantity, key) pairs of one type (optionally one owner),
        quantity-descending. Entries may be stale — re-check via
        `get`."""
        raise NotImplementedError

    def certification(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryTokenStore(TokenStore):
    def __init__(self):
        self._lock = threading.Lock()
        self._tokens: Dict[str, StoredToken] = {}  # insertion-ordered
        self._certs: Dict[str, bytes] = {}
        # token_type -> owner bytes -> quantity-ordered bucket
        self._index: Dict[str, Dict[bytes, _Bucket]] = {}

    # ------------------------------------------------------------ writes

    def apply(self, delta: VaultDelta) -> Dict[str, int]:
        with self._lock:
            return self._apply_locked(delta)

    def _apply_locked(self, delta: VaultDelta) -> Dict[str, int]:
        spent = certs_dropped = stored = 0
        for key in delta.spends:
            st = self._tokens.pop(key, None)
            if st is None:
                continue
            spent += 1
            self._unindex(st)
            # certifications die with their token — an unbounded cert map
            # for spent tokens is a leak, not a feature
            if self._certs.pop(key, None) is not None:
                certs_dropped += 1
        for st in delta.stores:
            self._tokens[st.id.key()] = st
            self._index_add(st)
            stored += 1
        for key, cert in delta.certs:
            self._certs[key] = cert
        return {"spent": spent, "stored": stored, "certs_dropped": certs_dropped}

    def _index_add(self, st: StoredToken) -> None:
        ut = st.decoded
        if ut is None:
            return  # unopenable tokens are held but never selectable
        bucket = self._index.setdefault(ut.type, {}).setdefault(
            ut.owner.raw, _Bucket()
        )
        bucket.add(st.id.key(), int(ut.quantity))

    def _unindex(self, st: StoredToken) -> None:
        ut = st.decoded
        if ut is None:
            return
        owners = self._index.get(ut.type)
        if owners is not None:
            bucket = owners.get(ut.owner.raw)
            if bucket is not None:
                bucket.discard(st.id.key())

    # ------------------------------------------------------------ reads

    def get(self, key: str) -> Optional[StoredToken]:
        with self._lock:
            return self._tokens.get(key)

    def tokens(self) -> List[StoredToken]:
        with self._lock:
            return list(self._tokens.values())

    def candidates(self, token_type: str,
                   owner: Optional[bytes] = None) -> Iterator[Tuple[int, str]]:
        with self._lock:
            owners = self._index.get(token_type)
            if not owners:
                return iter(())
            if owner is not None:
                bucket = owners.get(owner)
                lists = [bucket.merged()] if bucket is not None else []
            else:
                lists = [b.merged() for b in owners.values()]
        if not lists:
            return iter(())
        # merged() snapshots are never mutated in place, so iterating
        # them outside the lock is safe; stale keys filter at the caller
        it = iter(lists[0]) if len(lists) == 1 else heapq.merge(*lists)
        return ((-neg_q, key) for neg_q, key in it)

    def certification(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._certs.get(key)

    def cert_count(self) -> int:
        with self._lock:
            return len(self._certs)

    def __len__(self) -> int:
        with self._lock:
            return len(self._tokens)


def decoded_token(decode: Callable[[ID, bytes, Optional[bytes]], UnspentToken],
                  token_id: ID, output: bytes,
                  metadata: Optional[bytes]) -> StoredToken:
    """Build a StoredToken, tolerating (and counting) opening failures —
    a token whose metadata rotted is held raw, flagged, never selectable."""
    try:
        decoded = decode(token_id, output, metadata)
    except Exception as e:
        logger.warning("vault: cannot open token %s: %s", token_id, e)
        mx.counter("vault.tokens.open_failures").inc()
        decoded = None
    return StoredToken(token_id, output, metadata, decoded)


class PersistentTokenStore(InMemoryTokenStore):
    """Crash-safe vault backend: journal-then-apply per finality event,
    atomic snapshot compaction, recovery = snapshot + delta replay.

    Constructing on an EXISTING journal path keeps appending after
    whatever is already there — rebuild state first via
    `PersistentTokenStore.recover(...)` (or `Vault.recover`), exactly
    like `Network.recover` vs `Network(wal_path=...)`.
    """

    def __init__(self, path: str, snapshot_every: Optional[int] = None,
                 sync: Optional[bool] = None):
        super().__init__()
        self.path = str(path)
        self.snapshot_path = self.path + ".snap"
        self.snapshot_every = (
            int(os.environ.get("FTS_VAULT_SNAPSHOT_EVERY", "256"))
            if snapshot_every is None else snapshot_every
        )
        self._wal = WriteAheadLog(self.path, sync=sync)
        # serializes journal+apply against compaction, so a snapshot can
        # never miss an event whose journal record it is about to erase;
        # readers only ever contend on the (brief) in-memory lock
        self._io_lock = threading.Lock()
        self._events = 0

    # ------------------------------------------------------------ writes

    def apply(self, delta: VaultDelta) -> Dict[str, int]:
        record = self._record(delta)
        with self._io_lock:
            try:
                faults.fire("vault.append")
                self._wal.append(record)
                mx.counter("vault.appends").inc()
            except Exception:
                # durability degraded, view intact: LOUD, not corrupting
                mx.counter("vault.append_failures").inc()
                mx.flight("vault.append_failed", tx=delta.tx_id)
                logger.exception(
                    "vault: journal append failed for %r (in-memory view "
                    "unaffected; durability degraded until the journal "
                    "heals)", delta.tx_id,
                )
            with self._lock:
                stats = self._apply_locked(delta)
            self._events += 1
            due = (
                self.snapshot_every > 0
                and self._events % self.snapshot_every == 0
            )
        if due:
            try:
                self.compact()
            except Exception:
                # the event is already durable in the journal; a failed
                # compaction only means the journal keeps growing
                mx.counter("vault.snapshot_failures").inc()
                logger.exception(
                    "vault: snapshot compaction failed; journal keeps growing"
                )
        return stats

    def compact(self) -> None:
        """Write a full snapshot (atomic tmp+rename+fsync, dir fsync'd
        BEFORE the journal truncate — power loss can never persist the
        truncate but lose the rename), then reset the journal."""
        with self._io_lock:
            faults.fire("vault.snapshot")
            raw = self._snapshot_bytes()
            tmp = f"{self.snapshot_path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as fh:
                fh.write(raw)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.snapshot_path)
            if self._wal.sync:
                fsync_dir(self.snapshot_path)
            self._wal.reset()
        mx.counter("vault.snapshots").inc()

    def close(self) -> None:
        self._wal.close()

    # ------------------------------------------------------------ format

    @staticmethod
    def _rows(stored: List[StoredToken]) -> list:
        return [[st.id.tx_id, st.id.index, st.output, st.metadata]
                for st in stored]

    def _record(self, delta: VaultDelta) -> bytes:
        from ...crypto.serialization import dumps

        return dumps({
            "tx": delta.tx_id,
            "spends": list(delta.spends),
            "stores": self._rows(delta.stores),
            "certs": [[k, c] for k, c in delta.certs],
        })

    def _snapshot_bytes(self) -> bytes:
        from ...crypto.serialization import dumps

        with self._lock:
            return dumps({
                "tokens": self._rows(list(self._tokens.values())),
                "certs": [[k, c] for k, c in self._certs.items()],
            })

    # ------------------------------------------------------------ recover

    @classmethod
    def recover(cls, path: str,
                decode: Callable[[ID, bytes, Optional[bytes]], UnspentToken],
                snapshot_every: Optional[int] = None,
                sync: Optional[bool] = None) -> "PersistentTokenStore":
        """Rebuild a crashed client's store: latest snapshot (if any)
        plus a replay of the journal suffix (torn tail truncated by
        `WriteAheadLog.replay`), then keep journaling to the same files.
        `decode` re-opens each token (driver-backed in `Vault.recover`);
        opening failures are tolerated per token, never fatal."""
        faults.fire("vault.recover")
        from ...crypto.serialization import loads

        store = cls(path, snapshot_every=snapshot_every, sync=sync)
        if os.path.exists(store.snapshot_path):
            with open(store.snapshot_path, "rb") as fh:
                d = loads(fh.read())
            snap = VaultDelta(
                stores=[
                    decoded_token(decode, ID(t, i), o, m)
                    for t, i, o, m in d["tokens"]
                ],
                certs=[(k, c) for k, c in d["certs"]],
            )
            with store._lock:
                store._apply_locked(snap)
        replayed = 0
        for raw in store._wal.replay():
            d = loads(raw)
            delta = VaultDelta(
                tx_id=d["tx"],
                spends=list(d["spends"]),
                stores=[
                    decoded_token(decode, ID(t, i), o, m)
                    for t, i, o, m in d["stores"]
                ],
                certs=[(k, c) for k, c in d["certs"]],
            )
            with store._lock:
                store._apply_locked(delta)
            replayed += 1
        mx.counter("vault.recoveries").inc()
        mx.counter("vault.replayed.events").inc(replayed)
        mx.flight("vault.recover", tokens=len(store), replayed=replayed)
        logger.info(
            "vault: recovered %d tokens (%d journal events replayed) from %s",
            len(store), replayed, path,
        )
        return store
