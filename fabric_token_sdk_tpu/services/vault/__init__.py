from .store import (  # noqa: F401
    InMemoryTokenStore,
    PersistentTokenStore,
    StoredToken,
    TokenStore,
    VaultDelta,
)
from .vault import Vault  # noqa: F401
