from .vault import Vault  # noqa: F401
