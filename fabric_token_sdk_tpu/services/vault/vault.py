"""Per-party token vault: owned unspent tokens + certification store.

Reference: `token/services/vault/*` (token store, query engine,
certification) and `token/vault.go`. The vault subscribes to network
finality events; on every valid tx it deletes spent tokens (dropping
their certifications with them) and stores the outputs owned by this
party's wallets (openings arrive via the request metadata the party
already holds off-chain).

Storage is pluggable (`store.py`): the default `InMemoryTokenStore`
keeps the historical in-process behavior, `PersistentTokenStore` makes
the vault crash-safe (journal-then-apply per finality event, snapshot
compaction, `Vault.recover(path, ...)` after a crash). Every finality
event applies as ONE atomic `VaultDelta` — spends, stores and
certifications land together or not at all, in memory and on disk.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ...api.driver import Driver
from ...api.request import TokenRequest
from ...models.token import ID, UnspentToken
from ...utils import metrics as mx
from ..network.ledger import FinalityEvent, TxStatus
from .store import (  # noqa: F401  (StoredToken re-exported for compat)
    InMemoryTokenStore,
    PersistentTokenStore,
    StoredToken,
    TokenStore,
    VaultDelta,
    decoded_token,
)


class Vault:
    def __init__(self, driver: Driver, owns_identity: Callable[[bytes], bool],
                 store: Optional[TokenStore] = None):
        self.driver = driver
        self.owns_identity = owns_identity
        self.store = store if store is not None else InMemoryTokenStore()

    @classmethod
    def recover(cls, path: str, driver: Driver,
                owns_identity: Callable[[bytes], bool],
                snapshot_every: Optional[int] = None,
                sync: Optional[bool] = None) -> "Vault":
        """Rebuild a crashed client's vault from its journal + snapshot
        (`PersistentTokenStore.recover`): every finality event this
        process ever acknowledged is replayed — balances equal the
        acknowledged-finality replay, a torn journal tail is truncated,
        and the vault keeps journaling to the same files."""

        def decode(token_id: ID, output: bytes,
                   metadata: Optional[bytes]) -> UnspentToken:
            return driver.output_to_unspent(token_id, output, metadata)

        store = PersistentTokenStore.recover(
            path, decode, snapshot_every=snapshot_every, sync=sync
        )
        return cls(driver, owns_identity, store=store)

    # ------------------------------------------------------------ process

    def on_finality(self, event: FinalityEvent, request: TokenRequest) -> None:
        """Network finality listener (reference: vault processor)."""
        if event.status != TxStatus.VALID:
            return
        tx_id = event.tx_id
        with mx.span("vault.on_finality", tx=tx_id):
            delta = VaultDelta(tx_id)
            for rec in request.transfers:
                delta.spends.extend(t.key() for t in rec.input_ids)
            # store owned outputs; output indices are global across actions
            out_index = 0
            for rec in list(request.issues) + list(request.transfers):
                metas = rec.outputs_metadata
                outputs = self._action_outputs(rec.action)
                for raw, meta in zip(outputs, metas):
                    st = self._maybe_stored(tx_id, out_index, raw, meta)
                    if st is not None:
                        delta.stores.append(st)
                    out_index += 1
            stats = self.store.apply(delta)
            mx.counter("vault.tokens.spent").inc(stats["spent"])
            mx.counter("vault.certs.dropped").inc(stats["certs_dropped"])
            mx.gauge("vault.tokens.held").set(len(self.store))

    def _action_outputs(self, action_bytes: bytes) -> List[bytes]:
        from ...crypto.serialization import loads

        return loads(action_bytes)["outputs"]

    def _maybe_stored(self, tx_id: str, index: int, output: bytes,
                      metadata: Optional[bytes]) -> Optional[StoredToken]:
        owner = self.driver.output_owner(output)
        if not owner or not self.owns_identity(owner):
            return None
        # decoded_token holds the ONE copy of the open-failure policy
        # (keep raw bytes, flag loudly, token unusable until re-delivered)
        # shared with the recovery path
        st = decoded_token(
            self.driver.output_to_unspent, ID(tx_id, index), output, metadata
        )
        if st.decoded is not None:
            mx.counter("vault.tokens.stored").inc()
        return st

    # ------------------------------------------------------------ queries

    def unspent_tokens(self, token_type: Optional[str] = None) -> List[UnspentToken]:
        return [
            st.decoded
            for st in self.store.tokens()
            if st.decoded is not None
            and (token_type is None or st.decoded.type == token_type)
        ]

    def iter_unspent(self, token_type: str):
        """Quantity-descending candidates of one type, via the
        (type, owner) selection index — the selector's walk touches only
        candidate tokens, never the whole vault. Stale index entries
        (spent since the snapshot) filter out against the live store."""
        for _quantity, key in self.store.candidates(token_type):
            st = self.store.get(key)
            if st is not None and st.decoded is not None:
                yield st.decoded

    def get(self, token_id: ID) -> Optional[StoredToken]:
        return self.store.get(token_id.key())

    def get_many(self, ids) -> Tuple[List[bytes], List[bytes]]:
        outputs, metas = [], []
        for i in ids:
            st = self.store.get(i.key())
            if st is None:
                raise KeyError(f"token {i} not in vault")
            outputs.append(st.output)
            metas.append(st.metadata)
        return outputs, metas

    def balance(self, token_type: str) -> int:
        return sum(int(t.quantity) for t in self.unspent_tokens(token_type))

    def token_ids(self) -> List[ID]:
        return [st.id for st in self.store.tokens()]

    # ------------------------------------------------------------ certify

    def store_certification(self, token_id: ID, cert: bytes) -> None:
        # routed through apply() so a persistent store journals it with
        # the same durability as token state
        self.store.apply(VaultDelta(certs=[(token_id.key(), cert)]))

    def certification(self, token_id: ID) -> Optional[bytes]:
        return self.store.certification(token_id.key())
