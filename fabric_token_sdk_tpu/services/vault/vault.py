"""Per-party token vault: owned unspent tokens + certification store.

Reference: `token/services/vault/*` (token store, query engine,
certification) and `token/vault.go`. The vault subscribes to network
finality events; on every valid tx it deletes spent tokens and stores the
outputs owned by this party's wallets (openings arrive via the request
metadata the party already holds off-chain).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ...api.driver import Driver
from ...api.request import TokenRequest
from ...models.quantity import Quantity
from ...models.token import ID, UnspentToken
from ...utils import metrics as mx
from ..network.ledger import FinalityEvent, TxStatus


@dataclass
class StoredToken:
    id: ID
    output: bytes
    metadata: Optional[bytes]
    decoded: Optional[UnspentToken] = None  # cached opening (immutable)


class Vault:
    def __init__(self, driver: Driver, owns_identity: Callable[[bytes], bool]):
        self.driver = driver
        self.owns_identity = owns_identity
        self._tokens: Dict[str, StoredToken] = {}
        self._certified: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ process

    def on_finality(self, event: FinalityEvent, request: TokenRequest) -> None:
        """Network finality listener (reference: vault processor)."""
        if event.status != TxStatus.VALID:
            return
        tx_id = event.tx_id
        with mx.span("vault.on_finality", tx=tx_id), self._lock:
            # delete spent
            for rec in request.transfers:
                for token_id in rec.input_ids:
                    if self._tokens.pop(token_id.key(), None) is not None:
                        mx.counter("vault.tokens.spent").inc()
            # store owned outputs; output indices are global across actions
            out_index = 0
            for rec in request.issues:
                metas = rec.outputs_metadata
                outputs = self._action_outputs(rec.action)
                for raw, meta in zip(outputs, metas):
                    self._maybe_store(tx_id, out_index, raw, meta)
                    out_index += 1
            for rec in request.transfers:
                metas = rec.outputs_metadata
                outputs = self._action_outputs(rec.action)
                for raw, meta in zip(outputs, metas):
                    self._maybe_store(tx_id, out_index, raw, meta)
                    out_index += 1
            mx.gauge("vault.tokens.held").set(len(self._tokens))

    def _action_outputs(self, action_bytes: bytes) -> List[bytes]:
        from ...crypto.serialization import loads

        return loads(action_bytes)["outputs"]

    def _maybe_store(self, tx_id: str, index: int, output: bytes, metadata: Optional[bytes]) -> None:
        owner = self.driver.output_owner(output)
        if not owner or not self.owns_identity(owner):
            return
        token_id = ID(tx_id, index)
        try:
            decoded = self.driver.output_to_unspent(token_id, output, metadata)
            mx.counter("vault.tokens.stored").inc()
        except Exception as e:
            # metadata missing/mismatched: keep raw bytes, flag loudly —
            # the token is unusable until re-delivered
            from ...utils.tracing import logger

            logger.warning("vault: cannot open owned token %s: %s", token_id, e)
            mx.counter("vault.tokens.open_failures").inc()
            decoded = None
        self._tokens[token_id.key()] = StoredToken(token_id, output, metadata, decoded)

    # ------------------------------------------------------------ queries

    def unspent_tokens(self, token_type: Optional[str] = None) -> List[UnspentToken]:
        with self._lock:
            stored = list(self._tokens.values())
        return [
            st.decoded
            for st in stored
            if st.decoded is not None
            and (token_type is None or st.decoded.type == token_type)
        ]

    def get(self, token_id: ID) -> Optional[StoredToken]:
        with self._lock:
            return self._tokens.get(token_id.key())

    def get_many(self, ids) -> Tuple[List[bytes], List[bytes]]:
        outputs, metas = [], []
        with self._lock:
            for i in ids:
                st = self._tokens.get(i.key())
                if st is None:
                    raise KeyError(f"token {i} not in vault")
                outputs.append(st.output)
                metas.append(st.metadata)
        return outputs, metas

    def balance(self, token_type: str) -> int:
        return sum(int(t.quantity) for t in self.unspent_tokens(token_type))

    def token_ids(self) -> List[ID]:
        with self._lock:
            return [st.id for st in self._tokens.values()]

    # ------------------------------------------------------------ certify

    def store_certification(self, token_id: ID, cert: bytes) -> None:
        with self._lock:
            self._certified[token_id.key()] = cert

    def certification(self, token_id: ID) -> Optional[bytes]:
        with self._lock:
            return self._certified.get(token_id.key())
