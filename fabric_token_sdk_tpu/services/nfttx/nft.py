"""Non-fungible tokens: unique unit-value tokens carrying JSON state.

Reference: `token/services/nfttx/*` (uuid.go, state.go, marshaller, qe.go).
An NFT is a quantity-1 token whose type encodes a unique id + the state's
hash; the JSON state itself travels in request application metadata and is
queryable from the owner's vault.
"""

from __future__ import annotations

import hashlib
import json
import uuid as uuid_mod
from typing import Any, Dict, List, Optional

from ...models.token import ID
from ..ttx.party import Party
from ..ttx.transaction import Transaction

NFT_PREFIX = "nft."


def _state_key(state: Dict[str, Any]) -> str:
    canonical = json.dumps(state, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode()).hexdigest()[:16]


class NFTService:
    """Issue/transfer/query unique tokens for a party."""

    def __init__(self, party: Party):
        self.party = party

    def issue(self, issuer_wallet: str, state: Dict[str, Any], recipient: bytes,
              auditor=None, tx_id: Optional[str] = None) -> str:
        """Mint a unique token for `state`; returns its token type."""
        unique = uuid_mod.uuid4().hex
        token_type = f"{NFT_PREFIX}{unique}.{_state_key(state)}"
        tx = Transaction(self.party, tx_id)
        tx.issue(issuer_wallet, token_type, [1], [recipient], anonymous=False)
        tx.request.set_application_metadata(
            f"nft.{token_type}", json.dumps(state, sort_keys=True).encode()
        )
        tx.collect_endorsements(auditor)
        tx.submit()
        return token_type

    def transfer(self, owner_wallet: str, token_type: str, recipient: bytes,
                 auditor=None, tx_id: Optional[str] = None) -> None:
        tx = Transaction(self.party, tx_id)
        tx.transfer(owner_wallet, token_type, [1], [recipient])
        tx.collect_endorsements(auditor)
        tx.submit()

    # ------------------------------------------------------------ queries

    def my_nfts(self) -> List[str]:
        return [
            t.type
            for t in self.party.vault.unspent_tokens()
            if t.type.startswith(NFT_PREFIX)
        ]

    def state_matches(self, token_type: str, state: Dict[str, Any]) -> bool:
        """Check a claimed state against the hash committed in the type."""
        return token_type.endswith("." + _state_key(state))
