from .nft import NFTService  # noqa: F401
