"""Query service: balances and token listings over a party's vault.

Reference: `token/services/query/*` (client.go, handler.go).
"""

from __future__ import annotations

from typing import Dict, List

from ...models.token import UnspentToken
from ..vault.vault import Vault


class QueryService:
    def __init__(self, vault: Vault):
        self.vault = vault

    def balance(self, token_type: str) -> int:
        return self.vault.balance(token_type)

    def all_my_tokens(self) -> List[UnspentToken]:
        return self.vault.unspent_tokens()

    def balances_by_type(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for t in self.vault.unspent_tokens():
            out[t.type] = out.get(t.type, 0) + int(t.quantity)
        return out
