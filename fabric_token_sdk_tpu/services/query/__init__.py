from .query import QueryService  # noqa: F401
