/* bn254 — native host BN254 arithmetic for the token framework runtime.
 *
 * The reference SDK's host math is IBM mathlib backed by gnark-crypto's
 * assembly BN254 (vendored dep; see reference token/core/zkatdlog/crypto
 * usage of `math.Curve`). Our control plane is Python; this library is its
 * native hot path: 4x64-limb Montgomery Fp, Jacobian G1, windowed scalar
 * multiplication and multi-exponentiation, batched over arrays so one
 * ctypes call covers a whole proof's worth of group ops.
 *
 * Interface convention: field elements and scalars cross the boundary as
 * 4 little-endian uint64 limbs (non-Montgomery); points as affine (x, y)
 * limb pairs plus an infinity flag byte. All conversion to/from Montgomery
 * happens inside. Plain C99 + unsigned __int128; built on demand and
 * loaded via ctypes with a pure-Python fallback (see __init__.py).
 */

#include <stdint.h>
#include <string.h>

typedef unsigned __int128 u128;
typedef uint64_t u64;

/* ------------------------------------------------------------------ Fp */

static const u64 Pmod[4] = {0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                            0xb85045b68181585dULL, 0x30644e72e131a029ULL};
static const u64 R2[4] = {0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                          0x47ab1eff0a417ff6ULL, 0x06d89f71cab8351fULL};
static const u64 N0 = 0x87d20782e4866389ULL; /* -P^-1 mod 2^64 */
static const u64 MONT_ONE[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                                0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};

typedef struct { u64 v[4]; } fp;

static inline int fp_is_zero(const fp *a) {
  return (a->v[0] | a->v[1] | a->v[2] | a->v[3]) == 0;
}

static inline int fp_eq(const fp *a, const fp *b) {
  return a->v[0] == b->v[0] && a->v[1] == b->v[1] && a->v[2] == b->v[2] &&
         a->v[3] == b->v[3];
}

/* a -= P if a >= P (constant shape, not constant time — host verifier) */
static inline void fp_reduce(fp *a) {
  u64 t[4];
  u128 bw = 0;
  for (int i = 0; i < 4; i++) {
    u128 d = (u128)a->v[i] - Pmod[i] - (u64)bw;
    t[i] = (u64)d;
    bw = (d >> 64) & 1; /* borrow */
  }
  if (!bw)
    memcpy(a->v, t, sizeof t);
}

static inline void fp_add(fp *r, const fp *a, const fp *b) {
  u128 c = 0;
  for (int i = 0; i < 4; i++) {
    c += (u128)a->v[i] + b->v[i];
    r->v[i] = (u64)c;
    c >>= 64;
  }
  /* a, b < P < 2^254 so no limb overflow past c; subtract P if needed */
  fp_reduce(r);
}

static inline void fp_sub(fp *r, const fp *a, const fp *b) {
  u128 bw = 0;
  u64 t[4];
  for (int i = 0; i < 4; i++) {
    u128 d = (u128)a->v[i] - b->v[i] - (u64)bw;
    t[i] = (u64)d;
    bw = (d >> 64) & 1;
  }
  if (bw) {
    u128 c = 0;
    for (int i = 0; i < 4; i++) {
      c += (u128)t[i] + Pmod[i];
      t[i] = (u64)c;
      c >>= 64;
    }
  }
  memcpy(r->v, t, sizeof t);
}

static inline void fp_neg(fp *r, const fp *a) {
  if (fp_is_zero(a)) {
    memset(r->v, 0, sizeof r->v);
    return;
  }
  u128 bw = 0;
  for (int i = 0; i < 4; i++) {
    u128 d = (u128)Pmod[i] - a->v[i] - (u64)bw;
    r->v[i] = (u64)d;
    bw = (d >> 64) & 1;
  }
}

/* CIOS Montgomery multiplication: r = a*b*R^-1 mod P */
static void fp_mul(fp *r, const fp *a, const fp *b) {
  u64 t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; i++) {
    u128 c = 0;
    for (int j = 0; j < 4; j++) {
      c += (u128)a->v[j] * b->v[i] + t[j];
      t[j] = (u64)c;
      c >>= 64;
    }
    c += t[4];
    t[4] = (u64)c;
    t[5] = (u64)(c >> 64);

    u64 m = t[0] * N0;
    c = (u128)m * Pmod[0] + t[0];
    c >>= 64;
    for (int j = 1; j < 4; j++) {
      c += (u128)m * Pmod[j] + t[j];
      t[j - 1] = (u64)c;
      c >>= 64;
    }
    c += t[4];
    t[3] = (u64)c;
    t[4] = t[5] + (u64)(c >> 64);
  }
  memcpy(r->v, t, 4 * sizeof(u64));
  if (t[4]) { /* result >= 2^256: subtract P once (t < 2P always in CIOS) */
    u128 bw = 0;
    for (int i = 0; i < 4; i++) {
      u128 d = (u128)r->v[i] - Pmod[i] - (u64)bw;
      r->v[i] = (u64)d;
      bw = (d >> 64) & 1;
    }
  } else {
    fp_reduce(r);
  }
}

static inline void fp_sqr(fp *r, const fp *a) { fp_mul(r, a, a); }

static void fp_to_mont(fp *r, const fp *a) {
  fp rr;
  memcpy(rr.v, R2, sizeof R2);
  fp_mul(r, a, &rr);
}

static void fp_from_mont(fp *r, const fp *a) {
  fp one = {{1, 0, 0, 0}};
  fp_mul(r, a, &one);
}

/* r = a^e mod P (a in Montgomery; e plain little-endian limbs) */
static void fp_pow(fp *r, const fp *a, const u64 e[4]) {
  fp acc, base = *a;
  memcpy(acc.v, MONT_ONE, sizeof MONT_ONE);
  for (int limb = 0; limb < 4; limb++) {
    u64 bits = e[limb];
    for (int i = 0; i < 64; i++) {
      if (bits & 1)
        fp_mul(&acc, &acc, &base);
      fp_sqr(&base, &base);
      bits >>= 1;
    }
  }
  *r = acc;
}

static void fp_inv(fp *r, const fp *a) {
  /* a^(P-2) */
  u64 e[4];
  memcpy(e, Pmod, sizeof e);
  u128 bw = 2;
  for (int i = 0; i < 4 && bw; i++) {
    u128 d = (u128)e[i] - (u64)bw;
    e[i] = (u64)d;
    bw = (d >> 64) & 1;
  }
  fp_pow(r, a, e);
}

/* ------------------------------------------------------------------ G1 */

/* Jacobian coordinates in Montgomery form; infinity <=> Z == 0. */
typedef struct { fp X, Y, Z; } g1;

static void g1_set_inf(g1 *p) { memset(p, 0, sizeof *p); }

static inline int g1_is_inf(const g1 *p) { return fp_is_zero(&p->Z); }

static void g1_from_affine(g1 *p, const fp *x, const fp *y) {
  fp_to_mont(&p->X, x);
  fp_to_mont(&p->Y, y);
  memcpy(p->Z.v, MONT_ONE, sizeof MONT_ONE);
}

static void g1_to_affine(const g1 *p, fp *x, fp *y, uint8_t *inf) {
  if (g1_is_inf(p)) {
    memset(x, 0, sizeof *x);
    memset(y, 0, sizeof *y);
    *inf = 1;
    return;
  }
  fp zi, zi2, zi3, t;
  fp_inv(&zi, &p->Z);
  fp_sqr(&zi2, &zi);
  fp_mul(&zi3, &zi2, &zi);
  fp_mul(&t, &p->X, &zi2);
  fp_from_mont(x, &t);
  fp_mul(&t, &p->Y, &zi3);
  fp_from_mont(y, &t);
  *inf = 0;
}

/* dbl-2009-l (a = 0): 2M + 5S */
static void g1_dbl(g1 *r, const g1 *p) {
  if (g1_is_inf(p) || fp_is_zero(&p->Y)) {
    g1_set_inf(r);
    return;
  }
  fp A, B, C, D, E, F, t;
  fp_sqr(&A, &p->X);
  fp_sqr(&B, &p->Y);
  fp_sqr(&C, &B);
  fp_add(&t, &p->X, &B);
  fp_sqr(&t, &t);
  fp_sub(&t, &t, &A);
  fp_sub(&t, &t, &C);
  fp_add(&D, &t, &t);
  fp_add(&E, &A, &A);
  fp_add(&E, &E, &A);
  fp_sqr(&F, &E);
  fp newX, newY, newZ;
  fp_add(&t, &D, &D);
  fp_sub(&newX, &F, &t);
  fp_sub(&t, &D, &newX);
  fp_mul(&t, &E, &t);
  fp c8;
  fp_add(&c8, &C, &C);
  fp_add(&c8, &c8, &c8);
  fp_add(&c8, &c8, &c8);
  fp_sub(&newY, &t, &c8);
  fp_mul(&newZ, &p->Y, &p->Z);
  fp_add(&newZ, &newZ, &newZ);
  r->X = newX;
  r->Y = newY;
  r->Z = newZ;
}

/* add-2007-bl: 11M + 5S, with doubling/inverse handling */
static void g1_add(g1 *r, const g1 *p, const g1 *q) {
  if (g1_is_inf(p)) {
    *r = *q;
    return;
  }
  if (g1_is_inf(q)) {
    *r = *p;
    return;
  }
  fp Z1Z1, Z2Z2, U1, U2, S1, S2, t;
  fp_sqr(&Z1Z1, &p->Z);
  fp_sqr(&Z2Z2, &q->Z);
  fp_mul(&U1, &p->X, &Z2Z2);
  fp_mul(&U2, &q->X, &Z1Z1);
  fp_mul(&t, &q->Z, &Z2Z2);
  fp_mul(&S1, &p->Y, &t);
  fp_mul(&t, &p->Z, &Z1Z1);
  fp_mul(&S2, &q->Y, &t);
  if (fp_eq(&U1, &U2)) {
    if (fp_eq(&S1, &S2)) {
      g1_dbl(r, p);
    } else {
      g1_set_inf(r);
    }
    return;
  }
  fp H, I, J, rr, V;
  fp_sub(&H, &U2, &U1);
  fp_add(&I, &H, &H);
  fp_sqr(&I, &I);
  fp_mul(&J, &H, &I);
  fp_sub(&rr, &S2, &S1);
  fp_add(&rr, &rr, &rr);
  fp_mul(&V, &U1, &I);
  fp newX, newY, newZ;
  fp_sqr(&t, &rr);
  fp_sub(&t, &t, &J);
  fp v2;
  fp_add(&v2, &V, &V);
  fp_sub(&newX, &t, &v2);
  fp_sub(&t, &V, &newX);
  fp_mul(&t, &rr, &t);
  fp s1j;
  fp_mul(&s1j, &S1, &J);
  fp_add(&s1j, &s1j, &s1j);
  fp_sub(&newY, &t, &s1j);
  fp_add(&t, &p->Z, &q->Z);
  fp_sqr(&t, &t);
  fp_sub(&t, &t, &Z1Z1);
  fp_sub(&t, &t, &Z2Z2);
  fp_mul(&newZ, &t, &H);
  r->X = newX;
  r->Y = newY;
  r->Z = newZ;
}

/* 4-bit fixed-window scalar multiplication; scalar as plain LE limbs.
 *
 * TIMING CAVEAT: this is VARIABLE-TIME — the per-digit branch (`if (d)`),
 * the `started` skip of leading zero windows, and the non-constant-time
 * fp_reduce all leak scalar-dependent timing. That was acceptable while
 * the native library served only the host VERIFIER (public scalars), but
 * hostmath.py now installs it as the fast path for proof generation and
 * signing too, where scalars are secrets (blinding factors, signing
 * keys). This matches the equally variable-time pure-Python fallback, so
 * it is not a regression — but if the threat model ever includes
 * co-located attackers able to measure wall time, a constant-time ladder
 * (fixed window read via table scan + unconditional add-and-select) must
 * replace this for prover-side calls. The same applies to g2_scalar_mul.
 */
static void g1_scalar_mul(g1 *r, const g1 *p, const u64 k[4]) {
  g1 table[16];
  g1_set_inf(&table[0]);
  table[1] = *p;
  for (int i = 2; i < 16; i++)
    g1_add(&table[i], &table[i - 1], p);
  g1 acc;
  g1_set_inf(&acc);
  int started = 0;
  for (int limb = 3; limb >= 0; limb--) {
    for (int w = 60; w >= 0; w -= 4) {
      if (started) {
        g1_dbl(&acc, &acc);
        g1_dbl(&acc, &acc);
        g1_dbl(&acc, &acc);
        g1_dbl(&acc, &acc);
      }
      unsigned d = (unsigned)((k[limb] >> w) & 0xF);
      if (d) {
        g1_add(&acc, &acc, &table[d]);
        started = 1;
      }
    }
  }
  *r = acc;
}

/* ------------------------------------------------------- exported API
 *
 * Buffers: xs/ys = n*4 u64 limbs (LE, non-Montgomery), inf = n bytes,
 * ks = n*4 u64 limbs. Outputs likewise.
 */

static void load_point(g1 *p, const u64 *xs, const u64 *ys,
                       const uint8_t *inf, long i) {
  if (inf && inf[i]) {
    g1_set_inf(p);
    return;
  }
  fp x, y;
  memcpy(x.v, xs + 4 * i, 4 * sizeof(u64));
  memcpy(y.v, ys + 4 * i, 4 * sizeof(u64));
  g1_from_affine(p, &x, &y);
}

static void store_point(const g1 *p, u64 *ox, u64 *oy, uint8_t *oinf,
                        long i) {
  fp x, y;
  uint8_t f;
  g1_to_affine(p, &x, &y, &f);
  memcpy(ox + 4 * i, x.v, 4 * sizeof(u64));
  memcpy(oy + 4 * i, y.v, 4 * sizeof(u64));
  oinf[i] = f;
}

/* out[i] = ks[i] * P[i] */
void fts_g1_mul_batch(const u64 *xs, const u64 *ys, const uint8_t *inf,
                      const u64 *ks, long n, u64 *ox, u64 *oy,
                      uint8_t *oinf) {
  for (long i = 0; i < n; i++) {
    g1 p, r;
    load_point(&p, xs, ys, inf, i);
    g1_scalar_mul(&r, &p, ks + 4 * i);
    store_point(&r, ox, oy, oinf, i);
  }
}

/* out = sum_i ks[i] * P[i] (one point out) */
void fts_g1_multiexp(const u64 *xs, const u64 *ys, const uint8_t *inf,
                     const u64 *ks, long n, u64 *ox, u64 *oy,
                     uint8_t *oinf) {
  g1 acc, p, t;
  g1_set_inf(&acc);
  for (long i = 0; i < n; i++) {
    load_point(&p, xs, ys, inf, i);
    g1_scalar_mul(&t, &p, ks + 4 * i);
    g1_add(&acc, &acc, &t);
  }
  store_point(&acc, ox, oy, oinf, 0);
}

/* out = sum_i P[i] */
void fts_g1_sum(const u64 *xs, const u64 *ys, const uint8_t *inf, long n,
                u64 *ox, u64 *oy, uint8_t *oinf) {
  g1 acc, p;
  g1_set_inf(&acc);
  for (long i = 0; i < n; i++) {
    load_point(&p, xs, ys, inf, i);
    g1_add(&acc, &acc, &p);
  }
  store_point(&acc, ox, oy, oinf, 0);
}

/* out[i] = sum over row i: one multiexp per row of fixed width m.
 * Covers Pedersen commitments (3-term) and digit aggregates in one call. */
void fts_g1_multiexp_rows(const u64 *xs, const u64 *ys, const uint8_t *inf,
                          const u64 *ks, long rows, long m, u64 *ox,
                          u64 *oy, uint8_t *oinf) {
  for (long r0 = 0; r0 < rows; r0++) {
    g1 acc, p, t;
    g1_set_inf(&acc);
    for (long j = 0; j < m; j++) {
      long i = r0 * m + j;
      load_point(&p, xs, ys, inf, i);
      g1_scalar_mul(&t, &p, ks + 4 * i);
      g1_add(&acc, &acc, &t);
    }
    store_point(&acc, ox, oy, oinf, r0);
  }
}

/* ------------------------------------------------------------------ Fp2
 * a + b i with i^2 = -1; components in Montgomery form. */

typedef struct { fp a, b; } fp2;

static const fp2 XI_M = {/* 9 + i */
    {{0xf60647ce410d7ff7ULL, 0x2f3d6f4dd31bd011ULL, 0x2943337e3940c6d1ULL,
      0x1d9598e8a7e39857ULL}},
    {{0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL, 0x666ea36f7879462cULL,
      0x0e0a77c19a07df2fULL}}};

static inline void fp2_add_(fp2 *r, const fp2 *x, const fp2 *y) {
  fp_add(&r->a, &x->a, &y->a);
  fp_add(&r->b, &x->b, &y->b);
}

static inline void fp2_sub_(fp2 *r, const fp2 *x, const fp2 *y) {
  fp_sub(&r->a, &x->a, &y->a);
  fp_sub(&r->b, &x->b, &y->b);
}

static inline void fp2_neg_(fp2 *r, const fp2 *x) {
  fp_neg(&r->a, &x->a);
  fp_neg(&r->b, &x->b);
}

static inline int fp2_is_zero(const fp2 *x) {
  return fp_is_zero(&x->a) && fp_is_zero(&x->b);
}

static inline int fp2_eq(const fp2 *x, const fp2 *y) {
  return fp_eq(&x->a, &y->a) && fp_eq(&x->b, &y->b);
}

static void fp2_mul_(fp2 *r, const fp2 *x, const fp2 *y) {
  /* Karatsuba: (a+bi)(c+di) = ac - bd + ((a+b)(c+d) - ac - bd) i */
  fp ac, bd, s1, s2, t;
  fp_mul(&ac, &x->a, &y->a);
  fp_mul(&bd, &x->b, &y->b);
  fp_add(&s1, &x->a, &x->b);
  fp_add(&s2, &y->a, &y->b);
  fp_mul(&t, &s1, &s2);
  fp_sub(&t, &t, &ac);
  fp_sub(&t, &t, &bd);
  fp_sub(&r->a, &ac, &bd);
  r->b = t;
}

static void fp2_sqr_(fp2 *r, const fp2 *x) {
  /* (a+bi)^2 = (a+b)(a-b) + 2ab i */
  fp s, d, ab;
  fp_add(&s, &x->a, &x->b);
  fp_sub(&d, &x->a, &x->b);
  fp_mul(&ab, &x->a, &x->b);
  fp_mul(&r->a, &s, &d);
  fp_add(&r->b, &ab, &ab);
}

static void fp2_inv_(fp2 *r, const fp2 *x) {
  fp n, t, ninv;
  fp_sqr(&n, &x->a);
  fp_sqr(&t, &x->b);
  fp_add(&n, &n, &t);
  fp_inv(&ninv, &n);
  fp_mul(&r->a, &x->a, &ninv);
  fp_mul(&t, &x->b, &ninv);
  fp_neg(&r->b, &t);
}

static inline void fp2_conj_(fp2 *r, const fp2 *x) {
  r->a = x->a;
  fp_neg(&r->b, &x->b);
}

static inline void fp2_dbl_(fp2 *r, const fp2 *x) { fp2_add_(r, x, x); }

/* ------------------------------------------------------------------ G2
 * Jacobian over Fp2 on the D-twist y^2 = x^3 + 3/XI; infinity <=> Z = 0.
 * Same a = 0 formulas as G1. */

typedef struct { fp2 X, Y, Z; } g2;

static void g2_set_inf(g2 *p) { memset(p, 0, sizeof *p); }

static inline int g2_is_inf(const g2 *p) { return fp2_is_zero(&p->Z); }

static void g2_from_affine(g2 *p, const fp2 *x, const fp2 *y) {
  fp_to_mont(&p->X.a, &x->a);
  fp_to_mont(&p->X.b, &x->b);
  fp_to_mont(&p->Y.a, &y->a);
  fp_to_mont(&p->Y.b, &y->b);
  memcpy(p->Z.a.v, MONT_ONE, sizeof MONT_ONE);
  memset(p->Z.b.v, 0, sizeof p->Z.b.v);
}

static void g2_to_affine_mont(const g2 *p, fp2 *x, fp2 *y, uint8_t *inf) {
  if (g2_is_inf(p)) {
    memset(x, 0, sizeof *x);
    memset(y, 0, sizeof *y);
    *inf = 1;
    return;
  }
  fp2 zi, zi2, zi3;
  fp2_inv_(&zi, &p->Z);
  fp2_sqr_(&zi2, &zi);
  fp2_mul_(&zi3, &zi2, &zi);
  fp2_mul_(x, &p->X, &zi2);
  fp2_mul_(y, &p->Y, &zi3);
  *inf = 0;
}

static void g2_dbl(g2 *r, const g2 *p) {
  if (g2_is_inf(p) || fp2_is_zero(&p->Y)) {
    g2_set_inf(r);
    return;
  }
  fp2 A, B, C, D, E, F, t, newX, newY, newZ, c8;
  fp2_sqr_(&A, &p->X);
  fp2_sqr_(&B, &p->Y);
  fp2_sqr_(&C, &B);
  fp2_add_(&t, &p->X, &B);
  fp2_sqr_(&t, &t);
  fp2_sub_(&t, &t, &A);
  fp2_sub_(&t, &t, &C);
  fp2_dbl_(&D, &t);
  fp2_dbl_(&E, &A);
  fp2_add_(&E, &E, &A);
  fp2_sqr_(&F, &E);
  fp2_dbl_(&t, &D);
  fp2_sub_(&newX, &F, &t);
  fp2_sub_(&t, &D, &newX);
  fp2_mul_(&t, &E, &t);
  fp2_dbl_(&c8, &C);
  fp2_dbl_(&c8, &c8);
  fp2_dbl_(&c8, &c8);
  fp2_sub_(&newY, &t, &c8);
  fp2_mul_(&newZ, &p->Y, &p->Z);
  fp2_dbl_(&newZ, &newZ);
  r->X = newX;
  r->Y = newY;
  r->Z = newZ;
}

static void g2_add_(g2 *r, const g2 *p, const g2 *q) {
  if (g2_is_inf(p)) {
    *r = *q;
    return;
  }
  if (g2_is_inf(q)) {
    *r = *p;
    return;
  }
  fp2 Z1Z1, Z2Z2, U1, U2, S1, S2, t;
  fp2_sqr_(&Z1Z1, &p->Z);
  fp2_sqr_(&Z2Z2, &q->Z);
  fp2_mul_(&U1, &p->X, &Z2Z2);
  fp2_mul_(&U2, &q->X, &Z1Z1);
  fp2_mul_(&t, &q->Z, &Z2Z2);
  fp2_mul_(&S1, &p->Y, &t);
  fp2_mul_(&t, &p->Z, &Z1Z1);
  fp2_mul_(&S2, &q->Y, &t);
  if (fp2_eq(&U1, &U2)) {
    if (fp2_eq(&S1, &S2))
      g2_dbl(r, p);
    else
      g2_set_inf(r);
    return;
  }
  fp2 H, I, J, rr, V, newX, newY, newZ, v2, s1j;
  fp2_sub_(&H, &U2, &U1);
  fp2_dbl_(&I, &H);
  fp2_sqr_(&I, &I);
  fp2_mul_(&J, &H, &I);
  fp2_sub_(&rr, &S2, &S1);
  fp2_dbl_(&rr, &rr);
  fp2_mul_(&V, &U1, &I);
  fp2_sqr_(&t, &rr);
  fp2_sub_(&t, &t, &J);
  fp2_dbl_(&v2, &V);
  fp2_sub_(&newX, &t, &v2);
  fp2_sub_(&t, &V, &newX);
  fp2_mul_(&t, &rr, &t);
  fp2_mul_(&s1j, &S1, &J);
  fp2_dbl_(&s1j, &s1j);
  fp2_sub_(&newY, &t, &s1j);
  fp2_add_(&t, &p->Z, &q->Z);
  fp2_sqr_(&t, &t);
  fp2_sub_(&t, &t, &Z1Z1);
  fp2_sub_(&t, &t, &Z2Z2);
  fp2_mul_(&newZ, &t, &H);
  r->X = newX;
  r->Y = newY;
  r->Z = newZ;
}

static void g2_scalar_mul(g2 *r, const g2 *p, const u64 k[4]) {
  g2 table[16];
  g2_set_inf(&table[0]);
  table[1] = *p;
  for (int i = 2; i < 16; i++)
    g2_add_(&table[i], &table[i - 1], p);
  g2 acc;
  g2_set_inf(&acc);
  int started = 0;
  for (int limb = 3; limb >= 0; limb--) {
    for (int w = 60; w >= 0; w -= 4) {
      if (started) {
        g2_dbl(&acc, &acc);
        g2_dbl(&acc, &acc);
        g2_dbl(&acc, &acc);
        g2_dbl(&acc, &acc);
      }
      unsigned d = (unsigned)((k[limb] >> w) & 0xF);
      if (d) {
        g2_add_(&acc, &acc, &table[d]);
        started = 1;
      }
    }
  }
  *r = acc;
}

/* ----------------------------------------------------------------- Fp12
 * Flat basis c = sum_j c[j] w^j, c[j] in Fp2, w^6 = XI — mirrors the
 * pure-Python twin (crypto/hostmath.py) coefficient-for-coefficient so
 * the two paths are differentially testable. */

typedef struct { fp2 c[6]; } fp12;

/* Frobenius gammas XI^(j(P-1)/6), Montgomery (a, b) pairs. */
static const fp2 GAMMA[6] = {
    {{{0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL, 0x666ea36f7879462cULL,
       0x0e0a77c19a07df2fULL}},
     {{0x0000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL,
       0x0000000000000000ULL}}},
    {{{0xaf9ba69633144907ULL, 0xca6b1d7387afb78aULL, 0x11bded5ef08a2087ULL,
       0x02f34d751a1f3a7cULL}},
     {{0xa222ae234c492d72ULL, 0xd00f02a4565de15bULL, 0xdc2ff3a253dfc926ULL,
       0x10a75716b3899551ULL}}},
    {{{0xb5773b104563ab30ULL, 0x347f91c8a9aa6454ULL, 0x7a007127242e0991ULL,
       0x1956bcd8118214ecULL}},
     {{0x6e849f1ea0aa4757ULL, 0xaa1c7b6d89f89141ULL, 0xb6e713cdfae0ca3aULL,
       0x26694fbb4e82ebc3ULL}}},
    {{{0xe4bbdd0c2936b629ULL, 0xbb30f162e133bacbULL, 0x31a9d1b6f9645366ULL,
       0x253570bea500f8ddULL}},
     {{0xa1d77ce45ffe77c7ULL, 0x07affd117826d1dbULL, 0x6d16bd27bb7edc6bULL,
       0x2c87200285defeccULL}}},
    {{{0x7361d77f843abe92ULL, 0xa5bb2bd3273411fbULL, 0x9c941f314b3e2399ULL,
       0x15df9cddbb9fd3ecULL}},
     {{0x5dddfd154bd8c949ULL, 0x62cb29a5a4445b60ULL, 0x37bc870a0c7dd2b9ULL,
       0x24830a9d3171f0fdULL}}},
    {{{0xc970692f41690fe7ULL, 0xe240342127694b0bULL, 0x32bee66b83c459e8ULL,
       0x12aabced0ab08841ULL}},
     {{0x0d485d2340aebfa9ULL, 0x05193418ab2fcc57ULL, 0xd3b0a40b8a4910f5ULL,
       0x2f21ebb535d2925aULL}}}};

static void fp12_set_one(fp12 *r) {
  memset(r, 0, sizeof *r);
  memcpy(r->c[0].a.v, MONT_ONE, sizeof MONT_ONE);
}

static int fp12_is_one(const fp12 *x) {
  fp one;
  memcpy(one.v, MONT_ONE, sizeof MONT_ONE);
  if (!fp_eq(&x->c[0].a, &one) || !fp_is_zero(&x->c[0].b))
    return 0;
  for (int j = 1; j < 6; j++)
    if (!fp2_is_zero(&x->c[j]))
      return 0;
  return 1;
}

static int fp12_eq(const fp12 *x, const fp12 *y) {
  for (int j = 0; j < 6; j++)
    if (!fp2_eq(&x->c[j], &y->c[j]))
      return 0;
  return 1;
}

static void fp12_add_(fp12 *r, const fp12 *x, const fp12 *y) {
  for (int j = 0; j < 6; j++)
    fp2_add_(&r->c[j], &x->c[j], &y->c[j]);
}

static void fp12_sub_(fp12 *r, const fp12 *x, const fp12 *y) {
  for (int j = 0; j < 6; j++)
    fp2_sub_(&r->c[j], &x->c[j], &y->c[j]);
}

static void fp12_neg_(fp12 *r, const fp12 *x) {
  for (int j = 0; j < 6; j++)
    fp2_neg_(&r->c[j], &x->c[j]);
}

static void fp12_mul_(fp12 *r, const fp12 *x, const fp12 *y) {
  /* schoolbook 6x6 with w^6 = XI folding (mirrors hostmath.fp12_mul) */
  fp2 acc[6];
  memset(acc, 0, sizeof acc);
  for (int jx = 0; jx < 6; jx++) {
    if (fp2_is_zero(&x->c[jx]))
      continue;
    for (int jy = 0; jy < 6; jy++) {
      if (fp2_is_zero(&y->c[jy]))
        continue;
      fp2 t;
      fp2_mul_(&t, &x->c[jx], &y->c[jy]);
      int j = jx + jy;
      if (j >= 6) {
        j -= 6;
        fp2_mul_(&t, &t, &XI_M);
      }
      fp2_add_(&acc[j], &acc[j], &t);
    }
  }
  memcpy(r->c, acc, sizeof acc);
}

static void fp12_sqr_(fp12 *r, const fp12 *x);

static void fp12_conj_(fp12 *r, const fp12 *x) {
  for (int j = 0; j < 6; j++) {
    if (j & 1)
      fp2_neg_(&r->c[j], &x->c[j]);
    else
      r->c[j] = x->c[j];
  }
}

static void fp12_frobenius1(fp12 *r, const fp12 *x) {
  for (int j = 0; j < 6; j++) {
    fp2 t;
    fp2_conj_(&t, &x->c[j]);
    fp2_mul_(&r->c[j], &t, &GAMMA[j]);
  }
}

static void fp12_frobenius(fp12 *r, const fp12 *x, int n) {
  fp12 t = *x;
  for (int i = 0; i < n; i++)
    fp12_frobenius1(&t, &t);
  *r = t;
}

/* tower split for inversion: Fp6 = Fp2[v]/(v^3 - XI), v = w^2 */
typedef struct { fp2 a0, a1, a2; } fp6t;

static void fp6_mul_(fp6t *r, const fp6t *a, const fp6t *b) {
  fp2 t0, t1, t2, s1, s2, u, c0, c1, c2;
  fp2_mul_(&t0, &a->a0, &b->a0);
  fp2_mul_(&t1, &a->a1, &b->a1);
  fp2_mul_(&t2, &a->a2, &b->a2);
  /* c0 = t0 + XI((a1+a2)(b1+b2) - t1 - t2) */
  fp2_add_(&s1, &a->a1, &a->a2);
  fp2_add_(&s2, &b->a1, &b->a2);
  fp2_mul_(&u, &s1, &s2);
  fp2_sub_(&u, &u, &t1);
  fp2_sub_(&u, &u, &t2);
  fp2_mul_(&u, &u, &XI_M);
  fp2_add_(&c0, &t0, &u);
  /* c1 = (a0+a1)(b0+b1) - t0 - t1 + XI t2 */
  fp2_add_(&s1, &a->a0, &a->a1);
  fp2_add_(&s2, &b->a0, &b->a1);
  fp2_mul_(&u, &s1, &s2);
  fp2_sub_(&u, &u, &t0);
  fp2_sub_(&u, &u, &t1);
  fp2 xit2;
  fp2_mul_(&xit2, &t2, &XI_M);
  fp2_add_(&c1, &u, &xit2);
  /* c2 = (a0+a2)(b0+b2) - t0 - t2 + t1 */
  fp2_add_(&s1, &a->a0, &a->a2);
  fp2_add_(&s2, &b->a0, &b->a2);
  fp2_mul_(&u, &s1, &s2);
  fp2_sub_(&u, &u, &t0);
  fp2_sub_(&u, &u, &t2);
  fp2_add_(&c2, &u, &t1);
  r->a0 = c0;
  r->a1 = c1;
  r->a2 = c2;
}

static void fp6_mul_v(fp6t *r, const fp6t *a) {
  fp2 t;
  fp2_mul_(&t, &a->a2, &XI_M);
  r->a2 = a->a1;
  r->a1 = a->a0;
  r->a0 = t;
}

static void fp6_sub_(fp6t *r, const fp6t *a, const fp6t *b) {
  fp2_sub_(&r->a0, &a->a0, &b->a0);
  fp2_sub_(&r->a1, &a->a1, &b->a1);
  fp2_sub_(&r->a2, &a->a2, &b->a2);
}

static void fp6_neg_(fp6t *r, const fp6t *a) {
  fp2_neg_(&r->a0, &a->a0);
  fp2_neg_(&r->a1, &a->a1);
  fp2_neg_(&r->a2, &a->a2);
}

static void fp6_inv_(fp6t *r, const fp6t *a) {
  fp2 c0, c1, c2, t, u, tinv;
  /* c0 = a0^2 - XI a1 a2 */
  fp2_sqr_(&c0, &a->a0);
  fp2_mul_(&t, &a->a1, &a->a2);
  fp2_mul_(&t, &t, &XI_M);
  fp2_sub_(&c0, &c0, &t);
  /* c1 = XI a2^2 - a0 a1 */
  fp2_sqr_(&c1, &a->a2);
  fp2_mul_(&c1, &c1, &XI_M);
  fp2_mul_(&t, &a->a0, &a->a1);
  fp2_sub_(&c1, &c1, &t);
  /* c2 = a1^2 - a0 a2 */
  fp2_sqr_(&c2, &a->a1);
  fp2_mul_(&t, &a->a0, &a->a2);
  fp2_sub_(&c2, &c2, &t);
  /* t = XI(a2 c1 + a1 c2) + a0 c0 */
  fp2_mul_(&t, &a->a2, &c1);
  fp2_mul_(&u, &a->a1, &c2);
  fp2_add_(&t, &t, &u);
  fp2_mul_(&t, &t, &XI_M);
  fp2_mul_(&u, &a->a0, &c0);
  fp2_add_(&t, &t, &u);
  fp2_inv_(&tinv, &t);
  fp2_mul_(&r->a0, &c0, &tinv);
  fp2_mul_(&r->a1, &c1, &tinv);
  fp2_mul_(&r->a2, &c2, &tinv);
}

static void fp12_split(const fp12 *x, fp6t *c0, fp6t *c1) {
  c0->a0 = x->c[0];
  c0->a1 = x->c[2];
  c0->a2 = x->c[4];
  c1->a0 = x->c[1];
  c1->a1 = x->c[3];
  c1->a2 = x->c[5];
}

static void fp12_join(fp12 *r, const fp6t *c0, const fp6t *c1) {
  r->c[0] = c0->a0;
  r->c[1] = c1->a0;
  r->c[2] = c0->a1;
  r->c[3] = c1->a1;
  r->c[4] = c0->a2;
  r->c[5] = c1->a2;
}

static void fp6_add_(fp6t *r, const fp6t *a, const fp6t *b) {
  fp2_add_(&r->a0, &a->a0, &b->a0);
  fp2_add_(&r->a1, &a->a1, &b->a1);
  fp2_add_(&r->a2, &a->a2, &b->a2);
}

/* x^2 via the tower: (c0 + c1 w)^2 = (c0^2 + v c1^2) + 2 c0 c1 w.
 * 3 Fp6 muls (18 Fp2 muls) vs 36 for schoolbook — final exponentiation
 * is squaring-dominated, so this roughly halves pairing cost. */
static void fp12_sqr_(fp12 *r, const fp12 *x) {
  fp6t c0, c1, t0, t1, vc1, s, r0, r1;
  fp12_split(x, &c0, &c1);
  fp6_mul_(&t0, &c0, &c0);
  fp6_mul_(&t1, &c1, &c1);
  fp6_mul_v(&vc1, &t1);
  fp6_add_(&r0, &t0, &vc1);
  /* 2 c0 c1 = (c0 + c1)^2 - c0^2 - c1^2 */
  fp6_add_(&s, &c0, &c1);
  fp6_mul_(&r1, &s, &s);
  fp6_sub_(&r1, &r1, &t0);
  fp6_sub_(&r1, &r1, &t1);
  fp12_join(r, &r0, &r1);
}

static void fp12_inv_(fp12 *r, const fp12 *x) {
  fp6t c0, c1, n, t, ninv, r0, r1;
  fp12_split(x, &c0, &c1);
  fp6_mul_(&n, &c0, &c0);
  fp6_mul_(&t, &c1, &c1);
  fp6_mul_v(&t, &t);
  fp6_sub_(&n, &n, &t);
  fp6_inv_(&ninv, &n);
  fp6_mul_(&r0, &c0, &ninv);
  fp6_mul_(&r1, &c1, &ninv);
  fp6_neg_(&r1, &r1);
  fp12_join(r, &r0, &r1);
}

/* ------------------------------------------------------------- pairing
 * Optimal ate, mirroring the Python twin: untwist into E(Fp12), affine
 * Miller loop over 6u+2, two Frobenius line corrections, final
 * exponentiation = easy part x hard-part square-and-multiply. */

typedef struct { fp12 x, y; int inf; } e12;

/* line through t1,t2 evaluated at (px, py) embedded in Fp12 */
static void linefunc(fp12 *out, const e12 *t1, const e12 *t2,
                     const fp12 *px12, const fp12 *py12) {
  fp12 m, t, u;
  if (!fp12_eq(&t1->x, &t2->x)) {
    fp12_sub_(&t, &t2->y, &t1->y);
    fp12_sub_(&u, &t2->x, &t1->x);
    fp12_inv_(&u, &u);
    fp12_mul_(&m, &t, &u);
  } else if (fp12_eq(&t1->y, &t2->y)) {
    fp12_sqr_(&t, &t1->x);
    fp12 t3;
    fp12_add_(&t3, &t, &t);
    fp12_add_(&t, &t3, &t);
    fp12_add_(&u, &t1->y, &t1->y);
    fp12_inv_(&u, &u);
    fp12_mul_(&m, &t, &u);
  } else {
    fp12_sub_(out, px12, &t1->x);
    return;
  }
  fp12_sub_(&t, px12, &t1->x);
  fp12_mul_(&t, &m, &t);
  fp12_sub_(&u, py12, &t1->y);
  fp12_sub_(out, &t, &u);
}

static void e12_add(e12 *r, const e12 *p1, const e12 *p2) {
  if (p1->inf) {
    *r = *p2;
    return;
  }
  if (p2->inf) {
    *r = *p1;
    return;
  }
  fp12 m, t, u;
  if (fp12_eq(&p1->x, &p2->x)) {
    fp12_add_(&t, &p1->y, &p2->y);
    fp12 zero;
    memset(&zero, 0, sizeof zero);
    if (fp12_eq(&t, &zero)) {
      r->inf = 1;
      memset(&r->x, 0, sizeof r->x);
      memset(&r->y, 0, sizeof r->y);
      return;
    }
    fp12_sqr_(&t, &p1->x);
    fp12 t3;
    fp12_add_(&t3, &t, &t);
    fp12_add_(&t, &t3, &t);
    fp12_add_(&u, &p1->y, &p1->y);
    fp12_inv_(&u, &u);
    fp12_mul_(&m, &t, &u);
  } else {
    fp12_sub_(&t, &p2->y, &p1->y);
    fp12_sub_(&u, &p2->x, &p1->x);
    fp12_inv_(&u, &u);
    fp12_mul_(&m, &t, &u);
  }
  fp12 x3, y3;
  fp12_sqr_(&x3, &m);
  fp12_sub_(&x3, &x3, &p1->x);
  fp12_sub_(&x3, &x3, &p2->x);
  fp12_sub_(&t, &p1->x, &x3);
  fp12_mul_(&t, &m, &t);
  fp12_sub_(&y3, &t, &p1->y);
  r->x = x3;
  r->y = y3;
  r->inf = 0;
}

/* low 64 bits of 6u+2 (bit 64, the leading 1, is implicit) */
static const u64 ATE_LOW = 0x9d797039be763ba8ULL;

/* G1 point (affine, Montgomery) and G2 point (affine fp2, Montgomery) ->
 * Miller loop value accumulated into f (callers chain products). */
static void miller_accum(fp12 *f, const fp *px, const fp *py,
                         const fp2 *qx, const fp2 *qy) {
  fp12 px12, py12;
  memset(&px12, 0, sizeof px12);
  memset(&py12, 0, sizeof py12);
  px12.c[0].a = *px;
  py12.c[0].a = *py;
  /* untwist: (x, y) -> (x w^2, y w^3) */
  e12 qe, t;
  memset(&qe, 0, sizeof qe);
  qe.x.c[2] = *qx;
  qe.y.c[3] = *qy;
  qe.inf = 0;
  t = qe;
  fp12 acc, l;
  fp12_set_one(&acc);
  for (int i = 63; i >= 0; i--) {
    fp12_sqr_(&acc, &acc);
    linefunc(&l, &t, &t, &px12, &py12);
    fp12_mul_(&acc, &acc, &l);
    e12_add(&t, &t, &t);
    if ((ATE_LOW >> i) & 1) {
      linefunc(&l, &t, &qe, &px12, &py12);
      fp12_mul_(&acc, &acc, &l);
      e12_add(&t, &t, &qe);
    }
  }
  /* Frobenius corrections: Q1 = pi(Q), Q2 = -pi^2(Q) */
  e12 q1, nq2;
  fp12_frobenius(&q1.x, &qe.x, 1);
  fp12_frobenius(&q1.y, &qe.y, 1);
  q1.inf = 0;
  fp12_frobenius(&nq2.x, &q1.x, 1);
  fp12_frobenius(&nq2.y, &q1.y, 1);
  fp12_neg_(&nq2.y, &nq2.y);
  nq2.inf = 0;
  linefunc(&l, &t, &q1, &px12, &py12);
  fp12_mul_(&acc, &acc, &l);
  e12_add(&t, &t, &q1);
  linefunc(&l, &t, &nq2, &px12, &py12);
  fp12_mul_(&acc, &acc, &l);
  fp12_mul_(f, f, &acc);
}

/* hard part exponent (p^4 - p^2 + 1)/r, 761 bits */
static const u64 FE_HARD[12] = {
    0xe81bb482ccdf42b1ULL, 0x5abf5cc4f49c36d4ULL, 0xf1154e7e1da014fdULL,
    0xdcc7b44c87cdbacfULL, 0xaaa441e3954bcf8aULL, 0x6b887d56d5095f23ULL,
    0x79581e16f3fd90c6ULL, 0x3b1b1355d189227dULL, 0x4e529a5861876f6bULL,
    0x6c0eb522d5b12278ULL, 0x331ec15183177fafULL, 0x01baaa710b0759adULL};

static void final_exp_(fp12 *r, const fp12 *f) {
  fp12 t, u;
  /* easy: f^(p^6-1) = conj(f) * f^-1, then ^(p^2+1) */
  fp12_conj_(&t, f);
  fp12_inv_(&u, f);
  fp12_mul_(&t, &t, &u);
  fp12_frobenius(&u, &t, 2);
  fp12_mul_(&t, &u, &t);
  /* hard part: square-and-multiply over FE_HARD */
  fp12 acc, base = t;
  fp12_set_one(&acc);
  for (int limb = 0; limb < 12; limb++) {
    u64 bits = FE_HARD[limb];
    for (int i = 0; i < 64; i++) {
      if (bits & 1)
        fp12_mul_(&acc, &acc, &base);
      fp12_sqr_(&base, &base);
      bits >>= 1;
    }
  }
  *r = acc;
}

/* -------------------------------------------------- exported API (G2/GT)
 * G2 points cross as 16 u64: x.a, x.b, y.a, y.b (4 LE limbs each,
 * non-Montgomery). GT crosses as 48 u64: flat w-basis c[j] = (a, b),
 * j = 0..5, non-Montgomery. */

static void load_g2(g2 *p, const u64 *coords, const uint8_t *inf, long i) {
  if (inf && inf[i]) {
    g2_set_inf(p);
    return;
  }
  fp2 x, y;
  memcpy(x.a.v, coords + 16 * i, 4 * sizeof(u64));
  memcpy(x.b.v, coords + 16 * i + 4, 4 * sizeof(u64));
  memcpy(y.a.v, coords + 16 * i + 8, 4 * sizeof(u64));
  memcpy(y.b.v, coords + 16 * i + 12, 4 * sizeof(u64));
  g2_from_affine(p, &x, &y);
}

static void store_g2(const g2 *p, u64 *out, uint8_t *oinf, long i) {
  fp2 xm, ym;
  uint8_t f;
  g2_to_affine_mont(p, &xm, &ym, &f);
  oinf[i] = f;
  if (f) {
    memset(out + 16 * i, 0, 16 * sizeof(u64));
    return;
  }
  fp t;
  fp_from_mont(&t, &xm.a);
  memcpy(out + 16 * i, t.v, 4 * sizeof(u64));
  fp_from_mont(&t, &xm.b);
  memcpy(out + 16 * i + 4, t.v, 4 * sizeof(u64));
  fp_from_mont(&t, &ym.a);
  memcpy(out + 16 * i + 8, t.v, 4 * sizeof(u64));
  fp_from_mont(&t, &ym.b);
  memcpy(out + 16 * i + 12, t.v, 4 * sizeof(u64));
}

static void store_gt(const fp12 *x, u64 *out) {
  for (int j = 0; j < 6; j++) {
    fp t;
    fp_from_mont(&t, &x->c[j].a);
    memcpy(out + 8 * j, t.v, 4 * sizeof(u64));
    fp_from_mont(&t, &x->c[j].b);
    memcpy(out + 8 * j + 4, t.v, 4 * sizeof(u64));
  }
}

void fts_g2_mul_batch(const u64 *coords, const uint8_t *inf, const u64 *ks,
                      long n, u64 *out, uint8_t *oinf) {
  for (long i = 0; i < n; i++) {
    g2 p, r;
    load_g2(&p, coords, inf, i);
    g2_scalar_mul(&r, &p, ks + 4 * i);
    store_g2(&r, out, oinf, i);
  }
}

void fts_g2_multiexp(const u64 *coords, const uint8_t *inf, const u64 *ks,
                     long n, u64 *out, uint8_t *oinf) {
  g2 acc, p, t;
  g2_set_inf(&acc);
  for (long i = 0; i < n; i++) {
    load_g2(&p, coords, inf, i);
    g2_scalar_mul(&t, &p, ks + 4 * i);
    g2_add_(&acc, &acc, &t);
  }
  store_g2(&acc, out, oinf, 0);
}

void fts_g2_sum(const u64 *coords, const uint8_t *inf, long n, u64 *out,
                uint8_t *oinf) {
  g2 acc, p;
  g2_set_inf(&acc);
  for (long i = 0; i < n; i++) {
    load_g2(&p, coords, inf, i);
    g2_add_(&acc, &acc, &p);
  }
  store_g2(&acc, out, oinf, 0);
}

/* prod_i e(P_i, Q_i) with one shared final exponentiation.
 * Pairs with an infinite side contribute the identity. Returns the GT
 * element; `is_one` out-param set when the product is unity. */
void fts_pairing_product(const u64 *g1xs, const u64 *g1ys,
                         const uint8_t *g1inf, const u64 *g2coords,
                         const uint8_t *g2inf, long n, u64 *out,
                         uint8_t *is_one) {
  fp12 f;
  fp12_set_one(&f);
  for (long i = 0; i < n; i++) {
    if ((g1inf && g1inf[i]) || (g2inf && g2inf[i]))
      continue;
    fp px, py;
    fp2 qx, qy;
    memcpy(px.v, g1xs + 4 * i, 4 * sizeof(u64));
    memcpy(py.v, g1ys + 4 * i, 4 * sizeof(u64));
    fp pxm, pym;
    fp_to_mont(&pxm, &px);
    fp_to_mont(&pym, &py);
    fp t;
    memcpy(t.v, g2coords + 16 * i, 4 * sizeof(u64));
    fp_to_mont(&qx.a, &t);
    memcpy(t.v, g2coords + 16 * i + 4, 4 * sizeof(u64));
    fp_to_mont(&qx.b, &t);
    memcpy(t.v, g2coords + 16 * i + 8, 4 * sizeof(u64));
    fp_to_mont(&qy.a, &t);
    memcpy(t.v, g2coords + 16 * i + 12, 4 * sizeof(u64));
    fp_to_mont(&qy.b, &t);
    miller_accum(&f, &pxm, &pym, &qx, &qy);
  }
  fp12 e;
  final_exp_(&e, &f);
  store_gt(&e, out);
  *is_one = (uint8_t)fp12_is_one(&e);
}
