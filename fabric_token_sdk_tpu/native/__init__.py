"""Native (C) host runtime helpers, built on demand, hashlib fallback.

`sha256_many(messages)` — batch transcript hashing for Fiat-Shamir
challenge recomputation over verified blocks. The .so is compiled once
with the system C compiler into this package directory; any failure falls
back to pure-Python hashlib transparently.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional, Sequence

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "_fastser.so")
_SRC = os.path.join(_HERE, "fastser.c")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                for cc in ("cc", "gcc", "clang"):
                    try:
                        subprocess.run(
                            [cc, "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                            check=True, capture_output=True, timeout=120,
                        )
                        break
                    except Exception:
                        continue
                else:
                    return None
            lib = ctypes.CDLL(_SO)
            lib.sha256_batch.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
                ctypes.c_char_p,
            ]
            lib.sha256_batch.restype = None
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


def sha256_many(messages: Sequence[bytes], force_native: bool = False) -> List[bytes]:
    """Batch SHA-256.

    hashlib (OpenSSL, SHA-NI accelerated) is the default; the native path
    exists for environments without an accelerated libcrypto and as the
    ctypes integration seam for further native runtime components.
    """
    if not force_native and not os.environ.get("FTS_TPU_FORCE_NATIVE_SHA"):
        return [hashlib.sha256(m).digest() for m in messages]
    lib = _load()
    if lib is None or not messages:
        return [hashlib.sha256(m).digest() for m in messages]
    buf = b"".join(messages)
    n = len(messages)
    offs = (ctypes.c_uint64 * (n + 1))()
    pos = 0
    for i, m in enumerate(messages):
        offs[i] = pos
        pos += len(m)
    offs[n] = pos
    out = ctypes.create_string_buffer(32 * n)
    lib.sha256_batch(buf, offs, n, out)
    return [out.raw[32 * i : 32 * (i + 1)] for i in range(n)]
