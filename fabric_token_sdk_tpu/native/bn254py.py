"""ctypes binding for the native BN254 host library (bn254.c).

Batched G1 scalar multiplication / multiexp / sum on the host control
plane. Mirrors the group-op API of `crypto.hostmath`; `hostmath` installs
these as its fast path at import when the library builds (opt out with
FTS_TPU_NO_NATIVE=1). Points are affine int tuples or None (infinity),
scalars plain ints; conversion to 4x64 little-endian limb buffers happens
here.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "_bn254.so")
_SRC = os.path.join(_HERE, "bn254.c")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_R = 21888242871839275222246405745257275088548364400416034343698204186575808495617

_U64P = ctypes.POINTER(ctypes.c_uint64)
_U8P = ctypes.POINTER(ctypes.c_uint8)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                # build to a private temp path, os.rename into place:
                # atomic on POSIX, so concurrent builders never load a
                # half-written ELF
                tmp = f"{_SO}.{os.getpid()}.tmp"
                built = False
                for cc in ("cc", "gcc", "clang"):
                    try:
                        subprocess.run(
                            [cc, "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                            check=True, capture_output=True, timeout=180,
                        )
                        os.rename(tmp, _SO)
                        built = True
                        break
                    except Exception:
                        try:
                            os.unlink(tmp)
                        except OSError:
                            pass
                        continue
                if not built:
                    return None
            lib = ctypes.CDLL(_SO)
            for name in ("fts_g1_mul_batch", "fts_g1_multiexp", "fts_g1_sum",
                         "fts_g1_multiexp_rows"):
                getattr(lib, name).restype = None
            lib.fts_g1_mul_batch.argtypes = [
                _U64P, _U64P, _U8P, _U64P, ctypes.c_long, _U64P, _U64P, _U8P]
            lib.fts_g1_multiexp.argtypes = [
                _U64P, _U64P, _U8P, _U64P, ctypes.c_long, _U64P, _U64P, _U8P]
            lib.fts_g1_sum.argtypes = [
                _U64P, _U64P, _U8P, ctypes.c_long, _U64P, _U64P, _U8P]
            lib.fts_g1_multiexp_rows.argtypes = [
                _U64P, _U64P, _U8P, _U64P, ctypes.c_long, ctypes.c_long,
                _U64P, _U64P, _U8P]
            for name in ("fts_g2_mul_batch", "fts_g2_multiexp", "fts_g2_sum",
                         "fts_pairing_product"):
                getattr(lib, name).restype = None
            lib.fts_g2_mul_batch.argtypes = [
                _U64P, _U8P, _U64P, ctypes.c_long, _U64P, _U8P]
            lib.fts_g2_multiexp.argtypes = [
                _U64P, _U8P, _U64P, ctypes.c_long, _U64P, _U8P]
            lib.fts_g2_sum.argtypes = [_U64P, _U8P, ctypes.c_long, _U64P, _U8P]
            lib.fts_pairing_product.argtypes = [
                _U64P, _U64P, _U8P, _U64P, _U8P, ctypes.c_long, _U64P, _U8P]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    if os.environ.get("FTS_TPU_NO_NATIVE"):
        return False
    return _load() is not None


def _pack_points(points: Sequence):
    n = len(points)
    xs = (ctypes.c_uint64 * (4 * n))()
    ys = (ctypes.c_uint64 * (4 * n))()
    inf = (ctypes.c_uint8 * n)()
    for i, pt in enumerate(points):
        if pt is None:
            inf[i] = 1
            continue
        x, y = pt
        for j in range(4):
            xs[4 * i + j] = (x >> (64 * j)) & 0xFFFFFFFFFFFFFFFF
            ys[4 * i + j] = (y >> (64 * j)) & 0xFFFFFFFFFFFFFFFF
    return xs, ys, inf


def _pack_scalars(scalars: Sequence[int]):
    n = len(scalars)
    ks = (ctypes.c_uint64 * (4 * n))()
    for i, k in enumerate(scalars):
        k %= _R
        for j in range(4):
            ks[4 * i + j] = (k >> (64 * j)) & 0xFFFFFFFFFFFFFFFF
    return ks


def _unpack_points(ox, oy, oinf, n: int) -> List:
    out = []
    for i in range(n):
        if oinf[i]:
            out.append(None)
            continue
        x = y = 0
        for j in range(3, -1, -1):
            x = (x << 64) | ox[4 * i + j]
            y = (y << 64) | oy[4 * i + j]
        out.append((x, y))
    return out


def g1_mul_batch(points: Sequence, scalars: Sequence[int]) -> List:
    """[k_i * P_i] for parallel lists of points/scalars."""
    lib = _load()
    n = len(points)
    if len(scalars) != n:
        raise ValueError(f"mul_batch length mismatch: {n} != {len(scalars)}")
    if n == 0:
        return []
    xs, ys, inf = _pack_points(points)
    ks = _pack_scalars(scalars)
    ox = (ctypes.c_uint64 * (4 * n))()
    oy = (ctypes.c_uint64 * (4 * n))()
    oinf = (ctypes.c_uint8 * n)()
    lib.fts_g1_mul_batch(xs, ys, inf, ks, n, ox, oy, oinf)
    return _unpack_points(ox, oy, oinf, n)


def g1_mul(pt, k: int):
    return g1_mul_batch([pt], [k])[0]


def g1_multiexp(points: Sequence, scalars: Sequence[int]):
    lib = _load()
    n = len(points)
    if len(scalars) != n:
        raise ValueError(f"multiexp length mismatch: {n} != {len(scalars)}")
    if n == 0:
        return None
    xs, ys, inf = _pack_points(points)
    ks = _pack_scalars(scalars)
    ox = (ctypes.c_uint64 * 4)()
    oy = (ctypes.c_uint64 * 4)()
    oinf = (ctypes.c_uint8 * 1)()
    lib.fts_g1_multiexp(xs, ys, inf, ks, n, ox, oy, oinf)
    return _unpack_points(ox, oy, oinf, 1)[0]


def g1_sum(points: Sequence):
    lib = _load()
    n = len(points)
    if n == 0:
        return None
    xs, ys, inf = _pack_points(points)
    ox = (ctypes.c_uint64 * 4)()
    oy = (ctypes.c_uint64 * 4)()
    oinf = (ctypes.c_uint8 * 1)()
    lib.fts_g1_sum(xs, ys, inf, n, ox, oy, oinf)
    return _unpack_points(ox, oy, oinf, 1)[0]


def _pack_g2(points: Sequence):
    """G2 affine ((x0,x1),(y0,y1)) tuples / None -> 16 u64 limbs each."""
    n = len(points)
    coords = (ctypes.c_uint64 * (16 * n))()
    inf = (ctypes.c_uint8 * n)()
    for i, pt in enumerate(points):
        if pt is None:
            inf[i] = 1
            continue
        (x0, x1), (y0, y1) = pt
        for k, v in enumerate((x0, x1, y0, y1)):
            for j in range(4):
                coords[16 * i + 4 * k + j] = (v >> (64 * j)) & 0xFFFFFFFFFFFFFFFF
    return coords, inf


def _unpack_g2(out, oinf, n: int) -> List:
    res = []
    for i in range(n):
        if oinf[i]:
            res.append(None)
            continue
        vals = []
        for k in range(4):
            v = 0
            for j in range(3, -1, -1):
                v = (v << 64) | out[16 * i + 4 * k + j]
            vals.append(v)
        res.append(((vals[0], vals[1]), (vals[2], vals[3])))
    return res


def g2_mul_batch(points: Sequence, scalars: Sequence[int]) -> List:
    lib = _load()
    n = len(points)
    if len(scalars) != n:
        raise ValueError(f"g2 mul_batch length mismatch: {n} != {len(scalars)}")
    if n == 0:
        return []
    coords, inf = _pack_g2(points)
    ks = _pack_scalars(scalars)
    out = (ctypes.c_uint64 * (16 * n))()
    oinf = (ctypes.c_uint8 * n)()
    lib.fts_g2_mul_batch(coords, inf, ks, n, out, oinf)
    return _unpack_g2(out, oinf, n)


def g2_mul(pt, k: int):
    return g2_mul_batch([pt], [k])[0]


def g2_multiexp(points: Sequence, scalars: Sequence[int]):
    lib = _load()
    n = len(points)
    if len(scalars) != n:
        raise ValueError(f"g2 multiexp length mismatch: {n} != {len(scalars)}")
    if n == 0:
        return None
    coords, inf = _pack_g2(points)
    ks = _pack_scalars(scalars)
    out = (ctypes.c_uint64 * 16)()
    oinf = (ctypes.c_uint8 * 1)()
    lib.fts_g2_multiexp(coords, inf, ks, n, out, oinf)
    return _unpack_g2(out, oinf, 1)[0]


def g2_sum(points: Sequence):
    lib = _load()
    n = len(points)
    if n == 0:
        return None
    coords, inf = _pack_g2(points)
    out = (ctypes.c_uint64 * 16)()
    oinf = (ctypes.c_uint8 * 1)()
    lib.fts_g2_sum(coords, inf, n, out, oinf)
    return _unpack_g2(out, oinf, 1)[0]


def pairing_product(pairs: Sequence):
    """prod e(P_i, Q_i) with one shared final exponentiation.

    Returns the GT element as a 6-tuple of (a, b) int pairs in the flat
    w-basis — the same representation as `hostmath`'s Fp12.
    """
    lib = _load()
    g1s = [p for p, _ in pairs]
    g2s = [q for _, q in pairs]
    n = len(pairs)
    if n == 0:
        n = 1
        g1s, g2s = [None], [None]
    xs, ys, inf1 = _pack_points(g1s)
    coords, inf2 = _pack_g2(g2s)
    out = (ctypes.c_uint64 * 48)()
    is_one = (ctypes.c_uint8 * 1)()
    lib.fts_pairing_product(xs, ys, inf1, coords, inf2, n, out, is_one)
    gt = []
    for j in range(6):
        a = b = 0
        for k in range(3, -1, -1):
            a = (a << 64) | out[8 * j + k]
            b = (b << 64) | out[8 * j + 4 + k]
        gt.append((a, b))
    return tuple(gt)


def pairing(p, q):
    return pairing_product([(p, q)])


def g1_multiexp_rows(points_rows: Sequence[Sequence],
                     scalar_rows: Sequence[Sequence[int]]) -> List:
    """One multiexp per row; all rows must share the same width."""
    lib = _load()
    rows = len(points_rows)
    if rows == 0:
        return []
    m = len(points_rows[0])
    flat_pts, flat_ks = [], []
    for pr, sr in zip(points_rows, scalar_rows):
        if len(pr) != m or len(sr) != m:
            raise ValueError("multiexp_rows: ragged rows")
        flat_pts.extend(pr)
        flat_ks.extend(sr)
    xs, ys, inf = _pack_points(flat_pts)
    ks = _pack_scalars(flat_ks)
    ox = (ctypes.c_uint64 * (4 * rows))()
    oy = (ctypes.c_uint64 * (4 * rows))()
    oinf = (ctypes.c_uint8 * rows)()
    lib.fts_g1_multiexp_rows(xs, ys, inf, ks, rows, m, ox, oy, oinf)
    return _unpack_points(ox, oy, oinf, rows)
