"""Identity encoding shared by drivers.

Owner identities are tagged wire blobs so validators can dispatch:
  pk    — long-term Schnorr public key (fabtoken owners, issuers, auditors)
  nym   — pseudonym commitment (zkatdlog owners)
  htlc  — hash-time-locked-contract script (interop; see services/interop)

Reference: `token/core/identity/*`, `token/services/interop/htlc`.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..crypto import hostmath as hm, nym as nym_mod, sign
from ..crypto.serialization import dumps, loads


def pk_identity(public: sign.PublicKey) -> bytes:
    return dumps({"t": "pk", "pk": public.to_bytes()})


def nym_identity(nym_point) -> bytes:
    return dumps({"t": "nym", "nym": nym_point})


def htlc_identity(script: dict) -> bytes:
    return dumps({"t": "htlc", "script": script})


def parse(raw: bytes) -> dict:
    d = loads(raw)
    if not isinstance(d, dict) or "t" not in d:
        raise ValueError("invalid identity encoding")
    return d


def identity_kind(raw: bytes) -> str:
    return parse(raw)["t"]


def verify_signature(identity: bytes, message: bytes, signature: bytes,
                     nym_params=None, now=None) -> None:
    """Dispatch signature verification on the identity kind."""
    d = parse(identity)
    kind = d["t"]
    if kind == "pk":
        sign.PublicKey.from_bytes(d["pk"]).verify(message, signature)
    elif kind == "nym":
        if nym_params is None:
            raise ValueError("nym verification requires nym parameters")
        nym_mod.NymVerifier(d["nym"], list(nym_params)).verify(message, signature)
    elif kind == "htlc":
        # hash-time-locked script: claim/reclaim rules (lazy import to
        # avoid a services <-> drivers cycle)
        from ..services.interop.htlc import verify_htlc_spend

        verify_htlc_spend(identity, message, signature, nym_params, now=now)
    else:
        raise ValueError(f"cannot verify signature for identity kind [{kind}]")
