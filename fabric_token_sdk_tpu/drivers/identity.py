"""Identity encoding shared by drivers.

Owner identities are tagged wire blobs so validators can dispatch:
  pk    — long-term Schnorr public key (fabtoken owners, issuers, auditors)
  nym   — pseudonym commitment (zkatdlog owners)
  htlc  — hash-time-locked-contract script (interop; see services/interop)

Reference: `token/core/identity/*`, `token/services/interop/htlc`.

Parse cache: wallet workloads repeat owners heavily — the same auditor /
issuer / owner identity arrives with every tx — so `verify_signature`
and the batched signature plane share one bounded LRU keyed by the RAW
identity bytes that holds the decoded blob and (for `pk` identities) the
constructed `PublicKey` (`g1_from_bytes` incl. the on-curve check runs
ONCE per distinct identity, not once per verify). `FTS_IDENTITY_CACHE`
sizes it (default 4096; 0 disables); `identity.cache.hits/misses` are
the observability counters. `parse()` stays uncached on purpose: it
returns a caller-owned dict (callers may mutate it), while cache entries
are shared and must never be written to.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional, Tuple

from ..crypto import hostmath as hm, nym as nym_mod, sign
from ..crypto.serialization import dumps, loads
from ..utils import metrics as mx
from ..utils import profiler


def pk_identity(public: sign.PublicKey) -> bytes:
    return dumps({"t": "pk", "pk": public.to_bytes()})


def nym_identity(nym_point) -> bytes:
    return dumps({"t": "nym", "nym": nym_point})


def htlc_identity(script: dict) -> bytes:
    return dumps({"t": "htlc", "script": script})


def parse(raw: bytes) -> dict:
    d = loads(raw)
    if not isinstance(d, dict) or "t" not in d:
        raise ValueError("invalid identity encoding")
    return d


def identity_kind(raw: bytes) -> str:
    return parse(raw)["t"]


# ------------------------------------------------------------ parse cache


class _IdentityCache:
    """Bounded LRU: raw identity bytes -> (kind, PublicKey|None, parsed
    dict). Shared by the host verify dispatch and the batched signature
    plane's obligation collector. Parse/decode FAILURES are never cached
    (they re-raise on every lookup, exactly like the uncached path)."""

    def __init__(self, capacity: Optional[int] = None):
        # an explicit capacity is fixed; otherwise FTS_IDENTITY_CACHE is
        # resolved lazily on FIRST USE (not at import) and re-resolved
        # after clear(), so tests/operators configuring the env after
        # the SDK imported still take effect
        self._from_env = capacity is None
        self._capacity = max(0, capacity) if capacity is not None else None
        self._entries: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        if self._capacity is None:
            try:
                self._capacity = max(
                    0, int(os.environ.get("FTS_IDENTITY_CACHE", "4096"))
                )
            except ValueError:
                self._capacity = 4096
        return self._capacity

    def lookup(self, raw: bytes) -> Tuple[str, Optional[sign.PublicKey], dict]:
        if self.capacity == 0:  # disabled: no storage, no counters
            d = parse(raw)
            kind = d["t"]
            pk = sign.PublicKey.from_bytes(d["pk"]) if kind == "pk" else None
            return kind, pk, d
        with self._lock:
            entry = self._entries.get(raw)
            if entry is not None:
                self._entries.move_to_end(raw)
        if entry is not None:
            mx.counter("identity.cache.hits").inc()
            return entry
        mx.counter("identity.cache.misses").inc()
        d = parse(raw)  # may raise ValueError — not cached
        kind = d["t"]
        pk = sign.PublicKey.from_bytes(d["pk"]) if kind == "pk" else None
        entry = (kind, pk, d)
        with self._lock:
            self._entries[raw] = entry
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
        return entry

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            if self._from_env:
                # env-derived capacity re-resolves on next use; an
                # explicitly constructed capacity stays pinned
                self._capacity = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


_CACHE = _IdentityCache()


def cache_clear() -> None:
    """Drop every cached identity (tests; also after key rotation)."""
    _CACHE.clear()


def cache_len() -> int:
    return len(_CACHE)


def public_key(raw: bytes) -> Optional[sign.PublicKey]:
    """The cached `PublicKey` of a `pk`-kind identity, or None for every
    other kind AND for malformed blobs (the batched plane's collector
    must never raise — the host path re-parses and reports the precise
    error)."""
    try:
        kind, pk, _ = _CACHE.lookup(raw)
    except Exception:
        return None
    return pk if kind == "pk" else None


def verify_signature(identity: bytes, message: bytes, signature: bytes,
                     nym_params=None, now=None) -> None:
    """Dispatch signature verification on the identity kind."""
    with profiler.leg("sig_verify"):
        kind, pk, d = _CACHE.lookup(identity)
        if kind == "pk":
            pk.verify(message, signature)
        elif kind == "nym":
            if nym_params is None:
                raise ValueError("nym verification requires nym parameters")
            nym_mod.NymVerifier(d["nym"], list(nym_params)).verify(
                message, signature
            )
        elif kind == "htlc":
            # hash-time-locked script: claim/reclaim rules (lazy import
            # to avoid a services <-> drivers cycle)
            from ..services.interop.htlc import verify_htlc_spend

            verify_htlc_spend(identity, message, signature, nym_params,
                              now=now)
        else:
            raise ValueError(
                f"cannot verify signature for identity kind [{kind}]"
            )
