"""fabtoken driver — plaintext tokens, signature-based validation.

Reference: `token/core/fabtoken/*` (setup.go, issuer.go, sender.go,
validator.go, validator_transfer.go). Tokens are stored in the clear;
privacy comes only from identity management. Validation checks ownership
signatures, type consistency, and value conservation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from ...api.driver import Driver, IssueOutcome, TransferOutcome, ValidationError, vguard
from ...crypto.serialization import BytesCache, dumps, loads, loads_cached
from ...models.quantity import Quantity
from ...models.token import ID, Owner, Token, UnspentToken
from ...utils import profiler
from .. import identity

MAX_PRECISION = 64

# Bounded read-only decode cache: chained transfers spend the previous
# tx's outputs, so the same token bytes decode as an output in block N
# and an input in block N+1 (and again in every plan hook).
_TOKENS = BytesCache(Token.from_bytes)


@dataclass
class FabTokenPublicParams:
    """Reference `fabtoken/setup.go`: precision + authorized identities."""

    label: str = "fabtoken"
    quantity_precision: int = MAX_PRECISION
    issuers: List[bytes] = field(default_factory=list)
    auditor: bytes = b""

    def token_data_hiding(self) -> bool:
        return False

    def graph_hiding(self) -> bool:
        return False

    def max_token_value(self) -> int:
        return (1 << self.quantity_precision) - 1

    def serialize(self) -> bytes:
        return dumps(
            {
                "identifier": self.label,
                "precision": self.quantity_precision,
                "issuers": list(self.issuers),
                "auditor": self.auditor,
            }
        )

    @classmethod
    def deserialize(cls, raw: bytes) -> "FabTokenPublicParams":
        d = loads(raw)
        return cls(d["identifier"], d["precision"], d["issuers"], d["auditor"])

    def add_issuer(self, ident: bytes) -> None:
        self.issuers.append(ident)

    def add_auditor(self, ident: bytes) -> None:
        self.auditor = ident


class FabTokenDriver(Driver):
    name = "fabtoken"

    def __init__(self, pp: Optional[FabTokenPublicParams] = None):
        self.pp = pp or FabTokenPublicParams()

    def public_params(self) -> FabTokenPublicParams:
        return self.pp

    def precision(self) -> int:
        return self.pp.quantity_precision

    # ------------------------------------------------------------ actions

    def issue(self, issuer_identity, token_type, values, owners, anonymous=False) -> IssueOutcome:
        if len(values) != len(owners):
            raise ValueError("issue: values/owners length mismatch")
        for v in values:
            Quantity(v, self.pp.quantity_precision)  # range check
        outputs = [
            Token(Owner(owner), token_type, hex(v)).to_bytes()
            for v, owner in zip(values, owners)
        ]
        action = dumps({"outputs": outputs, "issuer": issuer_identity})
        # fabtoken metadata mirrors the clear outputs (reference: ppm.go)
        return IssueOutcome(action_bytes=action, outputs=outputs, metadata=list(outputs))

    def transfer(self, input_ids, input_tokens, input_metadata, token_type, values, owners) -> TransferOutcome:
        if len(values) != len(owners):
            raise ValueError("transfer: values/owners length mismatch")
        for v in values:
            Quantity(v, self.pp.quantity_precision)  # range check
        outputs = [
            Token(Owner(owner), token_type, hex(v)).to_bytes()
            for v, owner in zip(values, owners)
        ]
        action = dumps(
            {
                "ids": [[i.tx_id, i.index] for i in input_ids],
                "inputs": list(input_tokens),
                "outputs": outputs,
            }
        )
        return TransferOutcome(action_bytes=action, outputs=outputs, metadata=list(outputs))

    # ------------------------------------------------------------ validate

    @vguard
    def validate_issue(self, action_bytes: bytes):
        with profiler.leg("conservation"):
            d = loads_cached(action_bytes)
            outputs = d["outputs"]
            if not outputs:
                raise ValidationError("issue must have at least one output")
            issuer = d["issuer"]
            if self.pp.issuers and issuer not in self.pp.issuers:
                raise ValidationError("issuer is not authorized")
            token_type = None
            for raw in outputs:
                t = _TOKENS.lookup(raw)
                q = t.quantity_as(self.pp.quantity_precision)
                if q.is_zero():
                    raise ValidationError("issue output with zero value")
                if token_type is None:
                    token_type = t.type
                elif t.type != token_type:
                    raise ValidationError("issue outputs with mixed types")
        # fabtoken issues always require the action issuer's signature
        return outputs, issuer

    @vguard
    def validate_transfer(self, action_bytes, resolve_input, signed_payload,
                          signatures, now=None, proof_verified=None,
                          sig_verified=None, conservation_verified=None):
        # fabtoken carries no ZK proof: `transfer_batch_plan` never emits
        # a plan, so `proof_verified` is always None here and ignored
        with profiler.leg("input_match"):
            d = loads_cached(action_bytes)
            ids = [ID(t, i) for t, i in d["ids"]]
            if not ids:
                raise ValidationError("transfer must have at least one input")
            ledger_inputs = [resolve_input(i) for i in ids]
            # action must reference the same inputs it was signed over
            if d["inputs"] != ledger_inputs:
                raise ValidationError(
                    "transfer inputs do not match ledger state"
                )
        with profiler.leg("conservation"):
            inputs = [_TOKENS.lookup(raw) for raw in ledger_inputs]
            if conservation_verified is not True:
                # no block-level verdict for this action: full scalar
                # checks (the batch pass covered the ACTION-claimed
                # inputs, which the input_match leg above just pinned to
                # ledger state, and the same output bytes)
                outputs = [_TOKENS.lookup(raw) for raw in d["outputs"]]
                types = {t.type for t in inputs} | {t.type for t in outputs}
                if len(types) != 1:
                    raise ValidationError(
                        f"tokens must have the same type, got {sorted(types)}"
                    )
                p = self.pp.quantity_precision
                in_sum = sum(t.quantity_as(p).value for t in inputs)
                out_sum = sum(t.quantity_as(p).value for t in outputs)
                if in_sum != out_sum:
                    raise ValidationError(
                        f"transfer does not preserve value: "
                        f"in={in_sum} out={out_sum}"
                    )
        if len(signatures) != len(inputs):
            raise ValidationError("one signature per input owner required")
        for si, (t, sig) in enumerate(zip(inputs, signatures)):
            v = sig_verified.get(si) if sig_verified else None
            if v is not None and v[0] == t.owner.raw:
                # batched-plane verdict for THIS owner identity: the
                # inputs==ledger check above pinned the claimed owner
                # the verdict was computed over to ledger state
                if not v[1]:
                    raise ValidationError(
                        "invalid owner signature: rejected by the batched "
                        "signature plane"
                    )
                continue
            try:
                identity.verify_signature(t.owner.raw, signed_payload, sig, now=now)
            except ValueError as e:
                raise ValidationError(f"invalid owner signature: {e}") from e
        return ids, d["outputs"]

    # ------------------------------------------------------------ batching

    def transfer_sign_plan(self, action_bytes: bytes):
        """Signature-plane hook: the ACTION-claimed input owners, one per
        required signature. Malformed bytes return None (host path
        rejects them with the precise error)."""
        try:
            d = loads_cached(action_bytes)
            owners = [_TOKENS.lookup(raw).owner.raw for raw in d["inputs"]]
            return owners or None
        except Exception:
            return None

    def issue_sign_plan(self, action_bytes: bytes):
        """Signature-plane hook: fabtoken issues always require the
        action-named issuer's signature."""
        try:
            issuer = loads_cached(action_bytes)["issuer"]
            return issuer if isinstance(issuer, bytes) and issuer else None
        except Exception:
            return None

    def validate_conservation_many(self, actions) -> List[Optional[bool]]:
        """Block-level vectorized conservation over transfer actions.

        Every action's tokens decode into one flat column (bounded parse
        cache: chained transfers make the same bytes recur), type/value
        columns are computed in a single pass, and each verdict falls out
        of segment sums instead of a per-tx parse/sum loop.

        A True verdict is decisive for exactly the checks the per-tx
        conservation leg performs — uniform type and value preservation
        over the ACTION-claimed inputs and outputs (the per-tx input_match
        leg separately pins claimed inputs to ledger state before the
        verdict is consumed). Anything else returns None: degrade-only,
        the scalar path re-checks and owns the precise error.
        """
        actions = list(actions)
        out: List[Optional[bool]] = [None] * len(actions)
        plans = []  # (action index, column start, n_in, n_out)
        flat: List[bytes] = []
        for i, raw in enumerate(actions):
            try:
                d = loads_cached(raw)
                ins, outs = d["inputs"], d["outputs"]
                if not isinstance(ins, list) or not isinstance(outs, list):
                    continue
                if not ins or not outs:
                    continue
            except Exception:
                continue
            plans.append((i, len(flat), len(ins), len(outs)))
            flat.extend(ins)
            flat.extend(outs)
        if not plans:
            return out
        p = self.pp.quantity_precision
        cols: List[Optional[tuple]] = []
        for raw in flat:
            try:
                t = _TOKENS.lookup(raw)
                cols.append((t.type, t.quantity_as(p).value))
            except Exception:
                cols.append(None)  # malformed token: scalar path reports
        for i, start, n_in, n_out in plans:
            seg = cols[start : start + n_in + n_out]
            if any(c is None for c in seg):
                continue
            if len({c[0] for c in seg}) != 1:
                continue
            if sum(c[1] for c in seg[:n_in]) == sum(c[1] for c in seg[n_in:]):
                out[i] = True
        return out

    # ------------------------------------------------------------ tokens

    def output_to_unspent(self, token_id, output_bytes, metadata_bytes=None) -> UnspentToken:
        t = _TOKENS.lookup(output_bytes)
        q = t.quantity_as(self.pp.quantity_precision)
        return UnspentToken(token_id, t.owner, t.type, q.decimal())

    def output_owner(self, output_bytes: bytes) -> bytes:
        return _TOKENS.lookup(output_bytes).owner.raw

    def verify_owner_signature(self, owner_identity, message, signature) -> None:
        identity.verify_signature(owner_identity, message, signature)
