"""fabtoken driver — plaintext tokens, signature-based validation.

Reference: `token/core/fabtoken/*` (setup.go, issuer.go, sender.go,
validator.go, validator_transfer.go). Tokens are stored in the clear;
privacy comes only from identity management. Validation checks ownership
signatures, type consistency, and value conservation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from ...api.driver import Driver, IssueOutcome, TransferOutcome, ValidationError, vguard
from ...crypto.serialization import dumps, loads
from ...models.quantity import Quantity
from ...models.token import ID, Owner, Token, UnspentToken
from ...utils import profiler
from .. import identity

MAX_PRECISION = 64


@dataclass
class FabTokenPublicParams:
    """Reference `fabtoken/setup.go`: precision + authorized identities."""

    label: str = "fabtoken"
    quantity_precision: int = MAX_PRECISION
    issuers: List[bytes] = field(default_factory=list)
    auditor: bytes = b""

    def token_data_hiding(self) -> bool:
        return False

    def graph_hiding(self) -> bool:
        return False

    def max_token_value(self) -> int:
        return (1 << self.quantity_precision) - 1

    def serialize(self) -> bytes:
        return dumps(
            {
                "identifier": self.label,
                "precision": self.quantity_precision,
                "issuers": list(self.issuers),
                "auditor": self.auditor,
            }
        )

    @classmethod
    def deserialize(cls, raw: bytes) -> "FabTokenPublicParams":
        d = loads(raw)
        return cls(d["identifier"], d["precision"], d["issuers"], d["auditor"])

    def add_issuer(self, ident: bytes) -> None:
        self.issuers.append(ident)

    def add_auditor(self, ident: bytes) -> None:
        self.auditor = ident


class FabTokenDriver(Driver):
    name = "fabtoken"

    def __init__(self, pp: Optional[FabTokenPublicParams] = None):
        self.pp = pp or FabTokenPublicParams()

    def public_params(self) -> FabTokenPublicParams:
        return self.pp

    def precision(self) -> int:
        return self.pp.quantity_precision

    # ------------------------------------------------------------ actions

    def issue(self, issuer_identity, token_type, values, owners, anonymous=False) -> IssueOutcome:
        if len(values) != len(owners):
            raise ValueError("issue: values/owners length mismatch")
        for v in values:
            Quantity(v, self.pp.quantity_precision)  # range check
        outputs = [
            Token(Owner(owner), token_type, hex(v)).to_bytes()
            for v, owner in zip(values, owners)
        ]
        action = dumps({"outputs": outputs, "issuer": issuer_identity})
        # fabtoken metadata mirrors the clear outputs (reference: ppm.go)
        return IssueOutcome(action_bytes=action, outputs=outputs, metadata=list(outputs))

    def transfer(self, input_ids, input_tokens, input_metadata, token_type, values, owners) -> TransferOutcome:
        if len(values) != len(owners):
            raise ValueError("transfer: values/owners length mismatch")
        for v in values:
            Quantity(v, self.pp.quantity_precision)  # range check
        outputs = [
            Token(Owner(owner), token_type, hex(v)).to_bytes()
            for v, owner in zip(values, owners)
        ]
        action = dumps(
            {
                "ids": [[i.tx_id, i.index] for i in input_ids],
                "inputs": list(input_tokens),
                "outputs": outputs,
            }
        )
        return TransferOutcome(action_bytes=action, outputs=outputs, metadata=list(outputs))

    # ------------------------------------------------------------ validate

    @vguard
    def validate_issue(self, action_bytes: bytes):
        with profiler.leg("conservation"):
            d = loads(action_bytes)
            outputs = d["outputs"]
            if not outputs:
                raise ValidationError("issue must have at least one output")
            issuer = d["issuer"]
            if self.pp.issuers and issuer not in self.pp.issuers:
                raise ValidationError("issuer is not authorized")
            token_type = None
            for raw in outputs:
                t = Token.from_bytes(raw)
                q = t.quantity_as(self.pp.quantity_precision)
                if q.is_zero():
                    raise ValidationError("issue output with zero value")
                if token_type is None:
                    token_type = t.type
                elif t.type != token_type:
                    raise ValidationError("issue outputs with mixed types")
        # fabtoken issues always require the action issuer's signature
        return outputs, issuer

    @vguard
    def validate_transfer(self, action_bytes, resolve_input, signed_payload,
                          signatures, now=None, proof_verified=None,
                          sig_verified=None):
        # fabtoken carries no ZK proof: `transfer_batch_plan` never emits
        # a plan, so `proof_verified` is always None here and ignored
        with profiler.leg("input_match"):
            d = loads(action_bytes)
            ids = [ID(t, i) for t, i in d["ids"]]
            if not ids:
                raise ValidationError("transfer must have at least one input")
            ledger_inputs = [resolve_input(i) for i in ids]
            # action must reference the same inputs it was signed over
            if d["inputs"] != ledger_inputs:
                raise ValidationError(
                    "transfer inputs do not match ledger state"
                )
        with profiler.leg("conservation"):
            inputs = [Token.from_bytes(raw) for raw in ledger_inputs]
            outputs = [Token.from_bytes(raw) for raw in d["outputs"]]
            types = {t.type for t in inputs} | {t.type for t in outputs}
            if len(types) != 1:
                raise ValidationError(
                    f"tokens must have the same type, got {sorted(types)}"
                )
            p = self.pp.quantity_precision
            in_sum = sum(t.quantity_as(p).value for t in inputs)
            out_sum = sum(t.quantity_as(p).value for t in outputs)
            if in_sum != out_sum:
                raise ValidationError(
                    f"transfer does not preserve value: "
                    f"in={in_sum} out={out_sum}"
                )
        if len(signatures) != len(inputs):
            raise ValidationError("one signature per input owner required")
        for si, (t, sig) in enumerate(zip(inputs, signatures)):
            v = sig_verified.get(si) if sig_verified else None
            if v is not None and v[0] == t.owner.raw:
                # batched-plane verdict for THIS owner identity: the
                # inputs==ledger check above pinned the claimed owner
                # the verdict was computed over to ledger state
                if not v[1]:
                    raise ValidationError(
                        "invalid owner signature: rejected by the batched "
                        "signature plane"
                    )
                continue
            try:
                identity.verify_signature(t.owner.raw, signed_payload, sig, now=now)
            except ValueError as e:
                raise ValidationError(f"invalid owner signature: {e}") from e
        return ids, d["outputs"]

    # ------------------------------------------------------------ batching

    def transfer_sign_plan(self, action_bytes: bytes):
        """Signature-plane hook: the ACTION-claimed input owners, one per
        required signature. Malformed bytes return None (host path
        rejects them with the precise error)."""
        try:
            d = loads(action_bytes)
            owners = [Token.from_bytes(raw).owner.raw for raw in d["inputs"]]
            return owners or None
        except Exception:
            return None

    def issue_sign_plan(self, action_bytes: bytes):
        """Signature-plane hook: fabtoken issues always require the
        action-named issuer's signature."""
        try:
            issuer = loads(action_bytes)["issuer"]
            return issuer if isinstance(issuer, bytes) and issuer else None
        except Exception:
            return None

    # ------------------------------------------------------------ tokens

    def output_to_unspent(self, token_id, output_bytes, metadata_bytes=None) -> UnspentToken:
        t = Token.from_bytes(output_bytes)
        q = t.quantity_as(self.pp.quantity_precision)
        return UnspentToken(token_id, t.owner, t.type, q.decimal())

    def output_owner(self, output_bytes: bytes) -> bytes:
        return Token.from_bytes(output_bytes).owner.raw

    def verify_owner_signature(self, owner_identity, message, signature) -> None:
        identity.verify_signature(owner_identity, message, signature)
