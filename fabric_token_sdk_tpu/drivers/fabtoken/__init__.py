from .driver import FabTokenDriver, FabTokenPublicParams  # noqa: F401
