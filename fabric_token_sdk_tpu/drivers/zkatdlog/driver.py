"""zkatdlog driver — anonymous tokens with zero-knowledge validation.

Reference: `token/core/zkatdlog/nogh/*` (service.go, issuer.go, sender.go,
validator.go, deserializer.go). Tokens on the ledger are Pedersen
commitments + owner identities (pseudonyms); actions carry ZK proofs
(well-formedness + range) verified by every endorser.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

from ...api.driver import Driver, IssueOutcome, TransferOutcome, ValidationError, vguard
from ...crypto import hostmath as hm, issue as issue_mod, transfer as transfer_mod
from ...crypto.serialization import BytesCache, dumps, loads, loads_cached
from ...crypto.setup import PublicParams
from ...crypto.token import Metadata, Token as ZkToken, TokenDataWitness, token_in_the_clear, tokens_with_witness
from ...models.token import ID, Owner, UnspentToken
from ...utils import profiler
from .. import identity

# Bounded read-only decode cache: chained transfers spend the previous
# tx's outputs, so the same commitment bytes decode repeatedly across
# plan hooks and validation legs.
_ZTOKENS = BytesCache(ZkToken.from_bytes)


class ZKATDLogDriver(Driver):
    name = "zkatdlog"
    supports_anonymous_issue = True

    def __init__(self, pp: PublicParams):
        self.pp = pp
        self._batch_verifier = None
        self._batch_prover = None

    def public_params(self) -> PublicParams:
        return self.pp

    def precision(self) -> int:
        return self.pp.quantity_precision

    # ------------------------------------------------------------ actions

    def issue(self, issuer_identity, token_type, values, owners, anonymous=True,
              rng=None) -> IssueOutcome:
        if len(values) != len(owners):
            raise ValueError("issue: values/owners length mismatch")
        commitments, witnesses = tokens_with_witness(
            list(values), token_type, self.pp.ped_params, rng
        )
        proof = issue_mod.IssueProver(
            witnesses, commitments, anonymous, self.pp, rng
        ).prove()
        outputs = [
            ZkToken(owner=o, data=c).to_bytes() for o, c in zip(owners, commitments)
        ]
        metadata = [
            Metadata(token_type, w.value, w.bf, owner=o, issuer=issuer_identity).to_bytes()
            for w, o in zip(witnesses, owners)
        ]
        action = dumps(
            {
                "outputs": outputs,
                "proof": proof,
                "anon": anonymous,
                "issuer": b"" if anonymous else issuer_identity,
            }
        )
        return IssueOutcome(action_bytes=action, outputs=outputs, metadata=metadata)

    def _transfer_parts(self, input_ids, input_tokens, input_metadata, token_type,
                        values, owners, rng):
        """Everything of a transfer EXCEPT proof generation: witness
        decode/checks and fresh output commitments. Returns the prove
        request consumed by `TransferProver`/`TransferProver.batch` plus
        the assembly context."""
        if len(values) != len(owners):
            raise ValueError("transfer: values/owners length mismatch")
        in_tokens = [ZkToken.from_bytes(raw) for raw in input_tokens]
        in_meta = [Metadata.from_bytes(raw) for raw in input_metadata]
        in_witnesses = [
            TokenDataWitness(m.token_type, m.value, m.bf) for m in in_meta
        ]
        for t, m in zip(in_tokens, in_meta):
            # defensive: openings must match the commitments being spent
            token_in_the_clear(t, m, self.pp.ped_params)
        out_commitments, out_witnesses = tokens_with_witness(
            list(values), token_type, self.pp.ped_params, rng
        )
        prove_req = (
            in_witnesses,
            out_witnesses,
            [t.data for t in in_tokens],
            out_commitments,
        )
        return prove_req, (input_ids, input_tokens, token_type, values, owners)

    def _assemble_transfer(self, ctx, prove_req, proof) -> TransferOutcome:
        input_ids, input_tokens, token_type, values, owners = ctx
        _, out_witnesses, _, out_commitments = prove_req
        outputs = [
            ZkToken(owner=o, data=c).to_bytes() for o, c in zip(owners, out_commitments)
        ]
        metadata = [
            Metadata(token_type, w.value, w.bf, owner=o).to_bytes()
            for w, o in zip(out_witnesses, owners)
        ]
        action = dumps(
            {
                "ids": [[i.tx_id, i.index] for i in input_ids],
                "inputs": list(input_tokens),
                "outputs": outputs,
                "proof": proof,
            }
        )
        return TransferOutcome(action_bytes=action, outputs=outputs, metadata=metadata)

    def transfer(self, input_ids, input_tokens, input_metadata, token_type, values,
                 owners, rng=None) -> TransferOutcome:
        prove_req, ctx = self._transfer_parts(
            input_ids, input_tokens, input_metadata, token_type, values, owners, rng
        )
        proof = transfer_mod.TransferProver(*prove_req, self.pp, rng).prove()
        return self._assemble_transfer(ctx, prove_req, proof)

    def transfer_many(self, transfers: Sequence[tuple], rng=None,
                      min_batch=None) -> List[TransferOutcome]:
        """Batch-prove SPI: build many transfer actions in one pass, with
        proof generation routed through the batched device prover
        (`TransferProver.batch` groups same-shape requests; groups below
        `min_batch` — default FTS_PROVE_MIN_BATCH — and any device-plane
        failure take the host prover — degrade-only, same contract as
        block validation).

        `transfers`: tuples of `transfer()`'s positional arguments
        `(input_ids, input_tokens, input_metadata, token_type, values,
        owners)`. Returns outcomes in request order.
        """
        parts = [self._transfer_parts(*spec, rng) for spec in transfers]
        proofs = transfer_mod.TransferProver.batch(
            [req for req, _ in parts], self.pp, rng=rng, min_batch=min_batch,
        )
        return [
            self._assemble_transfer(ctx, req, proof)
            for (req, ctx), proof in zip(parts, proofs)
        ]

    # ------------------------------------------------------------ validate

    @vguard
    def validate_issue(self, action_bytes: bytes):
        d = loads_cached(action_bytes)
        outputs = [_ZTOKENS.lookup(raw) for raw in d["outputs"]]
        if not outputs:
            raise ValidationError("issue must have at least one output")
        anonymous = d["anon"]
        issuer = d["issuer"]
        if not anonymous:
            if self.pp.issuers and issuer not in self.pp.issuers:
                raise ValidationError("issuer is not authorized")
        elif issuer:
            raise ValidationError("anonymous issue must not name an issuer")
        try:
            with profiler.leg("fiat_shamir"):
                issue_mod.IssueVerifier(
                    [t.data for t in outputs], anonymous, self.pp
                ).verify(d["proof"])
        except ValueError as e:
            raise ValidationError(f"invalid issue proof: {e}") from e
        # non-anonymous issues require the named issuer's signature
        return d["outputs"], issuer

    @vguard
    def validate_transfer(self, action_bytes, resolve_input, signed_payload,
                          signatures, now=None, proof_verified=None,
                          sig_verified=None):
        with profiler.leg("input_match"):
            d = loads_cached(action_bytes)
            ids = [ID(t, i) for t, i in d["ids"]]
            if not ids:
                raise ValidationError("transfer must have at least one input")
            ledger_inputs = [resolve_input(i) for i in ids]
            if d["inputs"] != ledger_inputs:
                raise ValidationError(
                    "transfer inputs do not match ledger state"
                )
        with profiler.leg("conservation"):
            in_tokens = [_ZTOKENS.lookup(raw) for raw in ledger_inputs]
            out_tokens = [_ZTOKENS.lookup(raw) for raw in d["outputs"]]
        if proof_verified is False:
            raise ValidationError("invalid transfer proof")
        if proof_verified is None:
            # host path; proof_verified=True means the block-batched plane
            # already verified the SAME (inputs, outputs, proof) statement
            # this action carries (and the inputs==ledger check above
            # pins the claimed statement to ledger state)
            try:
                with profiler.leg("fiat_shamir"):
                    transfer_mod.TransferVerifier(
                        [t.data for t in in_tokens],
                        [t.data for t in out_tokens],
                        self.pp,
                    ).verify(d["proof"])
            except ValueError as e:
                raise ValidationError(f"invalid transfer proof: {e}") from e
        if len(signatures) != len(in_tokens):
            raise ValidationError("one signature per input owner required")
        for si, (t, sig) in enumerate(zip(in_tokens, signatures)):
            v = sig_verified.get(si) if sig_verified else None
            if v is not None and v[0] == t.owner:
                # batched-plane verdict for THIS owner identity (only pk
                # kinds ever get one — nym/htlc owners stay host-verified)
                if not v[1]:
                    raise ValidationError(
                        "invalid owner signature: rejected by the batched "
                        "signature plane"
                    )
                continue
            try:
                identity.verify_signature(
                    t.owner, signed_payload, sig, nym_params=self.pp.nym_params,
                    now=now,
                )
            except ValueError as e:
                raise ValidationError(f"invalid owner signature: {e}") from e
        return ids, d["outputs"]

    # ------------------------------------------------------------ batching

    def transfer_batch_plan(self, action_bytes: bytes):
        """Block-batched plane hook: extract `(n_in, n_out)` and the
        `(input_points, output_points, proof_bytes)` row the
        `BatchedTransferVerifier` consumes. The statement uses the
        ACTION-claimed inputs — `validate_transfer` separately pins them
        to ledger state, so a verdict computed here is exactly the host
        `TransferVerifier` check. Malformed bytes return None and fall to
        the host path (which rejects them with the precise error)."""
        try:
            d = loads_cached(action_bytes)
            in_tokens = [_ZTOKENS.lookup(raw) for raw in d["inputs"]]
            out_tokens = [_ZTOKENS.lookup(raw) for raw in d["outputs"]]
            proof = d["proof"]
            if not in_tokens or not out_tokens or not isinstance(proof, bytes):
                return None
            shape = (len(in_tokens), len(out_tokens))
            return shape, (
                [t.data for t in in_tokens],
                [t.data for t in out_tokens],
                proof,
            )
        except Exception:
            return None

    def transfer_host_batch(self, rows) -> List[Optional[bool]]:
        """Host-batched proof plane: `rows` are the (input_points,
        output_points, proof_bytes) tuples `transfer_batch_plan` emits for
        groups the device plane did not take. Verified in bulk via
        `transfer_mod.verify_transfer_proofs` — batched commitment
        multiexps plus ONE block-level Fiat-Shamir hash dispatch. True
        verdicts only are decisive; False/None rows fall back to the
        scalar `TransferVerifier`, which owns the precise error."""
        return transfer_mod.verify_transfer_proofs(list(rows), self.pp)

    def transfer_sign_plan(self, action_bytes: bytes):
        """Signature-plane hook: the ACTION-claimed input owners, one per
        required signature (`validate_transfer` pins claimed inputs to
        ledger state before any verdict is applied). Non-`pk` owner
        kinds (nym, htlc) survive here — the pipeline's collector routes
        them host when the identity cache yields no public key."""
        try:
            d = loads_cached(action_bytes)
            owners = [_ZTOKENS.lookup(raw).owner for raw in d["inputs"]]
            return owners or None
        except Exception:
            return None

    def issue_sign_plan(self, action_bytes: bytes):
        """Signature-plane hook: non-anonymous issues carry the named
        issuer's signature; anonymous issues need none."""
        try:
            d = loads_cached(action_bytes)
            if d["anon"]:
                return None
            issuer = d["issuer"]
            return issuer if isinstance(issuer, bytes) and issuer else None
        except Exception:
            return None

    def batch_verifier(self, mesh=None):
        """Cached `BatchedTransferVerifier` (imports the jax-backed ops
        stack lazily — constructing a driver must stay light). The cache
        holds the expensive tables; `mesh` is re-bound on EVERY call —
        including `mesh=None`, which unbinds back to the ambient
        env/unsharded dispatch — so each caller (e.g. each block
        pipeline sharing this driver) gets exactly the dp x mp dispatch
        it configured, never a mesh left over from a previous caller."""
        if self._batch_verifier is None:
            from ...crypto.batch import BatchedTransferVerifier

            self._batch_verifier = BatchedTransferVerifier(self.pp, mesh=mesh)
        else:
            self._batch_verifier.set_mesh(mesh)
        return self._batch_verifier

    def batch_prover(self, mesh=None):
        """Cached `BatchedTransferProver` — the prove-side twin of
        `batch_verifier` (lazy import for the same reason; shares the
        module-level `prover_for` cache with `TransferProver.batch`).
        `mesh` re-binds on every call, `None` unbinds — same contract as
        `batch_verifier`."""
        if self._batch_prover is None:
            from ...crypto.batch_prove import prover_for

            self._batch_prover = prover_for(self.pp, mesh=mesh)
        else:
            self._batch_prover.set_mesh(mesh)
        return self._batch_prover

    # ------------------------------------------------------------ tokens

    def output_to_unspent(self, token_id, output_bytes, metadata_bytes=None) -> UnspentToken:
        t = ZkToken.from_bytes(output_bytes)
        if metadata_bytes is None:
            raise ValueError("zkatdlog tokens need metadata to be opened")
        m = Metadata.from_bytes(metadata_bytes)
        token_type, value, owner = token_in_the_clear(t, m, self.pp.ped_params)
        return UnspentToken(token_id, Owner(owner), token_type, str(value))

    def output_owner(self, output_bytes: bytes) -> bytes:
        return _ZTOKENS.lookup(output_bytes).owner

    def verify_owner_signature(self, owner_identity, message, signature) -> None:
        identity.verify_signature(
            owner_identity, message, signature, nym_params=self.pp.nym_params
        )
