from .driver import ZKATDLogDriver  # noqa: F401
