"""Device-plane dispatch ledger: occupancy, padding waste, and compile
forensics for every staged XLA dispatch.

The staged execution model buys a tiny, fixed program set by padding
every batch up to the tile row count — which makes two numbers the
whole story of device efficiency: how full each tile was (occupancy)
and how much work was padding (waste). This module is the single place
those numbers are recorded. Every device entry point
(`ops/stages.run_rows`, the staged pairing dispatches, and through
them the batched verifiers/signer/prover) opens a `dispatch(...)`
frame naming the canonical XLA program it is about to run; the frame
records requested vs padded rows, dp/mp placement, and wall time, and
feeds the metrics registry:

  * ``device.dispatch.seconds``            — all dispatches, one histogram
  * ``device.dispatch.<program>.seconds``  — per-program wall time
  * ``device.<plane>.occupancy``           — rows / (rows + padding)
  * ``device.<program>.padded_rows``       — cumulative padding waste

Frames are thread-local, so the `jax.monitoring` compile/cache
listeners (ops/__init__) can attribute backend compile wall time and
persistent-cache hits to the program that triggered them — the join
between XLA's anonymous compile events and `stages.stage_programs()`.
Degrade decisions (breaker-open skips, dispatch-error fallbacks,
fused-pairing shape bailouts) land in the same per-program ledger via
`note_degrade`, so "this program ran slow because it ran on the host"
is visible next to its occupancy.

Contract (mirrors utils/profiler.py): **zero cost when off**. The
ledger is on by default (it is pure dict arithmetic on the dispatch
path — no threads, no sampling); ``FTS_DEVOBS=0`` turns every entry
point into a passthrough that touches neither the ledger nor the
metrics registry. On or off, it only observes: verify verdicts and
committed state are identical either way (tests/test_devobs.py pins
both properties differentially).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Optional, Tuple

from . import metrics as mx

__all__ = [
    "enabled",
    "dispatch",
    "plane",
    "attribute",
    "current_program",
    "note_compile",
    "note_cache",
    "note_degrade",
    "snapshot",
    "reset",
    "health_section",
    "section",
]

UNATTRIBUTED = "(unattributed)"
DEFAULT_PLANE = "stages"

# occupancy lives in (0, 1]; the default latency buckets would collapse
# it into two bins
_OCC_BUCKETS = tuple(i / 10.0 for i in range(1, 11))

_tl = threading.local()
_lock = threading.Lock()
# (plane, program) -> aggregate dict
_programs: Dict[Tuple[str, str], dict] = {}
# best-effort fallback for compile events fired on sharding worker
# threads (the dispatch frame lives on the caller's thread)
_last_frame: Optional[Tuple[str, str]] = None


def enabled() -> bool:
    """Ledger switch; read per entry so tests/operators can flip it."""
    return os.environ.get("FTS_DEVOBS", "1") != "0"


def _entry(frame: Tuple[str, str]) -> dict:
    e = _programs.get(frame)
    if e is None:
        e = _programs[frame] = {
            "dispatches": 0,
            "rows": 0,
            "padded_rows": 0,
            "wall_s": 0.0,
            "dp": 1,
            "mp": 1,
            "compiles": 0,
            "compile_s": 0.0,
            "cache_hits": 0,
            "cache_misses": 0,
            "degrades": {},
        }
    return e


def current_plane() -> str:
    return getattr(_tl, "plane", None) or DEFAULT_PLANE


@contextlib.contextmanager
def plane(name: str):
    """Tag dispatches in this block with a logical plane (verify, sign,
    prove, ...). Passthrough when the ledger is off."""
    if not enabled():
        yield
        return
    prev = getattr(_tl, "plane", None)
    _tl.plane = name
    try:
        yield
    finally:
        _tl.plane = prev


@contextlib.contextmanager
def attribute(program: str, plane_name: Optional[str] = None):
    """Attribute compile/cache events in this block to `program`
    WITHOUT recording a dispatch — the warmup precompiler's frame."""
    if not enabled():
        yield
        return
    global _last_frame
    frame = (plane_name or current_plane(), program)
    prev = getattr(_tl, "frame", None)
    _tl.frame = frame
    _last_frame = frame
    try:
        yield
    finally:
        _tl.frame = prev


@contextlib.contextmanager
def dispatch(
    program: str,
    *,
    rows: int,
    padded_rows: int = 0,
    dp: int = 1,
    mp: int = 1,
    plane: Optional[str] = None,
):
    """Record one device dispatch of `program`: requested vs padded
    rows, dp/mp placement, wall time. Passthrough when off."""
    if not enabled():
        yield
        return
    global _last_frame
    pl = plane or current_plane()
    frame = (pl, program)
    prev = getattr(_tl, "frame", None)
    _tl.frame = frame
    _last_frame = frame
    t0 = time.monotonic()
    try:
        yield
    finally:
        wall = time.monotonic() - t0
        _tl.frame = prev
        with _lock:
            e = _entry(frame)
            e["dispatches"] += 1
            e["rows"] += rows
            e["padded_rows"] += padded_rows
            e["wall_s"] += wall
            e["dp"] = dp
            e["mp"] = mp
        total = rows + padded_rows
        mx.histogram("device.dispatch.seconds").observe(wall)
        mx.histogram(f"device.dispatch.{program}.seconds").observe(wall)
        if total:
            mx.histogram(
                f"device.{pl}.occupancy", buckets=_OCC_BUCKETS
            ).observe(rows / total)
        if padded_rows:
            mx.counter(f"device.{program}.padded_rows").inc(padded_rows)


def _active_frame() -> Tuple[str, str]:
    f = getattr(_tl, "frame", None)
    return f or _last_frame or (DEFAULT_PLANE, UNATTRIBUTED)


def current_program() -> Optional[str]:
    """The program of the innermost dispatch/attribute frame (this
    thread first, then the process-wide last frame), else None."""
    f = getattr(_tl, "frame", None) or _last_frame
    return f[1] if f else None


def note_compile(seconds: float) -> None:
    """Called by the jax.monitoring duration listener: attribute one
    backend compile's wall time to the active program."""
    if not enabled():
        return
    frame = _active_frame()
    with _lock:
        e = _entry(frame)
        e["compiles"] += 1
        e["compile_s"] += seconds


def note_cache(event: str) -> None:
    """Called by the jax.monitoring event listener: attribute a
    persistent-compilation-cache hit/miss to the active program."""
    if not enabled():
        return
    if event.endswith("cache_hits"):
        key = "cache_hits"
    elif event.endswith("cache_misses"):
        key = "cache_misses"
    else:
        return
    frame = _active_frame()
    with _lock:
        _entry(frame)[key] += 1


def note_degrade(
    reason: str,
    program: Optional[str] = None,
    plane: Optional[str] = None,
) -> None:
    """Record a degrade decision (breaker-open skip, dispatch-error
    fallback, fused-pairing shape bailout) against the active — or
    explicitly named — program."""
    if not enabled():
        return
    if program is not None:
        frame = (plane or current_plane(), program)
    else:
        frame = _active_frame()
    with _lock:
        degrades = _entry(frame)["degrades"]
        degrades[reason] = degrades.get(reason, 0) + 1


def snapshot() -> Dict[Tuple[str, str], dict]:
    """Raw per-(plane, program) aggregates — for window diffing in
    tests and bench; values are copies."""
    with _lock:
        return {
            frame: dict(e, degrades=dict(e["degrades"]))
            for frame, e in _programs.items()
        }


def reset() -> None:
    """Drop all ledger state (registry metrics are untouched)."""
    global _last_frame
    with _lock:
        _programs.clear()
    _last_frame = None


def _occ(rows: int, padded: int) -> Optional[float]:
    total = rows + padded
    return round(rows / total, 4) if total else None


def _waste(rows: int, padded: int) -> Optional[float]:
    total = rows + padded
    return round(padded / total, 4) if total else None


def health_section() -> dict:
    """The `device` block of `Network.health()` / the `ops.health` RPC:
    per-plane occupancy plus the full per-program ledger."""
    snap = snapshot()
    programs: Dict[str, dict] = {}
    planes: Dict[str, dict] = {}
    for (pl, prog), e in sorted(snap.items()):
        q = mx.REGISTRY.histogram(f"device.dispatch.{prog}.seconds")
        p50 = q.quantile(0.5)
        p99 = q.quantile(0.99)
        programs[f"{pl}:{prog}"] = {
            "plane": pl,
            "program": prog,
            "dispatches": e["dispatches"],
            "rows": e["rows"],
            "padded_rows": e["padded_rows"],
            "occupancy": _occ(e["rows"], e["padded_rows"]),
            "waste_frac": _waste(e["rows"], e["padded_rows"]),
            "wall_s": round(e["wall_s"], 6),
            "p50_s": round(p50, 6) if p50 is not None else None,
            "p99_s": round(p99, 6) if p99 is not None else None,
            "dp": e["dp"],
            "mp": e["mp"],
            "compiles": e["compiles"],
            "compile_s": round(e["compile_s"], 3),
            "cache_hits": e["cache_hits"],
            "cache_misses": e["cache_misses"],
            "degrades": sum(e["degrades"].values()),
            "degrade_reasons": dict(e["degrades"]),
        }
        agg = planes.setdefault(
            pl, {"dispatches": 0, "rows": 0, "padded_rows": 0}
        )
        agg["dispatches"] += e["dispatches"]
        agg["rows"] += e["rows"]
        agg["padded_rows"] += e["padded_rows"]
    for agg in planes.values():
        agg["occupancy"] = _occ(agg["rows"], agg["padded_rows"])
        agg["waste_frac"] = _waste(agg["rows"], agg["padded_rows"])
    return {"enabled": enabled(), "planes": planes, "programs": programs}


def section() -> dict:
    """The schema-validated `device` section of a bench result
    (utils/benchschema.py): top-level scalars the `ftstop compare
    --device` gate reads, plus the per-plane / per-program breakdown."""
    h = health_section()
    rows = sum(e["rows"] for e in h["programs"].values())
    padded = sum(e["padded_rows"] for e in h["programs"].values())
    agg = mx.REGISTRY.histogram("device.dispatch.seconds")
    p50 = agg.quantile(0.5)
    p99 = agg.quantile(0.99)
    return {
        "dispatches": sum(
            e["dispatches"] for e in h["programs"].values()
        ),
        "rows": rows,
        "padded_rows": padded,
        "occupancy": _occ(rows, padded),
        "waste_frac": _waste(rows, padded),
        "dispatch_p50_s": round(p50, 6) if p50 is not None else None,
        "dispatch_p99_s": round(p99, 6) if p99 is not None else None,
        "compiles": sum(e["compiles"] for e in h["programs"].values()),
        "compile_s": round(
            sum(e["compile_s"] for e in h["programs"].values()), 3
        ),
        "cache_hits": sum(
            e["cache_hits"] for e in h["programs"].values()
        ),
        "cache_misses": sum(
            e["cache_misses"] for e in h["programs"].values()
        ),
        "degrades": sum(e["degrades"] for e in h["programs"].values()),
        "planes": h["planes"],
        "programs": h["programs"],
    }
