"""Resilience layer: bounded device dispatch + per-plane circuit breakers.

The degrade chains built so far (sharded -> unsharded -> host, device ->
host) only handle device calls that *fail fast*: an exception falls
through to the host path and the block commits with identical verdicts.
A call that HANGS — the sick-axon-tunnel failure mode behind every
historical rc=124, which bench/multichip guard with deadline watchdogs
but the product commit path did not — blocks the commit worker forever.
This module closes that gap with the two primitives every serving stack
pairs:

* ``bounded_call(fn, deadline_s)`` — run one device dispatch on a daemon
  worker thread and give the caller back control when the wall budget
  expires (``DeviceTimeout``). An abandoned JAX call **cannot be
  cancelled**: the worker keeps running until the backend returns, and
  its eventual result is DISCARDED, never applied (counted under
  ``resilience.bounded.stragglers``). Discarding is safe because every
  device plane here is read-only over request bytes — verdicts/proofs
  only take effect when the supervisor returns them, and a timed-out
  supervisor never does.

* ``CircuitBreaker`` — per-plane closed/open/half-open breaker. Bounded
  dispatch alone would let every new block pay a full deadline against a
  sick backend (and stack one abandoned worker per attempt); the breaker
  is what stops new work from piling on: after
  ``FTS_BREAKER_FAILURES`` consecutive failures or
  ``FTS_BREAKER_TIMEOUTS`` consecutive timeouts it OPENS, rejecting
  dispatches outright (instant host fallback) for
  ``FTS_BREAKER_COOLDOWN_S`` of monotonic-clock cooldown, then admits
  exactly ONE half-open probe; a probe success closes the breaker (the
  plane heals itself — no restart, no operator), a probe failure re-opens
  it and restarts the cooldown.

Accept/reject can never depend on this layer: a rejected or timed-out
dispatch falls to the exact host path the degrade chain already proves
verdict-identical (differential-tested including the ``hang`` fault kind
in tests/test_resilience.py).

Planes wired (one breaker each, registered lazily by name):

    verify  — `BlockValidationPipeline.proof_verdicts` group calls
    sign    — `BlockValidationPipeline.sign_verdicts` (REPLACES the old
              permanent construction-failure latch: a transient OOM now
              heals via the half-open probe)
    prove   — `TransferProver.batch` group routing
    stages  — `stages.run_tile_spans` sharded dispatch (breaker only:
              an open breaker skips straight to the sequential walk)

Deadlines resolve per plane via ``device_deadline_s(plane)``:
``FTS_DEVICE_DEADLINE_<PLANE>_S`` wins, else ``FTS_DEVICE_DEADLINE_S``,
else the default — commit-path planes (verify/sign) are bounded at
``ACCEL_DEADLINE_S`` (120s) when the live jax backend is a real
accelerator and UNBOUNDED on the CPU-emulated plane (where a legitimate
cold compile or big-block verify takes minutes and a tight default would
open the breaker against a healthy backend); client-side planes
(prove/stages) default unbounded. ``0`` always means unbounded, and an
unbounded call runs inline (no supervisor thread).

Observability: counters ``resilience.breaker.{open,close,probe,
rejected}`` and ``resilience.bounded.{calls,timeouts,stragglers}``, a
per-plane state gauge (0=closed, 1=half-open, 2=open), and a ``breaker``
flight event per transition/timeout/straggler — surfaced as the breaker
column in ``ftstop top`` (via ``ops.health``) and the resilience summary
line of ``ftsmetrics show``.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from . import metrics as mx
from .tracing import logger

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

# default wall budget of commit-path device dispatch on a REAL
# accelerator (generous: a healthy warmed-up device verify is seconds;
# only a wedged backend runs into minutes)
ACCEL_DEADLINE_S = 120.0

# planes bounded by default (on accelerators) — the commit path
_COMMIT_PLANES = ("verify", "sign")


class DeviceTimeout(RuntimeError):
    """A bounded device dispatch exceeded its wall deadline. The
    abandoned worker may still be running (a JAX call cannot be
    cancelled); its late result is discarded, never applied."""


def _env_num(name: str, default: float) -> float:
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        return default


class CircuitBreaker:
    """closed/open/half-open breaker guarding one device plane.

    Thread-safe; all transitions happen under one lock and are counted +
    flight-recorded OUTSIDE it. The clock is injectable for tests
    (monotonic by default — wall-clock jumps must not early-close a
    breaker).
    """

    def __init__(self, plane: str,
                 failure_threshold: Optional[int] = None,
                 timeout_threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.plane = plane
        self.failure_threshold = int(
            _env_num("FTS_BREAKER_FAILURES", 5)
            if failure_threshold is None else failure_threshold
        )
        self.timeout_threshold = int(
            _env_num("FTS_BREAKER_TIMEOUTS", 2)
            if timeout_threshold is None else timeout_threshold
        )
        self.cooldown_s = float(
            _env_num("FTS_BREAKER_COOLDOWN_S", 30.0)
            if cooldown_s is None else cooldown_s
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0  # consecutive failures of any kind
        self._timeouts = 0  # consecutive deadline timeouts
        self._opened_at = 0.0
        self._probing = False  # the single half-open probe is in flight
        self._gauge()  # live-state gauge exists from creation (0=closed)

    # ------------------------------------------------------------ state

    @property
    def state(self) -> str:
        """Current state, with the open->half-open cooldown transition
        applied (so observers see `half-open` once a probe is due)."""
        with self._lock:
            self._tick()
            return self._state

    def _tick(self) -> None:
        # lock held: promote open -> half-open once the cooldown expires
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._state = HALF_OPEN
            self._probing = False
            self._gauge()  # the live-state gauge tracks the promotion too

    def _gauge(self) -> None:
        mx.gauge(f"resilience.breaker.state.{self.plane}").set(
            _STATE_CODE[self._state]
        )

    def rejecting(self) -> bool:
        """Non-consuming admission preview: True while the plane is
        hard-open (cooldown not yet expired). Half-open is NOT rejecting
        — a probe is available. Cheap enough for per-block fast-path
        gates that want to skip even collection work."""
        with self._lock:
            self._tick()
            rejected = self._state == OPEN
        if rejected:
            mx.counter("resilience.breaker.rejected").inc()
        return rejected

    def allow(self) -> bool:
        """Consuming admission check, called immediately before one
        dispatch: True in closed state, True for exactly ONE caller in
        half-open (the probe — everyone else is rejected until the probe
        reports), False while open. The caller that got True MUST report
        back via `record_success`/`record_failure`."""
        with self._lock:
            self._tick()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                probe = True
            else:
                probe = False
        if probe:
            mx.counter("resilience.breaker.probe").inc()
            mx.flight("breaker", plane=self.plane, event="probe")
            return True
        mx.counter("resilience.breaker.rejected").inc()
        return False

    def cancel_probe(self) -> None:
        """Release a consumed `allow()` admission WITHOUT recording an
        outcome — for the caller that discovered there is nothing to
        dispatch after all (e.g. the driver has no batched plane). The
        half-open probe slot re-opens for the next dispatcher; state is
        otherwise unchanged. Without this, an unreported probe would
        wedge the breaker in half-open forever."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        with self._lock:
            was = self._state
            self._state = CLOSED
            self._failures = 0
            self._timeouts = 0
            self._probing = False
            self._gauge()
        if was != CLOSED:
            mx.counter("resilience.breaker.close").inc()
            mx.flight("breaker", plane=self.plane, event="close")
            logger.info(
                "resilience: %s breaker closed (plane healed)", self.plane
            )

    def record_failure(self, timeout: bool = False,
                       trip_now: bool = False) -> None:
        """`trip_now` opens the breaker on THIS failure regardless of
        thresholds — for structural failures (e.g. verifier construction
        OOM) where per-block retries are known-useless; unlike the old
        process-lifetime latch, the half-open probe still heals it."""
        with self._lock:
            self._failures += 1
            self._timeouts = self._timeouts + 1 if timeout else 0
            tripped = trip_now or self._state == HALF_OPEN  # failed probe
            if self._state == CLOSED and (
                self._failures >= self.failure_threshold
                or self._timeouts >= self.timeout_threshold
            ):
                tripped = True
            if tripped:
                self._state = OPEN
                self._opened_at = self._clock()
                self._probing = False
            self._gauge()
        if tripped:
            mx.counter("resilience.breaker.open").inc()
            mx.flight(
                "breaker", plane=self.plane, event="open",
                timeout=bool(timeout), cooldown_s=self.cooldown_s,
            )
            logger.warning(
                "resilience: %s breaker OPEN (%s) — dispatches fall "
                "straight to host for %.1fs, then one half-open probe",
                self.plane, "timeout" if timeout else "failures",
                self.cooldown_s,
            )


# ---------------------------------------------------------------- registry

_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker(plane: str) -> CircuitBreaker:
    """Process-wide breaker for one plane (created lazily; env-config is
    read at creation, so tests set `FTS_BREAKER_*` then `reset()`)."""
    with _breakers_lock:
        b = _breakers.get(plane)
        if b is None:
            b = _breakers[plane] = CircuitBreaker(plane)
        return b


def breaker_states() -> Dict[str, str]:
    """{plane: state} snapshot of every breaker that exists — the body
    of the `ops.health` breaker section and the `ftstop top` column."""
    with _breakers_lock:
        bs = list(_breakers.items())
    return {plane: b.state for plane, b in bs}


def reset() -> None:
    """Drop every breaker (test isolation — breakers are process-global
    by design, like the fault registry)."""
    with _breakers_lock:
        _breakers.clear()


# ---------------------------------------------------------------- deadlines


def _accelerator_backend() -> bool:
    """True when jax is ALREADY imported and its default backend is a
    real accelerator. Mirrors `sign_enabled` auto-resolution: this must
    never be the call that initializes a backend on the commit path."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return jax.default_backend() != "cpu"
    except Exception:
        return False


def device_deadline_s(plane: str) -> float:
    """Wall budget for one bounded dispatch of `plane`. Resolution:
    `FTS_DEVICE_DEADLINE_<PLANE>_S` > `FTS_DEVICE_DEADLINE_S` > default.
    0 = unbounded (runs inline, no supervisor thread). Default: the
    commit-path planes (verify/sign) are bounded at `ACCEL_DEADLINE_S`
    on a real accelerator and unbounded on the CPU-emulated plane —
    there a cold compile or big-block verify legitimately takes minutes,
    and a tight default would open the breaker against a healthy
    backend. Client-side planes (prove/stages) default unbounded."""
    v = os.environ.get(f"FTS_DEVICE_DEADLINE_{plane.upper()}_S")
    if v is None:
        v = os.environ.get("FTS_DEVICE_DEADLINE_S")
    if v is not None:
        try:
            return max(0.0, float(v))
        except ValueError:
            pass
    if plane in _COMMIT_PLANES and _accelerator_backend():
        return ACCEL_DEADLINE_S
    return 0.0


# ---------------------------------------------------------------- bounded

# live ABANDONED workers (timed-out dispatches still running). A daemon
# thread executing native XLA code while the interpreter tears down can
# segfault the process at exit (observed: rc=139 after a chaos run), so
# exit waits a bounded `FTS_STRAGGLER_DRAIN_S` for stragglers to finish
# — short stragglers drain cleanly; a truly hung one still cannot block
# shutdown for more than the budget.
_stragglers: List[threading.Thread] = []
_stragglers_lock = threading.Lock()

# thread-local view of the CURRENT bounded worker's abandonment event —
# the hook completion-contract counters consult (see call_abandoned)
_tls = threading.local()


def call_abandoned() -> bool:
    """True when called from inside a bounded worker whose supervisor
    already timed out and abandoned it. The device planes guard their
    counted-on-COMPLETION metrics (`batch.sign.rows`,
    `batch.prove.{batches,txs}`, `batch.transfer.txs`) with this, so a
    discarded straggler's work is never reported as device-served —
    those rows were ALSO counted as host fallbacks by the caller, and
    double-reporting would corrupt the soak's `sign_plane`/summary
    accounting. False on every ordinary thread."""
    evt = getattr(_tls, "abandon_evt", None)
    return evt is not None and evt.is_set()


def _track_straggler(worker: threading.Thread) -> None:
    with _stragglers_lock:
        _stragglers[:] = [t for t in _stragglers if t.is_alive()]
        _stragglers.append(worker)


def drain_stragglers(timeout_s: float = 5.0) -> bool:
    """Join abandoned workers for up to `timeout_s` total; True when
    none remain alive. Called automatically at interpreter exit."""
    deadline = time.monotonic() + max(0.0, timeout_s)
    with _stragglers_lock:
        live = [t for t in _stragglers if t.is_alive()]
        _stragglers[:] = live
    for t in live:
        t.join(max(0.0, deadline - time.monotonic()))
    with _stragglers_lock:
        _stragglers[:] = [t for t in _stragglers if t.is_alive()]
        return not _stragglers


atexit.register(
    lambda: drain_stragglers(
        _env_num("FTS_STRAGGLER_DRAIN_S", 5.0)
    )
)


def bounded_call(fn: Callable, deadline_s: Optional[float], *args,
                 plane: str = "device", **kwargs):
    """Run `fn(*args, **kwargs)` under a wall deadline.

    `deadline_s` None/0 runs inline (unbounded — zero overhead, the
    default on emulated backends). Otherwise `fn` runs on a daemon
    worker thread with the caller's trace context propagated; if it does
    not finish within the budget, `DeviceTimeout` raises on the CALLER's
    stack and the worker is abandoned — it keeps running (a JAX call
    cannot be cancelled), but whatever it eventually returns or raises
    is discarded, never applied, and counted as a straggler. Exceptions
    from a non-abandoned `fn` re-raise on the caller's stack unchanged.
    """
    if not deadline_s or deadline_s <= 0:
        return fn(*args, **kwargs)
    mx.counter("resilience.bounded.calls").inc()
    box: dict = {}
    done = threading.Event()
    abandon_evt = threading.Event()
    lock = threading.Lock()
    state = {"finished": False, "abandoned": False}
    ctx = mx.current_trace()

    def _run():
        _tls.abandon_evt = abandon_evt  # visible to call_abandoned()
        try:
            with mx.use_trace(ctx):
                box["result"] = fn(*args, **kwargs)
            box["ok"] = True
        except BaseException as e:  # delivered to (or discarded for) caller
            box["error"] = e
        finally:
            with lock:
                state["finished"] = True
                straggler = state["abandoned"]
            done.set()
            if straggler:
                # completed AFTER the caller gave up: the result above is
                # dead — the host fallback already resolved the block
                mx.counter("resilience.bounded.stragglers").inc()
                mx.flight(
                    "breaker", plane=plane, event="straggler",
                    ok="error" not in box,
                )

    worker = threading.Thread(
        target=_run, name=f"fts-bounded-{plane}", daemon=True
    )
    worker.start()
    if not done.wait(deadline_s):
        with lock:
            finished = state["finished"]
            if not finished:
                state["abandoned"] = True
                abandon_evt.set()
        if not finished:
            _track_straggler(worker)
            mx.counter("resilience.bounded.timeouts").inc()
            mx.flight(
                "breaker", plane=plane, event="timeout",
                deadline_s=deadline_s,
            )
            raise DeviceTimeout(
                f"{plane}: device dispatch exceeded its {deadline_s}s wall "
                "deadline (worker abandoned; a late result is discarded)"
            )
        # finished in the race window between wait() expiry and the lock:
        # box is fully populated before `finished` flips — take the result
    if box.get("ok"):
        return box["result"]
    raise box["error"]
