"""Lightweight span tracing + counters (reference: fabric-smart-client's
flogging/metrics used throughout token/services)."""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger("fts_tpu")


@dataclass
class Span:
    name: str
    start: float
    end: Optional[float] = None
    attrs: dict = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return (self.end or time.monotonic()) - self.start


class Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self.spans: List[Span] = []
        self.counters: Dict[str, int] = defaultdict(int)
        self.enabled = True

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        if not self.enabled:
            yield None
            return
        s = Span(name, time.monotonic(), attrs=attrs)
        try:
            yield s
        finally:
            s.end = time.monotonic()
            with self._lock:
                self.spans.append(s)
                if len(self.spans) > 10000:
                    del self.spans[:5000]

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def summary(self) -> Dict[str, dict]:
        with self._lock:
            agg: Dict[str, dict] = {}
            for s in self.spans:
                a = agg.setdefault(s.name, {"count": 0, "total_s": 0.0})
                a["count"] += 1
                a["total_s"] += s.duration
            return agg

    def reset(self) -> None:
        with self._lock:
            self.spans.clear()
            self.counters.clear()


tracer = Tracer()
