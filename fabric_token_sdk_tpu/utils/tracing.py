"""Span tracing facade over the metrics core (``utils/metrics.py``).

Historical note: this module began as a standalone 70-line tracer wired
into exactly one call site; it is now a thin compatibility adapter so
existing ``tracer.span(...)`` / ``tracer.count(...)`` call sites feed the
process-wide metrics registry (one export plane, one enable switch —
``FTS_METRICS=1``). New code should import ``utils.metrics`` directly.
"""

from __future__ import annotations

import logging
from typing import Dict

from . import metrics

logger = logging.getLogger("fts_tpu")

# re-exported for callers that used the old dataclass directly
Span = metrics.Span


class Tracer:
    """Compatibility shim: the old Tracer API over the shared registry."""

    @property
    def enabled(self) -> bool:
        return metrics.enabled()

    @enabled.setter
    def enabled(self, flag: bool) -> None:
        metrics.enable(flag)

    def span(self, name: str, **attrs):
        return metrics.span(name, **attrs)

    def count(self, name: str, n: int = 1) -> None:
        metrics.counter(name).inc(n)

    def summary(self) -> Dict[str, dict]:
        return metrics.REGISTRY.span_summary()

    def reset(self) -> None:
        metrics.REGISTRY.reset()


tracer = Tracer()
