"""Span tracing facade over the metrics core (``utils/metrics.py``).

Historical note: this module began as a standalone 70-line tracer wired
into exactly one call site; it is now a thin compatibility adapter so
existing ``tracer.span(...)`` / ``tracer.count(...)`` call sites feed the
process-wide metrics registry (one export plane, one enable switch —
``FTS_METRICS=1``). There is exactly ONE span model: `metrics.Span`,
which since the distributed-tracing plane landed also carries
``trace_id`` / ``span_id`` / ``parent_span_id`` — this facade delegates
to that trace-context API rather than keeping any parallel ID scheme
(``Span``, ``TraceContext``, ``new_trace``, ``current_trace``,
``use_trace`` are re-exported below). New code should import
``utils.metrics`` directly.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

from . import metrics

logger = logging.getLogger("fts_tpu")

# re-exported for callers that used the old dataclass directly, and for
# the trace-context API (one span model, one id scheme — metrics.py's)
Span = metrics.Span
TraceContext = metrics.TraceContext
new_trace = metrics.new_trace
current_trace = metrics.current_trace
use_trace = metrics.use_trace


class Tracer:
    """Compatibility shim: the old Tracer API over the shared registry."""

    @property
    def enabled(self) -> bool:
        return metrics.enabled()

    @enabled.setter
    def enabled(self, flag: bool) -> None:
        metrics.enable(flag)

    def span(self, name: str, **attrs):
        return metrics.span(name, **attrs)

    def count(self, name: str, n: int = 1) -> None:
        metrics.counter(name).inc(n)

    def current_trace(self) -> Optional[metrics.TraceContext]:
        return metrics.current_trace()

    def summary(self) -> Dict[str, dict]:
        return metrics.REGISTRY.span_summary()

    def reset(self) -> None:
        metrics.REGISTRY.reset()


tracer = Tracer()
