"""Fault injection: named fault points armed via env or API.

The durability/recovery guarantees of the ledger (WAL + snapshot
recovery, exactly-once remote submit) are only credible if they are
exercised under injected faults — this module is the lever the chaos
suite (`tests/test_recovery.py`) pulls. Production code sprinkles
zero-cost `faults.fire("<site>")` calls at the crash-interesting
boundaries; nothing happens unless a fault is armed for that site.

Registered sites (grep for `faults.fire` to confirm the live set):

    wal.append           before a WAL record is written + fsync'd
    ledger.commit_block  before the block's WAL append / atomic merge
    orderer.cut          before a block is cut from the ordering queue
    remote.send          client-side, before a request frame is sent
    remote.recv          client-side, before a response frame is read
    batch.verify         inside the device-plane block verify (degrades
                         to host validation, never fails the block)
    batch.sign           inside the batched signature verify (degrades
                         every signature row to the host loop, never
                         fails the block)
    vault.append         before a vault-journal record is written +
                         fsync'd (a failure degrades LOUDLY — counter +
                         flight event — the in-memory view still applies)
    vault.snapshot       before a vault snapshot compaction (a failure
                         only means the journal keeps growing)
    vault.recover        at the start of `PersistentTokenStore.recover`
    selector.lock        inside `ShardedLocker.try_lock` (kind `delay`
                         widens contention windows for chaos runs)
    repl.ship            leader-side, on the follower link's thread
                         before one WAL record is shipped (degrades that
                         ONE link — the bounded ack wait keeps the
                         commit path live; drops are counted loudly)
    repl.apply           follower-side, at the start of
                         `Network.apply_delta` (an error surfaces as a
                         typed answer to the shipper, which reconnects
                         and re-syncs from the journal)
    repl.heartbeat       leader-side, on the link thread before a lease
                         heartbeat (kind `drop`/`hang` starves the
                         follower's lease — how the auto-promotion
                         watchdog is chaos-tested)

Arming:

* Env: ``FTS_FAULTS="site:kind:prob[:count[:delay_s]]"``, comma-separated
  for multiple sites; parsed once at import and re-parseable via
  ``load_env()`` (tests set the env then call it). Example:
  ``FTS_FAULTS="remote.recv:drop:1.0:1"`` drops the client connection
  exactly once, with probability 1.
* Programmatic: ``faults.arm("wal.append", "error", prob=0.5, count=3)``.

Kinds: ``error`` raises ``FaultInjected``; ``drop`` raises
``FaultConnectionDrop`` (a ``ConnectionError``, so transport-level retry
paths treat it exactly like a real dead socket); ``delay`` sleeps
``delay_s`` then returns; ``hang`` BLOCKS the firing thread until the
site is disarmed (``disarm``/``clear`` release it) or a cap expires
(``delay_s``, default ``HANG_CAP_S`` = 120s when unspecified) — the
fault kind that models an indefinite device stall, which ``delay``
cannot (its sleep always returns on schedule). The resilience layer's
bounded dispatch (`utils/resilience.py`) is tested against ``hang``:
the hung worker is abandoned at the deadline and released here at
disarm/cap. Every firing increments the ``faults.injected.<site>``
counter, so a chaos run's sidecar records exactly what was injected
where.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from . import metrics as mx


class FaultInjected(RuntimeError):
    """An armed `error`-kind fault point fired."""


class FaultConnectionDrop(ConnectionError):
    """An armed `drop`-kind fault point fired (transport-shaped)."""


_KINDS = ("error", "drop", "delay", "hang")

# default cap of a `hang` firing when no explicit delay_s is armed: long
# enough that only a deadline-bounded caller escapes it, short enough
# that a hung worker thread is eventually released even if nobody disarms
HANG_CAP_S = 120.0


@dataclass
class _Armed:
    site: str
    kind: str
    prob: float = 1.0
    remaining: Optional[int] = None  # None = unlimited firings
    delay_s: float = 0.05
    exc: Optional[BaseException] = None  # overrides the default exception
    release: Optional[threading.Event] = None  # hang: set on disarm/clear


_armed: Dict[str, _Armed] = {}
_lock = threading.Lock()
# deterministic by default so prob<1 chaos runs are reproducible
_rng = random.Random(int(os.environ.get("FTS_FAULTS_SEED", "0xF75"), 0))


def arm(site: str, kind: str = "error", prob: float = 1.0,
        count: Optional[int] = None, delay_s: Optional[float] = None,
        exc: Optional[BaseException] = None) -> None:
    """Arm `site` to fire `count` times (None = forever) with `prob`.
    For `hang`, `delay_s` is the release CAP (default `HANG_CAP_S`)."""
    if kind not in _KINDS:
        raise ValueError(f"unknown fault kind {kind!r} (want one of {_KINDS})")
    if delay_s is None:
        delay_s = HANG_CAP_S if kind == "hang" else 0.05
    release = threading.Event() if kind == "hang" else None
    with _lock:
        old = _armed.get(site)
        _armed[site] = _Armed(site, kind, prob, count, delay_s, exc, release)
    if old is not None and old.release is not None:
        old.release.set()  # re-arming must not strand earlier hangers


def disarm(site: str) -> None:
    with _lock:
        f = _armed.pop(site, None)
    if f is not None and f.release is not None:
        f.release.set()  # release any thread blocked in a hang firing


def clear() -> None:
    with _lock:
        fs = list(_armed.values())
        _armed.clear()
    for f in fs:
        if f.release is not None:
            f.release.set()


def armed() -> Dict[str, str]:
    """Snapshot of armed sites -> kind (for logs/tests)."""
    with _lock:
        return {s: f.kind for s, f in _armed.items()}


def fire(site: str) -> None:
    """The fault point: no-op unless `site` is armed (the disarmed fast
    path is one dict lookup on an almost-always-empty dict)."""
    if not _armed:
        return
    with _lock:
        f = _armed.get(site)
        if f is None:
            return
        if f.remaining is not None and f.remaining <= 0:
            return
        if f.prob < 1.0 and _rng.random() >= f.prob:
            return
        if f.remaining is not None:
            f.remaining -= 1
        kind, delay_s, exc, release = f.kind, f.delay_s, f.exc, f.release
    mx.counter(f"faults.injected.{site}").inc()
    # flight-record the firing with the ACTIVE trace id, so a chaos run
    # can correlate each injected fault to the exact tx it hit
    mx.flight("fault", site=site, fault_kind=kind)
    if kind == "delay":
        time.sleep(delay_s)
        return
    if kind == "hang":
        # an indefinite stall, bounded only by disarm()/clear() or the
        # armed cap — the firing thread then RETURNS (the stall ended;
        # the call it was injected into proceeds normally, so a caller
        # that abandoned it at a deadline sees a straggler completion)
        release.wait(delay_s)
        return
    if exc is not None:
        raise exc
    if kind == "drop":
        raise FaultConnectionDrop(f"injected connection drop at {site}")
    raise FaultInjected(f"injected fault at {site}")


def load_env(spec: Optional[str] = None) -> int:
    """Parse ``FTS_FAULTS="site:kind:prob[:count[:delay_s]],..."`` and arm
    every entry; returns how many were armed. A malformed entry raises
    (arming faults wrong should be loud, not silent)."""
    if spec is None:
        spec = os.environ.get("FTS_FAULTS", "")
    n = 0
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(f"bad FTS_FAULTS entry {part!r}")
        site, kind = fields[0], fields[1]
        # an empty field keeps its default, so "site:delay:1.0::1.5"
        # reads as prob=1.0, unlimited count, delay_s=1.5
        prob = float(fields[2]) if len(fields) > 2 and fields[2] else 1.0
        count = int(fields[3]) if len(fields) > 3 and fields[3] else None
        # None lets arm() pick the per-kind default (hang: HANG_CAP_S)
        delay_s = float(fields[4]) if len(fields) > 4 and fields[4] else None
        arm(site, kind, prob=prob, count=count, delay_s=delay_s)
        n += 1
    return n


load_env()
