"""SLO engine: declarative service-level objectives over sliding windows.

SRE error-budget practice for the token node: each SLO states what
fraction of events must be GOOD over a sliding window, the engine
evaluates it from the always-on instruments (histograms + counters —
nothing new is measured, only re-read), and the error-budget BURN rate
is the one number an operator or CI gate needs: burn < 1 means the
window is within budget, burn >= 1 means the budget is exhausted and
the objective is being missed right now.

Objectives (targets via `FTS_SLO_*`, all optional):

    finality_p99   99% of submissions reach finality within
                   `FTS_SLO_FINALITY_P99_S` (default 1.0s), from
                   `network.submit_to_finality.seconds`
    commit_p99     99% of block commits complete within
                   `FTS_SLO_COMMIT_P99_S` (default 1.0s), from
                   `ledger.block.commit.seconds`
    availability   at least `FTS_SLO_AVAILABILITY` (default 0.999) of
                   submissions are admitted: bad = backpressure rejects
                   + breaker-open rejections, total = enqueued + rejects

A p99 <= T objective is evaluated as "fraction of window observations
<= T must be >= 0.99" — computed from bucket-count DELTAS between
ring-buffered cumulative histogram states (`Histogram.state()`), so the
cumulative snapshot/Prometheus semantics are untouched. Burn =
bad_frac / (1 - objective); budget_remaining = max(0, 1 - burn).

Surfaces: the `slo` section of `ops.health` (and from there the `slo=`
column of `ftstop top`), `slo.burn.<slo>` / `slo.budget.<slo>` gauges,
a `slo.breaches` counter plus one `slo.breach` flight event per
ok->exhausted transition, the `slo` section of the bench result JSON,
and the `ftstop compare --slo` CI gate (exit 1 on budget exhaustion).

Slow-tx exemplars: a bounded ring of the `FTS_SLO_EXEMPLARS` (default
5) slowest submit-to-finality transactions, recorded by
`Submission._resolve` and published into registry meta
(`slo.exemplars`) so every sidecar carries concrete tx/trace ids for
`ftstrace timeline` after a soak.
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import metrics as mx

DEFAULT_WINDOW_S = 60.0
DEFAULT_FINALITY_P99_S = 1.0
DEFAULT_COMMIT_P99_S = 1.0
DEFAULT_AVAILABILITY = 0.999

# the counters behind the availability objective (deltas over the window)
_CTR_ENQUEUED = "ledger.ordering.enqueued"
_CTR_BACKPRESSURE = "orderer.backpressure.rejects"
_CTR_BREAKER_REJECTED = "resilience.breaker.rejected"
_COUNTERS = (_CTR_ENQUEUED, _CTR_BACKPRESSURE, _CTR_BREAKER_REJECTED)

_HIST_FINALITY = "network.submit_to_finality.seconds"
_HIST_COMMIT = "ledger.block.commit.seconds"
_HISTS = (_HIST_FINALITY, _HIST_COMMIT)


def _env_num(name: str, default: float) -> float:
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        return default


class SLOEngine:
    """Sliding-window SLO evaluator over the process-wide registry.

    Lazily driven — no thread of its own: `evaluate()` (and the
    throttled `tick()` the ledger calls after each block commit)
    appends a timestamped cumulative state to a bounded ring, diffs the
    newest state against the one closest to `window_s` ago, and derives
    per-SLO burn. Evaluation touches only instrument locks, never the
    ledger or commit locks, so a health probe can never stall on it."""

    def __init__(self, window_s: Optional[float] = None,
                 finality_p99_s: Optional[float] = None,
                 commit_p99_s: Optional[float] = None,
                 availability: Optional[float] = None):
        self.window_s = max(
            1.0,
            _env_num("FTS_SLO_WINDOW_S", DEFAULT_WINDOW_S)
            if window_s is None else window_s,
        )
        self.finality_p99_s = (
            _env_num("FTS_SLO_FINALITY_P99_S", DEFAULT_FINALITY_P99_S)
            if finality_p99_s is None else finality_p99_s
        )
        self.commit_p99_s = (
            _env_num("FTS_SLO_COMMIT_P99_S", DEFAULT_COMMIT_P99_S)
            if commit_p99_s is None else commit_p99_s
        )
        self.availability = min(
            0.999999,
            _env_num("FTS_SLO_AVAILABILITY", DEFAULT_AVAILABILITY)
            if availability is None else availability,
        )
        self._lock = threading.Lock()
        # ring of (monotonic_t, {hist: (counts, count, sum)}, {ctr: value})
        self._ring: List[Tuple[float, dict, dict]] = []
        self._min_gap_s = max(0.25, self.window_s / 32.0)
        self._last_tick = 0.0
        self._last_ok: Dict[str, bool] = {}
        self._seed()

    def _seed(self) -> None:
        # seed the ring with the creation-time state so the FIRST
        # evaluation already has a baseline: until a full window has
        # passed, the "window" is everything since the engine was built
        # (engine construction == soak start in bench, process start
        # otherwise)
        hists, ctrs = self._capture()
        with self._lock:
            self._ring.append((time.monotonic(), hists, ctrs))

    # -- state capture ------------------------------------------------

    @staticmethod
    def _capture() -> Tuple[dict, dict]:
        hists = {}
        for name in _HISTS:
            h = mx.REGISTRY.histogram(name)
            hists[name] = (h.buckets,) + h.state()
        ctrs = {name: mx.REGISTRY.counter(name).value for name in _COUNTERS}
        return hists, ctrs

    def _append(self, now: float, hists: dict, ctrs: dict) -> None:
        with self._lock:
            if self._ring and now - self._ring[-1][0] < self._min_gap_s:
                return
            self._ring.append((now, hists, ctrs))
            # keep one state OLDER than the window as the delta baseline;
            # prune everything older than that
            cutoff = now - 1.5 * self.window_s
            while len(self._ring) > 2 and self._ring[1][0] < cutoff:
                self._ring.pop(0)

    def _baseline(self, now: float) -> Optional[Tuple[float, dict, dict]]:
        with self._lock:
            if not self._ring:
                return None
            base = self._ring[0]
            for entry in self._ring:
                if entry[0] <= now - self.window_s:
                    base = entry
                else:
                    break
            return base

    # -- evaluation ---------------------------------------------------

    def _latency_row(self, name: str, threshold: float,
                     now_h: dict, base_h: dict) -> dict:
        buckets, counts_n, count_n, _sum_n = now_h[name]
        _b, counts_b, count_b, _sum_b = base_h[name]
        delta = [a - b for a, b in zip(counts_n, counts_b)]
        total = count_n - count_b
        good_frac = mx.Histogram.fraction_le(buckets, delta, threshold)
        return self._row(0.99, good_frac, total, target_s=threshold)

    def _availability_row(self, now_c: dict, base_c: dict) -> dict:
        bad = (
            (now_c[_CTR_BACKPRESSURE] - base_c[_CTR_BACKPRESSURE])
            + (now_c[_CTR_BREAKER_REJECTED] - base_c[_CTR_BREAKER_REJECTED])
        )
        admitted = now_c[_CTR_ENQUEUED] - base_c[_CTR_ENQUEUED]
        total = admitted + (
            now_c[_CTR_BACKPRESSURE] - base_c[_CTR_BACKPRESSURE]
        )
        good_frac = (
            None if total <= 0 else max(0.0, 1.0 - bad / total)
        )
        return self._row(self.availability, good_frac, int(total))

    @staticmethod
    def _row(objective: float, good_frac: Optional[float], total: int,
             target_s: Optional[float] = None) -> dict:
        if good_frac is None:
            burn = 0.0  # no traffic in the window: nothing burned
            good_frac_out = None
        else:
            burn = (1.0 - good_frac) / (1.0 - objective)
            good_frac_out = round(good_frac, 6)
        row = {
            "objective": objective,
            "good_frac": good_frac_out,
            "total": max(0, int(total)),
            "burn": round(burn, 4),
            "budget_remaining": round(max(0.0, 1.0 - burn), 4),
            "ok": burn < 1.0,
        }
        if target_s is not None:
            row["target_s"] = target_s
        return row

    def evaluate(self) -> dict:
        """Evaluate every SLO over the sliding window; returns the
        `slo` section served by `ops.health` and recorded in the bench
        result JSON. Fires gauges, the `slo.breaches` counter and an
        `slo.breach` flight event on each ok -> exhausted transition."""
        now = time.monotonic()
        hists, ctrs = self._capture()
        self._append(now, hists, ctrs)
        base = self._baseline(now)
        if base is None:  # unreachable after the append above; defensive
            base = (now, hists, ctrs)
        _t, base_h, base_c = base
        slos = {
            "finality_p99": self._latency_row(
                _HIST_FINALITY, self.finality_p99_s, hists, base_h
            ),
            "commit_p99": self._latency_row(
                _HIST_COMMIT, self.commit_p99_s, hists, base_h
            ),
            "availability": self._availability_row(ctrs, base_c),
        }
        for name, row in slos.items():
            mx.gauge(f"slo.burn.{name}").set(row["burn"])
            mx.gauge(f"slo.budget.{name}").set(row["budget_remaining"])
            was_ok = self._last_ok.get(name, True)
            if was_ok and not row["ok"]:
                mx.counter("slo.breaches").inc()
                mx.flight(
                    "slo.breach", slo=name, burn=row["burn"],
                    good_frac=row["good_frac"], total=row["total"],
                    objective=row["objective"],
                )
            self._last_ok[name] = row["ok"]
        return {"window_s": self.window_s, "slos": slos}

    def tick(self) -> None:
        """Throttled evaluate — the ledger calls this after each block
        commit so breaches surface during load even when nothing polls
        `ops.health`. At most one evaluation per second."""
        now = time.monotonic()
        if now - self._last_tick < 1.0:
            return
        self._last_tick = now
        self.evaluate()

    def health_section(self) -> dict:
        """The `slo` body of `ops.health` (a fresh evaluation)."""
        return self.evaluate()

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last_ok.clear()
            self._last_tick = 0.0
        self._seed()


ENGINE = SLOEngine()


def reset(**kwargs) -> SLOEngine:
    """Rebuild the process-wide engine (re-reading `FTS_SLO_*` env) and
    clear the exemplar ring — test isolation, like `faults.clear()`."""
    global ENGINE
    ENGINE = SLOEngine(**kwargs)
    with _ex_lock:
        _ex_heap.clear()
    return ENGINE


# ------------------------------------------------------------ exemplars

_ex_lock = threading.Lock()
# min-heap of (seconds, seq, tx_id, trace_id): the K slowest stay, the
# heap root is the fastest of the kept set and the eviction candidate
_ex_heap: List[Tuple[float, int, str, Optional[str]]] = []
_ex_seq = 0


def _exemplar_k() -> int:
    try:
        return max(0, int(os.environ.get("FTS_SLO_EXEMPLARS", "5")))
    except ValueError:
        return 5


def record_exemplar(seconds: float, tx_id: str,
                    trace_id: Optional[str]) -> None:
    """Offer one submit-to-finality observation to the slow-tx ring.
    Keeps the K slowest; publishes to registry meta only when the kept
    set actually changes (so the common fast path is one lock + one
    heap peek)."""
    global _ex_seq
    k = _exemplar_k()
    if k <= 0:
        return
    with _ex_lock:
        if len(_ex_heap) >= k and seconds <= _ex_heap[0][0]:
            return
        _ex_seq += 1
        heapq.heappush(_ex_heap, (seconds, _ex_seq, tx_id, trace_id))
        while len(_ex_heap) > k:
            heapq.heappop(_ex_heap)
        top = sorted(_ex_heap, reverse=True)
    mx.REGISTRY.set_meta(
        "slo.exemplars",
        [[round(s, 6), tx, tr] for s, _q, tx, tr in top],
    )


def exemplars() -> List[Tuple[float, str, Optional[str]]]:
    """The current K slowest (seconds, tx_id, trace_id), slowest first."""
    with _ex_lock:
        top = sorted(_ex_heap, reverse=True)
    return [(s, tx, tr) for s, _q, tx, tr in top]
