"""Metrics core: counters, gauges, histograms, span trees, trace
contexts, a crash flight recorder, and the export plane.

Reference parity: fabric-smart-client threads a metrics provider
(`platform/view/services/metrics`) and `flogging` through every token
service; this module is our equivalent, grown out of the original
70-line `utils/tracing.py` span tracer.

Design:

* One process-wide thread-safe ``Registry`` (``REGISTRY``) holding named
  counters / gauges / histograms, completed span trees, and phase
  timelines. Instruments are get-or-create by name, so call sites never
  coordinate.
* **Counters are always live** — an increment is one lock + int add,
  unmeasurable next to any group operation — while **spans and
  heartbeats are env-gated** (``FTS_METRICS=1``, or ``enable()``):
  the disabled ``span()`` fast path is a single global check.
* **Trace contexts** (Dapper/OpenTelemetry style): ``new_trace()`` mints
  a ``trace_id``; ``use_trace(ctx)`` activates it for the thread; spans
  opened under it carry ``trace_id``/``span_id``/``parent_span_id`` and
  a wall-clock ``start_unix``, so per-transaction causal timelines can
  be stitched across threads AND processes (``TraceContext.to_wire`` /
  ``from_wire`` is the propagation format `remote.py` injects into
  request frames). ``cmd/ftstrace.py`` assembles the timelines.
* **Flight recorder** (``FLIGHT`` / ``flight(kind, ...)``): an always-on
  bounded ring of structured lifecycle events (submits, block cuts,
  verify decisions, WAL appends, faults, retries, compile/cache events),
  each tagged with the active trace id. Dumped to a ``*.flight.json``
  sidecar alongside every metrics sidecar flush — an rc=124 death
  leaves *what was happening*, not just final counter values.
* Export: ``to_json()`` (the ``*.metrics.json`` sidecar format read by
  ``cmd/ftsmetrics.py``) and ``to_prometheus()`` (text exposition
  format, counters/gauges/histograms only).
* Crash-proofing: ``install_sidecar(path)`` registers an ``atexit``
  hook plus SIGTERM/SIGINT handlers that flush the registry to a JSON
  sidecar, so a killed benchmark (rc=124) still leaves a full
  accounting. ``flush_sidecar()`` can also be called explicitly (e.g.
  from a watchdog thread about to ``os._exit``).
* ``Heartbeat`` emits phase-stamped progress lines to stderr from a
  daemon thread (``[fts] phase=compile elapsed=134s``) and records the
  phase timeline in the registry (and the flight recorder).
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import signal
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

_TRUTHY = ("1", "true", "yes", "on")

_enabled = os.environ.get("FTS_METRICS", "0").strip().lower() in _TRUTHY


def enabled() -> bool:
    return _enabled


def enable(flag: bool = True) -> None:
    """Turn span/heartbeat recording on (bench does this unconditionally)."""
    global _enabled
    _enabled = flag


# ------------------------------------------------------------ instruments


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value


# Latency buckets sized for this codebase: sub-ms host ops up through
# multi-minute XLA pairing compiles.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)

# Quantile labels every histogram exports (JSON snapshot keys and
# Prometheus `<name>_<label>` series) — the latency numbers the live ops
# plane (`ops.metrics` RPC, `cmd/ftstop.py top`) reads.
QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


class Histogram:
    __slots__ = ("name", "buckets", "_counts", "_count", "_sum", "_min",
                 "_max", "_lock")

    def __init__(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf bucket
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @staticmethod
    def _interp(q: float, buckets, counts, total: int,
                lo: float, hi: float) -> float:
        """Bucket-interpolated quantile estimate (Prometheus
        `histogram_quantile` style): find the bucket where the cumulative
        count crosses rank ``q*total`` and interpolate linearly between
        its bounds. The result is clamped to the OBSERVED ``[min, max]``
        — a single observation reports itself exactly, and the first
        bucket can never report below the true minimum. A rank landing
        in the +Inf bucket reports the observed max (the best bounded
        estimate an unbounded bucket allows)."""
        rank = q * total
        cum, prev = 0, 0.0
        for b, c in zip(buckets, counts):
            if c and cum + c >= rank:
                v = prev + (b - prev) * (rank - cum) / c
                return min(max(v, lo), hi)
            cum += c
            prev = b
        return hi

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the q-quantile (0 < q < 1); None when empty."""
        with self._lock:
            if not self._count:
                return None
            counts = list(self._counts)
            total, lo, hi = self._count, self._min, self._max
        return self._interp(q, self.buckets, counts, total, lo, hi)

    def state(self) -> tuple:
        """`(counts, count, sum)` — a consistent copy of the cumulative
        internal state, the primitive sliding-window consumers (the SLO
        engine) DIFF between two instants. Read-only: windowing lives
        entirely in the consumer's ring of these copies, so the
        cumulative `snapshot()`/`to_prometheus()` semantics are
        untouched by construction."""
        with self._lock:
            return list(self._counts), self._count, self._sum

    @staticmethod
    def fraction_le(buckets, counts, threshold: float) -> Optional[float]:
        """Fraction of observations <= `threshold` given per-bucket
        counts (typically a window DELTA of two `state()` copies),
        interpolating linearly inside the bucket the threshold falls in
        (Prometheus `histogram_quantile` style, inverted). None when the
        counts are empty — no data is not the same as all-good."""
        total = sum(counts)
        if total <= 0:
            return None
        good = 0.0
        prev = 0.0
        for b, c in zip(buckets, counts):
            if threshold >= b:
                good += c
                prev = b
                continue
            if threshold > prev and c:
                good += c * (threshold - prev) / (b - prev)
            break
        return min(1.0, good / total)

    def snapshot(self) -> dict:
        # timed acquire: may run under a signal handler (see Registry)
        acquired = self._lock.acquire(timeout=1.0)
        try:
            d = {
                "count": self._count,
                "sum": round(self._sum, 6),
                "buckets": {
                    ("%g" % b): c
                    for b, c in zip(self.buckets, self._counts)
                    if c
                },
            }
            if self._counts[-1]:
                d["buckets"]["+Inf"] = self._counts[-1]
            if self._count:
                d["min"] = round(self._min, 6)
                d["max"] = round(self._max, 6)
                d["mean"] = round(self._sum / self._count, 6)
                counts = list(self._counts)
                for label, q in QUANTILES:
                    d[label] = round(
                        self._interp(
                            q, self.buckets, counts, self._count,
                            self._min, self._max,
                        ),
                        6,
                    )
            return d
        finally:
            if acquired:
                self._lock.release()


# ------------------------------------------------------------ span trees


@dataclass
class Span:
    name: str
    start: float  # monotonic
    end: Optional[float] = None
    attrs: dict = field(default_factory=dict)
    children: List["Span"] = field(default_factory=list)
    # trace plane: wall-clock anchor + ids for cross-process stitching
    start_unix: float = 0.0
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else time.monotonic()) - self.start

    def to_dict(self) -> dict:
        d = {"name": self.name, "duration_s": round(self.duration, 6)}
        if self.start_unix:
            d["start_unix"] = round(self.start_unix, 6)
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.span_id:
            d["span_id"] = self.span_id
        if self.parent_span_id:
            d["parent_span_id"] = self.parent_span_id
        if self.attrs:
            d["attrs"] = {k: _jsonable(v) for k, v in self.attrs.items()}
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


_tls = threading.local()


# ------------------------------------------------------------ trace context


def _new_id(nbytes: int = 8) -> str:
    return os.urandom(nbytes).hex()


@dataclass
class TraceContext:
    """Propagatable trace identity (Dapper / OpenTelemetry trace-context
    style): ``trace_id`` names one end-to-end transaction; ``span_id``
    is the id new child spans adopt as their parent. ``to_wire()`` /
    ``from_wire()`` is the cross-process format `remote.py` carries in
    request frames."""

    trace_id: str
    span_id: str = ""

    def to_wire(self) -> list:
        return [self.trace_id, self.span_id]

    @classmethod
    def from_wire(cls, wire) -> Optional["TraceContext"]:
        if not wire:
            return None
        try:
            return cls(str(wire[0]), str(wire[1]) if len(wire) > 1 else "")
        except (TypeError, KeyError, IndexError):
            return None


def new_trace() -> TraceContext:
    """Mint a fresh trace context. Always available — trace ids tag
    flight-recorder events even when span recording is disabled."""
    REGISTRY.counter("trace.traces").inc()
    return TraceContext(_new_id(8), _new_id(4))


def current_trace() -> Optional[TraceContext]:
    """The thread's active trace context: derived from the innermost
    open span when it belongs to the `use_trace`-activated trace (so new
    children nest correctly), else the activation itself — an explicit
    `use_trace` of a DIFFERENT trace overrides enclosing spans. That
    override is what lets a group-commit thread attribute per-tx work to
    each submitting tx's trace while its own spans stay open."""
    ctx = getattr(_tls, "trace", None)
    stack = getattr(_tls, "stack", None)
    if stack:
        s = stack[-1]
        if s.trace_id and (ctx is None or ctx.trace_id == s.trace_id):
            return TraceContext(s.trace_id, s.span_id)
    return ctx


@contextlib.contextmanager
def use_trace(ctx: Optional[TraceContext]):
    """Activate `ctx` for this thread (None = no-op): spans opened and
    flight events recorded inside join the trace."""
    if ctx is None:
        yield None
        return
    prev = getattr(_tls, "trace", None)
    _tls.trace = ctx
    try:
        yield ctx
    finally:
        _tls.trace = prev


@contextlib.contextmanager
def span(name: str, **attrs):
    """Timed span; nests into the per-thread open span, inherits the
    active trace context, auto-observes its duration into histogram
    ``<name>.seconds``. No-op (yields None) when metrics are disabled."""
    if not _enabled:
        yield None
        return
    s = Span(name, time.monotonic(), attrs=attrs)
    s.start_unix = time.time()
    s.span_id = _new_id(4)
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    parent = stack[-1] if stack else None
    # trace linkage: inherit from the parent span when it belongs to the
    # same trace as the active `use_trace` context (or no context is
    # active); an explicitly activated DIFFERENT trace wins — the
    # group-commit thread validates other submitters' txs under their
    # traces while its own (traceless or other-trace) spans stay open
    ctx = getattr(_tls, "trace", None)
    if parent is not None and parent.trace_id and (
        ctx is None or ctx.trace_id == parent.trace_id
    ):
        s.trace_id = parent.trace_id
        s.parent_span_id = parent.span_id
    elif ctx is not None:
        s.trace_id = ctx.trace_id
        s.parent_span_id = ctx.span_id
    if s.trace_id:
        REGISTRY.counter("trace.spans").inc()
    stack.append(s)
    try:
        yield s
    finally:
        s.end = time.monotonic()
        stack.pop()
        if parent is not None:
            parent.children.append(s)
        else:
            REGISTRY.record_span_root(s)
        REGISTRY.histogram(name + ".seconds").observe(s.duration)


def record_span(name: str, start_unix: float, end_unix: float,
                trace: Optional[TraceContext] = None, **attrs) -> Optional[Span]:
    """Record an already-timed root span (for work measured across
    threads — e.g. a submission's queue wait stamped at block cut, or
    the per-tx client leg of a batched wire call). Gated like `span`."""
    if not _enabled:
        return None
    s = Span(name, 0.0, end=max(0.0, end_unix - start_unix), attrs=attrs)
    s.start_unix = start_unix
    s.span_id = _new_id(4)
    if trace is not None:
        s.trace_id = trace.trace_id
        s.parent_span_id = trace.span_id
        REGISTRY.counter("trace.spans").inc()
    REGISTRY.record_span_root(s)
    REGISTRY.histogram(name + ".seconds").observe(s.duration)
    return s


# ------------------------------------------------------------ registry


class Registry:
    """Thread-safe named-instrument store + export plane."""

    MAX_SPAN_ROOTS = 2000

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._span_roots: List[Span] = []
        self._phases: List[dict] = []
        self._meta: Dict[str, object] = {}

    # -- get-or-create -------------------------------------------------

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        """Get-or-create by name; `buckets` applies only on FIRST creation
        — a later caller passing different buckets gets the existing
        instrument unchanged."""
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name, buckets))
        return h

    # -- spans / phases / meta ----------------------------------------

    def record_span_root(self, s: Span) -> None:
        with self._lock:
            self._span_roots.append(s)
            if len(self._span_roots) > self.MAX_SPAN_ROOTS:
                del self._span_roots[: self.MAX_SPAN_ROOTS // 2]

    MAX_PHASES = 500

    def record_phase(self, name: str, start: float, end: Optional[float],
                     **attrs) -> None:
        row = {"name": name, "start_unix": round(start, 3)}
        if end is not None:
            row["elapsed_s"] = round(end - start, 3)
        if attrs:
            row["attrs"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            self._phases.append(row)
            if len(self._phases) > self.MAX_PHASES:
                del self._phases[: self.MAX_PHASES // 2]

    def set_meta(self, key: str, value) -> None:
        # timed acquire: called from the SIGTERM handler, which may have
        # interrupted the very thread holding this non-reentrant lock
        acquired = self._lock.acquire(timeout=1.0)
        try:
            self._meta[key] = _jsonable(value)
        finally:
            if acquired:
                self._lock.release()

    # -- export --------------------------------------------------------

    def span_summary(self) -> Dict[str, dict]:
        """Aggregate completed span trees by name (depth-first)."""
        agg: Dict[str, dict] = {}

        def walk(s: Span):
            a = agg.setdefault(s.name, {"count": 0, "total_s": 0.0})
            a["count"] += 1
            a["total_s"] += s.duration
            for c in s.children:
                walk(c)

        acquired = self._lock.acquire(timeout=1.0)
        try:
            roots = list(self._span_roots)
        finally:
            if acquired:
                self._lock.release()
        for s in roots:
            walk(s)
        for a in agg.values():
            a["total_s"] = round(a["total_s"], 6)
        return agg

    def snapshot(self) -> dict:
        # timed acquire: flush_sidecar() runs from signal handlers, which
        # can interrupt a thread that already holds this (non-reentrant)
        # lock — fall back to a best-effort unlocked read over deadlock
        acquired = self._lock.acquire(timeout=1.0)
        try:
            counters = {n: c.value for n, c in sorted(self._counters.items())}
            gauges = {n: g.value for n, g in sorted(self._gauges.items())}
            hists = {n: h for n, h in sorted(self._histograms.items())}
            phases = list(self._phases)
            meta = dict(self._meta)
            roots = list(self._span_roots)
        finally:
            if acquired:
                self._lock.release()
        return {
            "meta": meta,
            "pid": os.getpid(),
            "flushed_unix": round(time.time(), 3),
            "phases": phases,
            "counters": counters,
            "gauges": gauges,
            "histograms": {n: h.snapshot() for n, h in hists.items()},
            "span_summary": self.span_summary(),
            "spans": [s.to_dict() for s in roots[-200:]],
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=False)

    def to_prometheus(self) -> str:
        """Text exposition format. Metric names sanitized to [a-z0-9_]."""
        lines: List[str] = []
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._histograms.items())
        for name, c in counters:
            m = _prom_name(name)
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {c.value}")
        for name, g in gauges:
            m = _prom_name(name)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_prom_num(g.value)}")
        for name, h in hists:
            m = _prom_name(name)
            lines.append(f"# TYPE {m} histogram")
            cum = 0
            with h._lock:
                counts = list(h._counts)
                total, s = h._count, h._sum
                lo, hi = h._min, h._max
            for b, n in zip(h.buckets, counts):
                cum += n
                lines.append(f'{m}_bucket{{le="{_prom_num(b)}"}} {cum}')
            cum += counts[-1]
            lines.append(f'{m}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{m}_sum {_prom_num(s)}")
            lines.append(f"{m}_count {total}")
            if total:
                # bucket-interpolated quantiles as companion gauges (the
                # buckets above allow server-side histogram_quantile too)
                for label, q in QUANTILES:
                    v = Histogram._interp(q, h.buckets, counts, total, lo, hi)
                    lines.append(f"{m}_{label} {_prom_num(round(v, 9))}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._span_roots.clear()
            self._phases.clear()
            self._meta.clear()


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() else "_" for c in name.lower())
    if out and out[0].isdigit():
        out = "_" + out
    return "fts_" + out


def _prom_num(v: float) -> str:
    return ("%d" % v) if float(v).is_integer() else repr(float(v))


REGISTRY = Registry()


# convenience module-level aliases used throughout the runtime
def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str, buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, buckets)


@contextlib.contextmanager
def timed(hist_name: str):
    """Observe the block's wall time into a histogram (gated like span)."""
    if not _enabled:
        yield
        return
    t0 = time.monotonic()
    try:
        yield
    finally:
        REGISTRY.histogram(hist_name).observe(time.monotonic() - t0)


# ------------------------------------------------------------ heartbeat


class Heartbeat:
    """Phase-stamped progress lines on stderr from a daemon thread.

    ``[fts] phase=compile program=miller_tile elapsed=134s total=250s``

    Phases (and their wall times) are also recorded in the registry so a
    sidecar flushed at death reports exactly where the time went.
    """

    def __init__(self, tag: str = "fts", interval_s: Optional[float] = None,
                 stream=None):
        self.tag = tag
        self.interval_s = (
            float(os.environ.get("FTS_HEARTBEAT_SECS", "15"))
            if interval_s is None
            else interval_s
        )
        self.stream = stream if stream is not None else sys.stderr
        self._t0 = time.time()
        self._phase = "init"
        self._phase_start = self._t0
        self._attrs: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def set_phase(self, name: str, **attrs) -> None:
        now = time.time()
        with self._lock:
            prev, prev_start, prev_attrs = self._phase, self._phase_start, self._attrs
            self._phase, self._phase_start, self._attrs = name, now, attrs
        # lifecycle events are always flight-recorded (the ring is how a
        # killed run answers "which phase was live, after what history")
        FLIGHT.record("phase", phase=name, **attrs)
        if _enabled:  # phases are gated like spans/heartbeat lines
            # per-phase memory telemetry: stamp the COMPLETING phase with
            # the process/device footprint it ended at (sysmon never
            # triggers jax backend init — safe before the platform probe)
            done_attrs = dict(prev_attrs)
            try:
                from . import sysmon

                mem = sysmon.sample()
                done_attrs.setdefault("rss_mb", round(mem["rss_bytes"] / 1e6, 1))
                if mem.get("device_bytes") is not None:
                    done_attrs.setdefault(
                        "dev_mem_mb", round(mem["device_bytes"] / 1e6, 1)
                    )
            except Exception:
                pass  # telemetry must never break a phase change
            REGISTRY.record_phase(prev, prev_start, now, **done_attrs)
            REGISTRY.gauge("progress.phase_start_unix").set(now)
            REGISTRY.set_meta("progress.phase", name)
        self.emit()

    def emit(self) -> None:
        if not _enabled:
            return  # heartbeats are env-gated like spans (FTS_METRICS=1)
        with self._lock:
            phase, phase_start, attrs = self._phase, self._phase_start, self._attrs
        now = time.time()
        extra = "".join(f" {k}={_jsonable(v)}" for k, v in attrs.items())
        try:
            print(
                f"[{self.tag}] phase={phase}{extra} "
                f"elapsed={now - phase_start:.0f}s total={now - self._t0:.0f}s",
                file=self.stream,
                flush=True,
            )
        except Exception:
            pass  # stderr may be gone at interpreter teardown

    def start(self) -> "Heartbeat":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="fts-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.emit()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            phase, phase_start, attrs = self._phase, self._phase_start, self._attrs
        if _enabled:
            REGISTRY.record_phase(phase, phase_start, time.time(), **attrs)


# ------------------------------------------------------------ flight recorder


class FlightRecorder:
    """Bounded ring buffer of structured lifecycle events — the crash
    flight recorder. Always on (recording is one lock + deque append on
    rare events: submits, block cuts, verify decisions, WAL appends,
    faults, retries, compiles), so an rc=124 death leaves a causal trail
    of *what was happening*, not just final counter values. The ring is
    dumped to a ``*.flight.json`` sidecar by every `flush_sidecar` (and
    on demand via `dump`); capacity comes from ``FTS_FLIGHT_EVENTS``
    (default 1024) — sustained load evicts the oldest events only."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("FTS_FLIGHT_EVENTS", "1024"))
            except ValueError:
                capacity = 1024
        self.capacity = max(1, capacity)
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def record(self, kind: str, trace: Optional[TraceContext] = None,
               **attrs) -> None:
        ctx = trace if trace is not None else current_trace()
        evt = {"ts": round(time.time(), 6), "kind": kind}
        if ctx is not None:
            evt["trace_id"] = ctx.trace_id
        for k, v in attrs.items():
            if v is not None:
                evt[k] = _jsonable(v)
        # timed acquire: tail()/dump() may run under a signal handler
        acquired = self._lock.acquire(timeout=1.0)
        try:
            self._ring.append(evt)
        finally:
            if acquired:
                self._lock.release()
        REGISTRY.counter("flight.events").inc()

    def tail(self, n: Optional[int] = None) -> List[dict]:
        acquired = self._lock.acquire(timeout=1.0)
        try:
            if acquired:
                events = list(self._ring)
            else:
                # unlocked best-effort read (signal-handler path, lock
                # held by the interrupted thread): a concurrent append
                # can invalidate iteration — retry, then settle for an
                # empty tail rather than raising out of the flush
                events = []
                for _ in range(3):
                    try:
                        events = list(self._ring)
                        break
                    except RuntimeError:
                        continue
        finally:
            if acquired:
                self._lock.release()
        return events if n is None else events[-n:]

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self, path: str) -> Optional[str]:
        """Write the ring to `path` (atomic rename); returns the path,
        or None on failure. Safe under signal handlers — NEVER raises
        (the SIGTERM flush must not die building its own payload)."""
        try:
            payload = json.dumps(
                {
                    "dumped_unix": round(time.time(), 3),
                    "pid": os.getpid(),
                    "capacity": self.capacity,
                    "events": self.tail(),
                }
            )
        except Exception:
            return None
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as fh:
                fh.write(payload)
            os.replace(tmp, path)
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        REGISTRY.counter("flight.dumps").inc()
        return path


FLIGHT = FlightRecorder()


def flight(kind: str, trace: Optional[TraceContext] = None, **attrs) -> None:
    """Record one flight-recorder event (always on; tags the active —
    or explicitly passed — trace context)."""
    FLIGHT.record(kind, trace=trace, **attrs)


def flight_sidecar_path(metrics_path: str) -> str:
    """Derive the flight sidecar path from a metrics sidecar path
    (``X.metrics.json`` -> ``X.flight.json``)."""
    if metrics_path.endswith(".metrics.json"):
        return metrics_path[: -len(".metrics.json")] + ".flight.json"
    return metrics_path + ".flight.json"


# ------------------------------------------------------------ sidecar


_sidecar_lock = threading.Lock()
_sidecar_path: Optional[str] = None
_sidecar_installed = False


def flush_sidecar(path: Optional[str] = None) -> Optional[str]:
    """Write the registry snapshot to the sidecar JSON (atomic rename)
    and the flight-recorder ring to the derived ``*.flight.json``.

    Safe to call from signal handlers and watchdog threads; returns the
    metrics path written, or None if no path is configured.
    """
    p = path or _sidecar_path
    if not p:
        return None
    payload = REGISTRY.to_json()
    acquired = _sidecar_lock.acquire(timeout=2.0)  # may run under a signal
    try:
        tmp = f"{p}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as fh:
                fh.write(payload)
            os.replace(tmp, p)
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    finally:
        if acquired:
            _sidecar_lock.release()
    FLIGHT.dump(flight_sidecar_path(p))
    return p


def install_sidecar(path: str,
                    signals: Sequence[int] = (signal.SIGTERM, signal.SIGINT)) -> None:
    """Flush a metrics sidecar on normal exit AND on SIGTERM/SIGINT.

    This is what turns an rc=124 (``timeout`` sends SIGTERM) from a
    zero-information outcome into a full per-phase accounting. Signal
    handlers chain to the default disposition so the exit code still
    reflects the kill.
    """
    global _sidecar_path, _sidecar_installed
    _sidecar_path = path
    if _sidecar_installed:
        return
    _sidecar_installed = True
    atexit.register(flush_sidecar)

    def _on_signal(signum, frame):
        REGISTRY.set_meta("killed_by_signal", signum)
        flush_sidecar()
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    for sig in signals:
        try:
            signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            pass  # not the main thread / unsupported platform
