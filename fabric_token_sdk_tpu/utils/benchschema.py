"""One shared schema for the bench result JSON.

`bench.py` emits a headline result line (optionally superseded by an
enriched block-phase line), or a DEGRADED result when the internal
deadline fires; every outcome is also appended to `BENCH_history.jsonl`.
Three consumers must agree on that shape — the driver's parser, the
perf-regression observatory (`cmd/ftstop.py compare`), and the bench
rounds recorded as `BENCH_r*.json` — so the schema lives HERE, once,
and `tests/test_bench_schema.py` validates both the recorded rounds and
freshly built results against it. A round that fails this schema is a
bug in bench.py, not in the round.
"""

from __future__ import annotations

import json
from typing import List, Optional

METRIC_NAME = "zkatdlog_transfer_verify_throughput"
UNIT = "tx/s"

_NUM = (int, float)

# present in EVERY result (full, enriched, degraded)
HEADLINE_REQUIRED = {
    "metric": str,
    "value": _NUM,
    "unit": str,
    "vs_baseline": _NUM,
    "platform": str,
}

# present only in a full (non-degraded) result
FULL_REQUIRED = {
    "batch": int,
    "runs": int,
    "warmup_s": _NUM,
    "provegen_s": _NUM,
    "provegen_host_s": _NUM,
    "prove_txs": int,
    "prove_txs_per_s": _NUM,
    "prove_degraded": bool,
    "setup_s": _NUM,
    "stage_warmup_s": _NUM,
}

# present only in a degraded (deadline-fired) result
DEGRADED_REQUIRED = {
    "degraded": bool,
    "deadline_s": _NUM,
    "phase": str,
}

# type-checked when present; a tuple including NoneType allows null
_NULLABLE_NUM = _NUM + (type(None),)
OPTIONAL = {
    "prove_vs_host": _NULLABLE_NUM,
    "prove_txs_per_s": _NULLABLE_NUM,  # nullable in the degraded form
    "stage_warmup_s": _NUM,
    "block_txs_per_s": _NUM,
    "block_vs_baseline": _NUM,
    "block_txs": int,
    "block_batched_frac": _NUM,
    "block_provegen_s": _NUM,
    "wal_overhead_frac": _NUM,
    "scaling": list,  # throughput-vs-devices curve (validated per row)
    "soak": dict,  # sustained-load soak section (validated per field)
    "state": dict,  # state-plane scale section (validated per field)
    "profile": dict,  # host-path profiler section (validated per field)
    "slo": dict,  # error-budget section (validated per field)
    "device": dict,  # device-plane dispatch ledger (validated per field)
    "host": dict,  # batch-first host-validation section (per field)
    "failover": dict,  # kill-the-leader chaos-soak section (per field)
    "ts": _NUM,  # history-line stamp added by bench.append_history
}

# the sustained-load soak section (`soak` field): steady-state tx/s of
# the whole streaming engine under N concurrent clients, CLIENT-observed
# p99 finality (null when the run committed nothing), the queue-depth
# high-water (bounded by FTS_BENCH_SOAK_QUEUE_MAX admission control by
# construction), and how many submissions backpressure rejected
SOAK_REQUIRED = {
    "steady_txs_per_s": _NUM,
    "p99_finality_s": _NULLABLE_NUM,
    "queue_depth_max": _NUM,
    "backpressure_rejects": int,
}

# type-checked when present in a soak section (older rounds predate
# them, so they must stay OPTIONAL or the gate would drop its own
# baseline): which driver drove the corpus, what the batched signature
# plane actually DID ("device" = rows rode the device plane,
# "degraded" = enabled but every row fell back to host, "host" = off),
# the host_validate leg's fraction of block commit wall time, the
# batch.sign.* counter deltas, and the identity parse-cache hit rate
# over the soak window
SOAK_OPTIONAL = {
    "driver": str,
    "sign_plane": str,
    "host_validate_frac": _NULLABLE_NUM,
    "sign_rows": int,
    "sign_host": int,
    "sign_fallbacks": int,
    "identity_cache_hit_rate": _NULLABLE_NUM,
    # resilience accounting (rounds predating the chaos-soak mode omit
    # them): how many faults the chaos monkey landed
    # (`FTS_BENCH_SOAK_FAULTS=1`, else 0), how many times a circuit
    # breaker OPENED during the window, and how many device planes saw
    # at least one host fallback — the proof the node degraded AND
    # stayed live rather than stalling
    "faults_injected": int,
    "breaker_trips": int,
    "degraded_planes": int,
}


def validate_soak(soak) -> List[str]:
    """Schema problems of one `soak` section (empty list = valid)."""
    if not isinstance(soak, dict):
        return [f"soak is {type(soak).__name__}, expected object"]
    problems: List[str] = []
    _check(problems, soak, SOAK_REQUIRED, required=True)
    _check(problems, soak, SOAK_OPTIONAL, required=False)
    v = soak.get("steady_txs_per_s")
    if isinstance(v, _NUM) and not isinstance(v, bool) and v < 0:
        problems.append("soak.steady_txs_per_s is negative")
    return problems

# the batch-first host-validation section (`host` field, recorded by
# the soak phase and gated by `ftstop compare --host`): per-leg
# EXCLUSIVE seconds the sub-leg timers collected over the soak window
# (the scalar tail after the block-level batch passes), the host-leg
# fraction of block commit wall, and the batch-pass/cache counters that
# explain where the per-tx work went
HOST_REQUIRED = {
    "unmarshal_s": _NUM,
    "fiat_shamir_s": _NUM,
    "sig_verify_s": _NUM,
    "conservation_s": _NUM,
    "input_match_s": _NUM,
    "host_validate_frac": _NULLABLE_NUM,
}

HOST_OPTIONAL = {
    # per-block p99 of the named host legs over the window (null when
    # no block ran the leg)
    "unmarshal_p99_s": _NULLABLE_NUM,
    "fiat_shamir_p99_s": _NULLABLE_NUM,
    # wall spent inside the block-level batch passes (outside the legs)
    "sign_batch_s": _NUM,
    "proof_batch_s": _NUM,
    "conservation_batch_s": _NUM,
    # rows those passes decided (hostbatch.* counter deltas)
    "sign_batch_rows": int,
    "proof_batch_rows": int,
    "conservation_rows": int,
    # parse-cache effectiveness over the window (null when cold/disabled)
    "request_cache_hit_rate": _NULLABLE_NUM,
    "parse_cache_hit_rate": _NULLABLE_NUM,
    # resolved FTS_COMMIT_WORKERS pool size the window ran with
    "workers": int,
}


def validate_host(host) -> List[str]:
    """Schema problems of one `host` section (empty list = valid)."""
    if not isinstance(host, dict):
        return [f"host is {type(host).__name__}, expected object"]
    problems: List[str] = []
    _check(problems, host, HOST_REQUIRED, required=True)
    _check(problems, host, HOST_OPTIONAL, required=False)
    for key in HOST_REQUIRED:
        v = host.get(key)
        if isinstance(v, _NUM) and not isinstance(v, bool) and v < 0:
            problems.append(f"host.{key} is negative")
    for key in ("request_cache_hit_rate", "parse_cache_hit_rate",
                "host_validate_frac"):
        v = host.get(key)
        if isinstance(v, _NUM) and not isinstance(v, bool) and not (
            0 <= v <= 1
        ):
            problems.append(f"host.{key}={v} outside [0, 1]")
    return problems


# the kill-the-leader chaos-soak section (`failover` field, recorded by
# `FTS_BENCH_SOAK_FAILOVER=1` and gated by `ftstop compare --failover`):
# the replication contract as numbers — how many acknowledged txs the
# promoted node LOST (must be 0), how many tx ids committed twice across
# the switch (must be 0), the p99 client-observed submit stall across
# the failover window (null when no client observed one), and the
# maximum follower lag the window saw before the kill
FAILOVER_REQUIRED = {
    "acked_tx_loss": int,
    "duplicate_commits": int,
    "failover_p99_s": _NULLABLE_NUM,
    "follower_lag_max": _NUM,
}

# type-checked when present: forensics of the window — acked total,
# when the leader was killed (seconds into the window), the promoted
# node's epoch, how the promotion happened, and client failover switches
FAILOVER_OPTIONAL = {
    "acked_txs": int,
    "killed_at_s": _NUM,
    "promoted_epoch": int,
    "promotion": str,  # "auto" (lease watchdog) or "explicit" (RPC)
    "failover_switches": int,
    "stale_rejected": int,
}


def validate_failover(failover) -> List[str]:
    """Schema problems of one `failover` section (empty list = valid)."""
    if not isinstance(failover, dict):
        return [f"failover is {type(failover).__name__}, expected object"]
    problems: List[str] = []
    _check(problems, failover, FAILOVER_REQUIRED, required=True)
    _check(problems, failover, FAILOVER_OPTIONAL, required=False)
    for key in ("acked_tx_loss", "duplicate_commits", "follower_lag_max"):
        v = failover.get(key)
        if isinstance(v, _NUM) and not isinstance(v, bool) and v < 0:
            problems.append(f"failover.{key} is negative")
    return problems


# the state-plane scale section (`state` field, bench `state_scale`
# phase): synthetic token count populated into a persistent vault,
# populate/recover wall time + throughput, p99 selection latency under
# concurrent select+spend threads, and the RSS high-water the phase
# reached (sysmon) — the numbers `ftstop compare --state` gates
STATE_REQUIRED = {
    "tokens": int,
    "populate_s": _NUM,
    "populate_tokens_per_s": _NUM,
    "recover_s": _NUM,
    "recover_tokens_per_s": _NUM,
    "selector_p99_s": _NUM,
    "rss_high_water_mb": _NUM,
}

# type-checked when present in a state section. The calibration pair is
# measured by a PURE single-thread no-spend selection pass at both sizes
# (selection cost, not contention): `sublinear_ratio` =
# p99(pure, full size) / p99(pure, small size) — the recorded witness
# that indexed selection stays sub-linear in vault size, while
# `selector_p99_s` stays the concurrent select+spend headline.
STATE_OPTIONAL = {
    "selects": int,
    "spends": int,
    "threads": int,
    "selector_p99_small_s": _NULLABLE_NUM,  # pure p99 at the small size
    "small_tokens": int,
    "sublinear_ratio": _NULLABLE_NUM,  # pure p99(full) / pure p99(small)
}


def validate_state(state) -> List[str]:
    """Schema problems of one `state` section (empty list = valid)."""
    if not isinstance(state, dict):
        return [f"state is {type(state).__name__}, expected object"]
    problems: List[str] = []
    _check(problems, state, STATE_REQUIRED, required=True)
    _check(problems, state, STATE_OPTIONAL, required=False)
    for key in ("tokens", "selector_p99_s"):
        v = state.get(key)
        if isinstance(v, _NUM) and not isinstance(v, bool) and v < 0:
            problems.append(f"state.{key} is negative")
    return problems


# the host-path profiler section (`profile` field, recorded by the soak
# phase): sampling rate actually used (0 = sampler off), how many
# sampling passes ran, the per-leg seconds the sub-leg timers collected
# over the soak window ({leg: seconds}), what fraction of the host-leg
# wall time those named legs explain (null when no host leg ran), and
# the bounded collapsed-stack table ({"role;frame;frame": samples}) the
# `ftstrace flame` subcommand renders
PROFILE_REQUIRED = {
    "hz": _NUM,
    "samples": int,
    "host_legs": dict,
    "stacks": dict,
}

PROFILE_OPTIONAL = {
    "host_leg_coverage": _NULLABLE_NUM,
    "dropped_stacks": int,
}


def validate_profile(profile) -> List[str]:
    """Schema problems of one `profile` section (empty list = valid)."""
    if not isinstance(profile, dict):
        return [f"profile is {type(profile).__name__}, expected object"]
    problems: List[str] = []
    _check(problems, profile, PROFILE_REQUIRED, required=True)
    _check(problems, profile, PROFILE_OPTIONAL, required=False)
    legs = profile.get("host_legs")
    if isinstance(legs, dict):
        for k, v in legs.items():
            if isinstance(v, bool) or not isinstance(v, _NUM) or v < 0:
                problems.append(f"profile.host_legs[{k!r}] not a number >= 0")
    stacks = profile.get("stacks")
    if isinstance(stacks, dict):
        for k, v in stacks.items():
            if isinstance(v, bool) or not isinstance(v, int) or v <= 0:
                problems.append(f"profile.stacks[{k!r}] not a count > 0")
    cov = profile.get("host_leg_coverage")
    if isinstance(cov, _NUM) and not isinstance(cov, bool) and cov < 0:
        problems.append("profile.host_leg_coverage is negative")
    return problems


# the error-budget section (`slo` field, recorded by the soak phase and
# gated absolutely by `ftstop compare --slo`): the sliding window the
# engine evaluated over, and one row per SLO with its objective, burn
# rate ((1 - good_frac) / (1 - objective); >= 1 means the budget is
# exhausted), remaining budget fraction and verdict
SLO_ROW_REQUIRED = {
    "objective": _NUM,
    "burn": _NUM,
    "budget_remaining": _NUM,
    "total": int,
    "ok": bool,
}


def validate_slo(slo) -> List[str]:
    """Schema problems of one `slo` section (empty list = valid)."""
    if not isinstance(slo, dict):
        return [f"slo is {type(slo).__name__}, expected object"]
    problems: List[str] = []
    _check(problems, slo, {"window_s": _NUM, "slos": dict}, required=True)
    for name, row in (slo.get("slos") or {}).items():
        if not isinstance(row, dict):
            problems.append(f"slo.slos[{name!r}] is {type(row).__name__}")
            continue
        rp: List[str] = []
        _check(rp, row, SLO_ROW_REQUIRED, required=True)
        problems.extend(f"slo.slos[{name!r}]: {p}" for p in rp)
    return problems


# the device-plane dispatch ledger section (`device` field, recorded by
# the headline and soak phases from `utils/devobs.py.section()` and
# gated by `ftstop compare --device`): total dispatches, batch occupancy
# (rows / (rows + padding); null until something dispatched), padding
# waste fraction, dispatch wall-time quantiles, compile/cache forensics,
# and the per-plane / per-program breakdowns `ftstrace devices` renders
DEVICE_REQUIRED = {
    "dispatches": int,
    "occupancy": _NULLABLE_NUM,
    "waste_frac": _NULLABLE_NUM,
    "planes": dict,
    "programs": dict,
}

DEVICE_OPTIONAL = {
    "rows": int,
    "padded_rows": int,
    "dispatch_p50_s": _NULLABLE_NUM,
    "dispatch_p99_s": _NULLABLE_NUM,
    "compiles": int,
    "compile_s": _NUM,
    "cache_hits": int,
    "cache_misses": int,
    "degrades": int,
}

_DEVICE_PLANE_REQUIRED = {
    "dispatches": int,
    "rows": int,
    "padded_rows": int,
    "occupancy": _NULLABLE_NUM,
    "waste_frac": _NULLABLE_NUM,
}


def validate_device(device) -> List[str]:
    """Schema problems of one `device` section (empty list = valid)."""
    if not isinstance(device, dict):
        return [f"device is {type(device).__name__}, expected object"]
    problems: List[str] = []
    _check(problems, device, DEVICE_REQUIRED, required=True)
    _check(problems, device, DEVICE_OPTIONAL, required=False)
    for frac in ("occupancy", "waste_frac"):
        v = device.get(frac)
        if isinstance(v, _NUM) and not isinstance(v, bool) and not (
            0 <= v <= 1
        ):
            problems.append(f"device.{frac}={v} outside [0, 1]")
    for name, row in (device.get("planes") or {}).items():
        if not isinstance(row, dict):
            problems.append(f"device.planes[{name!r}] is {type(row).__name__}")
            continue
        rp: List[str] = []
        _check(rp, row, _DEVICE_PLANE_REQUIRED, required=True)
        problems.extend(f"device.planes[{name!r}]: {p}" for p in rp)
    for name, row in (device.get("programs") or {}).items():
        if not isinstance(row, dict):
            problems.append(
                f"device.programs[{name!r}] is {type(row).__name__}"
            )
    return problems


# one row of the throughput-vs-devices scaling curve (`scaling` field):
# `n_devices` is the dp x mp mesh extent the block phase ran under,
# `block_txs_per_s` its measured rate, `efficiency` the per-device
# speedup relative to the smallest mesh (rate_n * n_min / (n * rate_min))
SCALING_ROW_REQUIRED = {
    "n_devices": int,
    "block_txs_per_s": _NUM,
    "efficiency": _NUM,
}


def validate_scaling(curve) -> List[str]:
    """Schema problems of one `scaling` curve (empty list = valid): a
    non-empty list of rows, each carrying the required fields, with
    strictly increasing positive device counts."""
    if not isinstance(curve, list):
        return [f"scaling is {type(curve).__name__}, expected list"]
    problems: List[str] = []
    if not curve:
        problems.append("scaling curve is empty")
    prev = 0
    for i, row in enumerate(curve):
        if not isinstance(row, dict):
            problems.append(f"scaling[{i}] is {type(row).__name__}")
            continue
        _check(problems, row, SCALING_ROW_REQUIRED, required=True)
        n = row.get("n_devices")
        if isinstance(n, int) and not isinstance(n, bool):
            if n <= prev:
                problems.append(
                    f"scaling[{i}].n_devices={n} not strictly increasing"
                )
            prev = n
    return problems


def is_degraded(result: dict) -> bool:
    return bool(result.get("degraded"))


def _check(problems: List[str], result: dict, spec: dict,
           required: bool) -> None:
    for key, typ in spec.items():
        if key not in result:
            if required:
                problems.append(f"missing required field {key!r}")
            continue
        v = result[key]
        # bool is an int subclass: reject it where a number is expected
        if isinstance(v, bool) and typ is not bool and bool not in (
            typ if isinstance(typ, tuple) else (typ,)
        ):
            problems.append(f"field {key!r} is bool, expected {typ}")
        elif not isinstance(v, typ):
            problems.append(
                f"field {key!r} has type {type(v).__name__}, expected {typ}"
            )


def validate_result(result) -> List[str]:
    """Return every schema problem of one bench result dict (empty list
    = valid). Both the full and the degraded form are accepted; unknown
    extra fields are allowed (forward compatibility)."""
    if not isinstance(result, dict):
        return [f"result is {type(result).__name__}, expected object"]
    problems: List[str] = []
    _check(problems, result, HEADLINE_REQUIRED, required=True)
    if isinstance(result.get("metric"), str) and result["metric"] != METRIC_NAME:
        problems.append(
            f"metric is {result['metric']!r}, expected {METRIC_NAME!r}"
        )
    if isinstance(result.get("unit"), str) and result["unit"] != UNIT:
        problems.append(f"unit is {result['unit']!r}, expected {UNIT!r}")
    if isinstance(result.get("value"), _NUM) and not isinstance(
        result.get("value"), bool
    ) and result["value"] < 0:
        problems.append("value is negative")
    if is_degraded(result):
        _check(problems, result, DEGRADED_REQUIRED, required=True)
    else:
        _check(problems, result, FULL_REQUIRED, required=True)
    _check(problems, result, OPTIONAL, required=False)
    if isinstance(result.get("scaling"), list):
        problems.extend(validate_scaling(result["scaling"]))
    if isinstance(result.get("soak"), dict):
        problems.extend(validate_soak(result["soak"]))
    if isinstance(result.get("state"), dict):
        problems.extend(validate_state(result["state"]))
    if isinstance(result.get("profile"), dict):
        problems.extend(validate_profile(result["profile"]))
    if isinstance(result.get("slo"), dict):
        problems.extend(validate_slo(result["slo"]))
    if isinstance(result.get("device"), dict):
        problems.extend(validate_device(result["device"]))
    if isinstance(result.get("host"), dict):
        problems.extend(validate_host(result["host"]))
    if isinstance(result.get("failover"), dict):
        problems.extend(validate_failover(result["failover"]))
    return problems


def extract_result(doc) -> Optional[dict]:
    """Pull the result dict out of any bench artifact shape: a bare
    result, a history line, or a recorded round file (`BENCH_r*.json`,
    whose result lives under `parsed`). None when the artifact carries
    no parseable result (`parsed: null`)."""
    if not isinstance(doc, dict):
        return None
    if "metric" in doc:
        return doc
    if "parsed" in doc:
        p = doc["parsed"]
        return p if isinstance(p, dict) else None
    return None


def load_result(path: str) -> Optional[dict]:
    with open(path) as fh:
        return extract_result(json.load(fh))


def load_history(path: str) -> List[dict]:
    """Read a `BENCH_history.jsonl` observatory file: one JSON object
    per line, oldest first. Unparseable lines are skipped (a crash can
    tear the final line; history must still load)."""
    out: List[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # torn tail — same tolerance as the WAL
            if isinstance(row, dict):
                out.append(row)
    return out
