"""Host-path profiler: sub-leg timers + a sampling wall-clock profiler.

The soak plateaued with `host_validate_frac` ≈ 0.16 while the other ~84%
of each commit was per-tx Python the telemetry plane could not attribute
— `ledger.block.host_validate.seconds` was one opaque leg. This module
is the attribution layer, two instruments sharing one goal (find the
guilty milliseconds inside the per-tx host tail):

**Sub-leg timers** (`leg(name)`): explicit, always-on decomposition of
`_commit_block_inner`'s host-validate loop into named histograms
`ledger.host.<name>.seconds` for the legs

    unmarshal      request decode + canonical re-marshalling
                   (`TokenRequest.from_bytes`, `marshal_to_sign/audit`)
    fiat_shamir    host zk proof verification (zkatdlog transfer/issue
                   verifiers — the non-interactive challenge re-derivation)
    sig_verify     host signature checks (`identity.verify_signature`:
                   Schnorr pk, nym, htlc dispatch)
    conservation   fabtoken token parse + type/sum conservation checks
    input_match    input id decode, ledger resolve, action/record
                   consistency checks

Legs are attributed EXCLUSIVELY: a `leg` nested inside another bills the
inner leg only (the outer leg's self-time excludes it), so the sum of
legs never double-counts. Timing runs ONLY while a collector is active
(`collect()`, entered by the ledger around the host-validate loop);
everywhere else — client-side marshalling, wallet flows — `leg()` is a
zero-cost passthrough (one thread-local lookup), so the
`ledger.host.*` histograms see commit-path samples exclusively and the
off-path overhead is nil. Collected per-leg seconds ride the block's
critical-path breakdown (`host_<leg>_s`), the `block.commit` flight
event, and `ops.health`'s last-block line; cumulative totals
(`leg_totals()`) let bench compute what fraction of the host leg the
named sub-legs explain.

**Sampling profiler** (`SamplingProfiler`): a daemon thread walking
`sys._current_frames()` at `FTS_PROF_HZ` (default 0 = off — zero
threads, zero overhead), aggregating collapsed stacks per thread ROLE:

    commit-worker   the pipelined engine's stage-B thread
    stage-a-driver  whoever is driving cut + device verify
    remote-handler  per-connection server threads (remote.py)
    client          soak/bench submitter threads
    other           everything else (main thread included)

Roles resolve from an explicit registration (`set_thread_role`), then
the thread name, then a stack heuristic. The stack table is bounded by
`FTS_PROF_MAX_STACKS` (new stacks beyond the cap are dropped and
counted under `prof.dropped` — sampling must never grow unbounded).
`collapsed()` returns flamegraph-ready collapsed text
(`role;frame;frame count`), exported by `ftstrace flame` and the
`profile` section of the soak result JSON. Observability of the
observer: `prof.samples` counts sampling passes, `prof.stacks` gauges
the live table size.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import Dict, Optional

from . import metrics as mx

# the named sub-legs of the commit host-validate loop (breakdown key
# order — `host_<leg>_s` in the block critical-path breakdown)
LEGS = ("unmarshal", "fiat_shamir", "sig_verify", "conservation",
        "input_match")

_tl = threading.local()

# cumulative per-leg seconds collected across every `collect()` window
# of the process — the denominator-free totals bench diffs around a soak
# to compute host-leg coverage
_totals: Dict[str, float] = {}
_totals_lock = threading.Lock()


@contextlib.contextmanager
def leg(name: str):
    """Time one named sub-leg of the host-validate path.

    Active only under a `collect()` window on the same thread (the
    ledger's host-validate loop); anywhere else this is a zero-cost
    passthrough. Nested legs bill exclusively: the outer leg's recorded
    time excludes the inner leg's wall time."""
    stack = getattr(_tl, "stack", None)
    if stack is None:
        yield
        return
    frame = [name, time.monotonic(), 0.0]  # [name, t0, child_wall_s]
    stack.append(frame)
    try:
        yield
    finally:
        now = time.monotonic()
        stack.pop()
        wall = now - frame[1]
        self_s = max(0.0, wall - frame[2])
        if stack:
            stack[-1][2] += wall  # parent excludes this leg's wall time
        mx.histogram(f"ledger.host.{name}.seconds").observe(self_s)
        col = _tl.collector
        col[name] = col.get(name, 0.0) + self_s
        with _totals_lock:
            _totals[name] = _totals.get(name, 0.0) + self_s


@contextlib.contextmanager
def collect():
    """Activate sub-leg collection on this thread; yields the dict the
    window's per-leg seconds accumulate into ({leg: seconds}). Entered
    by `_commit_block_inner` around the per-tx host-validate loop (a
    single-threaded loop, so thread-local state is exact)."""
    prev_stack = getattr(_tl, "stack", None)
    prev_col = getattr(_tl, "collector", None)
    out: Dict[str, float] = {}
    _tl.stack = []
    _tl.collector = out
    try:
        yield out
    finally:
        _tl.stack = prev_stack
        _tl.collector = prev_col


def leg_totals() -> Dict[str, float]:
    """Cumulative per-leg seconds collected so far (process lifetime,
    collector windows only) — diff two copies around a measured window."""
    with _totals_lock:
        return dict(_totals)


# ------------------------------------------------------------ thread roles

_roles: Dict[int, str] = {}
_roles_lock = threading.Lock()

# thread-name prefixes -> role (the commit worker and bench clients are
# named at spawn; registration beats this map when both apply)
_NAME_ROLES = (
    ("fts-block-commit", "commit-worker"),
    ("fts-commit-host", "commit-worker"),
    ("fts-soak-client", "client"),
)

# sampler-internal threads that must never appear in their own profile
_SKIP_NAMES = ("fts-prof", "fts-heartbeat")


def set_thread_role(role: str) -> None:
    """Register the CALLING thread's profile role (commit worker, remote
    handler, client). Bounded implicitly: one entry per live thread id,
    overwritten on reuse."""
    with _roles_lock:
        _roles[threading.get_ident()] = role


def _classify(ident: int, name: str, frames) -> str:
    with _roles_lock:
        role = _roles.get(ident)
    if role:
        return role
    for prefix, r in _NAME_ROLES:
        if name.startswith(prefix):
            return r
    for filename, func in frames:
        if filename.endswith("remote.py"):
            return "remote-handler"
        if filename.endswith("pipeline.py") and func == "submit":
            return "stage-a-driver"
        if filename.endswith("orderer.py") and func in ("drive", "flush"):
            return "stage-a-driver"
    return "other"


# ------------------------------------------------------------ sampler


class SamplingProfiler:
    """Wall-clock sampling profiler over `sys._current_frames()`.

    `hz` <= 0 means OFF: `start()` spawns nothing and the process runs
    with zero profiler threads (the zero-cost-when-off contract the
    tests pin). The stack table is bounded at `max_stacks` distinct
    collapsed stacks; beyond the cap new stacks are dropped (counted,
    never grown) so a pathological workload cannot balloon memory."""

    def __init__(self, hz: Optional[float] = None,
                 max_stacks: Optional[int] = None,
                 max_depth: int = 48):
        if hz is None:
            try:
                hz = float(os.environ.get("FTS_PROF_HZ", "0"))
            except ValueError:
                hz = 0.0
        if max_stacks is None:
            try:
                max_stacks = int(os.environ.get("FTS_PROF_MAX_STACKS", "2000"))
            except ValueError:
                max_stacks = 2000
        self.hz = hz
        self.max_stacks = max(1, max_stacks)
        self.max_depth = max(1, max_depth)
        self.samples = 0
        self.dropped = 0
        self._stacks: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self.hz <= 0 or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fts-prof", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> "SamplingProfiler":
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
        return self

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self.sample()
            except Exception:
                # the observer must never take the process down
                mx.counter("prof.errors").inc()

    # -- sampling -----------------------------------------------------

    def sample(self) -> None:
        """One sampling pass over every live thread (public so tests can
        drive it deterministically without the daemon thread)."""
        me = threading.get_ident()
        names = {t.ident: t.name or "" for t in threading.enumerate()}
        for ident, top in sys._current_frames().items():
            if ident == me:
                continue
            name = names.get(ident, "")
            if name.startswith(_SKIP_NAMES):
                continue
            frames = []
            f = top
            while f is not None and len(frames) < self.max_depth:
                code = f.f_code
                frames.append((code.co_filename, code.co_name))
                f = f.f_back
            frames.reverse()  # root first, flamegraph order
            role = _classify(ident, name, frames)
            key = role + ";" + ";".join(
                "%s:%s" % (os.path.basename(fn).rsplit(".", 1)[0], func)
                for fn, func in frames
            )
            with self._lock:
                if key in self._stacks:
                    self._stacks[key] += 1
                elif len(self._stacks) < self.max_stacks:
                    self._stacks[key] = 1
                else:
                    self.dropped += 1
                    mx.counter("prof.dropped").inc()
        self.samples += 1
        mx.counter("prof.samples").inc()
        mx.gauge("prof.stacks").set(len(self._stacks))

    # -- export -------------------------------------------------------

    def collapsed(self, role: Optional[str] = None) -> Dict[str, int]:
        """{collapsed stack: sample count}; `role` filters to one thread
        role. Keys are `role;frame;frame` with root-first frames —
        `"\\n".join(f"{k} {v}")` is flamegraph.pl input."""
        with self._lock:
            items = dict(self._stacks)
        if role is not None:
            prefix = role + ";"
            items = {k: v for k, v in items.items() if k.startswith(prefix)}
        return items

    def stack_count(self) -> int:
        with self._lock:
            return len(self._stacks)


# process-wide sampler managed by bench (started around the soak window)
_active: Optional[SamplingProfiler] = None
_active_lock = threading.Lock()


def start(hz: Optional[float] = None,
          max_stacks: Optional[int] = None) -> Optional[SamplingProfiler]:
    """Start the process-wide sampler (idempotent). Returns None when
    the resolved rate is <= 0 — off means zero threads."""
    global _active
    with _active_lock:
        if _active is not None and _active.running():
            return _active
        p = SamplingProfiler(hz=hz, max_stacks=max_stacks)
        if p.hz <= 0:
            return None
        _active = p.start()
        return _active


def stop() -> Optional[SamplingProfiler]:
    """Stop the process-wide sampler; returns it (with its samples) or
    None if never started."""
    global _active
    with _active_lock:
        p = _active
        _active = None
    if p is not None:
        p.stop()
    return p


def active() -> Optional[SamplingProfiler]:
    return _active
