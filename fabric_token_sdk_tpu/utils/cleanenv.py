"""The one recipe for a local-CPU child/exec environment.

The ambient environment can pin JAX onto the remote-TPU axon platform
(a sitecustomize under ``.axon_site`` triggered by
``PALLAS_AXON_POOL_IPS``) whose PJRT client hangs every jax call when
the tunnel is down. Every re-exec / clean-subprocess fallback —
``bench.py``'s CPU re-exec, ``__graft_entry__.neutralize_axon``, and
``dryrun_multichip``'s probe delegation — must scrub the SAME three
things; keeping the recipe here means the next variable that needs
scrubbing is added once, not per call site. Stdlib-only on purpose:
callers run before jax (or any heavy import) comes up.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional


def clean_cpu_env(base: Optional[Mapping[str, str]] = None) -> dict:
    """A copy of the environment pinned to local CPU: the axon trigger
    removed, ``JAX_PLATFORMS=cpu``, and ``.axon_site`` stripped from
    ``PYTHONPATH``. Callers layer their own markers (``_FTS_*_REEXEC``,
    deadlines) on top."""
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":")
        if ".axon_site" not in p
    )
    return env
