"""Process + accelerator memory telemetry for the live ops plane.

Post-mortem sidecars answer "where did the time go"; this module answers
"how much memory is this node holding" — live, cheaply, and without ever
perturbing the data plane:

* **Host**: current RSS from ``/proc/self/statm`` (peak from
  ``getrusage``), exported as the ``proc.rss.bytes`` /
  ``proc.rss.peak.bytes`` gauges.
* **Device**: per-device allocator stats via ``Device.memory_stats()``
  where the backend reports them (TPU/GPU), falling back to the summed
  byte size of live ``jax`` arrays (the CPU backend has no allocator
  report). Exported as ``device.mem.bytes`` / ``device.mem.peak.bytes``.
* **Safety invariant**: sampling NEVER triggers jax import or backend
  initialization — the bench heartbeat samples during the
  ``platform_probe`` phase, where touching an uninitialized axon backend
  would hang the process. If jax is absent or no backend is initialized
  the device reading is simply ``None``.
* **Stage-runner high-water** (``sample_stages``): the batched
  verify/prove planes call this from ``ops/stages.run_rows`` after every
  tile dispatch; it is throttled to one real sample per
  ``FTS_MEM_SAMPLE_S`` (default 0.5s) and keeps the ``stages.mem.*``
  high-water gauges — the peak device/host footprint the data plane
  reached, which is what capacity planning needs from a bench round.

Zero XLA programs are ever compiled by sampling (reading live-array
sizes and allocator stats is pure bookkeeping), so the post-warmup
zero-cache-miss guarantee is untouched.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

from . import metrics as mx

try:
    _PAGE = os.sysconf("SC_PAGE_SIZE")
except (AttributeError, ValueError, OSError):
    _PAGE = 4096


def host_rss_bytes() -> int:
    """Current resident set size in bytes (0 if unreadable)."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return host_rss_peak_bytes()  # non-/proc platforms: peak is all we have


def host_rss_peak_bytes() -> int:
    """Peak RSS in bytes (``ru_maxrss`` is KiB on Linux)."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def device_memory_bytes() -> Optional[int]:
    """Device-resident bytes across every device of the initialized jax
    backend(s), or None when jax is absent / no backend is initialized.

    NEVER initializes a backend: probing must stay safe while the
    platform guard is still deciding whether the axon tunnel is alive.
    """
    if "jax" not in sys.modules:
        return None
    try:
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return None  # nothing initialized yet — do not trigger it
        import jax

        total, reported = 0, False
        for dev in jax.devices():
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                reported = True
        if reported:
            return total
        # CPU (and any backend without an allocator report): the live
        # committed arrays are the device-resident set
        return sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays())
    except Exception:
        return None


def sample() -> dict:
    """Take one memory sample and publish the process-wide gauges
    (`proc.rss.bytes`, `proc.rss.peak.bytes`, `device.mem.bytes`,
    `device.mem.peak.bytes`). Returns the raw readings."""
    rss = host_rss_bytes()
    peak = host_rss_peak_bytes()
    mx.gauge("proc.rss.bytes").set(rss)
    if peak:
        mx.gauge("proc.rss.peak.bytes").set(peak)
    out = {"rss_bytes": rss, "rss_peak_bytes": peak}
    dev = device_memory_bytes()
    out["device_bytes"] = dev
    if dev is not None:
        mx.gauge("device.mem.bytes").set(dev)
        g = mx.gauge("device.mem.peak.bytes")
        if dev > g.value:
            g.set(dev)
    return out


def _min_interval_s() -> float:
    try:
        return float(os.environ.get("FTS_MEM_SAMPLE_S", "0.5"))
    except ValueError:
        return 0.5


_lock = threading.Lock()
_last_stage_sample = 0.0


def sample_stages() -> Optional[dict]:
    """Throttled sampling hook for the stage-runner hot path: at most one
    real sample per `FTS_MEM_SAMPLE_S`, maintaining the `stages.mem.*`
    high-water gauges (peak device/host footprint of the batched
    verify/prove planes). Returns the sample, or None when throttled."""
    global _last_stage_sample
    now = time.monotonic()
    interval = _min_interval_s()
    with _lock:
        if now - _last_stage_sample < interval:
            return None
        _last_stage_sample = now
    s = sample()
    dev = s.get("device_bytes")
    if dev is not None:
        mx.gauge("stages.mem.device.bytes").set(dev)
        hw = mx.gauge("stages.mem.high_water.bytes")
        if dev > hw.value:
            hw.set(dev)
    rss_hw = mx.gauge("stages.mem.rss_high_water.bytes")
    if s["rss_bytes"] > rss_hw.value:
        rss_hw.set(s["rss_bytes"])
    return s
