"""Shared persistent-XLA-compilation-cache bootstrap.

The limb-tensor programs are compile-heavy (minutes each on a small CPU
host); every entry point (test suite, bench, graft entry) funnels through
`enable()` BEFORE importing jax so they all share one content-addressed
cache directory. Safe across concurrent processes.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def enable(root: str | None = None) -> str:
    """Point JAX at the shared on-disk compilation cache (idempotent)."""
    cache = os.path.join(root or _REPO_ROOT, ".jax_cache")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    return os.environ["JAX_COMPILATION_CACHE_DIR"]
