"""Batched BN254 G1 group ops on limb tensors (Jacobian, branch-free).

A batch of points is one int32 tensor of shape (..., 3, NLIMBS): Jacobian
(X, Y, Z) in Montgomery form, Z == 0 encoding infinity. All formulas are
select-based (no data-dependent branches) so they vmap/jit/shard cleanly —
the TPU-first counterpart of gnark's per-point assembly used by the
reference via IBM mathlib (`*math.G1`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import limbs as lb
from .field import FP, FR
from ..crypto import hostmath as hm


def infinity(shape=()) -> jnp.ndarray:
    """Batch of points at infinity."""
    return jnp.zeros(tuple(shape) + (3, lb.NLIMBS), dtype=jnp.int32)


def is_infinity(p):
    return FP.is_zero(p[..., 2, :])


def neg(p):
    return jnp.stack(
        [p[..., 0, :], FP.neg(p[..., 1, :]), p[..., 2, :]], axis=-2
    )


@jax.jit
def double(p):
    """dbl-2009-l (a=0): branch-free; Z=0 and Y=0 fall out naturally."""
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = FP.sqr(x)
    b = FP.sqr(y)
    c = FP.sqr(b)
    d = FP.sub(FP.sqr(FP.add(x, b)), FP.add(a, c))
    d = FP.add(d, d)
    e = FP.add(FP.add(a, a), a)
    f = FP.sqr(e)
    x3 = FP.sub(f, FP.add(d, d))
    c8 = FP.add(c, c)
    c8 = FP.add(c8, c8)
    c8 = FP.add(c8, c8)
    y3 = FP.sub(FP.mul(e, FP.sub(d, x3)), c8)
    z3 = FP.mul(FP.add(y, y), z)
    return jnp.stack([x3, y3, z3], axis=-2)


@jax.jit
def add(p, q):
    """General Jacobian addition (add-2007-bl) with select-based edge cases:
    either operand at infinity, P == Q (doubling), P == -Q (infinity)."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    x2, y2, z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    z1z1 = FP.sqr(z1)
    z2z2 = FP.sqr(z2)
    u1 = FP.mul(x1, z2z2)
    u2 = FP.mul(x2, z1z1)
    s1 = FP.mul(FP.mul(y1, z2), z2z2)
    s2 = FP.mul(FP.mul(y2, z1), z1z1)
    h = FP.sub(u2, u1)
    i = FP.sqr(FP.add(h, h))
    j = FP.mul(h, i)
    rr = FP.sub(s2, s1)
    rr = FP.add(rr, rr)
    v = FP.mul(u1, i)
    x3 = FP.sub(FP.sqr(rr), FP.add(j, FP.add(v, v)))
    s1j = FP.mul(s1, j)
    y3 = FP.sub(FP.mul(rr, FP.sub(v, x3)), FP.add(s1j, s1j))
    z3 = FP.mul(FP.sub(FP.sqr(FP.add(z1, z2)), FP.add(z1z1, z2z2)), h)
    out = jnp.stack([x3, y3, z3], axis=-2)

    same_x = FP.is_zero(h)
    same_y = FP.is_zero(rr)
    inf1 = FP.is_zero(z1)
    inf2 = FP.is_zero(z2)
    # P == Q (and neither infinite): use the doubling formula
    out = jnp.where((same_x & same_y & ~inf1 & ~inf2)[..., None, None], double(p), out)
    # P == -Q: infinity (out.Z is already 0 since h == 0 => z3 == 0, but X/Y
    # are garbage; zero the whole point for canonical equality)
    out = jnp.where(
        (same_x & ~same_y & ~inf1 & ~inf2)[..., None, None], jnp.zeros_like(out), out
    )
    out = jnp.where(inf1[..., None, None], q, out)
    out = jnp.where(inf2[..., None, None], p, out)
    return out


@jax.jit
def eq(p, q):
    """Equality in Jacobian coordinates (cross-multiplied, batch-wise)."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    x2, y2, z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    z1z1 = FP.sqr(z1)
    z2z2 = FP.sqr(z2)
    xe = FP.eq(FP.mul(x1, z2z2), FP.mul(x2, z1z1))
    ye = FP.eq(FP.mul(FP.mul(y1, z2), z2z2), FP.mul(FP.mul(y2, z1), z1z1))
    inf1 = FP.is_zero(z1)
    inf2 = FP.is_zero(z2)
    return jnp.where(inf1 | inf2, inf1 == inf2, xe & ye)


def scalar_bits(k_canon, nbits: int = 256):
    """Canonical (non-Montgomery) limb scalars (..., NLIMBS) -> bits
    (..., nbits), most significant first."""
    shifts = jnp.arange(lb.RADIX_BITS, dtype=jnp.int32)
    bits = (k_canon[..., :, None] >> shifts[None, :]) & 1  # (..., NLIMBS, 8) LSB-first
    flat = bits.reshape(bits.shape[:-2] + (lb.NLIMBS * lb.RADIX_BITS,))
    return flat[..., :nbits][..., ::-1]  # MSB first


@jax.jit
def scalar_mul(p, k_canon):
    """Batched double-and-add: (..., 3, L) x (..., L) -> (..., 3, L).

    k_canon is a canonical (non-Montgomery) limb scalar. 256 scan steps.
    """
    bits = scalar_bits(k_canon)  # (..., 256) MSB first
    bits_t = jnp.moveaxis(bits, -1, 0)  # (256, ...)

    def step(acc, bit):
        acc = double(acc)
        acc = jnp.where(bit[..., None, None] > 0, add(acc, p), acc)
        return acc, None

    out, _ = lax.scan(step, infinity(p.shape[:-2]), bits_t)
    return out


def tree_sum(points, axis: int = -3):
    """Sum a batch of points along `axis` via log-depth pairwise adds."""
    points = jnp.moveaxis(points, axis, 0)
    n = points.shape[0]
    while n > 1:
        half = n // 2
        odd = points[2 * half :]  # 0 or 1 leftover
        points = add(points[:half], points[half : 2 * half])
        if odd.shape[0]:
            points = jnp.concatenate([points, odd], axis=0)
        n = points.shape[0]
    return points[0]


# ---------------------------------------------------------------- host I/O

def encode_point(pt) -> np.ndarray:
    """Host affine (x, y) or None -> (3, NLIMBS) Montgomery Jacobian."""
    if pt is None:
        return np.zeros((3, lb.NLIMBS), dtype=np.int32)
    R = 1 << (lb.RADIX_BITS * lb.NLIMBS)
    x, y = pt
    return np.stack(
        [
            lb.int_to_limbs(x * R % hm.P),
            lb.int_to_limbs(y * R % hm.P),
            lb.int_to_limbs(R % hm.P),
        ]
    )


def encode_points(pts) -> jnp.ndarray:
    return jnp.asarray(np.stack([encode_point(p) for p in pts]))


_RINV = None  # lazily: R^-1 mod p for host Montgomery decode


def decode_points(arr):
    """Device (..., 3, NLIMBS) -> host affine tuples.

    Pure host arithmetic — Montgomery conversion is one modular multiply
    by R^-1 per coordinate, inversion via Fermat on python ints — so
    decoding compiles no device program (the batched verifiers' XLA
    program set stays independent of batch/statement shape)."""
    global _RINV
    if _RINV is None:
        _RINV = pow(1 << (lb.RADIX_BITS * lb.NLIMBS), -1, hm.P)
    flat = np.asarray(arr).reshape(-1, 3, lb.NLIMBS)
    out = []
    for row in flat:
        x, y, z = (lb.limbs_to_int(c) * _RINV % hm.P for c in row)
        if z == 0:
            out.append(None)
            continue
        zinv = hm.fp_inv(z)
        zi2 = zinv * zinv % hm.P
        out.append((x * zi2 % hm.P, y * zi2 % hm.P * zinv % hm.P))
    return out


def decode_point(arr):
    return decode_points(arr[None])[0]


def encode_scalars(ks) -> np.ndarray:
    """Host ints -> canonical limb scalars (N, NLIMBS).

    Returns numpy (host data): batch-assembly loops stack many of these
    before one device transfer; jit'd consumers convert implicitly.
    """
    return lb.ints_to_limbs([k % hm.R for k in ks])


# ---------------------------------------------------------------- fixed base

WINDOW_BITS = 4
DIGITS_PER_SCALAR = 256 // WINDOW_BITS  # 64


class FixedBaseTable:
    """Windowed multiples of a list of fixed bases for batched multiexp.

    Table[b, w, d] = base_b * (d << (4w)), shape (nbases, 64, 16, 3, L).
    A multiexp is then: one-hot digit selection (a dense matmul riding the
    MXU) followed by a log-depth tree of point additions.

    Used for the Pedersen-parameter bases (reference: PedParams/PedGen in
    setup.go) — the hottest multiexp in issue/transfer proving and
    verification.
    """

    def __init__(self, host_points):
        self.nbases = len(host_points)
        tables = np.zeros(
            (self.nbases, DIGITS_PER_SCALAR, 1 << WINDOW_BITS, 3, lb.NLIMBS),
            dtype=np.int32,
        )
        for b, pt in enumerate(host_points):
            for w in range(DIGITS_PER_SCALAR):
                step = hm.g1_mul(pt, (1 << (WINDOW_BITS * w)) % hm.R)
                acc = None
                for d in range(1 << WINDOW_BITS):
                    tables[b, w, d] = encode_point(acc)
                    acc = hm.g1_add(acc, step)
        # flatten for the one-hot contraction: (nbases*64, 16, 3*L)
        self.flat = jnp.asarray(
            tables.reshape(self.nbases * DIGITS_PER_SCALAR, 1 << WINDOW_BITS, 3 * lb.NLIMBS)
        )

    def msm(self, scalars):
        """scalars: canonical limb tensor (..., nbases, NLIMBS) ->
        points (..., 3, NLIMBS) = sum_b scalar_b * base_b."""
        return msm_flat(self.flat, scalars)


def msm_select(flat, scalars):
    """Window-digit point selection shared by every msm reduction:
    scalars (..., nbases, NLIMBS) x table (nbases*64, 16, 3L) ->
    selected window points (..., nbases*64, 3, NLIMBS). The one-hot
    digit contraction is a dense matmul that rides the MXU."""
    nbases = flat.shape[0] // DIGITS_PER_SCALAR
    shifts = jnp.arange(0, lb.RADIX_BITS, WINDOW_BITS, dtype=jnp.int32)
    digs = (scalars[..., :, :, None] >> shifts) & ((1 << WINDOW_BITS) - 1)
    # (..., nbases, NLIMBS * 2) -> (..., nbases*64)
    digs = digs.reshape(digs.shape[:-3] + (nbases * DIGITS_PER_SCALAR,))
    onehot = (digs[..., None] == jnp.arange(1 << WINDOW_BITS, dtype=jnp.int32)).astype(
        jnp.int32
    )  # (..., nbases*64, 16)
    sel = jnp.einsum("...td,tdc->...tc", onehot, flat)
    return sel.reshape(sel.shape[:-1] + (3, lb.NLIMBS))


@jax.jit
def msm_flat(flat, scalars):
    """Fixed-base windowed multiexp against a table passed as an ARGUMENT
    (not a baked constant), so the compiled program is shared across all
    parameter sets — callers with different Pedersen bases / public keys
    reuse one XLA executable per shape."""
    return tree_sum(msm_select(flat, scalars), axis=-3)


@functools.lru_cache(maxsize=8)
def generator_table(n: int = 1) -> FixedBaseTable:
    return FixedBaseTable([hm.G1_GEN] * n)
