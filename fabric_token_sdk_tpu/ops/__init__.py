"""TPU compute path: limb-tensor bigint, prime fields, curves, pairing.

Design (TPU-first, not a port — reference delegates to gnark's x86-64
assembly; we target the VPU/MXU instead):

* Field elements are tensors of 32 radix-2^8 limbs in ``int32``
  (little-endian limb order), batched over leading axes. 8-bit limbs keep
  every partial product and column sum inside int32 — no 64-bit emulation —
  and map onto TPU-native integer lanes.
* Multiplication is Montgomery (REDC, R = 2^256) built from branch-free
  column convolutions; carries use signed arithmetic-shift passes under
  ``lax.while_loop``.
* Group ops are batched Jacobian formulas with select-based (branch-free)
  edge handling; scalar multiplication is a ``lax.scan`` over bits.
* Hot multiexps use fixed-base window tables contracted with one-hot digit
  matrices — dense matmuls that ride the MXU.
"""

import os as _os

import jax as _jax


# ------------------------------------------------------- cache host keying
#
# XLA AOT cache entries bake in the compiling host's CPU features; loading
# an entry produced on a different machine triggers cpu_aot_loader
# machine-feature-mismatch warnings ("could lead to SIGILL") and, worse,
# can crash mid-kernel (the BENCH_r05 rc=124). The persistent cache dir is
# therefore HOST-KEYED: the first process writes a HOST_FINGERPRINT marker
# (platform + codegen-relevant CPU flags); any later process whose
# fingerprint differs is diverted to a per-host subdirectory, so foreign
# AOT entries are NEVER loaded. Diversions count the entries they skipped
# under `jax.cache.foreign_skipped`. Opt out: FTS_CACHE_FINGERPRINT=0.

_FINGERPRINT_MARKER = "HOST_FINGERPRINT"

# CPU-feature flags that change XLA:CPU codegen (vector ISA + carryless
# mul/AES used by some kernels); hypervisor/power-management flags are
# deliberately excluded so equivalent VMs of one fleet share a cache.
_CODEGEN_FLAG_PREFIXES = (
    "sse", "ssse", "avx", "fma", "bmi", "f16c", "aes", "pclmul",
    "popcnt", "movbe", "adx", "sha", "vaes", "gfni", "amx",
)


def host_fingerprint() -> str:
    """Stable fingerprint of this host's codegen-relevant CPU surface."""
    import hashlib
    import platform

    parts = [platform.machine(), platform.system()]
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.lower().startswith(("flags", "features")):
                    feats = sorted(
                        f for f in line.split(":", 1)[1].split()
                        if f.startswith(_CODEGEN_FLAG_PREFIXES)
                    )
                    parts.append(" ".join(feats))
                    break
    except OSError:  # non-Linux: machine/system only
        pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def _resolve_cache_dir(base: str, fingerprint: str) -> str:
    """Claim `base` for this host, or divert to a host-keyed subdir.

    * no marker: write one — this host owns the cache from now on;
    * marker matches: reuse the (warm) cache;
    * marker differs: the cache was populated on a FOREIGN host — count
      its entries under `jax.cache.foreign_skipped` and use
      `base/host-<fingerprint>` instead, so no mismatched AOT entry is
      ever handed to the loader.
    """
    from ..utils import metrics as _mx

    marker = _os.path.join(base, _FINGERPRINT_MARKER)
    try:
        _os.makedirs(base, exist_ok=True)
        try:
            # O_EXCL claim: exactly ONE host ever wins an unclaimed dir —
            # a lost race falls through to reading the winner's marker,
            # so two first-run hosts on a shared FS can never both write
            # AOT entries into the same dir
            fd = _os.open(marker, _os.O_WRONLY | _os.O_CREAT | _os.O_EXCL)
            with _os.fdopen(fd, "w") as fh:
                fh.write(fingerprint + "\n")
            return base
        except FileExistsError:
            pass
        with open(marker) as fh:
            recorded = fh.read().strip()
        if not recorded:
            # torn claim (a claimant died between O_EXCL create and
            # write): repair it, otherwise host-keying would be silently
            # disabled forever — the exact mixed-host hazard this guards
            with open(marker, "w") as fh:
                fh.write(fingerprint + "\n")
            return base
        if recorded != fingerprint:
            # count real AOT entries only (each program has a `-cache`
            # payload file; `-atime` companions and stray files would
            # double the number) — fall back to every file when the
            # naming convention is absent
            names = [
                n
                for n in _os.listdir(base)
                if n != _FINGERPRINT_MARKER
                and _os.path.isfile(_os.path.join(base, n))
            ]
            entries = [n for n in names if n.endswith("-cache")] or names
            _mx.REGISTRY.counter("jax.cache.foreign_skipped").inc(len(entries))
            _mx.REGISTRY.set_meta(
                "jax.cache.foreign_host", f"{recorded}!={fingerprint}"
            )
            sub = _os.path.join(base, f"host-{fingerprint}")
            _os.makedirs(sub, exist_ok=True)
            return sub
    except OSError:
        # unwritable/unreadable cache dir: let jax handle (or reject) it
        pass
    return base


# Persistent compilation cache: the pairing/Miller programs are large and
# XLA (esp. :CPU) compiles them slowly; cache them across processes.
_cache_dir = _os.environ.get(
    "FTS_TPU_JAX_CACHE", _os.path.expanduser("~/.cache/fts_tpu_jax")
)
if _os.environ.get("FTS_CACHE_FINGERPRINT", "1") != "0":
    _cache_dir = _resolve_cache_dir(_cache_dir, host_fingerprint())
try:
    _jax.config.update("jax_compilation_cache_dir", _cache_dir)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
except Exception:  # older jax without the knobs
    pass


# ---------------------------------------------------------- observability
#
# Compile/cache instrumentation: every XLA compile and persistent-cache
# hit/miss/load-failure lands in the metrics registry. This is the signal
# that diagnoses a silent rc=124 (unbounded recompiles after cache-load
# failures) in one read of the sidecar:
#   jax.core.compile.backend_compile_duration.seconds  — per-program wall
#     time histogram; its `count` IS the distinct-compiled-program count
#   jax.compilation_cache.cache_hits / cache_misses    — persistent cache
#   jax.cache.load_failures                            — AOT entries that
#     exist but refuse to load (e.g. cpu_aot_loader machine mismatch)


def _install_jax_monitoring() -> None:
    from ..utils import devobs as _devobs
    from ..utils import metrics as _mx

    def _event_name(raw: str) -> str:
        return "jax." + raw.strip("/").replace("/", ".").removeprefix("jax.")

    try:
        from jax import monitoring as _mon

        def _on_event(name, **kw):
            _mx.REGISTRY.counter(_event_name(name)).inc()
            # cache traffic is a lifecycle event: a run that suddenly
            # starts MISSING the persistent cache shows up in the flight
            # ring right next to the phase that triggered it
            if "compilation_cache" in name:
                ev = _event_name(name)
                _mx.flight("cache", event=ev)
                # listeners fire synchronously on the compiling thread,
                # so the dispatch ledger's active frame names the
                # program whose cache entry this was
                _devobs.note_cache(ev)

        def _on_duration(name, duration, **kw):
            # the histogram's own `count` is the event count — e.g. the
            # backend_compile histogram count IS the distinct-program count
            _mx.REGISTRY.histogram(_event_name(name) + ".seconds").observe(duration)
            if "backend_compile" in name:
                _mx.flight(
                    "compile", seconds=round(duration, 3),
                    program=_devobs.current_program(),
                )
                _devobs.note_compile(duration)

        _mon.register_event_listener(_on_event)
        _mon.register_event_duration_secs_listener(_on_duration)
    except Exception:  # older jax without monitoring
        pass

    # Persistent-cache load failures surface as `warnings.warn(...)` from
    # jax._src.compiler (`Error reading persistent compilation cache
    # entry ...`) — chain-wrap showwarning to count them. The message
    # includes the module name, so the once-per-location warning filter
    # still counts each failing program once.
    import warnings as _warnings

    _prev_showwarning = _warnings.showwarning

    def _classify_cache_error(text: str):
        # reads and writes fail for different reasons (unloadable entry
        # vs. full/read-only dir) — misfiling one as the other sends the
        # rc=124 investigation the wrong way
        if "persistent compilation cache" not in text:
            return None
        return (
            "jax.cache.write_failures"
            if "Error writing" in text
            else "jax.cache.load_failures"
        )

    def _count_cache_error(text: str) -> None:
        name = _classify_cache_error(text)
        if name:
            _mx.REGISTRY.counter(name).inc()
            _mx.REGISTRY.set_meta(name.replace("failures", "last_failure"),
                                  text[:500])

    def _counting_showwarning(message, category, filename, lineno,
                              file=None, line=None):
        _count_cache_error(str(message))
        _prev_showwarning(message, category, filename, lineno, file, line)

    _warnings.showwarning = _counting_showwarning

    # ... and some jax versions route them through logging instead.
    import logging as _logging

    class _CacheFailureCounter(_logging.Handler):
        def emit(self, record):
            if record.levelno >= _logging.WARNING:
                # same read/write classification as the showwarning hook
                _count_cache_error(record.getMessage())

    _h = _CacheFailureCounter(level=_logging.WARNING)
    for _name in ("jax._src.compilation_cache", "jax._src.compiler"):
        _logging.getLogger(_name).addHandler(_h)


_install_jax_monitoring()

from . import limbs  # noqa: F401
from .field import FP, FR, FieldSpec  # noqa: F401
