"""TPU compute path: limb-tensor bigint, prime fields, curves, pairing.

Design (TPU-first, not a port — reference delegates to gnark's x86-64
assembly; we target the VPU/MXU instead):

* Field elements are tensors of 32 radix-2^8 limbs in ``int32``
  (little-endian limb order), batched over leading axes. 8-bit limbs keep
  every partial product and column sum inside int32 — no 64-bit emulation —
  and map onto TPU-native integer lanes.
* Multiplication is Montgomery (REDC, R = 2^256) built from branch-free
  column convolutions; carries use signed arithmetic-shift passes under
  ``lax.while_loop``.
* Group ops are batched Jacobian formulas with select-based (branch-free)
  edge handling; scalar multiplication is a ``lax.scan`` over bits.
* Hot multiexps use fixed-base window tables contracted with one-hot digit
  matrices — dense matmuls that ride the MXU.
"""

import os as _os

import jax as _jax

# Persistent compilation cache: the pairing/Miller programs are large and
# XLA (esp. :CPU) compiles them slowly; cache them across processes.
_cache_dir = _os.environ.get(
    "FTS_TPU_JAX_CACHE", _os.path.expanduser("~/.cache/fts_tpu_jax")
)
try:
    _jax.config.update("jax_compilation_cache_dir", _cache_dir)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
except Exception:  # older jax without the knobs
    pass

from . import limbs  # noqa: F401
from .field import FP, FR, FieldSpec  # noqa: F401
