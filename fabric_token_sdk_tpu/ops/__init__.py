"""TPU compute path: limb-tensor bigint, prime fields, curves, pairing.

Design (TPU-first, not a port — reference delegates to gnark's x86-64
assembly; we target the VPU/MXU instead):

* Field elements are tensors of 32 radix-2^8 limbs in ``int32``
  (little-endian limb order), batched over leading axes. 8-bit limbs keep
  every partial product and column sum inside int32 — no 64-bit emulation —
  and map onto TPU-native integer lanes.
* Multiplication is Montgomery (REDC, R = 2^256) built from branch-free
  column convolutions; carries use signed arithmetic-shift passes under
  ``lax.while_loop``.
* Group ops are batched Jacobian formulas with select-based (branch-free)
  edge handling; scalar multiplication is a ``lax.scan`` over bits.
* Hot multiexps use fixed-base window tables contracted with one-hot digit
  matrices — dense matmuls that ride the MXU.
"""

import os as _os

import jax as _jax

# Persistent compilation cache: the pairing/Miller programs are large and
# XLA (esp. :CPU) compiles them slowly; cache them across processes.
_cache_dir = _os.environ.get(
    "FTS_TPU_JAX_CACHE", _os.path.expanduser("~/.cache/fts_tpu_jax")
)
try:
    _jax.config.update("jax_compilation_cache_dir", _cache_dir)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
except Exception:  # older jax without the knobs
    pass


# ---------------------------------------------------------- observability
#
# Compile/cache instrumentation: every XLA compile and persistent-cache
# hit/miss/load-failure lands in the metrics registry. This is the signal
# that diagnoses a silent rc=124 (unbounded recompiles after cache-load
# failures) in one read of the sidecar:
#   jax.core.compile.backend_compile_duration.seconds  — per-program wall
#     time histogram; its `count` IS the distinct-compiled-program count
#   jax.compilation_cache.cache_hits / cache_misses    — persistent cache
#   jax.cache.load_failures                            — AOT entries that
#     exist but refuse to load (e.g. cpu_aot_loader machine mismatch)


def _install_jax_monitoring() -> None:
    from ..utils import metrics as _mx

    def _event_name(raw: str) -> str:
        return "jax." + raw.strip("/").replace("/", ".").removeprefix("jax.")

    try:
        from jax import monitoring as _mon

        def _on_event(name, **kw):
            _mx.REGISTRY.counter(_event_name(name)).inc()

        def _on_duration(name, duration, **kw):
            # the histogram's own `count` is the event count — e.g. the
            # backend_compile histogram count IS the distinct-program count
            _mx.REGISTRY.histogram(_event_name(name) + ".seconds").observe(duration)

        _mon.register_event_listener(_on_event)
        _mon.register_event_duration_secs_listener(_on_duration)
    except Exception:  # older jax without monitoring
        pass

    # Persistent-cache load failures surface as `warnings.warn(...)` from
    # jax._src.compiler (`Error reading persistent compilation cache
    # entry ...`) — chain-wrap showwarning to count them. The message
    # includes the module name, so the once-per-location warning filter
    # still counts each failing program once.
    import warnings as _warnings

    _prev_showwarning = _warnings.showwarning

    def _classify_cache_error(text: str):
        # reads and writes fail for different reasons (unloadable entry
        # vs. full/read-only dir) — misfiling one as the other sends the
        # rc=124 investigation the wrong way
        if "persistent compilation cache" not in text:
            return None
        return (
            "jax.cache.write_failures"
            if "Error writing" in text
            else "jax.cache.load_failures"
        )

    def _count_cache_error(text: str) -> None:
        name = _classify_cache_error(text)
        if name:
            _mx.REGISTRY.counter(name).inc()
            _mx.REGISTRY.set_meta(name.replace("failures", "last_failure"),
                                  text[:500])

    def _counting_showwarning(message, category, filename, lineno,
                              file=None, line=None):
        _count_cache_error(str(message))
        _prev_showwarning(message, category, filename, lineno, file, line)

    _warnings.showwarning = _counting_showwarning

    # ... and some jax versions route them through logging instead.
    import logging as _logging

    class _CacheFailureCounter(_logging.Handler):
        def emit(self, record):
            if record.levelno >= _logging.WARNING:
                # same read/write classification as the showwarning hook
                _count_cache_error(record.getMessage())

    _h = _CacheFailureCounter(level=_logging.WARNING)
    for _name in ("jax._src.compilation_cache", "jax._src.compiler"):
        _logging.getLogger(_name).addHandler(_h)


_install_jax_monitoring()

from . import limbs  # noqa: F401
from .field import FP, FR, FieldSpec  # noqa: F401
