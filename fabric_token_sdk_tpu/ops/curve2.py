"""Batched BN254 G2 (twist) group ops on limb tensors.

Mirror of `curve.py` with coordinates in Fp2: Jacobian (X, Y, Z), shape
(..., 3, 2, L), Z == 0 encoding infinity. Needed on device for the
pairing-side of batched Pointcheval-Sanders / membership verification
(the verifier computes sum PK_i^{z_i} in G2 per proof).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import limbs as lb, tower as tw
from .field import FP
from ..crypto import hostmath as hm


def infinity(shape=()) -> jnp.ndarray:
    return jnp.zeros(tuple(shape) + (3, 2, lb.NLIMBS), dtype=jnp.int32)


def is_infinity(p):
    return tw.fp2_is_zero(p[..., 2, :, :])


def neg(p):
    return jnp.stack(
        [p[..., 0, :, :], tw.fp2_neg(p[..., 1, :, :]), p[..., 2, :, :]],
        axis=-3,
    )


@jax.jit
def double(p):
    """dbl-2009-l (a=0) over Fp2, stacked into 4 multiply rounds."""
    x, y, z = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    sq = tw.fp2_sqr(jnp.stack([x, y]))
    a, b = sq[0], sq[1]
    r2 = tw.fp2_sqr(jnp.stack([b, FP.add(x, b)]))
    c, t = r2[0], r2[1]
    d = FP.sub(t, FP.add(a, c))
    d = FP.add(d, d)
    e = FP.add(FP.add(a, a), a)
    r3 = tw.fp2_mul(jnp.stack([e, y]), jnp.stack([e, z]))
    f, yz = r3[0], r3[1]
    x3 = FP.sub(f, FP.add(d, d))
    c8 = FP.add(c, c)
    c8 = FP.add(c8, c8)
    c8 = FP.add(c8, c8)
    y3 = FP.sub(tw.fp2_mul(e, FP.sub(d, x3)), c8)
    z3 = FP.add(yz, yz)
    return jnp.stack([x3, y3, z3], axis=-3)


@jax.jit
def add(p, q):
    """General Jacobian addition with select-based edge handling."""
    x1, y1, z1 = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    x2, y2, z2 = q[..., 0, :, :], q[..., 1, :, :], q[..., 2, :, :]
    sq = tw.fp2_sqr(jnp.stack([z1, z2]))
    z1z1, z2z2 = sq[0], sq[1]
    r1 = tw.fp2_mul(
        jnp.stack([x1, x2, y1, y2]),
        jnp.stack([z2z2, z1z1, z2, z1]),
    )
    u1, u2, s1p, s2p = r1[0], r1[1], r1[2], r1[3]
    r2 = tw.fp2_mul(jnp.stack([s1p, s2p]), jnp.stack([z2z2, z1z1]))
    s1, s2 = r2[0], r2[1]
    h = FP.sub(u2, u1)
    rr = FP.sub(s2, s1)
    rr = FP.add(rr, rr)
    i = tw.fp2_sqr(FP.add(h, h))
    r3 = tw.fp2_mul(jnp.stack([h, u1]), jnp.stack([i, i]))
    j, v = r3[0], r3[1]
    x3 = FP.sub(tw.fp2_sqr(rr), FP.add(j, FP.add(v, v)))
    zsum = FP.sub(tw.fp2_sqr(FP.add(z1, z2)), FP.add(z1z1, z2z2))
    r4 = tw.fp2_mul(
        jnp.stack([rr, s1, zsum]),
        jnp.stack([FP.sub(v, x3), j, h]),
    )
    s1j = r4[1]
    y3 = FP.sub(r4[0], FP.add(s1j, s1j))
    z3 = r4[2]
    out = jnp.stack([x3, y3, z3], axis=-3)

    same_x = tw.fp2_is_zero(h)
    same_y = tw.fp2_is_zero(rr)
    inf1 = tw.fp2_is_zero(z1)
    inf2 = tw.fp2_is_zero(z2)
    sel = lambda m: m[..., None, None, None]
    out = jnp.where(sel(same_x & same_y & ~inf1 & ~inf2), double(p), out)
    out = jnp.where(sel(same_x & ~same_y & ~inf1 & ~inf2), jnp.zeros_like(out), out)
    out = jnp.where(sel(inf1), q, out)
    out = jnp.where(sel(inf2), p, out)
    return out


@jax.jit
def scalar_mul(p, k_canon):
    """(..., 3, 2, L) x (..., L) canonical scalars -> double-and-add scan."""
    from .curve import scalar_bits

    bits = scalar_bits(k_canon)
    bits_t = jnp.moveaxis(bits, -1, 0)

    def step(acc, bit):
        acc = double(acc)
        acc = jnp.where(bit[..., None, None, None] > 0, add(acc, p), acc)
        return acc, None

    out, _ = lax.scan(step, infinity(p.shape[:-3]), bits_t)
    return out


def tree_sum(points, axis: int = -4):
    points = jnp.moveaxis(points, axis, 0)
    n = points.shape[0]
    while n > 1:
        half = n // 2
        odd = points[2 * half :]
        points = add(points[:half], points[half : 2 * half])
        if odd.shape[0]:
            points = jnp.concatenate([points, odd], axis=0)
        n = points.shape[0]
    return points[0]


# ---------------------------------------------------------------- host I/O

def encode_points(pts) -> np.ndarray:
    """Host G2 affine (fp2 pairs) or None -> (N, 3, 2, L) Montgomery Jac."""
    out = np.zeros((len(pts), 3, 2, lb.NLIMBS), dtype=np.int32)
    for i, pt in enumerate(pts):
        if pt is None:
            continue
        out[i, 0] = tw.encode_fp2([pt[0]])[0]
        out[i, 1] = tw.encode_fp2([pt[1]])[0]
        out[i, 2] = tw.encode_fp2([(1, 0)])[0]
    return out


def decode_points(arr):
    """Device (..., 3, 2, L) -> host affine fp2 pairs (inversion on host)."""
    flat = np.asarray(arr).reshape(-1, 3, 2, lb.NLIMBS)
    coords = tw.decode_fp2(flat.reshape(-1, 2, lb.NLIMBS))
    out = []
    for i in range(len(flat)):
        x, y, z = coords[3 * i], coords[3 * i + 1], coords[3 * i + 2]
        if z == (0, 0):
            out.append(None)
            continue
        zinv = hm.fp2_inv(z)
        zi2 = hm.fp2_mul(zinv, zinv)
        out.append(
            (hm.fp2_mul(x, zi2), hm.fp2_mul(hm.fp2_mul(y, zi2), zinv))
        )
    return out


def to_affine_device(p):
    """Jacobian -> affine (..., 2, 2, L) on device (uses field inversion).

    Infinity lanes come back as (0, 0) — mask separately.
    """
    x, y, z = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    zi = tw.fp2_inv(z)
    zi2 = tw.fp2_sqr(zi)
    r = tw.fp2_mul(jnp.stack([x, tw.fp2_mul(y, zi)]), jnp.stack([zi2, zi2]))
    return jnp.stack([r[0], r[1]], axis=-3)
