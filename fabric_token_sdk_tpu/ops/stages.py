"""Primitive stage kernels: the compile-once tiles of the verify plane.

The previous data plane fused each verifier's whole group-math pipeline
into one giant per-shape XLA program (`_wf_kernel` & co in
`crypto/batch.py`): a new transfer shape `(n_in, n_out)` meant a new
multi-minute compile, and the FIRST compile alone could blow the tier-1
budget. This module generalizes the staged execution model proven by
`pairing.pairing_product_staged`: a small, fixed set of **primitive stage
kernels**, each `jax.jit`'d once at a single canonical tile shape, with
all inter-stage glue (reshape / broadcast / concat / challenge repeat) in
host numpy. Verifiers become host-side compositions of these stages, so
the total distinct-program count is a small constant — independent of
batch size, transfer shape, and parameter set.

Stage inventory (ROW_TILE flat rows each; tables/keys are ARGUMENTS, not
baked constants, so one executable serves every parameter set):

  G1:  msm tile (per nbases in {1,2,3}), variable-base scalar-mul tile,
       Jacobian add tile, Jacobian sub tile (add + neg fused),
       batch to-affine tile
  G2:  variable-base scalar-mul tile, Jacobian add tile,
       batch to-affine tile

Program-size discipline: one inlined Jacobian point-op costs ~40s of XLA
CPU compile on a small host, so every stage keeps at most ~2 point-ops in
its traced body. In particular the msm point reduction is a `lax.scan`
with a SINGLE add per step instead of a fully unrolled log-depth tree
(~191 inlined adds for a 3-base table) — the same total point additions
at runtime, but a ~100x smaller program.

`stage_programs()` enumerates every (name, jitted fn, canonical arg
shapes) triple so `ops/warmup.py` can AOT-compile the whole set into the
persistent cache ahead of time.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import curve as cv, curve2 as cv2, limbs as lb
from .field import FP
from ..utils import devobs
from ..utils import metrics as mx
from ..utils import resilience, sysmon
from ..utils.tracing import logger

# Canonical tile height: every stage kernel sees exactly ROW_TILE flat
# rows (batches are flattened over (B, n) and padded by repeating row 0;
# padded outputs are discarded).
ROW_TILE = 8

# ------------------------------------------------------------ tile kernels

@jax.jit
def _g1_msm_tile(table_flat, scalars):
    """Fixed-base windowed multiexp tile.

    table_flat: (nbases*64, 16, 3L) window table (argument, shared across
    parameter sets); scalars: (R, nbases, L) canonical limbs.
    Returns (R, 3, L) Jacobian. One program per nbases (3 total, ever).

    Digit selection is `cv.msm_select` (shared with `cv.msm_flat`); the
    point reduction is a scan with ONE add per step to keep the program
    small (see module docstring).
    """
    sel = cv.msm_select(table_flat, scalars)  # (R, T, 3, L)
    pts = jnp.moveaxis(sel, -3, 0)  # (T, R, 3, L)

    def step(acc, p):
        return cv.add(acc, p), None

    acc, _ = lax.scan(step, cv.infinity(pts.shape[1:-2]), pts)
    return acc


@jax.jit
def _g1_sub_tile(a, b):
    """a - b on (R, 3, L) Jacobian tiles (the commitment-minus-statement
    step of every sigma verification)."""
    return cv.add(a, cv.neg(b))


@jax.jit
def _g1_to_affine_tile(p):
    """(R, 3, L) Jacobian -> (R, 2, L) affine (Fermat inversion on
    device). Infinity lanes come back (0, 0) — the caller masks."""
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    zi = FP.inv(z)
    zi2 = FP.mul(zi, zi)
    return jnp.stack([FP.mul(x, zi2), FP.mul(FP.mul(y, zi2), zi)], axis=-2)


_g2_to_affine_tile = jax.jit(cv2.to_affine_device)


# ------------------------------------------------------------ tile runner

_env_clamp_seen = None


def mesh_env() -> tuple:
    """(n_devices, mp) from the ambient mesh env (`FTS_MESH_DEVICES`,
    `FTS_MESH_MP`). n_devices == 0 means no mesh is configured; mp is
    clamped to the largest divisor of n_devices so a bad pairing never
    knocks dispatch off the sharded path. A clamp counts under
    `sharding.clamped` — once per distinct (n, mp) misconfiguration,
    not per dispatch (this runs on every `run_rows` call)."""
    global _env_clamp_seen
    try:
        n = int(os.environ.get("FTS_MESH_DEVICES", "0") or 0)
    except ValueError:
        n = 0
    try:
        mp = int(os.environ.get("FTS_MESH_MP", "1") or 1)
    except ValueError:
        mp = 1
    mp = max(1, mp)
    if n > 0:
        want = mp
        while n % mp:
            mp -= 1
        if mp != want and _env_clamp_seen != (n, want):
            _env_clamp_seen = (n, want)
            mx.counter("sharding.clamped").inc()
            mx.counter("sharding.clamped.env").inc()
            mx.flight(
                "sharding.clamped", where="env", want=want, got=mp,
                n_devices=n,
            )
            logger.warning(
                "sharding: ambient mesh env clamped mp %d -> %d "
                "(FTS_MESH_DEVICES=%d)", want, mp, n,
            )
    return max(0, n), mp


def default_dp() -> int:
    """Data-parallel shard count for the stage runner: FTS_DP_SHARDS
    when set, else the dp extent of the ambient mesh env
    (`FTS_MESH_DEVICES` // `FTS_MESH_MP`), else 1 = unsharded. Both the
    batched verify plane (`crypto/batch.py`) and the batched prover
    (`crypto/batch_prove.py`) flow through `run_rows`, so one knob
    shards both."""
    v = os.environ.get("FTS_DP_SHARDS")
    if v:
        try:
            return max(1, int(v))
        except ValueError:
            return 1
    n, mp = mesh_env()
    return max(1, n // mp) if n > 0 else 1


def default_mp() -> int:
    """Model-parallel worker count of the staged pairing product (legs
    axis), from the ambient mesh env; 1 = unsharded."""
    n, mp = mesh_env()
    return mp if n > 0 else 1


def _run_span(kernel, consts, arrays, start, stop):
    """Sequentially run the tile kernel over [start, stop) row slabs."""
    return [
        kernel(*consts, *(jnp.asarray(a[t : t + ROW_TILE]) for a in arrays))
        for t in range(start, stop, ROW_TILE)
    ]


def run_tile_spans(fn, ntiles: int, workers: int, *args, calls, shards,
                   what="stages"):
    """The ONE sharded span-dispatch mechanism: `fn(*args, start, stop)`
    over contiguous tile-index spans from worker threads — ridden by
    both the row runner (`run_rows`) and the staged pairing product
    (`ops/pairing.py`). Outputs come back in span order, so the
    concatenated result is bit-identical to one sequential
    `fn(*args, 0, ntiles)` walk.

    Degrade chain, first link: any dispatch failure (thread-pool
    exhaustion, a worker crash) falls back to the sequential walk
    (`sharding.fallbacks`) — same executables, same results; the
    verifier/pipeline host fallback remains the second link, so
    accept/reject can never depend on sharding. `calls`/`shards` are
    incremented on COMPLETION only: a degraded dispatch must never
    report as sharded (tests and the observatory both read these as
    "the sharded path actually ran").

    The `stages` circuit breaker (utils/resilience.py) guards this
    seam: repeated dispatch failures OPEN it and later calls skip
    straight to the sequential walk (no thread pool spun up, no
    re-failure paid) until a half-open probe heals it — the plane
    degrades AND recovers without operator action."""
    if workers <= 1 or ntiles <= 1:
        return fn(*args, 0, ntiles)
    brk = resilience.breaker("stages")
    if not brk.allow():
        # breaker-open skip: the open/close TRANSITIONS are already
        # reasoned `breaker` flight events (utils/resilience.py); here
        # we only count the skipped dispatches and charge the degrade
        # to the active program's ledger entry
        mx.counter("sharding.breaker_skips").inc()
        devobs.note_degrade("breaker_open")
        return fn(*args, 0, ntiles)
    try:
        spans = dp_spans(ntiles, workers)
        with ThreadPoolExecutor(max_workers=len(spans)) as pool:
            futs = [pool.submit(fn, *args, a, b) for a, b in spans]
            outs = [o for f in futs for o in f.result()]
        calls.inc()
        shards.inc(len(spans))
        brk.record_success()
        return outs
    except Exception as e:
        brk.record_failure()
        mx.counter("sharding.fallbacks").inc()
        mx.flight(
            "sharding.fallback", what=what, workers=workers,
            reason="dispatch_error", error=type(e).__name__,
            program=devobs.current_program(),
        )
        devobs.note_degrade("dispatch_error")
        logger.exception(
            "%s: sharded dispatch failed (workers=%d); re-running "
            "unsharded", what, workers,
        )
        return fn(*args, 0, ntiles)


def dp_spans(ntiles: int, dp: int):
    """Split `ntiles` ROW_TILE slabs into at most `dp` contiguous,
    tile-aligned (start_tile, stop_tile) spans — the row partition of the
    per-shard stage-tile dispatch (`parallel/sharding.py`)."""
    dp = max(1, min(dp, ntiles))
    per, extra = divmod(ntiles, dp)
    spans, at = [], 0
    for s in range(dp):
        n = per + (1 if s < extra else 0)
        spans.append((at, at + n))
        at += n
    return spans


_PROGRAM_NAMES = None


def _program_of(kernel, arrays) -> str:
    """Canonical program name (the `stage_programs()` registry key) of a
    stage kernel — the join key the dispatch ledger (`utils/devobs.py`)
    and the compile listeners attribute by. The msm tile is one jitted
    fn serving three programs (disambiguated by the nbases axis of its
    scalar rows); g1/g2 share `__name__` for add/scalar_mul, so the map
    is keyed by function identity, not name."""
    global _PROGRAM_NAMES
    if kernel is _g1_msm_tile:
        return f"g1_msm{arrays[0].shape[1]}_tile"
    if _PROGRAM_NAMES is None:
        names = {}
        for name, fn, _shapes in stage_programs():
            names.setdefault(id(fn), name)
        _PROGRAM_NAMES = names
    return _PROGRAM_NAMES.get(id(kernel)) or (
        getattr(kernel, "__name__", None) or type(kernel).__name__
    )


def run_rows(kernel, *arrays, consts=(), dp=None):
    """Run `kernel(*consts, *tiles)` over ROW_TILE slabs of flat-row
    numpy arrays -> numpy. The staged successor of the old
    `crypto.batch._run_tiled`.

    * `arrays` share a leading flat row axis N; rows are padded to a
      ROW_TILE multiple by repeating row 0 (padded outputs discarded).
    * `consts` are parameter tensors (window tables, public keys) passed
      whole to every tile call — arguments, not baked jit constants.
    * Tiles are CONTIGUOUS numpy views of a single padded buffer (one
      host-side copy at most, only when padding is needed); the only
      host->device transfers are the per-tile `jnp.asarray` calls,
      counted in `batch.tiled.transfers`.
    * `dp` > 1 (default `FTS_DP_SHARDS`) splits the tile range into
      contiguous spans dispatched from worker threads — same executable,
      same results, overlapping host glue with device work. Device
      placement is intentionally NOT pinned per shard: per-device
      executables have distinct compile-cache keys, which would break
      the compile-once/warm-cache guarantees (see ARCHITECTURE.md).
    """
    N = arrays[0].shape[0]
    if N == 0:
        raise ValueError("run_rows: empty row batch (caller must guard)")
    pad = (-N) % ROW_TILE
    if pad:
        padded = []
        for a in arrays:
            buf = np.empty((N + pad,) + a.shape[1:], dtype=a.dtype)
            buf[:N] = a
            buf[N:] = a[:1]
            padded.append(buf)
        arrays = tuple(padded)
    else:
        arrays = tuple(np.ascontiguousarray(a) for a in arrays)
    ntiles = (N + pad) // ROW_TILE
    mx.counter("stages.calls").inc()
    mx.counter("stages.rows").inc(N)
    mx.counter("stages.tiles").inc(ntiles)
    mx.counter("batch.tiled.transfers").inc(ntiles * len(arrays))
    dp = default_dp() if dp is None else max(1, dp)
    # per-stage device timing: one `stages.run` span per dispatch, named
    # by the canonical program — the per-kernel breakdown a critical-path
    # trace (cmd/ftstrace.py) renders under the block's device verify;
    # the dispatch ledger (utils/devobs.py) records the same frame with
    # occupancy and dp placement for the ops plane
    kname = _program_of(kernel, arrays)
    t_dispatch = time.monotonic()
    with devobs.dispatch(kname, rows=N, padded_rows=pad, dp=dp), \
            mx.span("stages.run", kernel=kname, rows=N, tiles=ntiles):
        outs = run_tile_spans(
            lambda a, b: _run_span(
                kernel, consts, arrays, a * ROW_TILE, b * ROW_TILE
            ),
            ntiles, dp,
            calls=mx.counter("stages.sharded_calls"),
            shards=mx.counter("stages.shards"),
        )
    if not mx.enabled():
        # the span above feeds stages.run.seconds only when span
        # recording is on; the live ops plane needs the stage-dispatch
        # latency histogram (and its quantiles) unconditionally
        mx.histogram("stages.run.seconds").observe(
            time.monotonic() - t_dispatch
        )
    # device/host memory high-water of the data plane (throttled; never
    # compiles anything — see utils/sysmon.py)
    sysmon.sample_stages()
    if isinstance(outs[0], (tuple, list)):
        return tuple(
            np.concatenate([np.asarray(o[i]) for o in outs])[:N]
            for i in range(len(outs[0]))
        )
    return np.concatenate([np.asarray(o) for o in outs])[:N]


# ------------------------------------------------------------ compositions
#
# Thin named wrappers so verifier code reads as algebra. Every wrapper
# takes/returns HOST numpy (flat rows); `consts` device residency is the
# caller's choice (jnp tables stay resident, numpy is transferred).

def g1_msm_rows(table_flat, scalars: np.ndarray, dp=None) -> np.ndarray:
    """(N, nbases, L) canonical scalars x fixed-base table -> (N, 3, L)."""
    return run_rows(_g1_msm_tile, scalars, consts=(table_flat,), dp=dp)


def g1_mul_rows(points: np.ndarray, scalars: np.ndarray, dp=None) -> np.ndarray:
    """Variable-base scalar mul: (N, 3, L) x (N, L) -> (N, 3, L)."""
    return run_rows(cv.scalar_mul, points, scalars, dp=dp)


def g1_add_rows(a: np.ndarray, b: np.ndarray, dp=None) -> np.ndarray:
    return run_rows(cv.add, a, b, dp=dp)


def g1_sub_rows(a: np.ndarray, b: np.ndarray, dp=None) -> np.ndarray:
    return run_rows(_g1_sub_tile, a, b, dp=dp)


def g1_to_affine_rows(p: np.ndarray, dp=None) -> np.ndarray:
    return run_rows(_g1_to_affine_tile, p, dp=dp)


def g2_mul_rows(points: np.ndarray, scalars: np.ndarray, dp=None) -> np.ndarray:
    """(N, 3, 2, L) x (N, L) -> (N, 3, 2, L)."""
    return run_rows(cv2.scalar_mul, points, scalars, dp=dp)


def g2_add_rows(a: np.ndarray, b: np.ndarray, dp=None) -> np.ndarray:
    return run_rows(cv2.add, a, b, dp=dp)


def g2_to_affine_rows(p: np.ndarray, dp=None) -> np.ndarray:
    return run_rows(_g2_to_affine_tile, p, dp=dp)


def g2_tree_sum_rows(terms: np.ndarray, dp=None) -> np.ndarray:
    """Per-row sum of k G2 terms: (N, k, 3, 2, L) -> (N, 3, 2, L).

    Host-side log-depth fold — each level is ONE tiled add over the
    flattened pair rows, so no per-k device program exists.
    """
    while terms.shape[1] > 1:
        k = terms.shape[1]
        half = k // 2
        rest = terms[:, 2 * half :]
        flat_a = terms[:, :half].reshape((-1,) + terms.shape[2:])
        flat_b = terms[:, half : 2 * half].reshape((-1,) + terms.shape[2:])
        summed = g2_add_rows(flat_a, flat_b, dp=dp).reshape(
            (terms.shape[0], half) + terms.shape[2:]
        )
        terms = np.concatenate([summed, rest], axis=1) if rest.shape[1] else summed
    return terms[:, 0]


def affine_to_jac_np(p: np.ndarray) -> np.ndarray:
    """Host glue: (..., 2, L) Montgomery affine -> (..., 3, L) Jacobian
    with Z = 1 (pure numpy — no device program)."""
    one = np.broadcast_to(
        np.asarray(FP.one_mont, dtype=np.int32), p[..., 0, :].shape
    )
    return np.concatenate([p, one[..., None, :]], axis=-2)


# ------------------------------------------------------------ warmup hooks

def stage_programs():
    """Yield (name, jitted_fn, canonical arg shapes) for every stage
    program, for AOT precompilation (`ops/warmup.py`). int32 throughout."""
    R, L = ROW_TILE, lb.NLIMBS
    W = 1 << cv.WINDOW_BITS
    for nbases in (1, 2, 3):
        yield (
            f"g1_msm{nbases}_tile",
            _g1_msm_tile,
            ((nbases * cv.DIGITS_PER_SCALAR, W, 3 * L), (R, nbases, L)),
        )
    yield ("g1_mul_tile", cv.scalar_mul, ((R, 3, L), (R, L)))
    yield ("g1_add_tile", cv.add, ((R, 3, L), (R, 3, L)))
    yield ("g1_sub_tile", _g1_sub_tile, ((R, 3, L), (R, 3, L)))
    yield ("g1_to_affine_tile", _g1_to_affine_tile, ((R, 3, L),))
    yield ("g2_mul_tile", cv2.scalar_mul, ((R, 3, 2, L), (R, L)))
    yield ("g2_add_tile", cv2.add, ((R, 3, 2, L), (R, 3, 2, L)))
    yield ("g2_to_affine_tile", _g2_to_affine_tile, ((R, 3, 2, L),))
