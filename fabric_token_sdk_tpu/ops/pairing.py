"""Batched optimal-ate pairing on device (BN254).

The hot verification op of the framework: Pointcheval-Sanders signature /
membership-proof checks are pairing-product equations (reference
pssign/sign.go:153, sigproof/pok.go:196-203), verified here for whole
batches of proofs in one XLA program.

Design notes (TPU-first):
* G2 Miller-loop arithmetic runs on the twist in Jacobian coordinates with
  denominator-dropping line formulas — all Fp2-denominators lie in proper
  subfields and vanish under the final exponentiation, so every step is
  branch-free polynomial arithmetic on limb tensors.
* The Miller loop is a `lax.scan` over the static bits of 6u+2; the add
  step is computed every iteration and `select`ed (SIMD-friendly).
* Final exponentiation: easy part (one tower inversion), then the hard
  part via the balanced base-p / u-basis decomposition
  lambda_0 = -(36u^3+30u^2+18u+2), lambda_1 = 1-(36u^3+18u^2+12u),
  lambda_2 = 6u^2+1, lambda_3 = 1 (verified exactly at import), costing
  three u-exponentiations + small-exponent combinations + Frobenius maps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import limbs as lb, tower as tw
from .field import FP
from ..crypto import hostmath as hm
from ..utils import devobs
from ..utils import metrics as mx

# ---------------------------------------------------------------- constants

_ATE_BITS = np.array([int(b) for b in bin(hm.ATE_LOOP)[3:]], dtype=np.int32)
# ALL bits of u MSB-first ([2:] strips only '0b'); _pow_u skips the MSB itself
_U_BITS = np.array([int(b) for b in bin(hm.U)[2:]], dtype=np.int32)

# hard-part u-basis coefficients (c0..c3) per lambda_i — verified at import
_LAMBDA_COEFFS = [
    (-2, -18, -30, -36),
    (1, -12, -18, -36),
    (1, 0, 6, 0),
    (1, 0, 0, 0),
]


def _check_lambda_decomposition() -> None:
    D = (hm.P**4 - hm.P**2 + 1) // hm.R
    total = 0
    for i, cs in enumerate(_LAMBDA_COEFFS):
        lam = sum(c * hm.U**k for k, c in enumerate(cs))
        total += lam * hm.P**i
    if total != D:
        raise AssertionError("final-exponentiation decomposition is wrong")


_check_lambda_decomposition()


@functools.lru_cache(maxsize=None)
def _twist_frob_consts():
    """(c_x1, c_y1, c_x2, c_y2): XI^((p^n-1)/3), XI^((p^n-1)/2) for n=1,2."""
    cx1 = hm.fp2_pow(hm.XI, (hm.P - 1) // 3)
    cy1 = hm.fp2_pow(hm.XI, (hm.P - 1) // 2)
    cx2 = hm.fp2_pow(hm.XI, (hm.P**2 - 1) // 3)
    cy2 = hm.fp2_pow(hm.XI, (hm.P**2 - 1) // 2)
    return tw.encode_fp2([cx1, cy1, cx2, cy2])


# ---------------------------------------------------------------- host I/O

def encode_g1(points) -> np.ndarray:
    """Host G1 affine points -> (N, 2, L) Montgomery (x, y) tensor.

    Infinity encodes as (0, 0) and must be masked by the caller.
    """
    out = np.zeros((len(points), 2, lb.NLIMBS), dtype=np.int32)
    Rm = 1 << (lb.RADIX_BITS * lb.NLIMBS)
    for i, pt in enumerate(points):
        if pt is None:
            continue
        out[i, 0] = lb.int_to_limbs(pt[0] * Rm % hm.P)
        out[i, 1] = lb.int_to_limbs(pt[1] * Rm % hm.P)
    return out


def encode_g2(points) -> np.ndarray:
    """Host G2 affine points -> (N, 2, 2, L): [x, y] as Fp2 tensors."""
    out = np.zeros((len(points), 2, 2, lb.NLIMBS), dtype=np.int32)
    for i, pt in enumerate(points):
        if pt is None:
            continue
        out[i, 0] = tw.encode_fp2([pt[0]])[0]
        out[i, 1] = tw.encode_fp2([pt[1]])[0]
    return out


def g1_infinity_mask(points) -> np.ndarray:
    return np.array([p is None for p in points])


# ---------------------------------------------------------------- miller

def _scale2(a, ka, b, kb):
    """(a * ka, b * kb) for fp2 a,b and base-field ka,kb — one FP.mul."""
    X = jnp.stack([a[..., 0, :], a[..., 1, :], b[..., 0, :], b[..., 1, :]])
    K = jnp.stack([ka, ka, kb, kb])
    v = FP.mul(X, K)
    return (
        jnp.stack([v[0], v[1]], axis=-2),
        jnp.stack([v[2], v[3]], axis=-2),
    )


def _dbl_step(T, xp, yp):
    """Jacobian doubling + denominator-free line at P=(xp, yp).

    Stacked: 4 batched multiply rounds. Returns (T2, l0, l1, l3).
    """
    X, Y, Z = T[..., 0, :, :], T[..., 1, :, :], T[..., 2, :, :]
    sq = tw.fp2_sqr(jnp.stack([X, Y, Z]))
    XX, YY, ZZ = sq[0], sq[1], sq[2]
    M = FP.add(FP.add(XX, XX), XX)  # 3X^2
    r2 = tw.fp2_mul(jnp.stack([X, ZZ, Y]), jnp.stack([YY, Z, Z]))
    XYY, ZZZ, YZ = r2[0], r2[1], r2[2]
    S = _times2(_times2(XYY))  # 4XY^2
    r3 = tw.fp2_mul(
        jnp.stack([M, YY, Y, M, M]), jnp.stack([M, YY, ZZZ, ZZ, X])
    )
    M2, YYYY, YZZZ, MZZ, MX = r3[0], r3[1], r3[2], r3[3], r3[4]
    X3 = tw.fp2_sub(M2, _times2(S))
    Y3 = tw.fp2_sub(tw.fp2_mul(M, tw.fp2_sub(S, X3)), _times8(YYYY))
    Z3 = _times2(YZ)
    # line: l0 = -2YZ^3 yp ; l1 = 3X^2 Z^2 xp ; l3 = 2Y^2 - 3X^3
    l0, l1 = _scale2(FP.neg(_times2(YZZZ)), yp, MZZ, xp)
    l3 = tw.fp2_sub(_times2(YY), MX)
    return jnp.stack([X3, Y3, Z3], axis=-3), l0, l1, l3


def _add_step(T, Q, xp, yp):
    """Mixed addition T + Q (Q affine) + line at P; denominator-free.

    Stacked: 5 batched multiply rounds.
    """
    X, Y, Z = T[..., 0, :, :], T[..., 1, :, :], T[..., 2, :, :]
    x2, y2 = Q[..., 0, :, :], Q[..., 1, :, :]
    ZZ = tw.fp2_sqr(Z)
    r2 = tw.fp2_mul(jnp.stack([x2, ZZ]), jnp.stack([ZZ, Z]))
    U2, ZZZ = r2[0], r2[1]
    H = tw.fp2_sub(U2, X)
    r3 = tw.fp2_mul(jnp.stack([y2, H, Z]), jnp.stack([ZZZ, H, H]))
    S2, HH, Z3 = r3[0], r3[1], r3[2]
    r = tw.fp2_sub(S2, Y)
    r4 = tw.fp2_mul(jnp.stack([H, X, r, r]), jnp.stack([HH, HH, r, x2]))
    HHH, V, rr, rx2 = r4[0], r4[1], r4[2], r4[3]
    X3 = tw.fp2_sub(tw.fp2_sub(rr, HHH), _times2(V))
    r5 = tw.fp2_mul(
        jnp.stack([r, Y, Z3]), jnp.stack([tw.fp2_sub(V, X3), HHH, y2])
    )
    Y3 = tw.fp2_sub(r5[0], r5[1])
    l3 = tw.fp2_sub(r5[2], rx2)
    l0, l1 = _scale2(FP.neg(Z3), yp, r, xp)
    return jnp.stack([X3, Y3, Z3], axis=-3), l0, l1, l3


def _times2(x):
    return FP.add(x, x)


def _times8(x):
    return _times2(_times2(_times2(x)))


@jax.jit
def miller_loop(P, Q):
    """Batched Miller loop: P (..., 2, L) G1 affine, Q (..., 2, 2, L) G2
    affine -> f (..., 6, 2, L). Infinity handling is the caller's job."""
    xp, yp = P[..., 0, :], P[..., 1, :]
    batch = P.shape[:-2]
    T0 = jnp.concatenate(
        [Q, jnp.broadcast_to(tw.fp2_ones(batch)[..., None, :, :], Q[..., :1, :, :].shape)],
        axis=-3,
    ).astype(jnp.int32)
    f0 = tw.fp12_ones(batch).astype(jnp.int32)

    def step(carry, bit):
        f, T = carry
        f = tw.fp12_sqr(f)
        T2, l0, l1, l3 = _dbl_step(T, xp, yp)
        f = tw.fp12_mul_sparse013(f, l0, l1, l3)
        Ta, a0, a1, a3 = _add_step(T2, Q, xp, yp)
        fa = tw.fp12_mul_sparse013(f, a0, a1, a3)
        take = bit > 0
        f = jnp.where(take, fa, f)
        T = jnp.where(take, Ta, T2)
        return (f, T), None

    (f, T), _ = lax.scan(step, (f0, T0), jnp.asarray(_ATE_BITS))

    # frobenius corrections: Q1 = pi(Q), Q2n = -pi^2(Q)
    consts = jnp.asarray(_twist_frob_consts())
    cx1, cy1, cx2, cy2 = consts[0], consts[1], consts[2], consts[3]
    Qx, Qy = Q[..., 0, :, :], Q[..., 1, :, :]
    Q1 = jnp.stack(
        [tw.fp2_mul(tw.fp2_conj(Qx), cx1), tw.fp2_mul(tw.fp2_conj(Qy), cy1)],
        axis=-3,
    )
    Q2n = jnp.stack(
        [tw.fp2_mul(Qx, cx2), FP.neg(tw.fp2_mul(Qy, cy2))], axis=-3
    )
    T, l0, l1, l3 = _add_step(T, Q1, xp, yp)
    f = tw.fp12_mul_sparse013(f, l0, l1, l3)
    _, l0, l1, l3 = _add_step(T, Q2n, xp, yp)
    f = tw.fp12_mul_sparse013(f, l0, l1, l3)
    return f


# ---------------------------------------------------------------- final exp

def _pow_u(f):
    """f^u via scan over the fixed bits of u (cyclotomic input assumed)."""

    def step(acc, bit):
        acc = tw.fp12_sqr(acc)
        acc = jnp.where(bit > 0, tw.fp12_mul(acc, f), acc)
        return acc, None

    out, _ = lax.scan(step, f, jnp.asarray(_U_BITS[1:]))
    return out


# Straus tables for the hard part: bit matrix (nbits, 4 outputs, 4 bases)
# of |c_ik| MSB-first, and the sign matrix (4, 4).
_HP_NBITS = max(abs(c).bit_length() for cs in _LAMBDA_COEFFS for c in cs)
_HP_BITS = np.zeros((_HP_NBITS, 4, 4), dtype=np.int32)
_HP_SIGN = np.zeros((4, 4), dtype=np.int32)
for _i, _cs in enumerate(_LAMBDA_COEFFS):
    for _k, _c in enumerate(_cs):
        _HP_SIGN[_i, _k] = -1 if _c < 0 else 1
        for _b in range(_HP_NBITS):
            _HP_BITS[_HP_NBITS - 1 - _b, _i, _k] = (abs(_c) >> _b) & 1


@jax.jit
def final_exp(f):
    """f^((p^12-1)/r), batched.

    Hard part: one Straus simultaneous exponentiation over the 4x4
    coefficient matrix — a 6-step scan with a single stacked multiply per
    base — keeping the number of inlined fp12-op instances tiny.
    """
    # easy part: f^(p^6-1) then ^(p^2+1)
    t = tw.fp12_mul(tw.fp12_conj(f), tw.fp12_inv(f))
    t = tw.fp12_mul(tw.fp12_frobenius(t, 2), t)
    # u-power ladder
    fu = _pow_u(t)
    fu2 = _pow_u(fu)
    fu3 = _pow_u(fu2)
    powers = jnp.stack([t, fu, fu2, fu3])  # (4, ..., 6, 2, L)
    conj_p = tw.fp12_conj(powers)
    # sign-adjusted bases per (output, base): (4out, 4base, ..., 6, 2, L)
    sign = jnp.asarray(_HP_SIGN)
    # (4out, 4base, 1...) vs (1, 4base, *batch, 6, 2, L)
    bases = jnp.where(
        (sign > 0)[(...,) + (None,) * (powers.ndim - 1)],
        powers[None],
        conj_p[None],
    )
    batch = f.shape[:-3]
    acc = jnp.broadcast_to(
        tw.fp12_ones(), (4,) + batch + (6, 2, lb.NLIMBS)
    ).astype(jnp.int32)

    def step(acc, bits):  # bits: (4, 4)
        acc = tw.fp12_sqr(acc)
        for k in range(4):
            mult = tw.fp12_mul(acc, bases[:, k])
            take = bits[:, k][(...,) + (None,) * (acc.ndim - 1)] > 0
            acc = jnp.where(take, mult, acc)
        return acc, None

    acc, _ = lax.scan(step, acc, jnp.asarray(_HP_BITS))
    # combine with Frobenius powers: prod_i frob^i(acc[i])
    r01 = tw.fp12_mul(acc[0], tw.fp12_frobenius(acc[1], 1))
    r23 = tw.fp12_mul(
        tw.fp12_frobenius(acc[2], 2), tw.fp12_frobenius(acc[3], 3)
    )
    return tw.fp12_mul(r01, r23)


@jax.jit
def pairing_product(Ps, Qs, inf_mask=None):
    """prod_k e(P_k, Q_k) for each batch row.

    Ps: (..., K, 2, L), Qs: (..., K, 2, 2, L), inf_mask: (..., K) bool —
    True entries contribute the identity (point at infinity).
    Returns GT elements (..., 6, 2, L).
    """
    f = miller_loop(Ps, Qs)  # (..., K, 6, 2, L)
    if inf_mask is not None:
        one = jnp.broadcast_to(tw.fp12_ones(), f.shape).astype(jnp.int32)
        f = jnp.where(inf_mask[..., None, None, None], one, f)
    # multiply the K miller values per row (tree)
    k = f.shape[-4]
    while k > 1:
        half = k // 2
        rest = f[..., 2 * half :, :, :, :]
        f = tw.fp12_mul(f[..., :half, :, :, :], f[..., half : 2 * half, :, :, :])
        if rest.shape[-4]:
            f = jnp.concatenate([f, rest], axis=-4)
        k = f.shape[-4]
    return final_exp(f[..., 0, :, :, :])


def gt_is_one(e):
    return tw.fp12_is_one(e)


_GT_ONE = ((1, 0), (0, 0), (0, 0), (0, 0), (0, 0), (0, 0))


def gt_is_one_host(arr) -> np.ndarray:
    """Host-side GT == 1 test on a (B, 6, 2, L) numpy tensor.

    Pure host decode + compare: verifiers use this instead of the device
    `gt_is_one` so the check compiles no per-batch-shape program."""
    return np.array([v == _GT_ONE for v in tw.decode_fp12(arr)], dtype=bool)


# ------------------------------------------------- staged tiled execution
#
# `pairing_product` fuses miller + product + final-exp into ONE program per
# caller shape; every verifier that inlines it pays a separate multi-minute
# XLA compile of the same math. The staged path below splits the pipeline
# into shape-stable tile programs compiled once and shared by every
# verifier and batch size:
#   * miller tile  — (MILLER_TILE, ...) pairs            (1 program, ever)
#   * row product  — (FEXP_TILE, K, ...) tree fp12 mul   (tiny, per K)
#   * final-exp    — (FEXP_TILE, ...) GT rows            (1 program, ever)
# Tiles pad with generator pairs / GT ones; padding is masked out before
# the product so results are exact.

MILLER_TILE = 16
FEXP_TILE = 8


@functools.lru_cache(maxsize=None)
def _pad_pair_consts():
    return (
        encode_g1([hm.G1_GEN])[0],
        encode_g2([hm.G2_GEN])[0],
    )


@jax.jit
def _product_rows(f):
    """(B, K, 6, 2, L) -> (B, 6, 2, L): per-row product of K GT legs."""
    while f.shape[1] > 1:
        half = f.shape[1] // 2
        rest = f[:, 2 * half :]
        f = tw.fp12_mul(f[:, :half], f[:, half : 2 * half])
        if rest.shape[1]:
            f = jnp.concatenate([f, rest], axis=1)
    return f[:, 0]


def _miller_tiles(Pf, Qf, start: int, stop: int):
    """Sequential miller-tile walk over [start, stop) tile indices."""
    return [
        np.asarray(
            miller_loop(
                jnp.asarray(Pf[t : t + MILLER_TILE]),
                jnp.asarray(Qf[t : t + MILLER_TILE]),
            )
        )
        for t in range(start * MILLER_TILE, stop * MILLER_TILE, MILLER_TILE)
    ]


def _fexp_tiles(f, start: int, stop: int):
    """Sequential product+final-exp walk over [start, stop) tile indices."""
    return [
        np.asarray(final_exp(_product_rows(jnp.asarray(f[t : t + FEXP_TILE]))))
        for t in range(start * FEXP_TILE, stop * FEXP_TILE, FEXP_TILE)
    ]


def _sharded_tiles(fn, ntiles: int, workers: int, *args):
    """The dp x mp leg of the per-shard stage-tile dispatch: delegates
    to `stages.run_tile_spans` (the one sharded span-dispatch mechanism,
    degrade chain included) under the pairing-plane counters."""
    from . import stages as st

    return st.run_tile_spans(
        fn, ntiles, workers, *args,
        calls=mx.counter("pairing.staged.sharded_calls"),
        shards=mx.counter("pairing.staged.shards"),
        what="pairing.staged",
    )


def pairing_product_staged(Ps, Qs, inf_mask=None, dp=None, mp=None):
    """prod_k e(P_k, Q_k) per row via the compile-once tile programs.

    Ps: (B, K, 2, L), Qs: (B, K, 2, 2, L) Montgomery affine; inf_mask
    (B, K) True legs contribute the identity. Returns (B, 6, 2, L) GT as
    a host numpy array.

    `dp` x `mp` (default: the ambient mesh env, `FTS_MESH_DEVICES` /
    `FTS_MESH_MP`) shard the dispatch: the flat (row, leg) miller-tile
    stream splits into dp*mp contiguous spans and the final-exp tile
    stream into dp spans, each walked through the SAME tile executables
    from worker threads — the host-dispatch expression of "dp over rows,
    mp over pairing legs". Zero new XLA programs; bit-identical output.
    """
    from . import stages as st

    Ps = np.asarray(Ps)
    Qs = np.asarray(Qs)
    B, K = Ps.shape[0], Ps.shape[1]
    L = Ps.shape[-1]
    if B == 0:
        return np.zeros((0, 6, 2, L), dtype=np.int32)
    dp = st.default_dp() if dp is None else max(1, int(dp))
    mp = st.default_mp() if mp is None else max(1, int(mp))
    N = B * K
    Pf = Ps.reshape(N, 2, L)
    Qf = Qs.reshape(N, 2, 2, L)
    mask = np.zeros(N, dtype=bool)
    if inf_mask is not None:
        mask |= np.asarray(inf_mask).reshape(N)
    pad = (-N) % MILLER_TILE
    if pad:
        Pg, Qg = _pad_pair_consts()
        Pf = np.concatenate([Pf, np.broadcast_to(Pg, (pad, 2, L))])
        Qf = np.concatenate([Qf, np.broadcast_to(Qg, (pad, 2, 2, L))])
        mask = np.concatenate([mask, np.ones(pad, dtype=bool)])
    mx.counter("pairing.staged.calls").inc()
    mx.counter("pairing.staged.rows").inc(B)
    mx.counter("pairing.staged.legs").inc(N)
    mx.counter("pairing.staged.miller_tiles").inc((N + pad) // MILLER_TILE)
    with mx.span("pairing.product_staged", rows=B, legs_per_row=K):
        # all inter-stage glue (concat/mask/reshape/pad) stays in numpy so
        # the ONLY device programs are the three tile kernels — no
        # per-shape concatenate/select programs on the accelerator
        with devobs.dispatch(
            "miller_tile", rows=N, padded_rows=pad, dp=dp, mp=mp
        ), mx.timed("pairing.staged.miller.seconds"):
            f = np.concatenate(
                _sharded_tiles(
                    _miller_tiles, (N + pad) // MILLER_TILE, dp * mp, Pf, Qf
                ),
                axis=0,
            )
        # numpy constant (not tw.fp12_ones()): keeps the mask/pad glue off
        # the device so no per-shape broadcast program ever compiles
        one_np = tw.fp12_one_np()
        f[mask] = one_np
        f = f[:N].reshape(B, K, 6, 2, L)
        # pad rows BEFORE the product so both the per-K product program and
        # the final-exp program see only (FEXP_TILE, ...) shapes
        padB = (-B) % FEXP_TILE
        if padB:
            f = np.concatenate(
                [f, np.broadcast_to(one_np, (padB, K, 6, 2, L))], axis=0
            )
        mx.counter("pairing.staged.fexp_tiles").inc((B + padB) // FEXP_TILE)
        with devobs.dispatch(
            "fexp_tile", rows=B, padded_rows=padB, dp=dp
        ), mx.timed("pairing.staged.product_fexp.seconds"):
            gts = _sharded_tiles(
                _fexp_tiles, (B + padB) // FEXP_TILE, dp, f
            )
    return np.concatenate(gts, axis=0)[:B]


def decode_gt(arr):
    """Device GT tensor -> host flat fp12 tuples (hostmath layout)."""
    return tw.decode_fp12(arr if arr.ndim == 4 else arr[None])
