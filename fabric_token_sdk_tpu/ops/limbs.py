"""Radix-2^8 limb-vector arithmetic in int32 tensors.

A k-bit integer is a little-endian vector of 8-bit limbs stored as int32.
All intermediates are engineered to stay inside int32:

* 8x8-bit partial products are < 2^16,
* a product column accumulates at most 2*NLIMBS-1 = 63 of them plus a
  carried-in limb: < 2^23,
* carry normalization uses arithmetic shifts (floor semantics), so signed
  intermediates from subtraction are handled exactly — provided the TOTAL
  value is non-negative (callers add a modulus before subtracting).

These helpers are modulus-agnostic; ``field.py`` builds Montgomery fields
on top.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

RADIX_BITS = 8
RADIX = 1 << RADIX_BITS
MASK = RADIX - 1
NLIMBS = 32  # 256-bit elements


# ---------------------------------------------------------------- host conv

def int_to_limbs(x: int, nlimbs: int = NLIMBS) -> np.ndarray:
    """Host: python int -> little-endian limb vector."""
    if x < 0:
        raise ValueError("int_to_limbs: negative value")
    out = np.zeros(nlimbs, dtype=np.int32)
    for i in range(nlimbs):
        out[i] = x & MASK
        x >>= RADIX_BITS
    if x:
        raise ValueError("int_to_limbs: value does not fit")
    return out


def limbs_to_int(v) -> int:
    """Host: limb vector (canonical or not) -> python int."""
    arr = np.asarray(v).astype(object)
    return int(sum(int(arr[..., i]) << (RADIX_BITS * i) for i in range(arr.shape[-1])))


def ints_to_limbs(xs, nlimbs: int = NLIMBS) -> np.ndarray:
    """Host: iterable of ints -> (N, nlimbs) array."""
    return np.stack([int_to_limbs(x, nlimbs) for x in xs])


def batch_limbs_to_ints(arr) -> list:
    a = np.asarray(arr)
    flat = a.reshape(-1, a.shape[-1])
    return [limbs_to_int(row) for row in flat]


# ---------------------------------------------------------------- carries

def carry_pass(x):
    """One carry-propagation pass (signed, floor-shift semantics)."""
    c = x >> RADIX_BITS
    rem = x - (c << RADIX_BITS)
    shifted = jnp.concatenate(
        [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
    )
    return rem + shifted


def normalize(x):
    """Propagate carries until every limb is canonical (in [0, RADIX)).

    General data-dependent form (while_loop) — used only off the hot path.
    The represented TOTAL must be non-negative and fit the vector width.
    """

    def cond(v):
        return jnp.any((v < 0) | (v > MASK))

    return jax.lax.while_loop(cond, carry_pass, x)


def _roll_up(a, s: int):
    """a[i - s] with zeros shifted in (along the limb axis)."""
    pad = jnp.zeros_like(a[..., :s])
    return jnp.concatenate([pad, a[..., :-s]], axis=-1)


def normalize_fixed(x, passes: int):
    """Branch-free carry normalization for NON-NEGATIVE digit vectors.

    `passes` plain carry passes must bring every digit into [0, RADIX]
    (bound: B -> MASK + (B >> RADIX_BITS)); the residual +1 carries are then
    resolved exactly with a Kogge-Stone carry-lookahead (log-depth, no
    data-dependent control flow — the TPU-friendly form).
    """
    for _ in range(passes):
        x = carry_pass(x)
    # digits now in [0, RADIX]; resolve unit carries via (generate, propagate)
    g = (x > MASK).astype(jnp.int32)
    p = (x == MASK).astype(jnp.int32)
    n = x.shape[-1]
    s = 1
    while s < n:
        g = g | (p & _roll_up(g, s))
        p = p & _roll_up(p, s)
        s <<= 1
    c_in = _roll_up(g, 1)
    t = x + c_in
    return t - ((t > MASK).astype(jnp.int32) << RADIX_BITS)


# ---------------------------------------------------------------- add / cmp

def add(x, y):
    """Limb-wise add; caller normalizes/reduces."""
    return x + y


def compare_ge(x, y):
    """Lexicographic >= of two canonical limb vectors. Shapes broadcast."""
    x, y = jnp.broadcast_arrays(x, y)
    neq = x != y
    # index of the most significant differing limb (0 if none differ)
    msd = x.shape[-1] - 1 - jnp.argmax(neq[..., ::-1], axis=-1)
    xd = jnp.take_along_axis(x, msd[..., None], axis=-1)[..., 0]
    yd = jnp.take_along_axis(y, msd[..., None], axis=-1)[..., 0]
    return jnp.where(jnp.any(neq, axis=-1), xd >= yd, True)


def is_zero(x):
    return jnp.all(x == 0, axis=-1)


# ---------------------------------------------------------------- multiply

@functools.lru_cache(maxsize=None)
def _conv_matrix(nx: int, ny: int):
    """One-hot (nx*ny, nx+ny+1) matrix mapping outer-product cell (i,j) to
    product column i+j. Turns schoolbook multiplication into one dense
    matmul — the MXU-friendly formulation of limb convolution."""
    k = nx + ny + 1
    c = np.zeros((nx, ny, k), dtype=np.int32)
    for i in range(nx):
        for j in range(ny):
            c[i, j, i + j] = 1
    # NOTE: return the numpy constant — converting to a jax array here would
    # cache a tracer when first called under an active trace.
    return c.reshape(nx * ny, k)


def mul_full(x, y):
    """Full product of two limb vectors -> nx+ny+1 canonical limbs.

    Outer products are < 2^16 and each column sum < 2^23: all values are
    exactly representable in float32, so the column contraction runs as an
    f32 matmul (CPU: real GEMM; TPU: MXU with HIGHEST precision) and is
    cast back to int32 losslessly. Fully branch-free.
    """
    nx, ny = x.shape[-1], y.shape[-1]
    prod = x[..., :, None] * y[..., None, :]  # int32, exact (< 2^16)
    flat = prod.reshape(prod.shape[:-2] + (nx * ny,)).astype(jnp.float32)
    acc = jax.lax.dot_general(
        flat,
        _conv_matrix(nx, ny).astype(np.float32),
        (((flat.ndim - 1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )
    return normalize_fixed(acc.astype(jnp.int32), 3)


def mul_low(x, y, keep=None):
    """Low `keep` limbs of the product (i.e. product mod RADIX^keep)."""
    keep = x.shape[-1] if keep is None else keep
    return mul_full(x, y)[..., :keep]
