"""Warmup precompiler: AOT-compile every canonical verify-plane program.

The staged execution model (`ops/stages.py`, `ops/pairing.py` tiles)
makes the verifier's distinct-program set a small constant; this module
compiles that whole set ahead of time — populating the persistent XLA
compilation cache (`FTS_TPU_JAX_CACHE`, default `~/.cache/fts_tpu_jax`) —
so no verify, test, or benchmark ever pays a surprise giant compile
mid-flight. After `warmup()` (or `python cmd/ftswarmup.py`), a
`BatchedTransferVerifier.verify` recompiles nothing: every program loads
as a `jax.compilation_cache.cache_hits` hit (`cache_misses` stays 0).

Entry points:
  * `warmup()`               — library call (bench.py, pytest fixture)
  * `cmd/ftswarmup.py`       — CLI wrapper
  * `FTS_WARMUP=1 pytest`    — opt-in session fixture (tests/conftest.py)
"""

from __future__ import annotations

import time
from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp

from . import limbs as lb, pairing as pr, stages as st
from ..utils import devobs
from ..utils import metrics as mx

_CACHE_COUNTERS = (
    "jax.compilation_cache.cache_hits",
    "jax.compilation_cache.cache_misses",
)
_COMPILES = "jax.core.compile.backend_compile_duration.seconds"


def pairing_programs() -> Iterable[Tuple[str, object, tuple]]:
    """The staged pairing tile programs (miller / per-K product /
    final-exp), canonical shapes. K covers every verifier pairing product:
    2 legs (Pointcheval-Sanders, and the membership GT pre-commitment on
    the prove side) and 4 legs (membership verify)."""
    L = lb.NLIMBS
    yield (
        "miller_tile",
        pr.miller_loop,
        ((pr.MILLER_TILE, 2, L), (pr.MILLER_TILE, 2, 2, L)),
    )
    for k in (2, 4):
        yield (f"gt_product_k{k}_tile", pr._product_rows, ((pr.FEXP_TILE, k, 6, 2, L),))
    yield ("final_exp_tile", pr.final_exp, ((pr.FEXP_TILE, 6, 2, L),))


# Program-set classification for `cmd/ftswarmup.py --list` and the
# `--no-prover` opt-out. The batched prover (`crypto/batch_prove.py`) is
# BY CONSTRUCTION a composition of the same canonical tiles as the
# verify plane — its only private program is the Jacobian add tile (the
# signature-obfuscation step S'' = S' + P^bf); everything else is
# shared, which is what lets the post-warmup zero-cache-miss guarantee
# extend to proving without growing the program set.
PROVER_PROGRAMS = frozenset(
    {
        "g1_msm1_tile", "g1_msm2_tile", "g1_msm3_tile",
        "g1_mul_tile", "g1_add_tile",
        "g2_mul_tile", "g2_add_tile", "g2_to_affine_tile",
        "miller_tile", "gt_product_k2_tile", "final_exp_tile",
    }
)
PROVER_ONLY_PROGRAMS = frozenset({"g1_add_tile"})


def program_planes(name: str) -> str:
    """'verify', 'prove', or 'verify+prove' for a canonical program."""
    if name in PROVER_ONLY_PROGRAMS:
        return "prove"
    return "verify+prove" if name in PROVER_PROGRAMS else "verify"


def all_programs(include_pairing: bool = True, include_prover: bool = True):
    progs = list(st.stage_programs())
    if include_pairing:
        progs += list(pairing_programs())
    if not include_prover:
        progs = [p for p in progs if p[0] not in PROVER_ONLY_PROGRAMS]
    return progs


def warmup(
    include_pairing: bool = True,
    persist_all: bool = True,
    progress: Optional[callable] = None,
    include_prover: bool = True,
) -> dict:
    """AOT-lower and compile every canonical program; returns a summary.

    persist_all drops `jax_persistent_cache_min_compile_time_secs` to 0 so
    even fast-compiling tile programs land in the persistent cache — the
    guarantee that a LATER process replays the whole verify plane from
    cache hits alone (cache_misses stays 0; nothing recompiles).
    """
    prev_min_compile = None
    if persist_all:
        try:
            prev_min_compile = jax.config.jax_persistent_cache_min_compile_time_secs
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception:  # older jax without the knob
            pass

    before = {c: mx.REGISTRY.counter(c).value for c in _CACHE_COUNTERS}
    compiles_before = mx.REGISTRY.histogram(_COMPILES).count
    programs = []
    t_total = time.time()
    try:
        with mx.span("warmup.precompile", include_pairing=include_pairing):
            for name, fn, shapes in all_programs(include_pairing, include_prover):
                specs = [jax.ShapeDtypeStruct(s, jnp.int32) for s in shapes]
                t0 = time.time()
                # attribute the compile/cache events this AOT compile
                # fires to the canonical program name — the ledger join
                # between jax.monitoring and the program registry
                with devobs.attribute(name):
                    fn.lower(*specs).compile()
                dt = time.time() - t0
                mx.counter("warmup.programs").inc()
                mx.REGISTRY.histogram("warmup.program.seconds").observe(dt)
                programs.append({"name": name, "seconds": round(dt, 3)})
                if progress is not None:
                    progress(name, dt)
    finally:
        # confine persist-everything to the warmup set: later incidental
        # compiles go back to the configured persistence threshold
        if prev_min_compile is not None:
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    prev_min_compile,
                )
            except Exception:
                pass
    total = time.time() - t_total
    summary = {
        "programs": len(programs),
        "seconds": round(total, 3),
        "backend_compiles": mx.REGISTRY.histogram(_COMPILES).count - compiles_before,
        "per_program": programs,
    }
    for c in _CACHE_COUNTERS:
        summary[c.rsplit(".", 1)[-1]] = mx.REGISTRY.counter(c).value - before[c]
    mx.gauge("warmup.seconds").set(round(total, 3))
    return summary
