"""Batched extension-field towers on limb tensors (device).

Layouts (leading axes = batch):
  Fp2  : (..., 2, L)       c0 + c1*i,          i^2 = -1
  Fp12 : (..., 6, 2, L)    flat w-basis, w^6 = XI = 9 + i
The tower view Fp12 = Fp6[w]/(w^2 - v), Fp6 = Fp2[v]/(v^3 - XI) is
recovered by index parity: c0 = x[..., 0::2], c1 = x[..., 1::2]
(matching crypto.hostmath's flat representation exactly).

TPU-first structure: every composite op STACKS its independent base-field
multiplications into one batched Montgomery multiply (one limb-convolution
matmul round instead of dozens of small ones). An Fp12 multiply costs a
single FP.mul call on a 54x-wider batch — this keeps XLA graphs small and
feeds the MXU large uniform contractions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as lb
from .field import FP
from ..crypto import hostmath as hm


# ---------------------------------------------------------------- Fp2

def fp2_add(x, y):
    return FP.add(x, y)


def fp2_sub(x, y):
    return FP.sub(x, y)


def fp2_neg(x):
    return FP.neg(x)


@jax.jit
def fp2_conj(x):
    return jnp.stack([x[..., 0, :], FP.neg(x[..., 1, :])], axis=-2)


def _mul_components(x, y):
    """Karatsuba component products for a batch of fp2 pairs:
    returns (a0*b0, a1*b1, (a0+a1)*(b0+b1)) via ONE stacked FP.mul."""
    x, y = jnp.broadcast_arrays(x, y)
    a0, a1 = x[..., 0, :], x[..., 1, :]
    b0, b1 = y[..., 0, :], y[..., 1, :]
    X = jnp.stack([a0, a1, FP.add(a0, a1)])
    Y = jnp.stack([b0, b1, FP.add(b0, b1)])
    v = FP.mul(X, Y)
    return v[0], v[1], v[2]


@jax.jit
def fp2_mul(x, y):
    v0, v1, v01 = _mul_components(x, y)
    return jnp.stack([FP.sub(v0, v1), FP.sub(v01, FP.add(v0, v1))], axis=-2)


@jax.jit
def fp2_sqr(x):
    a0, a1 = x[..., 0, :], x[..., 1, :]
    X = jnp.stack([FP.add(a0, a1), a0])
    Y = jnp.stack([FP.sub(a0, a1), a1])
    v = FP.mul(X, Y)
    return jnp.stack([v[0], FP.add(v[1], v[1])], axis=-2)


@jax.jit
def fp2_scale(x, k):
    """Multiply both components by a base-field element (broadcast)."""
    X = jnp.stack([x[..., 0, :], x[..., 1, :]])
    K = jnp.stack([k, k])
    v = FP.mul(X, K)
    return jnp.stack([v[0], v[1]], axis=-2)


@jax.jit
def fp2_mul_xi(x):
    """Multiply by XI = 9 + i: (9 a0 - a1) + (a0 + 9 a1) i. Add-only."""
    a0, a1 = x[..., 0, :], x[..., 1, :]
    t0 = a0
    for _ in range(3):
        t0 = FP.add(t0, t0)
    nine_a0 = FP.add(t0, a0)
    t1 = a1
    for _ in range(3):
        t1 = FP.add(t1, t1)
    nine_a1 = FP.add(t1, a1)
    return jnp.stack([FP.sub(nine_a0, a1), FP.add(a0, nine_a1)], axis=-2)


@jax.jit
def fp2_inv(x):
    """(a - bi) / (a^2 + b^2): one base-field inversion."""
    a0, a1 = x[..., 0, :], x[..., 1, :]
    sq = FP.mul(jnp.stack([a0, a1]), jnp.stack([a0, a1]))
    n = FP.inv(FP.add(sq[0], sq[1]))
    v = FP.mul(jnp.stack([a0, a1]), jnp.stack([n, n]))
    return jnp.stack([v[0], FP.neg(v[1])], axis=-2)


def fp2_is_zero(x):
    return FP.is_zero(x[..., 0, :]) & FP.is_zero(x[..., 1, :])


def fp2_eq(x, y):
    return FP.eq(x[..., 0, :], y[..., 0, :]) & FP.eq(x[..., 1, :], y[..., 1, :])


def fp2_zeros(shape=()):
    return FP.zeros(tuple(shape) + (2,))


def _fp2_one_np() -> np.ndarray:
    out = np.zeros((2, lb.NLIMBS), dtype=np.int32)
    out[0] = np.asarray(FP.one_mont)
    return out


def fp2_ones(shape=()):
    return jnp.broadcast_to(
        jnp.asarray(_fp2_one_np()), tuple(shape) + (2, lb.NLIMBS)
    ).astype(jnp.int32)


# ------------------------------------------------------- host conversions

def encode_fp2(vals) -> np.ndarray:
    """Host fp2 tuples [(a,b), ...] -> (N, 2, L) Montgomery tensor.
    Pure numpy: safe to call during tracing (constants fold)."""
    Rm = 1 << (lb.RADIX_BITS * lb.NLIMBS)
    out = np.zeros((len(vals), 2, lb.NLIMBS), dtype=np.int32)
    for i, (a, b) in enumerate(vals):
        out[i, 0] = lb.int_to_limbs(a * Rm % hm.P)
        out[i, 1] = lb.int_to_limbs(b * Rm % hm.P)
    return out


_RINV = pow(1 << (lb.RADIX_BITS * lb.NLIMBS), -1, hm.P)


def decode_fp2(arr):
    """Montgomery limb tensor -> host fp2 int tuples.

    Pure host arithmetic (limb recomposition + one modular multiply by
    R^-1): decoding compiles no device program, so batched verifiers stay
    shape-invariant in their XLA program set."""
    a = np.asarray(arr).reshape(-1, lb.NLIMBS)
    flat = [lb.limbs_to_int(row) * _RINV % hm.P for row in a]
    return [(flat[2 * i], flat[2 * i + 1]) for i in range(len(flat) // 2)]


def encode_fp12(vals) -> np.ndarray:
    """Host flat fp12 tuples (6 x fp2) -> (N, 6, 2, L)."""
    return np.stack([encode_fp2(list(v)) for v in vals])


def decode_fp12(arr):
    a = np.asarray(arr)
    pairs = decode_fp2(a.reshape(-1, 2, lb.NLIMBS))
    return [tuple(pairs[6 * i : 6 * i + 6]) for i in range(len(pairs) // 6)]


# ---------------------------------------------------------------- Fp6
# (..., 3, 2, L): a0 + a1 v + a2 v^2. All six Karatsuba cross-products are
# evaluated in ONE stacked fp2_mul.

def _fp6_mul(a, b):
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    b0, b1, b2 = b[..., 0, :, :], b[..., 1, :, :], b[..., 2, :, :]
    X = jnp.stack([a0, a1, a2, FP.add(a1, a2), FP.add(a0, a1), FP.add(a0, a2)])
    Y = jnp.stack([b0, b1, b2, FP.add(b1, b2), FP.add(b0, b1), FP.add(b0, b2)])
    t = fp2_mul(X, Y)
    t0, t1, t2, t12, t01, t02 = (t[i] for i in range(6))
    c0 = fp2_add(t0, fp2_mul_xi(fp2_sub(t12, fp2_add(t1, t2))))
    c1 = fp2_add(fp2_sub(t01, fp2_add(t0, t1)), fp2_mul_xi(t2))
    c2 = fp2_add(fp2_sub(t02, fp2_add(t0, t2)), t1)
    return jnp.stack([c0, c1, c2], axis=-3)


def _fp6_mul_v(a):
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    return jnp.stack([fp2_mul_xi(a2), a0, a1], axis=-3)


def _fp6_neg(a):
    return FP.neg(a)


def _fp6_sub(a, b):
    return FP.sub(a, b)


def _fp6_inv(a):
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    s = fp2_mul(
        jnp.stack([a0, a2, a1, a1, a0, a0]),
        jnp.stack([a0, a2, a1, a2, a1, a2]),
    )
    a0a0, a2a2, a1a1, a1a2, a0a1, a0a2 = (s[i] for i in range(6))
    c0 = fp2_sub(a0a0, fp2_mul_xi(a1a2))
    c1 = fp2_sub(fp2_mul_xi(a2a2), a0a1)
    c2 = fp2_sub(a1a1, a0a2)
    u = fp2_mul(jnp.stack([a2, a1, a0]), jnp.stack([c1, c2, c0]))
    t = fp2_add(fp2_mul_xi(fp2_add(u[0], u[1])), u[2])
    tinv = fp2_inv(t)
    r = fp2_mul(
        jnp.stack([c0, c1, c2]),
        jnp.stack([tinv, tinv, tinv]),
    )
    return jnp.stack([r[0], r[1], r[2]], axis=-3)


# ---------------------------------------------------------------- Fp12

def _split(x):
    return x[..., 0::2, :, :], x[..., 1::2, :, :]


def _join(c0, c1):
    n = c0.shape[:-3]
    out = jnp.stack([c0, c1], axis=-3)
    return out.reshape(n + (6, 2, lb.NLIMBS))


@jax.jit
def fp12_mul(x, y):
    """One stacked _fp6_mul (3 products) = one FP.mul on a 54x batch."""
    x0, x1 = _split(x)
    y0, y1 = _split(y)
    A = jnp.stack([x0, x1, FP.add(x0, x1)])
    B = jnp.stack([y0, y1, FP.add(y0, y1)])
    V = _fp6_mul(A, B)
    v0, v1, v01 = V[0], V[1], V[2]
    c0 = FP.add(v0, _fp6_mul_v(v1))
    c1 = _fp6_sub(v01, FP.add(v0, v1))
    return _join(c0, c1)


@jax.jit
def fp12_sqr(x):
    x0, x1 = _split(x)
    A = jnp.stack([x0, FP.add(x0, x1)])
    B = jnp.stack([x1, FP.add(x0, _fp6_mul_v(x1))])
    V = _fp6_mul(A, B)
    v, t0 = V[0], V[1]
    c0 = _fp6_sub(_fp6_sub(t0, v), _fp6_mul_v(v))
    c1 = FP.add(v, v)
    return _join(c0, c1)


@jax.jit
def fp12_conj(x):
    sign = np.array([1, -1, 1, -1, 1, -1])
    return jnp.where((sign > 0)[:, None, None], x, FP.neg(x))


@jax.jit
def fp12_inv(x):
    x0, x1 = _split(x)
    S = _fp6_mul(jnp.stack([x0, x1]), jnp.stack([x0, x1]))
    n = _fp6_sub(S[0], _fp6_mul_v(S[1]))
    ninv = _fp6_inv(n)
    R = _fp6_mul(jnp.stack([x0, x1]), jnp.stack([ninv, ninv]))
    return _join(R[0], _fp6_neg(R[1]))


def fp12_one_np() -> np.ndarray:
    """The GT/Fp12 identity as a HOST numpy constant (Montgomery limbs) —
    for numpy glue paths that must not touch the device."""
    out = np.zeros((6, 2, lb.NLIMBS), dtype=np.int32)
    out[0, 0] = np.asarray(FP.one_mont)
    return out


_fp12_one_np = fp12_one_np  # internal alias (fp12_ones below)


def fp12_ones(shape=()):
    return jnp.broadcast_to(
        jnp.asarray(_fp12_one_np()), tuple(shape) + (6, 2, lb.NLIMBS)
    ).astype(jnp.int32)


@jax.jit
def fp12_eq(x, y):
    """Equality in the redundant [0, 2p) coefficient domain: canonicalize
    every coefficient before comparing (v and v+p must test equal)."""
    return jnp.all(FP.cond_sub_p(x) == FP.cond_sub_p(y), axis=(-1, -2, -3))


@jax.jit
def fp12_is_one(x):
    return fp12_eq(x, jnp.broadcast_to(fp12_ones(), x.shape).astype(jnp.int32))


# ---------------------------------------------------------------- frobenius

@functools.lru_cache(maxsize=None)
def _frob_gammas(n: int) -> np.ndarray:
    gs = [hm.fp2_pow(hm.XI, j * (hm.P**n - 1) // 6) for j in range(6)]
    return encode_fp2(gs)


@functools.partial(jax.jit, static_argnums=1)
def fp12_frobenius(x, n: int = 1):
    gam = jnp.asarray(_frob_gammas(n))
    c = x if n % 2 == 0 else jnp.concatenate(
        [x[..., :, 0:1, :], FP.neg(x[..., :, 1:2, :])], axis=-2
    )
    return fp2_mul(c, gam)


# ---------------------------------------------------------------- sparse mul

@jax.jit
def fp12_mul_sparse013(f, l0, l1, l3):
    """f * (l0 + l1 w + l3 w^3), l* in Fp2 — all 18 products stacked."""
    rows = [f[..., j, :, :] for j in range(6)]
    X = jnp.stack(
        [rows[j] for j in range(6)]
        + [rows[(j - 1) % 6] for j in range(6)]
        + [rows[(j - 3) % 6] for j in range(6)]
    )
    shape = X.shape[1:]
    Y = jnp.stack(
        [jnp.broadcast_to(l0, shape)] * 6
        + [jnp.broadcast_to(l1, shape)] * 6
        + [jnp.broadcast_to(l3, shape)] * 6
    )
    prod = fp2_mul(X, Y)
    out = []
    for j in range(6):
        t = prod[j]
        u = prod[6 + j]
        if j - 1 < 0:
            u = fp2_mul_xi(u)
        t = fp2_add(t, u)
        u = prod[12 + j]
        if j - 3 < 0:
            u = fp2_mul_xi(u)
        t = fp2_add(t, u)
        out.append(t)
    return jnp.stack(out, axis=-3)
