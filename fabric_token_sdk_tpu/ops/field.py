"""Batched Montgomery prime-field arithmetic on limb tensors.

Reference counterpart: IBM mathlib's Zr/Fp scalar ops (used throughout
token/core/zkatdlog/crypto). Here a field is a `FieldSpec` of baked numpy
limb constants; every op is branch-free, batched over leading axes, and
jit-safe. Elements live in Montgomery form (x·R mod p, R = 2^256) as
(..., 32) int32 limb tensors.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import limbs as lb
from ..crypto import hostmath as hm


def _opjit(fn=None, *, static=()):
    """jit a FieldSpec method with `self` static (specs are singletons)."""

    def wrap(f):
        return jax.jit(f, static_argnums=(0,) + tuple(static))

    return wrap(fn) if fn is not None else wrap


@dataclass(frozen=True, eq=False)
class FieldSpec:
    """A prime field with Montgomery constants baked as limb arrays."""

    name: str
    modulus: int
    nlimbs: int = lb.NLIMBS
    p_limbs: np.ndarray = field(init=False, repr=False)
    twop_limbs: np.ndarray = field(init=False, repr=False)
    pprime_limbs: np.ndarray = field(init=False, repr=False)  # -p^-1 mod R
    r2_limbs: np.ndarray = field(init=False, repr=False)  # R^2 mod p
    one_mont: np.ndarray = field(init=False, repr=False)  # R mod p

    def __post_init__(self):
        R = 1 << (lb.RADIX_BITS * self.nlimbs)
        # the redundant-domain REDC design needs 4p <= R so that products of
        # two [0, 2p) elements satisfy T < pR and outputs stay in [0, 2p)
        if 4 * self.modulus > R or self.modulus % 2 == 0:
            raise ValueError("modulus must be odd with 4p within the limb width")
        object.__setattr__(self, "p_limbs", lb.int_to_limbs(self.modulus, self.nlimbs))
        object.__setattr__(self, "twop_limbs", lb.int_to_limbs(2 * self.modulus, self.nlimbs))
        pprime = (-pow(self.modulus, -1, R)) % R
        object.__setattr__(self, "pprime_limbs", lb.int_to_limbs(pprime, self.nlimbs))
        object.__setattr__(self, "r2_limbs", lb.int_to_limbs(R * R % self.modulus, self.nlimbs))
        object.__setattr__(self, "one_mont", lb.int_to_limbs(R % self.modulus, self.nlimbs))

    # ------------------------------------------------------------- reduce
    #
    # Elements live in the REDUNDANT domain [0, 2p): REDC maps products of
    # two such elements back into it (4p^2 < p*2^W), so `mul` needs no
    # final subtraction, and add/sub need only a single select-subtract
    # driven by the top limb of a complement addition — no lexicographic
    # comparisons anywhere on the hot path. Canonical [0, p) form is
    # produced lazily (`canon`) for equality/decoding.

    def _select_sub(self, x, m_limbs: np.ndarray, passes: int):
        """Given digits of x (value < 2^W + range), return x - m if
        x >= m else x, via x + comp(m) + 1 over W+1 limbs: the top limb
        is 1 exactly when x >= m."""
        # numpy constant: comp(m) with the +1 folded into limb 0, plus a
        # zero top limb (branch- and scatter-free)
        compp1 = np.concatenate([lb.MASK - m_limbs, [0]]).astype(np.int32)
        compp1[0] += 1
        s = jnp.concatenate([x, jnp.zeros_like(x[..., :1])], axis=-1) + compp1
        s = lb.normalize_fixed(s, passes)
        ge = s[..., self.nlimbs :][..., 0] > 0
        return jnp.where(ge[..., None], s[..., : self.nlimbs], lb.normalize_fixed(x, passes))

    @_opjit
    def cond_sub_p(self, x):
        """Redundant [0, 2p) -> canonical [0, p)."""
        return self._select_sub(x, self.p_limbs, 1)

    def canon(self, x):
        return self.cond_sub_p(x)

    # ------------------------------------------------------------- ring ops

    @_opjit
    def add(self, x, y):
        """[0,2p) x [0,2p) -> [0,2p): add then select-subtract 2p."""
        return self._select_sub(x + y, self.twop_limbs, 2)

    @_opjit
    def sub(self, x, y):
        """x - y in [0, 2p), borrow-free.

        s = x + comp(y) + 1 over W+1 limbs has value x - y + 2^W; its top
        limb says whether x >= y. If so the low limbs ARE x - y; otherwise
        add 2p to them (total then overflows 2^W exactly once)."""
        comp_y1 = (lb.MASK - y) + np.concatenate([[1], np.zeros(self.nlimbs - 1, np.int32)]).astype(np.int32)
        s = jnp.concatenate(
            [x + comp_y1, jnp.zeros_like(x[..., :1])], axis=-1
        )  # digits <= 511
        s = lb.normalize_fixed(s, 1)
        x_ge_y = s[..., self.nlimbs :][..., 0] > 0
        s_low = s[..., : self.nlimbs]
        t = jnp.concatenate(
            [s_low + self.twop_limbs, jnp.zeros_like(x[..., :1])], axis=-1
        )
        t_low = lb.normalize_fixed(t, 1)[..., : self.nlimbs]
        return jnp.where(x_ge_y[..., None], s_low, t_low)

    @_opjit
    def neg(self, x):
        return self.sub(jnp.zeros_like(x), x)

    @_opjit
    def mul(self, x, y):
        """Montgomery product: REDC(x*y); stays in [0, 2p)."""
        n = self.nlimbs
        t = lb.mul_full(x, y)  # (..., 2n+1) canonical digits
        m = lb.mul_low(t[..., :n], self.pprime_limbs, keep=n)
        mp = lb.mul_full(m, self.p_limbs)  # (..., 2n+1)
        pad = [(0, 0)] * (t.ndim - 1) + [(0, 1)]
        acc = jnp.pad(t, pad) + jnp.pad(mp, pad)  # digits <= 510
        return lb.normalize_fixed(acc, 1)[..., n : 2 * n]

    @_opjit
    def sqr(self, x):
        return self.mul(x, x)

    @_opjit(static=(2,))
    def pow_const(self, x, e: int):
        """x^e for a python-int exponent, via scan over its bits (MSB first)."""
        if e == 0:
            return jnp.broadcast_to(jnp.asarray(self.one_mont), x.shape).astype(jnp.int32)
        bits = np.array([int(b) for b in bin(e)[2:]], dtype=np.int32)

        def step(acc, bit):
            acc = self.mul(acc, acc)
            acc = jnp.where(bit > 0, self.mul(acc, x), acc)
            return acc, None

        init = jnp.broadcast_to(jnp.asarray(self.one_mont), x.shape).astype(jnp.int32)
        out, _ = lax.scan(step, init, jnp.asarray(bits))
        return out

    @_opjit
    def inv(self, x):
        """Montgomery inverse by Fermat: x^(p-2). x must be nonzero."""
        return self.pow_const(x, self.modulus - 2)

    @_opjit(static=(2,))
    def mul_small(self, x, k: int):
        """x * k for a small static non-negative int, via double-and-add —
        every intermediate stays inside the [0, 2p) domain."""
        if k < 0:
            raise ValueError("mul_small: k must be non-negative")
        if k == 0:
            return jnp.zeros_like(x)
        acc = None
        for bit in bin(k)[2:]:
            acc = self.add(acc, acc) if acc is not None else None
            if bit == "1":
                acc = x if acc is None else self.add(acc, x)
        return acc

    # ------------------------------------------------------------- domain

    @_opjit
    def to_mont(self, x):
        return self.mul(x, jnp.asarray(self.r2_limbs))

    @_opjit
    def from_mont(self, x):
        one = np.zeros(self.nlimbs, dtype=np.int32)
        one[0] = 1
        return self.mul(x, jnp.broadcast_to(jnp.asarray(one), x.shape))

    # ------------------------------------------------------------- host I/O

    def encode(self, values) -> jnp.ndarray:
        """Host ints -> Montgomery limb tensor (N, nlimbs)."""
        vals = [v % self.modulus for v in values]
        raw = lb.ints_to_limbs(vals, self.nlimbs)
        return self.to_mont(jnp.asarray(raw))

    def encode_scalar(self, v: int) -> jnp.ndarray:
        return self.encode([v])[0]

    def decode(self, x) -> list:
        """Montgomery limb tensor -> host ints (canonicalized)."""
        return lb.batch_limbs_to_ints(np.asarray(self.cond_sub_p(self.from_mont(x))))

    def decode_scalar(self, x) -> int:
        return self.decode(x[None, ...])[0]

    # ------------------------------------------------------------- misc

    def zeros(self, shape=()) -> jnp.ndarray:
        return jnp.zeros(tuple(shape) + (self.nlimbs,), dtype=jnp.int32)

    def ones_mont(self, shape=()) -> jnp.ndarray:
        return jnp.broadcast_to(
            jnp.asarray(self.one_mont), tuple(shape) + (self.nlimbs,)
        ).astype(jnp.int32)

    @_opjit
    def is_zero(self, x):
        """Zero test in the redundant domain (0 and p both represent 0)."""
        return lb.is_zero(self.cond_sub_p(x))

    @_opjit
    def eq(self, x, y):
        """Equality in the redundant domain: canonicalize then compare."""
        return jnp.all(self.cond_sub_p(x) == self.cond_sub_p(y), axis=-1)


@functools.lru_cache(maxsize=None)
def _specs():
    return (
        FieldSpec("bn254_fp", hm.P),
        FieldSpec("bn254_fr", hm.R),
    )


FP, FR = _specs()
