"""Batched Montgomery prime-field arithmetic on limb tensors.

Reference counterpart: IBM mathlib's Zr/Fp scalar ops (used throughout
token/core/zkatdlog/crypto). Here a field is a `FieldSpec` of baked numpy
limb constants; every op is branch-free, batched over leading axes, and
jit-safe. Elements live in Montgomery form (x·R mod p, R = 2^256) as
(..., 32) int32 limb tensors.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import limbs as lb
from ..crypto import hostmath as hm


def _opjit(fn=None, *, static=()):
    """jit a FieldSpec method with `self` static (specs are singletons)."""

    def wrap(f):
        return jax.jit(f, static_argnums=(0,) + tuple(static))

    return wrap(fn) if fn is not None else wrap


@dataclass(frozen=True, eq=False)
class FieldSpec:
    """A prime field with Montgomery constants baked as limb arrays."""

    name: str
    modulus: int
    nlimbs: int = lb.NLIMBS
    p_limbs: np.ndarray = field(init=False, repr=False)
    pprime_limbs: np.ndarray = field(init=False, repr=False)  # -p^-1 mod R
    r2_limbs: np.ndarray = field(init=False, repr=False)  # R^2 mod p
    one_mont: np.ndarray = field(init=False, repr=False)  # R mod p

    def __post_init__(self):
        R = 1 << (lb.RADIX_BITS * self.nlimbs)
        if self.modulus >= R or self.modulus % 2 == 0:
            raise ValueError("modulus must be odd and fit the limb width")
        object.__setattr__(self, "p_limbs", lb.int_to_limbs(self.modulus, self.nlimbs))
        pprime = (-pow(self.modulus, -1, R)) % R
        object.__setattr__(self, "pprime_limbs", lb.int_to_limbs(pprime, self.nlimbs))
        object.__setattr__(self, "r2_limbs", lb.int_to_limbs(R * R % self.modulus, self.nlimbs))
        object.__setattr__(self, "one_mont", lb.int_to_limbs(R % self.modulus, self.nlimbs))

    # ------------------------------------------------------------- reduce

    @_opjit
    def cond_sub_p(self, x):
        """x in [0, 2p) -> x mod p."""
        ge = lb.compare_ge(x, self.p_limbs)
        d = jnp.where(ge[..., None], x - self.p_limbs, x)
        return lb.normalize(d)

    # ------------------------------------------------------------- ring ops

    @_opjit
    def add(self, x, y):
        return self.cond_sub_p(lb.normalize(x + y))

    @_opjit
    def sub(self, x, y):
        return self.cond_sub_p(lb.normalize(x + self.p_limbs - y))

    @_opjit
    def neg(self, x):
        return self.cond_sub_p(lb.normalize(self.p_limbs - x + jnp.zeros_like(x)))

    @_opjit
    def mul(self, x, y):
        """Montgomery product: REDC(x*y)."""
        n = self.nlimbs
        t = lb.mul_full(x, y)  # (..., 2n+1)
        m = lb.mul_low(t[..., :n], self.pprime_limbs, keep=n)
        mp = lb.mul_full(m, self.p_limbs)  # (..., 2n+1)
        width = 2 * n + 2
        acc = jnp.zeros(t.shape[:-1] + (width,), dtype=jnp.int32)
        acc = acc.at[..., : 2 * n + 1].add(t)
        acc = acc.at[..., : 2 * n + 1].add(mp)
        res = lb.normalize(acc)[..., n : 2 * n]
        return self.cond_sub_p(res)

    @_opjit
    def sqr(self, x):
        return self.mul(x, x)

    @_opjit(static=(2,))
    def pow_const(self, x, e: int):
        """x^e for a python-int exponent, via scan over its bits (MSB first)."""
        if e == 0:
            return jnp.broadcast_to(jnp.asarray(self.one_mont), x.shape).astype(jnp.int32)
        bits = np.array([int(b) for b in bin(e)[2:]], dtype=np.int32)

        def step(acc, bit):
            acc = self.mul(acc, acc)
            acc = jnp.where(bit > 0, self.mul(acc, x), acc)
            return acc, None

        init = jnp.broadcast_to(jnp.asarray(self.one_mont), x.shape).astype(jnp.int32)
        out, _ = lax.scan(step, init, jnp.asarray(bits))
        return out

    @_opjit
    def inv(self, x):
        """Montgomery inverse by Fermat: x^(p-2). x must be nonzero."""
        return self.pow_const(x, self.modulus - 2)

    @_opjit(static=(2,))
    def mul_small(self, x, k: int):
        """x * k for small non-negative python int k (k < 2^15)."""
        return self.cond_sub_p_loop(lb.normalize(x * jnp.int32(k)))

    def cond_sub_p_loop(self, x):
        """x in [0, k*p) for small k -> x mod p (repeated conditional subtract)."""

        def cond(v):
            return jnp.any(lb.compare_ge(v, self.p_limbs))

        def body(v):
            return self.cond_sub_p(v)

        return lax.while_loop(cond, body, x)

    # ------------------------------------------------------------- domain

    @_opjit
    def to_mont(self, x):
        return self.mul(x, jnp.asarray(self.r2_limbs))

    @_opjit
    def from_mont(self, x):
        one = jnp.zeros_like(x).at[..., 0].set(1)
        return self.mul(x, one)

    # ------------------------------------------------------------- host I/O

    def encode(self, values) -> jnp.ndarray:
        """Host ints -> Montgomery limb tensor (N, nlimbs)."""
        vals = [v % self.modulus for v in values]
        raw = lb.ints_to_limbs(vals, self.nlimbs)
        return self.to_mont(jnp.asarray(raw))

    def encode_scalar(self, v: int) -> jnp.ndarray:
        return self.encode([v])[0]

    def decode(self, x) -> list:
        """Montgomery limb tensor -> host ints."""
        return lb.batch_limbs_to_ints(np.asarray(self.from_mont(x)))

    def decode_scalar(self, x) -> int:
        return self.decode(x[None, ...])[0]

    # ------------------------------------------------------------- misc

    def zeros(self, shape=()) -> jnp.ndarray:
        return jnp.zeros(tuple(shape) + (self.nlimbs,), dtype=jnp.int32)

    def ones_mont(self, shape=()) -> jnp.ndarray:
        return jnp.broadcast_to(
            jnp.asarray(self.one_mont), tuple(shape) + (self.nlimbs,)
        ).astype(jnp.int32)

    def is_zero(self, x):
        return lb.is_zero(x)

    def eq(self, x, y):
        return jnp.all(x == y, axis=-1)


@functools.lru_cache(maxsize=None)
def _specs():
    return (
        FieldSpec("bn254_fp", hm.P),
        FieldSpec("bn254_fr", hm.R),
    )


FP, FR = _specs()
