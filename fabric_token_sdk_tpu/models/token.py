"""Clear-text token model (reference `token/token/token.go`)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .quantity import Quantity
from ..crypto.serialization import dumps, loads


@dataclass(frozen=True)
class ID:
    """(creating tx, output index) — unique token identity."""

    tx_id: str
    index: int

    def __str__(self) -> str:
        return f"[{self.tx_id}:{self.index}]"

    def key(self) -> str:
        return f"{self.tx_id}.{self.index}"


@dataclass(frozen=True)
class Owner:
    raw: bytes  # serialized owner identity (or script)


@dataclass
class Token:
    """Result of issue/transfer: owner + type + hex-encoded quantity."""

    owner: Owner
    type: str
    quantity: str  # 0x-hex

    def quantity_as(self, precision: int = 64) -> Quantity:
        return Quantity.from_hex(self.quantity, precision)

    def to_bytes(self) -> bytes:
        return dumps({"o": self.owner.raw, "t": self.type, "q": self.quantity})

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Token":
        d = loads(raw)
        return cls(Owner(d["o"]), d["t"], d["q"])


@dataclass
class UnspentToken:
    id: ID
    owner: Owner
    type: str
    quantity: str  # decimal string (reference parity)


@dataclass
class IssuedToken:
    id: ID
    owner: Owner
    type: str
    quantity: str
    issuer: Optional[Owner] = None


def sum_quantities(tokens: List[Token], precision: int = 64) -> Quantity:
    total = Quantity.zero(precision)
    for t in tokens:
        total = total.add(t.quantity_as(precision))
    return total
