"""Token data model: IDs, owners, quantities, clear tokens, actions, requests.

Reference: `token/token/*.go` (ID, Owner, Token, Quantity) and
`token/request.go` (TokenRequest assembly).
"""

from .token import ID, IssuedToken, Owner, Token, UnspentToken  # noqa: F401
from .quantity import Quantity  # noqa: F401
