"""Arbitrary-precision token quantities (reference `token/token/quantity.go`).

Quantities are non-negative integers bounded by a bit precision; the wire
encoding is a 0x-prefixed hex string.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Quantity:
    value: int
    precision: int

    def __post_init__(self):
        if self.precision == 0 or self.precision > 256:
            raise ValueError(f"invalid precision [{self.precision}]")
        if self.value < 0:
            raise ValueError("quantity must be larger than 0")
        if self.value >= (1 << self.precision):
            raise ValueError(f"quantity exceeds precision [{self.precision}]")

    # ------------------------------------------------------------- codecs

    @classmethod
    def from_uint64(cls, v: int, precision: int = 64) -> "Quantity":
        return cls(v, precision)

    @classmethod
    def from_hex(cls, s: str, precision: int = 64) -> "Quantity":
        if not s.startswith("0x"):
            raise ValueError(f"invalid input [{s}]: missing 0x prefix")
        return cls(int(s, 16), precision)

    @classmethod
    def from_decimal(cls, s: str, precision: int = 64) -> "Quantity":
        return cls(int(s, 10), precision)

    @classmethod
    def zero(cls, precision: int = 64) -> "Quantity":
        return cls(0, precision)

    def hex(self) -> str:
        return hex(self.value)

    def decimal(self) -> str:
        return str(self.value)

    # ------------------------------------------------------------- algebra

    def add(self, other: "Quantity") -> "Quantity":
        return Quantity(self.value + other.value, self.precision)

    def sub(self, other: "Quantity") -> "Quantity":
        if other.value > self.value:
            raise ValueError("failed to subtract: negative result")
        return Quantity(self.value - other.value, self.precision)

    def cmp(self, other: "Quantity") -> int:
        return (self.value > other.value) - (self.value < other.value)

    def is_zero(self) -> bool:
        return self.value == 0

    def __str__(self) -> str:
        return self.decimal()
