"""Assemble per-transaction distributed traces from sidecar dumps.

Usage:
    python cmd/ftstrace.py timeline <tx-id-or-trace-id> <sidecar.json> [...]
    python cmd/ftstrace.py export -o chrome_trace.json <sidecar.json> [...]
    python cmd/ftstrace.py tail [-n N] <flight.json>
    python cmd/ftstrace.py flame [--role ROLE] <result-or-history.json>
    python cmd/ftstrace.py devices [--plane PLANE] <result-or-history.json>

Inputs are any mix of ``*.metrics.json`` (span trees — what
``Registry.snapshot()`` flushes) and ``*.flight.json`` (flight-recorder
rings) sidecars, from ONE process or MANY: spans and events are stitched
by ``trace_id``, the propagation id `services/network/remote.py` carries
inside request frames — so a client sidecar plus a ledger-node sidecar
yield one causal timeline per transaction (client submit -> server
orderer -> batched device verify -> WAL append -> finality).

`timeline` prints one trace chronologically, including the per-block
critical-path breakdown (queue wait / grouping / device verify / host
validate with its named sub-legs / WAL / merge) of the block that
committed the tx. `export` writes Chrome-trace-event JSON (load in
chrome://tracing or https://ui.perfetto.dev). `tail` prints the last N
flight-recorder events of a crash dump — the first thing to read after
an rc=124. `flame` dumps the host-path sampling profile of a bench
result (the `profile.stacks` section `bench.py` records when
`FTS_PROF_HZ` > 0) in collapsed-stack format — pipe it straight into
flamegraph.pl or paste into speedscope.app. `devices` renders the
device-plane dispatch ledger of a bench result (the `device` section,
`utils/devobs.py`) as a per-program breakdown — dispatches, occupancy,
padding waste, wall share, compile forensics — from a result JSON or
the latest device-carrying round of `BENCH_history.jsonl` (same
dual-source rule as `flame`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

# breakdown keys of a `block.commit` flight event, in pipeline order
# (`overlap_s` only present on blocks the pipelined engine committed;
# the `host_*` sub-legs decompose `host_validate_s` by named phase)
BLOCK_BREAKDOWN_KEYS = (
    "queue_wait_max_s", "grouping_s", "device_verify_s", "sign_verify_s",
    "host_validate_s", "host_unmarshal_s", "host_fiat_shamir_s",
    "host_sig_verify_s", "host_conservation_s", "host_input_match_s",
    "wal_s", "merge_s", "overlap_s",
)


def _load(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def _fmt_s(v: float) -> str:
    if v >= 60:
        return f"{v / 60:.1f}m"
    if v >= 1:
        return f"{v:.2f}s"
    if v >= 0.001:
        return f"{v * 1000:.1f}ms"
    return f"{v * 1e6:.0f}us"


def _walk_spans(node: dict, out: List[dict], src: str, pid) -> None:
    row = dict(node)
    row.pop("children", None)
    row["src"] = src
    row["pid"] = pid
    out.append(row)
    for child in node.get("children", ()):
        _walk_spans(child, out, src, pid)


def collect(paths: List[str]) -> Tuple[List[dict], List[dict]]:
    """Load every sidecar; return (flat spans, flight events), each row
    tagged with its source file and pid."""
    spans: List[dict] = []
    events: List[dict] = []
    for path in paths:
        doc = _load(path)
        src = os.path.basename(path)
        pid = doc.get("pid", 0)
        for root in doc.get("spans", ()):
            _walk_spans(root, spans, src, pid)
        for evt in doc.get("events", ()):
            row = dict(evt)
            row["src"] = src
            row["pid"] = pid
            events.append(row)
    return spans, events


def known_traces(spans: List[dict], events: List[dict]) -> Dict[str, str]:
    """trace_id -> a tx anchor seen for it (or ''), discovery aid."""
    out: Dict[str, str] = {}
    for s in spans:
        t = s.get("trace_id")
        if t:
            out.setdefault(t, "")
            tx = (s.get("attrs") or {}).get("tx")
            if tx:
                out[t] = tx
    for e in events:
        t = e.get("trace_id")
        if t:
            out.setdefault(t, "")
            if e.get("tx"):
                out[t] = e["tx"]
        if e.get("kind") == "block.commit":
            for tx, tr in zip(e.get("txs", ()), e.get("traces", ())):
                if tr:
                    out[tr] = tx
    return out


def resolve_traces(ident: str, spans: List[dict],
                   events: List[dict]) -> List[str]:
    """Accept either a trace id or a tx anchor; return every matching
    trace id. A tx can legitimately own more than one (e.g. assembled
    under a ttx trace, then shipped as raw bytes through a batched
    `submit_many` that mints per-request traces) — the tx timeline is
    the union."""
    traces = known_traces(spans, events)
    if ident in traces:
        return [ident]
    return sorted(t for t, tx in traces.items() if tx == ident)


def _trace_rows(trace_ids: List[str], spans: List[dict],
                events: List[dict]) -> List[tuple]:
    """(ts, kind, label, detail) rows for a set of traces, chronological."""
    wanted = set(trace_ids)
    rows: List[tuple] = []
    for s in spans:
        if s.get("trace_id") not in wanted or not s.get("start_unix"):
            continue
        attrs = s.get("attrs") or {}
        detail = " ".join(f"{k}={v}" for k, v in attrs.items())
        rows.append((
            s["start_unix"], "span",
            f"{s['name']:<28} {_fmt_s(s.get('duration_s', 0.0)):>8}",
            f"pid={s['pid']} {detail}".strip(),
        ))
    for e in events:
        kind = e.get("kind", "?")
        in_trace = e.get("trace_id") in wanted
        in_block = (
            kind == "block.commit"
            and wanted.intersection(e.get("traces") or ())
        )
        if not (in_trace or in_block):
            continue
        if kind == "block.commit":
            # the block's critical path applies to every tx it committed
            parts = " ".join(
                f"{k[:-2]}={_fmt_s(float(e.get(k, 0.0)))}"
                for k in BLOCK_BREAKDOWN_KEYS if k in e
            )
            rows.append((
                e.get("ts", 0.0), "block",
                f"block {e.get('block')} critical path ({len(e.get('txs', ()))} txs)",
                parts,
            ))
            continue
        detail = " ".join(
            f"{k}={v}" for k, v in e.items()
            if k not in ("ts", "kind", "trace_id", "src", "pid")
        )
        rows.append((e.get("ts", 0.0), "event", kind, detail))
    rows.sort(key=lambda r: r[0])
    return rows


def timeline(ident: str, paths: List[str]) -> int:
    spans, events = collect(paths)
    trace_ids = resolve_traces(ident, spans, events)
    if not trace_ids:
        traces = known_traces(spans, events)
        print(f"no trace found for {ident!r}", file=sys.stderr)
        if traces:
            print("known traces:", file=sys.stderr)
            for t, tx in sorted(traces.items())[:20]:
                print(f"  {t}  tx={tx or '?'}", file=sys.stderr)
        return 1
    rows = _trace_rows(trace_ids, spans, events)
    if not rows:
        print(f"trace {trace_ids}: no timed rows recorded", file=sys.stderr)
        return 1
    t0 = rows[0][0]
    print(f"== trace {' + '.join(trace_ids)} ({ident}) — {len(rows)} rows "
          f"across {len(paths)} sidecar(s)")
    for ts, kind, label, detail in rows:
        print(f"  +{ts - t0:>10.6f}s  {kind:<5}  {label}"
              + (f"  [{detail}]" if detail else ""))
    return 0


def export(out_path: str, paths: List[str]) -> int:
    """Chrome-trace-event JSON: spans become complete ('X') events on a
    per-trace lane, flight events become instants ('i')."""
    spans, events = collect(paths)
    tid_of: Dict[str, int] = {}
    lanes: set = set()  # (pid, tid) pairs actually carrying events

    def tid(trace_id: Optional[str], pid) -> int:
        key = trace_id or "(untraced)"
        if key not in tid_of:
            tid_of[key] = len(tid_of) + 1
        lanes.add((pid, tid_of[key], key))
        return tid_of[key]

    out: List[dict] = []
    for s in spans:
        if not s.get("start_unix"):
            continue
        args = dict(s.get("attrs") or {})
        for k in ("trace_id", "span_id", "parent_span_id"):
            if s.get(k):
                args[k] = s[k]
        out.append({
            "name": s["name"], "cat": "span", "ph": "X",
            "ts": s["start_unix"] * 1e6,
            "dur": max(1.0, s.get("duration_s", 0.0) * 1e6),
            "pid": s["pid"], "tid": tid(s.get("trace_id"), s["pid"]),
            "args": args,
        })
    for e in events:
        args = {
            k: v for k, v in e.items()
            if k not in ("ts", "kind", "src", "pid")
        }
        out.append({
            "name": e.get("kind", "?"), "cat": "flight", "ph": "i",
            "ts": e.get("ts", 0.0) * 1e6, "s": "p",
            "pid": e["pid"], "tid": tid(e.get("trace_id"), e["pid"]),
            "args": args,
        })
    # label the per-trace lanes so the viewer shows trace ids, not ints
    # — one metadata row per (pid, tid) pair that actually carries
    # events, or the labels attach to nothing
    meta = [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": n,
         "args": {"name": f"trace {key}"}}
        for pid, n, key in sorted(lanes)
    ]
    with open(out_path, "w") as fh:
        json.dump({"traceEvents": meta + out}, fh)
    print(f"wrote {len(out)} events ({len(tid_of)} lanes) to {out_path}")
    return 0


def tail(path: str, n: int = 20) -> int:
    doc = _load(path)
    events = doc.get("events", [])
    print(f"== {path}: {len(events)} events "
          f"(capacity {doc.get('capacity', '?')}, pid {doc.get('pid', '?')})")
    for e in events[-n:]:
        detail = " ".join(
            f"{k}={v}" for k, v in e.items() if k not in ("ts", "kind")
        )
        print(f"  {e.get('ts', 0.0):.3f}  {e.get('kind', '?'):<20} {detail}")
    return 0


def _section_of(path: str, name: str) -> Optional[dict]:
    """A named dict section of a bench result file, or of the LATEST
    section-carrying round of a history jsonl."""
    if path.endswith(".jsonl"):
        sys.path.insert(
            0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
        )
        from fabric_token_sdk_tpu.utils import benchschema

        found = None
        for row in benchschema.load_history(path):
            result = benchschema.extract_result(row)
            if result and isinstance(result.get(name), dict):
                found = result[name]
        return found
    doc = _load(path)
    s = doc.get(name)
    return s if isinstance(s, dict) else None


def _profile_of(path: str) -> Optional[dict]:
    """The `profile` section of a bench result file, or of the LATEST
    profile-carrying round of a history jsonl."""
    return _section_of(path, "profile")


def flame(path: str, role: Optional[str] = None, out=None) -> int:
    """Print a recorded profile's collapsed stacks (`stack count` lines,
    hottest first) — flamegraph.pl / speedscope input. Stacks are keyed
    `role;mod:func;...`; `--role` keeps one thread role's stacks."""
    out = out if out is not None else sys.stdout
    prof = _profile_of(path)
    if prof is None:
        print(f"{path}: no profile section (run bench with FTS_PROF_HZ > 0)",
              file=sys.stderr)
        return 1
    stacks = prof.get("stacks") or {}
    if role:
        stacks = {s: c for s, c in stacks.items()
                  if s.split(";", 1)[0] == role}
    if not stacks:
        roles = sorted({s.split(";", 1)[0] for s in (prof.get("stacks") or {})})
        print(
            f"{path}: no stacks"
            + (f" for role {role!r} (roles seen: {', '.join(roles) or '-'})"
               if role else " recorded"),
            file=sys.stderr,
        )
        return 1
    for stack, count in sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0])):
        print(f"{stack} {count}", file=out)
    return 0


def _frac(v) -> str:
    return "-" if not isinstance(v, (int, float)) else f"{v:.1%}"


def devices(path: str, plane: Optional[str] = None, out=None) -> int:
    """Render the per-program device dispatch breakdown of a recorded
    bench round (`device` section): dispatches, occupancy, padding
    waste, share of total dispatch wall, and compile forensics —
    heaviest program first; `--plane` keeps one plane's programs."""
    out = out if out is not None else sys.stdout
    dev = _section_of(path, "device")
    if dev is None:
        print(f"{path}: no device section (recorded by bench.py when the "
              "dispatch ledger is on — FTS_DEVOBS, default on)",
              file=sys.stderr)
        return 1
    programs = dev.get("programs") or {}
    if plane:
        programs = {
            k: r for k, r in programs.items()
            if isinstance(r, dict) and r.get("plane") == plane
        }
    if not programs:
        planes = sorted((dev.get("planes") or {}))
        print(
            f"{path}: no programs"
            + (f" for plane {plane!r} (planes seen: "
               f"{', '.join(planes) or '-'})" if plane else " recorded"),
            file=sys.stderr,
        )
        return 1
    print(
        f"== device plane: {dev.get('dispatches', 0)} dispatches  "
        f"occupancy={_frac(dev.get('occupancy'))}  "
        f"waste={_frac(dev.get('waste_frac'))}  "
        f"p99={dev.get('dispatch_p99_s')}s  "
        f"compiles={dev.get('compiles', 0)} "
        f"({dev.get('compile_s', 0)}s)  "
        f"cache={dev.get('cache_hits', 0)}h/"
        f"{dev.get('cache_misses', 0)}m  "
        f"degrades={dev.get('degrades', 0)}",
        file=out,
    )
    total_wall = sum(
        r.get("wall_s", 0.0) for r in programs.values()
        if isinstance(r, dict)
    )
    rows = sorted(
        (r for r in programs.values() if isinstance(r, dict)),
        key=lambda r: -r.get("wall_s", 0.0),
    )
    for r in rows:
        share = (
            r.get("wall_s", 0.0) / total_wall if total_wall else 0.0
        )
        print(
            f"  {r.get('plane', '-'):<8} {r.get('program', '-'):<20} "
            f"disp={r.get('dispatches', 0):<6} "
            f"occ={_frac(r.get('occupancy')):<6} "
            f"waste={_frac(r.get('waste_frac')):<6} "
            f"wall={_fmt_s(r.get('wall_s', 0.0)):>8} ({share:.0%}) "
            f"p50={_fmt_s(r.get('p50_s') or 0.0):>8} "
            f"p99={_fmt_s(r.get('p99_s') or 0.0):>8} "
            f"compiles={r.get('compiles', 0)} "
            f"degrades={r.get('degrades', 0)}",
            file=out,
        )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ftstrace", description=__doc__.splitlines()[0]
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_tl = sub.add_parser(
        "timeline", help="print one tx's stitched causal timeline"
    )
    p_tl.add_argument("ident", help="tx anchor or trace id")
    p_tl.add_argument("sidecars", nargs="+")
    p_ex = sub.add_parser(
        "export", help="write Chrome-trace-event JSON for all traces"
    )
    p_ex.add_argument("-o", "--out", default="fts_trace.json")
    p_ex.add_argument("sidecars", nargs="+")
    p_ta = sub.add_parser(
        "tail", help="print the last N events of a flight dump"
    )
    p_ta.add_argument("-n", type=int, default=20)
    p_ta.add_argument("flight")
    p_fl = sub.add_parser(
        "flame", help="dump a recorded host-path profile as collapsed stacks"
    )
    p_fl.add_argument("--role", default=None,
                      help="keep one thread role (commit-worker, "
                           "stage-a-driver, remote-handler, client, other)")
    p_fl.add_argument("result",
                      help="bench result JSON or BENCH_history.jsonl")
    p_dv = sub.add_parser(
        "devices",
        help="render a recorded round's per-program device dispatch "
             "breakdown",
    )
    p_dv.add_argument("--plane", default=None,
                      help="keep one plane's programs (verify, sign, "
                           "prove, stages)")
    p_dv.add_argument("result",
                      help="bench result JSON or BENCH_history.jsonl")
    args = ap.parse_args(argv)
    if args.cmd == "timeline":
        return timeline(args.ident, args.sidecars)
    if args.cmd == "export":
        return export(args.out, args.sidecars)
    if args.cmd == "flame":
        return flame(args.result, args.role)
    if args.cmd == "devices":
        return devices(args.result, args.plane)
    return tail(args.flight, args.n)


if __name__ == "__main__":
    sys.exit(main())
